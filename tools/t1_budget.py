#!/usr/bin/env python
"""Tier-1 wall-clock budget watchdog (ISSUE 20 satellite).

The tier-1 verify recipe runs under a hard 870 s ``timeout``; ROADMAP.md
has tracked the suite creeping toward it for several PRs, and a breach
is indistinguishable from a hung test (rc 124, partial log). This tool
makes the creep VISIBLE per PR instead of discovered at the cliff:

    python -m pytest tests/ -q -m 'not slow' --durations=0 \
        --durations-min=0.05 ... | tee /tmp/_t1.log
    python tools/t1_budget.py /tmp/_t1.log

It parses pytest's ``--durations`` report (and the final summary wall as
a cross-check), prints the top offenders and the projected wall, and
exits nonzero once the measured wall passes the SOFT threshold
(``T1_BUDGET_SOFT_S``, default 700 of the 870 s hard timeout) — the PR
that pushes past it should move pins to the slow lane *in that PR*, not
leave the cliff for a later one.

Exit codes: 0 ok, 1 soft threshold exceeded, 2 log unparsable.
"""

from __future__ import annotations

import os
import re
import sys

HARD_TIMEOUT_S = 870.0

# "0.32s call     tests/test_x.py::test_y" (pytest --durations line)
_DUR_RE = re.compile(
    r"^\s*(?P<s>\d+(?:\.\d+)?)s\s+(?P<phase>call|setup|teardown)\s+"
    r"(?P<test>\S+)")
# "709 passed, 1 skipped in 633.50s" / "... in 633.50s (0:10:33)"
_WALL_RE = re.compile(r"\bin (?P<s>\d+(?:\.\d+)?)s(?:\s|\b)")


def parse(path: str):
    """→ (durations: list[(seconds, phase, test)], wall_s or None)."""
    durations, wall = [], None
    with open(path, "r", errors="replace") as f:
        for ln in f:
            m = _DUR_RE.match(ln)
            if m:
                durations.append((float(m.group("s")), m.group("phase"),
                                  m.group("test")))
                continue
            if " passed" in ln or " failed" in ln or " error" in ln:
                w = _WALL_RE.search(ln)
                if w:
                    wall = float(w.group("s"))
    return durations, wall


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    log = argv[0] if argv else "/tmp/_t1.log"
    soft = float(os.environ.get("T1_BUDGET_SOFT_S", 700))
    top_n = int(os.environ.get("T1_BUDGET_TOP", 20))
    if not os.path.exists(log):
        print(f"t1_budget: log {log!r} not found", file=sys.stderr)
        return 2
    durations, wall = parse(log)
    if wall is None and not durations:
        print(f"t1_budget: no pytest summary or --durations lines in "
              f"{log!r} (run pytest with --durations=0 --durations-min=0.05)",
              file=sys.stderr)
        return 2

    # per-test cost: sum call+setup+teardown under one test id
    per_test: dict = {}
    for s, _phase, test in durations:
        per_test[test] = per_test.get(test, 0.0) + s
    ranked = sorted(per_test.items(), key=lambda kv: -kv[1])
    tracked = sum(per_test.values())
    # projected wall: the measured summary wall when present (it includes
    # collection + interpreter startup the durations report does not),
    # else the tracked sum as a floor
    projected = wall if wall is not None else tracked

    print(f"tier-1 budget: projected wall {projected:.0f}s "
          f"of {HARD_TIMEOUT_S:.0f}s hard timeout "
          f"(soft threshold {soft:.0f}s)")
    if durations:
        print(f"  {len(per_test)} tests with tracked phases, "
              f"{tracked:.0f}s tracked; top {min(top_n, len(ranked))}:")
        for test, s in ranked[:top_n]:
            print(f"  {s:7.2f}s  {test}")
    headroom = HARD_TIMEOUT_S - projected
    if projected > soft:
        print(f"t1_budget: FAIL — projected wall {projected:.0f}s exceeds "
              f"the {soft:.0f}s soft threshold ({headroom:.0f}s headroom "
              f"to the hard timeout). Move the top offenders above to the "
              f"slow lane in THIS PR.", file=sys.stderr)
        return 1
    print(f"  ok: {headroom:.0f}s headroom to the hard timeout")
    return 0


if __name__ == "__main__":
    try:
        rc = main()
        sys.stdout.flush()
    except BrokenPipeError:
        # downstream pager/head closed the pipe mid-report: no traceback,
        # and never exit 0 — the verdict may not have been printed
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        rc = 1
    sys.exit(rc)
