#!/usr/bin/env python
"""Closed-loop load generator for the `/3/Predictions` serving path.

N worker threads each issue M back-to-back requests against one
(model, frame) pair and record per-request latency; the report prints
p50/p99 and aggregate throughput, plus the 429 (shed) and error counts so
an overload run is legible. Closed-loop means each thread waits for its
response before sending the next request — offered load tracks service
rate, which is the right shape for measuring the micro-batcher's
coalescing win (open-loop generators measure queue explosion instead).

Usage:
    python deploy/loadgen.py --port 54321 --model gbm_1 --frame fr_1 \\
        --threads 8 --requests 50

Importable: `run_load(...)` returns the stats dict (the smoke test in
tests/test_serving.py drives an in-process server through it).
"""

from __future__ import annotations

import argparse
import json
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, List, Optional


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return float("nan")
    i = min(int(q * len(sorted_vals)), len(sorted_vals) - 1)
    return sorted_vals[i]


def run_load(host: str, port: int, model: str, frame: str,
             threads: int = 8, requests: int = 50,
             duration_s: Optional[float] = None,
             timeout_s: float = 60.0) -> Dict:
    """Drive the predict route closed-loop; returns the stats dict.

    `duration_s` caps wall-clock instead of request count when set (each
    thread stops issuing new requests once the deadline passes)."""
    url = (f"http://{host}:{port}/3/Predictions/models/"
           f"{urllib.parse.quote(model)}/frames/"
           f"{urllib.parse.quote(frame)}")
    lock = threading.Lock()
    lat_s: List[float] = []
    shed = [0]
    errors = [0]
    t_end = (time.monotonic() + duration_s) if duration_s else None

    def worker():
        for _ in range(requests):
            if t_end is not None and time.monotonic() >= t_end:
                break
            t0 = time.monotonic()
            try:
                req = urllib.request.Request(url, data=b"")
                with urllib.request.urlopen(req, timeout=timeout_s) as r:
                    r.read()
                with lock:
                    lat_s.append(time.monotonic() - t0)
            except urllib.error.HTTPError as e:
                e.read()
                with lock:
                    (shed if e.code == 429 else errors)[0] += 1
            except OSError:
                with lock:
                    errors[0] += 1

    t0 = time.monotonic()
    ts = [threading.Thread(target=worker, daemon=True)
          for _ in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    wall = max(time.monotonic() - t0, 1e-9)
    srt = sorted(lat_s)
    return dict(
        url=url, threads=threads, requests_per_thread=requests,
        completed=len(srt), shed_429=shed[0], errors=errors[0],
        wall_s=round(wall, 3),
        throughput_rps=round(len(srt) / wall, 2),
        p50_ms=round(_percentile(srt, 0.50) * 1e3, 3) if srt else None,
        p99_ms=round(_percentile(srt, 0.99) * 1e3, 3) if srt else None,
    )


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--model", required=True, help="DKV model key")
    ap.add_argument("--frame", required=True, help="DKV frame key")
    ap.add_argument("--threads", type=int, default=8)
    ap.add_argument("--requests", type=int, default=50,
                    help="requests per thread")
    ap.add_argument("--duration-s", type=float, default=None,
                    help="stop issuing after this many seconds instead")
    args = ap.parse_args()
    stats = run_load(args.host, args.port, args.model, args.frame,
                     threads=args.threads, requests=args.requests,
                     duration_s=args.duration_s)
    print(json.dumps(stats, indent=2))
    return 0 if stats["completed"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
