#!/usr/bin/env python
"""Load generator for the `/3/Predictions` serving path — closed- and
open-loop.

**Closed-loop** (`run_load`): N worker threads each issue M back-to-back
requests; each thread waits for its response before sending the next, so
offered load tracks service rate — the right shape for measuring the
micro-batcher's coalescing win.

**Open-loop** (`run_load_open`): requests arrive on a fixed schedule
(`rate` per second) regardless of how fast earlier ones complete — the
right shape for a serving-SLO lane, because a slow server faces the SAME
offered load a fast one does instead of being graded on a curve. Latency
bins into `LATENCY_MS_BOUNDS` below — the same fixed buckets as
`h2o3_tpu.runtime.metrics_registry.LATENCY_MS_BOUNDS` (equality is
pinned by a test) — so the reported p50/p95/p99 are bucket-comparable
with the serving histograms scraped at `GET /3/Metrics`.

The standalone CLI is STDLIB-ONLY: it must run from a loadgen host with
no jax/h2o3 installed, and must not import (and configure) jax as a side
effect in the loadgen process. When the platform is already loaded
in-process (bench.py, the in-process test servers), every request is
additionally folded into the central registry
(`h2o3_loadgen_request_ms{mode=...}`) so a loadgen run is itself
scrapable.

Usage:
    python deploy/loadgen.py --port 54321 --model gbm_1 --frame fr_1 \\
        --threads 8 --requests 50               # closed-loop
    python deploy/loadgen.py --port 54321 --model gbm_1 --frame fr_1 \\
        --rate 50 --duration-s 10               # open-loop, 50 req/s
    python deploy/loadgen.py --port 54321 --model gbm_1 --frame fr_1 \\
        --rate 50 --duration-s 10 --router      # fleet router entry point

Importable: `run_load(...)` / `run_load_open(...)` return the stats dict
(the smoke tests in tests/test_serving.py and tests/test_observability.py
drive an in-process server through them; `BENCH_CONFIG=serving` in
bench.py is the open-loop SLO lane).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, List, Optional

if __package__ in (None, ""):  # `python deploy/loadgen.py` from anywhere
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


# literal copy of metrics_registry.LATENCY_MS_BOUNDS (this module cannot
# import the platform — see docstring); test_observability pins equality
LATENCY_MS_BOUNDS = (0.5, 1, 2, 5, 10, 25, 50, 100, 250, 500, 1000, 2500,
                     5000, 10000, 30000)


class _BucketHist:
    """Stdlib fixed-bound histogram over the shared latency buckets, with
    the same bucket-interpolated percentile estimate as the registry's
    Histogram — O(bounds) state, directly comparable with /3/Metrics."""

    def __init__(self, bounds=LATENCY_MS_BOUNDS):
        self.bounds = tuple(float(b) for b in bounds)
        self.counts = [0] * (len(self.bounds) + 1)
        self.n = 0
        self.total = 0.0
        self.vmin: Optional[float] = None
        self.vmax: Optional[float] = None

    def observe(self, v: float) -> None:
        i = len(self.bounds)
        for j, b in enumerate(self.bounds):
            if v <= b:
                i = j
                break
        self.counts[i] += 1
        self.n += 1
        self.total += v
        self.vmin = v if self.vmin is None else min(self.vmin, v)
        self.vmax = v if self.vmax is None else max(self.vmax, v)

    def percentile(self, q: float) -> Optional[float]:
        if self.n == 0:
            return None
        rank = q * (self.n - 1)
        cum = 0
        for i, cnt in enumerate(self.counts):
            if cnt == 0:
                continue
            if rank < cum + cnt:
                lo = self.bounds[i - 1] if i > 0 else (
                    self.vmin if self.vmin is not None else 0.0)
                hi = self.bounds[i] if i < len(self.bounds) else (
                    self.vmax if self.vmax is not None else lo)
                lo = max(lo, self.vmin) if self.vmin is not None else lo
                hi = min(hi, self.vmax) if self.vmax is not None else hi
                if hi <= lo:
                    return float(lo)
                frac = (rank - cum + 1) / cnt if cnt > 1 else 0.5
                frac = min(max(frac, 0.0), 1.0)
                return float(lo + (hi - lo) * frac)
            cum += cnt
        return self.vmax

    def summary(self) -> Dict:
        return dict(
            bounds=list(self.bounds), counts=list(self.counts), count=self.n,
            mean=round(self.total / self.n, 4) if self.n else None,
            min=self.vmin, max=self.vmax,
            p50=self.percentile(0.50), p95=self.percentile(0.95),
            p99=self.percentile(0.99),
        )


def _registry_hist():
    """The scrapable registry fold of every loadgen request — ONLY when
    the platform is already loaded in this process. The standalone CLI
    never imports h2o3_tpu (which would drag jax in and mutate its config
    as an import side effect); returns None there and callers skip the
    fold."""
    if "h2o3_tpu" not in sys.modules:
        return None
    from h2o3_tpu.runtime import metrics_registry as reg

    return reg.histogram(
        "h2o3_loadgen_request_ms",
        "loadgen request latency (ms), shared latency buckets",
        bounds=reg.LATENCY_MS_BOUNDS, labelnames=("mode",))


def _read_rss_bytes() -> Optional[int]:
    """Current resident set size — stdlib-only (/proc on Linux, ru_maxrss
    peak as the portable fallback). None when neither is readable."""
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        try:
            import resource

            return resource.getrusage(
                resource.RUSAGE_SELF).ru_maxrss * 1024
        except Exception:
            return None


def _ledger_total_bytes() -> Optional[int]:
    """Ledger-attributed host+device bytes — ONLY when the platform is
    already loaded in this process (same stance as _registry_hist: the
    standalone CLI must stay stdlib-only). Uses the ledger's rate-limited
    cached pass, NOT a forced walk: the sample runs in the open-loop
    dispatch thread, and a deep accounting pass there would perturb the
    arrival schedule whose p99 this run exists to measure."""
    if "h2o3_tpu" not in sys.modules:
        return None
    try:
        from h2o3_tpu.runtime import memory_ledger as ml

        t = ml.refresh()["totals"]
        return int(t["host_bytes"]) + int(t["device_bytes"])
    except Exception:
        return None


def _stream_totals() -> Optional[Dict]:
    """Out-of-core stream totals (`streamed_bytes`, `resident_block_peak`)
    — ONLY when the platform already streamed in this process (same
    stdlib-only stance as _ledger_total_bytes); None otherwise so the
    standalone CLI report is unchanged."""
    bs = sys.modules.get("h2o3_tpu.models.block_store")
    if bs is None:
        return None
    try:
        t = bs.process_totals()
        return dict(t) if t.get("streamed_bytes") else None
    except Exception:
        return None


def _growth_bytes_per_min(samples: List[Dict],
                          field: str) -> Optional[float]:
    """Least-squares slope of `field` over the sampled run, in bytes per
    minute — the leak-canary verdict. None below two usable samples."""
    pts = [(s["t_s"], s[field]) for s in samples
           if s.get(field) is not None]
    if len(pts) < 2 or pts[-1][0] - pts[0][0] <= 0:
        return None
    n = len(pts)
    mt = sum(t for t, _ in pts) / n
    mv = sum(v for _, v in pts) / n
    denom = sum((t - mt) ** 2 for t, _ in pts)
    if denom <= 0:
        return None
    slope = sum((t - mt) * (v - mv) for t, v in pts) / denom   # bytes/s
    return round(slope * 60.0, 1)


def fleet_summary(host: str, port: int,
                  timeout_s: float = 10.0) -> Optional[Dict]:
    """Fleet fold of an aggregator target (`GET /3/Fleet`, stdlib-only):
    fleet-merged request/error totals + predict p99 and per-replica
    liveness/error counts. None when the target has no fleet surface (an
    older or single-process server) — the report simply omits the fleet
    section rather than failing the run."""
    url = f"http://{host}:{port}/3/Fleet"
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as r:
            doc = json.loads(r.read().decode())
    except Exception:
        return None
    if "peers" not in doc:
        return None
    return dict(
        requests=doc.get("fleet", {}).get("requests"),
        errors=doc.get("fleet", {}).get("errors"),
        rejections=doc.get("fleet", {}).get("rejections"),
        predict_p99_ms=doc.get("fleet", {}).get("predict_p99_ms"),
        replicas_up=doc.get("totals", {}).get("up"),
        replicas=doc.get("totals", {}).get("peers"),
        per_replica=[dict(name=p.get("name"), up=p.get("up"),
                          requests=p.get("requests"),
                          errors=p.get("errors"),
                          rejections=p.get("rejections"),
                          predict_p99_ms=p.get("predict_p99_ms"))
                     for p in doc.get("peers", [])],
    )


def _fleet_delta_report(before: Optional[Dict], after: Optional[Dict],
                        wall_s: float) -> Optional[Dict]:
    """The loadgen summary's fleet section: the AFTER snapshot (liveness,
    per-replica error counts, fleet predict p99 over merged buckets) plus
    a fleet-scope throughput computed from the before/after request-count
    delta over this run's wall — counters are cumulative, so the delta is
    what THIS run drove through the fleet."""
    if after is None:
        return None
    out = dict(after)
    if (before is not None and before.get("requests") is not None
            and after.get("requests") is not None and wall_s > 0):
        # fleet totals only sum currently-REACHABLE replicas, so a peer
        # dying mid-run can shrink the after-snapshot below the before
        # one — floor deltas at 0 (a rate cannot be negative); the
        # replicas_up / per_replica fields carry the peer-loss signal
        out["throughput_rps"] = round(
            max(after["requests"] - before["requests"], 0) / wall_s, 2)
        for fld in ("errors", "rejections"):
            if before.get(fld) is not None and after.get(fld) is not None:
                out[f"{fld}_delta"] = max(after[fld] - before[fld], 0)
    return out


def _peer_skew(base_url: str, timeout_s: float = 10.0) -> Optional[Dict]:
    """One rank's collective-skew digest from its lossless registry
    export (`h2o3_collective_skew_ms`): every tag's series merged over
    the shared bounds, p50 via the same bucket interpolation as the
    registry, max from the exact per-series max. None when the rank
    recorded no instrumented fences (or is unreachable)."""
    url = base_url.rstrip("/") + "/3/Metrics?format=json"
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as r:
            doc = json.loads(r.read().decode())
    except Exception:
        return None
    fam = doc.get("h2o3_collective_skew_ms")
    if not fam:
        return None
    h = _BucketHist(fam.get("bounds") or LATENCY_MS_BOUNDS)
    for s in fam.get("series") or ():
        for i, c in enumerate(list(s.get("counts") or ())[: len(h.counts)]):
            h.counts[i] += int(c)
        h.n += int(s.get("n") or 0)
        for fld, pick in (("min", min), ("max", max)):
            v = s.get(fld)
            if v is not None:
                cur = getattr(h, f"v{fld}")
                setattr(h, f"v{fld}", v if cur is None else pick(cur, v))
    if not h.n:
        return None
    return dict(fences=h.n, skew_p50_ms=h.percentile(0.50),
                skew_max_ms=h.vmax)


def ranks_summary(host: str, port: int,
                  timeout_s: float = 10.0) -> Optional[List[Dict]]:
    """Pod-rank fold of the --fleet report (ISSUE 18): one row per
    launcher-registered ``rank<N>`` peer — liveness (peer_up) plus that
    rank's own collective-skew p50/max scraped from its registry export.
    The aggregator itself is rank 0 (the launcher registers every OTHER
    rank against it). None when no rank peers exist — single-process
    fleets keep their old report shape."""
    try:
        with urllib.request.urlopen(f"http://{host}:{port}/3/Fleet",
                                    timeout=timeout_s) as r:
            doc = json.loads(r.read().decode())
    except Exception:
        return None
    rows = doc.get("peers") or []
    if not any(str(p.get("name", "")).startswith("rank")
               and not p.get("is_self") for p in rows):
        return None
    out = []
    for p in rows:
        name = str(p.get("name", ""))
        if not (name.startswith("rank") or p.get("is_self")):
            continue   # serving replicas: already in the fleet section
        row = dict(name=("rank0" if p.get("is_self") and
                         not name.startswith("rank") else name),
                   peer_up=1 if p.get("up") else 0)
        base = p.get("url") or f"http://{host}:{port}"
        if row["peer_up"]:
            skew = _peer_skew(base, timeout_s)
            if skew:
                row.update(skew)
        out.append(row)
    return out or None


def router_summary(host: str, port: int,
                   timeout_s: float = 10.0) -> Optional[Dict]:
    """Router fold of a fleet-router target (`GET /3/Router?probe=0`,
    stdlib-only): shed/failover/rollback counters, ring liveness, and the
    per-model live/canary/shadow version pointers. None when the target
    has no router surface — the report omits the section, same stance as
    fleet_summary."""
    url = f"http://{host}:{port}/3/Router?probe=0"
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as r:
            doc = json.loads(r.read().decode())
    except Exception:
        return None
    if "ring" not in doc:
        return None
    totals = doc.get("totals") or {}
    return dict(
        totals=totals,
        replicas=len(doc.get("ring") or []),
        replicas_up=sum(1 for p in doc.get("ring") or [] if p.get("up")),
        drained=sum(1 for p in doc.get("ring") or [] if p.get("drained")),
        versions={m: dict(live=e.get("live"), canary=e.get("canary"),
                          canary_pct=e.get("canary_pct"),
                          shadow=e.get("shadow"))
                  for m, e in (doc.get("models") or {}).items()},
        canary_health=doc.get("canary_health") or {},
    )


def _router_lane_p99(host: str, port: int,
                     timeout_s: float = 10.0) -> Optional[Dict]:
    """Per-lane (live/canary/unversioned) p99 from the router's
    `h2o3_router_request_ms` histogram, via the JSON registry export —
    the per-version latency split of the run (stdlib bucket
    interpolation over the same shared bounds)."""
    url = f"http://{host}:{port}/3/Metrics?format=json"
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as r:
            doc = json.loads(r.read().decode())
    except Exception:
        return None
    fam = doc.get("h2o3_router_request_ms")   # export_state is the
    if not fam:                               # family map itself
        return None
    out = {}
    for s in fam.get("series") or ():
        labels = s.get("labels") or []
        lane = labels[0] if labels else "all"
        h = _BucketHist(fam.get("bounds") or LATENCY_MS_BOUNDS)
        h.counts = list(s.get("counts") or h.counts)
        h.n = int(s.get("n") or 0)
        h.vmin, h.vmax = s.get("min"), s.get("max")
        out[lane] = dict(n=h.n, p99_ms=h.percentile(0.99))
    return out or None


def _router_delta_report(before: Optional[Dict], after: Optional[Dict],
                         wall_s: float, offered: int = 0,
                         lane_p99: Optional[Dict] = None) -> Optional[Dict]:
    """The loadgen summary's router section: the AFTER snapshot plus
    counter deltas over this run — shed rate vs offered load, failover/
    retry/drain counts, and rollback EVENTS (a rollback delta > 0 means a
    canary was auto-aborted mid-run). `lane_p99` carries the per-version
    latency split when the registry export is reachable."""
    if after is None:
        return None
    out = dict(after)
    bt = (before or {}).get("totals") or {}
    at = after.get("totals") or {}
    deltas = {}
    for fld in ("shed", "errors", "retries", "failovers", "drains",
                "rollbacks"):
        if at.get(fld) is not None:
            deltas[fld] = max(at[fld] - (bt.get(fld) or 0), 0)
    out["deltas"] = deltas
    if offered > 0 and "shed" in deltas:
        out["shed_rate"] = round(deltas["shed"] / offered, 4)
    out["rollback_events"] = deltas.get("rollbacks", 0)
    if lane_p99:
        out["lane_p99_ms"] = lane_p99
    return out


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return float("nan")
    i = min(int(q * len(sorted_vals)), len(sorted_vals) - 1)
    return sorted_vals[i]


def _predict_url(host: str, port: int, model: str, frame: str,
                 router: bool = False) -> str:
    # router mode drives the fleet entry point (version split + failover)
    # instead of one replica's /3/Predictions
    base = "/3/Router/models/" if router else "/3/Predictions/models/"
    return (f"http://{host}:{port}{base}"
            f"{urllib.parse.quote(model)}/frames/"
            f"{urllib.parse.quote(frame)}")


def run_load(host: str, port: int, model: str, frame: str,
             threads: int = 8, requests: int = 50,
             duration_s: Optional[float] = None,
             timeout_s: float = 60.0, router: bool = False) -> Dict:
    """Drive the predict route closed-loop; returns the stats dict.

    `duration_s` caps wall-clock instead of request count when set (each
    thread stops issuing new requests once the deadline passes).
    `router=True` drives the fleet router entry point instead of a
    replica's predict route."""
    url = _predict_url(host, port, model, frame, router=router)
    lock = threading.Lock()
    lat_s: List[float] = []
    shed = [0]
    errors = [0]
    t_end = (time.monotonic() + duration_s) if duration_s else None
    reg_hist = _registry_hist()

    def worker():
        for _ in range(requests):
            if t_end is not None and time.monotonic() >= t_end:
                break
            t0 = time.monotonic()
            try:
                req = urllib.request.Request(url, data=b"")
                with urllib.request.urlopen(req, timeout=timeout_s) as r:
                    r.read()
                lat = time.monotonic() - t0
                if reg_hist is not None:
                    reg_hist.observe(lat * 1e3, "closed")
                with lock:
                    lat_s.append(lat)
            except urllib.error.HTTPError as e:
                e.read()
                with lock:
                    (shed if e.code == 429 else errors)[0] += 1
            except OSError:
                with lock:
                    errors[0] += 1

    t0 = time.monotonic()
    ts = [threading.Thread(target=worker, daemon=True)
          for _ in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    wall = max(time.monotonic() - t0, 1e-9)
    srt = sorted(lat_s)
    return dict(
        url=url, threads=threads, requests_per_thread=requests,
        completed=len(srt), shed_429=shed[0], errors=errors[0],
        wall_s=round(wall, 3),
        throughput_rps=round(len(srt) / wall, 2),
        p50_ms=round(_percentile(srt, 0.50) * 1e3, 3) if srt else None,
        p99_ms=round(_percentile(srt, 0.99) * 1e3, 3) if srt else None,
    )


def run_load_open(host: str, port: int, model: str, frame: str,
                  rate: float = 20.0, duration_s: float = 10.0,
                  timeout_s: float = 60.0, max_inflight: int = 256,
                  router: bool = False) -> Dict:
    """Drive the predict route open-loop at a fixed arrival rate.

    One dispatcher thread fires a request thread at each scheduled arrival
    (`t0 + i/rate`), never waiting for earlier responses — queueing delay
    shows up as latency, not as reduced load. `max_inflight` is the
    safety valve: arrivals beyond it are counted `dropped` (a dropped
    arrival means the server is more than `max_inflight` requests behind
    the schedule, itself an SLO verdict) instead of growing threads
    without bound.

    Percentiles come from the shared fixed latency buckets
    (LATENCY_MS_BOUNDS — the same bounds the serving histograms use), so
    they are directly comparable with `GET /3/Metrics` and with every
    other loadgen/bench report; `hist_*` fields carry the raw bucket
    vector for the bench JSON.

    Leak canary (sustained mode): RSS + memory-ledger totals are sampled
    once per decile of the arrival schedule, and the report carries the
    least-squares growth slopes (`mem_growth_bytes_per_min`,
    `ledger_growth_bytes_per_min`) — a sustained run whose memory climbs
    is a leak verdict even when every request succeeded. RSS sampling is
    stdlib-only; the ledger column stays None in the standalone CLI."""
    if rate <= 0:
        raise ValueError(f"open-loop rate must be > 0 req/s (got {rate})")
    url = _predict_url(host, port, model, frame, router=router)
    n_arrivals = max(int(rate * duration_s), 1)
    lock = threading.Lock()
    # per-run local histogram over the SAME shared bounds: the report must
    # cover this run only, while the registered family below (in-process
    # runs only) accumulates process-wide for the scrape surface
    hist = _BucketHist()
    reg_hist = _registry_hist()
    counts = dict(completed=0, shed_429=0, errors=0, dropped=0)
    inflight = [0]
    live: List[threading.Thread] = []

    def one_request():
        t_req = time.monotonic()
        try:
            req = urllib.request.Request(url, data=b"")
            with urllib.request.urlopen(req, timeout=timeout_s) as r:
                r.read()
            lat_ms = (time.monotonic() - t_req) * 1e3
            with lock:
                hist.observe(lat_ms)
            if reg_hist is not None:
                reg_hist.observe(lat_ms, "open")
            with lock:
                counts["completed"] += 1
        except urllib.error.HTTPError as e:
            e.read()
            with lock:
                counts["shed_429" if e.code == 429 else "errors"] += 1
        except OSError:
            with lock:
                counts["errors"] += 1
        finally:
            with lock:
                inflight[0] -= 1

    mem_samples: List[Dict] = []
    sample_every = max(n_arrivals // 10, 1)

    def _sample_mem(t0: float) -> None:
        mem_samples.append(dict(t_s=round(time.monotonic() - t0, 3),
                                rss_bytes=_read_rss_bytes(),
                                ledger_bytes=_ledger_total_bytes()))

    t0 = time.monotonic()
    for i in range(n_arrivals):
        if i % sample_every == 0:
            _sample_mem(t0)
        target = t0 + i / rate
        delay = target - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        with lock:
            if inflight[0] >= max_inflight:
                counts["dropped"] += 1
                continue
            inflight[0] += 1
        t = threading.Thread(target=one_request, daemon=True)
        t.start()
        live.append(t)
    # wall is the offered-load window (the arrival schedule), measured
    # BEFORE draining stragglers: one request hanging to its timeout must
    # show up as drain/latency, not deflate achieved_rps into a phantom
    # throughput collapse
    wall = max(time.monotonic() - t0, 1e-9)
    deadline = time.monotonic() + timeout_s + 5.0
    for t in live:
        t.join(timeout=max(deadline - time.monotonic(), 0.0))
    drain = max(time.monotonic() - t0 - wall, 0.0)
    _sample_mem(t0)   # final sample after the drain closes the series
    summary = hist.summary()
    offered = n_arrivals
    return dict(
        url=url, mode="open", rate_rps=rate,
        duration_s=round(duration_s, 3), offered=offered,
        completed=counts["completed"], shed_429=counts["shed_429"],
        errors=counts["errors"], dropped=counts["dropped"],
        wall_s=round(wall, 3), drain_s=round(drain, 3),
        achieved_rps=round(counts["completed"] / wall, 2),
        p50_ms=(round(summary["p50"], 3)
                if summary["p50"] is not None else None),
        p95_ms=(round(summary["p95"], 3)
                if summary["p95"] is not None else None),
        p99_ms=(round(summary["p99"], 3)
                if summary["p99"] is not None else None),
        mean_ms=summary["mean"], max_ms=summary["max"],
        hist_bounds_ms=summary["bounds"], hist_counts=summary["counts"],
        mem_samples=mem_samples,
        mem_growth_bytes_per_min=_growth_bytes_per_min(mem_samples,
                                                       "rss_bytes"),
        ledger_growth_bytes_per_min=_growth_bytes_per_min(mem_samples,
                                                          "ledger_bytes"),
        stream=_stream_totals(),
    )


def _merge_open_windows(windows: List[Dict]) -> Dict:
    """Fold several `run_load_open` reports into one: bucket counts add
    (same fixed bounds), percentiles re-estimated from the merged
    histogram, counters summed. The contended phase of a concurrent sweep
    is measured as repeated windows (the sweep's wall is not known up
    front), and the SLO verdict wants ONE p99 over all of them."""
    h = _BucketHist()
    out = dict(mode="open", windows=len(windows), completed=0, offered=0,
               shed_429=0, errors=0, dropped=0, wall_s=0.0)
    for w in windows:
        for i, c in enumerate(w.get("hist_counts") or []):
            h.counts[i] += int(c)
            h.n += int(c)
        if w.get("mean_ms") is not None and w.get("completed"):
            h.total += w["mean_ms"] * w["completed"]
        if w.get("max_ms") is not None:
            h.vmax = (w["max_ms"] if h.vmax is None
                      else max(h.vmax, w["max_ms"]))
        for k in ("completed", "offered", "shed_429", "errors", "dropped"):
            out[k] += int(w.get(k) or 0)
        out["wall_s"] = round(out["wall_s"] + (w.get("wall_s") or 0.0), 3)
    s = h.summary()
    out.update(p50_ms=(round(s["p50"], 3) if s["p50"] is not None else None),
               p95_ms=(round(s["p95"], 3) if s["p95"] is not None else None),
               p99_ms=(round(s["p99"], 3) if s["p99"] is not None else None),
               hist_bounds_ms=s["bounds"], hist_counts=s["counts"])
    if out["wall_s"]:
        out["achieved_rps"] = round(out["completed"] / out["wall_s"], 2)
    return out


def _run_sweep_inprocess(candidates: int, rows: int, ntrees: int,
                         out: Dict) -> None:
    """The training half of `--concurrent-sweep`: a GBM grid (depths 3..)
    through the TrainPool in THIS process — the same accelerator the
    serving path scores on. `score_tree_interval=1` gives per-tree chunk
    boundaries, i.e. the densest QoS yield cadence the tree driver offers.
    NOT stdlib-only (the plain CLI modes stay so): requires the platform
    importable where the server runs."""
    import numpy as np

    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
    from h2o3_tpu.runtime.trainpool import TrainPool

    rng = np.random.default_rng(11)
    X = rng.normal(size=(rows, 8))
    yv = (X @ rng.normal(size=8) + 0.5 * rng.normal(size=rows) > 0)
    fr = Frame.from_numpy(
        np.column_stack([X, yv.astype(float)]),
        names=[f"f{i}" for i in range(8)] + ["label"]).asfactor("label")

    def make(depth: int):
        def fit(job=None):
            est = H2OGradientBoostingEstimator(
                ntrees=ntrees, max_depth=depth, seed=42,
                score_tree_interval=1)
            est.train(y="label", training_frame=fr)
            return est

        return fit

    t0 = time.monotonic()
    pool = TrainPool(parallelism=1, label="qos_sweep")
    recs = pool.run([(f"gbm_depth{3 + i}", make(3 + i))
                     for i in range(candidates)])
    out["wall_s"] = round(time.monotonic() - t0, 3)
    out["candidates"] = candidates
    out["done"] = sum(1 for r in recs if r.status == "done")
    out["statuses"] = {r.name: r.status for r in recs}


def run_concurrent_sweep(host: str, port: int, model: str, frame: str,
                         rate: float, window_s: float = 8.0,
                         candidates: int = 4, sweep_rows: int = 20000,
                         sweep_ntrees: int = 10, timeout_s: float = 60.0,
                         max_inflight: int = 256, router: bool = False,
                         idle: bool = True) -> Dict:
    """`--concurrent-sweep`: the multi-tenant QoS measurement shape.

    Phase 1 (idle, optional): one open-loop window against a quiet server —
    the near-idle SLO baseline. Phase 2 (contended): the SAME open-loop
    load re-run in repeated `window_s` windows while an in-process
    `candidates`-way GBM grid sweep trains on the same accelerator; windows
    repeat until the sweep completes and fold into one histogram. The
    report carries split idle-vs-contended p50/p95/p99 plus the sweep's
    wall time — the numbers the `BENCH_CONFIG=qos` lane embeds.

    Requires the platform importable in this process (the sweep trains
    here); the plain closed/open CLI modes stay stdlib-only."""
    out: Dict = dict(mode="concurrent_sweep", rate_rps=rate,
                     window_s=window_s)
    if idle:
        out["idle"] = run_load_open(host, port, model, frame, rate=rate,
                                    duration_s=window_s, timeout_s=timeout_s,
                                    max_inflight=max_inflight, router=router)
    sweep: Dict = {}
    err: List[BaseException] = []

    def _sweep():
        try:
            _run_sweep_inprocess(candidates, sweep_rows, sweep_ntrees, sweep)
        except BaseException as e:   # surfaced in the report, not swallowed
            err.append(e)

    th = threading.Thread(target=_sweep, daemon=True,
                          name="loadgen-concurrent-sweep")
    t0 = time.monotonic()
    th.start()
    windows: List[Dict] = []
    # at least one contended window, then keep offering load until the
    # sweep lands (hard cap so a hung sweep cannot spin the loadgen
    # forever — the partial report still carries every finished window)
    while True:
        windows.append(run_load_open(host, port, model, frame, rate=rate,
                                     duration_s=window_s,
                                     timeout_s=timeout_s,
                                     max_inflight=max_inflight,
                                     router=router))
        if not th.is_alive():
            break
        if time.monotonic() - t0 > 1200:
            out["sweep_timeout"] = True
            break
    th.join(timeout=60.0)
    if err:
        sweep["error"] = f"{type(err[0]).__name__}: {err[0]}"
    out["contended"] = _merge_open_windows(windows)
    out["contended_windows"] = windows
    out["sweep"] = sweep
    out["completed"] = out["contended"]["completed"]
    idle_p99 = (out.get("idle") or {}).get("p99_ms")
    cont_p99 = out["contended"].get("p99_ms")
    if idle_p99 and cont_p99:
        out["p99_contended_over_idle"] = round(cont_p99 / idle_p99, 3)
    return out


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--model", required=True, help="DKV model key")
    ap.add_argument("--frame", required=True, help="DKV frame key")
    ap.add_argument("--threads", type=int, default=8)
    ap.add_argument("--requests", type=int, default=50,
                    help="requests per thread (closed-loop)")
    ap.add_argument("--duration-s", type=float, default=None,
                    help="closed-loop: stop issuing after this many "
                         "seconds; open-loop: run length (default 10)")
    ap.add_argument("--rate", type=float, default=None,
                    help="open-loop arrival rate (req/s); setting this "
                         "selects open-loop mode")
    ap.add_argument("--max-inflight", type=int, default=256,
                    help="open-loop: arrivals beyond this many in flight "
                         "are dropped (overload safety valve)")
    ap.add_argument("--fleet", action="store_true",
                    help="target is a fleet aggregator: report fleet-"
                         "scope throughput/p99 and per-replica error "
                         "counts from GET /3/Fleet in the summary")
    ap.add_argument("--router", action="store_true",
                    help="drive the fleet router entry point "
                         "(/3/Router/models/..) instead of a replica's "
                         "/3/Predictions, and report shed rate, per-"
                         "version p99 split and rollback events from "
                         "GET /3/Router in the summary")
    ap.add_argument("--concurrent-sweep", action="store_true",
                    help="multi-tenant QoS mode: launch an in-process GBM "
                         "grid sweep and report split idle-vs-contended "
                         "p50/p95/p99 plus sweep wall time (open-loop; "
                         "requires --rate and the platform importable in "
                         "this process)")
    ap.add_argument("--sweep-candidates", type=int, default=4,
                    help="concurrent-sweep: grid size (default 4)")
    ap.add_argument("--sweep-rows", type=int, default=20000,
                    help="concurrent-sweep: synthetic training rows")
    ap.add_argument("--sweep-ntrees", type=int, default=10,
                    help="concurrent-sweep: trees per candidate")
    args = ap.parse_args()
    if args.rate is not None and args.rate <= 0:
        ap.error("--rate must be > 0 (requests per second)")
    if args.concurrent_sweep:
        if args.rate is None:
            ap.error("--concurrent-sweep is open-loop: set --rate")
        stats = run_concurrent_sweep(
            args.host, args.port, args.model, args.frame, rate=args.rate,
            window_s=args.duration_s or 8.0,
            candidates=args.sweep_candidates, sweep_rows=args.sweep_rows,
            sweep_ntrees=args.sweep_ntrees, max_inflight=args.max_inflight,
            router=args.router)
        print(json.dumps(stats, indent=2))
        return 0 if (stats["completed"]
                     and stats["sweep"].get("done")) else 1
    fleet_before = (fleet_summary(args.host, args.port)
                    if args.fleet else None)
    router_before = (router_summary(args.host, args.port)
                     if args.router else None)
    if args.rate is not None:
        stats = run_load_open(args.host, args.port, args.model, args.frame,
                              rate=args.rate,
                              duration_s=args.duration_s or 10.0,
                              max_inflight=args.max_inflight,
                              router=args.router)
    else:
        stats = run_load(args.host, args.port, args.model, args.frame,
                         threads=args.threads, requests=args.requests,
                         duration_s=args.duration_s, router=args.router)
    if args.fleet:
        stats["fleet"] = _fleet_delta_report(
            fleet_before, fleet_summary(args.host, args.port),
            stats.get("wall_s") or 0.0)
        rk = ranks_summary(args.host, args.port)
        if rk:
            stats["ranks"] = rk
    if args.router:
        offered = stats.get("offered") or (
            stats.get("completed", 0) + stats.get("shed_429", 0)
            + stats.get("errors", 0))
        stats["router"] = _router_delta_report(
            router_before, router_summary(args.host, args.port),
            stats.get("wall_s") or 0.0, offered=offered,
            lane_p99=_router_lane_p99(args.host, args.port))
    print(json.dumps(stats, indent=2))
    return 0 if stats["completed"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
