"""Distributed quantiles (hex/quantile/Quantile.java equivalent): psum-merged
histograms + iterative refinement, tested on the 8-device cloud."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jax.sharding import PartitionSpec as P

from h2o3_tpu.ops.quantiles import distributed_quantiles
from h2o3_tpu.parallel import mesh as cloudlib
from h2o3_tpu.parallel.mesh import shard_map  # version-compat export


def test_single_device_matches_numpy(cloud1):
    rng = np.random.default_rng(0)
    x = rng.normal(size=100_000).astype(np.float32)
    w = np.ones_like(x)
    probs = (0.01, 0.25, 0.5, 0.75, 0.99)
    q = np.asarray(distributed_quantiles(jnp.asarray(x), jnp.asarray(w), probs))
    ref = np.quantile(x, probs)
    np.testing.assert_allclose(q, ref, atol=2e-3)


def test_weighted_and_nan(cloud1):
    x = jnp.asarray([1.0, 2.0, 3.0, np.nan, 100.0], jnp.float32)
    w = jnp.asarray([1.0, 1.0, 1.0, 1.0, 0.0], jnp.float32)  # mask the 100
    q = np.asarray(distributed_quantiles(x, w, (0.5,)))
    assert abs(q[0] - 2.0) < 0.01


def test_sharded_equals_global(cloud8):
    rng = np.random.default_rng(1)
    n = 8 * 4096
    x = rng.lognormal(size=n).astype(np.float32)
    w = np.ones_like(x)
    probs = (0.1, 0.5, 0.9)

    fn = jax.jit(
        shard_map(
            lambda x, w: distributed_quantiles(
                x, w, probs, axis_name=cloudlib.ROWS_AXIS),
            mesh=cloud8.mesh,
            in_specs=(P(cloudlib.ROWS_AXIS), P(cloudlib.ROWS_AXIS)),
            out_specs=P(),
        )
    )
    xd = jax.device_put(jnp.asarray(x), cloud8.row_sharding())
    wd = jax.device_put(jnp.asarray(w), cloud8.row_sharding())
    q = np.asarray(fn(xd, wd))
    ref = np.quantile(x, probs)
    np.testing.assert_allclose(q, ref, rtol=1e-3)
