"""Serving subsystem (h2o3_tpu/serving/) — compiled-scorer cache,
micro-batching, admission control, metrics, and the REST predict rewiring.

CPU-only, tier-1 friendly. The acceptance pins from the PR issue live
here: a warm second `/3/Predictions` call moves only the cache-hit counter
(no new compile), and 16 concurrent requests for one model are served in
≤ 4 device batches.
"""

import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.runtime.dkv import DKV
from h2o3_tpu.serving import (RejectedError, ScoringEngine, get_engine,
                              reset_engine)
from h2o3_tpu.serving.admission import AdmissionController
from h2o3_tpu.serving.batcher import MicroBatcher
from h2o3_tpu.serving.config import ServingConfig
from h2o3_tpu.serving.metrics import LatencyHistogram, ServingMetrics
from h2o3_tpu.serving.model_cache import (CompiledScorer, ScorerCache,
                                          bucket_rows)


class StubModel:
    """Deterministic numpy 'model': predict = row sum. `fail_above`
    poisons rows whose first column exceeds it (error-isolation tests);
    `delay_s` simulates device time (batching-window tests)."""

    def __init__(self, n_features=3, fail_above=None, delay_s=0.0,
                 gate=None):
        self.x = [f"f{i}" for i in range(n_features)]
        self.fail_above = fail_above
        self.delay_s = delay_s
        self.gate = gate            # threading.Event: block until set
        self.calls = 0

    def predict(self, fr):
        self.calls += 1
        if self.gate is not None:
            assert self.gate.wait(timeout=30)
        if self.delay_s:
            time.sleep(self.delay_s)
        X = np.column_stack([fr.vec(n).numeric_np() for n in self.x])
        if self.fail_above is not None and np.any(X[:, 0] > self.fail_above):
            raise ValueError("poisoned rows in batch")
        return Frame.from_dict({"predict": X.sum(axis=1)})


def _frame(n_rows, n_features=3, base=0.0):
    rng = np.random.default_rng(int(base * 1000) % 2**31)
    return Frame.from_dict(
        {f"f{i}": base + rng.random(n_rows) for i in range(n_features)})


def _cfg(**kw):
    return ServingConfig(**{**dict(
        max_batch_rows=4096, max_wait_ms=5.0, request_timeout_s=30.0,
        idle_worker_s=2.0, max_queue=64, model_inflight=64,
        retry_after_s=1.0, cache_capacity=8), **kw})


# -- model_cache ------------------------------------------------------------
def test_bucket_rows_padding_ladder():
    assert bucket_rows(1) == 64
    assert bucket_rows(64) == 64
    assert bucket_rows(65) == 128
    assert bucket_rows(200) == 256
    assert bucket_rows(300) == 512
    assert bucket_rows(513) == 1024
    assert bucket_rows(1025) == 1536


def test_cache_hit_miss_eviction():
    cache = ScorerCache(capacity=2)
    m1, m2, m3 = StubModel(), StubModel(), StubModel()
    e1, hit = cache.get_or_build("m1", m1)
    assert not hit and cache.misses == 1
    e1b, hit = cache.get_or_build("m1", m1)
    assert hit and e1b is e1 and cache.hits == 1
    cache.get_or_build("m2", m2)
    cache.get_or_build("m3", m3)          # capacity 2 → m1 evicted
    assert cache.evictions == 1
    _, hit = cache.get_or_build("m1", m1)
    assert not hit                         # rebuilt after eviction
    assert len(cache) == 2


def test_cache_stale_model_identity_rebuilds():
    """Re-training under the same DKV key must not serve the old model's
    executable."""
    cache = ScorerCache(capacity=4)
    old, new = StubModel(), StubModel()
    e_old, _ = cache.get_or_build("m", old)
    e_new, hit = cache.get_or_build("m", new)
    assert not hit and e_new is not e_old and e_new.model is new


def test_compiled_scorer_pads_and_slices():
    entry = CompiledScorer("m", StubModel(), "predict")
    fr = _frame(10)
    out, compiled, _ = entry.score(fr)
    assert compiled                        # cold bucket 64
    assert out.nrow == 10                  # pad rows sliced off
    expect = sum(fr.vec(n).numeric_np() for n in fr.names)
    np.testing.assert_allclose(out.vec("predict").numeric_np(), expect,
                               rtol=1e-6)
    _, compiled, _ = entry.score(_frame(37))
    assert not compiled                    # 37 → same 64 bucket: warm
    _, compiled, _ = entry.score(_frame(100))
    assert compiled                        # 100 → new 128 bucket
    assert entry.warm_buckets == {64, 128}


def test_unsupported_output_kind_raises_value_error():
    with pytest.raises(ValueError, match="does not support contributions"):
        CompiledScorer("m", StubModel(), "contributions")


# -- metrics ----------------------------------------------------------------
def test_latency_histogram_buckets_and_stats():
    h = LatencyHistogram((1, 10, 100))
    for v in (0.5, 5, 50, 500):
        h.record(v)
    assert h.counts == [1, 1, 1, 1]        # one per bucket incl. overflow
    s = h.snapshot()
    assert s["count"] == 4 and s["min"] == 0.5 and s["max"] == 500


def test_metrics_snapshot_totals():
    m = ServingMetrics()
    m.record_request("a")
    m.record_request("b")
    m.record_rejection("b")
    m.record_batch("a", n_requests=3, n_rows=24, device_s=0.01,
                   compiled=True)
    m.record_batch("a", n_requests=1, n_rows=8, device_s=0.001,
                   compiled=False)
    snap = m.snapshot()
    assert snap["totals"]["requests"] == 2
    assert snap["totals"]["rejections"] == 1
    a = snap["models"]["a"]["counters"]
    assert a["batches"] == 2 and a["batched_requests"] == 4
    assert a["compiles"] == 1 and a["cache_hits"] == 1


# -- admission control ------------------------------------------------------
def test_admission_global_and_per_model_bounds():
    metrics = ServingMetrics()
    adm = AdmissionController(_cfg(max_queue=3, model_inflight=2), metrics)
    adm.admit("a")
    adm.admit("a")
    with pytest.raises(RejectedError):     # per-model bound
        adm.admit("a")
    adm.admit("b")
    with pytest.raises(RejectedError) as ei:   # global bound
        adm.admit("c")
    assert ei.value.retry_after_s == 1.0
    adm.release("a")
    adm.admit("c")                         # slot freed
    assert metrics.counter("a", "rejections") == 1
    assert metrics.counter("c", "rejections") == 1
    assert adm.stats()["in_flight"] == 3


def test_engine_backpressure_sheds_excess_concurrency():
    gate = threading.Event()
    model = StubModel(gate=gate)
    eng = ScoringEngine(_cfg(max_queue=2, max_wait_ms=1.0))
    results, rejects = [], []

    def call(i):
        try:
            results.append(eng.score("m", model, _frame(4)))
        except RejectedError:
            rejects.append(i)

    ts = [threading.Thread(target=call, args=(i,)) for i in range(6)]
    for t in ts:
        t.start()
    time.sleep(0.3)          # let all six hit admission while gate is shut
    gate.set()
    for t in ts:
        t.join(timeout=30)
    assert len(rejects) == 4 and len(results) == 2
    assert eng.metrics.counter("m", "rejections") == 4
    eng.shutdown()


# -- micro-batcher ----------------------------------------------------------
def test_batcher_coalesces_16_concurrent_into_few_batches():
    """Acceptance: 16 concurrent requests for one model → ≤ 4 device
    batches (and every caller gets exactly its own rows back)."""
    model = StubModel(delay_s=0.02)
    eng = ScoringEngine(_cfg(max_wait_ms=60.0, max_batch_rows=4096))
    # warm the scorer so the first batch's window isn't spent compiling
    eng.score("m", model, _frame(8, base=0.5))
    before = eng.metrics.counter("m", "batches")

    def call(i):
        fr = _frame(8, base=float(i + 1))
        out = eng.score("m", model, fr)
        expect = sum(fr.vec(n).numeric_np() for n in fr.names)
        np.testing.assert_allclose(out.vec("predict").numeric_np(),
                                   expect, rtol=1e-6)
        return out.nrow

    with ThreadPoolExecutor(max_workers=16) as ex:
        rows = list(ex.map(call, range(16)))
    assert rows == [8] * 16
    snap = eng.metrics.snapshot()["models"]["m"]["counters"]
    n_batches = snap["batches"] - before
    assert n_batches <= 4, f"16 concurrent requests took {n_batches} batches"
    assert snap["batched_rows"] == 8 + 16 * 8
    eng.shutdown()


def test_batch_error_isolation():
    """A poisoned request fails alone; coalesced batch-mates still get
    their predictions (per-request rescore fallback)."""
    model = StubModel(fail_above=100.0, delay_s=0.02)
    eng = ScoringEngine(_cfg(max_wait_ms=80.0))
    eng.score("m", model, _frame(4, base=0.5))     # warm → fast batches

    oks, errs = [], []

    def good(i):
        out = eng.score("m", model, _frame(4, base=float(i + 1)))
        oks.append(out.nrow)

    def bad():
        try:
            eng.score("m", model, Frame.from_dict(
                {"f0": [1e6, 2.0], "f1": [0.1, 0.2], "f2": [0.1, 0.2]}))
        except ValueError as e:
            errs.append(str(e))

    threads = ([threading.Thread(target=good, args=(i,)) for i in range(6)]
               + [threading.Thread(target=bad)])
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert oks == [4] * 6                  # every good request answered
    assert errs and "poisoned" in errs[0]  # the bad one got ITS error
    assert eng.metrics.counter("m", "errors") == 1
    eng.shutdown()


def test_batcher_retires_expired_pendings_unscored():
    """Requests whose caller already timed out (admission slot released)
    are retired at pop time instead of scored: abandoned work must not
    consume device time, and the deque cannot grow past the live set
    under sustained overload (ROADMAP item)."""
    from h2o3_tpu.serving.batcher import _Pending
    from h2o3_tpu.serving.metrics import ServingMetrics
    from h2o3_tpu.serving.model_cache import ScorerCache

    model = StubModel()
    gate = threading.Event()
    blocker = StubModel(gate=gate)
    metrics = ServingMetrics()
    cfg = _cfg(request_timeout_s=0.15, max_wait_ms=1.0)
    batcher = MicroBatcher(ScorerCache(4), metrics, cfg)

    # a caller that will give up (its model blocks past the timeout)
    def abandoned():
        with pytest.raises(TimeoutError):
            batcher.submit("m", blocker, _frame(4, base=1.0))

    t = threading.Thread(target=abandoned)
    t.start()
    time.sleep(0.05)
    # pile queued requests behind the blocked batch; their callers all
    # time out before the worker ever gets to them
    stale = [_Pending(_frame(2, base=float(i + 2)), blocker)
             for i in range(5)]
    with batcher._lock:
        w = batcher._workers[("m", "predict")]
        with w.cond:
            w.q.extend(stale)
            w.cond.notify_all()
    t.join(timeout=10)
    time.sleep(0.3)            # let every stale entry pass its timeout
    gate.set()                 # unblock the in-flight batch
    # a FRESH live request is still served promptly...
    out = batcher.submit("m", model, _frame(3, base=9.0))
    assert out.nrow == 3
    # ...and the stale ones were retired unscored (blocker scored only its
    # first batch — the expired queue never reached the device)
    deadline = time.time() + 5
    while time.time() < deadline \
            and metrics.counter("m", "expired") < len(stale):
        time.sleep(0.02)
    assert metrics.counter("m", "expired") == len(stale)
    assert blocker.calls == 1
    for p in stale:
        assert p.result is None and isinstance(p.error, TimeoutError)
    batcher.shutdown()


def test_batcher_schema_mismatch_never_coalesced():
    """Frames with different schemas must not rbind into one batch."""
    class TwoColModel(StubModel):
        def predict(self, fr):
            self.calls += 1
            cols = [fr.vec(n).numeric_np() for n in fr.names]
            return Frame.from_dict({"predict": np.sum(cols, axis=0)})

    model = TwoColModel()
    cfg = _cfg(max_wait_ms=50.0)
    metrics = ServingMetrics()
    batcher = MicroBatcher(ScorerCache(4), metrics, cfg)
    outs = {}

    def call(name, frame):
        outs[name] = batcher.submit("m", model, frame)

    t1 = threading.Thread(target=call, args=("a", _frame(4, n_features=3)))
    t2 = threading.Thread(target=call, args=("b", _frame(4, n_features=2)))
    t1.start()
    t2.start()
    t1.join(timeout=30)
    t2.join(timeout=30)
    assert outs["a"].nrow == 4 and outs["b"].nrow == 4
    assert metrics.counter("m", "batches") == 2   # one per schema
    batcher.shutdown()


def test_idle_worker_expires_and_resurrects():
    model = StubModel()
    eng = ScoringEngine(_cfg(idle_worker_s=0.2, max_wait_ms=1.0))
    assert eng.score("m", model, _frame(4)).nrow == 4
    assert len(eng.batcher._workers) == 1
    deadline = time.time() + 10
    while eng.batcher._workers and time.time() < deadline:
        time.sleep(0.05)
    assert not eng.batcher._workers        # expired after quiet period
    assert eng.score("m", model, _frame(4)).nrow == 4   # fresh worker
    eng.shutdown()


# -- REST rewiring (acceptance: warm second call skips retracing) -----------
@pytest.fixture()
def rest_server():
    from h2o3_tpu.rest import start_server

    srv = start_server(port=0)
    engine = reset_engine(_cfg(max_wait_ms=2.0))
    yield srv, engine
    srv.stop()
    reset_engine()


def _http(method, port, path, headers=None):
    import json as _json
    import urllib.request

    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}",
                                 data=b"" if method == "POST" else None,
                                 method=method, headers=headers or {})
    with urllib.request.urlopen(req) as r:
        return _json.loads(r.read())


def _train_tiny_gbm(tag):
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

    rng = np.random.default_rng(7)
    n = 200
    X = rng.normal(size=(n, 3))
    y = (X[:, 0] + X[:, 1] > 0).astype(np.int64)
    fr = Frame.from_dict(
        {"a": X[:, 0], "b": X[:, 1], "c": X[:, 2],
         "y": np.asarray(["n", "p"], dtype=object)[y]},
        column_types={"y": "enum"})
    fr.key = f"serving_fr_{tag}"
    DKV.put(fr.key, fr)
    est = H2OGradientBoostingEstimator(ntrees=3, max_depth=3, seed=1,
                                       model_id=f"serving_gbm_{tag}")
    est.train(x=["a", "b", "c"], y="y", training_frame=fr)
    m = est.model
    DKV.put(m.model_id, m)
    return m.model_id, fr.key


def test_rest_warm_predict_hits_cache_no_new_compile(rest_server, cloud1):
    """Acceptance: the second `/3/Predictions` call for the same model is
    a pure cache hit — cache_hits increments, compiles does not move."""
    srv, engine = rest_server
    mid, fkey = _train_tiny_gbm("warm")
    r1 = _http("POST", srv.port, f"/3/Predictions/models/{mid}/frames/{fkey}")
    pred_key = r1["predictions_frame"]["name"]
    assert pred_key == f"prediction_{mid}_{fkey}"
    snap1 = _http("GET", srv.port, "/3/Serving/metrics")
    c1 = snap1["models"][mid]["counters"]
    assert c1["compiles"] >= 1

    from h2o3_tpu.runtime import phases

    xla1 = phases.xla_counts()
    r2 = _http("POST", srv.port, f"/3/Predictions/models/{mid}/frames/{fkey}")
    assert r2["predictions_frame"]["name"] == pred_key   # overwrote, same key
    snap2 = _http("GET", srv.port, "/3/Serving/metrics")
    c2 = snap2["models"][mid]["counters"]
    assert c2["compiles"] == c1["compiles"], "warm call re-traced!"
    # the counter pin (ISSUE 6): the warm call records ZERO new XLA traces
    # in the runtime/phases tracker — pinned at the jax-monitoring layer,
    # not just the serving cache's own bookkeeping
    xla2 = phases.xla_counts()
    assert xla2["traces"] == xla1["traces"], "warm predict traced!"
    assert xla2["retraces"] == xla1["retraces"]
    assert c2["cache_hits"] == c1["cache_hits"] + 1
    assert c2["requests"] == c1["requests"] + 1
    # histograms recorded
    h = snap2["models"][mid]["histograms"]
    assert h["queue_wait_ms"]["count"] >= 2
    assert h["batch_size"]["count"] >= 2
    # cache stats ride the same document
    assert snap2["cache"]["size"] >= 1


def test_rest_429_backpressure_with_retry_after(rest_server, cloud1):
    import urllib.error
    import urllib.request

    srv, _ = rest_server
    mid, fkey = _train_tiny_gbm("shed")
    reset_engine(_cfg(max_queue=0))        # reject everything
    with pytest.raises(urllib.error.HTTPError) as ei:
        _http("POST", srv.port, f"/3/Predictions/models/{mid}/frames/{fkey}")
    assert ei.value.code == 429
    assert ei.value.headers["Retry-After"] == "1"
    body = ei.value.read()
    assert b"429" in body or b"retry" in body.lower()
    snap = _http("GET", srv.port, "/3/Serving/metrics")
    assert snap["models"][mid]["counters"]["rejections"] == 1


def test_rest_serving_cache_clear_and_schema(rest_server, cloud1):
    srv, engine = rest_server
    mid, fkey = _train_tiny_gbm("clear")
    _http("POST", srv.port, f"/3/Predictions/models/{mid}/frames/{fkey}")
    assert len(engine.cache) >= 1
    out = _http("DELETE", srv.port, f"/3/Serving/cache?model={mid}")
    assert out["invalidated"] == 1
    sch = _http("GET", srv.port, "/3/Serving/metrics?schema=1")
    assert sch["name"] == "ServingMetricsV3"
    assert any(f["name"] == "cache" for f in sch["fields"])


def test_rest_contributions_via_serving_path(rest_server, cloud1):
    """The contributions output kind rides the serving path too (distinct
    cache entry per output_kind)."""
    srv, engine = rest_server
    mid, fkey = _train_tiny_gbm("contrib")
    r = _http("POST", srv.port,
              f"/3/Predictions/models/{mid}/frames/{fkey}"
              "?predict_contributions=true")
    assert r["predictions_frame"]["name"] == \
        f"prediction_contributions_{mid}_{fkey}"
    kinds = {e["output_kind"] for e in engine.cache.stats()["entries"]}
    assert "contributions" in kinds


def test_profiler_reports_serving_section():
    from h2o3_tpu.runtime import profiler

    reset_engine(_cfg())
    model = StubModel()
    get_engine().score("m", model, _frame(4))
    stats = profiler.serving_stats()
    assert stats["active"] and "m" in stats["models"]
    reset_engine()


# -- loadgen smoke (slow: excluded from tier-1) -----------------------------
@pytest.mark.slow
def test_loadgen_smoke_2s(cloud1):
    import importlib.util
    import os

    from h2o3_tpu.rest import start_server

    spec = importlib.util.spec_from_file_location(
        "loadgen", os.path.join(os.path.dirname(__file__), "..",
                                "deploy", "loadgen.py"))
    loadgen = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(loadgen)

    srv = start_server(port=0)
    reset_engine(_cfg())
    try:
        mid, fkey = _train_tiny_gbm("loadgen")
        stats = loadgen.run_load("127.0.0.1", srv.port, mid, fkey,
                                 threads=4, requests=10_000,
                                 duration_s=2.0)
        assert stats["completed"] > 0 and stats["errors"] == 0
        assert stats["throughput_rps"] > 0
        assert stats["p50_ms"] is not None and stats["p99_ms"] is not None
        snap = get_engine().snapshot()
        assert snap["models"][mid]["counters"]["batches"] >= 1
    finally:
        srv.stop()
        reset_engine()
