"""REST API layer (L8) — /3 endpoint surface over a live loopback server.

Reference parity tests: the route table of `water/api/RequestServer.java`
driven the way `h2o-py/h2o/backend/connection.py` drives it (JSON over HTTP).
"""

import json
import time
import urllib.error
import urllib.parse
import urllib.request

import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu.rest import start_server
from h2o3_tpu.runtime.dkv import DKV


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    srv = start_server(port=0)
    # a small CSV on disk for import
    d = tmp_path_factory.mktemp("rest")
    csv = d / "t.csv"
    rng = np.random.default_rng(0)
    n = 500
    X = rng.normal(size=(n, 3))
    y = (X[:, 0] + X[:, 1] > 0).astype(int)
    with open(csv, "w") as f:
        f.write("a,b,c,y\n")
        for i in range(n):
            f.write(",".join(f"{v:.4f}" for v in X[i]) + f",{y[i]}\n")
    yield srv, str(csv)
    srv.stop()
    DKV.clear()


def _get(srv, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{srv.port}{path}") as r:
        return json.loads(r.read())


def _post(srv, apipath, **params):
    data = urllib.parse.urlencode(params).encode()
    req = urllib.request.Request(f"http://127.0.0.1:{srv.port}{apipath}", data=data)
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read())


def test_cloud_and_about(server):
    srv, _ = server
    c = _get(srv, "/3/Cloud")
    assert c["cloud_name"] == "h2o3_tpu"
    assert "version" in c
    a = _get(srv, "/3/About")
    assert a["entries"]


def test_import_parse_frames(server):
    srv, csv = server
    r = _post(srv, "/3/ImportFiles", path=csv)
    key = r["destination_frames"][0]
    fl = _get(srv, "/3/Frames")
    assert any(f["frame_id"]["name"] == key for f in fl["frames"])
    s = _get(srv, f"/3/Frames/{key}/summary")
    col = s["frames"][0]
    assert col["rows"] == 500 and col["num_columns"] == 4
    setup = _post(srv, "/3/ParseSetup", path=csv)
    assert setup["column_names"] == ["a", "b", "c", "y"]


def test_train_poll_predict_delete(server):
    srv, csv = server
    r = _post(srv, "/3/ImportFiles", path=csv)
    key = r["destination_frames"][0]
    # categorical response via Rapids (the h2o-py client flow: asfactor →
    # Rapids string → train), then train gbm via REST (async job)
    _post(srv, "/99/Rapids",
          ast=f"(assign train2 (cbind (cols {key} [0 1 2])"
              f" (as.factor (cols {key} [3]))))")
    r = _post(srv, "/3/ModelBuilders/gbm", training_frame="train2",
              response_column="y", ntrees="10", max_depth="3",
              distribution="bernoulli")
    job_key = r["job"]["key"]["name"]
    for _ in range(600):
        j = _get(srv, f"/3/Jobs/{job_key}")["jobs"][0]
        if j["status"] in ("DONE", "FAILED"):
            break
        time.sleep(0.25)
    assert j["status"] == "DONE", j
    model_key = j["dest"]["name"]
    m = _get(srv, f"/3/Models/{model_key}")["models"][0]
    assert m["algo"] == "gbm"
    assert m["output"]["training_metrics"]["rmse"] < 0.5
    # predictions
    p = _post(srv, f"/3/Predictions/models/{model_key}/frames/{key}")
    pf = p["predictions_frame"]["name"]
    s = _get(srv, f"/3/Frames/{pf}/summary")["frames"][0]
    assert s["rows"] == 500
    # schemas endpoint lists gbm params
    sch = _get(srv, "/3/ModelBuilders/gbm")
    names = [f["name"] for f in sch["parameters"]]
    assert "ntrees" in names and "learn_rate" in names
    # delete
    _del(srv, f"/3/Models/{model_key}")
    with pytest.raises(urllib.error.HTTPError):
        _get(srv, f"/3/Models/{model_key}")


def _del(srv, path):
    req = urllib.request.Request(f"http://127.0.0.1:{srv.port}{path}",
                                 method="DELETE")
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read())


def test_rapids_endpoint(server):
    srv, csv = server
    r = _post(srv, "/3/ImportFiles", path=csv)
    key = r["destination_frames"][0]
    # scalar reducer
    out = _post(srv, "/99/Rapids", ast=f"(mean (cols {key} [0]))")
    assert abs(out["scalar"]) < 0.2
    # arithmetic + assign
    out = _post(srv, "/99/Rapids", ast=f"(assign tmp1 (* (cols {key} [0]) 2))")
    assert out["key"]["name"] == "tmp1"
    m1 = _post(srv, "/99/Rapids", ast="(mean tmp1)")
    m0 = _post(srv, "/99/Rapids", ast=f"(mean (cols {key} [0]))")
    assert m1["scalar"] == pytest.approx(2 * m0["scalar"], abs=1e-6)
    # nrow / quantile
    out = _post(srv, "/99/Rapids", ast=f"(nrow {key})")
    assert out["scalar"] == 500
    q = _post(srv, "/99/Rapids", ast=f"(quantile (cols {key} [0]) [0.5])")
    assert "key" in q or "columns" in q


def test_logs_timeline_profiler_metadata(server):
    srv, _ = server
    logs = _get(srv, "/3/Logs")
    assert isinstance(logs["logs"], list)
    tl = _get(srv, "/3/Timeline")
    assert any(e["kind"] == "rest" for e in tl["events"])
    prof = _get(srv, "/3/Profiler")
    assert prof["nodes"][0]["entries"]
    meta = _get(srv, "/3/Metadata/schemas")
    # algo builder schemas plus non-algo ones (ObservabilityV3)
    algos = [s["algo"] for s in meta["schemas"] if "algo" in s]
    assert {"gbm", "glm", "deeplearning", "kmeans"} <= set(algos)


def test_error_handling(server):
    srv, _ = server
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(srv, "/3/Models/nonexistent")
    assert e.value.code == 404
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(srv, "/3/ModelBuilders/nosuchalgo", training_frame="x")
    assert e.value.code == 404


def test_rapids_extended_prims(server):
    srv, csv = server
    r = _post(srv, "/3/ImportFiles", path=csv)
    key = r["destination_frames"][0]
    # sort by column 0 ascending → first value is the min
    out = _post(srv, "/99/Rapids", ast=f"(assign srt (sort {key} [0]))")
    mn = _post(srv, "/99/Rapids", ast=f"(min (cols {key} [0]))")["scalar"]
    first = out["columns"][0]["data"][0]
    assert abs(first - mn) < 1e-6
    # scale → mean 0
    _post(srv, "/99/Rapids", ast=f"(assign sc (scale (cols {key} [0]) 1 1))")
    m = _post(srv, "/99/Rapids", ast="(mean sc)")["scalar"]
    assert abs(m) < 1e-6
    # hist returns a table frame
    h = _post(srv, "/99/Rapids", ast=f"(hist (cols {key} [0]) 5)")
    names = [c["label"] for c in h["columns"]]
    assert set(names) == {"breaks", "counts", "mids"}
    # is.na
    na = _post(srv, "/99/Rapids", ast=f"(sum (is.na (cols {key} [0])))")
    assert na["scalar"] == 0.0


def test_model_metrics_endpoint(server):
    srv, csv = server
    r = _post(srv, "/3/ImportFiles", path=csv)
    key = r["destination_frames"][0]
    _post(srv, "/99/Rapids",
          ast=f"(assign mmtrain (cbind (cols {key} [0 1 2])"
              f" (as.factor (cols {key} [3]))))")
    r = _post(srv, "/3/ModelBuilders/gbm", training_frame="mmtrain",
              response_column="y", ntrees="5", max_depth="3")
    jk = r["job"]["key"]["name"]
    for _ in range(400):
        j = _get(srv, f"/3/Jobs/{jk}")["jobs"][0]
        if j["status"] in ("DONE", "FAILED"):
            break
        time.sleep(0.25)
    assert j["status"] == "DONE", j
    mk = j["dest"]["name"]
    mm = _post(srv, f"/3/ModelMetrics/models/{mk}/frames/mmtrain")
    row = mm["model_metrics"][0]
    assert row["model"]["name"] == mk
    assert 0.5 <= row["auc"] <= 1.0


def test_model_save_load_and_frame_export(server, tmp_path):
    srv, csv = server
    imp = _post(srv, "/3/ImportFiles", path=csv)
    key = imp["destination_frames"][0]
    _post(srv, "/99/Rapids", ast=f"(tmp= expfr (cbind (cols {key} [0 1 2]) (as.factor (cols {key} [3]))))")
    out = _post(srv, "/3/ModelBuilders/gbm",
                training_frame="expfr", response_column="y",
                ntrees=3, max_depth=3)
    import time as _t
    for _ in range(200):
        jobs = _get(srv, "/3/Jobs")["jobs"]
        if all(j["status"] in ("DONE", "FAILED") for j in jobs):
            break
        _t.sleep(0.25)
    models = _get(srv, "/3/Models")["models"]
    assert models, "no model trained via REST"
    mid = models[-1]["model_id"]["name"]
    saved = _post(srv, f"/99/Models.bin/{mid}", dir=str(tmp_path))
    assert saved["path"].endswith(".h2o3")
    loaded = _post(srv, "/99/Models.bin", path=saved["path"])
    assert loaded["models"][0]["model_id"]["name"]
    exp = _post(srv, f"/3/Frames/{key}/export",
                path=str(tmp_path / "out.csv"), force=True)
    assert exp["job"]["status"] == "DONE"
    import os
    assert os.path.exists(tmp_path / "out.csv")


def test_post_file_upload_parse(server):
    srv, csv = server
    body = open(csv, "rb").read()
    req = urllib.request.Request(
        f"http://127.0.0.1:{srv.port}/3/PostFile?destination_frame=up.csv",
        data=body, headers={"Content-Type": "application/octet-stream"})
    with urllib.request.urlopen(req) as r:
        out = json.loads(r.read())
    assert out["total_bytes"] == len(body)
    dest = out["destination_frame"]
    # uploaded key works as a Parse source
    p = _post(srv, "/3/Parse", source_frames=dest,
              destination_frame="uploaded")
    assert p["destination_frame"]["name"] == "uploaded"
    s = _get(srv, "/3/Frames/uploaded/summary")["frames"][0]
    assert s["rows"] == 500 and s["num_columns"] == 4


def test_grid_endpoints(server):
    srv, csv = server
    r = _post(srv, "/3/ImportFiles", path=csv)
    key = r["destination_frames"][0]
    _post(srv, "/99/Rapids",
          ast=f"(assign gtrain (cbind (cols {key} [0 1 2])"
              f" (as.factor (cols {key} [3]))))")
    r = _post(srv, "/99/Grid/gbm", training_frame="gtrain",
              response_column="y", grid_id="g1", ntrees="5",
              hyper_parameters=json.dumps({"max_depth": [2, 3]}))
    assert r["grid_id"] == "g1"
    job_key = r["job"]["key"]["name"]
    for _ in range(600):
        j = _get(srv, f"/3/Jobs/{job_key}")["jobs"][0]
        if j["status"] in ("DONE", "FAILED"):
            break
        time.sleep(0.25)
    assert j["status"] == "DONE", j
    g = _get(srv, "/99/Grids/g1")
    assert len(g["model_ids"]) == 2
    assert g["hyper_names"] == ["max_depth"]
    lst = _get(srv, "/99/Grids")
    assert any(x["grid_id"]["name"] == "g1" for x in lst["grids"])
    # 4xx for bad request, 404 for missing grid
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(srv, "/99/Grid/gbm", training_frame="gtrain",
              response_column="y")
    assert e.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(srv, "/99/Grids/nope")
    assert e.value.code == 404


def test_automl_endpoints(server):
    srv, csv = server
    r = _post(srv, "/3/ImportFiles", path=csv)
    key = r["destination_frames"][0]
    _post(srv, "/99/Rapids",
          ast=f"(assign atrain (cbind (cols {key} [0 1 2])"
              f" (as.factor (cols {key} [3]))))")
    r = _post(srv, "/99/AutoMLBuilder", training_frame="atrain",
              response_column="y", max_models="2", nfolds="2",
              seed="1", project_name="aml_rest")
    job_key = r["job"]["key"]["name"]
    for _ in range(1200):
        j = _get(srv, f"/3/Jobs/{job_key}")["jobs"][0]
        if j["status"] in ("DONE", "FAILED"):
            break
        time.sleep(0.5)
    assert j["status"] == "DONE", j
    lb = _get(srv, "/99/Leaderboards/aml_rest")["leaderboard"]["rows"]
    assert len(lb) >= 2
    a = _get(srv, "/99/AutoML/aml_rest")
    assert a["leader"]["name"] == lb[0]["model_id"]


def test_recovery_endpoint(server, tmp_path):
    srv, csv = server
    r = _post(srv, "/3/ImportFiles", path=csv)
    key = r["destination_frames"][0]
    _post(srv, "/99/Rapids",
          ast=f"(assign rtrain (cbind (cols {key} [0 1 2])"
              f" (as.factor (cols {key} [3]))))")
    import h2o3_tpu as h2o_mod
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
    from h2o3_tpu.models.grid import H2OGridSearch

    gs = H2OGridSearch(H2OGradientBoostingEstimator(ntrees=3),
                       {"max_depth": [2]}, grid_id="grec",
                       recovery_dir=str(tmp_path))
    gs.train(x=["a", "b", "c"], y="y", training_frame=DKV.get("rtrain"))
    out = _post(srv, "/3/Recovery", recovery_dir=str(tmp_path))
    assert out["grid_id"]["name"] == "grec"
    assert len(out["model_ids"]) == 1


def test_automl_poll_while_running(server):
    """Polling /99/AutoML and /99/Leaderboards mid-build must return the
    (possibly empty) board, not 500 (review r02)."""
    srv, csv = server
    r = _post(srv, "/3/ImportFiles", path=csv)
    key = r["destination_frames"][0]
    _post(srv, "/99/Rapids",
          ast=f"(assign ptrain (cbind (cols {key} [0 1 2])"
              f" (as.factor (cols {key} [3]))))")
    r = _post(srv, "/99/AutoMLBuilder", training_frame="ptrain",
              response_column="y", max_models="1", nfolds="2",
              seed="2", project_name="aml_poll")
    # immediately poll — build has barely started
    a = _get(srv, "/99/AutoML/aml_poll")
    assert "leaderboard" in a       # empty board, never a 500
    lb = _get(srv, "/99/Leaderboards/aml_poll")
    assert "leaderboard" in lb
    job_key = r["job"]["key"]["name"]
    for _ in range(1200):
        j = _get(srv, f"/3/Jobs/{job_key}")["jobs"][0]
        if j["status"] in ("DONE", "FAILED"):
            break
        time.sleep(0.5)
    assert j["status"] == "DONE", j


def test_flow_ui_served(server):
    srv, _ = server
    for path in ("/flow/", "/flow/index.html", "/"):
        req = urllib.request.urlopen(f"http://127.0.0.1:{srv.port}{path}")
        body = req.read().decode()
        assert req.headers["Content-Type"].startswith("text/html")
        assert "H2O Flow" in body and "/99/Rapids" in body


def test_tree_endpoint(server):
    """`GET /3/Tree` (hex/tree/TreeHandler analog) over a freshly trained
    GBM."""
    srv, csv = server
    _post(srv, "/3/ImportFiles", path=csv)
    _post(srv, "/3/Parse", source_frames=csv, destination_frame="treefr",
          asfactor="y")
    _post(srv, "/3/ModelBuilders/gbm", training_frame="treefr",
          response_column="y", ntrees="3", max_depth="3",
          model_id="treegbm")
    for _ in range(200):
        jobs = _get(srv, "/3/Jobs")["jobs"]
        if all(j["status"] != "RUNNING" for j in jobs):
            break
        time.sleep(0.1)
    models = [m["model_id"]["name"] for m in _get(srv, "/3/Models")["models"]]
    mid = [m for m in models if "gbm" in m][0]
    t = _get(srv, f"/3/Tree?model={mid}&tree_number=1")
    assert t["model"]["name"] == mid
    assert len(t["left_children"]) == len(t["features"])
    assert t["root_node_id"] == 0
    assert any(c >= 0 for c in t["left_children"])  # actually split
    # out-of-range tree -> 4xx
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(srv, f"/3/Tree?model={mid}&tree_number=99")
    assert e.value.code == 400


def test_model_metrics_list_endpoint(server):
    srv, _ = server
    out = _get(srv, "/3/ModelMetrics")
    assert isinstance(out["model_metrics"], list)
    if out["model_metrics"]:
        row = out["model_metrics"][0]
        assert "model" in row and "kind" in row


def test_typeahead_endpoint(server, tmp_path):
    srv, _ = server
    (tmp_path / "data_a.csv").write_text("x\n1\n")
    (tmp_path / "data_b.csv").write_text("x\n2\n")
    (tmp_path / "other.txt").write_text("")
    q = urllib.parse.quote(str(tmp_path / "data"))
    out = _get(srv, f"/99/Typeahead/files?src={q}&limit=10")
    names = [p.rsplit("/", 1)[-1] for p in out["matches"]]
    assert names == ["data_a.csv", "data_b.csv"]


def test_water_meter_endpoint(server):
    srv, _ = server
    out = _get(srv, "/3/WaterMeterCpuTicks/0")
    assert isinstance(out["cpu_ticks"], list)
    if out["cpu_ticks"]:
        assert len(out["cpu_ticks"][0]) == 4


def test_auth_token():
    """Opt-in bearer auth: 401 without the token, 200 with it; /3/Cloud
    stays open for discovery."""
    import urllib.error

    from h2o3_tpu.rest import start_server as _start

    srv = _start(port=0, auth_token="sekrit")
    try:
        base = f"http://127.0.0.1:{srv.port}"
        # open cloud endpoint
        with urllib.request.urlopen(f"{base}/3/Cloud") as r:
            assert json.loads(r.read())["cloud_name"] == "h2o3_tpu"
        # protected endpoint without token
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(f"{base}/3/Models")
        assert e.value.code == 401
        # with token
        req = urllib.request.Request(
            f"{base}/3/Models",
            headers={"Authorization": "Bearer sekrit"})
        with urllib.request.urlopen(req) as r:
            assert "models" in json.loads(r.read())
    finally:
        srv.stop()


def test_flows_save_load_roundtrip(server, tmp_path, monkeypatch):
    """`/99/Flows` — the notebook save/load surface (h2o-web .flow docs)."""
    monkeypatch.setenv("H2O3_FLOWS_DIR", str(tmp_path / "flows"))
    srv, _ = server
    cells = [{"type": "rapids", "src": "(nrow x)"},
             {"type": "plot", "src": "fr 0"}]
    out = _post_json(srv, "/99/Flows", {"name": "myflow", "cells": cells})
    assert out["saved"] and out["cells"] == 2
    lst = _get(srv, "/99/Flows")["flows"]
    assert any(f["name"] == "myflow" for f in lst)
    got = _get(srv, "/99/Flows/myflow")
    assert got["cells"] == cells
    # delete
    req = urllib.request.Request(
        f"http://127.0.0.1:{srv.port}/99/Flows/myflow", method="DELETE")
    with urllib.request.urlopen(req) as r:
        assert json.loads(r.read())["deleted"]
    with pytest.raises(urllib.error.HTTPError):
        _get(srv, "/99/Flows/myflow")


def _post_json(srv, path, obj):
    req = urllib.request.Request(
        f"http://127.0.0.1:{srv.port}{path}",
        data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read())


def test_flow_ui_has_notebook(server):
    srv, _ = server
    with urllib.request.urlopen(f"http://127.0.0.1:{srv.port}/flow/") as r:
        html = r.read().decode()
    assert "Notebook" in html and "saveFlow" in html and "svgHist" in html


def test_frames_pagination(server):
    srv, _ = server
    all_f = _get(srv, "/3/Frames")
    assert "total_frames" in all_f
    if all_f["total_frames"] >= 2:
        page = _get(srv, "/3/Frames?offset=1&limit=1")
        assert len(page["frames"]) == 1
        assert page["offset"] == 1


def test_network_test_and_gc(server):
    srv, _ = server
    nt = _get(srv, "/3/NetworkTest")
    assert nt["results"] and all(r["mbytes_per_sec"] > 0 for r in nt["results"])
    req = urllib.request.Request(
        f"http://127.0.0.1:{srv.port}/3/GarbageCollect", data=b"")
    with urllib.request.urlopen(req) as r:
        out = json.loads(r.read())
    assert "collected" in out and "dkv" in out


def test_frames_pagination_negative_clamped(server):
    """Negative offset/limit must not tail-slice (ADVICE r03)."""
    srv, _ = server
    all_f = _get(srv, "/3/Frames")
    page = _get(srv, "/3/Frames?offset=-1&limit=-5")
    assert page["offset"] == 0
    assert len(page["frames"]) == len(all_f["frames"])


def test_flow_name_with_disallowed_chars_rejected(server, tmp_path,
                                                  monkeypatch):
    """'my flow' and 'my_flow' must not collide on one file (ADVICE r03)."""
    monkeypatch.setenv("H2O3_FLOWS_DIR", str(tmp_path / "flows"))
    srv, _ = server
    with pytest.raises(urllib.error.HTTPError) as e:
        _post_json(srv, "/99/Flows",
                   {"name": "my flow", "cells": []})
    assert e.value.code == 400


def test_rapids_rows_param_returns_all_hist_bins(server):
    """Flow plot cells read every hist bin via rows= (ADVICE r03 medium)."""
    srv, csv = server
    imp = _post(srv, "/3/ImportFiles", path=csv)
    key = imp["destination_frames"][0]
    out = _post_json(srv, "/99/Rapids",
                     {"ast": f"(hist (cols {key} [0]) 20)", "rows": 64})
    counts = next(c for c in out["columns"] if "count" in c["label"].lower())
    assert len(counts["data"]) == 20  # all 20 bins, not the 10-row preview


def test_round5_functional_routes(server):
    """VERDICT r04 #3 follow-on: builders list, frame paging, column
    routes, Tabulate, JStack, PartialDependence, Metadata/endpoints,
    UnlockKeys."""
    srv, csv = server
    r = _post(srv, "/3/ImportFiles", path=csv)
    key = r["destination_frames"][0]

    bl = _get(srv, "/3/ModelBuilders")
    assert "gbm" in bl["model_builders"] and "glm" in bl["model_builders"]

    page = _get(srv, f"/3/Frames/{key}?row_offset=10&row_count=5")
    fr0 = page["frames"][0]
    assert fr0["row_count"] == 5
    assert len(fr0["columns"][0]["data"]) == 5

    cols = _get(srv, f"/3/Frames/{key}/columns")
    assert [c["label"] for c in cols["columns"]] == ["a", "b", "c", "y"]

    tab = _post(srv, "/3/Tabulate", dataset=key, predictor="a",
                response="y", nbins_predictor=5)
    assert len(tab["count_table"]) == 5
    total = sum(sum(row) for row in tab["count_table"])
    assert total == 500
    # response means within [0,1] for the 0/1 response
    assert all(m is None or 0 <= m <= 1 for m in tab["response_table"])

    js = _get(srv, "/3/JStack")
    assert js["traces"] and "stack" in js["traces"][0]

    ep = _get(srv, "/3/Metadata/endpoints")
    assert any(rt["url_pattern"].startswith("^/3/Tabulate")
               for rt in ep["routes"])

    ul = _post(srv, "/3/UnlockKeys")
    assert ul["unlocked"] == 0

    # train a model, then PDP over the wire
    tr = _post(srv, "/3/ModelBuilders/gbm", training_frame=key,
               response_column="y", ntrees="5", max_depth="3")
    jid = tr["job"]["key"]["name"]
    for _ in range(120):
        j = _get(srv, f"/3/Jobs/{urllib.parse.quote(jid)}")["jobs"][0]
        if j["status"] in ("DONE", "FAILED"):
            break
        time.sleep(0.5)
    assert j["status"] == "DONE", j
    mid = j["dest"]["name"]
    # the response must be an enum for PDP mean_response to be a prob —
    # numeric y trains regression here, fine for the route contract
    pdp = _post(srv, "/3/PartialDependence", model_id=mid, frame_id=key,
                cols=json.dumps(["a"]), nbins=8)
    data = pdp["partial_dependence_data"][0]
    assert "mean_response" in data and len(data["mean_response"]) >= 8
    again = _get(srv, f"/3/PartialDependence/"
                      f"{pdp['destination_key']['name']}")
    assert again["partial_dependence_data"] == pdp[
        "partial_dependence_data"]

    dom = _get(srv, f"/3/Frames/{key}/columns/y/domain")
    assert dom["domain"] == [[]]            # numeric column: no levels


def test_job_cancel_route(server):
    """POST /3/Jobs/{id}/cancel stops a long training run at its next
    scoring boundary; the job ends CANCELLED and no model lands in DKV."""
    srv, csv = server
    r = _post(srv, "/3/ImportFiles", path=csv)
    key = r["destination_frames"][0]
    tr = _post(srv, "/3/ModelBuilders/deeplearning", training_frame=key,
               response_column="y", hidden="[64,64]", epochs="500",
               mini_batch_size="8", score_interval="0")
    jid = tr["job"]["key"]["name"]
    time.sleep(1.0)
    c = _post(srv, f"/3/Jobs/{urllib.parse.quote(jid)}/cancel")
    assert c["job"]["cancel_requested"] or c["job"]["status"] == "CANCELLED"
    for _ in range(120):
        j = _get(srv, f"/3/Jobs/{urllib.parse.quote(jid)}")["jobs"][0]
        if j["status"] in ("DONE", "FAILED", "CANCELLED"):
            break
        time.sleep(0.5)
    assert j["status"] == "CANCELLED", j
    assert j["dest"]["name"] == jid       # no model key: result never set


def test_prediction_frames_overwrite_not_accumulate(server, cloud1):
    """Repeat scoring of the same (model, frame) pair must OVERWRITE the
    deterministic prediction key, never accumulate one leaked frame per
    call — DKV.keys()-based leak assertion (serving-subsystem satellite).

    The model is trained in-process (cloud1) so the assertion isolates the
    predict route's DKV behavior from the training path."""
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

    srv, csv = server
    rng = np.random.default_rng(3)
    X = rng.normal(size=(300, 3))
    yb = (X[:, 0] + X[:, 1] > 0).astype(int)
    fr = Frame.from_dict(
        {"a": X[:, 0], "b": X[:, 1], "c": X[:, 2],
         "y": np.asarray(["no", "yes"], dtype=object)[yb]},
        column_types={"y": "enum"})
    fr.key = "leaktr"
    DKV.put(fr.key, fr)
    est = H2OGradientBoostingEstimator(ntrees=3, max_depth=3, seed=1,
                                       model_id="leak_gbm")
    est.train(x=["a", "b", "c"], y="y", training_frame=fr)
    DKV.put("leak_gbm", est.model)
    p1 = _post(srv, "/3/Predictions/models/leak_gbm/frames/leaktr")
    pkey = p1["predictions_frame"]["name"]
    assert pkey == "prediction_leak_gbm_leaktr"   # deterministic key
    keys_after_first = set(DKV.keys())
    for _ in range(5):
        pn = _post(srv, "/3/Predictions/models/leak_gbm/frames/leaktr")
        assert pn["predictions_frame"]["name"] == pkey
    assert set(DKV.keys()) == keys_after_first, (
        "repeat /3/Predictions calls leaked DKV keys: "
        f"{sorted(set(DKV.keys()) - keys_after_first)}")


def test_predictions_route_options(server):
    """POST /3/Predictions with predict_contributions / leaf_node_assignment
    flags (ModelMetricsHandler.predict options)."""
    srv, csv = server
    r = _post(srv, "/3/ImportFiles", path=csv)
    key = r["destination_frames"][0]
    _post(srv, "/99/Rapids",
          ast=f"(assign ptr (cbind (cols {key} [0 1 2])"
              f" (as.factor (cols {key} [3]))))")
    tr = _post(srv, "/3/ModelBuilders/gbm", training_frame="ptr",
               response_column="y", ntrees="4", max_depth="3")
    jid = tr["job"]["key"]["name"]
    for _ in range(200):
        j = _get(srv, f"/3/Jobs/{jid}")["jobs"][0]
        if j["status"] in ("DONE", "FAILED"):
            break
        time.sleep(0.25)
    assert j["status"] == "DONE", j
    mid = j["dest"]["name"]
    c = _post(srv, f"/3/Predictions/models/{mid}/frames/ptr",
              predict_contributions="true")
    cf = _get(srv, f"/3/Frames/{c['predictions_frame']['name']}/summary")
    labels = [col["label"] for col in cf["frames"][0]["columns"]]
    assert "BiasTerm" in labels
    l = _post(srv, f"/3/Predictions/models/{mid}/frames/ptr",
              leaf_node_assignment="true")
    lf = _get(srv, f"/3/Frames/{l['predictions_frame']['name']}/summary")
    assert lf["frames"][0]["rows"] == 500
