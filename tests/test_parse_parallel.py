"""Parallel chunked ingest (ISSUE 2): chunk-boundary correctness, NA/dtype
parity, vectorized-coercion parity, parse_setup fixes, and ingest
observability. The load-bearing invariant: `parse_csv` output (names,
types, dtypes, domains, codes, NaN placement) is BIT-IDENTICAL across
1-chunk, N-chunk, and the seed per-line (H2O3_INGEST_LEGACY) pipelines."""

import csv
import os
import time

import numpy as np
import pytest

from h2o3_tpu.frame import chunked, ingest_stats
from h2o3_tpu.frame.parse import (_split_lines, _tokenize_numpy, parse_csv,
                                  parse_setup)
from h2o3_tpu.frame.vec import bulk_try_numeric


def _cmp_frames(a, b, msg=""):
    assert a.names == b.names, msg
    assert a.nrow == b.nrow, msg
    for n in a.names:
        va, vb = a.vec(n), b.vec(n)
        assert va.type == vb.type, (msg, n, va.type, vb.type)
        assert (va.domain or []) == (vb.domain or []), (msg, n)
        if va.type == "string":
            assert [str(x) for x in va.to_numpy()] \
                == [str(x) for x in vb.to_numpy()], (msg, n)
            continue
        assert va.data.dtype == vb.data.dtype, (msg, n)
        np.testing.assert_array_equal(
            np.asarray(va.data, np.float64), np.asarray(vb.data, np.float64),
            err_msg=f"{msg}:{n}")


def _legacy_parse(path, **kw):
    os.environ["H2O3_INGEST_LEGACY"] = "1"
    try:
        return parse_csv(path, **kw)
    finally:
        del os.environ["H2O3_INGEST_LEGACY"]


# -- chunk planning ----------------------------------------------------------
def test_plan_chunks_partition_at_line_boundaries():
    data = b"".join(b"row%d,%d\n" % (i, i) for i in range(500))
    chunks = chunked.plan_chunks(data, 256)
    assert chunks[0][0] == 0 and chunks[-1][1] == len(data)
    for (_, e1), (s2, _) in zip(chunks, chunks[1:]):
        assert e1 == s2                       # no gaps, no overlap
        assert data[e1 - 1:e1] == b"\n"       # cut right after a newline


def test_plan_chunks_heals_quoted_newlines():
    # every record holds a quoted field with embedded newline + separator;
    # no boundary may land on the quoted (inner) newlines
    rec = b'1,"a,b\nc",2\n'
    data = rec * 200
    chunks = chunked.plan_chunks(data, 40)
    assert len(chunks) > 3
    for _, e in chunks[:-1]:
        assert (e - len(rec) * (e // len(rec))) == 0, \
            "boundary inside a quoted field"


def test_plan_chunks_unbalanced_quote_degrades_to_one_chunk():
    data = b'x,"unterminated\n' + b"1,2\n" * 100
    assert chunked.plan_chunks(data, 64) == [(0, len(data))]


# -- bit-identity across chunkings -------------------------------------------
def _write_tricky(path, n=400):
    rng = np.random.default_rng(7)
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["num", "cat", "q", "big", "ws"])
        for i in range(n):
            num = "" if i % 37 == 0 else f"{rng.normal():.6f}"
            cat = "NA" if i % 29 == 0 else f"lvl{int(rng.integers(0, 17))}"
            if i % 11 == 0:
                q = f"with,{i} comma"              # quoted separator
            elif i % 83 == 5:
                q = f"line1\nline2_{i}"            # quoted embedded newline
            else:
                q = f"t{i % 5}"
            big = str((1 << 25) + i) if i > n // 2 else str(i)
            w.writerow([num, cat, q, big, f" pad{i % 3} "])
    return path


def test_chunked_vs_single_vs_legacy_bit_identical(tmp_path):
    p = _write_tricky(str(tmp_path / "t.csv"))
    single = parse_csv(p, chunk_bytes=1 << 30)
    for cb, nt in ((64, 1), (256, 4), (1024, 2)):
        _cmp_frames(single, parse_csv(p, chunk_bytes=cb, nthreads=nt),
                    f"cb={cb},nt={nt}")
    _cmp_frames(single, _legacy_parse(p), "legacy")


def test_quoted_token_wider_than_all_plain_tokens(tmp_path):
    """A quoted cell wider than every plain token used to overrun the
    fast tokenizer's byte pad (sized from OK-row token widths only) and
    IndexError the whole parse (PR 3 review repro)."""
    p = str(tmp_path / "wide.csv")
    with open(p, "w") as f:
        f.write('a,b\n"q,uoted,with,long,separators,inside",2\nx,3\n')
    fr = parse_csv(p)
    assert fr.nrow == 2
    _cmp_frames(fr, _legacy_parse(p), "legacy")


def test_quoted_field_straddling_chunk_split(tmp_path):
    """A quoted field containing the separator AND an embedded newline that
    straddles the chunk split must parse identically to the single-chunk
    path (the ISSUE acceptance pin)."""
    p = str(tmp_path / "q.csv")
    rows = ["h1,h2,h3"]
    for i in range(60):
        rows.append(f'{i},"x,{i}\nconts_{i}",tail{i}')
    with open(p, "w") as f:
        f.write("\n".join(rows) + "\n")
    single = parse_csv(p, chunk_bytes=1 << 30)
    # tiny chunks force boundaries into/around every quoted field
    for cb in (16, 32, 64, 128):
        _cmp_frames(single, parse_csv(p, chunk_bytes=cb, nthreads=3),
                    f"cb={cb}")
    _cmp_frames(single, _legacy_parse(p), "legacy")


def test_na_token_and_dtype_parity(tmp_path):
    p = str(tmp_path / "na.csv")
    with open(p, "w") as f:
        f.write("a,b,c,d\n")
        for i in range(100):
            a = ["", "NA", "na", "nan", str(i * 0.5)][i % 5]
            b = str((1 << 25) + i)       # forces float64 (no f32 downcast)
            c = ["", "NA", f"lv{i % 3}"][i % 3]
            f.write(f"{a},{b},{c},{i}\n")
    single = parse_csv(p, chunk_bytes=1 << 30)
    many = parse_csv(p, chunk_bytes=64, nthreads=4)
    legacy = _legacy_parse(p)
    _cmp_frames(single, many, "many")
    _cmp_frames(single, legacy, "legacy")
    assert single.vec("b").data.dtype == np.float64
    assert single.vec("d").data.dtype == np.float32
    assert single.vec("a").type == "real"
    assert single.vec("a").nacnt() == legacy.vec("a").nacnt() > 0
    assert single.vec("c").type == "enum"
    assert (np.asarray(single.vec("c").data) == -1).sum() > 0


def test_fast_path_fallbacks_stay_identical(tmp_path):
    # non-ASCII content, lone \r line breaks, and NUL bytes all route the
    # affected chunk to the generic tokenizer — results must not change
    cases = {
        "uni.csv": "x,y\n1,café\n2,naïve\n3,plain\n4,plain\n",
        "lone_cr.csv": "x,y\n1,a\r2,b\n3,c\n",
        "nul.csv": "x,y\n1,a\n2,b\x00b\n3,c\n",
    }
    for name, text in cases.items():
        p = str(tmp_path / name)
        with open(p, "w", newline="") as f:
            f.write(text)
        single = parse_csv(p, chunk_bytes=1 << 30)
        _cmp_frames(single, parse_csv(p, chunk_bytes=8, nthreads=2), name)
        _cmp_frames(single, _legacy_parse(p), name + ":legacy")


def test_crlf_and_whitespace_strip_parity(tmp_path):
    p = str(tmp_path / "ws.csv")
    with open(p, "w", newline="") as f:
        f.write('a,b,c\r\n 1 , x y ,"  keep  "\r\n2,\tz\t,w\r\n'
                '3, "qq" ,v\r\n,,\r\n9 , 8, 7 \r\n')
    single = parse_csv(p, chunk_bytes=1 << 30)
    _cmp_frames(single, parse_csv(p, chunk_bytes=8, nthreads=3), "crlf")
    _cmp_frames(single, _legacy_parse(p), "crlf:legacy")
    assert "  keep  " in (single.vec("c").domain or [])   # quoting preserved


def test_tokenize_lines_matches_split_lines():
    lines = ['1,2,3', 'a,"b,c",d', ' x ,y,', 'only', '1,2,3,4,5',
             '"q""uote",2,3']
    ref = _split_lines(lines, ",", 3)
    got, info = chunked.tokenize_lines(lines, ",", 3, nthreads=2,
                                       block_rows=2)
    assert info["n_chunks"] == 3
    for c in range(3):
        assert [str(v) for v in got[c]] == [str(v) for v in ref[c]], c


def test_tokenize_data_matches_tokenize_numpy(tmp_path):
    p = _write_tricky(str(tmp_path / "t.csv"), n=120)
    ref = _tokenize_numpy(p, ",", True, 5)
    with open(p, "rb") as f:
        data = f.read()
    got, info = chunked.tokenize_data(data, ",", True, 5, nthreads=2,
                                      chunk_bytes=512, use_native=False)
    assert info["n_chunks"] > 1

    def tok(v):   # fast chunks carry ASCII bytes tokens
        return v.decode() if isinstance(v, bytes) else str(v)

    for c in range(5):
        assert [tok(v) for v in got[c]] == [str(v) for v in ref[c]], c


# -- native per-chunk tokenizer ----------------------------------------------
def test_native_chunked_numeric_parity(tmp_path):
    from h2o3_tpu.native import loader

    if not loader.available():
        pytest.skip("native lib not built")
    p = str(tmp_path / "num.csv")
    with open(p, "w") as f:
        f.write("x,y\n")
        for i in range(1000):
            f.write(f"{i},{i * 0.5 if i % 7 else 'NA'}\n")
    single = parse_csv(p, chunk_bytes=1 << 30)
    many = parse_csv(p, chunk_bytes=128, nthreads=4)
    _cmp_frames(single, many, "native")
    assert ingest_stats.snapshot()["last"]["native"] is True


def test_native_agrees_with_python_semantics(tmp_path):
    """Native availability must not change results: quoted numerics route
    around the quote-blind C scanner, whitespace-only lines are blank on
    both paths, and wide NA markers ('?', 'null') make the column enum on
    both (C now fails them → python fallback)."""
    from h2o3_tpu.native import loader

    if not loader.available():
        pytest.skip("native lib not built")
    # quoted numeric holding the separator: must not take the native path
    p = str(tmp_path / "qn.csv")
    with open(p, "w") as f:
        f.write('a,b\n"1,234",5\n7,8\n')
    fr = parse_csv(p, chunk_bytes=1 << 30)
    _cmp_frames(fr, parse_csv(p, chunk_bytes=8, nthreads=2), "qnum")
    assert fr.nrow == 2
    assert fr.vec("a").type == "enum" and "1,234" in fr.vec("a").domain
    np.testing.assert_array_equal(fr.vec("b").numeric_np(), [5.0, 8.0])
    # whitespace-only line is blank on the native path too
    p2 = str(tmp_path / "ws.csv")
    with open(p2, "w") as f:
        f.write("a,b\n1,2\n \n3,4\n")
    fr2 = parse_csv(p2)
    assert fr2.nrow == 2
    assert ingest_stats.snapshot()["last"]["native"] is True
    # '?' NA marker: enum with or without the .so (C rejects it now)
    p3 = str(tmp_path / "na.csv")
    with open(p3, "w") as f:
        f.write("a,b\n1,2\n?,4\n5,6\n")
    fr3 = parse_csv(p3)
    assert fr3.vec("a").type == "enum"
    _cmp_frames(fr3, _legacy_parse(p3), "qmark")


# -- vectorized coercion parity ----------------------------------------------
def test_bulk_try_numeric_matches_elementwise_loop():
    na = ("", "NA", "na", "nan", None)
    toks = ["1.5", " 2e3 ", "-0.25", "NA", "", "inf", "-inf", "nan",
            "Infinity", "7", " 8 "]
    got = bulk_try_numeric(np.asarray(toks, dtype=object), na)
    ref = np.asarray([np.nan if v in na else float(v) for v in toks])
    np.testing.assert_array_equal(got, ref)
    # bytes + str certification path (the tokenizer's S columns)
    got_s = bulk_try_numeric(np.asarray([t.encode() for t in toks], "S10"),
                             na, assume_str=True)
    np.testing.assert_array_equal(got_s, ref)
    # non-numeric raises exactly like the loop
    with pytest.raises(ValueError):
        bulk_try_numeric(np.asarray(["1", "x"], dtype=object), na)
    # non-str objects keep float() semantics (np.float32 round-trip!)
    mixed = np.asarray([np.float32(0.1), "2.5", None], dtype=object)
    ref2 = np.asarray([float(np.float32(0.1)), 2.5, np.nan])
    np.testing.assert_array_equal(
        bulk_try_numeric(mixed, ("", None)), ref2)
    # strip_tokens applies the parser's wider NA rule
    got3 = bulk_try_numeric(np.asarray([" N/A ", "1"], dtype=object),
                            {"N/A"}, strip_tokens=True)
    np.testing.assert_array_equal(got3, [np.nan, 1.0])


# -- parse_setup fixes -------------------------------------------------------
def test_parse_setup_single_line_tiebreak(tmp_path):
    """The decided lone-line tiebreak (ROADMAP item, ISSUE 4): an all-text
    multi-column lone line is a HEADER over zero rows; any numeric token
    (or a single column) keeps the lone line as data (the ISSUE-2 rule)."""
    p = str(tmp_path / "one.csv")
    with open(p, "w") as f:
        f.write("alpha,beta,gamma\n")
    setup = parse_setup(p)
    assert setup["header"] is True            # all-text lone line = header
    fr = parse_csv(p)
    assert fr.nrow == 0 and fr.names == ["alpha", "beta", "gamma"]
    # single NUMERIC line was already data; stays so
    p2 = str(tmp_path / "one2.csv")
    with open(p2, "w") as f:
        f.write("1,2,3\n")
    assert parse_setup(p2)["header"] is False
    assert parse_csv(p2).nrow == 1
    # a lone MIXED line (text + numeric tokens) stays data
    p3 = str(tmp_path / "one3.csv")
    with open(p3, "w") as f:
        f.write("alpha,2,3\n")
    assert parse_setup(p3)["header"] is False
    assert parse_csv(p3).nrow == 1
    # a lone single-column word stays data (not a 1-column header)
    p4 = str(tmp_path / "one4.csv")
    with open(p4, "w") as f:
        f.write("hello\n")
    assert parse_setup(p4)["header"] is False
    assert parse_csv(p4).nrow == 1


def test_tokenize_block_long_line_skew(tmp_path):
    """A chunk mixing many short rows with ONE very long field must not
    materialize the (nrows × longest-line) fixed-width unicode matrix
    (ROADMAP item): 2000 short rows beside a ~100 KB cell would allocate
    ~800 MB there. The row-wise classification path produces identical
    tokens at O(total chars) memory."""
    import tracemalloc

    short = [f"{i},ab,{i * 0.5}" for i in range(2000)]
    long_cell = "x" * 100_000
    lines = short[:1000] + [f'7,"{long_cell}",1.5'] + short[1000:]
    tracemalloc.start()
    out = chunked.tokenize_block(lines, ",", 3)
    _cur, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    assert out.shape == (2001, 3)
    assert out[1000, 1] == long_cell        # RFC-4180 dequoted, intact
    assert out[0, 0] == "0" and out[2000, 2] == str(1999 * 0.5)
    # generous bound: ~10× the text itself, far under the ~8 GB matrix
    assert peak < 64 * 1024 * 1024, f"peak {peak / 1e6:.0f} MB"
    # and the skewed block tokenizes exactly like the per-line reference
    ref = np.empty_like(out)
    for i, ln in enumerate(lines):
        parts = chunked.split_csv_line(ln, ",")
        ref[i, :] = parts[:3] if len(parts) >= 3 else parts + [""] * (3 - len(parts))
    np.testing.assert_array_equal(out, ref)


def test_parse_header_only_csv_zero_rows(tmp_path):
    """`id,name\\n` with zero data rows parses as a named 0-row frame, not
    one DATA row named C1/C2 — pinned end to end through the chunked
    tokenizer and the column coercers."""
    p = str(tmp_path / "header_only.csv")
    with open(p, "w") as f:
        f.write("id,name\n")
    setup = parse_setup(p)
    assert setup["header"] is True
    assert setup["names"] == ["id", "name"]
    fr = parse_csv(p)
    assert fr.nrow == 0
    assert fr.names == ["id", "name"]


def test_parse_setup_quoted_first_line_sep_guess(tmp_path):
    # commas INSIDE the quoted cell must not elect ',' over the real ';'
    p = str(tmp_path / "q.csv")
    with open(p, "w") as f:
        f.write('"last, first, middle";age\n"doe, jane, q";41\n')
    setup = parse_setup(p)
    assert setup["sep"] == ";"
    assert setup["names"] == ["last, first, middle", "age"]
    fr = parse_csv(p)
    assert fr.ncol == 2 and fr.nrow == 1


def test_parse_setup_quoted_sample_types(tmp_path):
    # a quoted cell holding the separator must not shift the type guess
    p = str(tmp_path / "t.csv")
    with open(p, "w") as f:
        f.write('name,score\n"doe, jane",1.5\n"roe, rich",2.5\n')
    setup = parse_setup(p)
    assert setup["types"] == ["enum", "numeric"]
    fr = parse_csv(p)
    assert fr.vec("score").type in ("real", "int")
    assert sorted(fr.vec("name").domain) == ["doe, jane", "roe, rich"]


# -- observability -----------------------------------------------------------
def test_ingest_stats_and_profiler_surface(tmp_path):
    from h2o3_tpu.runtime import phases, profiler

    p = _write_tricky(str(tmp_path / "t.csv"), n=150)
    ingest_stats.reset()
    phases.reset()
    fr = parse_csv(p, chunk_bytes=512, nthreads=2)
    snap = ingest_stats.snapshot()
    assert snap["totals"]["parses"] == 1
    assert snap["totals"]["rows"] == fr.nrow
    assert snap["last"]["rows_per_s"] > 0
    assert snap["last"]["bytes_per_s"] > 0
    assert snap["last"]["n_chunks"] > 1
    assert set(snap["last"]["phases"]) <= set(ingest_stats.PHASE_ORDER)
    assert "tokenize" in snap["last"]["phases"]
    ph = phases.snapshot()
    assert "ingest_tokenize_s" in ph and ph["bytes_ingest_tokenize"] > 0
    prof = profiler.ingest_stats()
    assert prof["active"] is True and prof["totals"]["rows"] == fr.nrow


def test_ingest_metrics_schema():
    from h2o3_tpu.rest import schemas

    sch = schemas.ingest_metrics_schema()
    assert sch["name"] == schemas.INGEST_SCHEMA_NAME
    names = [f["name"] for f in sch["fields"]]
    assert "totals" in names and "last.rows_per_s" in names


# -- throughput smoke (tier-2) -----------------------------------------------
@pytest.mark.slow
def test_ingest_throughput_floor(tmp_path):
    """Parallel chunked parse must not regress vs 1-thread (ISSUE floor:
    parallel ≥ 1.0× single-thread; 10% scheduler-noise margin) and must
    beat the seed per-line tokenizer."""
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from bench import _write_ingest_csv

    p = str(tmp_path / "bench.csv")
    _write_ingest_csv(p, 8)
    parse_csv(p)   # warm-up: page cache + numpy kernels

    def best(fn, reps=3):
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            times.append(time.perf_counter() - t0)
        return min(times)

    t_legacy = best(lambda: _legacy_parse(p), reps=2)

    def measure():
        # interleave the modes so background-load drift hits both equally
        singles, pars = [], []
        for _ in range(3):
            t0 = time.perf_counter()
            parse_csv(p, nthreads=1)
            singles.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            parse_csv(p, nthreads=os.cpu_count() or 1)
            pars.append(time.perf_counter() - t0)
        return min(singles), min(pars)

    # ≥1.0× single-thread with a scheduler-noise margin (2-core CI hosts
    # run the pool and the pytest process on the same cores); one
    # re-measure damps transient-load flakes before calling it a
    # regression
    for _ in range(2):
        t_single, t_par = measure()
        if t_par <= t_single * 1.20:
            break
    assert t_par <= t_single * 1.20, (t_par, t_single)
    assert t_par <= t_legacy / 1.5, (t_par, t_legacy)
