"""Distributed ingest: N processes × byte ranges must reproduce the
single-process parse bit-identically (ParseDataset.MultiFileParseTask +
Categorical merge semantics — VERDICT r01 item 4)."""

import csv
import os

import numpy as np
import pytest

from tests.multiproc_util import run_workers


def _write_tricky_csv(path, n=997, seed=3):
    """Numerics with NAs, categoricals with NAs, a column that is numeric in
    the first half but categorical later (forces the cross-process type
    vote), and a quoted-string column."""
    rng = np.random.default_rng(seed)
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["num", "cat", "late_cat", "allnum", "biglate"])
        for i in range(n):
            num = "" if i % 53 == 0 else f"{rng.normal():.6f}"
            cat = "NA" if i % 41 == 0 else f"lvl{int(rng.integers(0, 23))}"
            late = (f"{i % 7}" if i < n // 2 + 11
                    else f"tag{int(rng.integers(0, 5))}")
            # big magnitudes only in the SECOND half: the f32-downcast
            # decision must be voted globally, not per shard
            big = str(100 + i) if i < n // 2 else str((1 << 25) + i)
            w.writerow([num, cat, late, str(i * 2), big])


def test_byte_range_semantics(tmp_path):
    from h2o3_tpu.frame.distributed_parse import byte_range, read_range_lines

    p = tmp_path / "t.csv"
    lines = [f"row{i},{i}" for i in range(100)]
    p.write_text("\n".join(lines) + "\n")
    size = os.path.getsize(p)
    got = []
    for r in range(3):
        s, e = byte_range(size, r, 3)
        got.extend(read_range_lines(str(p), s, e))
    assert got == lines  # every line exactly once, in order


def test_single_process_identical(tmp_path, cloud1):
    """1-process distributed path ≡ parse_csv exactly."""
    from h2o3_tpu.frame.distributed_parse import parse_csv_distributed
    from h2o3_tpu.frame.parse import parse_csv

    p = str(tmp_path / "t.csv")
    _write_tricky_csv(p)
    a = parse_csv(p)
    b = parse_csv_distributed(p)
    assert a.names == b.names
    for n in a.names:
        va, vb = a.vec(n), b.vec(n)
        assert va.type == vb.type, n
        assert va.data.dtype == vb.data.dtype, n
        assert (va.domain or []) == (vb.domain or []), n
        np.testing.assert_array_equal(
            np.asarray(va.data, np.float64), np.asarray(vb.data, np.float64))
    assert b.dist.global_nrow == a.nrow


def test_two_process_bit_identical(tmp_path):
    """2 processes under jax.distributed: concatenated shards ≡ the
    single-process Frame (codes AND domains), global row facts correct."""
    from h2o3_tpu.frame.parse import parse_csv

    p = str(tmp_path / "t.csv")
    _write_tricky_csv(p)
    ref = parse_csv(p)

    body = f"""
    import numpy as np
    from h2o3_tpu.frame.distributed_parse import parse_csv_distributed
    fr = parse_csv_distributed({p!r})
    rank = fr.dist.process_index
    np.savez({str(tmp_path)!r} + f"/shard{{rank}}.npz",
             offset=fr.dist.row_offset, gn=fr.dist.global_nrow,
             **{{f"c_{{n}}": np.asarray(fr.vec(n).data, np.float64)
                for n in fr.names}},
             **{{f"d_{{n}}": np.asarray(fr.vec(n).domain or [], dtype=object)
                for n in fr.names}},
             **{{f"t_{{n}}": np.asarray([str(fr.vec(n).data.dtype)])
                for n in fr.names}})
    print("rank", rank, "rows", fr.dist.local_nrow)
    """
    run_workers(2, body)

    sh = [np.load(tmp_path / f"shard{r}.npz", allow_pickle=True)
          for r in range(2)]
    assert int(sh[0]["gn"]) == ref.nrow == int(sh[1]["gn"])
    assert int(sh[1]["offset"]) == len(sh[0]["c_num"])
    assert ref.vec("biglate").data.dtype == np.float64  # the vote matters
    for r in range(2):
        assert str(sh[r]["t_biglate"][0]) == "float64", r
    for n in ref.names:
        whole = np.concatenate([sh[0][f"c_{n}"], sh[1][f"c_{n}"]])
        np.testing.assert_array_equal(
            whole, np.asarray(ref.vec(n).data, np.float64), err_msg=n)
        for r in range(2):
            assert list(sh[r][f"d_{n}"]) == (ref.vec(n).domain or []), n
