"""Parser formats beyond CSV: ARFF + SVMLight + MOJO kmeans/pca round-trips
(reference: water/parser/ARFFParser.java, SVMLightParser.java,
hex/genmodel algos)."""

import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu.frame.parse import parse_arff, parse_svmlight


def test_arff_dense(tmp_path, cloud1):
    p = tmp_path / "iris.arff"
    p.write_text("""% comment
@relation iris
@attribute sepal_len numeric
@attribute 'class' {setosa, versicolor}
@attribute note string
@data
5.1, setosa, 'first row'
4.9, versicolor, "second"
6.0, setosa, third
""")
    fr = parse_arff(str(p))
    assert fr.names == ["sepal_len", "class", "note"]
    np.testing.assert_allclose(fr.vec("sepal_len").numeric_np(),
                               [5.1, 4.9, 6.0], rtol=1e-6)
    v = fr.vec("class")
    assert v.type == "enum" and v.domain == ["setosa", "versicolor"]
    assert np.asarray(v.data).tolist() == [0, 1, 0]
    assert fr.vec("note").type == "string"
    # dispatch through import_file
    fr2 = h2o.import_file(str(p))
    assert fr2.nrow == 3


def test_arff_sparse_rows(tmp_path, cloud1):
    p = tmp_path / "s.arff"
    p.write_text("""@relation s
@attribute a numeric
@attribute b numeric
@attribute c numeric
@data
{0 1.5, 2 3}
{1 2.0}
""")
    fr = parse_arff(str(p))
    assert fr.vec("a").numeric_np().tolist() == [1.5, 0.0]
    assert fr.vec("b").numeric_np().tolist() == [0.0, 2.0]
    assert fr.vec("c").numeric_np().tolist() == [3.0, 0.0]


def test_arff_sparse_nominal_default_and_quotes(tmp_path, cloud1):
    p = tmp_path / "sn.arff"
    p.write_text("""@relation sn
@attribute num numeric
@attribute cls {setosa, versicolor}
@data
{0 1.5}
{1 'versicolor'}
""")
    fr = parse_arff(str(p))
    v = fr.vec("cls")
    # omitted sparse nominal = FIRST domain value (ARFF spec), quoted matches
    assert np.asarray(v.data).tolist() == [0, 1]
    assert fr.vec("num").numeric_np().tolist() == [1.5, 0.0]


def test_arff_quoted_comma_value(tmp_path, cloud1):
    p = tmp_path / "qc.arff"
    p.write_text("""@relation qc
@attribute a numeric
@attribute s string
@attribute b numeric
@data
5.1, 'big, green', 3.0
1.0, "x", 2.0
""")
    fr = parse_arff(str(p))
    assert list(fr.vec("s").to_numpy()) == ["big, green", "x"]
    assert fr.vec("b").numeric_np().tolist() == [3.0, 2.0]


def test_svmlight(tmp_path, cloud1):
    p = tmp_path / "d.svm"
    p.write_text("1 1:0.5 3:2.0 # comment\n-1 2:1.0\n")
    fr = parse_svmlight(str(p))
    assert fr.vec("C1").numeric_np().tolist() == [1.0, -1.0]
    assert fr.vec("C2").numeric_np().tolist() == [0.5, 0.0]
    assert fr.vec("C4").numeric_np().tolist() == [2.0, 0.0]


def test_mojo_kmeans_pca_roundtrip(tmp_path, cloud1):
    from h2o3_tpu.estimators import (
        H2OKMeansEstimator,
        H2OPrincipalComponentAnalysisEstimator,
    )
    from h2o3_tpu.frame.frame import Frame

    rng = np.random.default_rng(0)
    X = np.concatenate([rng.normal(0, 0.3, (100, 3)), rng.normal(4, 0.3, (100, 3))])
    fr = Frame.from_numpy(X, names=["a", "b", "c"])
    km = H2OKMeansEstimator(k=2, seed=1)
    km.train(x=["a", "b", "c"], training_frame=fr)
    path = h2o.save_model(km, str(tmp_path))
    scorer = h2o.load_model(path)
    p_live = km.predict(fr).vec("predict").numeric_np()
    p_mojo = scorer.predict(fr).vec("predict").numeric_np()
    np.testing.assert_array_equal(p_live, p_mojo)

    pca = H2OPrincipalComponentAnalysisEstimator(k=2, transform="STANDARDIZE")
    pca.train(x=["a", "b", "c"], training_frame=fr)
    path = h2o.save_model(pca, str(tmp_path))
    scorer = h2o.load_model(path)
    np.testing.assert_allclose(
        pca.predict(fr).vec("PC1").numeric_np(),
        scorer.predict(fr).vec("PC1").numeric_np(), rtol=1e-5)


def test_pallas_factored_histogram_matches():
    """TPU-only: the VMEM factored kernel matches the XLA one-hot path."""
    import jax

    if jax.default_backend() != "tpu":
        import pytest
        pytest.skip("pallas TPU kernel requires a TPU backend")
    import jax.numpy as jnp
    from h2o3_tpu.ops.histogram import build_histograms

    rng = np.random.default_rng(0)
    N, F, L, B = 10000, 5, 8, 16
    codes = jnp.asarray(rng.integers(0, B, (N, F), dtype=np.int8))
    idx = jnp.asarray(rng.integers(0, L, N, dtype=np.int32))
    g = jnp.asarray(rng.normal(size=N).astype(np.float32))
    h = jnp.ones(N, jnp.float32)
    w = jnp.ones(N, jnp.float32)
    a = build_histograms(codes, idx, g, h, w, L, B, method="onehot")
    b = build_histograms(codes, idx, g, h, w, L, B, method="pallas_factored")
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-3)


def test_native_forest_scorer_parity(cloud1):
    """mojo_scorer.cpp traversal == the numpy fallback, NaNs included."""
    from h2o3_tpu.native import loader

    if not loader.available():
        pytest.skip("native lib not built")
    rng = np.random.default_rng(0)
    ntrees, D = 10, 4
    T = 2 ** (D + 1) - 1
    feat = rng.integers(0, 3, (ntrees, T)).astype(np.int32)
    thr = rng.normal(size=(ntrees, T)).astype(np.float32)
    split = np.zeros((ntrees, T), bool)
    split[:, : 2**D - 1] = rng.random((ntrees, 2**D - 1)) < 0.8
    value = rng.normal(size=(ntrees, T)).astype(np.float32)
    X = rng.normal(size=(500, 3))
    X[rng.random(X.shape) < 0.1] = np.nan
    out = loader.score_forest(feat, thr, split, value, D, X)
    if out is None:
        pytest.skip("native lib lacks h2o3_score_forest (stale build)")
    total = np.zeros(X.shape[0])
    for t in range(ntrees):
        node = np.zeros(X.shape[0], np.int64)
        for _ in range(D):
            f = feat[t][node]
            s = split[t][node]
            xv = X[np.arange(X.shape[0]), f]
            right = np.isnan(xv) | (xv > thr[t][node])
            node = np.where(s, 2 * node + 1 + (right & s).astype(np.int64), node)
        total += value[t][node]
    np.testing.assert_allclose(out, total, atol=1e-6)


def test_mojo_isolation_forest_roundtrip(tmp_path, cloud1):
    from h2o3_tpu.estimators import H2OIsolationForestEstimator
    from h2o3_tpu.frame.frame import Frame

    rng = np.random.default_rng(3)
    X = rng.normal(size=(300, 4))
    X[:3] += 6.0
    fr = Frame.from_numpy(X, names=["a", "b", "c", "d"])
    iso = H2OIsolationForestEstimator(ntrees=20, sample_size=64, seed=4)
    iso.train(x=["a", "b", "c", "d"], training_frame=fr)
    path = h2o.save_model(iso, str(tmp_path))
    sc = h2o.load_model(path)
    p_live = iso.predict(fr).vec("predict").numeric_np()
    p_mojo = sc.predict(fr).vec("predict").numeric_np()
    np.testing.assert_allclose(p_live, p_mojo, atol=1e-6)


def test_multihost_launcher_single_process(cloud1):
    from h2o3_tpu.parallel.launcher import initialize_multihost

    facts = initialize_multihost()
    assert facts["process_count"] >= 1
    assert facts["global_devices"] >= facts["local_devices"] >= 1


def test_parquet_round_trip(tmp_path):
    pa = pytest.importorskip("pyarrow")
    import pyarrow.parquet as pq

    t = pa.table({
        "num": pa.array([1.5, 2.5, None, 4.0]),
        "cat": pa.array(["a", "b", None, "a"]),
        "flag": pa.array([True, False, True, None]),
        "count": pa.array([1, 2, 3, 4], type=pa.int64()),
    })
    p = tmp_path / "t.parquet"
    pq.write_table(t, p)
    fr = h2o.import_file(str(p))
    assert fr.names == ["num", "cat", "flag", "count"]
    assert fr.nrow == 4
    num = fr.vec("num").numeric_np()
    assert np.isnan(num[2]) and num[0] == 1.5
    assert fr.vec("cat").type == "enum"
    assert fr.vec("cat").domain == ["a", "b"]
    np.testing.assert_allclose(fr.vec("count").numeric_np(), [1, 2, 3, 4])


def test_orc_round_trip(tmp_path):
    pa = pytest.importorskip("pyarrow")
    from pyarrow import orc

    t = pa.table({"x": pa.array([1.0, 2.0, 3.0]),
                  "s": pa.array(["u", "v", "u"])})
    p = tmp_path / "t.orc"
    orc.write_table(t, p)
    fr = h2o.import_file(str(p))
    assert fr.nrow == 3 and fr.vec("s").type == "enum"
    np.testing.assert_allclose(fr.vec("x").numeric_np(), [1, 2, 3])


def test_parquet_timestamps_strings_and_errors(tmp_path):
    pa = pytest.importorskip("pyarrow")
    import datetime

    import pyarrow.parquet as pq

    t = pa.table({
        "ts": pa.array([datetime.datetime(2020, 1, 1), None,
                        datetime.datetime(2020, 1, 2)]),
        # '' and 'NA' are REAL values in parquet (nulls are explicit)
        "s": pa.array(["", "NA", None]),
    })
    p = tmp_path / "ts.parquet"
    pq.write_table(t, p)
    fr = h2o.import_file(str(p))
    assert fr.key == "ts.parquet"
    ts = fr.vec("ts").numeric_np()
    assert np.isnan(ts[1]) and ts[2] - ts[0] == 86400_000.0
    v = fr.vec("s")
    assert v.domain == ["", "NA"]
    assert np.asarray(v.data).tolist() == [0, 1, -1]
    # unsupported binary column -> clear error naming the column
    t2 = pa.table({"b": pa.array([b"ab", b"cd"], type=pa.binary())})
    p2 = tmp_path / "bin.parquet"
    pq.write_table(t2, p2)
    with pytest.raises(ValueError, match="'b'"):
        h2o.import_file(str(p2))


def test_import_directory_with_pattern(tmp_path, cloud1):
    """Directory import rbinds every matching file (ParseDataset
    multi-file import; `h2o.import_file(dir, pattern=...)`)."""
    import numpy as np

    import h2o3_tpu as h2o

    d = tmp_path / "parts"
    d.mkdir()
    for i in range(3):
        with open(d / f"part{i}.csv", "w") as f:
            f.write("a,b\n")
            for r in range(10):
                f.write(f"{i * 10 + r},{r}\n")
    (d / "notes.txt").write_text("not,data\nx,y\n")
    fr = h2o.import_file(str(d), pattern=r"part\d\.csv$")
    assert fr.nrow == 30 and fr.names == ["a", "b"]
    a = np.sort(fr.vec("a").numeric_np())
    np.testing.assert_allclose(a[:3], [0, 1, 2])
    np.testing.assert_allclose(a[-1], 29)
    import pytest

    with pytest.raises(ValueError, match="no files"):
        h2o.import_file(str(d), pattern=r"nomatch")
