"""Fused GBM hot path (ISSUE 7) — packed-code histograms, single-pass
split search, overlapped chunk scoring.

Pins: (1) every fused lever is BIT-EXACT against the ``H2O3_TREE_LEGACY=1``
comparator across the parity matrix (GBM/DRF, mtries, monotone,
compact-cap, CV fold reuse, overlap on/off); (2) a warm higgs-shaped fit
re-traces ZERO programs (the ROADMAP item 2 pin, via the PR 6 XLA
tracker); (3) the histogram kernel auto-dispatch is observable — per-fit
plans, dispatch counters, and the previously-silent VMEM-pressure
fallback; (4) the forced-CPU bench floor (slow)."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from h2o3_tpu.models import tree as treelib
from h2o3_tpu.ops import histogram, packing

from conftest import make_classification


@pytest.fixture()
def _no_legacy():
    """Isolate the legacy/overlap env knobs per test."""
    keys = ("H2O3_TREE_LEGACY", "H2O3_TREE_OVERLAP", "H2O3_HIST_METHOD",
            "H2O3_HOST_HIST_MIN_ROWS")
    prior = {k: os.environ.pop(k, None) for k in keys}
    yield
    for k, v in prior.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


def _tree_data(seed=1, N=2048, F=9, B=21):
    rng = np.random.default_rng(seed)
    codes = rng.integers(0, B, (N, F)).astype(np.uint8)
    g = rng.normal(size=N).astype(np.float32)
    h = rng.random(N).astype(np.float32) + 0.1
    w = np.where(rng.random(N) > 0.05, 1.0, 0.0).astype(np.float32)
    fm = np.ones(F, np.float32)
    edges = np.sort(rng.normal(size=(F, B - 2)), axis=1).astype(np.float32)
    return codes, g, h, w, fm, edges, B


def _leaves_equal(a, b):
    return all(np.array_equal(np.asarray(x), np.asarray(y), equal_nan=True)
               for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)))


# -- ops: packed consumption ------------------------------------------------

def test_packed_row_values_exact():
    rng = np.random.default_rng(0)
    N, F = 4096, 7
    for bits, B in ((4, 16), (5, 21), (6, 33)):
        codes = rng.integers(0, B, (N, F)).astype(np.uint8)
        pk = packing.pack_host(codes, bits)
        rf = rng.integers(0, F, N).astype(np.int32)
        got = np.asarray(packing.packed_row_values(
            jnp.asarray(pk), jnp.asarray(rf), bits))
        assert np.array_equal(got, codes[np.arange(N), rf])


def test_host_histogram_bitexact_with_segment_packed_and_dense():
    """The np.add.at host callback runs the same sequential in-order f32
    fold as the XLA sorted scatter — bit-exact, packed or dense."""
    rng = np.random.default_rng(2)
    N, F, L = 4096, 6, 4
    node = rng.integers(0, L, N).astype(np.int32)
    g = rng.normal(size=N).astype(np.float32)
    h = rng.random(N).astype(np.float32)
    w = (rng.random(N) > 0.1).astype(np.float32)
    for bits, B in ((4, 16), (5, 21), (6, 33)):
        codes = rng.integers(0, B, (N, F)).astype(np.uint8)
        pk = packing.pack_host(codes, bits)
        ref = np.asarray(histogram.build_histograms(
            jnp.asarray(codes), jnp.asarray(node), jnp.asarray(g),
            jnp.asarray(h), jnp.asarray(w), L, B, method="segment"))
        for codes_in, pb in ((codes, 0), (pk, bits)):
            got = np.asarray(histogram.build_histograms(
                jnp.asarray(codes_in), jnp.asarray(node), jnp.asarray(g),
                jnp.asarray(h), jnp.asarray(w), L, B, method="host",
                pack_bits=pb))
            assert np.array_equal(ref, got), (bits, pb)


# -- build_tree: the parity matrix ------------------------------------------

@pytest.mark.parametrize("variant", [
    "fused", "packed", "packed_fused", "mtries", "monotone",
    "alpha_lambda0",
])
def test_build_tree_fused_packed_parity(variant):
    codes, g, h, w, fm, edges, B = _tree_data()
    bits = packing.pack_bits_for(B, codes.shape[0])
    pk = packing.pack_host(codes, bits)
    key = jax.random.PRNGKey(3)
    kw = dict(max_depth=4, nbins=B, min_rows=5.0, key=key)
    if variant == "mtries":
        kw["mtries_rate"] = jnp.float32(0.5)
    if variant == "monotone":
        mono = np.zeros(codes.shape[1], np.float32)
        mono[0], mono[3] = 1.0, -1.0
        kw["monotone"] = jnp.asarray(mono)
    if variant == "alpha_lambda0":
        kw.update(reg_lambda=0.0, reg_alpha=0.5)   # NaN-prone gains
    base = treelib.build_tree(jnp.asarray(codes), g, h, w, fm, edges, **kw)
    fused_kw = dict(kw, fused_split=True)
    if variant != "fused":
        got = treelib.build_tree(jnp.asarray(pk), g, h, w, fm, edges,
                                 pack_bits=bits, **fused_kw)
    else:
        got = treelib.build_tree(jnp.asarray(codes), g, h, w, fm, edges,
                                 **fused_kw)
    assert _leaves_equal(base, got)


def test_build_tree_compact_cap_parity_and_overflow_flag():
    """Compact-phase split search + partition on packed/fused match the
    legacy dense comparator, including the overflow flag the driver's
    dense-rebuild guard consumes."""
    codes, g, h, w, fm, edges, B = _tree_data(N=2048, F=9)
    bits = packing.pack_bits_for(B, codes.shape[0])
    pk = packing.pack_host(codes, bits)
    key = jax.random.PRNGKey(5)
    kw = dict(max_depth=8, nbins=B, min_rows=1.0, key=key)
    base = treelib.build_tree(jnp.asarray(codes), g, h, w, fm, edges,
                              compact_cap=64, **kw)
    got = treelib.build_tree(jnp.asarray(pk), g, h, w, fm, edges,
                             compact_cap=64, pack_bits=bits,
                             fused_split=True, **kw)
    assert _leaves_equal(base, got)
    assert int(np.asarray(base[-1])) == int(np.asarray(got[-1]))
    # a cap too small for the live frontier must raise the flag on BOTH
    # paths (the driver then rebuilds densely — exactness never traded)
    *_, ov_l = treelib.build_tree(jnp.asarray(codes), g, h, w, fm, edges,
                                  compact_cap=4, **kw)
    *_, ov_f = treelib.build_tree(jnp.asarray(pk), g, h, w, fm, edges,
                                  compact_cap=4, pack_bits=bits,
                                  fused_split=True, **kw)
    assert int(np.asarray(ov_l)) > 0
    assert int(np.asarray(ov_l)) == int(np.asarray(ov_f))


# -- whole-fit parity against the legacy flag -------------------------------

# ONE shared whole-fit shape: every driver-level test below uses the same
# (row bucket, F, max_depth, nbins) so they all land on a single fused and
# a single legacy compiled tree program — the fused body is ~2x the trace
# work per structural config, so the suite pays it once, not per test.
_FIT_N, _FIT_F, _FIT_DEPTH = 4096, 6, 4
_FIT_X, _FIT_Y = make_classification(n=_FIT_N, f=_FIT_F, seed=7)
_FIT_NAMES = [f"f{i}" for i in range(_FIT_F)] + ["label"]


def _frame(X, y, names):
    from h2o3_tpu.frame.frame import Frame

    return Frame.from_numpy(np.column_stack([X, y]),
                            names=names).asfactor("label")


def _fit_gbm(legacy, X, y, names, overlap=None, **params):
    from h2o3_tpu.models import dataset_cache
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

    dataset_cache.clear()
    os.environ.pop("H2O3_TREE_LEGACY", None)
    if legacy:
        os.environ["H2O3_TREE_LEGACY"] = "1"
    if overlap is not None:
        os.environ["H2O3_TREE_OVERLAP"] = overlap
    else:
        os.environ.pop("H2O3_TREE_OVERLAP", None)
    try:
        gbm = H2OGradientBoostingEstimator(seed=42, **params)
        gbm.train(y="label", training_frame=_frame(X, y, names))
    finally:
        os.environ.pop("H2O3_TREE_LEGACY", None)
        os.environ.pop("H2O3_TREE_OVERLAP", None)
    return gbm


def _assert_models_bitexact(a, b):
    assert a.model.ntrees_built == b.model.ntrees_built
    for k in range(len(a.model.forest)):
        for f in treelib.Tree._fields:
            assert np.array_equal(
                np.asarray(getattr(a.model.forest[k], f)),
                np.asarray(getattr(b.model.forest[k], f))), (k, f)
    va = getattr(a.model, "varimp_table", None)
    vb = getattr(b.model, "varimp_table", None)
    if va is not None or vb is not None:
        assert [r[0] for r in va] == [r[0] for r in vb]
        np.testing.assert_array_equal([r[1] for r in va],
                                      [r[1] for r in vb])


def test_gbm_fit_parity_fused_vs_legacy(cloud1, _no_legacy):
    """Whole-fit pin: packed codes × fused split × overlapped scoring with
    early stopping produce the bit-identical forest, gain-based varimp,
    scoring history, and predictions of the legacy path."""
    X, y, names = _FIT_X, _FIT_Y, _FIT_NAMES
    params = dict(ntrees=12, max_depth=_FIT_DEPTH, learn_rate=0.1,
                  score_tree_interval=3, stopping_rounds=2,
                  stopping_tolerance=1e-9)
    # drop the host-kernel row floor so THIS fit exercises the full fused
    # stack (packed codes + np.add.at host histograms + overlap) end to
    # end; the other whole-fit tests keep the small-fit segment default
    os.environ["H2O3_HOST_HIST_MIN_ROWS"] = "1"
    new = _fit_gbm(False, X, y, names, **params)
    old = _fit_gbm(True, X, y, names, **params)
    _assert_models_bitexact(new, old)
    h_new = [e.get("logloss") for e in new.model.scoring_history]
    h_old = [e.get("logloss") for e in old.model.scoring_history]
    assert h_new == h_old
    fr = _frame(X, y, names)
    pa = new.model.predict(fr)
    pb = old.model.predict(fr)
    np.testing.assert_array_equal(np.asarray(pa.vec("1").data),
                                  np.asarray(pb.vec("1").data))


def test_gbm_fit_parity_overlap_off(cloud1, _no_legacy):
    """H2O3_TREE_OVERLAP=0 (no speculative chunk) is bit-identical to the
    overlapped default — overlap is a scheduling change, not a math one."""
    X, y, names = _FIT_X, _FIT_Y, _FIT_NAMES
    params = dict(ntrees=10, max_depth=_FIT_DEPTH, score_tree_interval=2,
                  stopping_rounds=1, stopping_tolerance=1e-9)
    a = _fit_gbm(False, X, y, names, overlap="1", **params)
    b = _fit_gbm(False, X, y, names, overlap="0", **params)
    _assert_models_bitexact(a, b)


def test_early_stop_discards_speculative_chunk(cloud1, _no_legacy):
    """When the stopper FIRES with a speculative chunk in flight, the
    chunk is discarded and the pre-dispatch state restored: tree count,
    forest, and the training metrics computed from the restored margins
    all match the legacy (never-speculated) path bit-for-bit."""
    X, y, names = _FIT_X, _FIT_Y, _FIT_NAMES
    # tiny learn rate + huge tolerance → the stopper fires mid-run
    params = dict(ntrees=40, max_depth=_FIT_DEPTH, learn_rate=0.01,
                  score_tree_interval=2, stopping_rounds=1,
                  stopping_tolerance=0.5)
    new = _fit_gbm(False, X, y, names, **params)
    old = _fit_gbm(True, X, y, names, **params)
    assert new.model.ntrees_built < 40, "stopper must fire for this pin"
    _assert_models_bitexact(new, old)
    np.testing.assert_array_equal(new.model.training_metrics.logloss(),
                                  old.model.training_metrics.logloss())


def test_drf_fit_parity_fused_vs_legacy(cloud1, _no_legacy):
    """DRF: per-node mtries column sampling + OOB scoring through the
    packed/fused path match the legacy comparator bit-for-bit."""
    from h2o3_tpu.models import dataset_cache
    from h2o3_tpu.models.drf import H2ORandomForestEstimator

    X, y, names = _FIT_X, _FIT_Y, _FIT_NAMES

    def fit(legacy):
        dataset_cache.clear()
        os.environ.pop("H2O3_TREE_LEGACY", None)
        if legacy:
            os.environ["H2O3_TREE_LEGACY"] = "1"
        try:
            drf = H2ORandomForestEstimator(ntrees=8, max_depth=_FIT_DEPTH,
                                           seed=42, score_tree_interval=4)
            drf.train(y="label", training_frame=_frame(X, y, names))
        finally:
            os.environ.pop("H2O3_TREE_LEGACY", None)
        return drf

    _assert_models_bitexact(fit(False), fit(True))


def test_cv_fold_reuse_parity_fused_vs_legacy(cloud1, _no_legacy):
    """CV fold reuse (PR 4) composes with the fused path: fold models
    slice the parent's PACKED artifact and the cross-validated parent is
    bit-identical to the legacy run's."""
    X, y, names = _FIT_X, _FIT_Y, _FIT_NAMES
    # folds inherit the parent's padded row bucket (_npad_floor), so even
    # the fold fits reuse the shared compiled programs
    params = dict(ntrees=6, max_depth=_FIT_DEPTH, nfolds=2,
                  keep_cross_validation_predictions=True)
    new = _fit_gbm(False, X, y, names, **params)
    old = _fit_gbm(True, X, y, names, **params)
    _assert_models_bitexact(new, old)
    ma = new.model.cross_validation_metrics
    mb = old.model.cross_validation_metrics
    assert ma is not None and mb is not None
    np.testing.assert_array_equal(ma.logloss(), mb.logloss())
    np.testing.assert_array_equal(ma.auc(), mb.auc())


# -- the warm-fit zero-retrace pin (ROADMAP item 2) -------------------------

def test_warm_fit_retraces_zero(cloud1, _no_legacy):
    """A warm higgs-shaped fit (same _StepCfg; scalar hyperparameters may
    differ — they are traced, not static) must trace ZERO new programs and
    re-trace nothing, per the PR 6 per-signature XLA tracker."""
    from h2o3_tpu.runtime import phases

    X, y, names = _FIT_X, _FIT_Y, _FIT_NAMES
    _fit_gbm(False, X, y, names, ntrees=5, max_depth=_FIT_DEPTH,
             learn_rate=0.1)
    before = phases.xla_counts()
    # warm fit: same structural shape, different traced scalar (learn_rate)
    _fit_gbm(False, X, y, names, ntrees=5, max_depth=_FIT_DEPTH,
             learn_rate=0.2)
    after = phases.xla_counts()
    assert after["retraces"] == before["retraces"], \
        "warm fit re-traced a program signature"
    assert after["traces"] == before["traces"], \
        "warm fit traced a NEW program (cfg key must cover it)"


# -- kernel-selection observability -----------------------------------------

def test_fit_plan_recorded_and_profiler_fold(cloud1, _no_legacy):
    X, y = make_classification(n=2048, f=5, seed=17)
    names = [f"f{i}" for i in range(5)] + ["label"]
    # force the host lane explicitly: auto only picks it past MIN_ROWS
    # AND with a spare core to service the callback (host_callback_safe —
    # 1-core hosts keep `segment`), and this test pins the host lane's
    # plan/dispatch observability, not the selection policy
    os.environ["H2O3_HIST_METHOD"] = "host"
    _fit_gbm(False, X, y, names, ntrees=2, max_depth=3)
    stats = histogram.kernel_stats()
    assert stats["plans"], "fit recorded no kernel plan"
    plan = stats["plans"][-1]
    assert plan["hist_method"] == "host"      # the fused CPU default
    assert plan["pack_bits"] in (4, 5, 6)
    assert all(lv["method"] == "host" for lv in plan["levels"])
    assert stats["dispatch"].get("host", 0) > 0
    from h2o3_tpu.runtime import profiler

    fold = profiler.tree_stats()
    assert fold["active"] and fold["plans"]
    # the dispatch counters reach the Prometheus scrape surface
    from h2o3_tpu.runtime import metrics_registry

    text = metrics_registry.prometheus_text()
    assert "h2o3_tree_hist_dispatch_total" in text


def test_vmem_fallback_counted_and_logged(_no_legacy):
    """The previously-silent `_factored_row_chunk` < 512 fallback is
    observable: resolve_method reports it, record_fit_plan counts it in
    the registry and logs once per fit."""
    from h2o3_tpu.runtime import metrics_registry

    # a level too wide for any VMEM row chunk (L·B blows the scratch)
    sel = histogram.resolve_method(1 << 16, 64, "pallas_factored",
                                   platform="tpu")
    assert sel == {"method": "segment", "row_chunk": None,
                   "fallback": "vmem"}
    # and a feasible one keeps the pallas kernel + its row chunk
    ok = histogram.resolve_method(16, 64, "pallas_factored", platform="tpu")
    assert ok["method"] == "pallas_factored" and ok["row_chunk"] >= 512
    before = metrics_registry.get("h2o3_tree_hist_vmem_fallbacks").total()
    plan = histogram.record_fit_plan(
        "test:vmem", [("d0", 1), ("d16", 1 << 16)], 64,
        "pallas_factored", platform="tpu")
    after = metrics_registry.get("h2o3_tree_hist_vmem_fallbacks").total()
    assert after == before + 1
    assert [lv["fallback"] for lv in plan["levels"]] == [None, "vmem"]
    # the host callback can never run under a collective program
    sel = histogram.resolve_method(4, 21, "host", axis_name="hosts")
    assert sel["method"] == "segment" and sel["fallback"] == "collective"


def test_dataset_cache_keys_pack_mode(cloud1, _no_legacy):
    """A packed and a full-width consumer never share a device artifact."""
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models import dataset_cache

    dataset_cache.clear()
    X, y = make_classification(n=512, f=4, seed=23)
    fr = Frame.from_numpy(np.column_stack([X, y]),
                          names=["a", "b", "c", "d", "label"])
    calls = []
    for bits in (0, 5, 5):
        dataset_cache.device_codes(
            fr, ["a", "b", "c", "d"], 21, "AUTO", 1, 512,
            builder=lambda: calls.append(1) or jnp.zeros((1,)),
            pack_bits=bits)
    assert len(calls) == 2   # 0-bit and 5-bit miss; second 5-bit hits


# -- the forced-CPU bench floor (acceptance) --------------------------------

@pytest.mark.slow
def test_gbm_cpu_fused_speedup_floor(cloud1, _no_legacy):
    """BENCH_CONFIG=gbm_cpu acceptance: the fused kernel is ≥1.5× the
    legacy kernel on the forced-CPU lane (measured ~6-9× on the dev box;
    the floor absorbs scheduler noise)."""
    import time

    X, y = make_classification(n=60_000, f=28, seed=42, informative=8)
    names = [f"f{i}" for i in range(28)] + ["label"]
    params = dict(ntrees=10, max_depth=6, learn_rate=0.1,
                  histogram_type="UniformAdaptive")

    def wall(legacy, reps):
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            _fit_gbm(legacy, X, y, names, **params)
            best = min(best, time.perf_counter() - t0)
        return best

    # best-of-2 BOTH ways: each path's rep 1 absorbs its own trace/compile,
    # so the floor compares warm kernel against warm kernel
    w_new = wall(False, 2)
    w_old = wall(True, 2)
    assert w_old / w_new >= 1.5, \
        f"fused {w_new:.2f}s vs legacy {w_old:.2f}s — floor 1.5x missed"
