"""Serving fleet router (ISSUE 16) — versioned model registry, warm-loaded
replicas, canary/shadow rollout, pressure-aware admission.

Tier-1 section: the registry's atomicity contract as pure filesystem
checks (a publish killed mid-write is never visible to `live()`,
double-publish is idempotent, rollback-with-no-canary is an audited
no-op), the routing decisions as pure units (deterministic canary split,
ring ordering, drain accounting), and the REST face driven in-process —
two ring members that are THREAD-backed servers in this process, so the
full forward/failover/warm/canary-rollback paths run without spawning
interpreters. Tier-1 is at ~647 s of its 870 s budget; the tests that
need real replica PROCESSES live in the slow lane below.

Slow section: the acceptance pin — loadgen drives the router open-loop
against three live replica processes, one is killed mid-load, and the
caller sees zero hard errors while `h2o3_fleet_peer_up` flips to 0 and
post-drain p99 stays within 2x of the pre-kill baseline — plus a
one-minute `loadgen --router` soak whose `mem_growth_bytes_per_min`
canary pins the router's RSS slope (round 19)."""

import json
import os
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.parse
import urllib.request

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.runtime import faults, fleet
from h2o3_tpu.runtime import metrics_registry as registry
from h2o3_tpu.runtime.dkv import DKV
from h2o3_tpu.runtime.timeline import Timeline
from h2o3_tpu.serving import reset_engine
from h2o3_tpu.serving.config import ServingConfig
from h2o3_tpu.serving.registry import reset_registry, versioned_key
from h2o3_tpu.serving.router import (RouterConfig, _Replica, reset_router)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_router(tmp_path):
    fleet.reset()
    faults.reset()
    reset_registry(str(tmp_path / "registry"))
    reset_router(RouterConfig())
    yield
    faults.reset()
    fleet.reset()
    reset_registry()
    reset_router()


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=30) as r:
        return json.loads(r.read())


def _post(port, path, data=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=urllib.parse.urlencode(data or {}).encode())
    with urllib.request.urlopen(req, timeout=60) as r:
        return json.loads(r.read())


# -- registry atomicity (pure; the publish pins) -----------------------------

def _src(tmp_path, name="src.zip", blob=b"mojo-bytes"):
    p = tmp_path / name
    p.write_bytes(blob)
    return str(p)


def test_publish_mid_write_failure_never_visible(tmp_path):
    """The atomicity pin: a publish whose artifact write dies (persist
    fault on the .part write) raises, and leaves `live()`/`versions()`/
    the registry directory exactly as they were — no half-artifact a
    replica could ever list or warm-load."""
    reg = reset_registry(str(tmp_path / "reg"))
    src = _src(tmp_path)
    faults.arm("persist.open", error="io", rate=1.0, match=".part")
    cur = Timeline.cursor()
    with pytest.raises(Exception):
        reg.publish("m", "v1", source_path=src)
    assert reg.live("m") is None
    assert reg.versions("m") == []
    # nothing on disk: no final .zip, and the .part was cleaned up
    assert not os.path.exists(reg.root) or os.listdir(reg.root) == []
    evs = [e for e in Timeline.snapshot(since=cur)
           if e["kind"] == "registry" and "publish_failed" in e["detail"]]
    assert evs, "failed publish must leave an audit event"
    # disarm → the same publish goes through and the record registers
    faults.reset()
    rec = reg.publish("m", "v1", source_path=src)
    assert rec["state"] == "published"
    assert os.path.exists(rec["artifact"])
    with open(rec["artifact"], "rb") as f:
        assert f.read() == b"mojo-bytes"
    assert reg.live("m") is None          # published, not yet promoted


def test_double_publish_is_idempotent(tmp_path):
    reg = reset_registry(str(tmp_path / "reg"))
    rec1 = reg.publish("m", "v1", source_path=_src(tmp_path, "a.zip",
                                                   b"first"))
    cur = Timeline.cursor()
    # second publish of the same (model, version) with DIFFERENT bytes:
    # the first artifact wins, the record comes back untouched
    rec2 = reg.publish("m", "v1", source_path=_src(tmp_path, "b.zip",
                                                   b"second"))
    assert rec2["artifact"] == rec1["artifact"]
    assert rec2["state"] == rec1["state"] == "published"
    assert len(reg.versions("m")) == 1
    with open(rec1["artifact"], "rb") as f:
        assert f.read() == b"first"
    evs = [e for e in Timeline.snapshot(since=cur)
           if e["kind"] == "registry"]
    assert any("publish_noop" in e["detail"] for e in evs)


def test_rollback_with_no_canary_is_audited_noop(tmp_path):
    reg = reset_registry(str(tmp_path / "reg"))
    cur = Timeline.cursor()
    out = reg.rollback("m", reason="operator said so")
    assert out["noop"] is True and out["rolled_back"] is None
    evs = [e for e in Timeline.snapshot(since=cur)
           if e["kind"] == "registry" and e["detail"].startswith("rollback")]
    assert len(evs) == 1 and evs[0]["noop"] is True
    assert evs[0]["reason"] == "operator said so"


def test_lifecycle_promote_canary_retire_rules(tmp_path):
    reg = reset_registry(str(tmp_path / "reg"))
    src = _src(tmp_path)
    reg.publish("m", "v1", source_path=src)
    reg.promote("m", "v1")
    assert reg.live("m") == "v1"
    reg.publish("m", "v2", source_path=src)
    # a live version cannot be its own canary
    with pytest.raises(ValueError):
        reg.set_canary("m", "v1", 10.0)
    # the live version cannot retire out from under traffic
    with pytest.raises(ValueError):
        reg.retire("m", "v1")
    reg.set_canary("m", "v2", 25.0)
    assert reg.canary("m") == ("v2", 25.0)
    # promote is the atomic flip: live moves, canary clears, v1 retires
    reg.promote("m", "v2")
    assert reg.live("m") == "v2"
    assert reg.canary("m") == (None, 0.0)
    states = {r["version"]: r["state"] for r in reg.versions("m")}
    assert states == {"v1": "retired", "v2": "live"}
    # rollback after the canary is gone: the audited no-op again
    assert reg.rollback("m")["noop"] is True
    # canary rolled back (not promoted) ends in `failed`
    reg.publish("m", "v3", source_path=src)
    reg.set_canary("m", "v3", 10.0)
    out = reg.rollback("m", reason="p99 breach")
    assert out["rolled_back"] == "v3" and out["noop"] is False
    rec = [r for r in reg.versions("m") if r["version"] == "v3"][0]
    assert rec["state"] == "failed" and "rollback" in rec["events"]


# -- routing decisions (pure units) ------------------------------------------

def test_canary_split_is_deterministic(tmp_path):
    """A 10% canary gets exactly 10 of every 100 requests — sequence mod
    100 against the split percent, not a coin flip."""
    reg = reset_registry(str(tmp_path / "reg"))
    src = _src(tmp_path)
    reg.publish("m", "v1", source_path=src)
    reg.promote("m", "v1")
    reg.publish("m", "v2", source_path=src)
    reg.set_canary("m", "v2", 10.0)
    router = reset_router(RouterConfig())
    lanes = [router._pick_version("m", s) for s in range(200)]
    assert lanes.count(("v2", "canary")) == 20
    assert lanes.count(("v1", "live")) == 180
    assert versioned_key("m", "v2") == "m@v2"
    # no registry state at all → the unversioned pass-through lane
    assert router._pick_version("other", 0) == (None, "unversioned")


def test_candidate_ranking_and_drain_accounting():
    router = reset_router(RouterConfig(drain_errors=2,
                                       drain_cooldown_s=30.0))
    a = _Replica("a", "http://x")
    a.pressure = 0.9
    b = _Replica("b", "http://x")
    b.inflight = 2
    b.pressure = 0.1
    c = _Replica("c", "http://x")
    c.up = False
    d = _Replica("d", "http://x")
    d.drained_until = time.monotonic() + 60
    router._replicas = {r.name: r for r in (a, b, c, d)}
    # in-flight dominates, drained replicas sort behind healthy ones,
    # down replicas last
    assert [r.name for r in router._candidates()] == ["a", "b", "d", "c"]
    # at equal in-flight, scraped pressure breaks the tie
    b.inflight = 0
    assert [r.name for r in router._candidates()][0] == "b"
    # drain only after `drain_errors` CONSECUTIVE failures
    router._mark_result(a, ok=False)
    assert a.drained_until <= time.monotonic()
    router._mark_result(a, ok=True)        # success resets the streak
    router._mark_result(a, ok=False)
    assert a.drained_until <= time.monotonic()
    router._mark_result(a, ok=False)
    assert a.drained_until > time.monotonic()
    assert router._counters["drains"] == 1


# -- REST face, in-process ----------------------------------------------------

@pytest.fixture(scope="module")
def router_server():
    from h2o3_tpu.rest.server import start_server

    srv = start_server(port=0)
    yield srv
    srv.stop()


def test_router_document_and_schema(router_server):
    doc = _get(router_server.port, "/3/Router?probe=0")
    assert doc["__meta"]["schema_type"] == "RouterV3"
    assert set(doc) >= {"__meta", "ring", "inflight", "totals", "models",
                        "canary_health", "config"}
    assert set(doc["totals"]) == {
        "requests", "errors", "shed", "retries", "failovers", "drains",
        "rollbacks", "warm_loads", "shadow_requests", "shadow_errors",
        "shadow_mismatches", "shadow_dropped"}
    schema = _get(router_server.port, "/3/Router?schema=1")
    assert schema["name"] == "RouterV3"
    fields = {f["name"] for f in schema["fields"]}
    assert {"ring", "totals", "models", "canary_health"} <= fields


def test_router_sheds_budget_with_retry_after(router_server):
    reset_router(RouterConfig(max_inflight=0, retry_after_s=2.0))
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(router_server.port, "/3/Router/models/m/frames/f")
    assert ei.value.code == 429
    assert ei.value.headers["Retry-After"] == "2"
    assert b"shed" in ei.value.read()
    doc = _get(router_server.port, "/3/Router?probe=0")
    assert doc["totals"]["shed"] == 1
    with urllib.request.urlopen(
            f"http://127.0.0.1:{router_server.port}/3/Metrics") as r:
        text = r.read().decode()
    assert 'h2o3_router_shed_total{reason="budget"}' in text


def test_router_sheds_when_ring_is_empty(router_server):
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(router_server.port, "/3/Router/models/m/frames/f")
    assert ei.value.code == 429
    assert b"no registered replicas" in ei.value.read()
    doc = _get(router_server.port, "/3/Router?probe=0")
    assert doc["totals"]["shed"] == 1 and doc["ring"] == []


def _train_gbm(tag):
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

    rng = np.random.default_rng(7)
    n = 200
    X = rng.normal(size=(n, 3))
    y = (X[:, 0] + X[:, 1] > 0).astype(np.int64)
    fr = Frame.from_dict(
        {"a": X[:, 0], "b": X[:, 1], "c": X[:, 2],
         "y": np.asarray(["n", "p"], dtype=object)[y]},
        column_types={"y": "enum"})
    fr.key = f"router_fr_{tag}"
    DKV.put(fr.key, fr)
    est = H2OGradientBoostingEstimator(ntrees=3, max_depth=3, seed=1,
                                       model_id=f"router_gbm_{tag}")
    est.train(x=["a", "b", "c"], y="y", training_frame=fr)
    DKV.put(est.model.model_id, est.model)
    return est.model.model_id, fr.key


@pytest.fixture()
def serving_engine():
    engine = reset_engine(ServingConfig(
        max_batch_rows=4096, max_wait_ms=2.0, request_timeout_s=30.0,
        idle_worker_s=2.0, max_queue=64, model_inflight=64,
        retry_after_s=1.0, cache_capacity=8))
    yield engine
    reset_engine()


def test_router_routes_and_fails_over_in_process(router_server, cloud1,
                                                 serving_engine):
    """Two ring members (both thread-backed by this process's server);
    the first one's forwards fail at the injection point — the request
    retries on the peer and the caller never sees an error."""
    mid, fkey = _train_gbm("failover")
    url = f"http://127.0.0.1:{router_server.port}"
    fleet.register_peer("r1", url)
    fleet.register_peer("r2", url)
    reset_router(RouterConfig(refresh_s=60.0, max_attempts=3,
                              drain_errors=100))
    faults.arm("router.forward", error="conn", rate=1.0, match="r1:")
    doc = _post(router_server.port,
                f"/3/Router/models/{mid}/frames/{fkey}")
    assert doc["predictions_frame"]["name"]
    snap = _get(router_server.port, "/3/Router?probe=0")
    assert snap["totals"]["requests"] == 1
    assert snap["totals"]["errors"] == 0
    assert snap["totals"]["failovers"] >= 1
    assert snap["totals"]["retries"] >= 1
    r1 = [r for r in snap["ring"] if r["name"] == "r1"][0]
    assert r1["consecutive_errors"] >= 1
    with urllib.request.urlopen(f"{url}/3/Metrics") as r:
        text = r.read().decode()
    assert 'h2o3_router_failovers_total{replica="r1"}' in text
    # the faulted replica exhausted on every lane → caller-visible 500
    faults.arm("router.forward", error="conn", rate=1.0)
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(router_server.port, f"/3/Router/models/{mid}/frames/{fkey}")
    assert ei.value.code == 500
    ei.value.read()
    snap2 = _get(router_server.port, "/3/Router?probe=0")
    assert snap2["totals"]["errors"] == 1


def test_warm_load_zero_trace_pin_and_shadow(router_server, cloud1,
                                             serving_engine):
    """The warm-load pin: publish → warm (replica loads the mojo and
    primes the compiled scorer) → promote → the FIRST routed predict on
    the live version records zero new XLA traces. Then a shadow version
    mirrors traffic without ever reaching the caller."""
    from h2o3_tpu.runtime import phases

    mid, fkey = _train_gbm("warm")
    url = f"http://127.0.0.1:{router_server.port}"
    fleet.register_peer("self", url)
    router = reset_router(RouterConfig(refresh_s=60.0,
                                       shadow_compare_rows=5))
    out = _post(router_server.port, "/3/Router",
                dict(action="publish", model=mid, version="v1"))
    assert out["state"] == "published" and os.path.exists(out["artifact"])
    warm = _post(router_server.port, "/3/Router",
                 dict(action="warm", model=mid, version="v1", frame=fkey))
    assert warm["warmed"] == 1
    rep = warm["replicas"]["self"]
    assert rep["ok"] and rep["primed"] and rep["model"] == f"{mid}@v1"
    _post(router_server.port, "/3/Router",
          dict(action="promote", model=mid, version="v1"))
    xla1 = phases.xla_counts()
    doc = _post(router_server.port,
                f"/3/Router/models/{mid}/frames/{fkey}")
    assert doc["predictions_frame"]["name"]
    # the hot-swap pin (ISSUE 6 counters): warm-loading primed the scorer
    # cache for the versioned key, so the first LIVE predict is traceless
    xla2 = phases.xla_counts()
    assert xla2["traces"] == xla1["traces"], "first live predict traced!"
    assert xla2["retraces"] == xla1["retraces"]
    snap = _get(router_server.port, "/3/Router?probe=0")
    m = snap["models"][mid]
    assert m["live"] == "v1"
    v1 = [r for r in m["versions"] if r["version"] == "v1"][0]
    assert v1["state"] == "live" and "self" in v1["warmed"]
    # shadow: publish+warm v2, mirror-only — the caller's traffic stays
    # on v1 while v2 sees a copy on a daemon thread
    _post(router_server.port, "/3/Router",
          dict(action="publish", model=mid, version="v2"))
    _post(router_server.port, "/3/Router",
          dict(action="warm", model=mid, version="v2", frame=fkey))
    _post(router_server.port, "/3/Router",
          dict(action="shadow", model=mid, version="v2"))
    doc = _post(router_server.port,
                f"/3/Router/models/{mid}/frames/{fkey}")
    assert doc["predictions_frame"]["name"]
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        totals = router.snapshot(probe=False)["totals"]
        if totals["shadow_requests"] >= 1 and router._shadow_inflight == 0:
            break
        time.sleep(0.05)
    assert totals["shadow_requests"] >= 1
    assert totals["shadow_errors"] == 0
    # same artifact → identical prediction heads → no mismatch verdict
    assert totals["shadow_mismatches"] == 0
    # empty version stops shadowing
    _post(router_server.port, "/3/Router", dict(action="shadow", model=mid))
    assert router.registry.shadow(mid) is None


def test_canary_auto_rollback_pin(router_server, cloud1, serving_engine):
    """The canary pin: a version-scoped `serving.scorer` crash fault on
    the candidate makes every canary-lane request fail; after
    `canary_min_samples` observations the router rolls the registry back
    automatically, live traffic never drops, and the story is visible in
    /3/Router, the rollback counter and the timeline."""
    mid, fkey = _train_gbm("canary")
    url = f"http://127.0.0.1:{router_server.port}"
    fleet.register_peer("self", url)
    router = reset_router(RouterConfig(refresh_s=60.0, drain_errors=1000,
                                       canary_min_samples=5))
    for action, ver in (("publish", "v1"), ("warm", "v1"),
                        ("promote", "v1"), ("publish", "v2"),
                        ("warm", "v2")):
        _post(router_server.port, "/3/Router",
              dict(action=action, model=mid, version=ver,
                   **(dict(frame=fkey) if action == "warm" else {})))
    _post(router_server.port, "/3/Router",
          dict(action="canary", model=mid, version="v2", pct=50))
    # fail EXACTLY the candidate's traffic: the fault matches the
    # versioned DKV key the router rewrites canary requests to
    faults.arm("serving.scorer", error="crash", rate=1.0,
               match=versioned_key(mid, "v2"))
    cur = Timeline.cursor()
    ok, failed = 0, 0
    for _ in range(60):
        try:
            _post(router_server.port,
                  f"/3/Router/models/{mid}/frames/{fkey}")
            ok += 1
        except urllib.error.HTTPError as e:
            assert e.code == 500
            e.read()
            failed += 1
    # the 50% split sends the first 50 of 100 sequence slots to the
    # canary; the 5th failure trips the verdict, everything after rides
    # the live lane untouched
    assert failed == 5 and ok == 55
    assert router.registry.canary(mid) == (None, 0.0)
    snap = _get(router_server.port, "/3/Router?probe=0")
    m = snap["models"][mid]
    assert m["live"] == "v1" and m["canary"] is None
    v2 = [r for r in m["versions"] if r["version"] == "v2"][0]
    assert v2["state"] == "failed" and "rollback" in v2["events"]
    assert snap["totals"]["rollbacks"] == 1
    assert snap["canary_health"] == {}     # window dropped with the canary
    with urllib.request.urlopen(f"{url}/3/Metrics") as r:
        text = r.read().decode()
    line = [l for l in text.splitlines() if l.startswith(
        f'h2o3_router_rollbacks_total{{model="{mid}"}}')]
    assert line and float(line[0].rsplit(" ", 1)[1]) == 1.0
    evs = [e for e in Timeline.snapshot(since=cur)
           if e["kind"] == "registry"
           and e["detail"] == f"rollback {mid}@v2"]
    assert evs and evs[0]["reason"].startswith("auto:")
    # live traffic still flows after the rollback
    doc = _post(router_server.port,
                f"/3/Router/models/{mid}/frames/{fkey}")
    assert doc["predictions_frame"]["name"]


def test_profiler_carries_router_fold(router_server):
    fleet.register_peer("rp", "http://127.0.0.1:1")
    reset_router(RouterConfig())
    doc = _get(router_server.port, "/3/Profiler")
    assert doc["router"]["active"] is True
    assert set(doc["router"]["totals"]) >= {"requests", "shed", "rollbacks"}


# -- the real thing: three live replica PROCESSES (slow lane) -----------------
# Multi-process router tests are slow-lane by charter: tier-1 sits at
# ~647 s of its 870 s budget, and this test pays three interpreter
# startups each importing jax and training a model before the first
# routed request.

REPLICA_BODY = """
import sys, time
sys.path.insert(0, {repo!r})
import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["H2O3_REPLICA_NAME"] = {name!r}
import numpy as np
import urllib.request
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.runtime.dkv import DKV
from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
from h2o3_tpu.rest.server import start_server
rng = np.random.default_rng(7)
n = 500
X = rng.normal(size=(n, 3))
y = (X[:, 0] + X[:, 1] > 0).astype(np.int64)
fr = Frame.from_dict(
    {{"a": X[:, 0], "b": X[:, 1], "c": X[:, 2],
      "y": np.asarray(["n", "p"], dtype=object)[y]}},
    column_types={{"y": "enum"}})
fr.key = "fleet_frame"
DKV.put(fr.key, fr)
est = H2OGradientBoostingEstimator(ntrees=5, max_depth=3, seed=42,
                                   model_id="fleet_gbm")
est.train(x=["a", "b", "c"], y="y", training_frame=fr)
DKV.put("fleet_gbm", est.model)
srv = start_server(port={port})
for _ in range(2):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/3/Predictions/models/fleet_gbm"
        "/frames/fleet_frame", data=b"")
    with urllib.request.urlopen(req, timeout=120) as r:
        r.read()
print("READY", flush=True)
time.sleep(600)
"""


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _load_loadgen():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "loadgen", os.path.join(REPO, "deploy", "loadgen.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.slow
def test_router_survives_replica_kill_mid_load():
    """The failover acceptance pin: open-loop load through the router
    against 3 replica processes; one replica is killed between measured
    windows WITHOUT telling the router, so window 2's early requests
    discover the corpse live. Zero hard errors in both windows, the dead
    replica drains and flips `h2o3_fleet_peer_up` to 0, and the post-kill
    p99 stays within 2x of the baseline."""
    from h2o3_tpu.rest.server import start_server

    loadgen = _load_loadgen()
    ports = [_free_port() for _ in range(3)]
    procs = []
    srv = None
    try:
        for i, port in enumerate(ports):
            procs.append(subprocess.Popen(
                [sys.executable, "-c",
                 REPLICA_BODY.format(repo=REPO, name=f"r{i + 1}",
                                     port=port)],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True))
        for i, p in enumerate(procs):
            deadline = time.monotonic() + 300
            while time.monotonic() < deadline:
                line = p.stdout.readline()
                if "READY" in line:
                    break
                if p.poll() is not None:
                    raise AssertionError(
                        f"replica {i} died: {p.stdout.read()[-2000:]}")
            else:
                raise AssertionError(f"replica {i} never came up")
        names = {}
        for i, port in enumerate(ports):
            name = f"r{i + 1}"
            names[name] = procs[i]
            fleet.register_peer(name, f"http://127.0.0.1:{port}")
        # drain on the FIRST failure (a dead socket is unambiguous) and
        # keep the corpse drained for the whole run; refresh_s is huge so
        # only the explicit probe and the OSError-forced refresh scrape —
        # the router must discover the corpse through a FAILED FORWARD,
        # not through a lucky background scrape beating the traffic to it
        router = reset_router(RouterConfig(refresh_s=600.0, drain_errors=1,
                                           drain_cooldown_s=120.0,
                                           max_attempts=3))
        router.refresh(force=True)
        srv = start_server(port=0)
        s1 = loadgen.run_load_open("127.0.0.1", srv.port, "fleet_gbm",
                                   "fleet_frame", rate=10.0,
                                   duration_s=4.0, router=True)
        assert s1["completed"] > 0
        assert s1["errors"] == 0 and s1["shed_429"] == 0
        # kill whichever replica the router currently ranks FIRST: the
        # next dispatch is then guaranteed to walk into the dead socket
        # (killing an arbitrary replica makes discovery — and therefore
        # the failover/drain counters — timing-dependent)
        victim_name = router._candidates()[0].name
        victim = names[victim_name]
        victim.kill()
        victim.wait(timeout=30)
        # window 2 discovers the corpse: requests that pick the dead
        # replica pay the reroute blip as LATENCY — the pin is that none
        # of them become caller-visible errors
        s2 = loadgen.run_load_open("127.0.0.1", srv.port, "fleet_gbm",
                                   "fleet_frame", rate=10.0,
                                   duration_s=4.0, router=True)
        assert s2["completed"] > 0
        assert s2["errors"] == 0 and s2["shed_429"] == 0
        # window 3 is post-drain: the dead replica is marked down and
        # drained, so p99 must recover to within 2x of the baseline
        s3 = loadgen.run_load_open("127.0.0.1", srv.port, "fleet_gbm",
                                   "fleet_frame", rate=10.0,
                                   duration_s=4.0, router=True)
        assert s3["completed"] > 0
        assert s3["errors"] == 0 and s3["shed_429"] == 0
        totals = router.snapshot(probe=True)["totals"]
        assert totals["failovers"] >= 1
        assert totals["drains"] >= 1
        gauge = registry.get("h2o3_fleet_peer_up")
        assert gauge is not None and gauge.value(victim_name) == 0.0
        ring = {r["name"]: r for r in router.snapshot(probe=False)["ring"]}
        assert ring[victim_name]["up"] == 0 and ring[victim_name]["drained"]
        # post-drain p99 within 2x of the pre-kill baseline (floored at
        # 25 ms so a sub-ms baseline doesn't turn scheduler noise into a
        # verdict)
        assert s1["p99_ms"] is not None and s3["p99_ms"] is not None
        assert s3["p99_ms"] <= 2.0 * max(s1["p99_ms"], 25.0)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        if srv is not None:
            srv.stop()


@pytest.mark.slow
def test_router_soak_memory_growth_canary(cloud1, serving_engine):
    """Sustained `loadgen --router` soak against a self-registered
    replica: a minute of open-loop traffic completes with zero hard
    errors and the RSS slope (`mem_growth_bytes_per_min`, the canary
    loadgen already computes for the serving engine) stays under a
    64 MB/min ceiling — a leaky router (response buffers, drained-replica
    state, per-request inflight entries) shows up here as a positive
    slope long before an OOM would."""
    from h2o3_tpu.rest.server import start_server

    loadgen = _load_loadgen()
    mid, fkey = _train_gbm("soak")
    srv = start_server(port=0)
    try:
        fleet.register_peer("self", f"http://127.0.0.1:{srv.port}")
        router = reset_router(RouterConfig(refresh_s=60.0, max_attempts=3,
                                           drain_errors=100))
        router.refresh(force=True)
        s = loadgen.run_load_open("127.0.0.1", srv.port, mid, fkey,
                                  rate=12.0, duration_s=60.0,
                                  timeout_s=30.0, router=True)
        assert s["completed"] >= 300, s
        assert s["errors"] == 0 and s["shed_429"] == 0, s
        assert len(s["mem_samples"]) >= 5
        growth = s["mem_growth_bytes_per_min"]
        assert growth is not None
        assert growth < 64 * 1024 * 1024, \
            f"router soak leaked {growth / 1e6:.1f} MB/min of RSS"
        # the ledger's view must not diverge either: accounted bytes
        # growing while RSS is flat means an owner is accumulating state
        lg = s["ledger_growth_bytes_per_min"]
        assert lg is None or lg < 64 * 1024 * 1024
    finally:
        srv.stop()
