"""Histogram-op and tree-builder unit tests (the ScoreBuildHistogram2 /
DTree.findBestSplitPoint layer, SURVEY.md §3.2)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from h2o3_tpu.ops.histogram import build_histograms
from h2o3_tpu.models import tree as treelib


def _ref_hist(codes, node_id, g, h, w, n_nodes, nbins):
    out = np.zeros((n_nodes, codes.shape[1], nbins, 3))
    for i in range(codes.shape[0]):
        for f in range(codes.shape[1]):
            out[node_id[i], f, codes[i, f], 0] += w[i]
            out[node_id[i], f, codes[i, f], 1] += g[i] * w[i]
            out[node_id[i], f, codes[i, f], 2] += h[i] * w[i]
    return out


@pytest.mark.parametrize("method", ["segment", "onehot"])
def test_histogram_matches_reference(method):
    rng = np.random.default_rng(0)
    N, F, B, L = 256, 5, 8, 4
    codes = rng.integers(0, B, (N, F)).astype(np.uint8)
    node = rng.integers(0, L, N).astype(np.int32)
    g = rng.normal(size=N).astype(np.float32)
    h = rng.uniform(0.1, 1, N).astype(np.float32)
    w = (rng.random(N) > 0.1).astype(np.float32)
    got = np.asarray(
        build_histograms(jnp.asarray(codes), jnp.asarray(node), jnp.asarray(g),
                         jnp.asarray(h), jnp.asarray(w), L, B, method=method)
    )
    want = _ref_hist(codes, node, g, h, w, L, B)
    np.testing.assert_allclose(got, want, rtol=2e-2, atol=1e-2)


def test_build_tree_learns_threshold_split():
    # y = 1[x0 > 0.37]: a depth-1 tree must find (almost exactly) that split
    rng = np.random.default_rng(1)
    N, B = 4096, 32
    x = rng.uniform(0, 1, N).astype(np.float32)
    y = (x > 0.37).astype(np.float32)
    edges = np.linspace(0, 1, B)[1:-1]
    codes = np.searchsorted(edges, x).astype(np.uint8)[:, None]
    g = (0.5 - y)  # bernoulli grad at margin 0
    h = np.full(N, 0.25, np.float32)
    pad_edges = np.full((1, B - 2), np.inf, np.float32)
    pad_edges[0, : len(edges)] = edges
    tree, leaf_idx, gains, _cov = treelib.build_tree(
        jnp.asarray(codes), jnp.asarray(g), jnp.asarray(h),
        jnp.ones(N, jnp.float32), jnp.ones(1, jnp.float32),
        jnp.asarray(pad_edges), max_depth=2, nbins=B, min_rows=10.0,
    )
    assert bool(tree.is_split[0])
    thr = float(tree.thr[0])
    assert abs(thr - 0.37) < 0.05
    # left leaf value negative margin? left = y=0 rows: g=0.5 → value < 0
    v = np.asarray(tree.value)
    assert v[1] < 0 < v[2]
    assert float(gains[0]) > 0


def test_build_tree_respects_min_rows():
    N, B = 64, 8
    codes = np.zeros((N, 1), np.uint8)
    codes[:2, 0] = 1  # only 2 rows distinguishable
    g = np.ones(N, np.float32)
    g[:2] = -1
    tree, _, _, _ = treelib.build_tree(
        jnp.asarray(codes), jnp.asarray(g), jnp.ones(N, jnp.float32),
        jnp.ones(N, jnp.float32), jnp.ones(1, jnp.float32),
        jnp.full((1, B - 2), jnp.inf, jnp.float32),
        max_depth=1, nbins=B, min_rows=10.0,
    )
    assert not bool(tree.is_split[0])


def test_predict_raw_matches_codes_path():
    rng = np.random.default_rng(2)
    N, Fn, B = 1024, 4, 16
    X = rng.normal(size=(N, Fn)).astype(np.float32)
    from h2o3_tpu.frame.binning import build_bins

    bm = build_bins(X, nbins=B)
    y = (X[:, 0] + X[:, 1] ** 2 > 0.5).astype(np.float32)
    g = 0.5 - y
    h = np.full(N, 0.25, np.float32)
    pad_edges = np.full((Fn, B - 2), np.inf, np.float32)
    for j, e in enumerate(bm.edges):
        pad_edges[j, : len(e)] = e
    tree, leaf_idx, _, _ = treelib.build_tree(
        jnp.asarray(bm.codes), jnp.asarray(g), jnp.asarray(h),
        jnp.ones(N, jnp.float32), jnp.ones(Fn, jnp.float32),
        jnp.asarray(pad_edges), max_depth=4, nbins=B, min_rows=5.0,
    )
    v_codes = np.asarray(treelib.predict_codes(tree, jnp.asarray(bm.codes), 4))
    v_raw = np.asarray(treelib.predict_raw(tree, jnp.asarray(X), 4))
    np.testing.assert_allclose(v_codes, v_raw, rtol=1e-5, atol=1e-6)
    # the returned training leaf idx agrees with traversal
    v_leaf = np.asarray(tree.value)[np.asarray(leaf_idx)]
    np.testing.assert_allclose(v_leaf, v_codes, rtol=1e-5, atol=1e-6)


def test_nan_goes_right():
    N, B = 512, 8
    x = np.linspace(-1, 1, N).astype(np.float32)
    x[::7] = np.nan
    from h2o3_tpu.frame.binning import build_bins

    bm = build_bins(x[:, None], nbins=B)
    assert (bm.codes[::7, 0] == B - 1).all()


def test_pack_roundtrip(cloud1):
    """4/5/6-bit code packing (H2D compression) is bit-exact."""
    import numpy as np

    from h2o3_tpu.models.shared_tree import (_pack_bits_for, _pack_host,
                                             _unpack_device)

    rng = np.random.default_rng(3)
    for bits, nbins in ((4, 16), (5, 32), (6, 64)):
        codes = rng.integers(0, nbins, size=(4096, 7)).astype(np.uint8)
        got = np.asarray(_unpack_device(_pack_host(codes, bits), bits))
        np.testing.assert_array_equal(got, codes)
    assert _pack_bits_for(16, 4096) == 4
    assert _pack_bits_for(21, 4096) == 5
    assert _pack_bits_for(33, 4096) == 6
    assert _pack_bits_for(65, 4096) == 0
    assert _pack_bits_for(21, 4098) == 0  # 4098 % 8 != 0 (and % 4 != 0)


def test_compact_matches_dense(cloud1):
    """Active-node compaction (compact_cap) must reproduce the dense build
    EXACTLY on reachable nodes, and flag overflow instead of truncating
    when the cap is too small."""
    import jax
    import jax.numpy as jnp

    from h2o3_tpu.frame.binning import build_bins
    from h2o3_tpu.models import tree as treelib

    rng = np.random.default_rng(0)
    N, F, B, D = 20000, 8, 16, 8
    X = rng.normal(size=(N, F)).astype(np.float32)
    y = (X[:, 0] + X[:, 1] * X[:, 2] + 0.3 * rng.normal(size=N) > 0
         ).astype(np.float32)
    bm = build_bins(X, nbins=B)
    g = jnp.asarray(0.5 - y)
    h = jnp.full(N, 0.25, jnp.float32)
    edges = np.full((F, B - 2), np.inf, np.float32)
    for j, e in enumerate(bm.edges):
        edges[j, : len(e)] = e
    args = (jnp.asarray(bm.codes), g, h, jnp.ones(N, jnp.float32),
            jnp.ones(F, jnp.float32), jnp.asarray(edges))
    kw = dict(max_depth=D, nbins=B, min_rows=2.0, key=jax.random.PRNGKey(7))
    td, lid, gd, cd = treelib.build_tree(*args, **kw)
    tc, lic, gc, cc, ov = treelib.build_tree(*args, compact_cap=256, **kw)
    assert int(ov) == 0
    iss = np.asarray(td.is_split)
    reach = np.zeros(len(iss), bool)
    reach[0] = True
    for n in range(len(reach) // 2):
        if reach[n] and iss[n]:
            reach[2 * n + 1] = reach[2 * n + 2] = True
    for name in ("feat", "bin", "is_split"):
        a = np.asarray(getattr(td, name))
        b = np.asarray(getattr(tc, name))
        np.testing.assert_array_equal(a[reach], b[reach])
    np.testing.assert_allclose(np.asarray(td.value)[reach],
                               np.asarray(tc.value)[reach],
                               rtol=2e-4, atol=1e-5)
    # per-row scores identical (leaf ids differ in representation only:
    # dense returns deepest-cell ids, compact returns frozen node ids)
    pd_ = np.asarray(treelib.value_at(td.value, lid))
    pc_ = np.asarray(treelib.value_at(tc.value, lic))
    np.testing.assert_allclose(pd_, pc_, rtol=2e-4, atol=1e-5)
    # a cap that is too small must raise the overflow flag
    *_, ov2 = treelib.build_tree(*args, compact_cap=4, **kw)
    assert int(ov2) > 0


def test_compact_with_mtries_rate(cloud1):
    """Traced mtries_rate engages per-node column sampling in both the
    dense and compact phases without recompilation per rate."""
    import jax
    import jax.numpy as jnp

    from h2o3_tpu.frame.binning import build_bins
    from h2o3_tpu.models import tree as treelib

    rng = np.random.default_rng(1)
    N, F, B, D = 5000, 6, 16, 7
    X = rng.normal(size=(N, F)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    bm = build_bins(X, nbins=B)
    edges = np.full((F, B - 2), np.inf, np.float32)
    for j, e in enumerate(bm.edges):
        edges[j, : len(e)] = e
    args = (jnp.asarray(bm.codes), jnp.asarray(0.5 - y),
            jnp.full(N, 0.25, jnp.float32), jnp.ones(N, jnp.float32),
            jnp.ones(F, jnp.float32), jnp.asarray(edges))
    t1, *_ , ov = treelib.build_tree(
        *args, max_depth=D, nbins=B, min_rows=2.0, compact_cap=64,
        mtries_rate=jnp.float32(0.5), key=jax.random.PRNGKey(3))
    assert int(np.asarray(t1.is_split).sum()) > 0
