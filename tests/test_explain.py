"""Model-understanding surface: partial dependence, permutation importance,
staged predictions, feature frequencies, tree inspection.

Mirrors the reference's h2o-py tests for `partial_plot`
(hex/PartialDependence.java), `permutation_varimp`
(hex/PermutationVarImp.java), `staged_predict_proba`, `feature_frequencies`
(hex/tree/SharedTreeModel), and `h2o.tree.H2OTree` (hex/tree/TreeHandler).
"""

import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu.estimators import (
    H2OGradientBoostingEstimator,
    H2OGeneralizedLinearEstimator,
)
from h2o3_tpu.tree_api import H2OTree


def _frame(n=1500, seed=0):
    rng = np.random.default_rng(seed)
    a = rng.normal(size=n)
    b = rng.normal(size=n)
    cat = rng.choice(["x", "y", "z"], size=n)
    shift = np.where(cat == "x", 1.0, np.where(cat == "y", 0.0, -1.0))
    logit = 2.0 * a + shift
    y = (rng.random(n) < 1 / (1 + np.exp(-logit))).astype(int)
    return h2o.H2OFrame_from_python(
        {"a": a, "b": b, "cat": cat, "y": y.astype(str)},
        column_types={"y": "enum", "cat": "enum"},
    )


@pytest.fixture(scope="module")
def gbm_and_frame():
    fr = _frame()
    gbm = H2OGradientBoostingEstimator(ntrees=12, max_depth=4, seed=3)
    gbm.train(x=["a", "b", "cat"], y="y", training_frame=fr)
    return gbm, fr


def test_partial_plot_numeric_monotone(gbm_and_frame):
    gbm, fr = gbm_and_frame
    (pdp,) = gbm.partial_plot(fr, cols=["a"], nbins=10)
    assert pdp.names == ["a", "mean_response", "stddev_response",
                         "std_error_mean_response"]
    vals = np.asarray(pdp.vec("a").data, np.float64)
    mr = np.asarray(pdp.vec("mean_response").data, np.float64)
    assert len(vals) == 10
    # y depends positively on a: pdp must rise end-to-end
    assert mr[-1] > mr[0] + 0.1
    assert ((mr >= 0) & (mr <= 1)).all()


def test_partial_plot_categorical_levels(gbm_and_frame):
    gbm, fr = gbm_and_frame
    (pdp,) = gbm.partial_plot(fr, cols=["cat"])
    assert pdp.vec("cat").domain == ["x", "y", "z"]
    mr = np.asarray(pdp.vec("mean_response").data, np.float64)
    # class-x shifts the logit up, class-z down
    assert mr[0] > mr[2]


def test_partial_plot_ice_row(gbm_and_frame):
    gbm, fr = gbm_and_frame
    (ice,) = gbm.partial_plot(fr, cols=["a"], nbins=5, row_index=3)
    assert ice.nrow == 5
    sd = np.asarray(ice.vec("stddev_response").data, np.float64)
    np.testing.assert_allclose(sd, 0.0)  # single row: no spread


def test_permutation_importance_ranks_signal_feature(gbm_and_frame):
    gbm, fr = gbm_and_frame
    pvi = gbm.permutation_importance(fr, metric="auc", seed=1)
    assert pvi.names == ["Variable", "Relative Importance",
                         "Scaled Importance", "Percentage"]
    top = pvi.vec("Variable").domain[
        int(np.asarray(pvi.vec("Variable").data)[0])]
    assert top == "a"
    pct = np.asarray(pvi.vec("Percentage").data, np.float64)
    np.testing.assert_allclose(pct.sum(), 1.0, atol=1e-9)
    scaled = np.asarray(pvi.vec("Scaled Importance").data, np.float64)
    assert scaled[0] == 1.0


def test_staged_predict_proba_converges_to_predict(gbm_and_frame):
    gbm, fr = gbm_and_frame
    staged = gbm.staged_predict_proba(fr)
    assert len(staged.names) == 12
    final = np.asarray(staged.vec("T12").data, np.float64)
    p1 = np.asarray(gbm.predict(fr).vec("1").data, np.float64)
    np.testing.assert_allclose(final, p1, atol=1e-5)


def test_feature_frequencies(gbm_and_frame):
    gbm, fr = gbm_and_frame
    ff = gbm.feature_frequencies(fr)
    assert ff.names == ["a", "b", "cat"]
    counts = np.column_stack(
        [np.asarray(ff.vec(n).data, np.float64) for n in ff.names])
    assert (counts >= 0).all()
    # 'a' is the dominant signal: used most on average
    assert counts[:, 0].mean() > counts[:, 1].mean()


def test_h2o_tree_inspection(gbm_and_frame):
    gbm, fr = gbm_and_frame
    t = H2OTree(gbm, tree_number=0)
    assert t.root_node_id == 0
    assert len(t) >= 3
    # children indices are consistent and in range
    for i in range(len(t)):
        l, r = t.left_children[i], t.right_children[i]
        assert (l == -1) == (r == -1)
        if l >= 0:
            assert 0 <= l < len(t) and 0 <= r < len(t)
            assert t.features[i] in ("a", "b", "cat")
            assert np.isfinite(t.thresholds[i])
        else:
            assert t.features[i] is None
    # alias: h2o.tree.H2OTree
    assert h2o.tree.H2OTree is H2OTree
    with pytest.raises(ValueError):
        H2OTree(gbm, tree_number=99)


def test_partial_plot_multinomial_requires_targets():
    rng = np.random.default_rng(7)
    n = 400
    a = rng.normal(size=n)
    y = np.digitize(a, [-0.5, 0.5]).astype(str)
    fr = h2o.H2OFrame_from_python(
        {"a": a, "b": rng.normal(size=n), "y": y}, column_types={"y": "enum"})
    gbm = H2OGradientBoostingEstimator(ntrees=3, max_depth=3, seed=1)
    gbm.train(x=["a", "b"], y="y", training_frame=fr)
    with pytest.raises(ValueError, match="targets"):
        gbm.partial_plot(fr, cols=["a"], nbins=5)
    # with targets: one table per target, probabilities in [0, 1]
    t0, t1 = gbm.partial_plot(fr, cols=["a"], nbins=5, targets=["0", "1"])
    for t in (t0, t1):
        mr = np.asarray(t.vec("mean_response").data, np.float64)
        assert ((mr >= 0) & (mr <= 1)).all()
    assert t0.target == "0" and t1.target == "1"


def test_h2o_tree_binomial_negative_class_rejected(gbm_and_frame):
    gbm, fr = gbm_and_frame
    with pytest.raises(ValueError, match="not.*modelled|modelled"):
        H2OTree(gbm, tree_number=0, tree_class="0")
    t = H2OTree(gbm, tree_number=0, tree_class="1")
    assert len(t) >= 3


def test_partial_plot_works_for_glm():
    fr = _frame(800, seed=2)
    glm = H2OGeneralizedLinearEstimator(family="binomial")
    glm.train(x=["a", "b", "cat"], y="y", training_frame=fr)
    (pdp,) = glm.partial_plot(fr, cols=["a"], nbins=8)
    mr = np.asarray(pdp.vec("mean_response").data, np.float64)
    assert mr[-1] > mr[0]
