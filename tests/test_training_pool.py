"""Parallel multi-model training engine (ISSUE 4): dataset-artifact cache
hit/miss/eviction, CV fold reuse vs the H2O3_CV_REBIN=1 seed path,
parallel-grid leaderboard determinism, per-job error isolation, the
`GET /3/Training/metrics` REST surface, and a slow grid-throughput floor."""

import os
import threading
import time

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models import dataset_cache
from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
from h2o3_tpu.models.grid import H2OGridSearch
from h2o3_tpu.runtime import trainpool

from conftest import make_classification


def _cls_frame(n=900, f=5, seed=0):
    X, y = make_classification(n, f, seed)
    return Frame.from_numpy(
        np.column_stack([X, y]), names=[f"x{i}" for i in range(f)] + ["y"]
    ).asfactor("y")


@pytest.fixture(autouse=True)
def _fresh_stats():
    dataset_cache.clear()
    dataset_cache.reset_stats()
    trainpool.reset()
    yield
    dataset_cache.clear()


# -- dataset-artifact cache ---------------------------------------------------
def test_dataset_cache_hit_miss_and_reuse_across_candidates(cloud1):
    fr = _cls_frame(600, 4, seed=1)
    for _ in range(3):   # three candidates sharing (frame, x, nbins, hist)
        m = H2OGradientBoostingEstimator(ntrees=3, max_depth=3, seed=5)
        m.train(y="y", training_frame=fr)
    s = dataset_cache.snapshot()
    assert s["matrix_misses"] == 1 and s["matrix_hits"] == 2
    assert s["bins_misses"] == 1 and s["bins_hits"] == 2
    assert s["device_misses"] == 1 and s["device_hits"] == 2
    assert s["entries"] == 1 and s["bytes"] > 0


def test_dataset_cache_key_isolation_and_mutation_invalidates(cloud1):
    fr = _cls_frame(500, 4, seed=2)
    H2OGradientBoostingEstimator(ntrees=2, max_depth=2, seed=1) \
        .train(y="y", training_frame=fr)
    # different nbins → new bins layer, same matrix layer
    H2OGradientBoostingEstimator(ntrees=2, max_depth=2, nbins=12, seed=1) \
        .train(y="y", training_frame=fr)
    s = dataset_cache.snapshot()
    assert s["matrix_hits"] == 1 and s["bins_misses"] == 2
    # in-place mutation bumps Frame._version → full re-fingerprint
    fr["x0"] = fr.vec("x0").numeric_np() * 2.0
    H2OGradientBoostingEstimator(ntrees=2, max_depth=2, seed=1) \
        .train(y="y", training_frame=fr)
    assert dataset_cache.snapshot()["matrix_misses"] == 2


def test_dataset_cache_eviction_and_disable(cloud1, monkeypatch):
    monkeypatch.setenv("H2O3_DATASET_CACHE_ENTRIES", "1")
    frames = [_cls_frame(400, 4, seed=s) for s in (3, 4)]
    for fr in frames:
        H2OGradientBoostingEstimator(ntrees=2, max_depth=2, seed=1) \
            .train(y="y", training_frame=fr)
    s = dataset_cache.snapshot()
    assert s["evictions"] >= 1 and s["entries"] == 1
    monkeypatch.setenv("H2O3_DATASET_CACHE", "0")
    assert dataset_cache.enabled() is False
    dataset_cache.reset_stats()
    H2OGradientBoostingEstimator(ntrees=2, max_depth=2, seed=1) \
        .train(y="y", training_frame=frames[0])
    s = dataset_cache.snapshot()   # disabled: no layer is consulted
    assert s["matrix_hits"] == s["matrix_misses"] == 0


# -- CV fold reuse -------------------------------------------------------------
def test_cv_reuse_metric_parity_with_rebin(cloud1, monkeypatch):
    """Fold reuse slices the parent's binned codes (fold-local bin edges
    differ from the seed per-fold re-bin) — the xval metrics must agree
    within a pinned tolerance, and H2O3_CV_REBIN=1 must actually flip the
    path (trainpool fold counters prove which ran)."""
    fr = _cls_frame(1000, 5, seed=6)

    def run():
        g = H2OGradientBoostingEstimator(ntrees=10, max_depth=3, nfolds=3,
                                         seed=11)
        g.train(y="y", training_frame=fr)
        return g

    reuse = run()
    assert trainpool.snapshot()["cv"] == dict(reuse_folds=3, rebin_folds=0)
    trainpool.reset()
    monkeypatch.setenv("H2O3_CV_REBIN", "1")
    rebin = run()
    assert trainpool.snapshot()["cv"] == dict(reuse_folds=0, rebin_folds=3)
    for metric in ("auc", "logloss"):
        a = float(getattr(reuse, metric)(xval=True))
        b = float(getattr(rebin, metric)(xval=True))
        assert abs(a - b) < 0.03, (metric, a, b)
    # holdout prediction vectors stay close row-by-row, not just on average
    d = np.abs(reuse.model._cv_holdout_pred - rebin.model._cv_holdout_pred)
    assert float(np.mean(d)) < 0.05


def test_cv_rebin_is_deterministic_seed_path(cloud1, monkeypatch):
    """parallelism=1 + H2O3_CV_REBIN=1 is the bit-exact seed path: two runs
    (one with the artifact cache live, one fully legacy) agree exactly."""
    fr = _cls_frame(700, 4, seed=7)
    monkeypatch.setenv("H2O3_CV_REBIN", "1")

    def run():
        g = H2OGradientBoostingEstimator(ntrees=6, max_depth=3, nfolds=3,
                                         seed=3)
        g.train(y="y", training_frame=fr)
        return g

    a = run()
    monkeypatch.setenv("H2O3_TRAIN_LEGACY", "1")
    b = run()
    assert float(a.auc(xval=True)) == float(b.auc(xval=True))
    np.testing.assert_array_equal(a.model._cv_holdout_pred,
                                  b.model._cv_holdout_pred)


def test_cv_reuse_respects_fold_column_and_weights(cloud1):
    """Reuse keeps *_column parameters working: the slim fold frame carries
    the weights column, and fold_column-driven CV reuses codes too."""
    X, y = make_classification(800, 4, seed=9)
    w = np.where(y == 1, 2.0, 1.0)
    foldc = np.arange(800) % 3
    fr = Frame.from_numpy(
        np.column_stack([X, y, w, foldc]),
        names=["a", "b", "c", "d", "y", "w", "fold"]).asfactor("y")
    g = H2OGradientBoostingEstimator(ntrees=5, max_depth=3, seed=2,
                                     weights_column="w", fold_column="fold")
    g.train(y="y", training_frame=fr, x=["a", "b", "c", "d"])
    assert g.model.cross_validation_metrics is not None
    assert trainpool.snapshot()["cv"]["reuse_folds"] == 3


# -- grid scheduler -------------------------------------------------------------
def _grid(fr, parallelism, **crit):
    g = H2OGridSearch(
        H2OGradientBoostingEstimator(ntrees=5, nfolds=2, seed=13),
        {"max_depth": [2, 3], "learn_rate": [0.1, 0.3]},
        parallelism=parallelism, search_criteria=crit or None)
    g.train(y="y", training_frame=fr)
    return g


def test_grid_parallel_leaderboard_identical_to_sequential(cloud1):
    fr = _cls_frame(700, 5, seed=21)
    seq = _grid(fr, 1).get_grid(sort_by="auc")
    par = _grid(fr, 4).get_grid(sort_by="auc")
    assert len(seq) == len(par) == 4
    lb_seq = [(m._grid_combo, float(m.auc(xval=True))) for m in seq.models]
    lb_par = [(m._grid_combo, float(m.auc(xval=True))) for m in par.models]
    assert lb_seq == lb_par   # same order AND bit-identical metrics
    assert trainpool.snapshot()["last_pool"]["parallelism"] == 4


def test_grid_per_job_error_isolation(cloud1):
    fr = _cls_frame(500, 4, seed=22)
    g = H2OGridSearch(
        H2OGradientBoostingEstimator(ntrees=4, seed=1),
        {"max_depth": [3, -1], "learn_rate": [0.2]},   # -1 → ValueError
        parallelism=2)
    g.train(y="y", training_frame=fr)
    assert len(g.models) == 1
    assert len(g.failed) == 1
    assert g.failed[0]["params"]["max_depth"] == -1
    assert "max_depth" in g.failed[0]["error"]


def test_grid_parent_job_cancel_skips_candidates(cloud1):
    from h2o3_tpu.models.model_base import Job

    fr = _cls_frame(500, 4, seed=23)
    g = H2OGridSearch(H2OGradientBoostingEstimator(ntrees=4, seed=1),
                      {"max_depth": [2, 3, 4]}, parallelism=1)
    job = Job(dest="grid_job", description="grid").start()
    job.cancel()
    g._external_job = job
    g.train(y="y", training_frame=fr)
    assert g.models == [] and g.failed == []
    snap = trainpool.snapshot()
    assert snap["totals"]["cancelled"] == 3


def test_trainpool_occupancy_and_error_records():
    def ok(job):
        time.sleep(0.01)
        return "fine"

    def boom(job):
        raise RuntimeError("candidate exploded")

    recs = trainpool.TrainPool(2, label="unit").run(
        [("a", ok), ("b", boom), ("c", ok)])
    assert [r.status for r in recs] == ["done", "failed", "done"]
    assert recs[1].error == "candidate exploded"
    snap = trainpool.snapshot()
    assert snap["totals"]["completed"] == 2
    assert snap["totals"]["failed"] == 1
    assert snap["last_pool"]["n_jobs"] == 3
    assert 0.0 < snap["last_pool"]["occupancy"] <= 1.0
    names = [c["name"] for c in snap["candidates"]]
    assert set(names) == {"a", "b", "c"}


def test_automl_parallel_smoke(cloud1):
    from h2o3_tpu.automl import H2OAutoML

    fr = _cls_frame(600, 4, seed=25)
    aml = H2OAutoML(max_models=2, seed=1, nfolds=2, parallelism=2,
                    include_algos=["GBM"])
    aml.train(y="y", training_frame=fr)
    assert len(aml._models) == 2
    assert aml.leader is not None


# -- REST surface ----------------------------------------------------------------
def test_training_metrics_rest_surface(cloud1):
    import json
    import urllib.request

    from h2o3_tpu.rest import start_server

    fr = _cls_frame(500, 4, seed=30)
    _grid(fr, 2)
    srv = start_server(port=0)
    try:
        def get(path):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}{path}") as r:
                return json.loads(r.read())

        body = get("/3/Training/metrics")
        assert body["__meta"]["schema_type"] == "TrainingMetricsV3"
        assert body["active"] is True
        assert body["totals"]["completed"] >= 4
        assert body["cache"]["bins_hits"] >= 1
        assert body["cv"]["reuse_folds"] >= 8
        assert body["last_pool"]["occupancy"] > 0
        assert body["candidates"] and "wall_s" in body["candidates"][0]
        schema = get("/3/Training/metrics?schema=1")
        assert schema["name"] == "TrainingMetricsV3"
        assert any(f["name"] == "cache" for f in schema["fields"])
        prof = get("/3/Profiler")
        assert "training" in prof and prof["training"]["active"] is True
    finally:
        srv.stop()


# -- throughput floor (slow lane) -------------------------------------------------
@pytest.mark.slow
def test_grid_throughput_floor_vs_seed(cloud1):
    """The pooled path (artifact cache + CV reuse + parallelism) must beat
    the sequential seed walk on a small GBM grid with CV. Conservative
    floor for noisy CI hosts; the bench artifact (BENCH_CONFIG=grid) pins
    the ≥2× acceptance on a quiet 2-core run."""
    if (os.cpu_count() or 1) < 2:
        pytest.skip("needs ≥2 cores for overlap")
    fr = _cls_frame(4000, 8, seed=40)

    def run(par, legacy):
        prior = os.environ.get("H2O3_TRAIN_LEGACY")
        if legacy:
            os.environ["H2O3_TRAIN_LEGACY"] = "1"
        else:
            os.environ.pop("H2O3_TRAIN_LEGACY", None)
        try:
            dataset_cache.clear()
            g = H2OGridSearch(
                H2OGradientBoostingEstimator(ntrees=10, nfolds=3, seed=42),
                {"max_depth": [3, 4], "learn_rate": [0.1, 0.2]},
                parallelism=par)
            t0 = time.perf_counter()
            g.train(y="y", training_frame=fr)
            wall = time.perf_counter() - t0
            assert len(g.models) == 4, g.failed
            return wall
        finally:
            if prior is None:
                os.environ.pop("H2O3_TRAIN_LEGACY", None)
            else:
                os.environ["H2O3_TRAIN_LEGACY"] = prior

    run(min(os.cpu_count() or 1, 4), legacy=False)   # warm compile caches
    wall_new = run(min(os.cpu_count() or 1, 4), legacy=False)
    wall_seed = run(1, legacy=True)
    speedup = wall_seed / wall_new
    assert speedup > 1.3, f"pooled grid only {speedup:.2f}x vs seed walk"
