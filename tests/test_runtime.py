"""Runtime aux subsystems: Log, Timeline, DKV, Persist, profiler
(reference: water/util/Log, water/TimeLine, water/DKV, water/persist,
water/api/ProfilerHandler)."""

import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.runtime import profiler
from h2o3_tpu.runtime.dkv import DKV
from h2o3_tpu.runtime.log import Log
from h2o3_tpu.runtime.persist import for_uri
from h2o3_tpu.runtime.timeline import Timeline


def test_log_ring_and_levels(tmp_path):
    Log.clear()
    Log.set_log_dir(str(tmp_path))
    Log.info("hello world")
    Log.warn("watch out")
    Log.debug("dropped at INFO level")
    lines = Log.get_logs()
    assert any("hello world" in l and "INFO" in l for l in lines)
    assert any("watch out" in l and "WARN" in l for l in lines)
    assert not any("dropped at INFO" in l for l in lines)
    # file sink received the same lines
    files = list(tmp_path.glob("h2o3tpu_*.log"))
    assert files and "hello world" in files[0].read_text()
    Log.set_log_dir(None)
    with pytest.raises(ValueError):
        Log.set_level("NOPE")


def test_timeline_ring():
    Timeline.clear()
    for i in range(5):
        Timeline.record("compile", f"program_{i}", dur=i)
    evs = Timeline.snapshot()
    assert len(evs) == 5
    assert evs[-1]["detail"] == "program_4"
    assert evs[0]["ts"] <= evs[-1]["ts"]


def test_dkv_lifecycle():
    DKV.put("k1", Frame.from_dict({"a": np.arange(3.0)}))
    assert isinstance(DKV.get("k1"), Frame)
    assert "k1" in DKV.keys(Frame)
    DKV.remove("k1")
    assert DKV.get("k1") is None


def test_persist_spi(tmp_path):
    f = tmp_path / "x.csv"
    f.write_text("a,b\n1,2\n")
    p = for_uri(str(f))
    assert p.exists(str(f))
    assert p.size(str(f)) > 0
    with p.open(f"file://{f}") as fh:
        assert fh.read().startswith(b"a,b")
    # glob listing
    assert p.list(str(tmp_path / "*.csv")) == [str(f)]
    # cloud schemes are real pyarrow.fs backends now; in this egress-less
    # environment first use surfaces a connectivity/credential error
    # (NOT NotImplementedError — the backend exists)
    s3 = for_uri("s3://bucket/key")
    with pytest.raises((OSError, RuntimeError)):
        s3.open("s3://bucket/key")
    with pytest.raises(ValueError):
        for_uri("weird://x")


def test_profiler_samples():
    samples = profiler.stack_samples()
    assert any("MainThread" in s["thread"] for s in samples)
    prof = profiler.profile(nsamples=2, interval=0.0)
    assert prof and all(p["count"] >= 1 for p in prof)


def test_http_persist_import(tmp_path, cloud1):
    """h2o-persist-http: import_file over a loopback HTTP server."""
    import http.server
    import threading

    d = tmp_path / "serve"
    d.mkdir()
    (d / "data.csv").write_text("a,b\n1,2\n3,4\n")

    handler = lambda *a, **k: http.server.SimpleHTTPRequestHandler(
        *a, directory=str(d), **k)
    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), handler)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        url = f"http://127.0.0.1:{httpd.server_address[1]}/data.csv"
        from h2o3_tpu.runtime import persist as P

        assert P.for_uri(url).exists(url)
        assert P.for_uri(url).size(url) > 0
        fr = h2o.import_file(url)
        assert fr.key == "data.csv"
        assert fr.vec("a").numeric_np().tolist() == [1.0, 3.0]
    finally:
        httpd.shutdown()


def test_cloud_scheme_backends_registered(cloud1):
    from h2o3_tpu.runtime import persist as P

    for scheme in ("s3", "gs", "hdfs"):
        b = P.for_uri(f"{scheme}://bucket/key")
        assert b.scheme == scheme
    with pytest.raises(ValueError):
        P.for_uri("ftp://x/y")


def test_dkv_stats_and_timeline_phases(cloud1):
    """VERDICT r01 weak #8: DKV size accounting + timeline depth."""
    import numpy as np

    import h2o3_tpu as h2o
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
    from h2o3_tpu.runtime.dkv import DKV
    from h2o3_tpu.runtime.timeline import Timeline

    rng = np.random.default_rng(0)
    X = rng.normal(size=(500, 4))
    y = (X[:, 0] > 0).astype(int)
    fr = h2o.H2OFrame_from_python(
        {**{f"c{i}": X[:, i] for i in range(4)}, "y": y.astype(str)},
        column_types={"y": "enum"})
    st = DKV.stats()
    assert st["entries"] >= 1
    assert st["by_kind"]["Frame"]["bytes"] >= 500 * 4 * 4  # 4 f32 cols min
    Timeline.clear()
    m = H2OGradientBoostingEstimator(ntrees=3, max_depth=3)
    m.train(x=[f"c{i}" for i in range(4)], y="y", training_frame=fr)
    phases = [e["detail"] for e in Timeline.snapshot() if e["kind"] == "train_phase"]
    # the training driver's cost structure is visible after the fact
    for expected in ("build_bins", "device_put", "training_metrics"):
        assert expected in phases, phases


def test_phases_accounting_and_mark_mapping():
    """runtime.phases: byte/second accumulation, mark→bucket mapping, and
    compile-time subtraction in accounted_h2d."""
    import jax.numpy as jnp

    from h2o3_tpu.runtime import phases

    phases.reset()
    phases.add("h2d", 0.5, 1000)
    phases.add("h2d", 0.25, 24)
    phases.add_mark("device_put", 0.1)          # → h2d bucket
    phases.add_mark("chunk_3_2trees", 0.2)      # → compute
    phases.add_mark("frame_to_matrix", 0.05)    # → host_prep
    phases.add_mark("margins_D2H", 0.01)        # → d2h
    snap = phases.snapshot()
    assert snap["bytes_h2d"] == 1024
    assert snap["h2d_s"] == pytest.approx(0.85, abs=1e-6)
    assert snap["compute_s"] == pytest.approx(0.2)
    assert snap["host_prep_s"] == pytest.approx(0.05)
    assert snap["d2h_s"] == pytest.approx(0.01)
    assert phases.totals(("h2d", "compute")) == pytest.approx(1.05)
    phases.reset()
    assert phases.snapshot() == {}

    # accounted_h2d: runs the thunk, books bytes; result passes through
    out = phases.accounted_h2d(lambda: jnp.arange(8), 32)
    assert int(out[3]) == 3
    assert phases.snapshot()["bytes_h2d"] == 32
    phases.reset()
