"""XGBoost / Grid / StackedEnsemble / AutoML tests — the `testdir_algos`
+ automl suites analog."""

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
from h2o3_tpu.models.glm import H2OGeneralizedLinearEstimator
from h2o3_tpu.models.drf import H2ORandomForestEstimator
from h2o3_tpu.models.xgboost import H2OXGBoostEstimator
from h2o3_tpu.models.grid import H2OGridSearch
from h2o3_tpu.models.ensemble import H2OStackedEnsembleEstimator
from h2o3_tpu.automl import H2OAutoML

from conftest import make_classification


def _cls_frame(n=1200, f=6, seed=0):
    X, y = make_classification(n, f, seed)
    return Frame.from_numpy(
        np.column_stack([X, y]), names=[f"x{i}" for i in range(f)] + ["y"]
    ).asfactor("y")


def test_xgboost_binomial(cloud1):
    fr = _cls_frame(2000, 8, seed=1)
    xgb = H2OXGBoostEstimator(ntrees=30, max_depth=5, eta=0.3, reg_lambda=1.0,
                              subsample=0.8, colsample_bytree=0.8, seed=2)
    xgb.train(y="y", training_frame=fr)
    assert xgb.auc() > 0.9
    p = xgb.predict(fr).vec("1").numeric_np()
    assert ((p >= 0) & (p <= 1)).all()


def test_xgboost_lambdarank_ndcg(cloud1):
    rng = np.random.default_rng(3)
    nq, per_q = 60, 20
    rows = nq * per_q
    X = rng.normal(size=(rows, 5))
    # relevance driven by two features
    rel_score = X[:, 0] + 0.5 * X[:, 1] + 0.3 * rng.normal(size=rows)
    rel = np.digitize(rel_score, np.quantile(rel_score, [0.5, 0.75, 0.9])).astype(float)
    qid = np.repeat(np.arange(nq), per_q)
    fr = Frame.from_dict({
        **{f"f{i}": X[:, i] for i in range(5)},
        "qid": qid.astype(float), "rel": rel,
    })
    xgb = H2OXGBoostEstimator(ntrees=20, max_depth=4, eta=0.3, seed=4,
                              objective="rank:ndcg", group_column="qid")
    xgb.train(y="rel", training_frame=fr,
              x=[f"f{i}" for i in range(5)])
    ndcg = xgb.ndcg(fr)
    # random ordering gives much lower ndcg; learned model should be high
    assert ndcg > 0.8


def test_grid_search_cartesian(cloud1):
    fr = _cls_frame(900, 5, seed=5)
    grid = H2OGridSearch(
        H2OGradientBoostingEstimator,
        hyper_params={"max_depth": [2, 4], "learn_rate": [0.1, 0.3]},
    )
    grid.train(y="y", training_frame=fr, x=[f"x{i}" for i in range(5)])
    assert len(grid) == 4
    grid.get_grid(sort_by="auc", decreasing=True)
    aucs = [m.auc() for m in grid]
    assert aucs == sorted(aucs, reverse=True)


def test_grid_search_random_discrete(cloud1):
    fr = _cls_frame(800, 5, seed=6)
    grid = H2OGridSearch(
        H2OGradientBoostingEstimator(ntrees=5, seed=1),
        hyper_params={"max_depth": [2, 3, 4, 5], "learn_rate": [0.05, 0.1, 0.2, 0.3]},
        search_criteria={"strategy": "RandomDiscrete", "max_models": 5, "seed": 7},
    )
    grid.train(y="y", training_frame=fr)
    assert len(grid) == 5


def test_stacked_ensemble(cloud1):
    fr = _cls_frame(900, 5, seed=8)
    common = dict(nfolds=2, keep_cross_validation_predictions=True, seed=9)
    gbm = H2OGradientBoostingEstimator(ntrees=15, max_depth=3, **common)
    gbm.train(y="y", training_frame=fr)
    drf = H2ORandomForestEstimator(ntrees=15, max_depth=8, **common)
    drf.train(y="y", training_frame=fr)
    glm = H2OGeneralizedLinearEstimator(family="binomial", lambda_=0.0, **common)
    glm.train(y="y", training_frame=fr)
    se = H2OStackedEnsembleEstimator(base_models=[gbm, drf, glm])
    se.train(y="y", training_frame=fr)
    best_base = max(gbm.auc(xval=True), drf.auc(xval=True), glm.auc(xval=True))
    assert se.auc() > best_base - 0.03  # ensemble ≥ roughly best base
    pred = se.predict(fr)
    assert pred.names == ["predict", "0", "1"]


def test_automl_leaderboard(cloud1):
    fr = _cls_frame(500, 4, seed=10)
    aml = H2OAutoML(max_models=4, max_runtime_secs=600, seed=11, nfolds=2,
                    exclude_algos=["DeepLearning"])
    aml.train(y="y", training_frame=fr)
    lb = aml.leaderboard
    assert len(lb) >= 4  # 4 base + ensembles
    assert aml.leader is not None
    # leaderboard sorted by AUC desc
    aucs = [r["auc"] for r in lb.rows if not np.isnan(r["auc"])]
    assert aucs == sorted(aucs, reverse=True)
    pred = aml.predict(fr)
    assert pred.nrow == fr.nrow
    algos = {r["algo"] for r in lb.rows}
    assert "stackedensemble" in algos


def test_xgboost_reg_alpha_shrinks_leaves(cloud1):
    fr = _cls_frame(1000, 5, seed=12)
    plain = H2OXGBoostEstimator(ntrees=5, max_depth=3, eta=0.3, seed=13)
    plain.train(y="y", training_frame=fr)
    strong = H2OXGBoostEstimator(ntrees=5, max_depth=3, eta=0.3, seed=13,
                                 reg_alpha=50.0)
    strong.train(y="y", training_frame=fr)
    v0 = float(np.abs(np.asarray(plain.model.forest[0].value)).sum())
    v1 = float(np.abs(np.asarray(strong.model.forest[0].value)).sum())
    assert v1 < v0  # L1 soft-threshold shrinks leaf outputs


def test_leaderboard_frame_and_best_model(cloud1):
    import numpy as np
    from h2o3_tpu.automl.automl import H2OAutoML
    from h2o3_tpu.frame.frame import Frame

    rng = np.random.default_rng(0)
    n = 600
    X = rng.normal(size=(n, 4))
    y = (X[:, 0] + X[:, 1] > 0).astype(int)
    fr = Frame.from_numpy(np.column_stack([X, y]),
                          names=["a", "b", "c", "d", "y"]).asfactor("y")
    aml = H2OAutoML(max_models=2, max_runtime_secs=120, nfolds=2, seed=1,
                    include_algos=["GBM", "GLM"])
    aml.train(y="y", training_frame=fr)
    lb = aml.leaderboard.as_frame()
    assert lb.nrow >= 2 and "auc" in lb.names
    best_glm = aml.get_best_model(algorithm="glm")
    assert best_glm is not None and best_glm.algo == "glm"
    assert aml.get_best_model() is aml.leaderboard[0]["_est"]


def test_se_level_one_cache_invalidation(cloud1):
    """The SE level-one cache must refresh when the frame mutates in
    place (keyed on the frame version counter)."""
    import numpy as np

    import h2o3_tpu as h2o
    from h2o3_tpu.models.ensemble import H2OStackedEnsembleEstimator
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

    rng = np.random.default_rng(4)
    X = rng.normal(size=(600, 4))
    y = (X[:, 0] + X[:, 1] > 0).astype(int)
    fr = h2o.H2OFrame_from_python(
        {**{f"c{i}": X[:, i] for i in range(4)}, "y": y.astype(str)},
        column_types={"y": "enum"})
    bases = []
    for depth in (2, 3):
        g = H2OGradientBoostingEstimator(
            ntrees=5, max_depth=depth, nfolds=2,
            keep_cross_validation_predictions=True, seed=1)
        g.train(x=[f"c{i}" for i in range(4)], y="y", training_frame=fr)
        bases.append(g)
    se = H2OStackedEnsembleEstimator(base_models=bases)
    se.train(x=[f"c{i}" for i in range(4)], y="y", training_frame=fr)
    p1 = se.predict(fr).as_data_frame()["1"].to_numpy()
    p1b = se.predict(fr).as_data_frame()["1"].to_numpy()  # cache hit
    np.testing.assert_array_equal(p1, p1b)
    fr["c0"] = np.zeros(600)  # in-place mutation bumps the version
    p2 = se.predict(fr).as_data_frame()["1"].to_numpy()
    assert not np.allclose(p1, p2)  # stale cache would return p1
