"""H2OFrame munging surface: impute/scale/sort/cut/string ops
(reference: water/rapids/ast/prims — AstImpute, AstScale, AstSort, AstCut,
string/*)."""

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.vec import Vec


def _sf(rows):
    return Frame({"s": Vec(None, "string",
                           strings=np.asarray(rows, dtype=object))})


def test_impute_mean_median_mode(cloud1):
    fr = Frame.from_dict({
        "a": np.asarray([1.0, np.nan, 3.0]),
        "b": np.asarray(["x", "y", "x"], dtype=object),
    }, column_types={"b": "enum"})
    codes = np.asarray(fr.vec("b").data).copy()
    codes[1] = -1
    fr._vecs["b"] = Vec(codes, "enum", domain=fr.vec("b").domain)
    fr.impute()
    assert fr.vec("a").numeric_np()[1] == pytest.approx(2.0)
    assert np.asarray(fr.vec("b").data).tolist() == [0, 0, 0]  # mode = 'x'
    fr2 = Frame.from_dict({"a": np.asarray([1.0, np.nan, 2.0, 10.0])})
    fr2.impute(method="median")
    assert fr2.vec("a").numeric_np()[1] == pytest.approx(2.0)


def test_scale_sort_na_omit_unique_head_tail(cloud1):
    fr = Frame.from_dict({"a": np.asarray([3.0, 1.0, 2.0, np.nan]),
                          "b": np.asarray([1.0, 2.0, 2.0, 4.0])})
    s = fr.scale()
    col = s.vec("a").numeric_np()
    assert abs(np.nanmean(col)) < 1e-6
    srt = fr.sort("a")
    assert srt.vec("a").numeric_np()[0] == 1.0
    srt2 = fr.sort(["b", "a"], ascending=[False, True])
    assert srt2.vec("b").numeric_np()[0] == 4.0
    no_na = fr.na_omit()
    assert no_na.nrow == 3
    u = fr[["b"]].unique()
    assert sorted(u.vec("b").numeric_np().tolist()) == [1.0, 2.0, 4.0]
    assert fr.head(2).nrow == 2 and fr.tail(1).vec("b").numeric_np()[0] == 4.0


def test_cor(cloud1):
    rng = np.random.default_rng(0)
    a = rng.normal(size=200)
    fr = Frame.from_dict({"a": a, "b": 2 * a + rng.normal(0, 0.01, 200)})
    c = fr.cor()
    assert c[0, 1] > 0.99


def test_cut(cloud1):
    fr = Frame.from_dict({"a": np.asarray([0.5, 1.5, 2.5, 5.0])})
    out = fr.cut([0, 1, 2, 3])
    v = out.vec("a")
    assert v.type == "enum"
    assert np.asarray(v.data).tolist() == [0, 1, 2, -1]  # 5.0 out of range


def test_string_ops(cloud1):
    fr = _sf([" Hello World ", "foo,bar", None])
    assert list(fr.trim().vec("s").to_numpy())[0] == "Hello World"
    assert list(fr.tolower().vec("s").to_numpy())[0] == " hello world "
    assert list(fr.gsub("o", "0").vec("s").to_numpy())[0] == " Hell0 W0rld "
    assert list(fr.sub("o", "0").vec("s").to_numpy())[0] == " Hell0 World "
    assert list(fr.substring(1, 6).vec("s").to_numpy())[0] == "Hello"
    nc = fr.nchar().vec("s").numeric_np()
    assert nc[0] == 13.0 and np.isnan(nc[2])
    cm = fr.countmatches("o").vec("s").numeric_np()
    assert cm[0] == 2.0 and cm[1] == 2.0
    sp = _sf(["a,b", "c"]).strsplit(",")
    assert list(sp.vec("C1").to_numpy()) == ["a", "c"]
    assert list(sp.vec("C2").to_numpy()) == ["b", None]
    # enum columns map through their domain
    ef = Frame.from_dict({"e": np.asarray(["Cat", "Dog"], dtype=object)},
                         column_types={"e": "enum"})
    assert ef.toupper().vec("e").domain == ["CAT", "DOG"]


def test_export_checkpoints_dir(tmp_path, cloud1):
    import os
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

    rng = np.random.default_rng(0)
    fr = Frame.from_dict({"a": rng.normal(size=200),
                          "y": rng.normal(size=200)})
    g = H2OGradientBoostingEstimator(ntrees=3, max_depth=2,
                                     export_checkpoints_dir=str(tmp_path))
    g.train(x=["a"], y="y", training_frame=fr)
    assert any(f.endswith(".h2o3") for f in os.listdir(tmp_path))


def test_grid_recovery_resume(tmp_path, cloud1):
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
    from h2o3_tpu.models.grid import H2OGridSearch

    rng = np.random.default_rng(1)
    fr = Frame.from_dict({"a": rng.normal(size=300),
                          "y": rng.normal(size=300)})
    hp = {"max_depth": [2, 3], "learn_rate": [0.1, 0.3]}
    g = H2OGridSearch(H2OGradientBoostingEstimator(ntrees=3), hp,
                      grid_id="g1", recovery_dir=str(tmp_path))
    g.train(x=["a"], y="y", training_frame=fr)
    assert len(g.models) == 4
    # resume: all 4 combos already done -> models restored, no retraining
    g2 = H2OGridSearch.load(str(tmp_path), "g1")
    assert len(g2._done_combos) == 4
    assert len(g2.models) == 4  # leaderboard complete from artifacts
    # recovered models score and expose persisted metrics
    p = g2.models[0].predict(fr)
    assert p.nrow == fr.nrow
    assert np.isfinite(g2.models[0].rmse())
    n_before = len(g2.models)
    g2.train(x=["a"], y="y", training_frame=fr)
    assert len(g2.models) == n_before  # nothing left to do
    # partial recovery: drop two combos from the state, resume builds them
    g2._done_combos = g2._done_combos[:2]
    g2.models = g2.models[:2]
    g2.train(x=["a"], y="y", training_frame=fr)
    assert len(g2.models) == 4


def test_impute_by_group_and_mode(cloud1):
    fr = Frame.from_dict({
        "g": np.asarray([0.0, 0.0, 1.0, 1.0]),
        "a": np.asarray([1.0, np.nan, 10.0, np.nan]),
    })
    fr.impute("a", method="mean", by="g")
    assert fr.vec("a").numeric_np().tolist() == [1.0, 1.0, 10.0, 10.0]
    fr2 = Frame.from_dict({"a": np.asarray([5.0, 5.0, 7.0, np.nan])})
    fr2.impute("a", method="mode")
    assert fr2.vec("a").numeric_np()[3] == 5.0
    with pytest.raises(ValueError):
        fr2.impute("a", method="bogus")


def test_target_encoder(cloud1):
    from h2o3_tpu.models.targetencoder import H2OTargetEncoderEstimator

    rng = np.random.default_rng(0)
    n = 1000
    lv = rng.integers(0, 3, n)
    y = (rng.uniform(size=n) < [0.2, 0.5, 0.8][0] * 0 + np.asarray([0.2, 0.5, 0.8])[lv]).astype(int)
    fr = Frame.from_dict({
        "c": np.asarray(["a", "b", "d"], dtype=object)[lv],
        "y": np.asarray(["no", "yes"], dtype=object)[y],
    }, column_types={"c": "enum", "y": "enum"})
    te = H2OTargetEncoderEstimator(columns=["c"], noise=0.0)
    te.train(x=["c"], y="y", training_frame=fr)
    out = te.transform(fr)
    enc = out.vec("c_te").numeric_np()
    # per-level encodings approximate the level response rates
    for code, rate in [(0, 0.2), (1, 0.5), (2, 0.8)]:
        got = enc[lv == code][0]
        assert abs(got - rate) < 0.08
    # blending pulls rare levels toward the prior
    te2 = H2OTargetEncoderEstimator(columns=["c"], blending=True,
                                    inflection_point=10000, smoothing=20, noise=0.0)
    te2.train(x=["c"], y="y", training_frame=fr)
    enc2 = te2.transform(fr).vec("c_te").numeric_np()
    prior = te2.model.prior
    assert np.all(np.abs(enc2 - prior) < np.abs(enc - prior) + 1e-12)
    # LOO excludes the row's own target
    te3 = H2OTargetEncoderEstimator(columns=["c"],
                                    data_leakage_handling="LeaveOneOut", noise=0.0)
    te3.train(x=["c"], y="y", training_frame=fr)
    loo = te3.transform(fr, as_training=True).vec("c_te").numeric_np()
    assert not np.allclose(loo, enc)
    # KFold: out-of-fold encodings differ across folds
    te4 = H2OTargetEncoderEstimator(columns=["c"],
                                    data_leakage_handling="KFold", noise=0.0)
    te4.train(x=["c"], y="y", training_frame=fr)
    kf = te4.transform(fr, as_training=True).vec("c_te").numeric_np()
    assert len(np.unique(np.round(kf[lv == 0], 6))) > 1


def test_time_ops_and_hist(cloud1):
    # 2020-03-15 13:45:30 UTC = 1584279930000 ms
    ms = 1584279930000.0
    fr = Frame.from_dict({"t": np.asarray([ms, np.nan])})
    assert fr.year().vec("t").numeric_np()[0] == 2020
    assert fr.month().vec("t").numeric_np()[0] == 3
    assert fr.day().vec("t").numeric_np()[0] == 15
    assert fr.hour().vec("t").numeric_np()[0] == 13
    assert fr.minute().vec("t").numeric_np()[0] == 45
    assert fr.second().vec("t").numeric_np()[0] == 30
    assert fr.dayOfWeek().vec("t").numeric_np()[0] == 6  # Sunday, Mon=0
    assert np.isnan(fr.year().vec("t").numeric_np()[1])
    h = Frame.from_dict({"a": np.r_[np.zeros(10), np.ones(30)]}).hist(breaks=2)
    assert h.vec("counts").numeric_np().tolist() == [10.0, 30.0]


def test_gains_lift_and_roc(cloud1):
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

    rng = np.random.default_rng(2)
    n = 2000
    X = rng.normal(size=(n, 3))
    y = (X[:, 0] > 0).astype(int)
    fr = Frame.from_dict({
        "a": X[:, 0], "b": X[:, 1], "c": X[:, 2],
        "y": np.asarray(["n", "p"], dtype=object)[y]}, column_types={"y": "enum"})
    m = H2OGradientBoostingEstimator(ntrees=10, max_depth=3)
    m.train(x=["a", "b", "c"], y="y", training_frame=fr)
    gl = m.model.gains_lift()
    assert gl and len(gl) >= 10
    # top group captures far above average; cumulative capture ends at 1
    assert gl[0]["lift"] > 1.5
    assert gl[-1]["cumulative_capture_rate"] == pytest.approx(1.0)
    fpr, tpr = m.model.roc()
    assert len(fpr) == len(tpr) and (np.diff(fpr) <= 1e-12).all()  # desc sweep


def test_frame_introspection_and_rapids_fn(cloud1):
    import h2o3_tpu as h2o

    fr = Frame.from_dict({
        "num": np.asarray([1.0, 2.0]),
        "cat": np.asarray(["a", "b"], dtype=object),
    }, column_types={"cat": "enum"})
    assert fr.isfactor() == [False, True]
    assert fr.isnumeric() == [True, False]
    assert fr.levels() == [[], ["a", "b"]]
    assert fr.nlevels() == [0, 2]
    assert fr.columns_by_type("categorical") == [1.0]
    fr.rename({"num": "n2"})
    assert fr.names == ["n2", "cat"]
    fr.set_names(["x", "y"])
    assert fr.names == ["x", "y"]
    from h2o3_tpu.runtime.dkv import DKV
    DKV.put("rfr", fr)
    assert h2o.rapids("(nrow rfr)") == 2


def test_rename_set_names_collisions(cloud1):
    fr = Frame.from_dict({"a": np.asarray([1.0]), "b": np.asarray([2.0])})
    with pytest.raises(ValueError):
        fr.rename({"a": "b"})
    with pytest.raises(ValueError):
        fr.set_names(["x", "x"])
    assert fr.ncol == 2  # untouched after failed renames


def test_apply_axis1_multivalue_rows(cloud1):
    """ADVICE r01: a row lambda returning ncol values on a square frame must
    become ncol OUTPUT COLUMNS (upstream AstApply row semantics), not be
    silently misread as a single full column."""
    import pytest as _pytest
    from h2o3_tpu.frame.frame import Frame

    fr = Frame.from_dict({"a": [1.0, 2.0], "b": [3.0, 4.0]})  # 2x2: ncol==nrow
    out = fr.apply(lambda row: row["a"] + row["b"], axis=1)
    assert out.nrow == 2 and out.ncol == 1
    assert list(np.asarray(out._col0())) == [4.0, 6.0]
    # nrow(==ncol) values per ROW -> 2 columns, not one misread column
    wide = fr.apply(lambda row: np.asarray([1.0, 2.0]), axis=1)
    assert wide.shape == (2, 2)
    # width depends on ROW CONTENT (not external iterator state — the
    # vectorized path probes the callable, so state-carrying lambdas
    # would observe extra calls); per-row widths 1 then 2 must raise
    with _pytest.raises(ValueError, match="ragged"):
        fr.apply(lambda row: np.ones(
            1 if float(row["a"]._col0()[0]) == 1.0 else 2), axis=1)


def test_rapids_apply_margin1_frame_result(cloud1):
    """ADVICE r01: (apply fr 1 fn) where fn returns a Frame must unwrap it
    like the margin=2 branch does."""
    import h2o3_tpu as h2o

    fr = h2o.H2OFrame({"a": [1.0, 2.0, 3.0], "b": [4.0, 5.0, 6.0]})
    # the lambda body yields a 2-col Frame per row — margin=1 must keep BOTH
    # columns (upstream row semantics), not TypeError on float(Frame) or
    # silently truncate to the first column
    out = h2o.rapids(f"(apply {fr.key} 1 {{ x . (+ x 1) }})")
    assert out.shape == (3, 2)
    assert list(np.asarray(out._col0())) == [2.0, 3.0, 4.0]
    assert list(np.asarray(out.vec(out.names[1]).numeric_np())) == [5.0, 6.0, 7.0]


def test_package_utilities_round4(cloud1, tmp_path):
    """h2o.models / remove_all / insert_missing_values / timezone /
    download_csv — h2o-py package-surface parity."""
    import h2o3_tpu as h2o
    from h2o3_tpu.estimators import H2OGradientBoostingEstimator

    rng = np.random.default_rng(0)
    fr = h2o.H2OFrame_from_python(
        {"a": rng.normal(size=200),
         "c": np.asarray([f"k{i%3}" for i in range(200)], dtype=object),
         "y": (rng.random(200) > 0.5).astype(int).astype(str)},
        column_types={"y": "enum", "c": "enum"})
    m = H2OGradientBoostingEstimator(ntrees=2, max_depth=2, seed=1)
    m.train(x=["a", "c"], y="y", training_frame=fr)
    assert m.model_id in h2o.ls()

    # timezone validate + roundtrip
    h2o.set_timezone("America/New_York")
    assert h2o.get_timezone() == "America/New_York"
    with pytest.raises(Exception):
        h2o.set_timezone("Not/AZone")
    h2o.set_timezone("UTC")

    # missing inserter: both numeric and enum columns gain NAs in place
    h2o.insert_missing_values(fr, fraction=0.3, seed=7)
    assert fr.vec("a").nacnt() > 20
    assert fr.vec("c").nacnt() > 20

    # download_csv writes the full frame
    p = str(tmp_path / "dl.csv")
    h2o.download_csv(fr, p)
    assert open(p).readline().strip() == "a,c,y"

    # remove_all with retention
    h2o.remove_all(retained=[fr])
    assert h2o.get_frame(fr.key) is fr
    h2o.remove_all()
    with pytest.raises(KeyError):
        h2o.get_frame(fr.key)


def test_frame_method_conveniences(cloud1):
    """h2o-py Frame conveniences delegating to the Rapids prims: cum*,
    kfold columns, relevel, difflag1, distance, rank_within_group_by,
    melt/pivot, drop_duplicates, var."""
    import numpy as np

    from h2o3_tpu.frame.frame import Frame

    fr = Frame.from_dict({"a": np.asarray([1.0, 2.0, 3.0, 4.0])})
    np.testing.assert_allclose(fr.cumsum().vec("a").numeric_np(),
                               [1, 3, 6, 10])
    np.testing.assert_allclose(fr.cumprod().vec("a").numeric_np(),
                               [1, 2, 6, 24])
    np.testing.assert_allclose(fr.difflag1().vec("difflag1").numeric_np()[1:],
                               [1, 1, 1])
    assert fr.var() == pytest.approx(np.var([1, 2, 3, 4], ddof=1))

    two = Frame.from_dict({"a": np.arange(10.0), "b": np.arange(10.0) * 2})
    cov = two.var()
    assert cov.shape == (2, 2) and cov[0, 1] == pytest.approx(2 * cov[0, 0])

    folds = two.kfold_column(n_folds=4, seed=1).vec("fold").numeric_np()
    assert set(folds) <= {0.0, 1.0, 2.0, 3.0}
    mod = two.modulo_kfold_column(n_folds=3).vec("fold").numeric_np()
    np.testing.assert_array_equal(mod, np.arange(10) % 3)

    yfr = Frame.from_dict(
        {"y": np.asarray(["a", "b"] * 8, dtype=object)},
        column_types={"y": "enum"})
    sf = yfr.stratified_kfold_column(n_folds=2, seed=1).vec("fold").numeric_np()
    # stratified: each class split evenly across folds
    ya = sf[::2]
    assert abs((ya == 0).sum() - (ya == 1).sum()) <= 1

    rl = yfr.relevel("b")
    assert rl.vec("y").domain[0] == "b"

    q = Frame.from_dict({"x": np.asarray([[0.0], [3.0]]).ravel()})
    r = Frame.from_dict({"x": np.asarray([0.0, 4.0])})
    dm = r.distance(q, "l2").to_numpy()
    assert dm.shape == (2, 2)
    assert dm[1, 0] == pytest.approx(4.0)

    g = Frame.from_dict({"g": np.asarray([1.0, 1, 2, 2]),
                         "v": np.asarray([5.0, 3, 9, 7])})
    rk = g.rank_within_group_by("g", "v", new_col_name="rk")
    rkv = rk.vec("rk").numeric_np()
    assert sorted(rkv[:2]) == [1, 2] and sorted(rkv[2:]) == [1, 2]

    wide = Frame.from_dict({"id": np.asarray([1.0, 2.0]),
                            "x": np.asarray([10.0, 20.0]),
                            "y": np.asarray([30.0, 40.0])})
    long = wide.melt(["id"])
    assert long.nrow == 4 and set(long.names) >= {"id", "variable", "value"}

    dup = Frame.from_dict({"k": np.asarray([1.0, 1, 2, 2, 3]),
                           "v": np.asarray([9.0, 8, 7, 6, 5])})
    dd = dup.drop_duplicates(columns=["k"], keep="first")
    assert dd.nrow == 3
    np.testing.assert_allclose(dd.vec("v").numeric_np(), [9, 7, 5])
    dl = dup.drop_duplicates(columns=["k"], keep="last")
    np.testing.assert_allclose(dl.vec("v").numeric_np(), [8, 6, 5])

    # string-keyed dedup (object columns take the tuple-hash path)
    from h2o3_tpu.frame.vec import Vec
    sfr = Frame({"s": Vec(None, "string", strings=np.asarray(
        ["x", "x", "y", "z", "y"], dtype=object)),
        "v": Vec(np.asarray([1.0, 2, 3, 4, 5]), "real")})
    sd = sfr.drop_duplicates(columns=["s"])
    assert sd.nrow == 3
    np.testing.assert_allclose(sd.vec("v").numeric_np(), [1, 3, 4])
    # no-numeric var raises; single-numeric respects na_rm
    import pytest as _pt
    nfr = Frame.from_dict({"x": np.asarray([1.0, np.nan, 3.0])})
    assert nfr.var() == _pt.approx(2.0)


def test_frame_ifelse(cloud1):
    c = Frame.from_dict({"c": np.asarray([1.0, 0.0, 1.0])})
    np.testing.assert_allclose(c.ifelse(10.0, 20.0)._col0(), [10, 20, 10])
    y = Frame.from_dict({"y": np.asarray([1.0, 2, 3])})
    n = Frame.from_dict({"n": np.asarray([9.0, 8, 7])})
    np.testing.assert_allclose(c.ifelse(y, n)._col0(), [1, 8, 3])
    # NA condition propagates NA (AstIfElse), never picks a branch
    cna = Frame.from_dict({"c": np.asarray([1.0, np.nan, 0.0])})
    out = cna.ifelse(10.0, 20.0)._col0()
    np.testing.assert_allclose(out[[0, 2]], [10, 20])
    assert np.isnan(out[1])
