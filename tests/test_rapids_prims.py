"""Extended /99/Rapids sexpr primitives.

Mirrors the reference's `water/rapids/ast/prims/**` coverage: unary math
(AstUniOp family), cumulative ops, reducers, GB group-by (AstGroup), ddply
with `{ x . body }` lambdas (AstDdply/AstFunction), apply, match, levels,
h2o.runif, predicates.
"""

import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu.frame.rapids_expr import RapidsSession


@pytest.fixture()
def sess():
    s = RapidsSession()
    rng = np.random.default_rng(0)
    fr = h2o.H2OFrame_from_python({
        "x": np.asarray([1.0, 4.0, 9.0, 16.0, 25.0]),
        "g": np.asarray(["a", "b", "a", "b", "a"], dtype=object),
        "y": np.asarray([2.0, -3.0, 4.0, -5.0, 6.0]),
    }, column_types={"g": "enum"})
    s.dkv.put("fr", fr)
    return s


def _col(fr, name=None):
    name = name or fr.names[0]
    return np.asarray(fr.vec(name).numeric_np(), np.float64)


def test_unary_math(sess):
    out = sess.execute("(sqrt (cols fr [0]))")
    np.testing.assert_allclose(_col(out), [1, 2, 3, 4, 5])
    out = sess.execute("(abs (cols fr [2]))")
    np.testing.assert_allclose(_col(out), [2, 3, 4, 5, 6])
    out = sess.execute("(log (cols fr [0]))")
    np.testing.assert_allclose(_col(out), np.log([1, 4, 9, 16, 25]))
    out = sess.execute("(ceiling (sqrt (cols fr [0])))")
    np.testing.assert_allclose(_col(out), [1, 2, 3, 4, 5])
    out = sess.execute("(not (cols fr [2]))")
    np.testing.assert_allclose(_col(out), [0, 0, 0, 0, 0])
    out = sess.execute("(lgamma (cols fr [0]))")
    import math
    np.testing.assert_allclose(
        _col(out), [math.lgamma(v) for v in [1, 4, 9, 16, 25]], rtol=1e-12)


def test_round_signif(sess):
    sess.dkv.put("r", h2o.H2OFrame_from_python({"v": [1.2345, 6.789]}))
    np.testing.assert_allclose(_col(sess.execute("(round r 2)")), [1.23, 6.79])
    np.testing.assert_allclose(_col(sess.execute("(signif r 2)")), [1.2, 6.8])


def test_cumulative_and_reducers(sess):
    np.testing.assert_allclose(
        _col(sess.execute("(cumsum (cols fr [0]))")), [1, 5, 14, 30, 55])
    np.testing.assert_allclose(
        _col(sess.execute("(cummax (cols fr [2]))")), [2, 2, 4, 4, 6])
    v = sess.execute("(var (cols fr [0]))")
    assert abs(v - np.var([1, 4, 9, 16, 25], ddof=1)) < 1e-9
    c = sess.execute("(cor (cols fr [0]) (cols fr [2]))")
    assert abs(c - np.corrcoef([1, 4, 9, 16, 25], [2, -3, 4, -5, 6])[0, 1]) < 1e-9
    assert sess.execute("(any (== (cols fr [0]) 9))") == 1.0
    assert sess.execute("(all (> (cols fr [0]) 0))") == 1.0
    assert sess.execute("(anyNA fr)") == 0.0
    wm = sess.execute("(which.max (cols fr [0]))")
    assert _col(wm)[0] == 4.0


def test_group_by_GB(sess):
    out = sess.execute('(GB fr [1] "mean" 0 "all" "nrow" 0 "all")')
    # groups a (rows 0,2,4) and b (rows 1,3)
    assert out.nrow == 2
    gcol = out.vec("g")
    means = np.asarray(out.vec(out.names[1]).numeric_np())
    labels = [gcol.domain[c] for c in np.asarray(gcol.data)]
    d = dict(zip(labels, means))
    np.testing.assert_allclose(d["a"], np.mean([1, 9, 25]))
    np.testing.assert_allclose(d["b"], np.mean([4, 16]))


def test_ddply_lambda(sess):
    out = sess.execute("(ddply fr [1] { sub . (mean (cols sub [0])) })")
    assert out.nrow == 2
    vals = np.asarray(out.vec("ddply_C1").numeric_np())
    np.testing.assert_allclose(
        sorted(vals), sorted([np.mean([1.0, 9.0, 25.0]),
                              np.mean([4.0, 16.0])]), rtol=1e-5)


def test_apply_columns(sess):
    out = sess.execute("(apply (cols fr [0 2]) 2 { c . (max c) })")
    assert set(out.names) == {"x", "y"}
    assert _col(out, "x")[0] == 25.0
    assert _col(out, "y")[0] == 6.0


def test_match_levels_predicates(sess):
    m = sess.execute('(match (cols fr [1]) ["b" "a"])')
    np.testing.assert_allclose(_col(m), [2, 1, 2, 1, 2])
    lv = sess.execute("(levels (cols fr [1]))")
    assert lv.vec("levels").domain == ["a", "b"]
    assert sess.execute("(is.factor (cols fr [1]))") == 1.0
    assert sess.execute("(is.numeric (cols fr [0]))") == 1.0
    assert sess.execute("(nlevels (cols fr [1]))") == 2.0


def test_runif_reproducible(sess):
    a = _col(sess.execute("(h2o.runif fr 42)"))
    b = _col(sess.execute("(h2o.runif fr 42)"))
    np.testing.assert_allclose(a, b)
    assert ((a >= 0) & (a < 1)).all() and len(a) == 5


def test_lambda_edge_cases(sess):
    # body ending in a bare symbol adjacent to '}' must tokenize
    out = sess.execute("(ddply fr [1] { sub . (nrow sub)})")
    assert out.nrow == 2
    # lambda in head position
    assert sess.execute("({ x . (+ x 1) } 5)") == 6.0
    # bare prim name as the function argument of apply
    out = sess.execute("(apply (cols fr [0]) 2 mean)")
    np.testing.assert_allclose(_col(out, "x"), [np.mean([1, 4, 9, 16, 25])])


def test_cumsum_propagates_na(sess):
    sess.dkv.put("na", h2o.H2OFrame_from_python({"v": [1.0, np.nan, 3.0]}))
    out = _col(sess.execute("(cumsum na)"))
    assert out[0] == 1.0 and np.isnan(out[1]) and np.isnan(out[2])


def test_gamma_overflow_is_inf(sess):
    sess.dkv.put("big", h2o.H2OFrame_from_python({"v": [200.0, 2.0]}))
    out = _col(sess.execute("(gamma big)"))
    assert np.isinf(out[0]) and abs(out[1] - 1.0) < 1e-9


def test_h2o_rapids_top_level():
    # the h2o.rapids() public surface routes through the same interpreter
    fr = h2o.H2OFrame_from_python({"z": [1.0, 2.0, 3.0]})
    res = h2o.rapids(f"(cumsum (cols {fr.key} [0]))")
    # rapids() may wrap results; accept Frame-like with the cumsum column
    vals = (np.asarray(res.vec(res.names[0]).numeric_np())
            if hasattr(res, "vec") else np.asarray(res))
    np.testing.assert_allclose(vals.ravel(), [1, 3, 6])
