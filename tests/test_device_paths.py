"""Device-resident data paths: DataInfo.device_design parity, tree-step
program sharing, ntrees-bucketed scoring, GLM device lambda path.

These lock in the TPU-first data-movement design decisions: compact
uploads + on-device expansion must be bit-compatible (to f32) with the
host transform, shared compiled programs must not change results, and
zero-padded scoring forests must be exact.
"""

import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu.estimators import (
    H2OGeneralizedLinearEstimator,
    H2OGradientBoostingEstimator,
)
from h2o3_tpu.models.model_base import DataInfo


def _mixed_frame(n=3000, seed=0, with_na=True):
    rng = np.random.default_rng(seed)
    a = rng.normal(size=n)
    if with_na:
        a[rng.random(n) < 0.08] = np.nan
    cat = rng.choice(["x", "y", "z", "w"], size=n)
    return h2o.H2OFrame_from_python(
        {"a": a, "b": rng.normal(size=n), "c": cat},
        column_types={"c": "enum"})


@pytest.mark.parametrize("standardize", [True, False])
@pytest.mark.parametrize("impute", [True, False])
def test_device_design_matches_fit_transform(standardize, impute):
    fr = _mixed_frame()
    d_host = DataInfo(fr, ["a", "b", "c"], standardize=standardize,
                      impute_missing=impute)
    X_host = d_host.fit_transform(fr)
    d_dev = DataInfo(fr, ["a", "b", "c"], standardize=standardize,
                     impute_missing=impute)
    X_dev = np.asarray(d_dev.device_design(fr, fit=True))
    np.testing.assert_allclose(X_host, X_dev, atol=1e-5)
    if standardize:
        np.testing.assert_allclose(d_host.means, d_dev.means, atol=1e-6)
        np.testing.assert_allclose(d_host.stds, d_dev.stds, atol=1e-6)
    # transform path on a frame with an unseen level
    fr2 = _mixed_frame(300, seed=9, with_na=True)
    np.testing.assert_allclose(
        d_host.transform(fr2),
        np.asarray(d_dev.device_design(fr2, fit=False)), atol=1e-5)


def test_device_design_all_nan_column():
    n = 100
    fr = h2o.H2OFrame_from_python(
        {"dead": np.full(n, np.nan), "b": np.arange(n, dtype=float)})
    di = DataInfo(fr, ["dead", "b"], standardize=True)
    X = np.asarray(di.device_design(fr, fit=True))
    assert np.isfinite(X).all()
    np.testing.assert_allclose(X[:, 0], 0.0)  # fit_transform semantics


def test_tree_program_shared_across_scalar_hyperparams():
    fr = _mixed_frame(2000, with_na=False)
    rng = np.random.default_rng(1)
    y = (rng.random(2000) < 0.5).astype(int)
    fr = fr.cbind(h2o.H2OFrame_from_python(
        {"y": y.astype(str)}, column_types={"y": "enum"}))
    from h2o3_tpu.parallel import mesh as cloudlib

    aucs = []
    for lrate, mr in [(0.1, 10.0), (0.05, 5.0), (0.2, 20.0)]:
        g = H2OGradientBoostingEstimator(ntrees=5, max_depth=3, seed=1,
                                         learn_rate=lrate, min_rows=mr)
        g.train(x=["a", "b", "c"], y="y", training_frame=fr)
        aucs.append(g.auc())
    # different scalars must produce different models ...
    assert len({round(a, 6) for a in aucs}) > 1
    # ... from ONE cached step program (same structural cfg)
    cache = cloudlib.cloud().__dict__.get("_step_fns_cache", {})
    matching = [cfg for cfg in cache
                if cfg.max_depth == 3 and cfg.K == 1 and cfg.F == 3]
    assert len(matching) == 1


def test_padded_scoring_exact_for_any_ntrees():
    rng = np.random.default_rng(2)
    n = 1500
    a = rng.normal(size=n)
    y = (a + rng.normal(scale=0.5, size=n) > 0).astype(int)
    fr = h2o.H2OFrame_from_python({"a": a, "y": y.astype(str)},
                                  column_types={"y": "enum"})
    for nt in (1, 3, 7):
        g = H2OGradientBoostingEstimator(ntrees=nt, max_depth=3, seed=1)
        g.train(x=["a"], y="y", training_frame=fr)
        m = g.model
        # padded margins == unpadded reference sum over real trees
        import jax.numpy as jnp

        from h2o3_tpu.models import tree as treelib

        Xm = m._matrix(fr)
        ref = np.zeros(n)
        st = m.forest[0]
        for t in range(nt):
            one = treelib.Tree(*[jnp.asarray(np.asarray(f)[t])
                                 for f in st])
            ref += np.asarray(treelib.predict_raw(
                one, jnp.asarray(Xm, jnp.float32), m.max_depth))
        f0 = m.f0 if np.ndim(m.f0) == 0 else m.f0[0]
        np.testing.assert_allclose(m._margins(Xm)[:, 0], ref + f0,
                                   atol=1e-5)


def test_glm_device_lambda_path_matches_host():
    rng = np.random.default_rng(3)
    n = 4000
    a = rng.normal(size=n)
    b = rng.normal(size=n)
    y = (rng.random(n) < 1 / (1 + np.exp(-(1.2 * a - 0.4 * b)))).astype(int)
    fr = h2o.H2OFrame_from_python({"a": a, "b": b, "y": y.astype(str)},
                                  column_types={"y": "enum"})
    glm = H2OGeneralizedLinearEstimator(family="binomial",
                                        lambda_search=True, alpha=0.5)
    glm.train(x=["a", "b"], y="y", training_frame=fr)
    assert glm.auc() > 0.7
    path = glm.model.full_path
    assert len(path) >= 20
    # the path must shrink coefficients as lambda grows (elastic net)
    l1_first = np.abs(path[0][1][:-1]).sum()    # largest lambda
    l1_last = np.abs(path[-1][1][:-1]).sum()    # smallest lambda
    assert l1_last > l1_first
    assert np.isfinite(np.asarray(glm.model.beta)).all()
    # PARITY: recompute a few path points with the retained host f64 IRLS
    # (cold warm-start) and compare the device f32 betas against them
    import jax.numpy as jnp

    m = glm.model
    Xd = m.dinfo.device_design(fr, fit=False, add_intercept=True)
    yd = np.asarray(fr.vec("y").data, np.float32)
    wd = np.ones(fr.nrow, np.float32)
    for i in (0, len(path) // 2, len(path) - 1):
        lam_i, beta_dev = path[i]
        beta_host = glm._irls_warm(
            Xd, jnp.asarray(yd), jnp.asarray(wd), "binomial", float(lam_i),
            0.5, 50, 1e-4, 1.5, np.zeros(Xd.shape[1], np.float64))
        np.testing.assert_allclose(beta_dev, beta_host, atol=5e-3)


def test_device_design_sharded_mesh_matches_dense(cloud8):
    """Single-process multi-device mesh: device_design(cloud=) produces the
    row-sharded byte-compressed design, equal to the dense f32 path, with
    zero-padded quota rows at the tail (VERDICT r04 #4)."""
    import numpy as np

    import h2o3_tpu as h2o
    from h2o3_tpu.models.model_base import DataInfo
    from h2o3_tpu.parallel import mesh as cloudlib

    rng = np.random.default_rng(3)
    n = 500                                  # NOT divisible by 8 → padding
    d = {
        "a": rng.integers(0, 200, n).astype(np.float64),       # uint8 group
        "b": rng.integers(-1000, 1000, n).astype(np.float64),  # int16 group
        "f": rng.normal(size=n),                               # f32 group
        "c": np.asarray([f"k{v}" for v in rng.integers(0, 4, n)],
                        dtype=object),
    }
    fr = h2o.H2OFrame_from_python(d, column_types={"c": "enum"})
    dinfo = DataInfo(fr, ["a", "b", "f", "c"], standardize=True)
    X = dinfo.fit_transform(fr)
    Xd = dinfo.device_design(fr, fit=False, cloud=cloud8)
    assert dinfo._transfer_groups == [0, 1, 2]
    quota = cloudlib.pad_to_multiple(n, cloud8.size)
    assert int(Xd.shape[0]) == quota
    got = np.asarray(Xd)
    np.testing.assert_allclose(got[:n], X, rtol=1e-5, atol=1e-5)
    # sharding really is by rows over the mesh
    assert len(Xd.sharding.device_set) == cloud8.size
