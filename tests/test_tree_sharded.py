"""Sharded end-to-end tree training (ISSUE 12) — shard-boundary
correctness and the N-device bit-stability contract.

The design under test: every row reduction of the fused tree path
(histograms, final leaf totals, scoring-event loss) runs as S ordered
block partials merged by `ops.histogram.ordered_axis_fold` (all_gather +
left-to-right fold), so the reduction tree is a function of S alone — an
8-device `shard_map` fit and a 1-device fit forced through the same
structure (``H2O3_TREE_SHARD=1``) are BIT-IDENTICAL, and the forced-CPU
lane exercises the identical sharded code path via the t5x-style
`mesh.shard_call` wrapper (plain call at 1 device, shard_map on a mesh).

Tier-1 section: kernel-level pins on the 8-virtual-device CPU mesh the
conftest provides (cheap — no estimator-driver compiles). The whole-fit
estimator parity matrix (GBM early-stop discard, DRF OOB/mtries,
monotone, CV fold reuse, escape hatch, observability surfaces) runs as
``slow`` — and the MULTICHIP lane (`__graft_entry__.dryrun_multichip`)
independently pins a complete sharded fit bit-stable every round.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from h2o3_tpu.models import shared_tree
from h2o3_tpu.models import tree as treelib
from h2o3_tpu.ops import histogram, packing
from h2o3_tpu.parallel import mesh as cloudlib

from conftest import make_classification


@pytest.fixture()
def _shard_env():
    """Isolate the sharding env knobs per test."""
    keys = ("H2O3_TREE_SHARD", "H2O3_TREE_SHARD_BLOCKS", "H2O3_TREE_LEGACY",
            "H2O3_HIST_METHOD", "H2O3_HOST_HIST_MIN_ROWS")
    prior = {k: os.environ.pop(k, None) for k in keys}
    yield
    for k, v in prior.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


# -- shard plan rules (pure host logic) -------------------------------------

def test_shard_plan_rules(_shard_env):
    tp = {}
    assert shared_tree._shard_plan(8, False, tp) == ("mesh", 8)
    assert shared_tree._shard_plan(1, False, tp) == ("off", 0)
    # ndev must divide S: non-power-of-two meshes raise S to the lcm
    assert shared_tree._shard_plan(6, False, tp) == ("mesh", 24)
    os.environ["H2O3_TREE_SHARD"] = "0"          # escape hatch: never shard
    assert shared_tree._shard_plan(8, False, tp) == ("off", 0)
    os.environ["H2O3_TREE_SHARD"] = "1"          # forced blocks at 1 device
    assert shared_tree._shard_plan(1, False, tp) == ("blocks", 8)
    os.environ.pop("H2O3_TREE_SHARD", None)
    os.environ["H2O3_TREE_SHARD_BLOCKS"] = "16"
    assert shared_tree._shard_plan(4, False, tp) == ("mesh", 16)
    os.environ.pop("H2O3_TREE_SHARD_BLOCKS", None)
    # legacy comparator / lossguide / multiproc keep the psum path
    os.environ["H2O3_TREE_LEGACY"] = "1"
    assert shared_tree._shard_plan(8, False, tp)[0] == "mesh_psum"
    # ...but the escape hatch overrides legacy/lossguide (a broken mesh
    # must not run THEIR collectives either)...
    os.environ["H2O3_TREE_SHARD"] = "0"
    assert shared_tree._shard_plan(8, False, tp) == ("off", 0)
    os.environ.pop("H2O3_TREE_LEGACY", None)
    assert shared_tree._shard_plan(
        8, False, {"grow_policy": "lossguide"}) == ("off", 0)
    # ...while multi-process clouds ignore it (their rows live on other
    # processes — "one device" is not an option)
    assert shared_tree._shard_plan(8, True, tp)[0] == "mesh_psum"
    os.environ.pop("H2O3_TREE_SHARD", None)
    assert shared_tree._shard_plan(
        8, False, {"grow_policy": "lossguide"})[0] == "mesh_psum"


def test_fit_plan_records_shards(_shard_env):
    """The /3/Profiler tree fold's per-fit plans carry the shard geometry
    (n_shards / n_devices / pack_bits) — the ISSUE 12 observability
    satellite — and the collective-safe kernel substitution still holds."""
    plan = histogram.record_fit_plan(
        "test:sharded", [("d0", 1), ("d1", 1)], 21, "auto",
        pack_bits=5, axis_name=cloudlib.ROWS_AXIS, n_shards=8, n_devices=8)
    assert plan["n_shards"] == 8 and plan["n_devices"] == 8
    assert plan["pack_bits"] == 5
    from h2o3_tpu.runtime import profiler

    fold = profiler.tree_stats()
    assert fold["plans"][-1]["n_shards"] == 8
    # the host callback can never run under a collective program
    sel = histogram.resolve_method(4, 21, "host", axis_name="hosts")
    assert sel["method"] == "segment" and sel["fallback"] == "collective"


# -- kernel-level shard invariance ------------------------------------------

def test_blocked_histograms_shard_invariant(cloud8, _shard_env):
    """8 devices × 1 block/device == 1 device × 8 blocks, bitwise — for the
    in-graph segment kernel (mesh lane) AND the np.add.at host callback
    (forced-CPU lane), packed and dense. The plain single-fold path stays
    untouched (last-ulp different), which is exactly why the sharded lane
    needs its own canonical reduction."""
    rng = np.random.default_rng(2)
    N, F, B, L, S = 256, 4, 16, 4, 8
    codes = rng.integers(0, B, (N, F)).astype(np.uint8)
    node = rng.integers(0, L, N).astype(np.int32)
    g = rng.normal(size=N).astype(np.float32)
    h = rng.random(N).astype(np.float32)
    w = (rng.random(N) > 0.1).astype(np.float32)
    bits = packing.pack_bits_for(B, N)
    pk = packing.pack_host(codes, bits)
    rspec = P(cloudlib.ROWS_AXIS)

    for codes_in, pb in ((codes, 0), (pk, bits)):
        def inner_mesh(c, n_, g_, h_, w_):
            return histogram.build_histograms(
                c, n_, g_, h_, w_, L, B, method="segment",
                axis_name=cloudlib.ROWS_AXIS, pack_bits=pb,
                n_shard_blocks=1)

        fn8 = jax.jit(cloudlib.shard_call(
            inner_mesh, cloud8, in_specs=(rspec,) * 5, out_specs=P(),
            check_rep=False))
        rs = cloud8.row_sharding()
        h8 = np.asarray(fn8(
            jax.device_put(jnp.asarray(codes_in), rs),
            jax.device_put(jnp.asarray(node), rs),
            jax.device_put(jnp.asarray(g), rs),
            jax.device_put(jnp.asarray(h), rs),
            jax.device_put(jnp.asarray(w), rs)))
        for meth in ("segment", "host"):
            got = np.asarray(jax.jit(
                lambda c, n_, g_, h_, w_, m=meth: histogram.build_histograms(
                    c, n_, g_, h_, w_, L, B, method=m, pack_bits=pb,
                    n_shard_blocks=S)
            )(jnp.asarray(codes_in), jnp.asarray(node), jnp.asarray(g),
              jnp.asarray(h), jnp.asarray(w)))
            assert np.array_equal(h8, got), (pb, meth)


def test_build_tree_sharded_parity_combined(cloud8, _shard_env):
    """One packed fused `build_tree` under shard_map (8 devices) vs the
    identical call with 8 local blocks on one device: bit-equal trees,
    leaf assignment, gains and covers — with mtries column sampling,
    monotone constraints and elastic-net regularization ALL active, a
    zero-weight pad tail (rows not divisible by the mesh are padded
    result-neutral through the collective), and a shard whose weights
    leave a SINGLE live row (shard-boundary degenerate case). Weight
    patterns are data, not shape — one compiled program pair covers every
    case."""
    rng = np.random.default_rng(4)
    N, F, B, D, S = 512, 5, 16, 3, 8
    codes = rng.integers(0, B, (N, F)).astype(np.uint8)
    g = rng.normal(size=N).astype(np.float32)
    h = (rng.random(N).astype(np.float32) + 0.1)
    w = np.ones(N, np.float32)
    w[-40:] = 0.0              # "979 rows on a 64-row grid" pad tail
    w[448:512] = 0.0           # shard 7 of the 8-device layout...
    w[450] = 1.0               # ...holds exactly ONE live row
    fm = np.ones(F, np.float32)
    edges = np.sort(rng.normal(size=(F, B - 2)), axis=1).astype(np.float32)
    mono = np.zeros(F, np.float32)
    mono[0] = 1.0
    bits = packing.pack_bits_for(B, N)
    pk = packing.pack_host(codes, bits)
    key = np.asarray(jax.random.PRNGKey(9))

    def builder(axis, nblocks):
        def fn(c, g_, h_, w_, k_):
            return treelib.build_tree(
                c, g_, h_, w_, jnp.asarray(fm), jnp.asarray(edges), key=k_,
                max_depth=D, nbins=B, min_rows=2.0,
                reg_lambda=0.5, reg_alpha=0.25,
                mtries_rate=jnp.float32(0.6), monotone=jnp.asarray(mono),
                fused_split=True, pack_bits=bits,
                axis_name=axis, n_shard_blocks=nblocks)
        return fn

    rspec = P(cloudlib.ROWS_AXIS)
    fn8 = jax.jit(cloudlib.shard_call(
        builder(cloudlib.ROWS_AXIS, 1), cloud8,
        in_specs=(rspec,) * 4 + (P(),),
        out_specs=(treelib.Tree(P(), P(), P(), P(), P()), rspec, P(), P()),
        check_rep=False))
    rs = cloud8.row_sharding()
    out8 = fn8(jax.device_put(jnp.asarray(pk), rs),
               jax.device_put(jnp.asarray(g), rs),
               jax.device_put(jnp.asarray(h), rs),
               jax.device_put(jnp.asarray(w), rs),
               jnp.asarray(key))
    out1 = jax.jit(builder(None, S))(
        jnp.asarray(pk), jnp.asarray(g), jnp.asarray(h), jnp.asarray(w),
        jnp.asarray(key))
    for a, b in zip(jax.tree.leaves(out8), jax.tree.leaves(out1)):
        assert np.array_equal(np.asarray(a), np.asarray(b), equal_nan=True)


# -- whole-fit estimator parity (slow lane; the MULTICHIP dryrun pins the
#    same contract every round) --------------------------------------------

_FIT_N, _FIT_F = 1000, 6
_FIT_X, _FIT_Y = make_classification(n=_FIT_N, f=_FIT_F, seed=7)
_FIT_NAMES = [f"f{i}" for i in range(_FIT_F)] + ["label"]


def _frame():
    from h2o3_tpu.frame.frame import Frame

    return Frame.from_numpy(np.column_stack([_FIT_X, _FIT_Y]),
                            names=_FIT_NAMES).asfactor("label")


def _fit(builder, ndev, shard=None):
    from h2o3_tpu.models import dataset_cache

    dataset_cache.clear()
    cloudlib.reset()
    if shard is None:
        os.environ.pop("H2O3_TREE_SHARD", None)
    else:
        os.environ["H2O3_TREE_SHARD"] = shard
    cloudlib.init(jax.devices()[:ndev])
    est = builder()
    est.train(y="label", training_frame=_frame())
    _ = est.model.forest          # host-materialize before the cloud resets
    os.environ.pop("H2O3_TREE_SHARD", None)
    return est


def _assert_bitexact(a, b):
    assert a.model.ntrees_built == b.model.ntrees_built
    for k in range(len(a.model.forest)):
        for f in treelib.Tree._fields:
            assert np.array_equal(
                np.asarray(getattr(a.model.forest[k], f)),
                np.asarray(getattr(b.model.forest[k], f))), (k, f)


@pytest.mark.slow
def test_sharded_gbm_fit_bitstable_with_early_stop(_shard_env):
    """The headline pin: a WHOLE 8-device GBM fit — packed codes, fused
    split search, overlapped chunk scoring, a FIRING early stop that
    discards the speculative chunk coherently across shards — is
    bit-identical to the 1-device fused path running the same canonical
    reduction (H2O3_TREE_SHARD=1): forests, scoring history, training
    metrics, predictions. 1000 rows on an 8×8-row grid also pins pad-row
    neutrality through the collective merge."""
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

    mk = lambda: H2OGradientBoostingEstimator(  # noqa: E731
        ntrees=8, max_depth=4, seed=42, score_tree_interval=2,
        stopping_rounds=1, stopping_tolerance=0.5)
    g8 = _fit(mk, 8)
    p8 = g8.predict(_frame()).vec("1").numeric_np()
    g1 = _fit(mk, 1, shard="1")
    p1 = g1.predict(_frame()).vec("1").numeric_np()
    assert g8.model.ntrees_built < 8, "the stopper must fire for this pin"
    _assert_bitexact(g8, g1)
    assert np.array_equal(p8, p1)
    h8 = [e.get("logloss") for e in g8.model.scoring_history]
    h1 = [e.get("logloss") for e in g1.model.scoring_history]
    assert h8 == h1
    np.testing.assert_array_equal(g8.model.training_metrics.logloss(),
                                  g1.model.training_metrics.logloss())
    # and the default (unsharded) 1-device fused path agrees to float dust
    g0 = _fit(mk, 1)
    p0 = g0.predict(_frame()).vec("1").numeric_np()
    np.testing.assert_allclose(p0, p8, rtol=3e-5, atol=2e-6)


@pytest.mark.slow
def test_sharded_drf_and_monotone_fits_bitstable(_shard_env):
    """DRF (per-node mtries + row sampling + OOB scoring) and GBM monotone
    constraints through the sharded path match the forced-1-device lane
    bit-for-bit."""
    from h2o3_tpu.models.drf import H2ORandomForestEstimator
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

    mkd = lambda: H2ORandomForestEstimator(  # noqa: E731
        ntrees=6, max_depth=4, seed=42, score_tree_interval=3)
    _assert_bitexact(_fit(mkd, 8), _fit(mkd, 1, shard="1"))
    mkm = lambda: H2OGradientBoostingEstimator(  # noqa: E731
        ntrees=5, max_depth=4, seed=42, monotone_constraints={"f0": 1})
    _assert_bitexact(_fit(mkm, 8), _fit(mkm, 1, shard="1"))


@pytest.mark.slow
def test_sharded_cv_fold_reuse_bitstable(_shard_env):
    """CV fold reuse composes with sharding: fold fits slice the parent's
    binned codes, inherit its padded row bucket, and train sharded — the
    cross-validated parent and the CV metrics are bit-identical across
    cloud sizes."""
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

    mk = lambda: H2OGradientBoostingEstimator(  # noqa: E731
        ntrees=4, max_depth=4, seed=42, nfolds=2,
        keep_cross_validation_predictions=True)
    c8 = _fit(mk, 8)
    c1 = _fit(mk, 1, shard="1")
    _assert_bitexact(c8, c1)
    np.testing.assert_array_equal(
        c8.model.cross_validation_metrics.logloss(),
        c1.model.cross_validation_metrics.logloss())


@pytest.mark.slow
def test_shard_escape_hatch_and_observability(_shard_env):
    """H2O3_TREE_SHARD=0 on an 8-device cloud bypasses the mesh entirely —
    bit-identical to a plain 1-device fit (the broken-mesh escape hatch).
    A sharded fit's observability: the kernel plan records
    n_shards/n_devices/pack_bits, dispatch counters reach the Prometheus
    scrape, and collective wait time lands in the runtime/phases
    ``collective`` bucket."""
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
    from h2o3_tpu.runtime import metrics_registry, phases

    mk = lambda: H2OGradientBoostingEstimator(  # noqa: E731
        ntrees=4, max_depth=4, seed=42)
    _assert_bitexact(_fit(mk, 8, shard="0"), _fit(mk, 1))
    phases.reset()
    _fit(mk, 8)
    stats = histogram.kernel_stats()
    plan = stats["plans"][-1]
    assert plan["n_shards"] == 8 and plan["n_devices"] == 8
    assert plan["pack_bits"] in (4, 5, 6)
    assert "h2o3_tree_hist_dispatch_total" in \
        metrics_registry.prometheus_text()
    # the collective bucket records fence wait time (unrounded: a tiny
    # CPU-mesh fit's waits are µs-scale and round to 0.0 in the snapshot)
    assert phases.totals(("collective",)) > 0.0, phases.snapshot()


@pytest.mark.slow
def test_sharded_device_codes_cached_per_shard_layout(_shard_env):
    """The dataset cache's device layer keys the shard layout: an 8-shard
    fit reuses the row-sharded packed artifact on a repeat candidate
    (device hit), and a 1-device consumer never shares it."""
    from h2o3_tpu.models import dataset_cache
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

    dataset_cache.clear()
    cloudlib.reset()
    os.environ.pop("H2O3_TREE_SHARD", None)
    cloudlib.init(jax.devices())
    fr = _frame()
    for lr in (0.1, 0.2):         # same (frame, x, nbins): second fit hits
        est = H2OGradientBoostingEstimator(ntrees=2, max_depth=3, seed=1,
                                           learn_rate=lr)
        est.train(y="label", training_frame=fr)
    snap = dataset_cache.snapshot()
    assert snap["device_hits"] >= 1, snap


# -- per-lane collective skew + straggler detection (ISSUE 13) ---------------

def test_lane_recorder_flush_and_straggler_detection(monkeypatch):
    """Host-level contract of the lane-timing recorder: 8 concurrent
    arrival callbacks flush one fence record; a lane whose arrival is
    delayed by the `mesh.lane_delay` fault persistently past the median
    fires the straggler counter for EXACTLY that lane. Runs the real
    callback path (faults.check inside _lane_arrive_cb) without device
    programs — tier-1 cheap."""
    import threading

    from h2o3_tpu.runtime import faults, metrics_registry as registry

    # explicit 8-device cloud: the fence flushes when every lane of the
    # CURRENT cloud has reported (the session cloud8 fixture's global
    # cloud is reset between tests — init fresh, don't depend on order)
    cloudlib.init(jax.devices())
    cloudlib.lane_reset()
    monkeypatch.setenv("H2O3_STRAGGLER_FENCES", "2")
    faults.arm("mesh.lane_delay", error="none", latency_ms=150, lane=2)
    try:
        for _fence in range(3):
            ts = [threading.Thread(target=cloudlib._lane_arrive_cb,
                                   args=("t", lane)) for lane in range(8)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
        st = cloudlib.lane_stats()
        assert st["fences"] == 3
        rec = st["records"][-1]
        assert len(rec["waits_ms"]) == 8
        worst = max(rec["waits_ms"], key=rec["waits_ms"].get)
        assert worst == "2", rec
        assert rec["skew_ms"] >= 100
        # fired once per streak (at the 2nd consecutive flagged fence),
        # for the delayed lane ONLY
        assert st["stragglers"] == {"2": 1}, st
        c = registry.get("h2o3_stragglers")
        assert c.value("2") >= 1
        # fence + skew surfaces reached the scrape
        text = registry.prometheus_text()
        assert 'h2o3_stragglers_total{lane="2"}' in text
        assert "h2o3_collective_skew_ms_bucket" in text
    finally:
        faults.reset()
        cloudlib.lane_reset()


def test_straggler_fires_on_two_lane_mesh(monkeypatch):
    """Lower-median threshold: with only 2 lanes the healthy lane sets
    the baseline — the upper middle would be the straggler's own wait
    (threshold = factor x itself, unfirable)."""
    import threading

    from h2o3_tpu.runtime import faults

    cloudlib.init(jax.devices()[:2])
    cloudlib.lane_reset()
    monkeypatch.setenv("H2O3_STRAGGLER_FENCES", "2")
    faults.arm("mesh.lane_delay", error="none", latency_ms=120, lane=1)
    try:
        for _fence in range(2):
            ts = [threading.Thread(target=cloudlib._lane_arrive_cb,
                                   args=("t", lane)) for lane in range(2)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
        assert cloudlib.lane_stats()["stragglers"] == {"1": 1}
    finally:
        faults.reset()
        cloudlib.lane_reset()


def test_lane_summary_and_last_waits():
    """lane_summary folds only the fences after `since_seq` (the per-fit
    attribution window) and lane_last_waits is the watchdog's host-only
    read."""
    import threading

    cloudlib.init(jax.devices())
    cloudlib.lane_reset()
    try:
        def fence():
            ts = [threading.Thread(target=cloudlib._lane_arrive_cb,
                                   args=("t", lane)) for lane in range(8)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()

        fence()
        seq0 = cloudlib.lane_seq()
        assert seq0 == 1
        fence()
        s = cloudlib.lane_summary(seq0)
        assert s["fences"] == 1            # only the post-seq0 fence
        assert set(s["per_lane_max_ms"]) == {str(i) for i in range(8)}
        lw = cloudlib.lane_last_waits()
        assert len(lw) == 8 and all(isinstance(k, int) for k in lw)
        # a hung fence (lanes 6,7 never arrive) takes priority in the
        # watchdog read: the MISSING lanes are the suspects
        for lane in range(6):
            cloudlib._lane_arrive_cb("t", lane)
        hung = cloudlib.lane_last_waits()
        assert set(hung) == set(range(6)), hung
    finally:
        cloudlib.lane_reset()


@pytest.mark.slow
def test_injected_lane_delay_fires_straggler_on_exact_lane(_shard_env,
                                                          monkeypatch):
    """The acceptance pin: a WHOLE sharded GBM fit with an injected
    `mesh.lane_delay` fault on lane 5 fires the straggler detector on
    exactly lane 5, deterministically; the fit plan carries the skew
    summary naming the same lane."""
    from h2o3_tpu.models import dataset_cache
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
    from h2o3_tpu.runtime import faults, metrics_registry as registry

    dataset_cache.clear()
    cloudlib.reset()
    cloudlib.init(jax.devices())
    cloudlib.lane_reset()
    monkeypatch.setenv("H2O3_STRAGGLER_FENCES", "2")
    before = registry.get("h2o3_stragglers")
    before5 = before.value("5") if before else 0.0
    faults.arm("mesh.lane_delay", error="none", latency_ms=120, lane=5)
    try:
        est = H2OGradientBoostingEstimator(ntrees=8, max_depth=3, seed=3,
                                           score_tree_interval=1)
        est.train(y="label", training_frame=_frame())
        st = cloudlib.lane_stats()
        assert st["fences"] >= 3, st
        assert set(st["stragglers"]) == {"5"}, st
        c = registry.get("h2o3_stragglers")
        assert c.value("5") == before5 + 1
        plan = histogram.kernel_stats()["plans"][-1]
        skew = plan.get("collective_skew")
        assert skew and skew["worst_lane"] == 5, plan
        assert skew["skew_max_ms"] >= 100
        assert skew["fences"] == st["fences"]
    finally:
        faults.reset()
        cloudlib.lane_reset()
        dataset_cache.clear()


@pytest.mark.slow
def test_lane_timing_quiet_without_fault_and_off_hot_path(_shard_env):
    """Without injected latency an 8-device fit records fences whose skew
    is benign and fires NO straggler; fences count scoring events, not
    levels (the instrument must stay off the per-level hot path)."""
    from h2o3_tpu.models import dataset_cache
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

    dataset_cache.clear()
    cloudlib.reset()
    cloudlib.init(jax.devices())
    cloudlib.lane_reset()
    try:
        ntrees, interval = 8, 2
        est = H2OGradientBoostingEstimator(ntrees=ntrees, max_depth=3,
                                           seed=3,
                                           score_tree_interval=interval)
        est.train(y="label", training_frame=_frame())
        st = cloudlib.lane_stats()
        assert st["fences"] >= 1
        # one instrumented fence per scoring event (+ warm-up), NEVER one
        # per level: depth-3 x 8 trees would be >= 24 level passes
        assert st["fences"] <= ntrees // interval + 2, st
        assert st["stragglers"] == {}, st
    finally:
        cloudlib.lane_reset()
        dataset_cache.clear()
