"""Frame/Vec/parse tests — the `h2o-py/tests/testdir_munging` analog."""

import os
import tempfile

import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu.frame.binning import bin_apply, build_bins
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.vec import Vec


def test_vec_types():
    v = Vec.from_numpy(np.asarray([1.0, 2.0, 3.0]))
    assert v.type == "int"
    v = Vec.from_numpy(np.asarray([1.5, 2.0, np.nan]))
    assert v.type == "real" and v.nacnt() == 1
    v = Vec.from_numpy(np.asarray(["a", "b", "a", "NA"], dtype=object))
    assert v.type == "enum" and v.domain == ["a", "b"] and v.nacnt() == 1


def test_frame_basics():
    fr = Frame.from_dict({"a": [1, 2, 3, 4], "b": [1.5, 2.5, 3.5, np.nan],
                          "c": ["x", "y", "x", "y"]})
    assert fr.shape == (4, 3)
    assert fr.types == {"a": "int", "b": "real", "c": "enum"}
    sub = fr[["a", "c"]]
    assert sub.names == ["a", "c"]
    rows = fr[np.asarray([0, 2])]
    assert rows.nrow == 2
    masked = fr[np.asarray([True, False, True, False])]
    assert masked.nrow == 2
    d = fr.describe()
    assert d["a"]["mean"] == pytest.approx(2.5)


def test_split_frame_and_rbind_cbind():
    fr = Frame.from_dict({"a": np.arange(100), "c": ["u", "v"] * 50})
    tr, te = fr.split_frame([0.75], seed=42)
    assert tr.nrow + te.nrow == 100
    assert 60 < tr.nrow < 90
    both = tr.rbind(te)
    assert both.nrow == 100
    wide = fr.cbind(fr)
    assert wide.ncol == 4


def test_csv_roundtrip(tmp_path):
    p = tmp_path / "t.csv"
    p.write_text("a,b,cat\n1,2.5,x\n2,NA,y\n3,4.5,x\n")
    fr = h2o.import_file(str(p))
    assert fr.names == ["a", "b", "cat"]
    assert fr.nrow == 3
    assert fr.vec("b").nacnt() == 1
    assert fr.vec("cat").type == "enum"
    assert fr.vec("cat").domain == ["x", "y"]


def test_headerless_csv(tmp_path):
    p = tmp_path / "t.csv"
    p.write_text("1,2.5\n2,3.5\n")
    fr = h2o.import_file(str(p))
    assert fr.names == ["C1", "C2"] and fr.nrow == 2


def test_binning_uniform_and_quantile():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(500, 3))
    X[::17, 1] = np.nan
    for ht in ("UniformAdaptive", "QuantilesGlobal", "Random"):
        bm = build_bins(X, nbins=32, histogram_type=ht)
        assert bm.codes.shape == (500, 3)
        assert bm.codes.max() <= 31
        # NA rows land in the NA bin
        assert (bm.codes[::17, 1] == bm.na_bin).all()
        # re-apply matches
        again = bin_apply(bm, X)
        np.testing.assert_array_equal(again, bm.codes)


def test_binning_monotone():
    X = np.linspace(0, 1, 100)[:, None]
    bm = build_bins(X, nbins=16)
    assert (np.diff(bm.codes[:, 0].astype(int)) >= 0).all()


def test_asfactor():
    fr = Frame.from_dict({"y": [0, 1, 0, 1]})
    fr2 = fr.asfactor("y")
    assert fr2.vec("y").type == "enum"
    assert fr2.vec("y").nlevels == 2


def test_h2o_module_functions(tmp_path, cloud1):
    import os
    import h2o3_tpu as h2o
    from h2o3_tpu.frame.frame import Frame

    fr = h2o.create_frame(rows=100, cols=6, categorical_fraction=0.3,
                          real_fraction=0.4, integer_fraction=0.3,
                          factors=4, missing_fraction=0.1, seed=7,
                          has_response=True)
    assert fr.nrow == 100 and fr.ncol == 7
    assert any(v.type == "enum" for v in fr.vecs())
    assert any(v.nacnt() > 0 for v in fr.vecs())
    # export → reimport round trip
    p = str(tmp_path / "out.csv")
    h2o.export_file(fr[["C1", "C2"]], p)
    back = h2o.import_file(p)
    assert back.nrow == 100 and back.ncol == 2
    import pytest
    with pytest.raises(FileExistsError):
        h2o.export_file(fr[["C1"]], p)
    # deep copy is independent
    cp = h2o.deep_copy(fr, "the_copy")
    assert "the_copy" in h2o.frames()
    # get_model after a train
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
    import numpy as np
    tr = Frame.from_dict({"a": np.arange(50.0), "y": np.arange(50.0) * 2})
    m = H2OGradientBoostingEstimator(ntrees=2, max_depth=2)
    m.train(x=["a"], y="y", training_frame=tr)
    assert h2o.get_model(m.model_id) is m.model
    assert m.model_id in h2o.ls()


def test_pandas_interop(cloud1):
    pd = pytest.importorskip("pandas")
    df = pd.DataFrame({"x": [1.0, 2.0, 3.0],
                       "c": ["a", "b", "a"],
                       "n": [1, 2, 3]})
    fr = h2o.H2OFrame_from_python(df)
    assert fr.names == ["x", "c", "n"]
    assert fr.vec("c").type == "enum" and fr.vec("c").domain == ["a", "b"]
    np.testing.assert_allclose(fr.vec("x").numeric_np(), [1, 2, 3])
    back = fr.as_data_frame()
    assert isinstance(back, pd.DataFrame)
    assert list(back["c"]) == ["a", "b", "a"]
    d = fr.as_data_frame(use_pandas=False)
    assert isinstance(d, dict)


def test_pandas_missing_and_datetime(cloud1):
    pd = pytest.importorskip("pandas")
    df = pd.DataFrame({
        "c": ["a", np.nan, "b"],
        "s": pd.array(["x", pd.NA, "y"], dtype="string"),
        "t": pd.to_datetime(["2020-01-01", None, "2020-01-02"]),
    })
    fr = h2o.H2OFrame_from_python(df)
    v = fr.vec("c")
    assert v.domain == ["a", "b"]
    assert np.asarray(v.data).tolist() == [0, -1, 1]
    assert fr.vec("s").domain == ["x", "y"]
    t = fr.vec("t")
    assert t.type == "time"
    ts = t.numeric_np()
    assert np.isnan(ts[1]) and ts[2] - ts[0] == 86400_000.0
    # non-string column label + typed hint keyed by the original label
    df2 = pd.DataFrame({0: [1.0, 2.0, 1.0]})
    fr2 = h2o.H2OFrame_from_python(df2, column_types={0: "enum"})
    assert fr2.vec("0").type == "enum"


def test_assign_apply_export_parquet(tmp_path, cloud1):
    fr = h2o.H2OFrame_from_python({"x": [1.0, 2.0, 3.0], "y": [4.0, 5.0, 6.0]})
    old_key = fr.key
    h2o.assign(fr, "renamed")
    assert h2o.get_frame("renamed") is fr
    with pytest.raises(KeyError):
        h2o.get_frame(old_key)
    # column apply
    mx = fr.apply(lambda c: c.vec(c.names[0]).max(), axis=0)
    assert mx.vec("x").numeric_np()[0] == 3.0
    assert mx.vec("y").numeric_np()[0] == 6.0
    # parquet export round trip
    p = str(tmp_path / "out.parquet")
    h2o.export_file(fr, p)
    back = h2o.import_file(p)
    np.testing.assert_allclose(back.vec("x").numeric_np(), [1, 2, 3])


def test_apply_transform_and_format_override(tmp_path, cloud1):
    fr = h2o.H2OFrame_from_python({"x": [1.0, 2.0, 3.0]})
    doubled = fr.apply(lambda c: c * 2.0, axis=0)
    np.testing.assert_allclose(doubled.vec("x").numeric_np(), [2, 4, 6])
    with pytest.raises(ValueError, match="axis"):
        fr.apply(lambda c: 0, axis=2)
    # explicit csv format wins over a .parquet extension
    p = str(tmp_path / "weird.parquet")
    h2o.export_file(fr, p, format="csv")
    assert open(p).readline().strip() == "x"


def test_apply_comparison_and_save_force(tmp_path, cloud1):
    fr = h2o.H2OFrame_from_python({"x": [0.5, 1.5, 2.5]})
    mask = fr.apply(lambda c: c > 1, axis=0)
    np.testing.assert_allclose(mask.vec("x").numeric_np(), [0, 1, 1])
    # save_model honors force
    from h2o3_tpu.estimators import H2OKMeansEstimator
    km = H2OKMeansEstimator(k=2, seed=1)
    km.train(x=["x"], training_frame=fr)
    p = h2o.save_model(km, str(tmp_path))
    with pytest.raises(FileExistsError):
        h2o.save_model(km, str(tmp_path))
    h2o.save_model(km, str(tmp_path), force=True)


def test_csv_roundtrip_quoted_cells(tmp_path, cloud1):
    """frame_to_csv emits RFC-4180 quoting; the parser must read it back
    (quoted cells may contain the separator)."""
    import h2o3_tpu as h2o
    from h2o3_tpu.frame.frame import frame_to_csv

    fr = h2o.H2OFrame_from_python(
        {"s": np.asarray(["a,b", 'say "hi"', "plain"], dtype=object),
         "x": [1.5, 2.5, 3.5]})
    text = frame_to_csv(fr)
    p = tmp_path / "q.csv"
    p.write_text(text)
    back = h2o.import_file(str(p))
    assert back.nrow == 3 and back.ncol == 2
    v = back.vec("s")
    vals = [v.domain[c] if v.type == "enum" else c
            for c in (np.asarray(v.data) if v.type == "enum"
                      else v.to_numpy())]
    assert vals[0] == "a,b" and vals[1] == 'say "hi"' 
    np.testing.assert_allclose(back.vec("x").numeric_np(), [1.5, 2.5, 3.5])
