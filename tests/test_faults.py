"""Fault-injected runtime hardening (ISSUE 5): retry-policy semantics, the
deterministic injection registry, persist/client/trainpool wiring, grid
kill-and-resume, AutoML checkpoint resume, serving scorer quarantine +
CPU-fallback circuit breaker, the /3/Faults REST surface, and the slow
chaos smoke (loadgen under 1% injected scorer faults)."""

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.runtime import faults, retry, trainpool
from h2o3_tpu.runtime.dkv import DKV

from conftest import make_classification


@pytest.fixture(autouse=True)
def _fresh_faults():
    faults.reset()
    retry.reset()
    trainpool.reset()
    yield
    faults.reset()
    retry.reset()


def _cls_frame(n=300, f=4, seed=0):
    X, y = make_classification(n, f, seed)
    return Frame.from_numpy(
        np.column_stack([X, y]), names=[f"x{i}" for i in range(f)] + ["y"]
    ).asfactor("y")


# -- retry policy -------------------------------------------------------------

def test_retry_transient_recovers_and_counts():
    calls = []
    pol = retry.RetryPolicy(name="t1", max_attempts=4, base_delay_s=1e-4,
                            max_delay_s=1e-3, deadline_s=5.0)

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionError("drop")
        return 42

    assert pol.call(flaky) == 42
    assert len(calls) == 3
    s = retry.snapshot()["policies"]["t1"]
    assert s["retries"] == 2 and s["recovered"] == 1


def test_retry_permanent_fails_fast():
    calls = []
    pol = retry.RetryPolicy(name="t2", max_attempts=4, base_delay_s=1e-4)

    def bad():
        calls.append(1)
        raise ValueError("semantic")

    with pytest.raises(ValueError):
        pol.call(bad)
    assert len(calls) == 1          # no retry on permanent errors
    assert retry.snapshot()["policies"]["t2"]["permanent_failures"] == 1


def test_retry_attempts_and_deadline_bound():
    pol = retry.RetryPolicy(name="t3", max_attempts=3, base_delay_s=1e-4,
                            max_delay_s=1e-3, deadline_s=5.0)
    calls = []

    def always():
        calls.append(1)
        raise ConnectionError("down")

    with pytest.raises(ConnectionError):
        pol.call(always)
    assert len(calls) == 3
    assert retry.snapshot()["policies"]["t3"]["attempts_exhausted"] == 1
    # a deadline of ~zero refuses even the first backoff sleep
    pol2 = retry.RetryPolicy(name="t3b", max_attempts=10, base_delay_s=0.05,
                             deadline_s=0.01)
    calls.clear()
    with pytest.raises(ConnectionError):
        pol2.call(always)
    assert len(calls) == 1
    assert retry.snapshot()["policies"]["t3b"]["deadline_exceeded"] == 1


def test_retry_budget_exhaustion_degrades_to_fail_fast():
    budget = retry.RetryBudget(capacity=2, refill_per_s=0.0)
    pol = retry.RetryPolicy(name="t4", max_attempts=10, base_delay_s=1e-4,
                            deadline_s=5.0, budget=budget)
    calls = []

    def always():
        calls.append(1)
        raise ConnectionError("down")

    with pytest.raises(ConnectionError):
        pol.call(always)
    assert len(calls) == 3          # 1 try + 2 budgeted retries, then stop
    assert retry.snapshot()["policies"]["t4"]["budget_exhausted"] == 1


def test_retry_backoff_is_capped_decorrelated_jitter():
    pol = retry.RetryPolicy(name="t5", base_delay_s=0.1, max_delay_s=0.5)
    d = pol.base_delay_s
    for _ in range(50):
        d = pol.next_delay(d)
        assert pol.base_delay_s <= d <= pol.max_delay_s + 1e-9


def test_device_error_classification():
    assert retry.is_device_error(faults.InjectedDeviceError("x"))
    assert retry.is_transient(faults.InjectedDeviceError("x"))
    assert not retry.is_device_error(ValueError("x"))
    assert not retry.is_transient(faults.InjectedCrash("x"))
    assert not retry.is_transient(FileNotFoundError("gone"))


# -- injection registry -------------------------------------------------------

def test_faults_default_off_and_reset():
    snap = faults.snapshot()
    assert snap["active"] is False and snap["points"] == []
    faults.check("persist.open")    # unarmed: no-op
    faults.arm("persist.open", count=1)
    assert faults.active()
    faults.reset()
    assert not faults.active()


def test_faults_seeded_rate_is_deterministic():
    def fire_seq(seed):
        faults.reset()
        faults.arm("client.request", error="conn", rate=0.3, seed=seed)
        seq = []
        for _ in range(40):
            try:
                faults.check("client.request")
                seq.append(0)
            except ConnectionError:
                seq.append(1)
        return seq

    a, b = fire_seq(7), fire_seq(7)
    assert a == b and 0 < sum(a) < 40
    assert fire_seq(8) != a


def test_faults_count_fires_first_n_then_clears():
    faults.arm("persist.open", error="io", count=2)
    fired = 0
    for _ in range(5):
        try:
            faults.check("persist.open")
        except IOError:
            fired += 1
    assert fired == 2
    assert faults.snapshot()["points"][0]["fires"] == 2


def test_faults_env_arming(monkeypatch):
    monkeypatch.setenv("H2O3_FAULT_SERVING_SCORER",
                       "error=device,rate=0.5,seed=3")
    faults._env_parse()
    pt = {p["point"]: p for p in faults.snapshot()["points"]}
    assert pt["serving.scorer"]["error"] == "device"
    assert pt["serving.scorer"]["rate"] == 0.5


# -- persist wiring -----------------------------------------------------------

def test_persist_open_retry_then_succeed(tmp_path):
    from h2o3_tpu.runtime import persist

    p = tmp_path / "x.txt"
    p.write_text("payload")
    faults.arm("persist.open", error="io", count=2)
    with persist.Persist().open(str(p)) as f:
        assert f.read() == b"payload"
    assert faults.snapshot()["points"][0]["fires"] == 2
    assert retry.snapshot()["policies"]["persist"]["retries"] == 2


def test_persist_open_permanent_not_retried(tmp_path):
    from h2o3_tpu.runtime import persist

    with pytest.raises(FileNotFoundError):
        persist.Persist().open(str(tmp_path / "missing.csv"))
    assert retry.snapshot()["policies"]["persist"]["retries"] == 0


class _HttpStub(BaseHTTPRequestHandler):
    """Scriptable origin for persist/client tests."""

    content = b"abc,def\n1,2\n"
    no_content_length = False

    def _head(self, code=200, headers=()):
        self.send_response(code)
        for k, v in headers:
            self.send_header(k, v)
        self.end_headers()

    def do_HEAD(self):
        if self.no_content_length:
            self._head(200)
        else:
            self._head(200, [("Content-Length", str(len(self.content)))])

    def do_GET(self):
        body = self.content
        self._head(200, [("Content-Length", str(len(body)))])
        self.wfile.write(body)

    def log_message(self, *a):
        pass


@pytest.fixture()
def http_stub():
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _HttpStub)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()
    srv.server_close()


def test_http_persist_size_raises_without_content_length(http_stub):
    from h2o3_tpu.runtime.persist import HttpPersist

    uri = http_stub + "/data.csv"
    assert HttpPersist().size(uri) == len(_HttpStub.content)
    _HttpStub.no_content_length = True
    try:
        with pytest.raises(IOError, match="data.csv.*Content-Length"):
            HttpPersist().size(uri)
    finally:
        _HttpStub.no_content_length = False


def test_http_persist_exists_propagates_bad_uri():
    from h2o3_tpu.runtime.persist import HttpPersist

    # network-shaped failures stay False ...
    assert HttpPersist().exists("http://127.0.0.1:1/nope") is False
    # ... but a malformed URI is a caller bug and must raise, not
    # masquerade as "does not exist"
    with pytest.raises(ValueError):
        HttpPersist().exists("http://[bad_ipv6/csv")


def test_http_persist_read_resumes_after_drop(http_stub):
    from h2o3_tpu.runtime.persist import HttpPersist

    uri = http_stub + "/data.csv"
    faults.arm("persist.read", error="io", count=1)
    with HttpPersist().open(uri) as f:
        assert f.read() == _HttpStub.content
    assert faults.snapshot()["points"][0]["fires"] == 1


def test_http_stream_reopen_failure_does_not_truncate(http_stub, monkeypatch):
    """If the Range-resume reopen ITSELF fails transiently, the next retry
    must reopen again — falling back to the dead original response would
    read b'' and silently truncate the body (closed http responses return
    EOF, not an error)."""
    from h2o3_tpu.runtime.persist import HttpPersist, _ResumingHttpStream

    uri = http_stub + "/data.csv"
    f = HttpPersist().open(uri)
    assert f.read(4) == _HttpStub.content[:4]

    resp = f._resp
    real_read, state = resp.read, {"dropped": False}

    def drop_once(n=-1):
        if not state["dropped"]:
            state["dropped"] = True
            resp.close()
            raise ConnectionResetError("mid-body drop")
        return real_read(n)

    resp.read = drop_once
    real_reopen, reopens = _ResumingHttpStream._reopen, []

    def flaky_reopen(self):
        reopens.append(1)
        if len(reopens) == 1:
            raise ConnectionError("reopen refused")
        return real_reopen(self)

    monkeypatch.setattr(_ResumingHttpStream, "_reopen", flaky_reopen)
    assert f.read() == _HttpStub.content[4:]
    assert len(reopens) == 2


def test_http_stream_is_iterable_and_tracks_position(http_stub):
    """The raw HTTPResponse surface HttpPersist.open used to return is
    iterable; the resuming wrapper must keep that, and line reads must
    advance the resume offset or a later Range request re-serves bytes."""
    from h2o3_tpu.runtime.persist import HttpPersist

    uri = http_stub + "/data.csv"
    with HttpPersist().open(uri) as f:
        assert list(f) == [b"abc,def\n", b"1,2\n"]
        assert f._pos == len(_HttpStub.content)
    with HttpPersist().open(uri) as f:
        assert f.readline() == b"abc,def\n"
        assert f.read() == b"1,2\n"     # mixed readline+read stays aligned


def test_http_stream_multifault_streak_at_range_boundary(http_stub):
    """ISSUE 20 satellite: a STREAK of faults inside one read() — two
    injected drops back-to-back while the response is already dead — must
    resume with a Range reopen at the exact byte boundary, not truncate
    or re-serve bytes (the stub ignores Range, so the skip-read path is
    exercised too)."""
    from h2o3_tpu.runtime.persist import HttpPersist

    uri = http_stub + "/data.csv"
    f = HttpPersist().open(uri)
    assert f.read(4) == _HttpStub.content[:4]
    f._dead = True                  # the prior read marked the resp dead
    faults.arm("persist.read", error="io", count=2)
    assert f.read() == _HttpStub.content[4:]   # exact tail, no overlap
    assert f._pos == len(_HttpStub.content)
    assert faults.snapshot()["points"][0]["fires"] == 2


def test_file_open_resuming_multifault_streak_in_one_read(tmp_path):
    """Same discipline on the file backend: two injected faults PLUS a
    genuinely-dead file handle inside a single read() — three failures,
    recovered on the policy's last attempt by a reopen+seek to the exact
    offset."""
    from h2o3_tpu.runtime.persist import for_uri

    payload = bytes(range(256)) * 8
    p = tmp_path / "blob.bin"
    p.write_bytes(payload)
    s = for_uri(str(p)).open_resuming(str(p))
    assert s.read(100) == payload[:100]
    s._fh.close()                   # next attempt reads a closed handle
    faults.arm("persist.read", error="io", count=2)
    assert s.read() == payload[100:]
    assert faults.snapshot()["points"][0]["fires"] == 2
    s.close()


def test_file_open_resuming_streak_exhaustion_keeps_exact_offset(tmp_path):
    """A streak LONGER than the retry policy's attempts fails the read —
    but the stream's offset must not move, so the caller's own retry
    resumes at the exact boundary with no lost or duplicated bytes."""
    from h2o3_tpu.runtime.persist import for_uri

    payload = b"0123456789" * 50
    p = tmp_path / "blob.bin"
    p.write_bytes(payload)
    s = for_uri(str(p)).open_resuming(str(p))
    assert s.read(7) == payload[:7]
    faults.arm("persist.read", error="io", count=100)
    with pytest.raises(IOError):
        s.read()
    faults.reset()
    assert s.read() == payload[7:]  # resumes at byte 7 exactly
    s.close()


# -- client wiring ------------------------------------------------------------

class _RetryAfterStub(BaseHTTPRequestHandler):
    """First request is shed with 429 + Retry-After, the second served."""

    hits = []

    def do_GET(self):
        self.hits.append(time.monotonic())
        if len(self.hits) == 1:
            body = b'{"msg": "shed"}'
            self.send_response(429)
            self.send_header("Retry-After", "0")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        body = json.dumps(dict(status="healthy")).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    do_POST = do_GET

    def log_message(self, *a):
        pass


def test_client_honors_retry_after_429():
    from h2o3_tpu.client import H2OConnection

    _RetryAfterStub.hits = []
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _RetryAfterStub)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        conn = H2OConnection(f"http://127.0.0.1:{srv.server_address[1]}")
        out = conn.get("/3/Ping")
        assert out["status"] == "healthy"
        assert len(_RetryAfterStub.hits) == 2       # shed once, then served
        s = retry.snapshot()["policies"]["client"]
        assert s["retries"] == 1 and s["recovered"] == 1
        # POSTs honor Retry-After too: admission shed them before acting
        _RetryAfterStub.hits = []
        assert conn.post("/3/Ping")["status"] == "healthy"
        assert len(_RetryAfterStub.hits) == 2
    finally:
        srv.shutdown()
        srv.server_close()


def test_client_connection_drop_retries_get_not_post(http_stub):
    from h2o3_tpu.client import H2OConnection, H2OConnectionError

    conn = H2OConnection(http_stub)
    conn.request = conn.request      # use the real path
    faults.arm("client.request", error="conn", count=1)
    out = conn.request("GET", "/data.csv", raw=True)
    assert out == _HttpStub.content                # GET retried the drop
    faults.reset()
    faults.arm("client.request", error="conn", count=1)
    with pytest.raises(H2OConnectionError):
        conn.request("POST", "/data.csv")          # POST must not re-send


def test_wait_for_job_timeout_cancels_server_side():
    from h2o3_tpu.client import H2OConnection

    seen = []

    class _Jobs(BaseHTTPRequestHandler):
        def do_GET(self):
            body = json.dumps(dict(jobs=[dict(
                status="RUNNING", progress=0.1, warnings=[])])).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_POST(self):
            seen.append(self.path)
            body = b"{}"
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = ThreadingHTTPServer(("127.0.0.1", 0), _Jobs)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        conn = H2OConnection(f"http://127.0.0.1:{srv.server_address[1]}")
        with pytest.raises(TimeoutError):
            conn.wait_for_job("j1", poll=0.01, timeout=0.05)
        assert "/3/Jobs/j1/cancel" in seen   # no stranded server-side work
    finally:
        srv.shutdown()
        srv.server_close()


# -- trainpool hardening ------------------------------------------------------

def test_candidate_transient_retry_vs_permanent_fail_fast():
    attempts = {"t": 0, "p": 0}

    def transient(job):
        attempts["t"] += 1
        if attempts["t"] == 1:
            raise ConnectionError("flaky backend")
        return "ok"

    def permanent(job):
        attempts["p"] += 1
        raise ValueError("bad params")

    recs = trainpool.TrainPool(1, label="hx", candidate_retries=2).run(
        [("a", transient), ("b", permanent)])
    assert recs[0].status == "done" and recs[0].retries == 1
    assert recs[1].status == "failed" and recs[1].retries == 0
    assert attempts == {"t": 2, "p": 1}
    tot = trainpool.snapshot()["totals"]
    assert tot["retried"] == 1 and tot["failed"] == 1


def test_candidate_injected_fault_point_is_retried():
    faults.arm("trainpool.candidate", error="conn", count=1)
    recs = trainpool.TrainPool(1, label="hf", candidate_retries=1).run(
        [("a", lambda job: "built")])
    assert recs[0].status == "done" and recs[0].retries == 1


def test_candidate_watchdog_deadline_cancels_runaway():
    def runaway(job):
        for _ in range(1000):
            job.check_cancelled()      # scoring-boundary safe points
            time.sleep(0.01)
        return "never"

    pool = trainpool.TrainPool(1, label="wd", candidate_retries=0,
                               candidate_deadline_s=0.15)
    t0 = time.monotonic()
    recs = pool.run([("slow", runaway)])
    assert time.monotonic() - t0 < 5.0
    assert recs[0].status == "failed"
    assert "watchdog deadline" in recs[0].error
    assert trainpool.snapshot()["totals"]["watchdog_cancelled"] == 1


def test_failed_candidate_partial_model_cleaned_from_dkv(cloud1):
    """Extends the DKV leak discipline: a candidate that fails AFTER its
    model landed in the DKV (e.g. during post-train checkpointing) must
    not leak the half-finished model into h2o.ls."""
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

    fr = _cls_frame(200, 3, seed=3)
    built = {}

    def fn(job):
        est = H2OGradientBoostingEstimator(ntrees=2, max_depth=2, seed=1)
        est._external_job = job
        est.train(y="y", training_frame=fr)
        built["id"] = est.model_id
        assert DKV.get(est.model_id) is not None
        raise ValueError("post-train step exploded")

    recs = trainpool.TrainPool(1, label="leak").run([("c", fn)])
    assert recs[0].status == "failed"
    assert DKV.get(built["id"]) is None    # partial artifact removed
    DKV.remove(fr.key)


# -- grid: transient crash + kill-and-resume ---------------------------------

_HYPER = {"max_depth": [2, 3], "learn_rate": [0.1, 0.2]}


def _grid(fr, grid_id, recovery_dir=None):
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
    from h2o3_tpu.models.grid import H2OGridSearch

    return H2OGridSearch(
        H2OGradientBoostingEstimator(ntrees=3, seed=7), dict(_HYPER),
        grid_id=grid_id, recovery_dir=recovery_dir)


def _aucs(gs):
    return sorted(round(float(m.auc()), 6) for m in gs.models)


def test_grid_with_transient_crash_matches_clean_leaderboard(cloud1):
    fr = _cls_frame(300, 4, seed=5)
    clean = _grid(fr, "gclean")
    clean.train(y="y", training_frame=fr)
    assert len(clean.models) == 4

    faults.arm("trainpool.candidate", error="conn", count=1)
    crashy = _grid(fr, "gcrash")
    crashy.train(y="y", training_frame=fr)
    assert not crashy.failed
    assert _aucs(crashy) == _aucs(clean)   # headline behavior (a)
    assert trainpool.snapshot()["totals"]["retried"] == 1


def test_grid_kill_and_resume_retrains_zero_completed(cloud1, tmp_path):
    fr = _cls_frame(300, 4, seed=5)
    rdir = str(tmp_path / "rec")
    clean = _grid(fr, "gref")
    clean.train(y="y", training_frame=fr)

    g1 = _grid(fr, "gres", recovery_dir=rdir)
    g1.train(y="y", training_frame=fr)
    # simulate the kill: the state a sweep killed after 2 combos leaves on
    # disk is exactly the full state minus the later records + artifacts
    sp = os.path.join(rdir, "gres.grid.json")
    with open(sp) as f:
        state = json.load(f)
    for d in state["done_combos"][2:]:
        os.remove(os.path.join(rdir, d["file"]))
    state["done_combos"] = state["done_combos"][:2]
    with open(sp, "w") as f:
        json.dump(state, f)

    trainpool.reset()
    g2 = _grid(fr, "gres", recovery_dir=rdir)   # re-submitted, same params
    g2.train(y="y", training_frame=fr)
    tot = trainpool.snapshot()["totals"]
    assert tot["resumed"] == 2                  # checkpoint counters pinned
    assert tot["submitted"] == 2                # headline behavior (b):
    assert tot["completed"] == 2                # zero completed retrained
    assert len(g2.models) == 4
    assert _aucs(g2) == _aucs(clean)


def test_grid_resume_with_tuple_hyperparams(cloud1, tmp_path):
    """JSON round-trips tuples to lists: the done-combo filter must compare
    in JSON space or a resumed sweep retrains every completed combo (and
    keeps the restored shims as duplicates)."""
    from h2o3_tpu.models.deeplearning import H2ODeepLearningEstimator
    from h2o3_tpu.models.grid import H2OGridSearch

    fr = _cls_frame(120, 3, seed=8)
    rdir = str(tmp_path / "rect")

    def mk():
        return H2OGridSearch(
            H2ODeepLearningEstimator(epochs=2, seed=3),
            {"hidden": [(4,), (6,)]}, grid_id="gtup", recovery_dir=rdir)

    g1 = mk()
    g1.train(y="y", training_frame=fr)
    assert len(g1.models) == 2

    trainpool.reset()
    g2 = mk()                       # re-submitted after an end-of-sweep kill
    g2.train(y="y", training_frame=fr)
    tot = trainpool.snapshot()["totals"]
    assert tot["resumed"] == 2 and tot["submitted"] == 0
    assert len(g2.models) == 2      # no duplicate shim + retrain pairs


def test_grid_resume_ignores_other_datasets_state(cloud1, tmp_path):
    """Same grid_id + hyper space, DIFFERENT training data: the data
    fingerprint must block restore (the models belong to the other data)."""
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
    from h2o3_tpu.models.grid import H2OGridSearch

    rdir = str(tmp_path / "recfp")

    def mk():
        return H2OGridSearch(H2OGradientBoostingEstimator(ntrees=3, seed=7),
                             {"max_depth": [2]}, grid_id="gfp",
                             recovery_dir=rdir)

    frA = _cls_frame(200, 4, seed=5)
    mk().train(y="y", training_frame=frA)

    frB = _cls_frame(150, 3, seed=6)
    trainpool.reset()
    g2 = mk()
    g2.train(y="y", training_frame=frB)
    tot = trainpool.snapshot()["totals"]
    assert tot["resumed"] == 0 and tot["submitted"] == 1
    assert len(g2.models) == 1
    DKV.remove(frA.key)
    DKV.remove(frB.key)


def test_grid_resume_missing_artifact_retrains(cloud1, tmp_path):
    """A done-combo record whose artifact file is gone must RETRAIN the
    combo — keeping the record would skip training while restoring
    nothing, and the model silently vanishes from the grid."""
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
    from h2o3_tpu.models.grid import H2OGridSearch

    fr = _cls_frame(200, 4, seed=5)
    rdir = str(tmp_path / "recgone")

    def mk():
        return H2OGridSearch(H2OGradientBoostingEstimator(ntrees=3, seed=7),
                             {"max_depth": [2]}, grid_id="ggone",
                             recovery_dir=rdir)

    g1 = mk()
    g1.train(y="y", training_frame=fr)
    os.remove(os.path.join(rdir, g1._done_combos[0]["file"]))

    trainpool.reset()
    g2 = mk()
    g2.train(y="y", training_frame=fr)
    tot = trainpool.snapshot()["totals"]
    assert tot["resumed"] == 0 and tot["submitted"] == 1
    assert len(g2.models) == 1
    DKV.remove(fr.key)


def test_grid_resume_ignores_mismatched_state(cloud1, tmp_path):
    fr = _cls_frame(250, 4, seed=6)
    rdir = str(tmp_path / "rec2")
    g1 = _grid(fr, "gmix", recovery_dir=rdir)
    g1.train(y="y", training_frame=fr)
    # same grid_id, DIFFERENT hyper space: the state is someone else's
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
    from h2o3_tpu.models.grid import H2OGridSearch

    trainpool.reset()
    g2 = H2OGridSearch(H2OGradientBoostingEstimator(ntrees=3, seed=7),
                       {"max_depth": [2]}, grid_id="gmix",
                       recovery_dir=str(tmp_path / "rec2"))
    g2.train(y="y", training_frame=fr)
    assert trainpool.snapshot()["totals"]["resumed"] == 0
    assert len(g2.models) == 1


# -- AutoML checkpoint resume -------------------------------------------------

def test_automl_checkpoint_resume_skips_completed(cloud1, tmp_path):
    from h2o3_tpu.automl.automl import H2OAutoML

    X, y = make_classification(200, 4, seed=9)
    fr = Frame.from_numpy(
        np.column_stack([X, y]), names=["a", "b", "c", "d", "y"]
    ).asfactor("y")
    ckdir = str(tmp_path / "aml")

    def mk(max_models):
        return H2OAutoML(max_models=max_models, seed=1, nfolds=2,
                         include_algos=["GBM"], project_name="amlr",
                         checkpoint_dir=ckdir)

    a1 = mk(1)
    a1.train(y="y", training_frame=fr)
    assert len(a1.leaderboard) == 1
    row1 = {k: a1.leaderboard[0][k] for k in ("model_id", "auc")}

    trainpool.reset()
    a2 = mk(2)                                # killed-then-resumed sweep
    a2.train(y="y", training_frame=fr)
    tot = trainpool.snapshot()["totals"]
    assert tot["resumed"] == 1                # GBM_1 restored, not retrained
    assert tot["submitted"] == 1              # only GBM_2 trained
    assert len(a2.leaderboard) == 2
    restored = [r for r in a2.leaderboard.rows
                if r["model_id"] == row1["model_id"]]
    assert restored and restored[0]["auc"] == pytest.approx(row1["auc"])
    # the restored entry scores through its saved artifact
    shim = restored[0]["_est"]
    assert shim.predict(fr).nrow == fr.nrow
    DKV.remove(fr.key)


def test_sweep_checkpoint_fingerprint_guard(tmp_path):
    from h2o3_tpu.runtime.trainpool import SweepCheckpoint

    fp = dict(y="y", nrow=100)
    c1 = SweepCheckpoint(str(tmp_path), "s", fingerprint=fp)
    c1.mark("GBM_1", dict(model_id="m1"))
    # same identity → records restore
    assert SweepCheckpoint(str(tmp_path), "s",
                           fingerprint=dict(fp)).completed("GBM_1")
    # different dataset/response → someone else's sweep: ignored
    c3 = SweepCheckpoint(str(tmp_path), "s",
                         fingerprint=dict(y="other", nrow=100))
    assert c3.completed("GBM_1") is None
    assert len(c3) == 0


def test_automl_checkpoint_missing_artifact_retrains(cloud1, tmp_path):
    """A checkpoint record whose artifact is gone (or was never exported)
    must retrain its candidate — restoring it would put an unscorable shim
    on the leaderboard that crashes predict() far from the cause."""
    from h2o3_tpu.automl.automl import H2OAutoML

    fr = _cls_frame(200, 4, seed=9)
    ckdir = str(tmp_path / "amlgone")

    def mk():
        return H2OAutoML(max_models=1, seed=1, nfolds=2,
                         include_algos=["GBM"], project_name="amlgone",
                         checkpoint_dir=ckdir)

    a1 = mk()
    a1.train(y="y", training_frame=fr)
    arts = [f for f in os.listdir(ckdir) if f.endswith(".h2o3")]
    assert arts
    for f in arts:
        os.remove(os.path.join(ckdir, f))

    trainpool.reset()
    a2 = mk()
    a2.train(y="y", training_frame=fr)
    tot = trainpool.snapshot()["totals"]
    assert tot["resumed"] == 0 and tot["submitted"] == 1
    assert a2.leader.predict(fr).nrow == fr.nrow    # leader is scorable
    DKV.remove(fr.key)


def test_automl_checkpoint_ignores_other_datasets_records(cloud1, tmp_path):
    """Candidate names (GBM_1, ...) are constants: without the run
    fingerprint a checkpoint written for dataset A would silently restore
    A's models — and serve A's metrics — under a run on dataset B."""
    from h2o3_tpu.automl.automl import H2OAutoML

    ckdir = str(tmp_path / "amlfp")

    def mk():
        return H2OAutoML(max_models=1, seed=1, nfolds=2,
                         include_algos=["GBM"], project_name="amlfp",
                         checkpoint_dir=ckdir)

    X, y = make_classification(200, 4, seed=9)
    frA = Frame.from_numpy(np.column_stack([X, y]),
                           names=["a", "b", "c", "d", "y"]).asfactor("y")
    mk().train(y="y", training_frame=frA)

    # same project + checkpoint_dir, DIFFERENT data: records must not apply
    X2, y2 = make_classification(150, 3, seed=11)
    frB = Frame.from_numpy(np.column_stack([X2, y2]),
                           names=["p", "q", "r", "y"]).asfactor("y")
    trainpool.reset()
    a2 = mk()
    a2.train(y="y", training_frame=frB)
    tot = trainpool.snapshot()["totals"]
    assert tot["resumed"] == 0 and tot["submitted"] == 1
    DKV.remove(frA.key)
    DKV.remove(frB.key)


# -- serving failover ---------------------------------------------------------

def _serving_model(fr):
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

    est = H2OGradientBoostingEstimator(ntrees=3, max_depth=2, seed=1)
    est.train(y="y", training_frame=fr)
    return est.model


def test_scorer_quarantine_rebuild_and_cpu_fallback(cloud1):
    from h2o3_tpu.serving import ScoringEngine
    from h2o3_tpu.serving.config import ServingConfig

    fr = _cls_frame(200, 4, seed=11)
    model = _serving_model(fr)
    eng = ScoringEngine(ServingConfig(max_wait_ms=1.0, breaker_reset_s=0.3))
    test = Frame({n: fr.vec(n) for n in fr.names if n != "y"})
    clean = eng.score("m1", model, test).vec("predict").to_numpy()

    faults.arm("serving.scorer", error="device", rate=1.0)
    # headline behavior (c): every request is served via quarantine /
    # fallback — no unhandled error reaches the caller
    for _ in range(3):
        out = eng.score("m1", model, test).vec("predict").to_numpy()
        assert (out == clean).all()
    t = eng.snapshot()["totals"]
    assert t["errors"] == 0
    assert t["scorer_faults"] >= 2
    assert t["quarantines"] == 1          # quarantined once, then breaker
    assert t["breaker_opens"] == 1
    assert t["fallback_scores"] >= 3
    st = eng.snapshot()["failover"]["breakers"][0]
    assert st["state"] == "open"

    # fault clears → the half-open probe recovers the primary path
    faults.reset()
    time.sleep(0.35)
    eng.score("m1", model, test)
    assert eng.snapshot()["failover"]["breakers"][0]["state"] == "closed"
    t2 = eng.snapshot()["totals"]
    eng.score("m1", model, test)
    t3 = eng.snapshot()["totals"]
    assert t3["fallback_scores"] == t2["fallback_scores"]   # primary again
    eng.shutdown()
    DKV.remove(fr.key)


def test_scorer_transient_fault_rebuild_once_no_breaker(cloud1):
    """One bad score then a healthy rebuild: quarantine + rebuild, breaker
    stays closed, nothing falls back."""
    from h2o3_tpu.serving import ScoringEngine
    from h2o3_tpu.serving.config import ServingConfig

    fr = _cls_frame(150, 4, seed=12)
    model = _serving_model(fr)
    eng = ScoringEngine(ServingConfig(max_wait_ms=1.0))
    test = Frame({n: fr.vec(n) for n in fr.names if n != "y"})
    faults.arm("serving.scorer", error="device", count=1)
    out = eng.score("m2", model, test)
    assert out.nrow == test.nrow
    t = eng.snapshot()["totals"]
    assert t["quarantines"] == 1 and t["scorer_rebuilds"] == 1
    assert t["breaker_opens"] == 0 and t["fallback_scores"] == 0
    eng.shutdown()
    DKV.remove(fr.key)


def test_non_device_scoring_error_still_fails_the_request(cloud1):
    """Failover is for SCORER faults; a bad request keeps its 4xx-shaped
    error instead of being silently served by the fallback."""
    from h2o3_tpu.serving import ScoringEngine
    from h2o3_tpu.serving.config import ServingConfig

    fr = _cls_frame(100, 4, seed=13)
    model = _serving_model(fr)
    eng = ScoringEngine(ServingConfig(max_wait_ms=1.0))
    bad = Frame({"wrong": fr.vec("x0")})
    with pytest.raises(Exception):
        eng.score("m3", model, bad)
    t = eng.snapshot()["totals"]
    assert t["quarantines"] == 0 and t["fallback_scores"] == 0
    assert t["errors"] == 1
    eng.shutdown()
    DKV.remove(fr.key)


def test_half_open_probe_aborted_by_bad_request_does_not_wedge(cloud1):
    """A half-open probe that dies on the REQUEST's own bad rows must give
    the probe slot back: the next healthy request re-probes and closes the
    breaker instead of the model being pinned to the fallback forever."""
    from h2o3_tpu.serving import ScoringEngine
    from h2o3_tpu.serving.config import ServingConfig

    fr = _cls_frame(150, 4, seed=14)
    model = _serving_model(fr)
    eng = ScoringEngine(ServingConfig(max_wait_ms=1.0, breaker_reset_s=0.2))
    test = Frame({n: fr.vec(n) for n in fr.names if n != "y"})
    faults.arm("serving.scorer", error="device", rate=1.0)
    eng.score("m4", model, test)          # opens the breaker
    faults.reset()                        # device "recovers"
    time.sleep(0.25)
    bad = Frame({"wrong": fr.vec("x0")})
    with pytest.raises(Exception):
        eng.score("m4", model, bad)       # elected prober, dies on rows
    # a later healthy request must still be able to probe + close
    eng.score("m4", model, test)
    assert eng.snapshot()["failover"]["breakers"][0]["state"] == "closed"
    eng.shutdown()
    DKV.remove(fr.key)


# -- mesh re-init -------------------------------------------------------------

def test_mesh_reinit_idempotent_and_conflict_detection(cloud1):
    from h2o3_tpu.parallel import mesh

    prior = mesh._dist_topology
    try:
        # simulate an already-initialized distributed runtime
        mesh._dist_topology = ("10.0.0.1:1234", 2, 0)
        live = mesh.cloud()
        again = mesh.init(coordinator_address="10.0.0.1:1234",
                          num_processes=2, process_id=0)
        assert again is live              # idempotent: no re-initialize
        with pytest.raises(RuntimeError, match="conflicts"):
            mesh.init(coordinator_address="10.0.0.9:9999",
                      num_processes=4, process_id=1)
    finally:
        mesh._dist_topology = prior


# -- REST surfaces ------------------------------------------------------------

def _rest(srv, method, path, **params):
    import urllib.parse
    import urllib.request

    url = f"http://127.0.0.1:{srv.port}{path}"
    data = urllib.parse.urlencode(params).encode() if params else None
    req = urllib.request.Request(url, data=data, method=method)
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read())


def test_faults_rest_toggle_and_metrics_surfaces(cloud1):
    from h2o3_tpu.rest.server import start_server

    srv = start_server(port=0)
    try:
        out = _rest(srv, "POST", "/3/Faults", point="serving.scorer",
                    error="device", rate=0.25, seed=9)
        assert out["point"] == "serving.scorer" and out["rate"] == 0.25
        got = _rest(srv, "GET", "/3/Faults")
        assert got["faults"]["active"] is True
        assert got["faults"]["points"][0]["point"] == "serving.scorer"
        # training metrics carry the hardening counters + retry section
        tm = _rest(srv, "GET", "/3/Training/metrics")
        assert "retried" in tm["totals"] and "resumed" in tm["totals"]
        assert "policies" in tm["retry"]
        assert tm["faults"]["active"] is True
        # profiler folds the fault/retry document in
        prof = _rest(srv, "GET", "/3/Profiler")
        assert "faults" in prof and "retry" in prof["faults"]
        out = _rest(srv, "DELETE", "/3/Faults?point=serving.scorer")
        assert out["disarmed"] is True
        assert _rest(srv, "GET", "/3/Faults")["faults"]["active"] is False
    finally:
        srv.stop()


def test_serving_metrics_expose_failover_section(cloud1):
    from h2o3_tpu.rest.server import start_server
    from h2o3_tpu.serving import reset_engine

    reset_engine()
    srv = start_server(port=0)
    try:
        sm = _rest(srv, "GET", "/3/Serving/metrics")
        assert "failover" in sm
        assert sm["failover"]["cpu_fallback_enabled"] is True
        assert "breaker_reset_s" in sm["config"] or True
    finally:
        srv.stop()


# -- chaos smoke --------------------------------------------------------------

@pytest.mark.slow
def test_chaos_smoke_loadgen_under_injected_faults(cloud1):
    """1% injected scorer device-faults under closed-loop load: p99 stays
    finite and no hard errors escape (the BENCH_CONFIG=chaos acceptance)."""
    import sys

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "deploy"))
    from loadgen import run_load

    from h2o3_tpu.rest.server import start_server
    from h2o3_tpu.serving import reset_engine

    fr = _cls_frame(1000, 4, seed=21)
    model = _serving_model(fr)
    DKV.put("chaos_m", model)
    score = Frame({n: fr.vec(n) for n in fr.names if n != "y"})
    score.key = "chaos_f"
    DKV.put(score.key, score)
    reset_engine()
    srv = start_server(port=0)
    try:
        run_load("127.0.0.1", srv.port, "chaos_m", "chaos_f",
                 threads=2, requests=2)        # warm before arming
        faults.arm("serving.scorer", error="device", rate=0.01, seed=1)
        stats = run_load("127.0.0.1", srv.port, "chaos_m", "chaos_f",
                         threads=4, requests=25)
        assert stats["errors"] == 0
        assert stats["completed"] == 100
        assert stats["p99_ms"] is not None and np.isfinite(stats["p99_ms"])
    finally:
        faults.reset()
        srv.stop()
        DKV.remove("chaos_m")
        DKV.remove("chaos_f")
        DKV.remove(fr.key)
