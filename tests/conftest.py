"""Test cloud bootstrap — the analog of `water.TestUtil` +
`@RunWith(H2ORunner)` spinning an in-process cloud (SURVEY.md §4): an
8-virtual-device CPU mesh stands in for an 8-host TPU pod, so every
distributed code path (shard_map + psum) runs the real collective lowering
on loopback, mirroring the reference's multi-JVM-on-one-host clouds."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_test_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.1")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# this image's sitecustomize registers an `axon` TPU backend and pins
# jax_platforms programmatically — env alone doesn't win; config does
jax.config.update("jax_platforms", "cpu")
# same story for the persistent compilation cache: engage it via config
jax.config.update("jax_compilation_cache_dir",
                  os.environ["JAX_COMPILATION_CACHE_DIR"])
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)

import numpy as np  # noqa: E402
import pytest  # noqa: E402

assert len(jax.devices()) >= 8, "test cloud needs 8 virtual CPU devices"


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running tests excluded from the tier-1 run "
        "(-m 'not slow')")


@pytest.fixture(scope="session")
def cloud8():
    """8-device cloud (all virtual CPU devices)."""
    import jax
    from h2o3_tpu.parallel import mesh

    c = mesh.init(jax.devices())
    yield c
    mesh.reset()


@pytest.fixture()
def cloud1():
    """Single-device cloud — resets the global cloud to 1 device."""
    import jax
    from h2o3_tpu.parallel import mesh

    c = mesh.init(jax.devices()[:1])
    yield c
    mesh.reset()


@pytest.fixture(autouse=True)
def _reset_cloud():
    yield
    from h2o3_tpu.parallel import mesh

    mesh.reset()


def make_classification(n=2000, f=10, seed=0, informative=5):
    """Synthetic binary problem (separable-ish) — TestFrameBuilder stand-in."""
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    beta = np.zeros(f)
    informative = min(informative, f)
    beta[:informative] = rng.uniform(0.5, 2.0, informative) * rng.choice([-1, 1], informative)
    logits = X @ beta + 0.5 * X[:, 0] * X[:, 1]
    p = 1 / (1 + np.exp(-logits))
    y = (rng.random(n) < p).astype(int)
    return X, y


def make_regression(n=2000, f=8, seed=0, noise=0.1):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = X[:, 0] * 2 + np.sin(X[:, 1] * 3) + 0.5 * X[:, 2] ** 2 + noise * rng.normal(size=n)
    return X, y
