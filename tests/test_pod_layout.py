"""Pod canonical-layout math (ISSUE 18) — in-process tier-1 coverage.

The multi-interpreter pod fits live in the slow lane
(test_multiprocess.py); everything here is the PURE routing/grid math
those fits depend on — `canonical_counts` / `export_spans` /
`to_canonical` and the shard-plan grid invariants — exercised without
spawning a single worker, so a broken re-split is caught in seconds, not
after a 2-process gloo bring-up.
"""

import numpy as np
import pytest

from h2o3_tpu.models import estimator_engine as _est
from h2o3_tpu.parallel import distdata


# -- canonical_counts ---------------------------------------------------------

def _simulate_resplit(src_counts, dst_counts):
    """Reference re-split: every rank's destination slice assembled from
    the overlap + every rank's exported spans — the exact assembly
    `exchange_rows` performs, minus the byte transport."""
    src = np.asarray(src_counts, np.int64)
    dst = np.asarray(dst_counts, np.int64)
    n = int(src.sum())
    glob = np.arange(n, dtype=np.int64)     # global rows = their indices
    nproc = len(src)
    out = []
    for r in range(nproc):
        doff = int(dst[:r].sum())
        dn = int(dst[r])
        dest = np.full(dn, -1, np.int64)
        # own overlap
        soff, sn = int(src[:r].sum()), int(src[r])
        lo, hi = max(soff, doff), min(soff + sn, doff + dn)
        if hi > lo:
            dest[lo - doff: hi - doff] = glob[lo:hi]
        # every rank's exported spans that land in this destination
        for q in range(nproc):
            for gstart, glen in distdata.export_spans(src, dst, q):
                s_lo, s_hi = max(gstart, doff), min(gstart + glen, doff + dn)
                if s_hi > s_lo:
                    seg = dest[s_lo - doff: s_hi - doff]
                    assert (seg == -1).all(), "span overlaps prior coverage"
                    dest[s_lo - doff: s_hi - doff] = glob[s_lo:s_hi]
        out.append(dest)
    return out


@pytest.mark.parametrize("counts,npad", [
    ([5, 5], 16), ([7, 3], 16), ([0, 10], 16), ([10, 0], 16),
    ([3, 3, 3, 3], 16), ([1, 2, 3, 4], 24), ([13, 1, 1, 1], 16),
])
def test_canonical_counts_partition(counts, npad):
    cc = distdata.canonical_counts(np.asarray(counts), npad)
    nproc = len(counts)
    shard = npad // nproc
    n = int(np.sum(counts))
    # real rows conserved, no shard overfilled, pad all at the tail
    assert int(cc.sum()) == n
    assert (cc <= shard).all() and (cc >= 0).all()
    # the split is the equal canonical split of [real | pad]: every shard
    # before the pad boundary is FULL, everything after it empty
    full = n // shard
    assert (cc[:full] == shard).all()
    if full < nproc:
        assert int(cc[full]) == n - full * shard
        assert (cc[full + 1:] == 0).all()


def test_canonical_counts_rejects_ragged_grid():
    with pytest.raises(ValueError):
        distdata.canonical_counts(np.asarray([5, 5, 5]), 16)


# -- export_spans / re-split coverage ----------------------------------------

@pytest.mark.parametrize("src", [
    [5, 5], [7, 3], [0, 10], [16, 0], [4, 4, 4, 4], [1, 7, 2, 6],
    [13, 1, 1, 1], [0, 0, 8, 8],
])
def test_resplit_to_canonical_is_exact_and_ordered(src):
    src = np.asarray(src, np.int64)
    npad = 16 if len(src) == 2 else 32
    dst = distdata.canonical_counts(src, npad)
    slices = _simulate_resplit(src, dst)
    # full coverage, exactly once, order preserved: concatenating the
    # destination slices in rank order IS the global ingest order
    got = np.concatenate(slices)
    assert (got == np.arange(int(src.sum()))).all()


def test_export_spans_stay_outside_destination():
    src = np.asarray([7, 3], np.int64)
    dst = distdata.canonical_counts(src, 16)       # [8, 2]
    for r in range(2):
        doff, dn = int(dst[:r].sum()), int(dst[r])
        for gstart, glen in distdata.export_spans(src, dst, r):
            if glen:
                # an exported span never overlaps the exporter's own
                # destination range (it would be a pointless self-send)
                assert gstart + glen <= doff or gstart >= doff + dn


def test_exchange_rows_single_process_identity():
    a = np.arange(12, dtype=np.float32).reshape(6, 2)
    out = distdata.exchange_rows(a, np.asarray([6]), np.asarray([6]))
    assert (out == a).all()
    with pytest.raises(ValueError):
        distdata.exchange_rows(a, np.asarray([6]), np.asarray([5]))


def test_to_from_canonical_single_process_roundtrip():
    a = np.arange(10, dtype=np.float32)
    c = distdata.to_canonical(a, 16, fill=-1)
    assert c.shape == (16,)
    assert (c[:10] == a).all() and (c[10:] == -1).all()
    back = distdata.from_canonical(c, 16, np.asarray([10]))
    assert (back == a).all()
    # 2-D rows travel as rows
    m = np.arange(12, dtype=np.float32).reshape(6, 2)
    cm = distdata.to_canonical(m, 8)
    assert cm.shape == (8, 2) and (cm[6:] == 0).all()


# -- shard-plan grid invariants ----------------------------------------------

@pytest.mark.parametrize("ndev", [2, 4, 8])
def test_estimator_pod_shard_plan_grid(monkeypatch, ndev):
    monkeypatch.delenv("H2O3_EST_SHARD", raising=False)
    monkeypatch.delenv("H2O3_EST_LEGACY", raising=False)
    mode, s = _est.shard_plan(ndev, multiproc=True)
    assert mode == "mesh"
    # S shared with the 1-device forced-shard comparator lane: a multiple
    # of the base block count AND of the device count, so npad splits
    # into equal 8-row-aligned per-rank quotas for any nproc | ndev
    assert s % _est.shard_blocks() == 0 and s % ndev == 0
    for n in (ndev, 1000, 4096, 100_003):
        npad = _est.pad_rows(n, s)
        assert npad >= n and npad % s == 0
        for nproc in (1, 2, ndev):
            if ndev % nproc == 0:
                assert npad % nproc == 0          # canonical split exists
                quota = npad // nproc
                assert quota % (ndev // nproc) == 0   # per-device rows


def test_estimator_pod_shard_plan_escape_hatches(monkeypatch):
    monkeypatch.setenv("H2O3_EST_SHARD", "0")
    assert _est.shard_plan(4, multiproc=True) == ("off", 0)
    monkeypatch.delenv("H2O3_EST_SHARD", raising=False)
    monkeypatch.setenv("H2O3_EST_LEGACY", "1")
    assert _est.shard_plan(4, multiproc=True) == ("off", 0)


def test_block_grid_matches_between_blocks_and_mesh():
    # the bit-identity contract's geometry: S blocks cut on one device
    # and S/ndev blocks per device over the same npad rows land on the
    # SAME global row boundaries
    s = 8
    npad = _est.pad_rows(1000, s)
    whole = _est.block_slices(npad, s)
    ndev = 2
    per_dev = npad // ndev
    stitched = []
    for d in range(ndev):
        for sl in _est.block_slices(per_dev, s // ndev):
            stitched.append(slice(d * per_dev + sl.start,
                                  d * per_dev + sl.stop))
    assert [(sl.start, sl.stop) for sl in whole] == \
           [(sl.start, sl.stop) for sl in stitched]

# -- watchdog rank attribution ------------------------------------------------

def test_lane_hang_report_names_suspect_ranks(monkeypatch):
    """bench/MULTICHIP watchdog embed (ISSUE 18): a hung collective's
    partial line names the suspect RANK from the cached lane→process
    topology — pure host-dict logic, exercised without a mesh."""
    from h2o3_tpu.parallel import mesh

    monkeypatch.setattr(mesh, "_LANE_PROC", {0: 0, 1: 0, 2: 1, 3: 1})
    monkeypatch.setattr(mesh, "_LANE_SELF", 0)
    monkeypatch.setattr(mesh, "_LANE_OPEN", {})
    monkeypatch.setattr(mesh, "_LANE_LAST_TS", 0.0)
    rep = mesh.lane_hang_report()
    assert rep["n_ranks"] == 2 and rep["self_rank"] == 0
    assert rep["local_lanes"] == [0, 1]
    # no open fence: every local lane made its last rendezvous — a hang
    # is waiting on lanes this process never hears from
    assert rep["suspect_ranks"] == [1]
    # an open fence missing a LOCAL lane blames THIS rank
    monkeypatch.setattr(mesh, "_LANE_OPEN", {"hist": {0: 1.0}})
    rep = mesh.lane_hang_report()
    assert rep["open_fence"] == "hist"
    assert rep["missing_local_lanes"] == [1]
    assert rep["suspect_ranks"] == [0]
    # every local lane arrived yet the fence is still open: remote ranks
    monkeypatch.setattr(mesh, "_LANE_OPEN", {"hist": {0: 1.0, 1: 1.002}})
    rep = mesh.lane_hang_report()
    assert rep["missing_local_lanes"] == []
    assert rep["suspect_ranks"] == [1]
    # no topology cached (no sharded fit ran): empty — the watchdog
    # embeds nothing rather than guessing
    monkeypatch.setattr(mesh, "_LANE_PROC", {})
    assert mesh.lane_hang_report() == {}
