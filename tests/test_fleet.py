"""Fleet-scope observability (ISSUE 13): cross-process metric
aggregation and trace merging.

Tier-1 section: the merge semantics as PURE functions (counters sum,
histogram buckets sum so percentiles stay exact, gauges keep per-replica
series, unreachable peers become explicit `h2o3_fleet_peer_up 0`, trace
merges get one process track per replica), plus the REST face against a
canned stub peer — no subprocesses, no jax work, tier-1-cheap by design
(the tier-1 budget is ~826 s of the 870 s timeout).

Slow section: the real thing — two LIVE peer processes each running a
full REST server, scraped and merged by an in-process aggregator, then
one peer killed mid-flight (the acceptance pin: summed counters,
bucket-merged latency histograms, killed peer marked down)."""

import json
import os
import socket
import subprocess
import sys
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from h2o3_tpu.runtime import fleet
from h2o3_tpu.runtime import metrics_registry as registry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_fleet():
    fleet.reset()
    yield
    fleet.reset()


def _counter(value_by_labels, labelnames=("k",)):
    return dict(kind="counter", help="h", labelnames=list(labelnames),
                series=[dict(labels=list(lv), value=v)
                        for lv, v in value_by_labels.items()])


def _hist(bounds, series):
    return dict(kind="histogram", help="h", labelnames=["k"],
                bounds=list(bounds), series=series)


# -- merge semantics (pure) --------------------------------------------------

def test_merge_counters_sum_across_replicas():
    sA = {"h2o3_x": _counter({("a",): 3.0, ("b",): 1.0})}
    sB = {"h2o3_x": _counter({("a",): 4.0})}
    m = fleet.merge_states([("r1", sA), ("r2", sB)])
    fam = m["families"]["h2o3_x"]
    assert fam["kind"] == "counter"
    by = {tuple(s["labels"]): s["value"] for s in fam["series"]}
    assert by == {("a",): 7.0, ("b",): 1.0}
    # rendered as one fleet total, no replica label on counters
    text = fleet.render_prometheus(m)
    assert 'h2o3_x_total{k="a"} 7' in text
    assert "replica" not in text.split("h2o3_x_total", 1)[1].split("\n")[0]


def test_merge_histogram_buckets_sum_and_percentiles_stay_exact():
    bounds = [1.0, 10.0, 100.0]
    sA = {"h2o3_ms": _hist(bounds, [dict(labels=["m"], counts=[2, 3, 0, 0],
                                         n=5, sum=20.0, min=0.5, max=9.0)])}
    sB = {"h2o3_ms": _hist(bounds, [dict(labels=["m"], counts=[0, 1, 4, 0],
                                         n=5, sum=220.0, min=2.0,
                                         max=95.0)])}
    m = fleet.merge_states([("r1", sA), ("r2", sB)])
    s = m["families"]["h2o3_ms"]["series"][0]
    # bucket-wise sums: the merged histogram is EXACTLY the histogram of
    # the union of observations, so any percentile computed from it is
    # the true fleet percentile (not an average of per-replica quantiles)
    assert s["counts"] == [2, 4, 4, 0]
    assert s["n"] == 10 and s["sum"] == 240.0
    assert s["min"] == 0.5 and s["max"] == 95.0
    p50 = fleet._bucket_percentile(bounds, s["counts"], s["n"], 0.50,
                                   s["min"], s["max"])
    assert 1.0 <= p50 <= 10.0          # rank 4.5 lands in the (1,10] bucket
    p99 = fleet._bucket_percentile(bounds, s["counts"], s["n"], 0.99,
                                   s["min"], s["max"])
    assert 10.0 <= p99 <= 95.0         # clamped by the fleet max
    # exposition: cumulative buckets + +Inf + _sum/_count
    text = fleet.render_prometheus(m)
    assert 'h2o3_ms_bucket{k="m",le="1"} 2' in text
    assert 'h2o3_ms_bucket{k="m",le="10"} 6' in text
    assert 'h2o3_ms_bucket{k="m",le="+Inf"} 10' in text
    assert 'h2o3_ms_count{k="m"} 10' in text


def test_merge_gauges_keep_per_replica_series():
    sA = {"h2o3_g": dict(kind="gauge", help="h", labelnames=[],
                         series=[dict(labels=[], value=0.25)])}
    sB = {"h2o3_g": dict(kind="gauge", help="h", labelnames=[],
                         series=[dict(labels=[], value=0.75)])}
    m = fleet.merge_states([("r1", sA), ("r2", sB)])
    fam = m["families"]["h2o3_g"]
    assert fam["labelnames"] == ["replica"]
    by = {tuple(s["labels"]): s["value"] for s in fam["series"]}
    # NOT summed: a gauge is process state, attributed per replica
    assert by == {("r1",): 0.25, ("r2",): 0.75}
    text = fleet.render_prometheus(m)
    assert 'h2o3_g{replica="r1"} 0.25' in text


def test_unreachable_peer_is_explicit_peer_up_zero():
    m = fleet.merge_states([("r1", {"h2o3_x": _counter({("a",): 1.0})}),
                            ("dead", None)])
    assert m["peer_up"] == {"r1": 1, "dead": 0}
    text = fleet.render_prometheus(m)
    assert 'h2o3_fleet_peer_up{replica="dead"} 0' in text
    assert 'h2o3_fleet_peer_up{replica="r1"} 1' in text
    # the down peer did not shrink the scrape: r1's data is still there
    assert 'h2o3_x_total{k="a"} 1' in text


def test_merge_conflicting_shapes_drop_not_corrupt():
    sA = {"h2o3_ms": _hist([1, 10], [dict(labels=["m"], counts=[1, 0, 0],
                                          n=1, sum=0.5, min=0.5, max=0.5)])}
    sB = {"h2o3_ms": _hist([1, 10, 100],          # version-skewed bounds
                           [dict(labels=["m"], counts=[0, 1, 0, 0],
                                 n=1, sum=5.0, min=5.0, max=5.0)])}
    m = fleet.merge_states([("r1", sA), ("r2", sB)])
    s = m["families"]["h2o3_ms"]["series"][0]
    assert s["n"] == 1 and s["counts"] == [1, 0, 0]   # first shape kept
    assert m["dropped_series"] == 1                    # loudly counted


def test_merge_conflicting_label_arity_drops_not_zips():
    # version-skewed LABELS: same name+kind, an extra labelname on r2 —
    # zipping ["get","200"] against ["op"] would silently truncate into
    # a duplicate {op="get"} series; it must drop + count instead
    sA = {"h2o3_x": _counter({("get",): 3.0}, labelnames=("op",))}
    sB = {"h2o3_x": _counter({("get", "200"): 4.0},
                             labelnames=("op", "status"))}
    m = fleet.merge_states([("r1", sA), ("r2", sB)])
    assert [s["value"] for s in m["families"]["h2o3_x"]["series"]] == [3.0]
    assert m["dropped_series"] == 1


def test_remove_peer_clears_liveness_series():
    from h2o3_tpu.runtime import metrics_registry as registry

    fleet.reset()
    fleet.register_peer("gone", "http://127.0.0.1:1")
    fleet.scrape_states()                       # marks peer_up{gone} 0
    assert 'h2o3_fleet_peer_up{replica="gone"} 0' in registry.prometheus_text()
    assert fleet.remove_peer("gone")
    # a decommissioned replica's LIVENESS series leaves the scrape — a
    # frozen peer_up 0 would alert forever for a peer that no longer
    # exists (the monotone scrape counters keep their history, correctly)
    text = registry.prometheus_text()
    assert 'h2o3_fleet_peer_up{replica="gone"}' not in text
    assert 'h2o3_fleet_scrapes_total{replica="gone"} 1' in text
    fleet.reset()


def test_trace_merge_one_process_track_per_replica():
    trA = dict(traceEvents=[
        dict(name="GET /3/Ping", cat="request", ph="X", ts=1.0, dur=2.0,
             pid=4242, tid=1, args={}),
    ])
    trB = dict(traceEvents=[
        dict(name="job:gbm", cat="job", ph="X", ts=2.0, dur=5.0,
             pid=777, tid=3, args={}),
    ])
    merged = fleet.merge_traces([("router", trA), ("worker", trB),
                                 ("gone", None)])
    tracks = {e["pid"]: e["args"]["name"] for e in merged["traceEvents"]
              if e.get("name") == "process_name"}
    assert tracks == {1: "replica:router", 2: "replica:worker"}
    # span events were re-pid'd onto their replica's track
    spans = {e["name"]: e["pid"] for e in merged["traceEvents"]
             if e.get("ph") == "X"}
    assert spans == {"GET /3/Ping": 1, "job:gbm": 2}
    assert merged["otherData"]["unreachable"] == ["gone"]


def test_export_state_is_lossless_for_merging():
    """The registry's own export feeds the merge unchanged: one peer's
    export merged alone must reproduce its counters/buckets exactly."""
    c = registry.counter("h2o3_fleet_test_ctr", "t", labelnames=("k",))
    c.inc(5, "x")
    h = registry.histogram("h2o3_fleet_test_ms", "t", bounds=(1, 10),
                           labelnames=("k",))
    h.observe(0.5, "x")
    h.observe(7.0, "x")
    state = registry.export_state()
    m = fleet.merge_states([("solo", state)])
    ctr = m["families"]["h2o3_fleet_test_ctr"]
    assert any(s["labels"] == ["x"] and s["value"] == 5.0
               for s in ctr["series"])
    hs = [s for s in m["families"]["h2o3_fleet_test_ms"]["series"]
          if s["labels"] == ["x"]][0]
    assert hs["counts"] == [1, 1, 0] and hs["n"] == 2
    assert hs["min"] == 0.5 and hs["max"] == 7.0


# -- REST face against a canned stub peer (tier-1 cheap) ---------------------

PEER_BOUNDS = list(registry.LATENCY_MS_BOUNDS)


def _stub_state():
    return {
        "h2o3_rest_requests": dict(
            kind="counter", help="x", labelnames=["handler", "status"],
            series=[dict(labels=["ping", "200"], value=11.0)]),
        "h2o3_rest_request_ms": dict(
            kind="histogram", help="x", labelnames=["handler"],
            bounds=PEER_BOUNDS,
            series=[dict(labels=["predict"],
                         counts=[0] * 4 + [6] + [0] * (len(PEER_BOUNDS) - 4),
                         n=6, sum=48.0, min=6.0, max=9.5)]),
        "h2o3_memory_pressure_stub": dict(
            kind="gauge", help="x", labelnames=[],
            series=[dict(labels=[], value=0.42)]),
    }


class _StubPeer(BaseHTTPRequestHandler):
    def log_message(self, *a):
        pass

    def do_GET(self):
        if "/3/Metrics" in self.path:
            body = json.dumps(_stub_state()).encode()
        else:
            body = json.dumps(dict(traceEvents=[
                dict(name="peer_span", cat="job", ph="X", ts=1.0, dur=2.0,
                     pid=9, tid=1, args={})])).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


@pytest.fixture()
def stub_peer():
    srv = HTTPServer(("127.0.0.1", 0), _StubPeer)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv
    srv.shutdown()
    srv.server_close()


@pytest.fixture(scope="module")
def fleet_server():
    from h2o3_tpu.rest.server import start_server

    srv = start_server(port=0)
    yield srv
    srv.stop()


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=30) as r:
        return r.read().decode()


def _post(port, path, data):
    import urllib.parse

    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=urllib.parse.urlencode(data).encode())
    with urllib.request.urlopen(req, timeout=30) as r:
        return r.read().decode()


def test_rest_fleet_scrape_merges_and_marks_downed_peer(fleet_server,
                                                       stub_peer):
    _post(fleet_server.port, "/3/Fleet",
          dict(name="r1", url=f"http://127.0.0.1:{stub_peer.server_port}"))
    registry.counter("h2o3_rest_requests", "x",
                     labelnames=("handler", "status")).inc(4, "ping", "200")
    local = registry.get("h2o3_rest_requests").value("ping", "200")
    text = _get(fleet_server.port, "/3/Metrics?scope=fleet")
    # summed counter: stub's 11 + everything this process counted
    line = [l for l in text.splitlines()
            if l.startswith('h2o3_rest_requests_total{handler="ping"')][0]
    assert float(line.rsplit(" ", 1)[1]) == local + 11.0
    assert 'h2o3_fleet_peer_up{replica="r1"} 1' in text
    # per-replica gauge attribution
    assert 'h2o3_memory_pressure_stub{replica="r1"} 0.42' in text
    # the /3/Fleet fold sees the peer's serving essentials
    doc = json.loads(_get(fleet_server.port, "/3/Fleet"))
    row = [r for r in doc["peers"] if r["name"] == "r1"][0]
    assert row["up"] == 1 and row["predict_count"] == 6
    assert 6.0 <= row["predict_p99_ms"] <= 9.5
    # kill the peer: the next scrape marks it down EXPLICITLY
    stub_peer.shutdown()
    stub_peer.server_close()
    text2 = _get(fleet_server.port, "/3/Metrics?scope=fleet")
    assert 'h2o3_fleet_peer_up{replica="r1"} 0' in text2
    doc2 = json.loads(_get(fleet_server.port, "/3/Fleet"))
    row2 = [r for r in doc2["peers"] if r["name"] == "r1"][0]
    assert row2["up"] == 0 and row2["last_error"]
    # unregister
    assert json.loads(_get(fleet_server.port, "/3/Fleet?probe=0"))[
        "totals"]["peers"] == 1
    req = urllib.request.Request(
        f"http://127.0.0.1:{fleet_server.port}/3/Fleet?name=r1",
        method="DELETE")
    with urllib.request.urlopen(req, timeout=30) as r:
        assert json.loads(r.read())["removed"] is True


def test_rest_fleet_trace_scope_tracks(fleet_server, stub_peer):
    _post(fleet_server.port, "/3/Fleet",
          dict(name="r1", url=f"http://127.0.0.1:{stub_peer.server_port}"))
    doc = json.loads(_get(fleet_server.port, "/3/Trace?scope=fleet"))
    names = {e["args"]["name"] for e in doc["traceEvents"]
             if e.get("name") == "process_name"}
    assert names == {"replica:self", "replica:r1"}
    assert any(e.get("name") == "peer_span" for e in doc["traceEvents"])


def test_rest_metrics_format_json_is_lossless(fleet_server):
    registry.histogram("h2o3_fleet_test_ms2", "t",
                       bounds=(1, 10)).observe(3.0)
    doc = json.loads(_get(fleet_server.port, "/3/Metrics?format=json"))
    fam = doc["h2o3_fleet_test_ms2"]
    assert fam["kind"] == "histogram" and fam["bounds"] == [1.0, 10.0]
    assert fam["series"][0]["counts"] == [0, 1, 0]


def test_profiler_carries_fleet_fold(fleet_server):
    fleet.register_peer("rp", "http://127.0.0.1:1")
    doc = json.loads(_get(fleet_server.port, "/3/Profiler"))
    assert doc["fleet"]["totals"]["peers"] >= 1
    # profiler fold never scrapes (no blocking on dead peers): the row is
    # registration state only
    assert any(p["name"] == "rp" for p in doc["fleet"]["peers"])


# -- the real thing: two live peer PROCESSES (slow lane) ---------------------

PEER_BODY = """
import sys, time
sys.path.insert(0, {repo!r})
import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["H2O3_REPLICA_NAME"] = {name!r}
from h2o3_tpu.rest.server import start_server
import urllib.request
srv = start_server(port={port})
for _ in range(5):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/3/Ping", timeout=10) as r:
        r.read()
print("READY", flush=True)
time.sleep(120)
"""


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_fleet_scrape_two_live_peer_processes(fleet_server):
    """The acceptance pin: an aggregator with >= 2 live peer PROCESSES
    returns summed counters and bucket-merged latency histograms labelled
    per replica; a killed peer reports as h2o3_fleet_peer_up 0."""
    ports = [_free_port(), _free_port()]
    procs = []
    try:
        for i, port in enumerate(ports):
            procs.append(subprocess.Popen(
                [sys.executable, "-c",
                 PEER_BODY.format(repo=REPO, name=f"p{i + 1}", port=port)],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True))
        for i, p in enumerate(procs):
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                line = p.stdout.readline()
                if "READY" in line:
                    break
                if p.poll() is not None:
                    raise AssertionError(
                        f"peer {i} died: {p.stdout.read()[-2000:]}")
            else:
                raise AssertionError(f"peer {i} never came up")
        for i, port in enumerate(ports):
            _post(fleet_server.port, "/3/Fleet",
                  dict(name=f"p{i + 1}", url=f"http://127.0.0.1:{port}"))
        text = _get(fleet_server.port, "/3/Metrics?scope=fleet")
        # counters sum: each live peer drove 5 pings through itself
        line = [l for l in text.splitlines()
                if l.startswith('h2o3_rest_requests_total{handler="ping"')]
        assert line, text[:2000]
        local = registry.get("h2o3_rest_requests")
        local_pings = local.value("ping", "200") if local else 0.0
        assert float(line[0].rsplit(" ", 1)[1]) == local_pings + 10.0
        # bucket-merged latency histogram, fleet-wide count covers both
        cnt = [l for l in text.splitlines()
               if l.startswith('h2o3_rest_request_ms_count'
                               '{handler="ping"}')]
        assert cnt and float(cnt[0].rsplit(" ", 1)[1]) >= 10
        assert 'h2o3_fleet_peer_up{replica="p1"} 1' in text
        assert 'h2o3_fleet_peer_up{replica="p2"} 1' in text
        # the merged histogram really is bucket series, not a summary
        assert 'h2o3_rest_request_ms_bucket{handler="ping",le="+Inf"}' \
            in text
        # kill one replica: explicit down-marking, no silent shrink
        procs[1].kill()
        procs[1].wait(timeout=30)
        text2 = _get(fleet_server.port, "/3/Metrics?scope=fleet")
        assert 'h2o3_fleet_peer_up{replica="p2"} 0' in text2
        assert 'h2o3_fleet_peer_up{replica="p1"} 1' in text2
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


def test_loadgen_ranks_summary_lists_rank_peers(fleet_server, stub_peer):
    """deploy/loadgen --fleet `ranks` section (ISSUE 18): one row per
    launcher-registered rank peer, aggregator counted as rank0."""
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "deploy"))
    from loadgen import ranks_summary

    # no rank peers: single-process fleets keep their old report shape
    assert ranks_summary("127.0.0.1", fleet_server.port) is None
    _post(fleet_server.port, "/3/Fleet",
          dict(name="rank1", url=f"http://127.0.0.1:{stub_peer.server_port}"))
    rows = ranks_summary("127.0.0.1", fleet_server.port)
    assert rows is not None
    byname = {r["name"]: r for r in rows}
    assert byname["rank0"]["peer_up"] == 1          # the aggregator itself
    assert byname["rank1"]["peer_up"] == 1          # the registered rank
