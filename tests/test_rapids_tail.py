"""Round-2 Rapids prim-tail parity (`water/rapids/ast/prims/**` long tail):
NA-propagating reducers, time construction, string metrics, reshapers, fold
columns, sequences, 2-column table — VERDICT r01 item 7."""

import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu.frame.frame import Frame


def _fr(**cols):
    types = {k: "enum" for k, v in cols.items()
             if np.asarray(v).dtype.kind in "OUS"}
    return h2o.H2OFrame(dict(cols), column_types=types or None)


def _col(fr, i=0):
    return np.asarray(fr.vec(fr.names[i]).numeric_np())


def test_na_reducers(cloud1):
    fr = _fr(a=[1.0, np.nan, 3.0])
    assert np.isnan(h2o.rapids(f"(sumNA {fr.key})"))
    assert np.isnan(h2o.rapids(f"(maxNA {fr.key})"))
    assert h2o.rapids(f"(nacnt {fr.key})") == [1.0]
    m = _fr(a=[1.0, 2.0, 2.0, 3.0])
    assert h2o.rapids(f"(mode {m.key})") == 2.0


def test_math_tail(cloud1):
    fr = _fr(a=[0.5])
    np.testing.assert_allclose(
        _col(h2o.rapids(f"(asinh {fr.key})"))[0], np.arcsinh(0.5))
    np.testing.assert_allclose(
        _col(h2o.rapids(f"(cospi {fr.key})"))[0], np.cos(np.pi * 0.5),
        atol=1e-12)


def test_time_tail(cloud1):
    # 2020-03-04 05:06:07.250 UTC
    ts = 1583298367250.0
    fr = _fr(t=[ts])
    assert _col(h2o.rapids(f"(millis {fr.key})"))[0] == 250.0
    assert _col(h2o.rapids(f"(week {fr.key})"))[0] == 10.0  # ISO week 10
    mk = h2o.rapids("(mktime 2020 2 3 5 6 7 250)")  # 0-based month/day
    assert _col(mk)[0] == ts


def test_string_tail(cloud1):
    fr = _fr(s=["  ab", "cd  ", "aabb"])
    out = h2o.rapids(f"(lstrip {fr.key})")
    assert out.vec(out.names[0]).domain[0] == "ab"
    ent = _col(h2o.rapids(f'(entropy {fr.key})'))
    # row 2 is "aabb": two symbols equally likely -> 1 bit
    assert ent[2] == 1.0
    g = h2o.rapids(f'(grep {fr.key} "ab")')
    assert len(_col(g)) == 2  # "  ab" and "aabb" match


def test_frame_tail(cloud1):
    fr = _fr(a=[1.0, 2.0], b=[np.nan, np.nan], s=["x", "y"])
    names = h2o.rapids(f"(colnames {fr.key})")
    assert list(names.vec("names").domain) == ["a", "b", "s"]
    num = h2o.rapids(f'(columnsByType {fr.key} "numeric")')
    assert list(_col(num)) == [0.0, 1.0]
    keep = h2o.rapids(f"(filterNACols {fr.key} 0.5)")
    assert list(_col(keep)) == [0.0, 2.0]
    one = _fr(z=[7.0])
    assert h2o.rapids(f"(flatten {one.key})") == 7.0
    row = h2o.rapids(f"(getrow {one.key})")
    assert list(_col(row)) == [7.0]
    d = _fr(a=[1.0, 2.0, np.nan, np.nan, 5.0])
    filled = h2o.rapids(f'(h2o.fillna {d.key} "forward" 0 1)')
    np.testing.assert_array_equal(
        _col(filled), [1.0, 2.0, 2.0, np.nan, 5.0])
    df = h2o.rapids(f"(difflag1 {d.key})")
    assert _col(df)[1] == 1.0 and np.isnan(_col(df)[0])


def test_melt_pivot_roundtrip(cloud1):
    fr = _fr(id=["r1", "r2"], x=[1.0, 2.0], y=[3.0, 4.0])
    long = h2o.rapids(f'(melt {fr.key} [0] [1 2] "var" "val" FALSE)')
    assert long.shape == (4, 3)
    wide = h2o.rapids(
        f'(pivot (melt {fr.key} [0] [1 2] "var" "val" FALSE) "id" "var" "val")')
    assert wide.shape == (2, 3)
    assert list(np.asarray(wide.vec("x").numeric_np())) == [1.0, 2.0]
    assert list(np.asarray(wide.vec("y").numeric_np())) == [3.0, 4.0]


def test_levels_tail(cloud1):
    fr = _fr(c=["lo", "hi", "lo", "mid"])
    rel = h2o.rapids(f'(relevel {fr.key} "mid")')
    v = rel.vec(rel.names[0])
    assert v.domain[0] == "mid"
    # values preserved under the domain permutation
    labels = [v.domain[c] for c in np.asarray(v.data)]
    assert labels == ["lo", "hi", "lo", "mid"]
    dom = h2o.rapids(f'(setDomain {fr.key} ["H" "L" "M"])')
    v2 = dom.vec(dom.names[0])
    assert v2.domain == ["H", "L", "M"]  # hi,lo,mid sorted -> renamed


def test_fold_and_seq(cloud1):
    fr = _fr(y=["a", "b", "a", "b", "a", "b", "a", "b"])
    f1 = _col(h2o.rapids(f"(kfold_column {fr.key} 4 42)"))
    assert set(f1) <= {0.0, 1.0, 2.0, 3.0}
    f2 = _col(h2o.rapids(f"(modulo_kfold_column {fr.key} 4)"))
    assert list(f2[:4]) == [0.0, 1.0, 2.0, 3.0]
    f3 = _col(h2o.rapids(f"(stratified_kfold_column {fr.key} 2 7)"))
    y = np.asarray(fr.vec("y").data)
    for cls in (0, 1):  # each class split evenly across folds
        vals, cnt = np.unique(f3[y == cls], return_counts=True)
        assert list(cnt) == [2, 2]
    assert list(_col(h2o.rapids("(seq 2 6 2)"))) == [2.0, 4.0, 6.0]
    assert list(_col(h2o.rapids("(seq_len 3)"))) == [1.0, 2.0, 3.0]
    rl = _fr(a=[1.0, 2.0])
    assert list(_col(h2o.rapids(f"(rep_len {rl.key} 5)"))) == [
        1.0, 2.0, 1.0, 2.0, 1.0]


def test_topn_and_table2(cloud1):
    fr = _fr(v=[5.0, 1.0, 9.0, 3.0, 7.0, 2.0, 8.0, 4.0, 6.0, 0.0])
    top = h2o.rapids(f"(topn {fr.key} 0 20 TRUE)")
    assert list(_col(top, 1)) == [9.0, 8.0]
    bot = h2o.rapids(f"(topn {fr.key} 0 20 FALSE)")
    assert list(_col(bot, 1)) == [0.0, 1.0]
    t2 = _fr(a=["x", "x", "y"], b=["p", "p", "q"])
    tab = h2o.rapids(f"(table (cols {t2.key} [0 1]) )")
    assert tab.shape == (2, 3)
    counts = {(r0, r1): c for r0, r1, c in zip(
        [tab.vec("a").domain[i] for i in np.asarray(tab.vec("a").data)],
        [tab.vec("b").domain[i] for i in np.asarray(tab.vec("b").data)],
        np.asarray(tab.vec("Counts").numeric_np()))}
    assert counts == {("x", "p"): 2.0, ("y", "q"): 1.0}


def test_operator_tail(cloud1):
    fr = _fr(a=[5.0, 7.0])
    assert list(_col(h2o.rapids(f"(%% {fr.key} 3)"))) == [2.0, 1.0]
    assert list(_col(h2o.rapids(f"(%/% {fr.key} 3)"))) == [1.0, 2.0]
    assert list(_col(h2o.rapids(f"(^ {fr.key} 2)"))) == [25.0, 49.0]
    x = _fr(a=[1.0, 0.0, np.nan])
    y = _fr(b=[1.0, 1.0, 0.0])
    band = _col(h2o.rapids(f"(& {x.key} {y.key})"))
    np.testing.assert_array_equal(band, [1.0, 0.0, 0.0])  # NA & FALSE = FALSE
    bor = _col(h2o.rapids(f"(| {x.key} {y.key})"))
    np.testing.assert_array_equal(bor, [1.0, 1.0, np.nan])  # NA | FALSE = NA


def test_review_fixes_r02(cloud1):
    # scalar-first non-commutative binops must not swap operands
    fr = _fr(a=[1.0, 2.0])
    assert list(_col(h2o.rapids(f"(- 5 {fr.key})"))) == [4.0, 3.0]
    assert list(_col(h2o.rapids(f"(/ 6 {fr.key})"))) == [6.0, 3.0]
    # topn skips NAs
    nafr = _fr(v=[5.0, np.nan, 3.0, 9.0, np.nan, 1.0])
    top = h2o.rapids(f"(topn {nafr.key} 0 35 TRUE)")
    assert list(_col(top, 1)) == [9.0, 5.0]
    # pivot orders numeric keys numerically
    lng = _fr(idx=[1.0, 10.0, 2.0], c=["k", "k", "k"], v=[1.0, 2.0, 3.0])
    wide = h2o.rapids(f'(pivot {lng.key} "idx" "c" "v")')
    assert list(_col(wide, 0)) == [1.0, 2.0, 10.0]
    # fillna axis=1 fills across columns
    rowfr = _fr(a=[1.0, np.nan], b=[np.nan, np.nan], c=[7.0, 8.0])
    f = h2o.rapids(f'(h2o.fillna {rowfr.key} "forward" 1 1)')
    assert _col(f, 1)[0] == 1.0 and np.isnan(_col(f, 0)[1])
    # mktime with NA component yields NA, not a crash
    nfr = _fr(y=[2020.0, np.nan])
    mk = _col(h2o.rapids(f"(mktime {nfr.key} 0 0 0 0 0 0)"))
    assert not np.isnan(mk[0]) and np.isnan(mk[1])
    # vectorized week still correct across a year boundary (2021-01-01 -> 53)
    wfr = _fr(t=[1609459200000.0])
    assert _col(h2o.rapids(f"(week {wfr.key})"))[0] == 53.0


def test_scalar_first_multicolumn(cloud1):
    fr = _fr(a=[1.0, 2.0], b=[10.0, 20.0])
    out = h2o.rapids(f"(- 100 {fr.key})")
    assert out.ncol == 2
    assert list(_col(out, 0)) == [99.0, 98.0]
    assert list(_col(out, 1)) == [90.0, 80.0]


@pytest.mark.parametrize("expr", [
    "(append)", "(cut)", "(mean)", "(unique)", "(strDistance)",
    '(unique "x" "y" TRUE)', '(trim "x" TRUE [])',
    '(+ (hist 1 "x") (is.na -3.5 1 "x"))',
])
def test_malformed_rapids_raise_value_error(cloud1, expr):
    """Wrong arity / argument kinds are USER errors (ValueError → 400),
    never interpreter-internal 500s — found by fuzzing /99/Rapids."""
    import h2o3_tpu as h2o

    with pytest.raises((ValueError, TypeError, KeyError)):
        h2o.rapids(expr)
