"""Long-tail algos batch 2: isotonic regression, SVD, aggregator.

Mirrors the reference pyunits: `pyunit_isotonic_regression.py`,
`pyunit_svd_*`, `pyunit_aggregator_*` (tolerance asserts vs known values).
"""

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.aggregator import H2OAggregatorEstimator
from h2o3_tpu.models.isotonic import H2OIsotonicRegressionEstimator, pav
from h2o3_tpu.models.svd import H2OSingularValueDecompositionEstimator


def test_pav_monotone_and_pooling():
    x = np.array([1.0, 2.0, 3.0, 4.0, 5.0])
    y = np.array([1.0, 3.0, 2.0, 4.0, 5.0])
    tx, ty = pav(x, y, np.ones_like(x))
    # fitted values must be monotone non-decreasing
    assert (np.diff(ty) >= -1e-12).all()
    # violator pair (3,2) pools to 2.5
    fit = np.interp(x, tx, ty)
    np.testing.assert_allclose(fit, [1.0, 2.5, 2.5, 4.0, 5.0])


def test_isotonic_estimator_fit_predict(cloud1):
    rng = np.random.default_rng(7)
    x = rng.uniform(0, 10, 400)
    y = np.sqrt(x) + rng.normal(0, 0.1, 400)
    fr = Frame.from_dict({"x": x, "y": y})
    iso = H2OIsotonicRegressionEstimator(out_of_bounds="clip")
    iso.train(x=["x"], y="y", training_frame=fr)
    assert iso.model.training_metrics.rmse < 0.15
    # out-of-bounds clip: prediction at x=100 equals fit at max knot
    test = Frame.from_dict({"x": np.array([-5.0, 100.0])})
    p = iso.predict(test).vec("predict").numeric_np()
    assert p[0] == pytest.approx(iso.model.thresholds_y[0])
    assert p[1] == pytest.approx(iso.model.thresholds_y[-1])
    # NA mode
    iso2 = H2OIsotonicRegressionEstimator(out_of_bounds="NA")
    iso2.train(x=["x"], y="y", training_frame=fr)
    p2 = iso2.predict(test).vec("predict").numeric_np()
    assert np.isnan(p2).all()


def test_svd_gram_matches_numpy(cloud1):
    rng = np.random.default_rng(0)
    X = rng.normal(size=(200, 6)).astype(np.float64)
    fr = Frame.from_numpy(X, names=[f"c{i}" for i in range(6)])
    svd = H2OSingularValueDecompositionEstimator(nv=3, transform="NONE", keep_u=True)
    svd.train(x=fr.names, training_frame=fr)
    m = svd.model
    _, s_ref, _ = np.linalg.svd(X, full_matrices=False)
    np.testing.assert_allclose(m.d, s_ref[:3], rtol=1e-3)
    # u d v' reconstructs the dominant subspace: check column orthonormality
    np.testing.assert_allclose(m.v.T @ m.v, np.eye(3), atol=1e-5)
    u = m.u
    np.testing.assert_allclose((u.T @ u), np.eye(3), atol=1e-2)
    # projection of training data reproduces u
    proj = svd.predict(fr)
    np.testing.assert_allclose(proj.vec("u1").numeric_np(), u[:, 0], atol=1e-4)


def test_svd_power_matches_gram(cloud1):
    rng = np.random.default_rng(1)
    X = rng.normal(size=(150, 5))
    fr = Frame.from_numpy(X, names=[f"c{i}" for i in range(5)])
    g = H2OSingularValueDecompositionEstimator(nv=2, svd_method="GramSVD")
    g.train(x=fr.names, training_frame=fr)
    pw = H2OSingularValueDecompositionEstimator(nv=2, svd_method="Power", seed=3)
    pw.train(x=fr.names, training_frame=fr)
    np.testing.assert_allclose(pw.model.d, g.model.d, rtol=1e-3)


def test_aggregator_reduces_rows(cloud1):
    rng = np.random.default_rng(2)
    # 3 well-separated gaussian blobs, 900 rows
    X = np.concatenate([rng.normal(c, 0.05, size=(300, 2)) for c in (0.0, 5.0, 10.0)])
    fr = Frame.from_numpy(X, names=["a", "b"])
    agg = H2OAggregatorEstimator(target_num_exemplars=10, rel_tol_num_exemplars=0.9)
    agg.train(x=["a", "b"], training_frame=fr)
    out = agg.model.aggregated_frame
    assert 1 <= out.nrow < 900
    # counts conserve the row total
    assert out.vec("counts").numeric_np().sum() == 900
