"""Multi-tenant QoS gate (ISSUE 19) — tier-1 tests.

Covers the cooperative two-class dispatch gate (runtime/qos): priority
ordering (serving never waits, training yields), the
``H2O3_QOS_TRAIN_MIN_SHARE`` anti-starvation floor under the armed
``qos.starve`` fault, yield/wait bookkeeping (totals + registry families +
the ``qos_wait`` phase bucket), admission-throttle hysteresis, the single
``pressure_view()`` snapshot shared by serving admission and the dataset
cache, and the bit-exactness pins: a fit under QoS (tree chunk yields,
estimator ``while_loop`` segmentation) is bit-identical to QoS-off.

The full concurrent soak (live REST server + open-loop load + in-process
grid sweep) lives in the slow lane (`test_qos_concurrent_soak_slow`):
tier-1 already runs ~700 s of its 870 s budget, and the soak needs
multi-second serving windows to produce meaningful percentiles — it is
exercised by ``BENCH_CONFIG=qos`` and nightly ``-m slow`` runs instead.
"""

import math
import threading
import time

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.runtime import faults, phases, qos
from h2o3_tpu.runtime import metrics_registry as reg


@pytest.fixture(autouse=True)
def _qos_clean(monkeypatch):
    """Every test starts and ends with a cold gate and no armed faults."""
    qos.reset()
    faults.reset()
    yield
    qos.reset()
    faults.reset()


def _rng_frame(rows=200, seed=7, binomial=True):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(rows, 4)).astype(np.float64)
    if binomial:
        y = (X[:, 0] + 0.5 * X[:, 1] + rng.normal(scale=0.3, size=rows)
             > 0).astype(np.float64)
    else:
        y = X[:, 0] - 2.0 * X[:, 2] + rng.normal(scale=0.1, size=rows)
    names = ["a", "b", "c", "d", "y"]
    fr = Frame.from_numpy(np.column_stack([X, y]), names=names)
    return fr.asfactor("y") if binomial else fr


# ---------------------------------------------------------------- gate basics

def test_qos_off_is_free(monkeypatch):
    monkeypatch.delenv("H2O3_QOS", raising=False)
    assert not qos.enabled()
    assert qos.yield_point("tree_chunk") == 0.0
    with qos.serving_dispatch("m"):
        pass
    t = qos.totals()
    assert t["yields"] == 0 and t["serving_dispatches"] == 0


def test_serving_priority_over_training(monkeypatch):
    """A training yield waits while a serving dispatch is in flight and
    resumes promptly on release; serving entry itself never blocks."""
    monkeypatch.setenv("H2O3_QOS", "1")
    monkeypatch.setenv("H2O3_QOS_TRAIN_MIN_SHARE", "0.1")
    monkeypatch.setenv("H2O3_QOS_LINGER_MS", "0")
    monkeypatch.setenv("H2O3_QOS_MAX_WAIT_MS", "2000")

    release = threading.Event()
    entered = threading.Event()

    def serve():
        t0 = time.monotonic()
        with qos.serving_dispatch("gbm_1"):
            entry_cost = time.monotonic() - t0
            assert entry_cost < 0.05  # serving entry is non-blocking
            entered.set()
            release.wait(2.0)

    srv = threading.Thread(target=serve, daemon=True)
    # seed the training thread's share ledger with some "ran" time so the
    # min-share wait budget is positive
    assert qos.yield_point("tree_chunk") == 0.0
    time.sleep(0.05)
    srv.start()
    assert entered.wait(2.0)
    timer = threading.Timer(0.15, release.set)
    timer.start()
    waited = qos.yield_point("tree_chunk")
    timer.cancel()
    srv.join(2.0)
    assert waited >= 0.10  # blocked until the serving release
    assert waited < 1.0
    t = qos.totals()
    assert t["yields"] == 2 and t["serving_dispatches"] == 1
    assert t["waits_ms"] >= 100


def test_min_share_floor_under_starve_fault(monkeypatch):
    """With qos.starve armed every yield sees a closed gate; the
    min-share floor bounds cumulative wait so ran/(ran+waited) converges
    to the configured share instead of starving."""
    monkeypatch.setenv("H2O3_QOS", "1")
    monkeypatch.setenv("H2O3_QOS_TRAIN_MIN_SHARE", "0.5")
    monkeypatch.setenv("H2O3_QOS_MAX_WAIT_MS", "5000")
    faults.arm("qos.starve", error="none")

    qos.yield_point("tree_chunk")          # first visit: ran=0, no wait
    ran = 0.0
    for _ in range(3):
        time.sleep(0.03)
        ran += 0.03
        qos.yield_point("tree_chunk")
    waited = qos.totals()["waits_ms"] / 1e3
    # share=0.5 → cumulative wait tracks cumulative run time
    assert waited == pytest.approx(ran, rel=0.6)
    assert waited > 0.04
    # and with the fault disarmed the gate opens instantly again
    faults.reset()
    assert qos.yield_point("tree_chunk") < 0.02


def test_starve_fault_match_scoping(monkeypatch):
    """`match=` scopes qos.starve to one yield site — the other sites
    pass through an open gate."""
    monkeypatch.setenv("H2O3_QOS", "1")
    monkeypatch.setenv("H2O3_QOS_TRAIN_MIN_SHARE", "0.5")
    faults.arm("qos.starve", error="none", match="tree_block")
    assert faults.is_armed("qos.starve", "tree_block")
    assert not faults.is_armed("qos.starve", "est_segment")


def test_preempt_delay_fault_and_bookkeeping(monkeypatch):
    """qos.preempt_delay injects latency at the yield itself; yields are
    counted per site in the registry and in the process totals."""
    monkeypatch.setenv("H2O3_QOS", "1")
    faults.arm("qos.preempt_delay", error="none", latency_ms=30)
    t0 = time.monotonic()
    qos.yield_point("score_event")
    assert time.monotonic() - t0 >= 0.025
    assert qos.totals()["yields"] == 1
    fam = reg.get("h2o3_qos_yields")
    assert fam is not None


def test_qos_wait_booked_into_phases(monkeypatch):
    """Waits land in the ``qos_wait`` phase bucket and are subtracted
    from the compensated bucket (no double-booking)."""
    monkeypatch.setenv("H2O3_QOS", "1")
    monkeypatch.setenv("H2O3_QOS_TRAIN_MIN_SHARE", "0.5")
    faults.arm("qos.starve", error="none")
    phases.reset()
    qos.yield_point("tree_chunk")
    time.sleep(0.04)
    w = qos.yield_point("tree_chunk", compensate="compute")
    assert w > 0.01
    snap = phases.snapshot()
    assert snap.get("qos_wait_s", 0.0) >= 0.01
    # compensated bucket went negative by the same amount (subtraction
    # happened; the real sites only pass compensate while accounting)
    assert snap.get("compute_s", 0.0) <= -0.01


# ------------------------------------------------------------------ throttle

def test_throttle_hysteresis(monkeypatch):
    """Enter at pressure >= HI, stay throttled between LO and HI, exit
    only at <= LO — exactly two transitions, both counted."""
    monkeypatch.setenv("H2O3_QOS", "1")
    monkeypatch.setenv("H2O3_QOS_PRESSURE_HI", "0.9")
    monkeypatch.setenv("H2O3_QOS_PRESSURE_LO", "0.75")
    monkeypatch.setenv("H2O3_QOS_SLO_MS", "0")  # pressure-only

    cur = {"p": 0.5}

    def fake_view(max_age_s=None):
        return qos.PressureView(cur["p"], cur["p"] >= 0.97,
                                cur["p"] >= 0.9, time.monotonic())

    monkeypatch.setattr(qos, "pressure_view", fake_view)
    assert not qos.throttled()
    cur["p"] = 0.95
    assert qos.throttled()          # transition 1: on
    cur["p"] = 0.8
    assert qos.throttled()          # hysteresis: still on above LO
    cur["p"] = 0.7
    assert not qos.throttled()      # transition 2: off
    assert qos.totals()["throttle_transitions"] == 2


def test_throttle_latency_term(monkeypatch):
    """p99 >= SLO*RATIO_HI alone throttles; exit needs p99 <= SLO*LO."""
    monkeypatch.setenv("H2O3_QOS", "1")
    monkeypatch.setenv("H2O3_QOS_SLO_MS", "10")
    monkeypatch.setenv("H2O3_QOS_P99_RATIO_HI", "2.0")
    monkeypatch.setenv("H2O3_QOS_P99_RATIO_LO", "1.5")
    monkeypatch.setattr(qos, "pressure_view", lambda max_age_s=None:
                        qos.PressureView(0.1, False, False,
                                         time.monotonic()))
    p99 = {"v": 5.0}
    monkeypatch.setattr(qos, "serving_p99_ms", lambda: p99["v"])
    assert not qos.throttled()
    p99["v"] = 25.0                  # 2.5x SLO → throttle
    assert qos.throttled()
    p99["v"] = 17.0                  # 1.7x: above exit ratio → hold
    assert qos.throttled()
    p99["v"] = 12.0                  # 1.2x: below exit ratio → open
    assert not qos.throttled()


def test_admission_gate_bounded_wait(monkeypatch):
    """admission_gate can never deadlock a sweep: the wait is bounded by
    H2O3_QOS_THROTTLE_MAX_WAIT_S even with the throttle stuck closed."""
    monkeypatch.setenv("H2O3_QOS", "1")
    monkeypatch.setenv("H2O3_QOS_THROTTLE_MAX_WAIT_S", "0.15")
    monkeypatch.setenv("H2O3_QOS_THROTTLE_POLL_MS", "20")
    monkeypatch.setattr(qos, "pressure_view", lambda max_age_s=None:
                        qos.PressureView(0.99, True, True,
                                         time.monotonic()))
    t0 = time.monotonic()
    waited = qos.admission_gate("cand_0")
    assert 0.1 <= waited <= 1.0
    assert time.monotonic() - t0 < 2.0
    assert qos.totals()["throttle_waits_ms"] >= 100


# -------------------------------------------------------------- pressure view

def test_pressure_view_invariant(monkeypatch):
    """Within one snapshot shed_serving implies evict_cache (0.97 vs 0.9):
    training artifacts always shed before serving requests do."""
    from h2o3_tpu.runtime import memory_ledger as ml

    for p in (0.5, 0.91, 0.98):
        monkeypatch.setattr(ml, "pressure", lambda p=p: p)
        v = qos.pressure_view()
        assert not (v.shed_serving and not v.evict_cache)
        assert v.value == p
    # threshold ordering that guarantees it
    assert 0.97 >= ml.evict_threshold()


def test_admission_sheds_through_view(monkeypatch):
    """Serving admission's 429 path reads the same snapshot: pressure
    0.98 rejects, pressure 0.5 admits."""
    from h2o3_tpu.runtime import memory_ledger as ml
    from h2o3_tpu.serving.admission import AdmissionController, RejectedError
    from h2o3_tpu.serving.config import ServingConfig
    from h2o3_tpu.serving.metrics import ServingMetrics

    ctl = AdmissionController(ServingConfig(), ServingMetrics())
    monkeypatch.setattr(ml, "pressure", lambda: 0.98)
    with pytest.raises(RejectedError):
        ctl.admit("m")
    monkeypatch.setattr(ml, "pressure", lambda: 0.5)
    ctl.admit("m")
    ctl.release("m")


# ------------------------------------------------------------- observability

def test_gate_state_and_profiler_fold(monkeypatch):
    monkeypatch.setenv("H2O3_QOS", "1")
    from h2o3_tpu.runtime import profiler

    assert qos.gate_state()["holder"] == "idle"
    with qos.serving_dispatch("gbm_7"):
        gs = qos.gate_state()
        assert gs["holder"] == "serving"
        assert gs["serving_detail"] == "gbm_7"
    qos.yield_point("tree_block")
    gs = qos.gate_state()
    assert gs["holder"] == "training"
    assert gs["last_training_site"] == "tree_block"
    fold = profiler.qos_stats()
    assert fold["active"] and fold["totals"]["yields"] == 1


# ----------------------------------------------------------- bit-exactness

def _canon_history(model):
    """Scoring-history rows with NaN canonicalized (NaN != NaN) and the
    wall-clock timestamp dropped."""
    rows = []
    for r in model.scoring_history:
        rows.append({k: ("nan" if isinstance(v, float) and math.isnan(v)
                         else v)
                     for k, v in r.items() if k != "timestamp"})
    return rows


def test_gbm_bit_exact_under_qos(monkeypatch):
    """QoS changes WHEN tree programs dispatch, never what they compute:
    forest, varimp, scoring history and early-stop tree count are
    bit-identical with the gate armed."""
    import jax

    from h2o3_tpu.models.gbm import GBM

    fr = _rng_frame(rows=200, seed=3)
    kw = dict(ntrees=4, max_depth=3, seed=42, score_tree_interval=2)

    monkeypatch.delenv("H2O3_QOS", raising=False)
    m_off = GBM(**kw).train(x=["a", "b", "c", "d"], y="y",
                            training_frame=fr).model
    monkeypatch.setenv("H2O3_QOS", "1")
    qos.reset()
    m_on = GBM(**kw).train(x=["a", "b", "c", "d"], y="y",
                           training_frame=fr).model

    assert m_on.ntrees_built == m_off.ntrees_built
    for a, b in zip(jax.tree_util.tree_leaves(m_on.forest),
                    jax.tree_util.tree_leaves(m_off.forest)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    assert m_on.varimp(use_pandas=False) == m_off.varimp(use_pandas=False)
    assert _canon_history(m_on) == _canon_history(m_off)
    assert qos.totals()["yields"] > 0  # the gate actually ran


def test_kmeans_bit_exact_under_segmentation(monkeypatch):
    """The estimator engine's while_loop segmentation (bounded device
    programs with yields between them) is the identity on results."""
    from h2o3_tpu.models.kmeans import KMeans

    rng = np.random.default_rng(5)
    X = np.concatenate([rng.normal(i * 4.0, 1.0, size=(60, 3))
                        for i in range(3)]).astype(np.float64)
    fr = Frame.from_numpy(X, names=["x0", "x1", "x2"])

    monkeypatch.delenv("H2O3_QOS", raising=False)
    monkeypatch.delenv("H2O3_QOS_EST_ITERS_PER_DISPATCH", raising=False)
    m_off = KMeans(k=3, max_iterations=12, seed=9).train(
        x=["x0", "x1", "x2"], training_frame=fr).model
    monkeypatch.setenv("H2O3_QOS", "1")
    monkeypatch.setenv("H2O3_QOS_EST_ITERS_PER_DISPATCH", "3")
    qos.reset()
    m_on = KMeans(k=3, max_iterations=12, seed=9).train(
        x=["x0", "x1", "x2"], training_frame=fr).model
    assert np.array_equal(np.asarray(m_off.centers()),
                          np.asarray(m_on.centers()))


def test_glm_bit_exact_under_segmentation(monkeypatch):
    from h2o3_tpu.models.glm import GLM

    fr = _rng_frame(rows=200, seed=11, binomial=False)
    kw = dict(family="gaussian", lambda_=0.01, max_iterations=10, seed=1)

    monkeypatch.delenv("H2O3_QOS", raising=False)
    monkeypatch.delenv("H2O3_QOS_EST_ITERS_PER_DISPATCH", raising=False)
    g_off = GLM(**kw).train(x=["a", "b", "c", "d"], y="y",
                            training_frame=fr)
    monkeypatch.setenv("H2O3_QOS", "1")
    monkeypatch.setenv("H2O3_QOS_EST_ITERS_PER_DISPATCH", "3")
    qos.reset()
    g_on = GLM(**kw).train(x=["a", "b", "c", "d"], y="y",
                           training_frame=fr)
    assert g_off.coef() == g_on.coef()


def test_segment_stops(monkeypatch):
    from h2o3_tpu.models import estimator_engine as est

    monkeypatch.delenv("H2O3_QOS", raising=False)
    monkeypatch.delenv("H2O3_QOS_EST_ITERS_PER_DISPATCH", raising=False)
    assert est.max_iters_per_dispatch() == 0      # QoS off: unbounded
    assert est.segment_stops(100) == [100]
    monkeypatch.setenv("H2O3_QOS", "1")
    assert est.max_iters_per_dispatch() == 32     # QoS on: default cap
    monkeypatch.setenv("H2O3_QOS_EST_ITERS_PER_DISPATCH", "3")
    assert est.segment_stops(10) == [3, 6, 9, 10]
    assert est.segment_stops(3) == [3]
    assert est.segment_stops(2) == [2]


# ------------------------------------------------------------------ slow soak

@pytest.mark.slow
def test_qos_concurrent_soak_slow(tmp_path, monkeypatch):
    """Full concurrent soak: live REST server + open-loop serving load
    while an in-process grid sweep trains on the same backend, QoS armed.

    Slow-lane on purpose: tier-1 already consumes ~700 s of its 870 s
    budget and this needs multi-second load windows for stable
    percentiles. BENCH_CONFIG=qos runs the same flow with assertions on
    the p99 ratio; here we assert completion + gate activity only."""
    import sys

    sys.path.insert(0, "/root/repo/deploy")
    try:
        import loadgen
    finally:
        sys.path.pop(0)

    from h2o3_tpu.models.gbm import GBM
    from h2o3_tpu.rest.server import start_server
    from h2o3_tpu.runtime.dkv import DKV

    fr = _rng_frame(rows=600, seed=2)
    est = GBM(ntrees=5, max_depth=3, seed=42).train(
        x=["a", "b", "c", "d"], y="y", training_frame=fr)
    DKV.put("soak_gbm", est.model)
    DKV.put(fr.key, fr)
    monkeypatch.setenv("H2O3_QOS", "1")
    qos.reset()
    srv = start_server(port=0)
    try:
        stats = loadgen.run_concurrent_sweep(
            "127.0.0.1", srv.port, "soak_gbm", fr.key,
            rate=8.0, window_s=3.0, candidates=2, sweep_rows=4000,
            sweep_ntrees=4, timeout_s=30.0, idle=False)
    finally:
        srv.stop()
    assert stats["sweep"].get("done") == 2
    assert stats["completed"] > 0
    assert stats["contended"]["p99_ms"] > 0
    assert qos.totals()["yields"] > 0
