"""Mid-fit supervisor (ISSUE 20): checkpoint store round-trip +
rejection discipline, `after=` fault placement, the deadline'd collective
fence abort, the supervisor state machine + fleet mark-down, the GBM
kill-at-tree-k → resume-bit-identical pin, the estimator segment-carry
snapshots, the SweepCheckpoint in-flight rider, and the tier-1 budget
tool. The multi-interpreter pod_chaos pin (2-process rank kill) lives in
the slow lane — each spawned interpreter cold-compiles for minutes,
which the tier-1 budget cannot absorb."""

import json
import os
import sys
import time

import numpy as np
import pytest

from h2o3_tpu.runtime import faults, supervisor, trainpool

from conftest import make_classification


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    faults.reset()
    supervisor.reset()
    trainpool.reset()
    monkeypatch.delenv("H2O3_CKPT", raising=False)
    monkeypatch.delenv("H2O3_CKPT_DIR", raising=False)
    monkeypatch.delenv("H2O3_CKPT_TREES", raising=False)
    monkeypatch.delenv("H2O3_FENCE_DEADLINE_S", raising=False)
    yield
    faults.reset()
    supervisor.reset()


def _totals():
    return supervisor.snapshot()["totals"]


# -- checkpoint store ---------------------------------------------------------

def test_ckpt_roundtrip_single_rank(tmp_path):
    d = str(tmp_path)
    fp = supervisor.run_fingerprint(algo="t", rows=100, seed=7)
    arrays = dict(a=np.arange(12, dtype=np.float32).reshape(3, 4),
                  b=np.array([1.5, -2.25], np.float64))
    supervisor.save_fit_checkpoint(d, "tree", fp, 5, arrays,
                                   meta=dict(history=[{"m": 5}]))
    rec = supervisor.latest_fit_checkpoint(d, "tree", fp)
    assert rec["step"] == 5 and rec["nproc"] == 1
    sh = rec["shards"][0]
    assert np.array_equal(sh["a"], arrays["a"]) and sh["a"].dtype == np.float32
    assert np.array_equal(sh["b"], arrays["b"])
    assert rec["meta"]["history"] == [{"m": 5}]
    # newest step wins; keep=2 GC drops the oldest of three
    supervisor.save_fit_checkpoint(d, "tree", fp, 10, arrays)
    supervisor.save_fit_checkpoint(d, "tree", fp, 15, arrays)
    assert supervisor.latest_fit_checkpoint(d, "tree", fp)["step"] == 15
    steps = sorted(int(f.split("_s")[1][:8]) for f in os.listdir(d))
    assert steps == [10, 15]
    assert not any(f.endswith(".part") for f in os.listdir(d))


def test_ckpt_multirank_requires_complete_rank_set(tmp_path):
    d = str(tmp_path)
    fp = supervisor.run_fingerprint(algo="t", rows=100)
    a = dict(x=np.ones(3, np.float32))
    # step 8: both ranks present; step 12: rank 1 missing (died mid-save)
    supervisor.save_fit_checkpoint(d, "tree", fp, 8, a, rank=0, nproc=2)
    supervisor.save_fit_checkpoint(d, "tree", fp, 8, a, rank=1, nproc=2)
    supervisor.save_fit_checkpoint(d, "tree", fp, 12, a, rank=0, nproc=2)
    r0 = _totals()["ckpt_rejects"]
    rec = supervisor.latest_fit_checkpoint(d, "tree", fp)
    assert rec["step"] == 8 and rec["nproc"] == 2
    assert len(rec["shards"]) == 2
    assert _totals()["ckpt_rejects"] == r0 + 1   # the torn step-12 set


def test_ckpt_fingerprint_mismatch_never_restores(tmp_path):
    d = str(tmp_path)
    fp_a = supervisor.run_fingerprint(seed=1, rows=100)
    fp_b = supervisor.run_fingerprint(seed=2, rows=100)
    assert fp_a != fp_b
    supervisor.save_fit_checkpoint(d, "tree", fp_a, 5,
                                   dict(x=np.zeros(2, np.float32)))
    assert supervisor.latest_fit_checkpoint(d, "tree", fp_b) is None


def test_run_fingerprint_sanitizes_and_orders():
    a = supervisor.run_fingerprint(rows=np.int64(100), lr=np.float32(0.1),
                                   cols=("a", "b"))
    b = supervisor.run_fingerprint(cols=["a", "b"], lr=0.10000000149011612,
                                   rows=100)
    assert a == b and len(a) == 16


def test_ckpt_truncated_rejected_falls_back_to_older(tmp_path):
    d = str(tmp_path)
    fp = supervisor.run_fingerprint(seed=3)
    a = dict(x=np.arange(64, dtype=np.float32))
    supervisor.save_fit_checkpoint(d, "tree", fp, 5, a)
    p10 = supervisor.save_fit_checkpoint(d, "tree", fp, 10, a)
    with open(p10, "rb") as f:
        blob = f.read()
    with open(p10, "wb") as f:
        f.write(blob[: len(blob) // 2])   # torn exactly like a crash
    r0 = _totals()["ckpt_rejects"]
    rec = supervisor.latest_fit_checkpoint(d, "tree", fp)
    assert rec["step"] == 5                      # fell back, didn't die
    assert _totals()["ckpt_rejects"] == r0 + 1


def test_ckpt_corrupt_fault_produces_rejected_snapshot(tmp_path):
    d = str(tmp_path)
    fp = supervisor.run_fingerprint(seed=4)
    a = dict(x=np.arange(32, dtype=np.float32))
    faults.arm("supervisor.ckpt_corrupt", error="io", count=1)
    supervisor.save_fit_checkpoint(d, "tree", fp, 5, a)   # torn on disk
    assert faults.snapshot()["points"][0]["fires"] == 1
    assert supervisor.latest_fit_checkpoint(d, "tree", fp) is None
    # the next save (fault exhausted) is valid and restores normally
    supervisor.save_fit_checkpoint(d, "tree", fp, 10, a)
    assert supervisor.latest_fit_checkpoint(d, "tree", fp)["step"] == 10


# -- `after=` fault placement -------------------------------------------------

def test_fault_after_skips_first_k_checks():
    faults.arm("p.x", error="io", count=1, after=2)
    faults.check("p.x")
    faults.check("p.x")
    with pytest.raises(faults.InjectedIOError):
        faults.check("p.x")
    faults.check("p.x")   # count=1 exhausted
    desc = faults.snapshot()["points"][0]
    assert desc["after"] == 2 and desc["fires"] == 1 and desc["checks"] == 4


def test_fault_after_parses_from_env(monkeypatch):
    monkeypatch.setenv("H2O3_FAULT_MESH_RANK_KILL",
                       "error=crash,count=1,after=12")
    faults._env_parse()
    desc = [p for p in faults.snapshot()["points"]
            if p["point"] == "mesh.rank_kill"][0]
    assert desc["after"] == 12 and desc["count"] == 1
    assert desc["error"] == "crash"


# -- deadline'd fence ---------------------------------------------------------

def test_deadline_block_aborts_hung_collective():
    t0 = _totals()
    with pytest.raises(supervisor.CollectiveTimeout) as ei:
        supervisor.deadline_block(None, timeout_s=0.2, tag="fence7",
                                  _blocker=lambda: time.sleep(30))
    assert "fence7" in str(ei.value)
    assert isinstance(ei.value, TimeoutError)   # retry-classifier: transient
    t1 = _totals()
    assert t1["aborts"] == t0["aborts"] + 1
    snap = supervisor.snapshot()
    assert snap["state"] == "aborted"
    assert snap["last_abort"]["tag"] == "fence7"
    assert snap["last_abort"]["latency_s"] >= 0.19
    assert snap["detect_ms"]["count"] >= 1


def test_deadline_block_passes_results_and_errors_through():
    hits = []
    supervisor.deadline_block(None, timeout_s=5.0,
                              _blocker=lambda: hits.append(1))
    assert hits == [1]
    # no deadline configured → direct call, no worker thread
    supervisor.deadline_block(None, timeout_s=0,
                              _blocker=lambda: hits.append(2))
    assert hits == [1, 2]

    def boom():
        raise ValueError("bad dispatch")

    with pytest.raises(ValueError, match="bad dispatch"):
        supervisor.deadline_block(None, timeout_s=5.0, _blocker=boom)


def test_state_machine_and_snapshot():
    supervisor.fit_started("tree", "fp123", total=40)
    supervisor.pulse("tree", 10)
    s = supervisor.snapshot()
    assert s["state"] == "watching"
    assert s["fit"]["tag"] == "tree" and s["fit"]["total"] == 40
    assert s["heartbeat"]["step"] == 10
    supervisor.fit_finished("other")      # stale tag: no-op
    assert supervisor.snapshot()["state"] == "watching"
    supervisor.fit_finished("tree")
    s = supervisor.snapshot()
    assert s["state"] == "idle" and s["fit"] is None
    assert set(s["totals"]) == {"aborts", "resumes", "ckpt_saves",
                                "ckpt_rejects", "marked_down"}
    assert s["config"]["ckpt_trees"] == 25


def test_mark_ranks_down_flips_fleet_peer_up_gauge():
    from h2o3_tpu.runtime import fleet

    supervisor.mark_ranks_down([3], reason="test")
    assert fleet._registry()["peer_up"].value("rank3") == 0.0


# -- GBM kill-at-tree-k → resume (the tier-1 pin) -----------------------------

def _gbm_frame():
    from h2o3_tpu.frame.frame import Frame

    X, y = make_classification(200, 3, seed=11)
    return Frame.from_numpy(
        np.column_stack([X, y]), names=["x0", "x1", "x2", "y"]
    ).asfactor("y")


def _fit_gbm(fr):
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

    est = H2OGradientBoostingEstimator(ntrees=9, max_depth=2, seed=13,
                                       score_tree_interval=3)
    est.train(y="y", training_frame=fr)
    return est.model


def _assert_models_bitidentical(a, b):
    assert len(a.forest) == len(b.forest)
    for ta, tb in zip(a.forest, b.forest):
        for f in ("feat", "bin", "thr", "value"):
            assert np.array_equal(np.asarray(getattr(ta, f)),
                                  np.asarray(getattr(tb, f))), f
    assert [r[0] for r in a.varimp_table] == [r[0] for r in b.varimp_table]
    assert np.array_equal(
        np.asarray([r[1] for r in a.varimp_table], np.float64),
        np.asarray([r[1] for r in b.varimp_table], np.float64))
    for ra, rb in zip(a.scoring_history, b.scoring_history):
        for k, va in ra.items():
            if k == "timestamp":
                continue
            vb = rb[k]
            if (isinstance(va, float) and isinstance(vb, float)
                    and np.isnan(va) and np.isnan(vb)):
                continue
            assert va == vb, k


def test_gbm_midfit_kill_and_resume_bitidentical(cloud1, monkeypatch,
                                                 tmp_path):
    """The ISSUE 20 tier-1 acceptance pin: a fit killed at tree k with
    H2O3_CKPT_TREES=c resumes from its snapshot, retrains <= c trees, and
    the final model is BIT-identical (forest, varimp, scoring history) to
    an undisturbed fit — and H2O3_CKPT=0 disables the whole machinery."""
    fr = _gbm_frame()
    ref = _fit_gbm(fr)                      # baseline: checkpointing off

    d = str(tmp_path / "ck")
    monkeypatch.setenv("H2O3_CKPT_DIR", d)
    monkeypatch.setenv("H2O3_CKPT_TREES", "3")

    # escape hatch first: H2O3_CKPT=0 with a dir set writes nothing and
    # matches the pre-supervisor fit bit-for-bit
    monkeypatch.setenv("H2O3_CKPT", "0")
    off = _fit_gbm(fr)
    _assert_models_bitidentical(off, ref)
    assert not os.path.exists(d) or not os.listdir(d)
    monkeypatch.setenv("H2O3_CKPT", "1")

    # kill at the second chunk (after=1 skips the m=0 boundary): the
    # m=0..2 chunk completed and checkpointed at step 3 before the crash
    faults.arm("supervisor.fit_abort", error="crash", count=1, after=1)
    with pytest.raises(faults.InjectedCrash):
        _fit_gbm(fr)
    assert any(f.startswith("fitckpt_tree_") for f in os.listdir(d))
    assert supervisor.snapshot()["state"] == "watching"  # died mid-fit

    resumed = _fit_gbm(fr)                  # same params → restores
    s = supervisor.snapshot()
    assert s["last_resume"] is not None
    assert s["last_resume"]["step"] == 3    # retrained 9-3=6 <= ntrees
    assert s["totals"]["resumes"] >= 1
    assert trainpool.snapshot()["totals"]["resumed_mid_fit"] >= 1
    assert s["state"] == "idle"             # fit_finished after resume
    assert resumed.ntrees_built == ref.ntrees_built
    _assert_models_bitidentical(resumed, ref)


# -- estimator segment carry --------------------------------------------------

def test_estimator_segment_carry_roundtrip(monkeypatch, tmp_path):
    import jax.numpy as jnp

    from h2o3_tpu.models import estimator_engine as _est

    # gate: no ckpt dir → fingerprint None → save/restore are no-ops
    assert _est.segment_fingerprint("kmeans", rows=10) is None
    monkeypatch.setenv("H2O3_CKPT_DIR", str(tmp_path))
    fp = _est.segment_fingerprint("kmeans", rows=10, k=3, seed=1)
    assert fp is not None
    carry = (jnp.arange(6, dtype=jnp.float32).reshape(2, 3),
             jnp.asarray(4, jnp.int32), jnp.asarray(0.25, jnp.float32))
    _est.segment_carry_save("kmeans", fp, 4, carry)
    step, back = _est.segment_carry_restore("kmeans", fp)
    assert step == 4 and len(back) == 3
    for orig, rb in zip(carry, back):
        assert np.array_equal(np.asarray(orig), np.asarray(rb))
        assert np.asarray(orig).dtype == np.asarray(rb).dtype
    assert trainpool.snapshot()["totals"]["resumed_mid_fit"] >= 1
    # a different fit identity must not see these snapshots
    assert _est.segment_carry_restore(
        "kmeans", _est.segment_fingerprint("kmeans", rows=11)) is None


def test_kmeans_segmented_fit_checkpoints_and_resumes(cloud1, monkeypatch,
                                                      tmp_path):
    """A segmented (QoS-capped) K-Means fit snapshots its carry at segment
    boundaries; a re-run fit restores and lands on bitwise-identical
    centroids."""
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models.kmeans import H2OKMeansEstimator

    # unstructured data: Lloyd must NOT converge inside 6 iterations, or
    # the done-gate skips every segment save and there is nothing to
    # restore (well-separated blobs converge in ~2)
    rng = np.random.default_rng(5)
    X = rng.normal(size=(240, 3))
    fr = Frame.from_numpy(X, names=["a", "b", "c"])
    monkeypatch.setenv("H2O3_QOS_EST_ITERS_PER_DISPATCH", "2")

    def _fit():
        km = H2OKMeansEstimator(k=3, max_iterations=6, seed=1)
        km.train(training_frame=fr)
        return np.asarray(km.model.centers_std, np.float64)

    ref = _fit()                            # no ckpt dir: plain segmented
    monkeypatch.setenv("H2O3_CKPT_DIR", str(tmp_path))
    c1 = _fit()
    assert any(f.startswith("fitckpt_estkmeans_")
               for f in os.listdir(tmp_path))
    assert np.array_equal(c1, ref)
    c2 = _fit()                             # restores a saved carry
    assert supervisor.snapshot()["totals"]["resumes"] >= 1
    assert np.array_equal(c2, ref)


# -- SweepCheckpoint in-flight rider ------------------------------------------

def test_sweep_checkpoint_inflight_roundtrip(tmp_path):
    d = str(tmp_path)
    ck = trainpool.SweepCheckpoint(d, "sw1", fingerprint=dict(seed=1))
    ck.mark_inflight("GBM_1", dict(ckpt_dir="/ck", fingerprint="abc"))
    # a killed sweep leaves the pointer on disk for the re-run
    ck2 = trainpool.SweepCheckpoint(d, "sw1", fingerprint=dict(seed=1))
    info = ck2.inflight("GBM_1")
    assert info["ckpt_dir"] == "/ck" and info["fingerprint"] == "abc"
    # completion clears it — a finished candidate needs no pointer
    ck2.mark("GBM_1", dict(auc=0.9))
    ck3 = trainpool.SweepCheckpoint(d, "sw1", fingerprint=dict(seed=1))
    assert ck3.inflight("GBM_1") is None and ck3.inflight() == {}
    assert ck3.completed("GBM_1") == {"auc": 0.9}
    # a mismatched fingerprint drops in-flight pointers with the records
    ck4 = trainpool.SweepCheckpoint(d, "sw1", fingerprint=dict(seed=2))
    assert ck4.completed("GBM_1") is None and ck4.inflight() == {}


# -- tools/t1_budget ----------------------------------------------------------

def _t1_budget():
    import importlib.util

    p = os.path.join(os.path.dirname(__file__), "..", "tools",
                     "t1_budget.py")
    spec = importlib.util.spec_from_file_location("t1_budget", p)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_t1_budget_parses_and_thresholds(tmp_path, monkeypatch, capsys):
    tb = _t1_budget()
    log = tmp_path / "t1.log"
    log.write_text(
        "....\n"
        "2.50s call     tests/test_a.py::test_slow\n"
        "0.30s setup    tests/test_a.py::test_slow\n"
        "1.10s call     tests/test_b.py::test_other\n"
        "709 passed, 1 skipped in 633.50s\n")
    durations, wall = tb.parse(str(log))
    assert wall == 633.50 and len(durations) == 3
    monkeypatch.setenv("T1_BUDGET_SOFT_S", "700")
    assert tb.main([str(log)]) == 0
    out = capsys.readouterr().out
    assert "634s" in out and "test_a.py::test_slow" in out
    monkeypatch.setenv("T1_BUDGET_SOFT_S", "600")
    assert tb.main([str(log)]) == 1          # past the soft threshold
    assert tb.main([str(tmp_path / "missing.log")]) == 2
    empty = tmp_path / "empty.log"
    empty.write_text("hello\n")
    assert tb.main([str(empty)]) == 2


# -- slow lane: the multi-interpreter pod_chaos pin ---------------------------

@pytest.mark.slow
def test_pod_chaos_rank_kill_resume_bitidentical():
    """The full ISSUE 20 acceptance drill — 2-process pod GBM fit, rank 1
    hard-killed mid-fit (mesh.rank_kill), survivor aborts within the
    fence deadline, degraded single-host resume bit-identical to an
    undisturbed run. Slow lane (tracked reason): every spawned
    interpreter cold-compiles its own jit cache — minutes per run, far
    past the tier-1 budget; the in-process pin above covers the
    state-machine/checkpoint logic in tier-1."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from bench import bench_pod_chaos

    name, detect_s, details = bench_pod_chaos()
    assert name == "pod_chaos_detect_s"
    assert details["bitexact"] is True
    assert details["aborts"] >= 1 or details["abort_error"]
    assert details["trees_retrained"] <= 20
    assert np.isfinite(detect_s) and detect_s > 0
