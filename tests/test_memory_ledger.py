"""Memory ledger (ISSUE 8) — unified host+device byte accounting, pressure
signals and leak detection.

The acceptance pins live here: ledger-vs-census attribution reconciliation
on a real GBM fit + predict (the unattributed remainder is explicit, never
silently absorbed), kill-the-frame leak detection fires AND clears,
pressure-driven dataset-cache eviction in LRU order, the `GET /3/Memory` /
Prometheus / MemoryV3 schema surfaces, DKV.stats() delegation (the two
surfaces can never disagree), and the loadgen sustained-mode leak canary.
"""

import gc
import json
import os
import urllib.request

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.runtime import memory_ledger as ml
from h2o3_tpu.runtime import metrics_registry as registry
from h2o3_tpu.runtime.dkv import DKV
from h2o3_tpu.runtime.timeline import Timeline


def _cls_frame(key, n=400, f=4, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + X[:, 1] > 0).astype(np.int64)
    d = {f"x{i}": X[:, i] for i in range(f)}
    d["y"] = np.asarray(["n", "p"], dtype=object)[y]
    fr = Frame.from_dict(d, column_types={"y": "enum"})
    fr.key = key
    DKV.put(key, fr)
    return fr


def _gbm(fr, **kw):
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

    est = H2OGradientBoostingEstimator(
        ntrees=kw.pop("ntrees", 3), max_depth=kw.pop("max_depth", 3),
        seed=kw.pop("seed", 1), **kw)
    est.train(x=[c for c in fr.names if c != "y"], y="y",
              training_frame=fr)
    return est


def _census_device_bytes():
    import jax

    return sum(int(a.nbytes) for a in jax.live_arrays())


# -- measure(): the one deep sizer --------------------------------------------

def test_measure_counts_jax_and_nested_buffers(cloud1):
    import jax.numpy as jnp

    arr = np.zeros((1000, 4), np.float32)
    h, d = ml.measure(arr)
    assert (h, d) == (16000, 0)
    dev = jnp.zeros((256, 4), jnp.float32)
    h, d = ml.measure(dev)
    assert h == 0 and d == 256 * 4 * 4
    # nested: a dict holding both plus a Frame
    fr = Frame.from_dict({"a": np.arange(100.0)})
    h, d = ml.measure({"host": arr, "dev": dev, "frame": fr})
    assert h >= 16000 + 100 * 4 and d == 256 * 4 * 4
    # shared-buffer dedup inside one graph
    h2, _ = ml.measure({"x": arr, "y": arr})
    assert h2 == 16000


def test_dkv_nbytes_counts_device_values_and_stats_delegates(cloud1):
    """Satellite: DKV._nbytes no longer reports ~0 for device-resident
    values, and DKV.stats() is the ledger's view — one accounting."""
    import jax.numpy as jnp

    class Holder:
        pass

    h = Holder()
    h.pack = jnp.zeros((512, 6), jnp.float32)     # a device-resident value
    assert DKV._nbytes(h) >= 512 * 6 * 4
    DKV.put("ml_dev_holder", h)
    try:
        st = DKV.stats()
        assert st["by_kind"]["Holder"]["bytes"] >= 512 * 6 * 4
        # the two surfaces are the same store: every DKV entry is a ledger
        # dkv: owner and the by-kind sums agree by construction
        assert st == ml.dkv_stats()
        dkv_owners = ml.owners("dkv:ml_dev_holder")
        assert len(dkv_owners) == 1
        assert dkv_owners[0]["device_bytes"] >= 512 * 6 * 4
    finally:
        DKV.remove("ml_dev_holder")
    assert ml.owners("dkv:ml_dev_holder") == []


# -- attribution reconciliation (THE acceptance pin) ---------------------------

def test_attribution_reconciliation_gbm_fit_predict(cloud1):
    """≥95% of the device bytes a GBM train + predict leaves resident must
    be attributed to named owners; the remainder is explicitly
    `unaccounted` in /3/Memory, never silently absorbed."""
    from h2o3_tpu.models import dataset_cache

    dataset_cache.clear()
    gc.collect()
    ml.refresh(force=True)
    census0 = _census_device_bytes()
    dev0 = ml.totals()["device_bytes"]

    fr = _cls_frame("ml_attr_fr", n=20_000, f=8, seed=3)
    est = _gbm(fr, ntrees=5, max_depth=4)
    DKV.put("ml_attr_gbm", est.model)
    pred = est.model.predict(fr)
    assert pred.nrow == fr.nrow

    gc.collect()
    snap = ml.snapshot()
    census1 = _census_device_bytes()
    dev1 = snap["totals"]["device_bytes"]
    delta_census = census1 - census0
    delta_ledger = dev1 - dev0
    assert delta_census > 10_000, \
        f"workload left no device bytes to attribute ({delta_census})"
    assert delta_ledger >= 0.95 * delta_census - 65_536, \
        (f"ledger attributed {delta_ledger} of {delta_census} "
         f"census-new device bytes; owners={snap['owners'][:6]}")
    # the reconciliation contract: probe - attributed == unaccounted ≥ 0
    probe = snap["device"]
    assert probe["probe"] in ("census", "memory_stats")
    assert snap["totals"]["unaccounted_device_bytes"] == max(
        int(probe["in_use_bytes"]) - dev1, 0)
    # named owners of the taxonomy actually carry the bytes
    kinds = snap["by_kind"]
    assert "dataset_cache" in kinds and "model" in kinds
    DKV.remove("ml_attr_gbm")
    DKV.remove("ml_attr_fr")


# -- leak detection ------------------------------------------------------------

def test_kill_the_frame_leak_fires_and_clears(cloud1):
    """A dead owner whose buffers persist (something else pins them) is a
    leak: h2o3_memory_leaked_bytes rises and a timeline event fires; when
    the buffers are finally released the leak CLEARS and the owner
    retires."""
    fr = _cls_frame("ml_leak_fr", n=500)
    hold = {"buf": fr.vec("x0").data}      # the rogue cache pinning a buffer
    ml.register("frame:ml_leak_probe", kind="frame", referent=fr,
                bytes_fn=lambda: (hold["buf"].nbytes if "buf" in hold
                                  else 0, 0))
    ml.refresh(force=True)
    assert not any(l["owner"] == "frame:ml_leak_probe"
                   for l in ml.snapshot()["leaks"])
    DKV.remove("ml_leak_fr")
    del fr
    gc.collect()
    cur = Timeline.cursor()
    snap = ml.snapshot()
    leaks = [l for l in snap["leaks"] if l["owner"] == "frame:ml_leak_probe"]
    assert leaks and leaks[0]["reason"] == "referent_dead"
    assert snap["totals"]["leaked_bytes"] >= 500 * 4
    assert registry.get("h2o3_memory_leaked_bytes").value() >= 500 * 4
    evs = [e for e in Timeline.snapshot(n=10_000)
           if e["kind"] == "memory" and "leak frame:ml_leak_probe"
           in e["detail"]]
    assert evs, "leak did not land in the timeline"
    # release the pinned buffer → the leak clears and the gauge drops
    hold.clear()
    snap2 = ml.snapshot()
    assert not any(l["owner"] == "frame:ml_leak_probe"
                   for l in snap2["leaks"])
    assert not any(o["owner"] == "frame:ml_leak_probe"
                   for o in ml.owners("frame:ml_leak_probe"))
    cleared = [e for e in Timeline.snapshot(since=cur, n=10_000)
               if e["kind"] == "memory"
               and "leak_cleared frame:ml_leak_probe" in e["detail"]]
    assert cleared


def test_frame_death_cleans_cache_owners_without_leak(cloud1):
    """The healthy path: killing a frame drops its dataset-cache entry via
    weakref, unregisters the ledger owners and leaks NOTHING."""
    from h2o3_tpu.models import dataset_cache

    dataset_cache.clear()
    fr = _cls_frame("ml_clean_fr", n=300)
    _gbm(fr, ntrees=2, max_depth=2)
    ml.refresh(force=True)
    assert ml.owners("dataset_cache:"), "fit registered no cache owners"
    base0 = ml.snapshot()["totals"]["leaked_bytes"]
    DKV.remove("ml_clean_fr")
    del fr
    gc.collect()
    snap = ml.snapshot()
    assert ml.owners("dataset_cache:") == []
    assert snap["totals"]["leaked_bytes"] <= base0


def test_job_end_leak_fires_and_clears(cloud1):
    """DKV keys not freed after a failed job surface in the leak report
    (and in h2o3_memory_leaked_bytes) until the key is removed."""
    fr = _cls_frame("ml_job_fr", n=300)
    est = _gbm(fr, ntrees=2, max_depth=2)
    DKV.put("ml_job_partial", est.model)
    ml.job_end("ml_job_partial", "FAILED")
    snap = ml.snapshot()
    leaks = [l for l in snap["leaks"] if l["owner"] == "dkv:ml_job_partial"]
    assert leaks and leaks[0]["reason"] == "job_failed"
    assert leaks[0]["bytes"] > 0
    DKV.remove("ml_job_partial")
    snap2 = ml.snapshot()
    assert not any(l["owner"] == "dkv:ml_job_partial"
                   for l in snap2["leaks"])
    DKV.remove("ml_job_fr")
    # a DONE job never flags anything
    DKV.put("ml_job_done", est.model)
    ml.job_end("ml_job_done", "DONE")
    assert not any(l["owner"] == "dkv:ml_job_done"
                   for l in ml.snapshot()["leaks"])
    DKV.remove("ml_job_done")


# -- pressure ------------------------------------------------------------------

def test_pressure_threshold_crossing_events(cloud1):
    events = registry.get("h2o3_memory_events") or ml._registry()["events"]
    before_hi = events.value("pressure_high", "ledger")
    before_lo = events.value("pressure_normal", "ledger")
    os.environ["H2O3_MEM_BUDGET_MB"] = "1"     # rss >> 1MB → pressure 1.0
    try:
        st = ml.refresh(force=True)
        assert st["pressure"]["value"] == 1.0
        assert ml.pressure() == 1.0
        assert events.value("pressure_high", "ledger") == before_hi + 1
    finally:
        os.environ.pop("H2O3_MEM_BUDGET_MB", None)
    st = ml.refresh(force=True)
    assert st["pressure"]["value"] < 1.0
    assert events.value("pressure_normal", "ledger") == before_lo + 1


def test_pressure_driven_cache_eviction_lru_order(cloud1, monkeypatch):
    """Past H2O3_MEM_EVICT_PRESSURE the dataset cache sheds LRU entries —
    oldest first, each eviction a traced `pressure` event."""
    from h2o3_tpu.models import dataset_cache

    dataset_cache.clear()
    frames = [_cls_frame(f"ml_press_{i}", n=300, seed=10 + i)
              for i in range(3)]
    _gbm(frames[0], ntrees=2, max_depth=2)
    owners0 = {o["owner"].rsplit(":", 1)[0]
               for o in ml.owners("dataset_cache:")}
    assert len(owners0) == 1
    base0 = owners0.pop()
    _gbm(frames[1], ntrees=2, max_depth=2)
    bases = {o["owner"].rsplit(":", 1)[0]
             for o in ml.owners("dataset_cache:")}
    base1 = (bases - {base0}).pop()
    cur = Timeline.cursor()
    monkeypatch.setenv("H2O3_MEM_BUDGET_MB", "1")
    monkeypatch.setenv("H2O3_MEM_EVICT_PRESSURE", "0.5")
    try:
        ml.refresh(force=True)
        _gbm(frames[2], ntrees=2, max_depth=2)
        evs = [e for e in Timeline.snapshot(since=cur, n=10_000)
               if e["kind"] == "memory" and e.get("trigger") == "pressure"]
        owners_evicted = [e["owner"] for e in evs]
        assert base0 in owners_evicted and base1 in owners_evicted, evs
        assert owners_evicted.index(base0) < owners_evicted.index(base1), \
            "pressure eviction was not LRU-ordered"
        s = dataset_cache.snapshot()
        assert s["entries"] == 1 and s["evictions"] >= 2
    finally:
        monkeypatch.delenv("H2O3_MEM_BUDGET_MB", raising=False)
        ml.refresh(force=True)     # drop the cached pressure=1.0 state
    for fr in frames:
        DKV.remove(fr.key)


# -- scorer cache + eviction events -------------------------------------------

def test_scorer_owner_attributes_deleted_model_and_evict_events(cloud1):
    """While the DKV holds a model its scorer owner reports 0 (no double
    count); after DELETE the compiled-scorer cache is what pins it and the
    bytes move to `scorer:<key>:<kind>`; invalidation emits an evict
    event."""
    from h2o3_tpu.serving.model_cache import ScorerCache

    fr = _cls_frame("ml_sc_fr", n=300)
    est = _gbm(fr, ntrees=2, max_depth=2)
    DKV.put("ml_sc_gbm", est.model)
    cache = ScorerCache(capacity=4)
    entry, hit = cache.get_or_build("ml_sc_gbm", est.model, "predict")
    assert not hit
    ml.refresh(force=True)
    (own,) = ml.owners("scorer:ml_sc_gbm:predict")
    assert own["host_bytes"] + own["device_bytes"] == 0   # DKV accounts it
    DKV.remove("ml_sc_gbm")
    DKV.remove(est.model.model_id)   # train auto-registered this key too
    ml.refresh(force=True)
    (own,) = ml.owners("scorer:ml_sc_gbm:predict")
    assert own["host_bytes"] + own["device_bytes"] > 0    # scorer pins it
    cur = Timeline.cursor()
    cache.invalidate("ml_sc_gbm")
    assert ml.owners("scorer:ml_sc_gbm:predict") == []
    evs = [e for e in Timeline.snapshot(since=cur, n=1000)
           if e["kind"] == "memory"
           and e["owner"] == "scorer:ml_sc_gbm:predict"]
    assert evs and evs[0]["trigger"] == "invalidate"
    assert evs[0]["bytes"] > 0
    DKV.remove("ml_sc_fr")


def test_dataset_cache_cap_eviction_emits_event(cloud1, monkeypatch):
    """Satellite: cap evictions are no longer silent — owner, bytes freed
    and the trigger land in the timeline (and the events counter)."""
    from h2o3_tpu.models import dataset_cache

    dataset_cache.clear()
    monkeypatch.setenv("H2O3_DATASET_CACHE_ENTRIES", "1")
    events = ml._registry()["events"]
    before = events.value("evict", "dataset_cache")
    fr1 = _cls_frame("ml_cap_1", n=300, seed=20)
    fr2 = _cls_frame("ml_cap_2", n=300, seed=21)
    _gbm(fr1, ntrees=2, max_depth=2)
    cur = Timeline.cursor()
    _gbm(fr2, ntrees=2, max_depth=2)
    evs = [e for e in Timeline.snapshot(since=cur, n=10_000)
           if e["kind"] == "memory" and e.get("trigger") == "cap"
           and e["owner"].startswith("dataset_cache:")]
    assert evs and evs[0]["bytes"] > 0
    assert events.value("evict", "dataset_cache") > before
    DKV.remove("ml_cap_1")
    DKV.remove("ml_cap_2")


def test_ingest_buffer_accounted(cloud1):
    from h2o3_tpu.frame import chunked

    events = ml._registry()["events"]
    before = events.value("alloc", "ingest")
    cols, info = chunked.tokenize_data(b"a,b\n1,2\n3,4\n", ",", True, 2)
    assert len(cols) == 2
    assert events.value("alloc", "ingest") == before + 1
    ml.refresh(force=True)
    (own,) = ml.owners("ingest:tokenize")
    assert own["host_bytes"] == 0      # transient: released after the parse


# -- REST + loadgen surfaces ---------------------------------------------------

@pytest.fixture(scope="module")
def mem_server():
    from h2o3_tpu.rest import start_server

    srv = start_server(port=0)
    yield srv
    srv.stop()


def _http(port, path, post=False):
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}",
                                 data=(b"" if post else None))
    with urllib.request.urlopen(req) as r:
        raw = r.read()
        return (json.loads(raw) if "json" in r.headers.get("Content-Type",
                                                           "") else raw)


def test_rest_memory_json_schema_and_prometheus(mem_server, cloud1):
    fr = _cls_frame("ml_rest_fr", n=500)
    doc = _http(mem_server.port, "/3/Memory")
    assert doc["__meta"]["schema_type"] == "MemoryV3"
    assert doc["totals"]["owner_count"] >= 1
    assert any(o["owner"] == "dkv:ml_rest_fr" for o in doc["owners"])
    assert 0.0 <= doc["pressure"]["value"] <= 1.0
    assert doc["device"]["probe"] in ("census", "memory_stats",
                                      "unavailable")
    assert doc["watermarks"]["total_bytes"] >= doc["totals"]["host_bytes"]
    sch = _http(mem_server.port, "/3/Memory?schema=1")
    assert sch["name"] == "MemoryV3" and sch["fields"]
    meta = _http(mem_server.port, "/3/Metadata/schemas")
    assert any(s.get("name") == "MemoryV3" for s in meta["schemas"])
    text = _http(mem_server.port, "/3/Metrics").decode()
    for needle in ("h2o3_memory_bytes", "h2o3_memory_pressure",
                   "h2o3_memory_leaked_bytes", "h2o3_memory_owners",
                   "h2o3_memory_high_watermark_bytes",
                   'owner_kind="unaccounted"'):
        assert needle in text, f"{needle} missing from /3/Metrics"
    prof = _http(mem_server.port, "/3/Profiler")
    assert prof["memory"]["totals"]["owner_count"] >= 1
    # metrics-consistency contract: every numeric totals field of
    # /3/Memory is declared registry-backed (bind_rest_field)
    declared = registry.rest_bindings().get("memory", {})
    for k, v in doc["totals"].items():
        if isinstance(v, (int, float)):
            assert f"totals.{k}" in declared, f"totals.{k} not bound"
    DKV.remove("ml_rest_fr")


def test_loadgen_leak_canary_fields(mem_server, cloud1):
    import sys

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "deploy"))
    from loadgen import run_load_open

    fr = _cls_frame("ml_lg_fr", n=64, seed=5)
    est = _gbm(fr, ntrees=2, max_depth=2)
    DKV.put("ml_lg_gbm", est.model)
    stats = run_load_open("127.0.0.1", mem_server.port, "ml_lg_gbm",
                          "ml_lg_fr", rate=10.0, duration_s=1.2,
                          timeout_s=30.0)
    assert stats["completed"] >= 1
    # per-decile samples + the post-drain closer, each with RSS and (in-
    # process) ledger bytes
    assert len(stats["mem_samples"]) >= 3
    assert all(s["rss_bytes"] and s["rss_bytes"] > 0
               for s in stats["mem_samples"])
    assert all(s["ledger_bytes"] is not None
               for s in stats["mem_samples"])
    assert stats["mem_growth_bytes_per_min"] is not None
    assert stats["ledger_growth_bytes_per_min"] is not None
    DKV.remove("ml_lg_gbm")
    DKV.remove("ml_lg_fr")
