"""GBM/DRF end-to-end — the `h2o-py/tests/testdir_algos/gbm` analog:
train on synthetic data, assert metric quality with tolerances."""

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
from h2o3_tpu.models.drf import H2ORandomForestEstimator

from conftest import make_classification, make_regression


def _cls_frame(n=2000, f=10, seed=0):
    X, y = make_classification(n, f, seed)
    fr = Frame.from_numpy(np.column_stack([X, y]),
                          names=[f"x{i}" for i in range(f)] + ["y"])
    return fr.asfactor("y")


def test_gbm_binomial_auc(cloud1):
    fr = _cls_frame()
    train, valid = fr.split_frame([0.8], seed=7)
    gbm = H2OGradientBoostingEstimator(ntrees=30, max_depth=4, learn_rate=0.2, seed=42)
    gbm.train(y="y", training_frame=train, validation_frame=valid)
    assert gbm.auc() > 0.90
    assert gbm.auc(valid=True) > 0.80
    assert gbm.logloss() < 0.45
    pred = gbm.predict(valid)
    assert pred.names == ["predict", "0", "1"]
    assert pred.nrow == valid.nrow
    p1 = pred.vec("1").numeric_np()
    assert ((p1 >= 0) & (p1 <= 1)).all()


def test_gbm_regression(cloud1):
    X, y = make_regression(1500, 6, seed=3)
    names = [f"x{i}" for i in range(6)] + ["y"]
    fr = Frame.from_numpy(np.column_stack([X, y]), names=names)
    gbm = H2OGradientBoostingEstimator(ntrees=40, max_depth=5, learn_rate=0.2, seed=1)
    gbm.train(y="y", training_frame=fr)
    base = float(np.var(y))
    assert gbm.mse() < 0.3 * base
    assert gbm.model.varimp_table is not None
    top = gbm.model.varimp_table[0][0]
    assert top in ("x0", "x1", "x2")


def test_gbm_multinomial(cloud1):
    rng = np.random.default_rng(5)
    n = 1800
    X = rng.normal(size=(n, 5))
    y = (X[:, 0] > 0.5).astype(int) + (X[:, 1] > 0).astype(int)  # 3 classes
    fr = Frame.from_numpy(np.column_stack([X, y]),
                          names=["a", "b", "c", "d", "e", "y"]).asfactor("y")
    gbm = H2OGradientBoostingEstimator(ntrees=25, max_depth=4, learn_rate=0.3, seed=2)
    gbm.train(y="y", training_frame=fr)
    m = gbm.model.training_metrics
    assert m.logloss < 0.4
    assert m.accuracy > 0.85
    pred = gbm.predict(fr)
    assert pred.ncol == 4  # predict + 3 class probs


def test_gbm_with_nas(cloud1):
    X, y = make_classification(1200, 6, seed=9)
    X[::5, 2] = np.nan
    fr = Frame.from_numpy(np.column_stack([X, y]),
                          names=[f"x{i}" for i in range(6)] + ["y"]).asfactor("y")
    gbm = H2OGradientBoostingEstimator(ntrees=20, max_depth=4, seed=3)
    gbm.train(y="y", training_frame=fr)
    assert gbm.auc() > 0.80
    pred = gbm.predict(fr)
    assert not np.isnan(pred.vec("1").numeric_np()).any()


def test_gbm_categorical_features(cloud1):
    rng = np.random.default_rng(11)
    n = 1500
    cat = rng.integers(0, 4, n)
    x1 = rng.normal(size=n)
    y = ((cat >= 2) ^ (x1 > 0)).astype(int)
    fr = Frame.from_dict({
        "cat": np.asarray(["lvl%d" % c for c in cat], dtype=object),
        "x1": x1,
        "y": y,
    }).asfactor("y")
    gbm = H2OGradientBoostingEstimator(ntrees=30, max_depth=4, learn_rate=0.3, seed=4)
    gbm.train(y="y", training_frame=fr)
    assert gbm.auc() > 0.95


def test_gbm_early_stopping(cloud1):
    # noisy response ⇒ validation logloss bottoms out and overfits back up;
    # ScoreKeeper watches the validation metric (hex.ScoreKeeper semantics)
    fr = _cls_frame(1500, 8, seed=13)
    train, valid = fr.split_frame([0.7], seed=13)
    gbm = H2OGradientBoostingEstimator(
        ntrees=500, max_depth=3, learn_rate=0.3, seed=5,
        stopping_rounds=3, stopping_tolerance=1e-3, score_tree_interval=5,
    )
    gbm.train(y="y", training_frame=train, validation_frame=valid)
    assert len(gbm.scoring_history) > 0
    assert gbm.model.forest[0].feat.shape[0] < 500  # stopped early
    assert "validation_logloss" in gbm.scoring_history[-1]


def test_gbm_weights_column(cloud1):
    X, y = make_classification(1000, 5, seed=17)
    w = np.where(y == 1, 2.0, 1.0)
    fr = Frame.from_numpy(np.column_stack([X, y, w]),
                          names=["a", "b", "c", "d", "e", "y", "w"]).asfactor("y")
    gbm = H2OGradientBoostingEstimator(ntrees=10, max_depth=3, weights_column="w", seed=6)
    gbm.train(y="y", training_frame=fr, x=["a", "b", "c", "d", "e"])
    assert gbm.auc() > 0.75


def test_gbm_distribution_poisson(cloud1):
    rng = np.random.default_rng(21)
    n = 1200
    X = rng.normal(size=(n, 4))
    lam = np.exp(0.5 * X[:, 0] - 0.3 * X[:, 1])
    y = rng.poisson(lam)
    fr = Frame.from_numpy(np.column_stack([X, y]), names=["a", "b", "c", "d", "y"])
    gbm = H2OGradientBoostingEstimator(ntrees=30, distribution="poisson", seed=7)
    gbm.train(y="y", training_frame=fr)
    pred = gbm.predict(fr).vec("predict").numeric_np()
    assert (pred >= 0).all()  # log link ⇒ positive means
    assert np.corrcoef(pred, lam)[0, 1] > 0.7


def test_drf_binomial(cloud1):
    fr = _cls_frame(2000, 8, seed=23)
    drf = H2ORandomForestEstimator(ntrees=30, max_depth=12, seed=8)
    drf.train(y="y", training_frame=fr)
    # training metrics are OOB (DRF semantics) — lower than in-bag
    assert drf.auc() > 0.74
    p = drf.predict(fr).vec("1").numeric_np()
    assert ((p >= 0) & (p <= 1)).all()


def test_drf_regression(cloud1):
    X, y = make_regression(1500, 6, seed=29)
    fr = Frame.from_numpy(np.column_stack([X, y]),
                          names=[f"x{i}" for i in range(6)] + ["y"])
    drf = H2ORandomForestEstimator(ntrees=40, max_depth=14, seed=9)
    drf.train(y="y", training_frame=fr)
    # OOB mse (honest estimate) — looser than the old in-bag bound
    assert drf.mse() < 0.8 * float(np.var(y))


def test_gbm_cv(cloud1):
    fr = _cls_frame(1200, 6, seed=31)
    gbm = H2OGradientBoostingEstimator(ntrees=15, max_depth=3, nfolds=3, seed=10,
                                       keep_cross_validation_predictions=True)
    gbm.train(y="y", training_frame=fr)
    assert gbm.model.cross_validation_metrics is not None
    assert gbm.auc(xval=True) > 0.75
    assert gbm.model._cv_holdout_pred is not None
    assert gbm.model._cv_holdout_pred.shape[0] == fr.nrow


def test_gbm_multichip_shard_map(cloud8):
    """The distributed path: rows sharded over 8 devices, histogram psum."""
    fr = _cls_frame(2048, 6, seed=37)
    gbm = H2OGradientBoostingEstimator(ntrees=8, max_depth=3, seed=11)
    gbm.train(y="y", training_frame=fr)
    auc8 = gbm.auc()
    assert auc8 > 0.85


def test_balance_classes_weights_minority(cloud1):
    import numpy as np
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

    rng = np.random.default_rng(5)
    n = 2000
    X = rng.normal(size=(n, 3))
    # rare positive class (5%) driven by x0
    y = ((X[:, 0] > 1.6) | (rng.uniform(size=n) < 0.01)).astype(int)
    fr = Frame.from_dict({
        "a": X[:, 0], "b": X[:, 1], "c": X[:, 2],
        "y": np.asarray(["n", "p"], dtype=object)[y]}, column_types={"y": "enum"})
    m = H2OGradientBoostingEstimator(ntrees=10, max_depth=3,
                                     balance_classes=True, seed=1)
    m.train(x=["a", "b", "c"], y="y", training_frame=fr)
    # the priorClassDist correction keeps scored probabilities calibrated to
    # the ORIGINAL prior despite balanced training (hex.Model semantics)
    pm = m.predict(fr).vec("p").numeric_np().mean()
    prior = y.mean()
    assert abs(pm - prior) < 0.1
    assert m.auc() > 0.8


def test_monotone_constraints(cloud1):
    import numpy as np
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

    rng = np.random.default_rng(7)
    n = 1500
    x = rng.uniform(-2, 2, n)
    z = rng.normal(size=n)
    # mostly increasing relationship with local noise dips
    y = x + 0.6 * np.sin(4 * x) + 0.3 * z
    fr = Frame.from_dict({"x": x, "z": z, "y": y})
    m = H2OGradientBoostingEstimator(ntrees=40, max_depth=4,
                                     monotone_constraints={"x": 1}, seed=1)
    m.train(x=["x", "z"], y="y", training_frame=fr)
    # predictions along x (z fixed) must be non-decreasing
    grid = Frame.from_dict({"x": np.linspace(-2, 2, 200),
                            "z": np.zeros(200)})
    p = m.predict(grid).vec("predict").numeric_np()
    # bound propagation guarantees ZERO violations (hex/tree Constraints)
    viol = np.diff(p) < -1e-5
    assert viol.sum() == 0, f"{viol.sum()} monotonicity violations"
    # unconstrained model does violate (the sin dips)
    m2 = H2OGradientBoostingEstimator(ntrees=40, max_depth=4, seed=1)
    m2.train(x=["x", "z"], y="y", training_frame=fr)
    p2 = m2.predict(grid).vec("predict").numeric_np()
    assert (np.diff(p2) < -1e-4).sum() > 0
    # categorical constraint is rejected
    fr2 = Frame.from_dict({"c": np.asarray(["a", "b"] * 50, dtype=object),
                           "y": rng.normal(size=100)},
                          column_types={"c": "enum"})
    with pytest.raises(ValueError):
        H2OGradientBoostingEstimator(ntrees=2, monotone_constraints={"c": 1}
                                     ).train(x=["c"], y="y", training_frame=fr2)


def test_calibrate_model_platt_and_isotonic(cloud1):
    rng = np.random.default_rng(31)
    n = 3000
    X = rng.normal(size=(n, 4))
    p_true = 1 / (1 + np.exp(-(1.5 * X[:, 0] - 0.5)))
    y = (rng.uniform(size=n) < p_true).astype(int)
    fr = Frame.from_numpy(np.column_stack([X, y]),
                          names=["a", "b", "c", "d", "y"]).asfactor("y")
    tr, cal = fr.split_frame([0.7], seed=1)
    for method in ("PlattScaling", "IsotonicRegression"):
        m = H2OGradientBoostingEstimator(
            ntrees=30, max_depth=5, learn_rate=0.3, seed=1,
            calibrate_model=True, calibration_frame=cal,
            calibration_method=method)
        m.train(y="y", training_frame=tr)
        pred = m.predict(cal)
        assert "cal_1" in pred.names and "cal_0" in pred.names
        raw = pred.vec("1").numeric_np()
        calp = pred.vec("cal_1").numeric_np()
        ycal = np.asarray(cal.vec("y").data, np.float64)
        # calibrated probabilities are no worse (usually better) in brier
        brier_raw = np.mean((raw - ycal) ** 2)
        brier_cal = np.mean((calp - ycal) ** 2)
        assert brier_cal <= brier_raw + 0.01, (method, brier_raw, brier_cal)
    with pytest.raises(ValueError):
        H2OGradientBoostingEstimator(ntrees=2, calibrate_model=True).train(
            y="y", training_frame=tr)


def test_drf_oob_training_metrics(cloud1):
    # OOB metrics are pessimistic vs in-bag: on noisy data the OOB AUC must
    # sit clearly below a deliberately-overfit forest's in-bag AUC
    rng = np.random.default_rng(41)
    n = 1500
    X = rng.normal(size=(n, 4))
    p = 1 / (1 + np.exp(-1.0 * X[:, 0]))
    y = (rng.uniform(size=n) < p).astype(int)
    fr = Frame.from_numpy(np.column_stack([X, y]),
                          names=["a", "b", "c", "d", "y"]).asfactor("y")
    drf = H2ORandomForestEstimator(ntrees=30, max_depth=12, seed=1)
    drf.train(y="y", training_frame=fr)
    oob_auc = drf.auc()
    # in-bag AUC computed via predict() on the training frame
    pr = drf.predict(fr).vec("1").numeric_np()
    from h2o3_tpu.models.metrics import auc_exact
    inbag_auc = auc_exact(y.astype(float), pr)
    assert oob_auc < inbag_auc - 0.02, (oob_auc, inbag_auc)
    # and OOB should approximate the true generalization (~AUC of p)
    true_auc = auc_exact(y.astype(float), p)
    # ~11 OOB trees per row at ntrees=30 → a noisy but unbiased-ish estimate
    assert abs(oob_auc - true_auc) < 0.12, (oob_auc, true_auc)


def test_sample_rate_per_class(cloud1):
    rng = np.random.default_rng(51)
    n = 2000
    X = rng.normal(size=(n, 3))
    y = (X[:, 0] > 1.0).astype(int)  # ~16% minority
    fr = Frame.from_numpy(np.column_stack([X, y]),
                          names=["a", "b", "c", "y"]).asfactor("y")
    m = H2ORandomForestEstimator(ntrees=20, max_depth=6, seed=1,
                                 sample_rate_per_class=[0.3, 1.0])
    m.train(y="y", training_frame=fr)
    assert m.auc() > 0.8
    with pytest.raises(ValueError):
        H2ORandomForestEstimator(ntrees=2, sample_rate_per_class=[0.5]).train(
            y="y", training_frame=fr)
