"""Round-3 Rapids final-tail parity (`water/rapids/ast/prims/**`):
digamma/trigamma, moment/asDate/timezones, string distance/title/
substring-count, rank_within_groupby, relevel.by.freq, distance, isax,
setproperty/setLevel/append — VERDICT r02 missing #6."""

import datetime

import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu.frame.frame import Frame


def _fr(**cols):
    types = {k: "enum" for k, v in cols.items()
             if np.asarray(v).dtype.kind in "OUS"}
    return h2o.H2OFrame(dict(cols), column_types=types or None)


def _col(fr, i=0):
    return np.asarray(fr.vec(fr.names[i]).numeric_np())


def test_digamma_trigamma(cloud1):
    fr = _fr(a=[1.0, 0.5, 10.5])
    got = _col(h2o.rapids(f"(digamma {fr.key})"))
    np.testing.assert_allclose(
        got, [-0.5772156649, -1.9635100260, 2.3030010343], atol=1e-9)
    got = _col(h2o.rapids(f"(trigamma {fr.key})"))
    np.testing.assert_allclose(
        got, [np.pi ** 2 / 6, np.pi ** 2 / 2, 0.0999169561], atol=1e-8)


def test_moment_and_asdate(cloud1):
    out = h2o.rapids("(moment 2020 2 29 12 30 15 250)")
    want = datetime.datetime(2020, 2, 29, 12, 30, 15, 250000,
                             tzinfo=datetime.timezone.utc).timestamp() * 1000
    assert _col(out)[0] == want
    # column-valued year
    fr = _fr(y=[2019.0, 2021.0])
    out = _col(h2o.rapids(f"(moment {fr.key} 1 1 0 0 0 0)"))
    for i, yr in enumerate((2019, 2021)):
        want = datetime.datetime(yr, 1, 1,
                                 tzinfo=datetime.timezone.utc
                                 ).timestamp() * 1000
        assert out[i] == want
    # invalid date -> NA
    assert np.isnan(_col(h2o.rapids("(moment 2021 2 30 0 0 0 0)"))[0])

    sf = _fr(d=np.asarray(["2021-03-05", "1999-12-31"], dtype=object))
    got = _col(h2o.rapids(f'(asDate {sf.key} "yyyy-MM-dd")'))
    want0 = datetime.datetime(2021, 3, 5,
                              tzinfo=datetime.timezone.utc).timestamp() * 1000
    assert got[0] == want0


def test_timezones(cloud1):
    tz = h2o.rapids("(listTimeZones)")
    assert tz.nrow > 100
    h2o.rapids('(setTimeZone "America/New_York")')
    got = h2o.rapids("(getTimeZone)")
    assert got.vec(got.names[0]).to_numpy()[0] == "America/New_York"
    with pytest.raises(Exception):
        h2o.rapids('(setTimeZone "Not/AZone")')
    # moment honors the session zone: midnight in New York is 5h later
    # than midnight UTC (Jan = EST)
    ny = _col(h2o.rapids("(moment 2021 1 1 0 0 0 0)"))[0]
    h2o.rapids('(setTimeZone "UTC")')
    utc = _col(h2o.rapids("(moment 2021 1 1 0 0 0 0)"))[0]
    assert ny - utc == 5 * 3600 * 1000


def test_str_distance_and_title(cloud1):
    a = _fr(s=np.asarray(["kitten", "abc"], dtype=object))
    b = _fr(s=np.asarray(["sitting", "abc"], dtype=object))
    got = _col(h2o.rapids(f'(strDistance {a.key} {b.key} "lv" TRUE)'))
    np.testing.assert_array_equal(got, [3.0, 0.0])
    got = _col(h2o.rapids(f'(strDistance {a.key} {b.key} "jw" TRUE)'))
    assert got[1] == 1.0 and 0 < got[0] < 1
    t = h2o.rapids(f"(toTitle {a.key})")
    assert t.vec(t.names[0]).domain[0] in ("Kitten", "Abc") or \
        list(t.vec(t.names[0]).to_numpy())[0] == "Kitten"


def test_num_valid_substrings(cloud1, tmp_path):
    words = tmp_path / "words.txt"
    words.write_text("cat\nhat\nat\n")
    fr = _fr(s=np.asarray(["concatenate", "zzz"], dtype=object))
    got = _col(h2o.rapids(f'(num_valid_substrings {fr.key} "{words}")'))
    # substrings of "concatenate" include cat + at (hat absent)
    np.testing.assert_array_equal(got, [2.0, 0.0])


def test_rank_within_groupby(cloud1):
    fr = _fr(g=[1.0, 1.0, 1.0, 2.0, 2.0], v=[3.0, 1.0, 2.0, 5.0, 4.0])
    out = h2o.rapids(
        f'(rank_within_groupby {fr.key} [0] [1] [1] "rk" 0)')
    rk = np.asarray(out.vec("rk").numeric_np())
    # original row order preserved; rank follows ascending v within g
    np.testing.assert_array_equal(rk, [3.0, 1.0, 2.0, 2.0, 1.0])
    out2 = h2o.rapids(
        f'(rank_within_groupby {fr.key} [0] [1] [0] "rk" 0)')
    rk2 = np.asarray(out2.vec("rk").numeric_np())
    np.testing.assert_array_equal(rk2, [1.0, 3.0, 2.0, 1.0, 2.0])
    # NA group values form ONE group (NaN != NaN must not split them)
    fr2 = _fr(g=[1.0, np.nan, np.nan, np.nan], v=[1.0, 3.0, 1.0, 2.0])
    out3 = h2o.rapids(
        f'(rank_within_groupby {fr2.key} [0] [1] [1] "rk" 0)')
    rk3 = np.asarray(out3.vec("rk").numeric_np())
    np.testing.assert_array_equal(rk3, [1.0, 3.0, 1.0, 2.0])


def test_relevel_by_freq(cloud1):
    fr = _fr(c=np.asarray(["a", "b", "b", "c", "b", "c"], dtype=object))
    out = h2o.rapids(f"(relevel.by.freq {fr.key} -1)")
    v = out.vec(out.names[0])
    assert v.domain == ["b", "c", "a"]
    # values unchanged under the remap
    got = [v.domain[c] for c in np.asarray(v.data)]
    assert got == ["a", "b", "b", "c", "b", "c"]


def test_distance(cloud1):
    x = _fr(a=[0.0, 3.0], b=[0.0, 4.0])
    y = _fr(a=[0.0], b=[0.0])
    out = h2o.rapids(f'(distance {x.key} {y.key} "l2")')
    np.testing.assert_allclose(_col(out), [0.0, 5.0])
    out = h2o.rapids(f'(distance {x.key} {x.key} "l1")')
    assert _col(out, 0)[0] == 0.0 and _col(out, 1)[0] == 7.0
    out = h2o.rapids(f'(distance {x.key} {x.key} "cosine")')
    np.testing.assert_allclose(np.asarray(_col(out, 1)[1]), 1.0, atol=1e-12)


def test_isax(cloud1):
    rng = np.random.default_rng(0)
    data = {f"t{i}": rng.normal(size=4) for i in range(16)}
    fr = h2o.H2OFrame(data)
    out = h2o.rapids(f"(isax {fr.key} 4 8 0)")
    assert out.nrow == 4
    words = list(out.vec("iSax_index").to_numpy())
    assert all(len(w.split("^")) == 4 for w in words)
    syms = np.asarray(out.vec("iSax_word_0").numeric_np())
    assert ((syms >= 0) & (syms <= 7)).all()


def test_setproperty_setlevel_append(cloud1):
    h2o.rapids('(setproperty "h2o3.test.flag" "42")')
    from h2o3_tpu.frame.rapids_expr import _SYS_PROPS

    assert _SYS_PROPS["h2o3.test.flag"] == "42"

    fr = _fr(c=np.asarray(["x", "y", "x"], dtype=object))
    out = h2o.rapids(f'(setLevel {fr.key} "y")')
    v = out.vec(out.names[0])
    assert [v.domain[c] for c in np.asarray(v.data)] == ["y", "y", "y"]
    with pytest.raises(Exception):
        h2o.rapids(f'(setLevel {fr.key} "nope")')

    fr2 = _fr(a=[1.0, 2.0])
    out = h2o.rapids(f'(append {fr2.key} 7 "seven")')
    assert out.names == ["a", "seven"]
    np.testing.assert_array_equal(
        np.asarray(out.vec("seven").numeric_np()), [7.0, 7.0])


def test_str_distance_all_six_measures(cloud1):
    """strDistance 6/6 (AstStrDistance over the Apache measures) —
    round-4 completion of the r03 inventory gap."""
    a = _fr(s=np.asarray(["kitten", "robert", "night"], dtype=object))
    b = _fr(s=np.asarray(["sitting", "rupert", "nacht"], dtype=object))
    # lcs: |a|+|b| - 2*LCS ; LCS(kitten, sitting) = "ittn" (4)
    got = _col(h2o.rapids(f'(strDistance {a.key} {b.key} "lcs" TRUE)'))
    assert got[0] == 6.0 + 7.0 - 2 * 4.0
    # qgram: bigram profile L1 distance
    got = _col(h2o.rapids(f'(strDistance {a.key} {b.key} "qgram" TRUE)'))
    assert got[0] > 0 and np.isfinite(got).all()
    ident = _fr(s=np.asarray(["abc"], dtype=object))
    same = _col(h2o.rapids(
        f'(strDistance {ident.key} {ident.key} "qgram" TRUE)'))
    assert same[0] == 0.0
    # jaccard: 1 - |chars∩|/|chars∪|
    got = _col(h2o.rapids(f'(strDistance {a.key} {b.key} "jaccard" TRUE)'))
    assert 0.0 < got[0] < 1.0
    # soundex: robert/rupert encode identically (R163) -> 4 agreeing chars
    got = _col(h2o.rapids(f'(strDistance {a.key} {b.key} "soundex" TRUE)'))
    assert got[1] == 4.0
    with pytest.raises(Exception):
        h2o.rapids(f'(strDistance {a.key} {b.key} "bogus" TRUE)')
