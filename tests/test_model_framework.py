"""Cross-cutting model-framework regressions: NA responses, col_types hints,
test-frame domain adaptation, CV param propagation, artifacts."""

import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
from h2o3_tpu.models.glm import H2OGeneralizedLinearEstimator

from conftest import make_classification


def test_na_response_rows_dropped(cloud1):
    X, y = make_classification(1000, 5, seed=0)
    yf = y.astype(float)
    yf[::10] = np.nan
    fr = Frame.from_numpy(np.column_stack([X, yf]),
                          names=["a", "b", "c", "d", "e", "y"])
    fr["y"] = Frame.from_dict({"y": yf}).asfactor("y").vec("y")
    gbm = H2OGradientBoostingEstimator(ntrees=5, max_depth=3, seed=1)
    gbm.train(y="y", training_frame=fr)
    # all-NA rows dropped: nobs reflects only labeled rows
    assert gbm.model.training_metrics.nobs == int((~np.isnan(yf)).sum())
    assert gbm.auc() > 0.7


def test_col_types_enum_hint(tmp_path, cloud1):
    p = tmp_path / "t.csv"
    p.write_text("x,label\n" + "\n".join(f"{i*0.1:.1f},{i%2}" for i in range(50)) + "\n")
    fr = h2o.import_file(str(p), col_types={"label": "enum"})
    assert fr.vec("label").type == "enum"
    assert fr.vec("label").nlevels == 2


def test_predict_domain_adaptation(cloud1):
    rng = np.random.default_rng(5)
    n = 1200
    lv = np.asarray(["blue", "green", "red"], dtype=object)
    cat = lv[rng.integers(0, 3, n)]
    y = (cat == "red").astype(int) ^ (rng.random(n) < 0.05)
    fr = Frame.from_dict({"color": cat, "y": y.astype(int)}).asfactor("y")
    gbm = H2OGradientBoostingEstimator(ntrees=10, max_depth=3, seed=2)
    gbm.train(y="y", training_frame=fr)
    # test frame interns only a subset => different code mapping
    test = Frame.from_dict({"color": np.asarray(["red"] * 10 + ["green"] * 10, dtype=object)})
    pred = gbm.predict(test).vec("1").numeric_np()
    assert pred[:10].mean() > 0.7      # red => class 1
    assert pred[10:].mean() < 0.3      # green => class 0
    # unseen level behaves like NA, doesn't crash
    test2 = Frame.from_dict({"color": np.asarray(["purple"] * 5, dtype=object)})
    p2 = gbm.predict(test2).vec("1").numeric_np()
    assert np.isfinite(p2).all()


def test_cv_propagates_weights(cloud1):
    X, y = make_classification(900, 5, seed=3)
    w = np.where(y == 1, 3.0, 1.0)
    fr = Frame.from_numpy(np.column_stack([X, y, w]),
                          names=["a", "b", "c", "d", "e", "y", "w"]).asfactor("y")
    gbm = H2OGradientBoostingEstimator(ntrees=5, max_depth=3, nfolds=2,
                                       weights_column="w", seed=4)
    gbm.train(y="y", training_frame=fr, x=["a", "b", "c", "d", "e"])
    assert gbm.model.cross_validation_metrics is not None


def test_glm_lambda_actually_regularizes(cloud1):
    # review regression: penalty must scale with n (sum-scale Gram)
    rng = np.random.default_rng(6)
    n = 2000
    X = rng.normal(size=(n, 4))
    y = 2 * X[:, 0] + 0.05 * rng.normal(size=n)
    fr = Frame.from_numpy(np.column_stack([X, y]),
                          names=["a", "b", "c", "d", "y"])
    strong = H2OGeneralizedLinearEstimator(family="gaussian", lambda_=1.0, alpha=0.0)
    strong.train(y="y", training_frame=fr)
    # λ=1 ridge must shrink the true coef visibly (≈ x0_coef/(1+λ) on std scale)
    assert abs(strong.coef_norm()["a"]) < 1.5
    lasso = H2OGeneralizedLinearEstimator(family="gaussian", lambda_=1.0, alpha=1.0)
    lasso.train(y="y", training_frame=fr)
    cn = lasso.coef_norm()
    assert all(abs(cn[c]) < 1e-6 for c in ("b", "c", "d"))  # exactly zeroed


def test_mojo_roundtrip_gbm(tmp_path, cloud1):
    X, y = make_classification(800, 5, seed=7)
    fr = Frame.from_numpy(np.column_stack([X, y]),
                          names=["a", "b", "c", "d", "e", "y"]).asfactor("y")
    gbm = H2OGradientBoostingEstimator(ntrees=8, max_depth=3, seed=5)
    gbm.train(y="y", training_frame=fr)
    path = h2o.save_model(gbm, str(tmp_path))
    scorer = h2o.load_model(path)
    p_live = gbm.predict(fr).vec("1").numeric_np()
    p_mojo = scorer.predict(fr).vec("1").numeric_np()
    np.testing.assert_allclose(p_live, p_mojo, rtol=1e-5, atol=1e-6)


def test_mojo_roundtrip_glm(tmp_path, cloud1):
    rng = np.random.default_rng(8)
    n = 600
    X = rng.normal(size=(n, 3))
    y = (X[:, 0] > 0).astype(int)
    fr = Frame.from_numpy(np.column_stack([X, y]),
                          names=["a", "b", "c", "y"]).asfactor("y")
    glm = H2OGeneralizedLinearEstimator(family="binomial", lambda_=0.0)
    glm.train(y="y", training_frame=fr)
    path = h2o.save_model(glm, str(tmp_path))
    scorer = h2o.load_model(path)
    np.testing.assert_allclose(
        glm.predict(fr).vec("1").numeric_np(),
        scorer.predict(fr).vec("1").numeric_np(),
        rtol=1e-5, atol=1e-6,
    )


def test_mojo_roundtrip_dl(tmp_path, cloud1):
    from h2o3_tpu.models.deeplearning import H2ODeepLearningEstimator

    X, y = make_classification(600, 4, seed=9)
    fr = Frame.from_numpy(np.column_stack([X, y]),
                          names=["a", "b", "c", "d", "y"]).asfactor("y")
    dl = H2ODeepLearningEstimator(hidden=[8], epochs=3, seed=6, mini_batch_size=64)
    dl.train(y="y", training_frame=fr)
    path = h2o.save_model(dl, str(tmp_path))
    scorer = h2o.load_model(path)
    np.testing.assert_allclose(
        dl.predict(fr).vec("1").numeric_np(),
        scorer.predict(fr).vec("1").numeric_np(),
        rtol=1e-4, atol=1e-5,
    )


def test_keep_cross_validation_models(cloud1):
    import numpy as np
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

    rng = np.random.default_rng(0)
    fr = Frame.from_dict({"a": rng.normal(size=300),
                          "y": rng.normal(size=300)})
    m = H2OGradientBoostingEstimator(ntrees=3, max_depth=2, nfolds=3)
    m.train(x=["a"], y="y", training_frame=fr)
    cvs = m.model.cross_validation_models
    assert cvs and len(cvs) == 3
    assert all(c.validation_metrics is not None for c in cvs)
    m2 = H2OGradientBoostingEstimator(ntrees=3, max_depth=2, nfolds=3,
                                      keep_cross_validation_models=False)
    m2.train(x=["a"], y="y", training_frame=fr)
    assert m2.model.cross_validation_models is None


def test_h2o_interaction(cloud1):
    import numpy as np
    import h2o3_tpu as h2o
    from h2o3_tpu.frame.frame import Frame

    rng = np.random.default_rng(1)
    a = np.asarray(["x", "y"], dtype=object)[rng.integers(0, 2, 200)]
    b = np.asarray(["p", "q", "r"], dtype=object)[rng.integers(0, 3, 200)]
    fr = Frame.from_dict({"a": a, "b": b},
                         column_types={"a": "enum", "b": "enum"})
    out = h2o.interaction(fr, factors=["a", "b"], pairwise=True,
                          max_factors=100, min_occurrence=1)
    v = out.vec("a_b")
    assert v.type == "enum" and 4 <= v.nlevels <= 6
    # capping pools rare combos into 'other'
    out2 = h2o.interaction(fr, factors=["a", "b"], pairwise=True,
                           max_factors=2, min_occurrence=1)
    assert "other" in out2.vec("a_b").domain


def test_model_summary_show(cloud1, capsys):
    import numpy as np
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
    from h2o3_tpu.models.glm import H2OGeneralizedLinearEstimator

    rng = np.random.default_rng(0)
    fr = Frame.from_dict({"a": rng.normal(size=300), "b": rng.normal(size=300),
                          "y": rng.normal(size=300)})
    m = H2OGradientBoostingEstimator(ntrees=5, max_depth=3)
    m.train(x=["a", "b"], y="y", training_frame=fr)
    s = m.model.summary()
    assert s["number_of_trees"] == 5 and 1 <= s["max_depth"] <= 3
    m.model.show()
    out = capsys.readouterr().out
    assert "number_of_trees" in out
    g = H2OGeneralizedLinearEstimator(family="gaussian", lambda_=0.0)
    g.train(x=["a", "b"], y="y", training_frame=fr)
    gs = g.model.summary()
    assert gs["family"] == "gaussian" and gs["number_of_predictors_total"] == 2
