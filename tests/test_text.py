"""Text utils: tokenize / tf_idf / grep (reference: AstTokenize, hex/tfidf,
hex/grep)."""

import numpy as np

import h2o3_tpu as h2o
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.vec import Vec


def _string_frame(rows):
    return Frame({"text": Vec(None, "string",
                              strings=np.asarray(rows, dtype=object))})


def test_tokenize_sentence_separators(cloud1):
    fr = _string_frame(["hello world", "foo bar baz", None])
    tok = fr.tokenize(split=" ")
    vals = list(tok.vec("C1").to_numpy())
    # tokens in order, None after each input row
    assert vals == ["hello", "world", None, "foo", "bar", "baz", None, None]


def test_tokenize_regex_split(cloud1):
    fr = _string_frame(["a,b,,c"])
    tok = fr.tokenize(split=",")
    vals = [v for v in tok.vec("C1").to_numpy() if v is not None]
    assert vals == ["a", "b", "c"]


def test_tf_idf(cloud1):
    fr = Frame({
        "doc": Vec.from_numpy(np.asarray([0, 1, 2], np.float64)),
        "text": Vec(None, "string", strings=np.asarray(
            ["cat dog cat", "dog fish", "cat"], dtype=object)),
    })
    out = h2o.tf_idf(fr, 0, 1)
    toks = list(out.vec("token").to_numpy())
    tf = out.vec("TF").numeric_np()
    tfidf = out.vec("TF_IDF").numeric_np()
    i = [j for j, (d, t) in enumerate(zip(out.vec("doc").numeric_np(), toks))
         if d == 0 and t == "cat"][0]
    assert tf[i] == 2.0
    # 'cat' appears in 2 of 3 docs; 'fish' in 1 → fish has larger idf
    idf = dict(zip(toks, out.vec("IDF").numeric_np()))
    assert idf["fish"] > idf["cat"]
    assert np.allclose(tfidf, tf * out.vec("IDF").numeric_np())


def test_grep(cloud1):
    fr = _string_frame(["error: disk full", "ok", "error: timeout", None])
    hits = h2o.grep(fr, r"error:")
    assert list(hits.vec("row").numeric_np()) == [0.0, 2.0]
    inv = h2o.grep(fr, r"error:", invert=True)
    assert list(inv.vec("row").numeric_np()) == [1.0, 3.0]
