"""Text utils: tokenize / tf_idf / grep (reference: AstTokenize, hex/tfidf,
hex/grep)."""

import numpy as np

import h2o3_tpu as h2o
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.vec import Vec


def _string_frame(rows):
    return Frame({"text": Vec(None, "string",
                              strings=np.asarray(rows, dtype=object))})


def test_tokenize_sentence_separators(cloud1):
    fr = _string_frame(["hello world", "foo bar baz", None])
    tok = fr.tokenize(split=" ")
    vals = list(tok.vec("C1").to_numpy())
    # tokens in order, None after each input row
    assert vals == ["hello", "world", None, "foo", "bar", "baz", None, None]


def test_tokenize_regex_split(cloud1):
    fr = _string_frame(["a,b,,c"])
    tok = fr.tokenize(split=",")
    vals = [v for v in tok.vec("C1").to_numpy() if v is not None]
    assert vals == ["a", "b", "c"]


def test_tf_idf(cloud1):
    fr = Frame({
        "doc": Vec.from_numpy(np.asarray([0, 1, 2], np.float64)),
        "text": Vec(None, "string", strings=np.asarray(
            ["cat dog cat", "dog fish", "cat"], dtype=object)),
    })
    out = h2o.tf_idf(fr, 0, 1)
    toks = list(out.vec("token").to_numpy())
    tf = out.vec("TF").numeric_np()
    tfidf = out.vec("TF_IDF").numeric_np()
    i = [j for j, (d, t) in enumerate(zip(out.vec("doc").numeric_np(), toks))
         if d == 0 and t == "cat"][0]
    assert tf[i] == 2.0
    # 'cat' appears in 2 of 3 docs; 'fish' in 1 → fish has larger idf
    idf = dict(zip(toks, out.vec("IDF").numeric_np()))
    assert idf["fish"] > idf["cat"]
    assert np.allclose(tfidf, tf * out.vec("IDF").numeric_np())


def test_grep(cloud1):
    fr = _string_frame(["error: disk full", "ok", "error: timeout", None])
    hits = h2o.grep(fr, r"error:")
    assert list(hits.vec("row").numeric_np()) == [0.0, 2.0]
    inv = h2o.grep(fr, r"error:", invert=True)
    assert list(inv.vec("row").numeric_np()) == [1.0, 3.0]


def test_string_method_wrappers(cloud1, tmp_path):
    """Frame wrappers over the string prims: lstrip/rstrip, entropy,
    num_valid_substrings, grep, ascharacter."""
    import numpy as np
    import pytest

    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.frame.vec import Vec

    fr = Frame({"s": Vec(None, "string", strings=np.asarray(
        ["  ab", "cd  ", "aaaa"], dtype=object))})
    np.testing.assert_array_equal(
        np.asarray(fr.lstrip().vec("s").to_numpy(), dtype=object),
        ["ab", "cd  ", "aaaa"])
    np.testing.assert_array_equal(
        np.asarray(fr.rstrip().vec("s").to_numpy(), dtype=object),
        ["  ab", "cd", "aaaa"])
    ent = fr.entropy().vec("entropy").numeric_np()
    assert ent[2] == pytest.approx(0.0)          # "aaaa": one symbol
    assert ent[0] > 0.5

    words = tmp_path / "w.txt"
    words.write_text("ab\ncd\n")
    nv = fr.num_valid_substrings(str(words)).vec(
        "num_valid_substrings").numeric_np()
    assert nv[0] == 1.0 and nv[2] == 0.0

    g = fr.grep("a", output_logical=True)
    np.testing.assert_allclose(g._col0(), [1, 0, 1])
    # NA rows count as NON-matches, so invert includes them (h2o.grep parity)
    na_fr = Frame({"s": Vec(None, "string", strings=np.asarray(
        ["ax", None, "b"], dtype=object))})
    gi = na_fr.grep("a", invert=True, output_logical=True)
    np.testing.assert_allclose(gi._col0(), [0, 1, 1])
    idx = na_fr.grep("a")
    np.testing.assert_allclose(idx._col0(), [0])

    efr = Frame.from_dict({"c": np.asarray(["x", "y", "x"], dtype=object)},
                          column_types={"c": "enum"})
    ch = efr.ascharacter()
    assert ch.vec("c").type == "string"
    np.testing.assert_array_equal(
        np.asarray(ch.vec("c").to_numpy(), dtype=object), ["x", "y", "x"])
    # numeric columns stringify too (upstream ascharacter semantics)
    nch = Frame.from_dict({"x": np.asarray([1.5, 2.5])}).ascharacter()
    assert nch.vec("x").type == "string"
    assert list(nch.vec("x").to_numpy()) == ["1.5", "2.5"]
