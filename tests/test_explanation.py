"""Explanation bundle (`h2o-py/h2o/explanation/_explain.py`) — data-first:
every function returns the tables upstream's plots draw."""

import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu.estimators import (H2OGradientBoostingEstimator,
                                 H2OGeneralizedLinearEstimator)


@pytest.fixture()
def models_and_frame(cloud1):
    rng = np.random.default_rng(0)
    n = 1500
    X = rng.normal(size=(n, 4))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(int)
    d = {f"c{i}": X[:, i] for i in range(4)}
    d["y"] = y.astype(str)
    fr = h2o.H2OFrame_from_python(d, column_types={"y": "enum"})
    gbm = H2OGradientBoostingEstimator(ntrees=8, max_depth=3, seed=1)
    gbm.train(y="y", training_frame=fr)
    glm = H2OGeneralizedLinearEstimator(family="binomial")
    glm.train(y="y", training_frame=fr)
    return [gbm, glm], fr


def test_varimp_heatmap(models_and_frame):
    ms, fr = models_and_frame
    hm = h2o.varimp_heatmap(ms)
    assert hm.names[0] == "feature" and hm.ncol == 3
    feats = [hm.vec("feature").domain[c]
             for c in np.asarray(hm.vec("feature").data)]
    assert "c0" in feats
    # the signal feature dominates for both models
    for mid in hm.names[1:]:
        col = hm.vec(mid).numeric_np()
        assert col[feats.index("c0")] == max(col)


def test_model_correlation_heatmap(models_and_frame):
    ms, fr = models_and_frame
    cm = h2o.model_correlation_heatmap(ms, fr)
    assert cm.ncol == 3
    ids = [cm.vec("model").domain[c]
           for c in np.asarray(cm.vec("model").data)]
    # diagonal 1, off-diagonal high (same signal learned)
    for j, mid in enumerate(ids):
        col = cm.vec(mid).numeric_np()
        assert col[j] == pytest.approx(1.0, abs=1e-9)
        assert all(v > 0.8 for v in col)


def test_pd_multi_plot_and_explain(models_and_frame):
    ms, fr = models_and_frame
    pd = h2o.pd_multi_plot(ms, fr, "c0")
    assert pd.names[0] == "c0" and pd.ncol == 3
    # monotone-ish response in the signal feature for both models
    for mid in pd.names[1:]:
        resp = pd.vec(mid).numeric_np()
        assert resp[-1] > resp[0]

    bundle = h2o.explain(ms, fr)
    assert set(bundle["varimp"]) == {m.model_id for m in ms}
    assert "varimp_heatmap" in bundle and "model_correlation_heatmap" in bundle
    assert "c0" in bundle["pdp"] and bundle["pdp"]["c0"].ncol == 3


def test_explain_row_and_residuals(models_and_frame, cloud1):
    ms, fr = models_and_frame
    row = h2o.explain_row(ms, fr, 3)
    assert set(row["predictions"]) == {m.model_id for m in ms}
    # tree model contributes SHAP, GLM doesn't
    gbm_id = ms[0].model_id
    assert gbm_id in row["contributions"]
    assert "BiasTerm" in row["contributions"][gbm_id]

    # regression residuals
    rng = np.random.default_rng(1)
    t = rng.normal(size=500)
    fr2 = h2o.H2OFrame_from_python(
        {"a": t, "y": 2 * t + 0.1 * rng.normal(size=500)})
    reg = H2OGradientBoostingEstimator(ntrees=10, max_depth=3, seed=1)
    reg.train(y="y", training_frame=fr2)
    ra = h2o.residual_analysis(reg, fr2)
    assert set(ra.names) == {"fitted", "residual"}
    assert abs(ra.vec("residual").numeric_np().mean()) < 0.2
