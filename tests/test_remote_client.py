"""Remote-attach client e2e (VERDICT r03 #3): the server runs in a
SEPARATE process; the client connects by URL only and round-trips
upload → munge → train → predict → metrics without touching any
in-process state. Reference: `h2o-py/h2o/backend/connection.py` —
upstream's client is fundamentally a REST client."""

import os
import subprocess
import sys
import time

import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu.client import (H2OConnectionError, H2OServerError,
                             RemoteFrame, RemoteModel)
from h2o3_tpu.runtime.dkv import DKV

_SERVER_SRC = """
import sys, time
from h2o3_tpu.rest.server import start_server
import h2o3_tpu as h2o
h2o.init()
srv = start_server(port=0, auth_token={token!r})
print(srv.port, flush=True)
time.sleep(600)
"""


@pytest.fixture(scope="module")
def remote_server():
    env = dict(os.environ, PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu",
               PYTHONPATH=os.path.dirname(os.path.dirname(__file__)))
    proc = subprocess.Popen(
        [sys.executable, "-c", _SERVER_SRC.format(token=None)],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env, text=True)
    try:
        port = int(proc.stdout.readline())
        yield f"http://127.0.0.1:{port}"
    finally:
        proc.kill()
        proc.wait()
    h2o.shutdown()


@pytest.fixture()
def csvfile(tmp_path):
    rng = np.random.default_rng(0)
    n = 400
    X = rng.normal(size=(n, 3))
    y = (X[:, 0] + X[:, 1] > 0).astype(int)
    p = tmp_path / "remote.csv"
    with open(p, "w") as f:
        f.write("a,b,c,y\n")
        for i in range(n):
            f.write(",".join(f"{v:.4f}" for v in X[i]) + f",{y[i]}\n")
    return str(p)


def test_connect_unreachable_raises():
    with pytest.raises(H2OConnectionError):
        h2o.connect(url="http://127.0.0.1:9", verbose=False)
    assert h2o.connection() is None


def test_remote_roundtrip_train_predict_metrics(remote_server, csvfile):
    conn = h2o.connect(url=remote_server)
    try:
        assert h2o.connection() is conn
        local_keys_before = set(DKV.keys())

        # upload: client-side bytes travel over PostFile + Parse
        fr = h2o.upload_file(csvfile, destination_frame="remote_train")
        assert isinstance(fr, RemoteFrame)
        assert fr.shape == (400, 4)
        assert fr.names == ["a", "b", "c", "y"]

        # munge: asfactor through Rapids assigns
        fr["y"] = fr["y"].asfactor()
        assert fr.types["y"] == "enum"

        # train through /3/ModelBuilders + /3/Jobs polling — the NORMAL
        # estimator surface, no in-process code path
        from h2o3_tpu.estimators import H2OGradientBoostingEstimator

        m = H2OGradientBoostingEstimator(ntrees=5, max_depth=3, seed=1)
        m.train(x=["a", "b", "c"], y="y", training_frame=fr)
        assert isinstance(m._model, RemoteModel)
        assert m.auc() > 0.8
        assert m.model_id.startswith("gbm")

        # predict on the server; fetch the head through the client
        pred = m.predict(fr)
        assert isinstance(pred, RemoteFrame)
        assert pred.names[0] == "predict"
        assert pred.nrow == 400

        # fresh-frame metrics via /3/ModelMetrics
        perf = m.model_performance(fr)
        assert perf.auc() > 0.8

        # h2o.get_model round-trips by id
        again = h2o.get_model(m.model_id)
        assert isinstance(again, RemoteModel)
        assert again.algo == "gbm"

        # nothing leaked into THIS process's DKV
        assert set(DKV.keys()) == local_keys_before
    finally:
        h2o.shutdown()   # disconnect; later tests are in-process again
    assert h2o.connection() is None


def test_remote_import_server_side_path(remote_server, csvfile):
    h2o.init(url=remote_server)
    try:
        fr = h2o.import_file(csvfile)   # path resolved ON the server
        assert isinstance(fr, RemoteFrame)
        assert fr.nrow == 400
        cols = fr[["a", "b"]]
        assert cols.ncol == 2
        fr.delete()
        with pytest.raises(H2OServerError):
            h2o.get_frame(fr.key)
    finally:
        h2o.shutdown()


def test_remote_auth_token(csvfile):
    env = dict(os.environ, PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu",
               PYTHONPATH=os.path.dirname(os.path.dirname(__file__)))
    proc = subprocess.Popen(
        [sys.executable, "-c", _SERVER_SRC.format(token="sekrit")],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env, text=True)
    try:
        url = f"http://127.0.0.1:{int(proc.stdout.readline())}"
        # /3/Cloud stays open for discovery, so connect() itself succeeds;
        # every OTHER route 401s without the bearer token
        conn = h2o.connect(url=url, verbose=False)
        with pytest.raises(H2OServerError) as e:
            conn.get("/3/Models")
        assert e.value.status == 401
        conn = h2o.connect(url=url, token="sekrit", verbose=False)
        assert "models" in conn.get("/3/Models")
    finally:
        proc.kill()
        proc.wait()
        h2o.shutdown()


def test_remote_train_validates_args_locally(remote_server, csvfile):
    """Bad train() calls raise client-side (ValueError), not as a FAILED
    server job surfacing RuntimeError."""
    h2o.connect(url=remote_server, verbose=False)
    try:
        from h2o3_tpu.estimators import H2OGradientBoostingEstimator

        fr = h2o.upload_file(csvfile)
        with pytest.raises(ValueError, match="response column"):
            H2OGradientBoostingEstimator(ntrees=2).train(training_frame=fr)
    finally:
        h2o.shutdown()


def test_remote_frame_from_python_and_parse_options(remote_server, tmp_path):
    """H2OFrame_from_python uploads to the server when connected; parse
    options (sep/col_types) ride /3/Parse instead of being dropped."""
    h2o.connect(url=remote_server, verbose=False)
    try:
        fr = h2o.H2OFrame_from_python(
            {"a": [1.0, 2.0, 3.0], "lab": ["x", "y", "x"]},
            column_types={"lab": "enum"})
        assert isinstance(fr, RemoteFrame)
        assert fr.nrow == 3 and fr.types["lab"] == "enum"

        ssv = tmp_path / "t.ssv"
        ssv.write_text("a;b\n1;2\n3;4\n")
        fr2 = h2o.import_file(str(ssv), sep=";")
        assert fr2.names == ["a", "b"] and fr2.ncol == 2

        # local validation_frame with remote training_frame raises loudly
        from h2o3_tpu.estimators import H2OGradientBoostingEstimator
        from h2o3_tpu.frame.frame import Frame
        import numpy as np

        with pytest.raises(TypeError, match="RemoteFrame"):
            est = H2OGradientBoostingEstimator(ntrees=2)
            est.train(y="lab", training_frame=fr,
                      validation_frame=Frame.from_numpy(
                          np.zeros((3, 2)), names=["a", "b"]))
    finally:
        h2o.shutdown()


def test_remote_automl_leaderboard(remote_server, csvfile):
    """AutoML drives /99/AutoMLBuilder + Jobs + /99/AutoML over the wire —
    the 'leaderboard' leg of the client contract (VERDICT r03 #3)."""
    h2o.connect(url=remote_server, verbose=False)
    try:
        from h2o3_tpu.automl.automl import H2OAutoML

        fr = h2o.upload_file(csvfile, destination_frame="aml_remote")
        fr["y"] = fr["y"].asfactor()
        aml = H2OAutoML(max_models=2, seed=1, nfolds=2,
                        project_name="aml_rc")
        aml.train(x=["a", "b", "c"], y="y", training_frame=fr)
        assert aml.leaderboard.rows, "empty remote leaderboard"
        assert aml.leaderboard.rows[0]["auc"] > 0.7
        assert aml.leaderboard.sort_metric == "auc"
        assert isinstance(aml.leader, RemoteModel)
        best = aml.get_best_model()
        assert isinstance(best, RemoteModel)
        pred = aml.predict(fr)
        assert pred.nrow == 400
    finally:
        h2o.shutdown()


def test_remote_grid_search(remote_server, csvfile):
    """Grid search over the wire: /99/Grid/{algo} + Jobs + /99/Grids —
    h2o-py's grid REST choreography."""
    h2o.connect(url=remote_server, verbose=False)
    try:
        from h2o3_tpu.estimators import H2OGradientBoostingEstimator
        from h2o3_tpu.models.grid import H2OGridSearch

        fr = h2o.upload_file(csvfile, destination_frame="grid_remote")
        fr["y"] = fr["y"].asfactor()
        gs = H2OGridSearch(H2OGradientBoostingEstimator(ntrees=4, seed=1),
                           hyper_params={"max_depth": [2, 4]},
                           grid_id="rgrid")
        gs.train(x=["a", "b", "c"], y="y", training_frame=fr)
        assert len(gs.models) == 2
        assert all(isinstance(m, RemoteModel) for m in gs.models)
        gs.get_grid(sort_by="auc")
        assert gs.models[0].auc() >= gs.models[1].auc()
    finally:
        h2o.shutdown()


def test_remote_mojo_download_and_frame_pull(remote_server, csvfile,
                                             tmp_path):
    """h2o.save_model on a REST-backed model downloads the artifact;
    RemoteFrame.as_data_frame pulls full contents over DownloadDataset."""
    h2o.connect(url=remote_server, verbose=False)
    try:
        from h2o3_tpu.estimators import H2OGradientBoostingEstimator

        fr = h2o.upload_file(csvfile, destination_frame="dl_remote")
        fr["y"] = fr["y"].asfactor()
        m = H2OGradientBoostingEstimator(ntrees=3, max_depth=3, seed=1)
        m.train(x=["a", "b", "c"], y="y", training_frame=fr)
        path = h2o.save_model(m, str(tmp_path))
        data = fr.as_data_frame()
        assert len(data["a"]) == 400 and isinstance(data["a"][0], float)
    finally:
        h2o.shutdown()
    # artifact loads and scores OFFLINE (no connection)
    scorer = h2o.load_model(path)
    import numpy as np
    from h2o3_tpu.frame.frame import Frame

    Xl = Frame.from_dict({"a": np.asarray(data["a"]),
                          "b": np.asarray(data["b"]),
                          "c": np.asarray(data["c"])})
    p1 = scorer.predict(Xl).vec("1").numeric_np()
    assert np.isfinite(p1).all() and len(p1) == 400


def test_remote_create_frame_interaction_missing_inserter(remote_server):
    """VERDICT r04 #3: the functional route tail — synthetic frame
    generation (/3/CreateFrame), factor interactions (/3/Interaction) and
    NA insertion (/3/MissingInserter) all run SERVER-side, driven through
    the same package surface that works in-process."""
    h2o.connect(url=remote_server, verbose=False)
    try:
        fr = h2o.create_frame(rows=300, cols=6, categorical_fraction=0.5,
                              integer_fraction=0.25, real_fraction=0.25,
                              factors=4, seed=7, frame_id="synth_remote")
        assert isinstance(fr, RemoteFrame)
        assert fr.shape == (300, 6)
        cat_cols = [n for n in fr.names if fr.types.get(n) == "enum"]
        assert len(cat_cols) >= 2, fr.types

        inter = h2o.interaction(fr, factors=cat_cols[:2], pairwise=True,
                                max_factors=100, min_occurrence=1,
                                destination_frame="synth_inter")
        assert isinstance(inter, RemoteFrame)
        assert inter.shape[0] == 300 and inter.shape[1] == 1
        assert inter.types[inter.names[0]] == "enum"

        # MissingInserter mutates the server-side frame in place
        num_col = next(n for n in fr.names if fr.types.get(n) != "enum")
        before = fr.as_data_frame(use_pandas=False)[num_col]
        h2o.insert_missing_values(fr, fraction=0.5, seed=1)
        after = fr.as_data_frame(use_pandas=False)[num_col]
        import math

        n_na = sum(1 for v in after if isinstance(v, float) and math.isnan(v))
        assert n_na > sum(1 for v in before
                          if isinstance(v, float) and math.isnan(v))
        assert 0.3 < n_na / 300 < 0.7
    finally:
        h2o.shutdown()


def test_remote_remove_all_retained(remote_server):
    """`h2o.remove_all(retained=[...])` over a connection clears the
    server DKV except the listed keys (RemoveAllHandler retained_keys)."""
    h2o.connect(url=remote_server, verbose=False)
    try:
        a = h2o.create_frame(rows=50, cols=2, seed=1, frame_id="keepme")
        h2o.create_frame(rows=50, cols=2, seed=2, frame_id="dropme")
        h2o.remove_all(retained=[a])
        keys = [f["frame_id"]["name"] if isinstance(f.get("frame_id"), dict)
                else f.get("frame_id")
                for f in h2o.connection().get("/3/Frames")["frames"]]
        assert "keepme" in keys and "dropme" not in keys
    finally:
        h2o.shutdown()


def test_remote_batch_munging_round_trips(remote_server, csvfile):
    """VERDICT r04 #7: a chained 10-op munge inside `with h2o.batch():`
    reaches the server as ONE multi-statement Rapids POST (plus one read),
    instead of 10 eager round-trips."""
    conn = h2o.connect(url=remote_server, verbose=False)
    try:
        fr = h2o.upload_file(csvfile, destination_frame="batch_src")
        calls = []
        orig = type(conn).request

        def counting(self, method, path, *a, **kw):
            calls.append((method, path))
            return orig(self, method, path, *a, **kw)

        type(conn).request = counting
        try:
            with h2o.batch():
                g = fr["a"]                 # slice + 10 chained derivations
                for _ in range(5):
                    g = g.asfactor()
                    g = g.asnumeric()
                nrows = g.nrow              # first read flushes the chain
            during = list(calls)
        finally:
            type(conn).request = orig
        assert nrows == 400
        rapids_posts = [c for c in during if c[1] == "/99/Rapids"]
        assert len(rapids_posts) == 1, during
        # 1 source-metadata read (fr["a"] name lookup) + 1 flush + 1 final
        # read — the 11 chained derivations themselves cost zero trips
        assert len(during) <= 3, during
        # the chain's final key really exists server-side with full contents
        data = g.as_data_frame(use_pandas=False)
        assert list(data) == ["a"] and len(data["a"]) == 400
    finally:
        h2o.shutdown()


def test_remote_batch_flushes_on_exception(remote_server, csvfile):
    """An exception inside `with h2o.batch():` still lands the assigns
    already chained, so returned RemoteFrame handles stay valid."""
    h2o.connect(url=remote_server, verbose=False)
    try:
        fr = h2o.upload_file(csvfile, destination_frame="batch_exc")
        g = None
        with pytest.raises(RuntimeError, match="boom"):
            with h2o.batch():
                g = fr["a"].asfactor()
                raise RuntimeError("boom")
        assert g.nrow == 400          # the deferred assign reached the server
        assert g.types[g.names[0]] == "enum"
        # and value-returning rapids stayed EAGER inside batch
        with h2o.batch():
            out = h2o.rapids("(+ 1 2)")
        assert out.get("scalar") == 3.0
    finally:
        h2o.shutdown()
