"""NaiveBayes / Word2Vec / GLRM tests — long-tail algorithm coverage."""

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.vec import Vec
from h2o3_tpu.models.naive_bayes import H2ONaiveBayesEstimator
from h2o3_tpu.models.word2vec import H2OWord2vecEstimator
from h2o3_tpu.models.glrm import H2OGeneralizedLowRankEstimator


def test_naive_bayes_gaussian(cloud1):
    rng = np.random.default_rng(0)
    n = 2000
    y = rng.integers(0, 2, n)
    X = rng.normal(size=(n, 3)) + y[:, None] * np.asarray([2.0, -1.5, 0.0])
    fr = Frame.from_numpy(np.column_stack([X, y]),
                          names=["a", "b", "c", "y"]).asfactor("y")
    nb = H2ONaiveBayesEstimator()
    nb.train(y="y", training_frame=fr)
    assert nb.auc() > 0.9
    pred = nb.predict(fr)
    assert pred.names == ["predict", "0", "1"]


def test_naive_bayes_categorical_laplace(cloud1):
    rng = np.random.default_rng(1)
    n = 1500
    c1 = rng.integers(0, 3, n)
    y = ((c1 == 2) ^ (rng.random(n) < 0.1)).astype(int)
    fr = Frame.from_dict({
        "c1": np.asarray(["a", "b", "c"], dtype=object)[c1],
        "y": y,
    }).asfactor("y")
    nb = H2ONaiveBayesEstimator(laplace=1.0)
    nb.train(y="y", training_frame=fr)
    assert nb.auc() > 0.85


def test_word2vec_synonyms(cloud1):
    # tiny corpus with two topic clusters
    rng = np.random.default_rng(2)
    animals = ["cat", "dog", "mouse", "horse"]
    foods = ["apple", "bread", "cheese", "pasta"]
    sents = []
    for _ in range(400):
        group = animals if rng.random() < 0.5 else foods
        sent = list(rng.choice(group, 4)) + [None]  # NA = sentence break
        sents.extend(sent)
    fr = Frame({"words": Vec(None, "string",
                             strings=np.asarray(sents, dtype=object))})
    w2v = H2OWord2vecEstimator(vec_size=16, min_word_freq=2, epochs=100,
                               window_size=3, seed=3, init_learning_rate=1.0)
    w2v.train(training_frame=fr)
    syn = w2v.model.find_synonyms("cat", count=3)
    assert len(syn) == 3
    top = list(syn)[0]
    assert top in animals  # nearest neighbor stays in-topic
    # sentence embedding
    emb = w2v.model.transform(fr, aggregate_method="AVERAGE")
    assert emb.ncol == 16


def test_glrm_low_rank_recovery_and_impute(cloud1):
    rng = np.random.default_rng(4)
    n, p, k = 300, 10, 3
    U = rng.normal(size=(n, k))
    V = rng.normal(size=(k, p))
    A = U @ V + 0.01 * rng.normal(size=(n, p))
    A_missing = A.copy()
    holes = rng.random((n, p)) < 0.15
    A_missing[holes] = np.nan
    fr = Frame.from_numpy(A_missing, names=[f"c{i}" for i in range(p)])
    glrm = H2OGeneralizedLowRankEstimator(k=k, gamma_x=1e-4, gamma_y=1e-4,
                                          max_iterations=100, seed=5)
    glrm.train(training_frame=fr)
    rec = glrm.model.reconstruct(fr).to_numpy()
    # imputed entries close to the true low-rank values
    err = np.abs(rec[holes] - A[holes])
    assert np.median(err) < 0.2
    arch = glrm.model.archetypes()
    assert arch.shape == (k, p)


def test_host_solver_size_guard_warns(cloud1, monkeypatch):
    """Long-tail host-numpy fits warn loudly past their documented row
    envelope (docs/architecture.md 'Host-side solvers')."""
    from h2o3_tpu.models.model_base import warn_host_solver
    from h2o3_tpu.runtime.log import Log

    seen = []
    monkeypatch.setattr(Log, "warn", staticmethod(seen.append))
    warn_host_solver("coxph", 100, bound=500_000)
    assert not seen
    warn_host_solver("coxph", 600_000, bound=500_000)
    assert seen and "host-side" in seen[0]
