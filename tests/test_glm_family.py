"""GLM-family extensions: CoxPH, GAM, ANOVAGLM, ModelSelection.

Mirrors reference pyunits `pyunit_coxph_*`, `pyunit_gam_*`,
`pyunit_anovaglm_*`, `pyunit_modelselection_*` (tolerance asserts vs known
generating processes)."""

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.anovaglm import H2OANOVAGLMEstimator
from h2o3_tpu.models.coxph import H2OCoxProportionalHazardsEstimator
from h2o3_tpu.models.gam import H2OGeneralizedAdditiveEstimator
from h2o3_tpu.models.glm import H2OGeneralizedLinearEstimator
from h2o3_tpu.models.modelselection import H2OModelSelectionEstimator


def _surv_data(n=600, beta=(0.8, -0.5), seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, len(beta)))
    lam = np.exp(X @ np.asarray(beta))
    t = rng.exponential(1.0 / lam)
    c = rng.exponential(2.0 / lam.mean(), n)  # independent censoring
    time = np.minimum(t, c)
    event = (t <= c).astype(np.float64)
    return Frame.from_dict({"x1": X[:, 0], "x2": X[:, 1], "time": time, "event": event})


def test_coxph_recovers_coefficients(cloud1):
    fr = _surv_data()
    cox = H2OCoxProportionalHazardsEstimator(stop_column="time", ties="efron")
    cox.train(x=["x1", "x2"], y="event", training_frame=fr)
    m = cox.model
    coef = m.coef()
    assert coef["x1"] == pytest.approx(0.8, abs=0.2)
    assert coef["x2"] == pytest.approx(-0.5, abs=0.2)
    # likelihood improved over null, concordance well above chance
    assert m.loglik > m.loglik_null
    assert m.concordance > 0.6
    tab = m.coefficients_table()
    assert all(r["se_coef"] > 0 for r in tab)
    # breslow close to efron on modest ties
    cox2 = H2OCoxProportionalHazardsEstimator(stop_column="time", ties="breslow")
    cox2.train(x=["x1", "x2"], y="event", training_frame=fr)
    assert cox2.model.coef()["x1"] == pytest.approx(coef["x1"], abs=0.05)


def test_coxph_start_column_and_strata(cloud1):
    fr = _surv_data(seed=8)
    # start=0 for everyone ⇒ identical fit to no start_column
    z = np.zeros(fr.nrow)
    fr["start"] = z
    base = H2OCoxProportionalHazardsEstimator(stop_column="time")
    base.train(x=["x1", "x2"], y="event", training_frame=fr)
    cp = H2OCoxProportionalHazardsEstimator(stop_column="time", start_column="start")
    cp.train(x=["x1", "x2"], y="event", training_frame=fr)
    assert cp.model.coef()["x1"] == pytest.approx(base.model.coef()["x1"], abs=1e-5)
    # late entry removes early-time rows from risk sets → coefficients move
    rng = np.random.default_rng(9)
    fr["start"] = np.minimum(rng.uniform(0, 0.05, fr.nrow),
                             fr.vec("time").numeric_np() * 0.5)
    cp2 = H2OCoxProportionalHazardsEstimator(stop_column="time", start_column="start")
    cp2.train(x=["x1", "x2"], y="event", training_frame=fr)
    assert np.isfinite(cp2.model.coef()["x1"])
    # strata: stratified fit still recovers the signs/magnitudes
    g = (rng.uniform(size=fr.nrow) > 0.5).astype(int)
    fr["grp"] = np.asarray(["a", "b"], dtype=object)[g]
    fr = fr.asfactor("grp")
    cs = H2OCoxProportionalHazardsEstimator(stop_column="time", stratify_by=["grp"])
    cs.train(x=["x1", "x2", "grp"], y="event", training_frame=fr)
    assert cs.model.coef()["x1"] == pytest.approx(0.8, abs=0.25)
    assert "grp" not in "".join(cs.model.coef().keys())


def test_gam_beats_glm_on_nonlinear(cloud1):
    rng = np.random.default_rng(3)
    x = rng.uniform(-3, 3, 800)
    z = rng.normal(size=800)
    y = np.sin(x) * 2 + 0.5 * z + rng.normal(0, 0.1, 800)
    fr = Frame.from_dict({"x": x, "z": z, "y": y})
    gam = H2OGeneralizedAdditiveEstimator(gam_columns=["x"], num_knots=[10], family="gaussian")
    gam.train(x=["x", "z"], y="y", training_frame=fr)
    glm = H2OGeneralizedLinearEstimator(family="gaussian", lambda_=0.0)
    glm.train(x=["x", "z"], y="y", training_frame=fr)
    assert gam.model.training_metrics.rmse < 0.5 * glm.model.training_metrics.rmse
    assert gam.model.training_metrics.rmse < 0.2
    p = gam.predict(fr).vec("predict").numeric_np()
    assert np.corrcoef(p, y)[0, 1] > 0.98


def test_gam_binomial(cloud1):
    rng = np.random.default_rng(4)
    x = rng.uniform(-3, 3, 600)
    eta = np.sin(x) * 3
    y = (rng.uniform(size=600) < 1 / (1 + np.exp(-eta))).astype(int)
    fr = Frame.from_dict({"x": x, "y": np.asarray(["n", "p"], dtype=object)[y]},
                         column_types={"y": "enum"})
    gam = H2OGeneralizedAdditiveEstimator(gam_columns=["x"], num_knots=[8], family="binomial")
    gam.train(x=["x"], y="y", training_frame=fr)
    assert gam.model.training_metrics.auc > 0.8


def test_anovaglm_identifies_active_term(cloud1):
    rng = np.random.default_rng(5)
    a = rng.normal(size=500)
    b = rng.normal(size=500)
    y = 2.0 * a + rng.normal(0, 0.5, 500)  # only a matters; no interaction
    fr = Frame.from_dict({"a": a, "b": b, "y": y})
    an = H2OANOVAGLMEstimator(family="gaussian", highest_interaction_term=2)
    an.train(x=["a", "b"], y="y", training_frame=fr)
    res = an.model.result()
    mv = res.vec("model")
    terms = [mv.domain[c] for c in np.asarray(mv.data)]
    pvals = dict(zip(terms, res.vec("p_value").numeric_np()))
    assert pvals["a"] < 0.01
    assert pvals["b"] > 0.05
    assert pvals["a:b"] > 0.01


def test_modelselection_modes(cloud1):
    rng = np.random.default_rng(6)
    n = 400
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    x3 = rng.normal(size=n)  # noise
    y = 3 * x1 + 1.5 * x2 + rng.normal(0, 0.3, n)
    fr = Frame.from_dict({"x1": x1, "x2": x2, "x3": x3, "y": y})
    for mode in ("maxr", "allsubsets", "backward"):
        ms = H2OModelSelectionEstimator(mode=mode, max_predictor_number=3,
                                        family="gaussian")
        ms.train(x=["x1", "x2", "x3"], y="y", training_frame=fr)
        preds = ms.model.get_best_model_predictors()
        # size-1 best is x1, size-2 best is {x1, x2}
        assert preds[0] == ["x1"]
        assert set(preds[1]) == {"x1", "x2"}
        r2s = ms.model.get_best_r2_values()
        assert r2s[1] > r2s[0]
        assert r2s[1] > 0.95
    coefs = ms.model.coef(predictor_size=2)
    assert coefs["x1"] == pytest.approx(3.0, abs=0.1)


def test_glm_p_values(cloud1):
    rng = np.random.default_rng(11)
    n = 2000
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)  # noise predictor
    sigma = 3.0
    y = 2.0 * x1 + rng.normal(0, sigma, n)
    fr = Frame.from_dict({"x1": x1, "x2": x2, "y": y})
    # default standardize=True: table must still report data-scale values
    g = H2OGeneralizedLinearEstimator(family="gaussian", lambda_=0.0,
                                      compute_p_values=True)
    g.train(x=["x1", "x2"], y="y", training_frame=fr)
    tab = g.model.coef_with_p_values()
    row = {r["names"]: r for r in tab}
    assert row["x1"]["p_value"] < 1e-6
    assert row["x2"]["p_value"] > 0.001
    # dispersion-scaled SE ≈ sigma/sqrt(n)
    se_true = sigma / np.sqrt(n)
    assert row["x1"]["std_error"] == pytest.approx(se_true, rel=0.3)
    # data-scale coefficients match coef()
    assert row["x1"]["coefficients"] == pytest.approx(g.model.coef()["x1"], abs=1e-8)


def test_poisson_family_deviance_for_lambda_search(cloud1):
    """Lambda selection must use the poisson unit deviance, not squared
    error (ADVICE r01): with a low-mean count response the two orderings
    disagree, and the per-family deviance of the chosen model must be
    no worse than what plain MSE selection would imply."""
    from h2o3_tpu.models.glm import _family_deviance_sum

    rng = np.random.default_rng(5)
    n = 4000
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    mu = np.exp(0.3 + 0.8 * x1 - 0.5 * x2)
    y = rng.poisson(mu).astype(np.float64)
    fr = Frame.from_dict({"x1": x1, "x2": x2, "y": y})
    g = H2OGeneralizedLinearEstimator(family="poisson", lambda_search=True,
                                      nlambdas=12)
    g.train(x=["x1", "x2"], y="y", training_frame=fr)
    coefs = g.model.coef()
    # recovers the generating coefficients reasonably
    assert coefs["x1"] == pytest.approx(0.8, abs=0.15)
    assert coefs["x2"] == pytest.approx(-0.5, abs=0.15)
    # unit-deviance helper sanity: perfect fit has ~zero deviance
    assert float(_family_deviance_sum("poisson", y, np.clip(y, 1e-10, None),
                                      np.ones(n), xp=np)) < 1e-6 * n


def test_tweedie_boundary_powers_lambda_search(cloud1):
    """tweedie_variance_power of exactly 1.0/2.0 must use the poisson/gamma
    limit deviances, not divide by zero (review r02)."""
    rng = np.random.default_rng(7)
    n = 1500
    x1 = rng.normal(size=n)
    mu = np.exp(0.5 + 0.6 * x1)
    y = rng.gamma(shape=2.0, scale=mu / 2.0)
    fr = Frame.from_dict({"x1": x1, "y": y})
    for power in (1.0, 2.0):
        g = H2OGeneralizedLinearEstimator(
            family="tweedie", tweedie_variance_power=power,
            lambda_search=True, nlambdas=8)
        g.train(x=["x1"], y="y", training_frame=fr)
        assert g.model.coef()["x1"] == pytest.approx(0.6, abs=0.2)


def test_gamma_tweedie_unit_deviances():
    from h2o3_tpu.models.glm import _family_deviance_sum

    y = np.asarray([0.5, 1.0, 2.0, 4.0])
    w = np.ones(4)
    # deviance is zero at mu == y and positive elsewhere
    for fam, tp in [("gamma", 1.5), ("tweedie", 1.5)]:
        d0 = float(_family_deviance_sum(fam, y, y, w, tp, xp=np))
        d1 = float(_family_deviance_sum(fam, y, y * 1.5, w, tp, xp=np))
        assert abs(d0) < 1e-9
        assert d1 > 0
