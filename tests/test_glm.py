"""GLM tests — `h2o-py/tests/testdir_algos/glm` analog: coefficient recovery
and metric quality vs known generating models."""

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.glm import H2OGeneralizedLinearEstimator


def test_glm_gaussian_ols_recovers_coefficients(cloud1):
    rng = np.random.default_rng(0)
    n = 2000
    X = rng.normal(size=(n, 3))
    beta = np.asarray([2.0, -1.0, 0.5])
    y = X @ beta + 3.0 + 0.01 * rng.normal(size=n)
    fr = Frame.from_numpy(np.column_stack([X, y]), names=["a", "b", "c", "y"])
    glm = H2OGeneralizedLinearEstimator(family="gaussian", lambda_=0.0)
    glm.train(y="y", training_frame=fr)
    coef = glm.coef()
    assert coef["a"] == pytest.approx(2.0, abs=0.02)
    assert coef["b"] == pytest.approx(-1.0, abs=0.02)
    assert coef["c"] == pytest.approx(0.5, abs=0.02)
    assert coef["Intercept"] == pytest.approx(3.0, abs=0.02)
    assert glm.model.r2() > 0.99 if hasattr(glm.model, "r2") else True


def test_glm_binomial_logistic(cloud1):
    rng = np.random.default_rng(1)
    n = 4000
    X = rng.normal(size=(n, 2))
    logits = 1.5 * X[:, 0] - 2.0 * X[:, 1] + 0.3
    y = (rng.random(n) < 1 / (1 + np.exp(-logits))).astype(int)
    fr = Frame.from_numpy(np.column_stack([X, y]), names=["a", "b", "y"]).asfactor("y")
    glm = H2OGeneralizedLinearEstimator(family="binomial", lambda_=0.0)
    glm.train(y="y", training_frame=fr)
    coef = glm.coef()
    assert coef["a"] == pytest.approx(1.5, abs=0.25)
    assert coef["b"] == pytest.approx(-2.0, abs=0.3)
    assert glm.auc() > 0.85
    pred = glm.predict(fr)
    assert pred.names == ["predict", "0", "1"]


def test_glm_ridge_shrinks(cloud1):
    rng = np.random.default_rng(2)
    n = 500
    X = rng.normal(size=(n, 4))
    y = X[:, 0] + 0.1 * rng.normal(size=n)
    fr = Frame.from_numpy(np.column_stack([X, y]), names=["a", "b", "c", "d", "y"])
    g0 = H2OGeneralizedLinearEstimator(family="gaussian", lambda_=0.0, alpha=0.0)
    g0.train(y="y", training_frame=fr)
    g1 = H2OGeneralizedLinearEstimator(family="gaussian", lambda_=10.0, alpha=0.0)
    g1.train(y="y", training_frame=fr)
    assert abs(g1.coef()["a"]) < abs(g0.coef()["a"])


def test_glm_lasso_sparsifies(cloud1):
    rng = np.random.default_rng(3)
    n = 800
    X = rng.normal(size=(n, 6))
    y = 2 * X[:, 0] + 0.05 * rng.normal(size=n)  # only x0 matters
    fr = Frame.from_numpy(np.column_stack([X, y]),
                          names=[f"x{i}" for i in range(6)] + ["y"])
    glm = H2OGeneralizedLinearEstimator(family="gaussian", lambda_=0.05, alpha=1.0)
    glm.train(y="y", training_frame=fr)
    cn = glm.coef_norm()
    noise = [abs(cn[f"x{i}"]) for i in range(1, 6)]
    assert max(noise) < 0.02
    assert abs(cn["x0"]) > 0.5


def test_glm_lambda_search(cloud1):
    rng = np.random.default_rng(4)
    n = 600
    X = rng.normal(size=(n, 5))
    y = X[:, 0] - X[:, 1] + 0.1 * rng.normal(size=n)
    fr = Frame.from_numpy(np.column_stack([X, y]),
                          names=[f"x{i}" for i in range(5)] + ["y"])
    glm = H2OGeneralizedLinearEstimator(family="gaussian", lambda_search=True, alpha=0.5)
    glm.train(y="y", training_frame=fr)
    path = H2OGeneralizedLinearEstimator.getGLMRegularizationPath(glm)
    assert len(path["lambdas"]) > 5
    assert glm.model.training_metrics.mse < 0.05


def test_glm_poisson(cloud1):
    rng = np.random.default_rng(5)
    n = 3000
    X = rng.normal(size=(n, 2))
    lam = np.exp(0.8 * X[:, 0] - 0.4 * X[:, 1] + 0.2)
    y = rng.poisson(lam)
    fr = Frame.from_numpy(np.column_stack([X, y]), names=["a", "b", "y"])
    glm = H2OGeneralizedLinearEstimator(family="poisson", lambda_=0.0)
    glm.train(y="y", training_frame=fr)
    coef = glm.coef()
    assert coef["a"] == pytest.approx(0.8, abs=0.1)
    assert coef["b"] == pytest.approx(-0.4, abs=0.1)


def test_glm_multinomial(cloud1):
    rng = np.random.default_rng(6)
    n = 3000
    X = rng.normal(size=(n, 4))
    scores = np.column_stack([X[:, 0], X[:, 1], -X[:, 0] - X[:, 1]])
    y = scores.argmax(axis=1)
    fr = Frame.from_numpy(np.column_stack([X, y]),
                          names=["a", "b", "c", "d", "y"]).asfactor("y")
    glm = H2OGeneralizedLinearEstimator(family="multinomial", lambda_=0.0)
    glm.train(y="y", training_frame=fr)
    m = glm.model.training_metrics
    assert m.accuracy > 0.85
    assert m.logloss < 0.5


def test_glm_categorical_expansion(cloud1):
    rng = np.random.default_rng(7)
    n = 1500
    cat = rng.integers(0, 3, n)
    effect = np.asarray([0.0, 1.0, -1.0])[cat]
    y = effect + 0.05 * rng.normal(size=n)
    fr = Frame.from_dict({
        "g": np.asarray(["a", "b", "c"], dtype=object)[cat], "y": y,
    })
    glm = H2OGeneralizedLinearEstimator(family="gaussian", lambda_=0.0)
    glm.train(y="y", training_frame=fr)
    coef = glm.coef()
    assert "g.b" in coef and "g.c" in coef
    assert coef["g.b"] == pytest.approx(1.0, abs=0.05)
    assert coef["g.c"] == pytest.approx(-1.0, abs=0.05)


def test_glm_pvalues(cloud1):
    rng = np.random.default_rng(8)
    n = 1000
    X = rng.normal(size=(n, 2))
    y = X @ np.asarray([1.0, 0.0]) + 0.5 * rng.normal(size=n)
    fr = Frame.from_numpy(np.column_stack([X, y]), names=["a", "b", "y"])
    glm = H2OGeneralizedLinearEstimator(family="gaussian", lambda_=0.0,
                                        compute_p_values=True, standardize=False)
    glm.train(y="y", training_frame=fr)
    assert glm.model.stderr is not None
    assert glm.model.stderr.shape[0] == 3


def test_glm_multichip(cloud8):
    rng = np.random.default_rng(9)
    n = 4096
    X = rng.normal(size=(n, 3))
    y = X @ np.asarray([1.0, -0.5, 0.25]) + 2.0 + 0.01 * rng.normal(size=n)
    fr = Frame.from_numpy(np.column_stack([X, y]), names=["a", "b", "c", "y"])
    glm = H2OGeneralizedLinearEstimator(family="gaussian", lambda_=0.0)
    glm.train(y="y", training_frame=fr)
    assert glm.coef()["a"] == pytest.approx(1.0, abs=0.02)


def test_lambda_search_validation_selection(cloud1):
    import numpy as np
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models.glm import H2OGeneralizedLinearEstimator

    rng = np.random.default_rng(3)
    n, p = 120, 40  # p-heavy: training deviance favours tiny lambda
    X = rng.normal(size=(n, p))
    beta = np.zeros(p)
    beta[:3] = [2.0, -1.5, 1.0]
    y = X @ beta + rng.normal(0, 1.0, n)
    names = [f"x{i}" for i in range(p)]
    fr = Frame.from_numpy(np.column_stack([X, y]), names=names + ["y"])
    Xv = rng.normal(size=(200, p))
    yv = Xv @ beta + rng.normal(0, 1.0, 200)
    vf = Frame.from_numpy(np.column_stack([Xv, yv]), names=names + ["y"])
    g = H2OGeneralizedLinearEstimator(family="gaussian", alpha=1.0,
                                      lambda_search=True)
    g.train(x=names, y="y", training_frame=fr, validation_frame=vf)
    gt = H2OGeneralizedLinearEstimator(family="gaussian", alpha=1.0,
                                       lambda_search=True)
    gt.train(x=names, y="y", training_frame=fr)
    # validation-selected lambda regularizes more than train-selected
    assert g.model.lambda_best >= gt.model.lambda_best
    assert g.model.lambda_best > 0
