"""Edge-case robustness across the main estimator families — the analog of
the reference's testdir_jira regression sweeps: all-NA columns, constant
columns, tiny frames, unseen categories at predict, single-class responses.
"""

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame


def _edge_frame(n=60, seed=0):
    rng = np.random.default_rng(seed)
    return Frame.from_dict({
        "num": rng.normal(size=n),
        "allna": np.full(n, np.nan),
        "const": np.ones(n),
        "cat": np.asarray(["a", "b", "c"], dtype=object)[rng.integers(0, 3, n)],
        "y": (rng.uniform(size=n) > 0.5).astype(float),
    }, column_types={"cat": "enum"})


def test_gbm_edge_cases(cloud1):
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

    fr = _edge_frame().asfactor("y")
    m = H2OGradientBoostingEstimator(ntrees=3, max_depth=2, seed=1)
    # all-NA and const columns are dropped/ignored without crashing
    m.train(x=["num", "allna", "const", "cat"], y="y", training_frame=fr)
    # predict with an UNSEEN category level
    test = Frame.from_dict({
        "num": np.asarray([0.0]), "allna": np.asarray([np.nan]),
        "const": np.asarray([1.0]),
        "cat": np.asarray(["zzz_new"], dtype=object)},
        column_types={"cat": "enum"})
    p = m.predict(test)
    assert p.nrow == 1 and np.isfinite(p.vec("1").numeric_np()).all()


def test_glm_edge_cases(cloud1):
    from h2o3_tpu.models.glm import H2OGeneralizedLinearEstimator

    fr = _edge_frame(seed=1)
    g = H2OGeneralizedLinearEstimator(family="gaussian", lambda_=0.0)
    g.train(x=["num", "allna", "const", "cat"], y="y", training_frame=fr)
    p = g.predict(fr)
    assert np.isfinite(p.vec("predict").numeric_np()).all()


def test_tiny_frames(cloud1):
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
    from h2o3_tpu.models.kmeans import H2OKMeansEstimator

    # 3-row regression
    fr = Frame.from_dict({"a": np.asarray([1.0, 2.0, 3.0]),
                          "y": np.asarray([1.0, 2.0, 3.0])})
    m = H2OGradientBoostingEstimator(ntrees=2, max_depth=2, min_rows=1.0)
    m.train(x=["a"], y="y", training_frame=fr)
    assert np.isfinite(m.predict(fr).vec("predict").numeric_np()).all()
    # kmeans with k > distinct points clamps/degrades gracefully
    km = H2OKMeansEstimator(k=2, seed=1)
    km.train(x=["a"], training_frame=fr)
    assert km.predict(fr).nrow == 3


def test_na_response_rows_dropped(cloud1):
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

    rng = np.random.default_rng(2)
    y = rng.normal(size=50)
    y[:10] = np.nan
    fr = Frame.from_dict({"a": rng.normal(size=50), "y": y})
    m = H2OGradientBoostingEstimator(ntrees=2, max_depth=2)
    m.train(x=["a"], y="y", training_frame=fr)  # NA-response rows dropped
    assert m.model.training_metrics.nobs == 40


def test_single_class_response_fails_cleanly(cloud1):
    from h2o3_tpu.models.glm import H2OGeneralizedLinearEstimator

    fr = Frame.from_dict({
        "a": np.asarray([1.0, 2.0, 3.0, 4.0]),
        "y": np.asarray(["x", "x", "x", "x"], dtype=object),
    }, column_types={"y": "enum"})
    g = H2OGeneralizedLinearEstimator(family="binomial")
    with pytest.raises(Exception):  # clean error, not a hang/garbage model
        g.train(x=["a"], y="y", training_frame=fr)


def test_predict_missing_column_errors_clearly(cloud1):
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

    rng = np.random.default_rng(3)
    fr = Frame.from_dict({"a": rng.normal(size=50), "b": rng.normal(size=50),
                          "y": rng.normal(size=50)})
    m = H2OGradientBoostingEstimator(ntrees=2, max_depth=2)
    m.train(x=["a", "b"], y="y", training_frame=fr)
    with pytest.raises(KeyError):
        m.predict(Frame.from_dict({"a": np.asarray([1.0])}))


def test_deeplearning_constant_target(cloud1):
    from h2o3_tpu.models.deeplearning import H2ODeepLearningEstimator

    rng = np.random.default_rng(4)
    fr = Frame.from_dict({"a": rng.normal(size=100),
                          "y": np.full(100, 3.0)})
    dl = H2ODeepLearningEstimator(hidden=[4], epochs=2, mini_batch_size=16)
    dl.train(x=["a"], y="y", training_frame=fr)
    p = dl.predict(fr).vec("predict").numeric_np()
    assert np.isfinite(p).all()


def test_mojo_roundtrip_with_enum_and_na(tmp_path, cloud1):
    import h2o3_tpu as h2o
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

    fr = _edge_frame(200, seed=5).asfactor("y")
    m = H2OGradientBoostingEstimator(ntrees=5, max_depth=3, seed=1)
    m.train(x=["num", "cat"], y="y", training_frame=fr)
    path = h2o.save_model(m, str(tmp_path))
    sc = h2o.load_model(path)
    a = m.predict(fr).vec("1").numeric_np()
    b = sc.predict(fr).vec("1").numeric_np()
    np.testing.assert_allclose(a, b, atol=1e-6)


@pytest.mark.parametrize("cls_name,kw", [
    ("gbm", dict(ntrees=0)), ("gbm", dict(ntrees=-5)),
    ("gbm", dict(learn_rate=0.0)), ("gbm", dict(learn_rate=-1.0)),
    ("gbm", dict(sample_rate=0.0)), ("gbm", dict(sample_rate=2.0)),
    ("gbm", dict(max_depth=0)), ("gbm", dict(nbins=1)),
    ("gbm", dict(min_rows=-3)), ("gbm", dict(col_sample_rate=0.0)),
    ("gbm", dict(nfolds=1)), ("gbm", dict(nfolds=-2)),
    ("drf", dict(mtries=99)),
    ("glm", dict(family="bogus")), ("glm", dict(alpha=5.0)),
    ("glm", dict(lambda_=-1.0)),
    ("dl", dict(hidden=[])), ("dl", dict(hidden=[-5])),
    ("dl", dict(epochs=-1)), ("dl", dict(mini_batch_size=0)),
])
def test_invalid_param_values_raise(cloud1, cls_name, kw):
    """Value-range validation (hex.ModelBuilder.init): nonsense parameter
    values raise LOUDLY instead of training degenerate models (found by
    fuzzing — e.g. ntrees=0 used to 'train' to AUC 0.5)."""
    import h2o3_tpu as h2o
    from h2o3_tpu.estimators import (H2OGradientBoostingEstimator,
                                     H2OGeneralizedLinearEstimator,
                                     H2ODeepLearningEstimator,
                                     H2ORandomForestEstimator)

    cls = {"gbm": H2OGradientBoostingEstimator,
           "drf": H2ORandomForestEstimator,
           "glm": H2OGeneralizedLinearEstimator,
           "dl": H2ODeepLearningEstimator}[cls_name]
    rng = np.random.default_rng(0)
    fr = h2o.H2OFrame_from_python(
        {"a": rng.normal(size=80), "b": rng.normal(size=80),
         "y": (rng.random(80) > 0.5).astype(int).astype(str)},
        column_types={"y": "enum"})
    est = cls(**kw, seed=1)   # constructor accepts; TRAIN validates values
    with pytest.raises(ValueError):
        est.train(x=["a", "b"], y="y", training_frame=fr)
