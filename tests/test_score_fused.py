"""Fused forest scoring (`build_score_table` + `predict_forest_fused`)
must match the reference per-level walk (`predict_forest_raw`) bit-for-bit
up to reduction-order rounding, across depths, raggedness, NaNs, and the
large-F gather fallback. Scoring analog of the in-cluster ≡ MOJO parity
tests upstream keeps for `SharedTreeMojoModel.scoreTree`."""

import numpy as np
import jax.numpy as jnp
import pytest

from h2o3_tpu.models import tree as treelib


def _random_forest(rng, nt, depth, F, frac_leaf=0.15):
    T = treelib.heap_size(depth)
    feat = rng.integers(0, F, size=(nt, T)).astype(np.int32)
    thr = rng.normal(size=(nt, T)).astype(np.float32)
    issp = np.zeros((nt, T), bool)
    issp[:, : 2 ** depth - 1] = True
    issp[rng.random((nt, T)) < frac_leaf] = False
    val = (rng.normal(size=(nt, T)) * 0.1).astype(np.float32)
    return treelib.Tree(jnp.asarray(feat), jnp.asarray(feat),
                        jnp.asarray(thr), jnp.asarray(issp),
                        jnp.asarray(val))


@pytest.mark.parametrize("depth", [1, 2, 4, 5, 6, 8, 11])
def test_fused_matches_walk(depth):
    rng = np.random.default_rng(depth)
    F = 7
    forest = _random_forest(rng, nt=6, depth=depth, F=F)
    X = rng.normal(size=(257, F)).astype(np.float32)
    X[rng.random(X.shape) < 0.05] = np.nan
    Xj = jnp.asarray(X)
    ref = np.asarray(treelib.predict_forest_raw(forest, Xj, depth))
    walk, value = treelib.build_score_table_jit(forest, max_depth=depth)
    out = np.asarray(treelib.predict_forest_fused(walk, value, Xj, depth))
    np.testing.assert_allclose(out, ref, rtol=0, atol=1e-5)


def test_fused_large_f_gather_fallback():
    """F > _XV_ONEHOT_MAX exercises the flat-gather X fetch branch."""
    rng = np.random.default_rng(0)
    F = treelib._XV_ONEHOT_MAX + 5
    forest = _random_forest(rng, nt=3, depth=4, F=F)
    X = rng.normal(size=(64, F)).astype(np.float32)
    X[rng.random(X.shape) < 0.05] = np.nan
    Xj = jnp.asarray(X)
    ref = np.asarray(treelib.predict_forest_raw(forest, Xj, 4))
    walk, value = treelib.build_score_table_jit(forest, max_depth=4)
    out = np.asarray(treelib.predict_forest_fused(walk, value, Xj, 4))
    np.testing.assert_allclose(out, ref, rtol=0, atol=1e-5)


def test_fused_depth_zero_stumps():
    rng = np.random.default_rng(1)
    T = 1
    forest = treelib.Tree(jnp.zeros((4, T), jnp.int32),
                          jnp.zeros((4, T), jnp.int32),
                          jnp.zeros((4, T), jnp.float32),
                          jnp.zeros((4, T), bool),
                          jnp.asarray(rng.normal(size=(4, T)),
                                      jnp.float32))
    X = jnp.asarray(rng.normal(size=(10, 3)), jnp.float32)
    ref = np.asarray(treelib.predict_forest_raw(forest, X, 0))
    walk, value = treelib.build_score_table_jit(forest, max_depth=0)
    out = np.asarray(treelib.predict_forest_fused(walk, value, X, 0))
    np.testing.assert_allclose(out, ref, rtol=0, atol=1e-6)


def test_fused_padded_zero_trees():
    """Zero-padded trees (pow2 tree-count bucketing) contribute exactly 0."""
    rng = np.random.default_rng(2)
    forest = _random_forest(rng, nt=5, depth=3, F=4)
    zpad = treelib.Tree(*[jnp.concatenate(
        [np.asarray(f), np.zeros((3,) + np.asarray(f).shape[1:],
                                 np.asarray(f).dtype)], axis=0)
        for f in forest])
    X = jnp.asarray(rng.normal(size=(50, 4)), jnp.float32)
    ref = np.asarray(treelib.predict_forest_raw(forest, X, 3))
    walk, value = treelib.build_score_table_jit(zpad, max_depth=3)
    out = np.asarray(treelib.predict_forest_fused(walk, value, X, 3))
    np.testing.assert_allclose(out, ref, rtol=0, atol=1e-6)


def test_model_margins_fused_equals_walk(cloud1, monkeypatch, tmp_path):
    """End-to-end: a trained GBM scores a FRESH frame identically through
    the fused scorer and the reference walk."""
    import h2o3_tpu as h2o
    from h2o3_tpu.estimators import H2OGradientBoostingEstimator

    rng = np.random.default_rng(3)
    n = 400
    X = rng.normal(size=(n, 4))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(int)
    csv = tmp_path / "t.csv"
    with open(csv, "w") as f:
        f.write("a,b,c,d,y\n")
        for i in range(n):
            f.write(",".join(f"{v:.5f}" for v in X[i]) + f",{y[i]}\n")
    fr = h2o.import_file(str(csv))
    fr["y"] = fr["y"].asfactor()
    m = H2OGradientBoostingEstimator(ntrees=8, max_depth=4, seed=1)
    m.train(x=["a", "b", "c", "d"], y="y", training_frame=fr)
    Xnew = rng.normal(size=(97, 4)).astype(np.float32)
    Xnew[rng.random(Xnew.shape) < 0.05] = np.nan
    mb = m._model
    monkeypatch.setenv("H2O3_FOREST_SCORER", "walk")
    ref = mb._margins(Xnew)
    mb.__dict__.pop("_score_tables", None)
    monkeypatch.setenv("H2O3_FOREST_SCORER", "fused")
    out = mb._margins(Xnew)
    np.testing.assert_allclose(out, ref, rtol=0, atol=1e-5)
