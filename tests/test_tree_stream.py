"""Out-of-core streamed GBM/DRF (ISSUE 14) — block streaming under the
memory ledger's budget, bit-exactness vs the in-core fit, and GOSS.

Pins: (1) a streamed fit (sampling OFF) is BIT-IDENTICAL to the in-core
fit sharing its block count S — forest, varimp, scoring history,
early-stop tree count, CV metrics, predictions — across GBM/DRF ×
early-stop × CV fold reuse × host-kernel lane; (2) the `H2O3_TREE_OOC=0`
escape hatch is pinned bit-equal to a plain fit; (3) BlockStore device
eviction ORDER lands in the timeline (cap = LRU, pressure = shed keeps
only the double buffer), mirroring test_memory_ledger's LRU pin; (4) the
stream is observable — per-fit `_stream_stats`, the plan's `stream` fold,
the `h2d_stream` phase bucket and the Prometheus counters; (5) GOSS is
deterministic per seed, streams FEWER bytes than the unsampled fit, and
rejects invalid configs; (6) the disk tier (round 19) — spill LRU ORDER
via timeline events, evict-then-restore keeps the host watermark under
budget, restores are bit-identical (also mid-read under an armed
`persist.read` fault), spilled copies are kept, and a spilled fit is
bit-identical to in-core across GBM early-stop × DRF × CV fold reuse,
with `H2O3_TREE_OOC_DISK=0` pinning the host-only escape hatch. The
oversubscribed whole-fit (matrix ≥10× the budget, resident watermark
under budget) and the mesh-oversubscription pin run as ``slow`` (tier-1
budget is tight)."""

import os

import numpy as np
import pytest

import jax.numpy as jnp

from h2o3_tpu.models import block_store as bslib
from h2o3_tpu.models import tree as treelib
from h2o3_tpu.ops import histogram, packing
from h2o3_tpu.runtime import memory_ledger as ml
from h2o3_tpu.runtime.timeline import Timeline

from conftest import make_classification

_ENV_KEYS = ("H2O3_TREE_OOC", "H2O3_STREAM_BLOCKS", "H2O3_STREAM_BUDGET_MB",
             "H2O3_TREE_SHARD", "H2O3_TREE_SHARD_BLOCKS", "H2O3_TREE_LEGACY",
             "H2O3_HIST_METHOD", "H2O3_HOST_HIST_MIN_ROWS",
             "H2O3_MEM_BUDGET_MB", "H2O3_MEM_EVICT_PRESSURE",
             "H2O3_STREAM_HOST_BUDGET_MB", "H2O3_TREE_OOC_DISK",
             "H2O3_SPILL_DIR")

# the streamed fit and its in-core comparator share S=4 — the reduction
# tree is a function of S alone (PR 9), which is what makes the pair
# bit-comparable
_STREAM_ENV = {"H2O3_TREE_OOC": "1", "H2O3_STREAM_BLOCKS": "4",
               "H2O3_STREAM_BUDGET_MB": "0.02"}
_INCORE_ENV = {"H2O3_TREE_OOC": "0", "H2O3_TREE_SHARD": "1",
               "H2O3_TREE_SHARD_BLOCKS": "4"}
# the spilled fit adds a host-tier budget under the packed matrix size,
# so blocks overflow through the disk tier too — same S=4 grid, so the
# whole bit-exactness matrix above applies unchanged
_SPILL_ENV = dict(_STREAM_ENV, H2O3_STREAM_HOST_BUDGET_MB="0.005")

_X, _Y = make_classification(n=1500, f=8, seed=3)
_NAMES = [f"f{i}" for i in range(8)] + ["label"]


@pytest.fixture()
def _ooc_env():
    prior = {k: os.environ.pop(k, None) for k in _ENV_KEYS}
    yield
    for k, v in prior.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    ml.refresh(force=True)


def _frame(X=_X, y=_Y, names=_NAMES, factor=True):
    from h2o3_tpu.frame.frame import Frame

    fr = Frame.from_numpy(np.column_stack([X, y]), names=names)
    return fr.asfactor("label") if factor else fr


def _fit(env, mode="gbm", X=_X, y=_Y, names=_NAMES, frame=None,
         factor=True, **params):
    from h2o3_tpu.models import dataset_cache
    from h2o3_tpu.models.drf import H2ORandomForestEstimator
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

    dataset_cache.clear()
    for k in _ENV_KEYS:
        os.environ.pop(k, None)
    os.environ.update(env)
    try:
        cls = (H2OGradientBoostingEstimator if mode == "gbm"
               else H2ORandomForestEstimator)
        est = cls(seed=42, **params)
        est.train(y="label",
                  training_frame=frame if frame is not None
                  else _frame(X, y, names, factor))
    finally:
        for k in _ENV_KEYS:
            os.environ.pop(k, None)
    return est


def _assert_bitexact(a, b):
    assert a.model.ntrees_built == b.model.ntrees_built
    for k in range(len(a.model.forest)):
        for f in treelib.Tree._fields:
            assert np.array_equal(
                np.asarray(getattr(a.model.forest[k], f)),
                np.asarray(getattr(b.model.forest[k], f))), (k, f)
    va = getattr(a.model, "varimp_table", None)
    vb = getattr(b.model, "varimp_table", None)
    if va is not None or vb is not None:
        assert [r[0] for r in va] == [r[0] for r in vb]
        np.testing.assert_array_equal([r[1] for r in va],
                                      [r[1] for r in vb])


# -- ops: the block-wise pack API -------------------------------------------

def test_pack_host_range_matches_whole_matrix_pack():
    """A block packed via pack_host_range is byte-identical to the same
    rows of a whole-matrix pack — O(block) ingest, same bitstream."""
    rng = np.random.default_rng(5)
    for bits, B in ((4, 16), (5, 21), (6, 33)):
        codes = rng.integers(0, B, (256, 6)).astype(np.uint8)
        whole = packing.pack_host(codes, bits)
        group, gbytes = packing.GROUP_ROWS[bits], packing.GROUP_BYTES[bits]
        r0, r1 = 4 * group, 12 * group
        blk = packing.pack_host_range(codes, bits, r0, r1)
        np.testing.assert_array_equal(
            blk, whole[r0 // group * gbytes:r1 // group * gbytes])
    with pytest.raises(ValueError):
        packing.pack_host_range(codes, 5, 3, 19)   # off the pack group


# -- BlockStore: LRU residency + eviction order ------------------------------

def _mk_store(n_blocks=4, rows=64, F=4):
    rng = np.random.default_rng(0)
    blocks = [rng.integers(0, 16, (rows, F)).astype(np.uint8)
              for _ in range(n_blocks)]
    nb = blocks[0].nbytes
    return bslib.BlockStore(blocks, rows, 0, budget_bytes=2 * nb,
                            register=False), nb


def test_block_store_cap_eviction_is_lru_ordered(_ooc_env):
    """Walking blocks under a 2-block budget evicts LRU-first; every
    eviction is a timeline `memory` event naming the block."""
    st, nb = _mk_store()
    cur = Timeline.cursor()
    for b in range(4):
        st.get(b)
    assert st.resident_bytes() == 2 * nb
    evs = [e for e in Timeline.snapshot(since=cur, n=1000)
           if e["kind"] == "memory" and e["owner"].startswith(st.owner)]
    assert [e["owner"] for e in evs] == [f"{st.owner}:block0",
                                         f"{st.owner}:block1"]
    assert all(e["trigger"] == "cap" and e["bytes"] == nb for e in evs)
    assert st.counters["uploaded"] == 4 and st.counters["evicted"] == 2
    st.get(2)                       # LRU hit — no upload
    assert st.counters["reused"] == 1


def test_block_store_pressure_shed_order_and_double_buffer(_ooc_env):
    """Past the ledger's eviction threshold a get() sheds everything but
    the double buffer (b, b+1) BEFORE growing the resident set — LRU
    order, trigger='pressure', pinned via timeline events."""
    st, nb = _mk_store()
    st.get(2)
    st.get(3)
    os.environ["H2O3_MEM_BUDGET_MB"] = "1"
    os.environ["H2O3_MEM_EVICT_PRESSURE"] = "0.5"
    ml.refresh(force=True)
    cur = Timeline.cursor()
    try:
        st.get(0)
    finally:
        os.environ.pop("H2O3_MEM_BUDGET_MB", None)
        os.environ.pop("H2O3_MEM_EVICT_PRESSURE", None)
        ml.refresh(force=True)
    evs = [e for e in Timeline.snapshot(since=cur, n=1000)
           if e["kind"] == "memory" and e.get("trigger") == "pressure"
           and e["owner"].startswith(st.owner)]
    assert [e["owner"] for e in evs] == [f"{st.owner}:block2",
                                         f"{st.owner}:block3"]
    assert st.resident_bytes() == nb    # only block0 resident


def test_dataset_cache_sheds_device_blocks_first(cloud1, _ooc_env):
    """The dataset cache's pressure response drops device blocks before
    entries — a shed block keeps its host copy (cost: one re-upload)."""
    from h2o3_tpu.models import dataset_cache as dsc

    fr = _frame()   # kept alive: the cache entry is weakref'd to it
    est = _fit(dict(_STREAM_ENV), frame=fr, ntrees=2, max_depth=3)
    assert est.model._stream_stats["blocks_uploaded"] > 0
    entries = [e for e in dsc._ENTRIES.values() if e.blocks]
    assert entries, "streamed fit did not land a blocked cache layer"
    st = next(iter(entries[0].blocks.values()))
    assert st.resident_bytes() > 0
    os.environ["H2O3_MEM_BUDGET_MB"] = "1"
    os.environ["H2O3_MEM_EVICT_PRESSURE"] = "0.5"
    ml.refresh(force=True)
    cur = Timeline.cursor()
    try:
        with dsc._LOCK:
            dsc._evict_locked()
    finally:
        os.environ.pop("H2O3_MEM_BUDGET_MB", None)
        os.environ.pop("H2O3_MEM_EVICT_PRESSURE", None)
        ml.refresh(force=True)
    assert st.resident_bytes() == 0
    evs = [e for e in Timeline.snapshot(since=cur, n=1000)
           if e["kind"] == "memory" and e.get("trigger") == "pressure"
           and e["owner"].startswith(st.owner)]
    assert evs, "block shedding did not land in the timeline"
    dsc.clear()


# -- BlockStore: disk tier (round 19) ----------------------------------------

def _mk_spill_store(tmp_path, n_blocks=4, rows=64, F=4):
    """Store whose 4-block host set overflows a 2-block host budget, with
    spill files rooted in the test's tmp dir; returns pristine copies of
    the blocks for restore bit-compares."""
    os.environ["H2O3_SPILL_DIR"] = str(tmp_path)
    rng = np.random.default_rng(0)
    blocks = [rng.integers(0, 16, (rows, F)).astype(np.uint8)
              for _ in range(n_blocks)]
    ref = [b.copy() for b in blocks]
    nb = blocks[0].nbytes
    st = bslib.BlockStore(blocks, rows, 0, budget_bytes=2 * nb,
                          host_budget_bytes=2 * nb, register=False)
    return st, ref, nb


def test_block_store_disk_spill_lru_order_and_restore_bitexact(
        _ooc_env, tmp_path):
    """Overflowing the host budget spills LRU-first (timeline-pinned
    order), a restore is bit-identical, its spill file is KEPT, and the
    restore makes room FIRST so the host watermark never exceeds the
    budget — the evict-then-restore ordering lands in the timeline too."""
    cur = Timeline.cursor()
    st, ref, nb = _mk_spill_store(tmp_path)
    try:
        evs = [e for e in Timeline.snapshot(since=cur, n=1000)
               if e["kind"] == "memory" and e.get("space") == "disk"
               and e["owner"].startswith(st.owner)]
        assert [e["owner"] for e in evs] == [f"{st.owner}:block0",
                                             f"{st.owner}:block1"]
        assert all(e["detail"].startswith("spill ") and e["bytes"] == nb
                   and e["trigger"] == "host_cap" for e in evs)
        assert st.counters["spilled"] == 2
        assert st.host_bytes() == 2 * nb and st.disk_bytes() == 2 * nb
        assert sorted(os.listdir(st._spill_dir)) == ["block0.bin",
                                                     "block1.bin"]
        # construction necessarily sees all blocks resident (they are
        # passed in); the watermark contract starts at the fit's window
        st.peak_window_start()
        cur2 = Timeline.cursor()
        got = st.fetch_host(0)
        np.testing.assert_array_equal(got, ref[0])
        assert st.counters["restored"] == 1
        # spilled copies kept: the restored block's file is still there
        assert os.path.exists(st._spill_path(0))
        # evict-then-restore: the colder victim's spill event precedes
        # the restore event, so residency never exceeded the budget
        evs2 = [e for e in Timeline.snapshot(since=cur2, n=1000)
                if e["kind"] == "memory" and e.get("space") == "disk"
                and e["owner"].startswith(st.owner)]
        assert [e["detail"].split()[0] for e in evs2] == ["spill",
                                                          "restore"]
        assert evs2[0]["owner"] == f"{st.owner}:block2"
        assert evs2[1]["owner"] == f"{st.owner}:block0"
        assert st.host_peak_window_bytes() <= st.host_budget_bytes()
        # every spilled block restores bit-identically
        for b in range(4):
            np.testing.assert_array_equal(st.fetch_host(b), ref[b])
        assert st.host_peak_window_bytes() <= st.host_budget_bytes()
    finally:
        st.close()
    # close() removes the spill files and the per-store directory
    assert not os.path.exists(st._spill_dir)


def test_block_store_spill_read_fault_resumes_bitexact(_ooc_env, tmp_path):
    """An armed `persist.read` fault mid-restore resumes under the shared
    retry policy and the restored block is still bit-identical — the
    Range-resume machinery is the same one the ingest path uses."""
    from h2o3_tpu.runtime import faults

    st, ref, nb = _mk_spill_store(tmp_path)
    try:
        faults.arm("persist.read", error="io", count=1)
        try:
            got = st.fetch_host(1)
            fired = faults.snapshot()["points"][0]["fires"]
        finally:
            faults.reset()
        assert fired == 1, "the armed fault never fired"
        np.testing.assert_array_equal(got, ref[1])
        assert st.counters["restored"] == 1
    finally:
        st.close()


def test_spill_ledger_disk_space_and_leak_detection(_ooc_env, tmp_path):
    """Spill bytes surface as `h2o3_memory_bytes{space="disk"}` under the
    block_store kind; a store dropped WITHOUT close() leaves its dead
    `:spill` owner still reporting filesystem bytes — a leak — which
    clears when the files go away."""
    import gc

    from h2o3_tpu.runtime import metrics_registry as reg

    st, ref, nb = _mk_spill_store(tmp_path)
    owner = st.owner
    sd = st._spill_dir
    snap = ml.refresh(force=True)
    bk = snap["by_kind"].get("block_store")
    assert bk is not None and bk["disk_bytes"] >= 2 * nb
    assert snap["totals"]["disk_bytes"] >= 2 * nb
    text = reg.prometheus_text()
    assert 'h2o3_memory_bytes{owner_kind="block_store",space="disk"}' \
        in text
    del st
    gc.collect()
    snap = ml.refresh(force=True)
    leaks = [l for l in snap["leaks"] if l["owner"] == f"{owner}:spill"]
    assert leaks and leaks[0]["reason"] == "referent_dead"
    assert leaks[0]["bytes"] >= 2 * nb
    for f in os.listdir(sd):
        os.remove(os.path.join(sd, f))
    os.rmdir(sd)
    snap = ml.refresh(force=True)
    assert not any(l["owner"] == f"{owner}:spill" for l in snap["leaks"])


# -- the bit-exactness matrix ------------------------------------------------

def test_streamed_gbm_early_stop_bitexact_vs_incore(cloud1, _ooc_env):
    """GBM + firing early stop: streamed forest, varimp, scoring history,
    tree count and predictions == the in-core fit sharing S."""
    params = dict(ntrees=10, max_depth=3, learn_rate=0.3,
                  score_tree_interval=2, stopping_rounds=2,
                  stopping_tolerance=0.5)
    a = _fit(dict(_STREAM_ENV), **params)
    assert a.model._stream_stats["streamed_bytes"] > 0
    assert a.model.ntrees_built < 10, "early stop never fired"
    b = _fit(dict(_INCORE_ENV), **params)
    assert not hasattr(b.model, "_stream_stats")
    _assert_bitexact(a, b)
    ha = [e.get("logloss") for e in a.model.scoring_history]
    hb = [e.get("logloss") for e in b.model.scoring_history]
    assert ha == hb
    fr = _frame()
    np.testing.assert_array_equal(
        np.asarray(a.model.predict(fr).vec("1").data),
        np.asarray(b.model.predict(fr).vec("1").data))


def test_streamed_drf_bitexact_vs_incore(cloud1, _ooc_env):
    """DRF (row sampling + mtries + OOB) streams bit-identically."""
    params = dict(ntrees=5, max_depth=3, sample_rate=0.7, mtries=3)
    a = _fit(dict(_STREAM_ENV), mode="drf", **params)
    assert a.model._stream_stats["blocks"] == 4
    b = _fit(dict(_INCORE_ENV), mode="drf", **params)
    _assert_bitexact(a, b)


def test_streamed_host_kernel_lane_bitexact(cloud1, _ooc_env):
    """The host-histogram lane (np.add.at via the ONE dedicated worker,
    never pure_callback) is bit-exact with the in-core host lane."""
    env_a = dict(_STREAM_ENV, H2O3_HOST_HIST_MIN_ROWS="1")
    env_b = dict(_INCORE_ENV, H2O3_HOST_HIST_MIN_ROWS="1")
    params = dict(ntrees=4, max_depth=3, learn_rate=0.2)
    _assert_bitexact(_fit(env_a, **params), _fit(env_b, **params))


def test_streamed_cv_fold_reuse_bitexact(cloud1, _ooc_env):
    """CV fold reuse composes with streaming: fold models slice the same
    quantization grid and the cross-validated parent is bit-identical."""
    params = dict(ntrees=4, max_depth=3, nfolds=2)
    a = _fit(dict(_STREAM_ENV), **params)
    b = _fit(dict(_INCORE_ENV), **params)
    _assert_bitexact(a, b)
    ma, mb = a.model.cross_validation_metrics, b.model.cross_validation_metrics
    assert ma is not None and mb is not None
    np.testing.assert_array_equal(ma.logloss(), mb.logloss())
    np.testing.assert_array_equal(ma.auc(), mb.auc())


def test_ooc_escape_hatch_is_plain_fit(cloud1, _ooc_env):
    """H2O3_TREE_OOC=0 under a tiny budget == a plain fit, bit-identical
    (the acceptance-criteria escape hatch)."""
    params = dict(ntrees=4, max_depth=3)
    a = _fit({"H2O3_TREE_OOC": "0", "H2O3_STREAM_BUDGET_MB": "0.001"},
             **params)
    b = _fit({}, **params)
    assert not hasattr(a.model, "_stream_stats")
    _assert_bitexact(a, b)


def test_ooc_auto_streams_only_when_oversubscribed(cloud1, _ooc_env):
    """auto (the default) consults the stream budget: a matrix over
    budget streams, one under it does not."""
    small = _fit({"H2O3_STREAM_BUDGET_MB": "0.002"}, ntrees=2, max_depth=3)
    assert small.model._stream_stats["blocks_uploaded"] > 0
    big = _fit({"H2O3_STREAM_BUDGET_MB": "100"}, ntrees=2, max_depth=3)
    assert not hasattr(big.model, "_stream_stats")


# -- disk tier: spilled fits (round 19) --------------------------------------

def _assert_spilled_under_budget(st):
    """The fit genuinely crossed the disk tier AND its host-resident
    watermark stayed under the effective host budget (configured value,
    floored at the 2-block disk double buffer)."""
    assert st["spilled_blocks"] > 0 and st["restored_blocks"] > 0
    per_block = st["spilled_bytes"] // max(st["spilled_blocks"], 1)
    budget = max(int(0.005 * 1e6), 2 * per_block)
    assert st["resident_host_peak"] <= budget, \
        f"host watermark {st['resident_host_peak']} over budget {budget}"


def test_spilled_gbm_early_stop_bitexact_vs_incore(cloud1, _ooc_env):
    """A fit overflowing BOTH the device and host budgets (blocks live on
    disk mid-fit) is bit-identical to the in-core fit sharing S — forest,
    varimp, scoring history, early-stop tree count."""
    params = dict(ntrees=10, max_depth=3, learn_rate=0.3,
                  score_tree_interval=2, stopping_rounds=2,
                  stopping_tolerance=0.5)
    a = _fit(dict(_SPILL_ENV), **params)
    st = a.model._stream_stats
    _assert_spilled_under_budget(st)
    assert st["disk_bytes"] > 0
    assert a.model.ntrees_built < 10, "early stop never fired"
    b = _fit(dict(_INCORE_ENV), **params)
    _assert_bitexact(a, b)
    ha = [e.get("logloss") for e in a.model.scoring_history]
    hb = [e.get("logloss") for e in b.model.scoring_history]
    assert ha == hb


def test_spilled_drf_bitexact_vs_incore(cloud1, _ooc_env):
    """DRF (row sampling + mtries + OOB) through the disk tier streams
    bit-identically."""
    params = dict(ntrees=5, max_depth=3, sample_rate=0.7, mtries=3)
    a = _fit(dict(_SPILL_ENV), mode="drf", **params)
    _assert_spilled_under_budget(a.model._stream_stats)
    _assert_bitexact(a, _fit(dict(_INCORE_ENV), mode="drf", **params))


def test_spilled_cv_fold_reuse_bitexact(cloud1, _ooc_env):
    """CV fold reuse composes with the disk tier: fold fits share the
    spilled block grid and the cross-validated parent stays
    bit-identical."""
    params = dict(ntrees=4, max_depth=3, nfolds=2)
    a = _fit(dict(_SPILL_ENV), **params)
    st = a.model._stream_stats
    assert st["restored_blocks"] > 0 and st["disk_bytes"] > 0
    b = _fit(dict(_INCORE_ENV), **params)
    _assert_bitexact(a, b)
    ma, mb = a.model.cross_validation_metrics, b.model.cross_validation_metrics
    assert ma is not None and mb is not None
    np.testing.assert_array_equal(ma.logloss(), mb.logloss())


def test_disk_tier_escape_hatch_streams_without_spilling(cloud1, _ooc_env):
    """H2O3_TREE_OOC_DISK=0 under a tiny host budget keeps the two-tier
    behaviour: the fit still streams, writes NOTHING to disk, and is
    bit-identical to the spilled fit (same S)."""
    params = dict(ntrees=4, max_depth=3)
    a = _fit(dict(_SPILL_ENV, H2O3_TREE_OOC_DISK="0"), **params)
    st = a.model._stream_stats
    assert st["blocks_uploaded"] > 0
    assert st["spilled_blocks"] == 0 and st["disk_bytes"] == 0
    b = _fit(dict(_SPILL_ENV), **params)
    assert b.model._stream_stats["spilled_blocks"] > 0
    _assert_bitexact(a, b)


def test_spilled_fit_survives_midstream_read_fault(cloud1, _ooc_env):
    """An armed `persist.read` fault mid-fit (a torn spill read) resumes
    under the retry policy and the fit is STILL bit-identical — fault
    recovery never changes bits."""
    from h2o3_tpu.runtime import faults

    params = dict(ntrees=3, max_depth=3)
    b = _fit(dict(_SPILL_ENV), **params)
    faults.arm("persist.read", error="io", count=1)
    try:
        a = _fit(dict(_SPILL_ENV), **params)
        fired = faults.snapshot()["points"][0]["fires"]
    finally:
        faults.reset()
    assert fired == 1, "the armed fault never fired"
    assert a.model._stream_stats["restored_blocks"] > 0
    _assert_bitexact(a, b)


# -- observability -----------------------------------------------------------

def test_stream_stats_plan_phase_and_prometheus_surface(cloud1, _ooc_env):
    """The fit's stream trajectory is a read, not a rerun: model stats,
    the kernel plan's `stream` fold, the h2d_stream phase bucket and the
    Prometheus counters all carry it."""
    from h2o3_tpu.runtime import metrics_registry as reg
    from h2o3_tpu.runtime import phases

    est = _fit(dict(_STREAM_ENV), ntrees=3, max_depth=3)
    st = est.model._stream_stats
    assert st["blocks"] == 4 and st["blocks_uploaded"] >= 4
    assert st["streamed_bytes"] > 0 and st["resident_block_peak"] > 0
    assert st["bytes_per_tree"] > 0 and st["goss"] is False
    plans = [p for p in histogram.kernel_stats()["plans"] if "stream" in p]
    assert plans and plans[-1]["stream"]["streamed_bytes"] == \
        st["streamed_bytes"]
    snap = phases.snapshot()
    assert snap.get("bytes_h2d_stream", 0) > 0
    text = reg.prometheus_text()
    assert "h2o3_tree_stream_bytes" in text
    assert 'h2o3_tree_stream_blocks_total{event="uploaded"}' in text
    totals = bslib.process_totals()
    assert totals["streamed_bytes"] >= st["streamed_bytes"]
    assert totals["resident_block_peak"] >= st["resident_block_peak"]


# -- GOSS ---------------------------------------------------------------------

def test_goss_streams_fewer_bytes_and_is_deterministic(cloud1, _ooc_env):
    """Past goss_start_tree later trees stream a fraction of the blocks
    (the perf headline when oversubscribed); the same seed reproduces the
    identical forest."""
    # a budget of ~2 blocks forces genuine oversubscription (every level
    # pass re-streams evicted blocks) — the regime where sampling pays;
    # with the whole matrix resident GOSS's compact-sample uploads would
    # only ADD bytes
    env = dict(_STREAM_ENV, H2O3_STREAM_BUDGET_MB="0.004")
    params = dict(ntrees=6, max_depth=3, learn_rate=0.2)
    plain = _fit(env, **params)
    assert plain.model._stream_stats["blocks_evicted"] > 0
    g1 = _fit(env, goss=True, goss_start_tree=2, **params)
    g2 = _fit(env, goss=True, goss_start_tree=2, **params)
    assert g1.model._stream_stats["goss"] is True
    assert (g1.model._stream_stats["streamed_bytes"]
            < plain.model._stream_stats["streamed_bytes"])
    _assert_bitexact(g1, g2)


def test_goss_validation_and_ineligible_fallback(cloud1, _ooc_env):
    """Invalid GOSS configs fail fast; an ineligible fit (DRF / custom
    sample_rate / bad rates) never silently samples."""
    with pytest.raises(ValueError, match="goss rates"):
        _fit(dict(_STREAM_ENV), ntrees=2, max_depth=2, goss=True,
             goss_top_rate=0.9, goss_other_rate=0.3)
    with pytest.raises(ValueError, match="goss_start_tree"):
        _fit(dict(_STREAM_ENV), ntrees=2, max_depth=2, goss=True,
             goss_start_tree=0)
    with pytest.raises(ValueError, match="sample_rate"):
        _fit(dict(_STREAM_ENV), ntrees=2, max_depth=2, goss=True,
             sample_rate=0.5)
    # an explicit 0.0 rate reaches the validator (not swapped for the
    # default by an `or` coercion)
    with pytest.raises(ValueError, match="goss rates"):
        _fit(dict(_STREAM_ENV), ntrees=2, max_depth=2, goss=True,
             goss_top_rate=0.0)


def test_goss_validation_fires_on_mesh_fits_too(cloud8, _ooc_env):
    """A bad goss config fails identically on a mesh-sharded fit — the
    shard gate must not silently drop the request."""
    with pytest.raises(ValueError, match="sample_rate"):
        _fit({"H2O3_TREE_OOC": "1"}, ntrees=2, max_depth=2, goss=True,
             sample_rate=0.5)


def test_goss_tied_gradients_sample_exactly(cloud1, _ooc_env):
    """Sign-shaped gradients (quantile loss: every row ties on |g|) still
    select EXACTLY the configured fraction — a >=threshold mask would
    mark every row `top` and the cap trim would keep an index-biased
    subset."""
    rng = np.random.default_rng(13)
    X = rng.normal(size=(1200, 6))
    y = X[:, 0] * 2 + rng.normal(scale=0.2, size=1200)
    names = [f"f{i}" for i in range(6)] + ["label"]
    est = _fit(dict(_STREAM_ENV), X=X, y=y, names=names, factor=False,
               distribution="quantile", ntrees=4, max_depth=3,
               goss=True, goss_start_tree=1)
    st = est.model._stream_stats
    assert st["goss"] is True and st["streamed_bytes"] > 0


def test_predict_codes_packed_matches_dense(cloud1):
    """The packed-word forest traversal (GOSS margin update) matches the
    dense predict_codes on every pack width."""
    rng = np.random.default_rng(9)
    N, F, D = 512, 5, 3
    T = treelib.heap_size(D)
    tree = treelib.Tree(
        feat=jnp.asarray(rng.integers(0, F, T).astype(np.int32)),
        bin=jnp.asarray(rng.integers(0, 14, T).astype(np.int32)),
        thr=jnp.zeros(T, jnp.float32),
        is_split=jnp.asarray(rng.random(T) < 0.8),
        value=jnp.asarray(rng.normal(size=T).astype(np.float32)))
    for bits, B in ((4, 16), (5, 21), (6, 33)):
        codes = rng.integers(0, B, (N, F)).astype(np.uint8)
        dense = treelib.predict_codes(tree, jnp.asarray(codes), D)
        packed = treelib.predict_codes_packed(
            tree, jnp.asarray(packing.pack_host(codes, bits)), bits, D)
        np.testing.assert_array_equal(np.asarray(dense), np.asarray(packed))


# -- slow lane ---------------------------------------------------------------

@pytest.mark.slow
def test_oversubscribed_whole_fit_stays_under_budget(cloud1, _ooc_env):
    """The acceptance pin: a packed matrix ≥10× the stream budget trains
    end-to-end with the device-resident block watermark under budget, and
    the ledger never sees the whole matrix resident."""
    X, y = make_classification(n=20_000, f=12, seed=11)
    names = [f"f{i}" for i in range(12)] + ["label"]
    est = _fit({"H2O3_TREE_OOC": "1", "H2O3_STREAM_BUDGET_MB": "0.015"},
               X=X, y=y, names=names, ntrees=8, max_depth=5,
               learn_rate=0.2, score_tree_interval=4)
    st = est.model._stream_stats
    budget = int(0.015 * 1e6)
    host_total = st["streamed_bytes"] / max(st["blocks_uploaded"], 1) \
        * st["blocks"]
    assert host_total >= 10 * budget, \
        f"matrix {host_total}B is not >=10x the {budget}B budget"
    assert st["resident_block_peak"] <= budget
    assert st["blocks_evicted"] > 0
    assert float(est.auc()) > 0.75
    # streamed vs in-core bit-exactness at this scale: the in-core
    # comparator only picks the host np.add.at kernel when a spare core
    # can service the callback (`host_callback_safe` — the 1-core
    # in-graph-callback deadlock this test used to dodge with a raised
    # MIN_ROWS is now gated out at method selection), and host and
    # segment are pinned bit-equal, so the pair compares on any host
    params = dict(ntrees=3, max_depth=4)
    env_a = {"H2O3_TREE_OOC": "1", "H2O3_STREAM_BUDGET_MB": "0.015"}
    env_b = dict(_INCORE_ENV, H2O3_TREE_SHARD_BLOCKS=str(st["blocks"]))
    a = _fit(env_a, X=X, y=y, names=names, **params)
    b = _fit(env_b, X=X, y=y, names=names, **params)
    _assert_bitexact(a, b)


@pytest.mark.slow
def test_mesh_sharded_fit_streams_when_oversubscribed(cloud8, _ooc_env):
    """Round 19 closes PR 11's gap: a mesh-sharded fit under a tiny
    budget is OOC-ELIGIBLE now — it converts to single-device streaming
    over a block grid matching the mesh shard count (S=8), so the
    streamed forest is bit-identical to the plain mesh fit."""
    params = dict(ntrees=3, max_depth=3)
    a = _fit({"H2O3_TREE_OOC": "1", "H2O3_STREAM_BUDGET_MB": "0.001",
              "H2O3_STREAM_BLOCKS": "8"}, **params)
    st = getattr(a.model, "_stream_stats", None)
    assert st is not None, "oversubscribed mesh fit did not stream"
    assert st["blocks"] == 8 and st["blocks_uploaded"] > 0
    b = _fit({}, **params)
    _assert_bitexact(a, b)
