"""Out-of-core streamed GBM/DRF (ISSUE 14) — block streaming under the
memory ledger's budget, bit-exactness vs the in-core fit, and GOSS.

Pins: (1) a streamed fit (sampling OFF) is BIT-IDENTICAL to the in-core
fit sharing its block count S — forest, varimp, scoring history,
early-stop tree count, CV metrics, predictions — across GBM/DRF ×
early-stop × CV fold reuse × host-kernel lane; (2) the `H2O3_TREE_OOC=0`
escape hatch is pinned bit-equal to a plain fit; (3) BlockStore device
eviction ORDER lands in the timeline (cap = LRU, pressure = shed keeps
only the double buffer), mirroring test_memory_ledger's LRU pin; (4) the
stream is observable — per-fit `_stream_stats`, the plan's `stream` fold,
the `h2d_stream` phase bucket and the Prometheus counters; (5) GOSS is
deterministic per seed, streams FEWER bytes than the unsampled fit, and
rejects invalid configs. The oversubscribed whole-fit (matrix ≥10× the
budget, resident watermark under budget) and the mesh-ineligibility pin
run as ``slow`` (tier-1 budget is tight)."""

import os

import numpy as np
import pytest

import jax.numpy as jnp

from h2o3_tpu.models import block_store as bslib
from h2o3_tpu.models import tree as treelib
from h2o3_tpu.ops import histogram, packing
from h2o3_tpu.runtime import memory_ledger as ml
from h2o3_tpu.runtime.timeline import Timeline

from conftest import make_classification

_ENV_KEYS = ("H2O3_TREE_OOC", "H2O3_STREAM_BLOCKS", "H2O3_STREAM_BUDGET_MB",
             "H2O3_TREE_SHARD", "H2O3_TREE_SHARD_BLOCKS", "H2O3_TREE_LEGACY",
             "H2O3_HIST_METHOD", "H2O3_HOST_HIST_MIN_ROWS",
             "H2O3_MEM_BUDGET_MB", "H2O3_MEM_EVICT_PRESSURE")

# the streamed fit and its in-core comparator share S=4 — the reduction
# tree is a function of S alone (PR 9), which is what makes the pair
# bit-comparable
_STREAM_ENV = {"H2O3_TREE_OOC": "1", "H2O3_STREAM_BLOCKS": "4",
               "H2O3_STREAM_BUDGET_MB": "0.02"}
_INCORE_ENV = {"H2O3_TREE_OOC": "0", "H2O3_TREE_SHARD": "1",
               "H2O3_TREE_SHARD_BLOCKS": "4"}

_X, _Y = make_classification(n=1500, f=8, seed=3)
_NAMES = [f"f{i}" for i in range(8)] + ["label"]


@pytest.fixture()
def _ooc_env():
    prior = {k: os.environ.pop(k, None) for k in _ENV_KEYS}
    yield
    for k, v in prior.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    ml.refresh(force=True)


def _frame(X=_X, y=_Y, names=_NAMES, factor=True):
    from h2o3_tpu.frame.frame import Frame

    fr = Frame.from_numpy(np.column_stack([X, y]), names=names)
    return fr.asfactor("label") if factor else fr


def _fit(env, mode="gbm", X=_X, y=_Y, names=_NAMES, frame=None,
         factor=True, **params):
    from h2o3_tpu.models import dataset_cache
    from h2o3_tpu.models.drf import H2ORandomForestEstimator
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

    dataset_cache.clear()
    for k in _ENV_KEYS:
        os.environ.pop(k, None)
    os.environ.update(env)
    try:
        cls = (H2OGradientBoostingEstimator if mode == "gbm"
               else H2ORandomForestEstimator)
        est = cls(seed=42, **params)
        est.train(y="label",
                  training_frame=frame if frame is not None
                  else _frame(X, y, names, factor))
    finally:
        for k in _ENV_KEYS:
            os.environ.pop(k, None)
    return est


def _assert_bitexact(a, b):
    assert a.model.ntrees_built == b.model.ntrees_built
    for k in range(len(a.model.forest)):
        for f in treelib.Tree._fields:
            assert np.array_equal(
                np.asarray(getattr(a.model.forest[k], f)),
                np.asarray(getattr(b.model.forest[k], f))), (k, f)
    va = getattr(a.model, "varimp_table", None)
    vb = getattr(b.model, "varimp_table", None)
    if va is not None or vb is not None:
        assert [r[0] for r in va] == [r[0] for r in vb]
        np.testing.assert_array_equal([r[1] for r in va],
                                      [r[1] for r in vb])


# -- ops: the block-wise pack API -------------------------------------------

def test_pack_host_range_matches_whole_matrix_pack():
    """A block packed via pack_host_range is byte-identical to the same
    rows of a whole-matrix pack — O(block) ingest, same bitstream."""
    rng = np.random.default_rng(5)
    for bits, B in ((4, 16), (5, 21), (6, 33)):
        codes = rng.integers(0, B, (256, 6)).astype(np.uint8)
        whole = packing.pack_host(codes, bits)
        group, gbytes = packing.GROUP_ROWS[bits], packing.GROUP_BYTES[bits]
        r0, r1 = 4 * group, 12 * group
        blk = packing.pack_host_range(codes, bits, r0, r1)
        np.testing.assert_array_equal(
            blk, whole[r0 // group * gbytes:r1 // group * gbytes])
    with pytest.raises(ValueError):
        packing.pack_host_range(codes, 5, 3, 19)   # off the pack group


# -- BlockStore: LRU residency + eviction order ------------------------------

def _mk_store(n_blocks=4, rows=64, F=4):
    rng = np.random.default_rng(0)
    blocks = [rng.integers(0, 16, (rows, F)).astype(np.uint8)
              for _ in range(n_blocks)]
    nb = blocks[0].nbytes
    return bslib.BlockStore(blocks, rows, 0, budget_bytes=2 * nb,
                            register=False), nb


def test_block_store_cap_eviction_is_lru_ordered(_ooc_env):
    """Walking blocks under a 2-block budget evicts LRU-first; every
    eviction is a timeline `memory` event naming the block."""
    st, nb = _mk_store()
    cur = Timeline.cursor()
    for b in range(4):
        st.get(b)
    assert st.resident_bytes() == 2 * nb
    evs = [e for e in Timeline.snapshot(since=cur, n=1000)
           if e["kind"] == "memory" and e["owner"].startswith(st.owner)]
    assert [e["owner"] for e in evs] == [f"{st.owner}:block0",
                                         f"{st.owner}:block1"]
    assert all(e["trigger"] == "cap" and e["bytes"] == nb for e in evs)
    assert st.counters["uploaded"] == 4 and st.counters["evicted"] == 2
    st.get(2)                       # LRU hit — no upload
    assert st.counters["reused"] == 1


def test_block_store_pressure_shed_order_and_double_buffer(_ooc_env):
    """Past the ledger's eviction threshold a get() sheds everything but
    the double buffer (b, b+1) BEFORE growing the resident set — LRU
    order, trigger='pressure', pinned via timeline events."""
    st, nb = _mk_store()
    st.get(2)
    st.get(3)
    os.environ["H2O3_MEM_BUDGET_MB"] = "1"
    os.environ["H2O3_MEM_EVICT_PRESSURE"] = "0.5"
    ml.refresh(force=True)
    cur = Timeline.cursor()
    try:
        st.get(0)
    finally:
        os.environ.pop("H2O3_MEM_BUDGET_MB", None)
        os.environ.pop("H2O3_MEM_EVICT_PRESSURE", None)
        ml.refresh(force=True)
    evs = [e for e in Timeline.snapshot(since=cur, n=1000)
           if e["kind"] == "memory" and e.get("trigger") == "pressure"
           and e["owner"].startswith(st.owner)]
    assert [e["owner"] for e in evs] == [f"{st.owner}:block2",
                                         f"{st.owner}:block3"]
    assert st.resident_bytes() == nb    # only block0 resident


def test_dataset_cache_sheds_device_blocks_first(cloud1, _ooc_env):
    """The dataset cache's pressure response drops device blocks before
    entries — a shed block keeps its host copy (cost: one re-upload)."""
    from h2o3_tpu.models import dataset_cache as dsc

    fr = _frame()   # kept alive: the cache entry is weakref'd to it
    est = _fit(dict(_STREAM_ENV), frame=fr, ntrees=2, max_depth=3)
    assert est.model._stream_stats["blocks_uploaded"] > 0
    entries = [e for e in dsc._ENTRIES.values() if e.blocks]
    assert entries, "streamed fit did not land a blocked cache layer"
    st = next(iter(entries[0].blocks.values()))
    assert st.resident_bytes() > 0
    os.environ["H2O3_MEM_BUDGET_MB"] = "1"
    os.environ["H2O3_MEM_EVICT_PRESSURE"] = "0.5"
    ml.refresh(force=True)
    cur = Timeline.cursor()
    try:
        with dsc._LOCK:
            dsc._evict_locked()
    finally:
        os.environ.pop("H2O3_MEM_BUDGET_MB", None)
        os.environ.pop("H2O3_MEM_EVICT_PRESSURE", None)
        ml.refresh(force=True)
    assert st.resident_bytes() == 0
    evs = [e for e in Timeline.snapshot(since=cur, n=1000)
           if e["kind"] == "memory" and e.get("trigger") == "pressure"
           and e["owner"].startswith(st.owner)]
    assert evs, "block shedding did not land in the timeline"
    dsc.clear()


# -- the bit-exactness matrix ------------------------------------------------

def test_streamed_gbm_early_stop_bitexact_vs_incore(cloud1, _ooc_env):
    """GBM + firing early stop: streamed forest, varimp, scoring history,
    tree count and predictions == the in-core fit sharing S."""
    params = dict(ntrees=10, max_depth=3, learn_rate=0.3,
                  score_tree_interval=2, stopping_rounds=2,
                  stopping_tolerance=0.5)
    a = _fit(dict(_STREAM_ENV), **params)
    assert a.model._stream_stats["streamed_bytes"] > 0
    assert a.model.ntrees_built < 10, "early stop never fired"
    b = _fit(dict(_INCORE_ENV), **params)
    assert not hasattr(b.model, "_stream_stats")
    _assert_bitexact(a, b)
    ha = [e.get("logloss") for e in a.model.scoring_history]
    hb = [e.get("logloss") for e in b.model.scoring_history]
    assert ha == hb
    fr = _frame()
    np.testing.assert_array_equal(
        np.asarray(a.model.predict(fr).vec("1").data),
        np.asarray(b.model.predict(fr).vec("1").data))


def test_streamed_drf_bitexact_vs_incore(cloud1, _ooc_env):
    """DRF (row sampling + mtries + OOB) streams bit-identically."""
    params = dict(ntrees=5, max_depth=3, sample_rate=0.7, mtries=3)
    a = _fit(dict(_STREAM_ENV), mode="drf", **params)
    assert a.model._stream_stats["blocks"] == 4
    b = _fit(dict(_INCORE_ENV), mode="drf", **params)
    _assert_bitexact(a, b)


def test_streamed_host_kernel_lane_bitexact(cloud1, _ooc_env):
    """The host-histogram lane (np.add.at via the ONE dedicated worker,
    never pure_callback) is bit-exact with the in-core host lane."""
    env_a = dict(_STREAM_ENV, H2O3_HOST_HIST_MIN_ROWS="1")
    env_b = dict(_INCORE_ENV, H2O3_HOST_HIST_MIN_ROWS="1")
    params = dict(ntrees=4, max_depth=3, learn_rate=0.2)
    _assert_bitexact(_fit(env_a, **params), _fit(env_b, **params))


def test_streamed_cv_fold_reuse_bitexact(cloud1, _ooc_env):
    """CV fold reuse composes with streaming: fold models slice the same
    quantization grid and the cross-validated parent is bit-identical."""
    params = dict(ntrees=4, max_depth=3, nfolds=2)
    a = _fit(dict(_STREAM_ENV), **params)
    b = _fit(dict(_INCORE_ENV), **params)
    _assert_bitexact(a, b)
    ma, mb = a.model.cross_validation_metrics, b.model.cross_validation_metrics
    assert ma is not None and mb is not None
    np.testing.assert_array_equal(ma.logloss(), mb.logloss())
    np.testing.assert_array_equal(ma.auc(), mb.auc())


def test_ooc_escape_hatch_is_plain_fit(cloud1, _ooc_env):
    """H2O3_TREE_OOC=0 under a tiny budget == a plain fit, bit-identical
    (the acceptance-criteria escape hatch)."""
    params = dict(ntrees=4, max_depth=3)
    a = _fit({"H2O3_TREE_OOC": "0", "H2O3_STREAM_BUDGET_MB": "0.001"},
             **params)
    b = _fit({}, **params)
    assert not hasattr(a.model, "_stream_stats")
    _assert_bitexact(a, b)


def test_ooc_auto_streams_only_when_oversubscribed(cloud1, _ooc_env):
    """auto (the default) consults the stream budget: a matrix over
    budget streams, one under it does not."""
    small = _fit({"H2O3_STREAM_BUDGET_MB": "0.002"}, ntrees=2, max_depth=3)
    assert small.model._stream_stats["blocks_uploaded"] > 0
    big = _fit({"H2O3_STREAM_BUDGET_MB": "100"}, ntrees=2, max_depth=3)
    assert not hasattr(big.model, "_stream_stats")


# -- observability -----------------------------------------------------------

def test_stream_stats_plan_phase_and_prometheus_surface(cloud1, _ooc_env):
    """The fit's stream trajectory is a read, not a rerun: model stats,
    the kernel plan's `stream` fold, the h2d_stream phase bucket and the
    Prometheus counters all carry it."""
    from h2o3_tpu.runtime import metrics_registry as reg
    from h2o3_tpu.runtime import phases

    est = _fit(dict(_STREAM_ENV), ntrees=3, max_depth=3)
    st = est.model._stream_stats
    assert st["blocks"] == 4 and st["blocks_uploaded"] >= 4
    assert st["streamed_bytes"] > 0 and st["resident_block_peak"] > 0
    assert st["bytes_per_tree"] > 0 and st["goss"] is False
    plans = [p for p in histogram.kernel_stats()["plans"] if "stream" in p]
    assert plans and plans[-1]["stream"]["streamed_bytes"] == \
        st["streamed_bytes"]
    snap = phases.snapshot()
    assert snap.get("bytes_h2d_stream", 0) > 0
    text = reg.prometheus_text()
    assert "h2o3_tree_stream_bytes" in text
    assert 'h2o3_tree_stream_blocks_total{event="uploaded"}' in text
    totals = bslib.process_totals()
    assert totals["streamed_bytes"] >= st["streamed_bytes"]
    assert totals["resident_block_peak"] >= st["resident_block_peak"]


# -- GOSS ---------------------------------------------------------------------

def test_goss_streams_fewer_bytes_and_is_deterministic(cloud1, _ooc_env):
    """Past goss_start_tree later trees stream a fraction of the blocks
    (the perf headline when oversubscribed); the same seed reproduces the
    identical forest."""
    # a budget of ~2 blocks forces genuine oversubscription (every level
    # pass re-streams evicted blocks) — the regime where sampling pays;
    # with the whole matrix resident GOSS's compact-sample uploads would
    # only ADD bytes
    env = dict(_STREAM_ENV, H2O3_STREAM_BUDGET_MB="0.004")
    params = dict(ntrees=6, max_depth=3, learn_rate=0.2)
    plain = _fit(env, **params)
    assert plain.model._stream_stats["blocks_evicted"] > 0
    g1 = _fit(env, goss=True, goss_start_tree=2, **params)
    g2 = _fit(env, goss=True, goss_start_tree=2, **params)
    assert g1.model._stream_stats["goss"] is True
    assert (g1.model._stream_stats["streamed_bytes"]
            < plain.model._stream_stats["streamed_bytes"])
    _assert_bitexact(g1, g2)


def test_goss_validation_and_ineligible_fallback(cloud1, _ooc_env):
    """Invalid GOSS configs fail fast; an ineligible fit (DRF / custom
    sample_rate / bad rates) never silently samples."""
    with pytest.raises(ValueError, match="goss rates"):
        _fit(dict(_STREAM_ENV), ntrees=2, max_depth=2, goss=True,
             goss_top_rate=0.9, goss_other_rate=0.3)
    with pytest.raises(ValueError, match="goss_start_tree"):
        _fit(dict(_STREAM_ENV), ntrees=2, max_depth=2, goss=True,
             goss_start_tree=0)
    with pytest.raises(ValueError, match="sample_rate"):
        _fit(dict(_STREAM_ENV), ntrees=2, max_depth=2, goss=True,
             sample_rate=0.5)
    # an explicit 0.0 rate reaches the validator (not swapped for the
    # default by an `or` coercion)
    with pytest.raises(ValueError, match="goss rates"):
        _fit(dict(_STREAM_ENV), ntrees=2, max_depth=2, goss=True,
             goss_top_rate=0.0)


def test_goss_validation_fires_on_mesh_fits_too(cloud8, _ooc_env):
    """A bad goss config fails identically on a mesh-sharded fit — the
    shard gate must not silently drop the request."""
    with pytest.raises(ValueError, match="sample_rate"):
        _fit({"H2O3_TREE_OOC": "1"}, ntrees=2, max_depth=2, goss=True,
             sample_rate=0.5)


def test_goss_tied_gradients_sample_exactly(cloud1, _ooc_env):
    """Sign-shaped gradients (quantile loss: every row ties on |g|) still
    select EXACTLY the configured fraction — a >=threshold mask would
    mark every row `top` and the cap trim would keep an index-biased
    subset."""
    rng = np.random.default_rng(13)
    X = rng.normal(size=(1200, 6))
    y = X[:, 0] * 2 + rng.normal(scale=0.2, size=1200)
    names = [f"f{i}" for i in range(6)] + ["label"]
    est = _fit(dict(_STREAM_ENV), X=X, y=y, names=names, factor=False,
               distribution="quantile", ntrees=4, max_depth=3,
               goss=True, goss_start_tree=1)
    st = est.model._stream_stats
    assert st["goss"] is True and st["streamed_bytes"] > 0


def test_predict_codes_packed_matches_dense(cloud1):
    """The packed-word forest traversal (GOSS margin update) matches the
    dense predict_codes on every pack width."""
    rng = np.random.default_rng(9)
    N, F, D = 512, 5, 3
    T = treelib.heap_size(D)
    tree = treelib.Tree(
        feat=jnp.asarray(rng.integers(0, F, T).astype(np.int32)),
        bin=jnp.asarray(rng.integers(0, 14, T).astype(np.int32)),
        thr=jnp.zeros(T, jnp.float32),
        is_split=jnp.asarray(rng.random(T) < 0.8),
        value=jnp.asarray(rng.normal(size=T).astype(np.float32)))
    for bits, B in ((4, 16), (5, 21), (6, 33)):
        codes = rng.integers(0, B, (N, F)).astype(np.uint8)
        dense = treelib.predict_codes(tree, jnp.asarray(codes), D)
        packed = treelib.predict_codes_packed(
            tree, jnp.asarray(packing.pack_host(codes, bits)), bits, D)
        np.testing.assert_array_equal(np.asarray(dense), np.asarray(packed))


# -- slow lane ---------------------------------------------------------------

@pytest.mark.slow
def test_oversubscribed_whole_fit_stays_under_budget(cloud1, _ooc_env):
    """The acceptance pin: a packed matrix ≥10× the stream budget trains
    end-to-end with the device-resident block watermark under budget, and
    the ledger never sees the whole matrix resident."""
    X, y = make_classification(n=20_000, f=12, seed=11)
    names = [f"f{i}" for i in range(12)] + ["label"]
    est = _fit({"H2O3_TREE_OOC": "1", "H2O3_STREAM_BUDGET_MB": "0.015"},
               X=X, y=y, names=names, ntrees=8, max_depth=5,
               learn_rate=0.2, score_tree_interval=4)
    st = est.model._stream_stats
    budget = int(0.015 * 1e6)
    host_total = st["streamed_bytes"] / max(st["blocks_uploaded"], 1) \
        * st["blocks"]
    assert host_total >= 10 * budget, \
        f"matrix {host_total}B is not >=10x the {budget}B budget"
    assert st["resident_block_peak"] <= budget
    assert st["blocks_evicted"] > 0
    assert float(est.auc()) > 0.75
    # streamed vs in-core bit-exactness at this scale rides the segment
    # kernel (H2O3_HOST_HIST_MIN_ROWS high keeps the in-core comparator
    # off the known pure_callback warm-thread hang — docs/perf.md)
    params = dict(ntrees=3, max_depth=4)
    env_a = {"H2O3_TREE_OOC": "1", "H2O3_STREAM_BUDGET_MB": "0.015",
             "H2O3_HOST_HIST_MIN_ROWS": "1000000"}
    env_b = dict(_INCORE_ENV, H2O3_HOST_HIST_MIN_ROWS="1000000",
                 H2O3_TREE_SHARD_BLOCKS=str(st["blocks"]))
    a = _fit(env_a, X=X, y=y, names=names, **params)
    b = _fit(env_b, X=X, y=y, names=names, **params)
    _assert_bitexact(a, b)


@pytest.mark.slow
def test_mesh_sharded_fit_is_ooc_ineligible(cloud8, _ooc_env):
    """A mesh-sharded fit ignores H2O3_TREE_OOC=1 (its rows already live
    across devices): no stream stats, bit-identical to the same mesh fit
    without the env — the '2-device shard' cell of the matrix."""
    params = dict(ntrees=3, max_depth=3)
    a = _fit({"H2O3_TREE_OOC": "1", "H2O3_STREAM_BUDGET_MB": "0.001"},
             **params)
    assert not hasattr(a.model, "_stream_stats")
    b = _fit({}, **params)
    _assert_bitexact(a, b)
