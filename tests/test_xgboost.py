"""XGBoost estimator surface: lossguide growth, parameter honesty, leaf caps.

Reference behaviors: `h2o-ext-xgboost/.../XGBoostModel.java` createParamsMap
(grow_policy / max_leaves / booster passthrough to the native booster);
xgboost's `hist` updater semantics.
"""

import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
from h2o3_tpu.models.xgboost import H2OXGBoostEstimator


def _frame(n=4000, f=8, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = ((X[:, 0] + X[:, 1] * X[:, 2] + 0.3 * rng.normal(size=n)) > 0)
    d = {f"f{i}": X[:, i] for i in range(f)}
    d["y"] = y.astype(int).astype(str)
    fr = h2o.H2OFrame_from_python(d, column_types={"y": "enum"})
    return fr, [f"f{i}" for i in range(f)]


def _leaf_counts(model):
    """Leaves per tree from the heap arrays: #splits + 1."""
    out = []
    for k_forest in model.forest:
        for t in range(k_forest.is_split.shape[0]):
            out.append(int(np.asarray(k_forest.is_split[t]).sum()) + 1)
    return out


def test_lossguide_leaf_cap_honored():
    fr, x = _frame()
    xgb = H2OXGBoostEstimator(ntrees=8, max_depth=6, seed=1,
                              grow_policy="lossguide", max_leaves=8)
    xgb.train(x=x, y="y", training_frame=fr)
    leaves = _leaf_counts(xgb.model)
    assert max(leaves) <= 8, leaves
    assert max(leaves) > 2, "trees did not grow at all"
    assert float(xgb.auc()) > 0.85


def test_lossguide_depth_cap_binds():
    fr, x = _frame()
    xgb = H2OXGBoostEstimator(ntrees=5, max_depth=2, seed=1,
                              grow_policy="lossguide", max_leaves=64)
    xgb.train(x=x, y="y", training_frame=fr)
    # depth 2 heap can hold at most 4 leaves regardless of the leaf budget
    assert max(_leaf_counts(xgb.model)) <= 4


def test_lossguide_matches_depthwise_when_unconstrained():
    # with a leaf budget >= 2^depth every positive-gain node splits in both
    # policies; split decisions are local, so the models score identically
    fr, x = _frame(n=2000)
    kw = dict(ntrees=4, max_depth=3, seed=7, min_rows=10)
    a = H2OXGBoostEstimator(**kw)
    a.train(x=x, y="y", training_frame=fr)
    b = H2OXGBoostEstimator(grow_policy="lossguide", max_leaves=8, **kw)
    b.train(x=x, y="y", training_frame=fr)
    pa = a.predict(fr).vec("1").numeric_np()
    pb = b.predict(fr).vec("1").numeric_np()
    np.testing.assert_allclose(pa, pb, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("params", [
    dict(rate_drop=0.1),                       # DART param without dart
    dict(one_drop=True),
    dict(skip_drop=0.5),
    dict(booster="dart", rate_drop=1.5),       # out of range
    dict(booster="gbforest"),                  # unknown booster
    dict(booster="dart", normalize_type="bogus"),
    dict(grow_policy="bogus"),
    dict(max_leaves=16),                       # needs lossguide
    dict(grow_policy="lossguide", max_depth=0),
    dict(grow_policy="lossguide", max_leaves=1),
])
def test_unimplemented_params_raise(params):
    fr, x = _frame(n=500)
    est = H2OXGBoostEstimator(ntrees=2, **params)
    with pytest.raises(ValueError):
        est.train(x=x, y="y", training_frame=fr)


# ---- DART booster (xgboost dart.cc; h2o-ext-xgboost passthrough) --------


def test_dart_skip_drop_one_equals_gbtree():
    """skip_drop=1.0 means dropout never fires — DART must be bit-equal to
    gbtree (all round scales stay 1)."""
    fr, x = _frame(n=2000)
    kw = dict(ntrees=6, max_depth=3, seed=5)
    a = H2OXGBoostEstimator(**kw)
    a.train(x=x, y="y", training_frame=fr)
    b = H2OXGBoostEstimator(booster="dart", rate_drop=0.5, skip_drop=1.0,
                            **kw)
    b.train(x=x, y="y", training_frame=fr)
    pa = a.predict(fr).vec("1").numeric_np()
    pb = b.predict(fr).vec("1").numeric_np()
    np.testing.assert_allclose(pb, pa, rtol=1e-6, atol=1e-7)


@pytest.mark.parametrize("normalize_type", ["tree", "forest"])
def test_dart_trains_and_scores_sane(normalize_type):
    fr, x = _frame(n=3000)
    est = H2OXGBoostEstimator(booster="dart", rate_drop=0.3, one_drop=True,
                              normalize_type=normalize_type,
                              ntrees=12, max_depth=3, seed=11)
    est.train(x=x, y="y", training_frame=fr)
    assert est.auc() > 0.8
    # margins maintained incrementally through drop/commit cycles must
    # agree with the final baked forest rescored from scratch (f32 drift
    # from per-round scale adjustments allows a few near-tie rank flips)
    auc_rescore = est.model_performance(fr).auc()
    assert abs(est.auc() - auc_rescore) < 1e-3
    # determinism: same seed, same dropout path, same model
    est2 = H2OXGBoostEstimator(booster="dart", rate_drop=0.3, one_drop=True,
                               normalize_type=normalize_type,
                               ntrees=12, max_depth=3, seed=11)
    est2.train(x=x, y="y", training_frame=fr)
    p1 = est.predict(fr).vec("1").numeric_np()
    p2 = est2.predict(fr).vec("1").numeric_np()
    np.testing.assert_array_equal(p1, p2)


def test_dart_normalization_math_exact():
    """rate_drop=1 with 2 trees: round 2 always drops round 1, so (with no
    row/col sampling) both trees learn the SAME f0-residual tree c. 'tree'
    normalization must yield margin = f0 + c/(1+lr) + c/(1+lr)."""
    fr, x = _frame(n=1500)
    lr = 0.3
    g = H2OXGBoostEstimator(ntrees=1, max_depth=3, seed=2, learn_rate=lr)
    g.train(x=x, y="y", training_frame=fr)
    d = H2OXGBoostEstimator(booster="dart", rate_drop=1.0, skip_drop=0.0,
                            ntrees=2, max_depth=3, seed=2, learn_rate=lr)
    d.train(x=x, y="y", training_frame=fr)
    Xm = g.model._matrix(fr)
    c = g.model._margins(Xm)[:, 0] - float(g.model.f0)   # lr-folded tree
    md = d.model._margins(Xm)[:, 0] - float(d.model.f0)
    np.testing.assert_allclose(md, 2.0 * c / (1.0 + lr), rtol=2e-5,
                               atol=2e-6)


def test_dart_with_validation_frame_consistent():
    """DART's validation margins go through drop/commit adjustments; the
    scoring-history valid metric must match a from-scratch rescore."""
    fr, x = _frame(n=3000)
    tr, va = fr.split_frame([0.7], seed=1)
    est = H2OXGBoostEstimator(booster="dart", rate_drop=0.4, one_drop=True,
                              ntrees=10, max_depth=3, seed=3,
                              score_tree_interval=5)
    est.train(x=x, y="y", training_frame=tr, validation_frame=va)
    va_auc_hist = est.model._m(valid=True).auc()
    va_auc_rescore = est.model_performance(va).auc()
    # same f32-drift allowance as the train-side test: AUC is rank-based,
    # so per-round adjustment rounding can flip a few near-ties
    assert abs(va_auc_hist - va_auc_rescore) < 1e-3


def test_max_abs_leafnode_pred_clamps_gbm():
    fr, x = _frame(n=2000)
    cap, lr = 0.02, 0.1
    gbm = H2OGradientBoostingEstimator(ntrees=5, max_depth=4, seed=3,
                                       learn_rate=lr,
                                       max_abs_leafnode_pred=cap)
    gbm.train(x=x, y="y", training_frame=fr)
    for k_forest in gbm.model.forest:
        vals = np.asarray(k_forest.value)
        assert np.abs(vals).max() <= cap * lr * (1 + 1e-5)


def test_max_delta_step_clamps_xgb():
    fr, x = _frame(n=2000)
    xgb = H2OXGBoostEstimator(ntrees=5, max_depth=4, seed=3, learn_rate=0.3,
                              max_delta_step=0.05)
    xgb.train(x=x, y="y", training_frame=fr)
    for k_forest in xgb.model.forest:
        vals = np.asarray(k_forest.value)
        assert np.abs(vals).max() <= 0.05 * 0.3 * (1 + 1e-5)


# ---- gblinear booster (updater_shotgun.cc CoordinateDelta; VERDICT r04 #5)


def test_gblinear_gaussian_matches_glm():
    """With no regularization a converged gblinear IS the least-squares
    GLM — coefficient-level parity on a linear problem."""
    from h2o3_tpu.models.glm import H2OGeneralizedLinearEstimator

    rng = np.random.default_rng(1)
    n = 3000
    X = rng.normal(size=(n, 4)).astype(np.float32)
    beta_true = np.asarray([2.0, -1.0, 0.5, 0.0])
    yv = X @ beta_true + 1.5 + 0.05 * rng.normal(size=n)
    d = {f"f{i}": X[:, i] for i in range(4)}
    d["y"] = yv
    fr = h2o.H2OFrame_from_python(d)
    x = [f"f{i}" for i in range(4)]

    xgb = H2OXGBoostEstimator(booster="gblinear", ntrees=300, learn_rate=0.5,
                              reg_lambda=0.0, reg_alpha=0.0, seed=1)
    xgb.train(x=x, y="y", training_frame=fr)
    glm = H2OGeneralizedLinearEstimator(family="gaussian", lambda_=0.0,
                                        standardize=False)
    glm.train(x=x, y="y", training_frame=fr)
    cx, cg = xgb.model.coef(), glm.model.coef()
    for k in cg:
        assert abs(cx[k] - cg[k]) < 2e-2, (k, cx[k], cg[k])
    # and both recover the generating coefficients
    assert abs(cx["f0"] - 2.0) < 0.05 and abs(cx["Intercept"] - 1.5) < 0.05


def test_gblinear_binomial_trains_and_scores():
    fr, x = _frame(n=3000)
    xgb = H2OXGBoostEstimator(booster="gblinear", ntrees=100, learn_rate=0.5,
                              reg_lambda=1.0, seed=1)
    xgb.train(x=x, y="y", training_frame=fr)
    assert float(xgb.auc()) > 0.80          # x0 + x1*x2: linear part learnable
    pred = xgb.predict(fr)
    assert pred.names == ["predict", "0", "1"]
    p1 = pred.vec("1").numeric_np()
    assert np.isfinite(p1).all() and 0 <= p1.min() and p1.max() <= 1


def test_gblinear_reg_alpha_sparsifies():
    """L1 soft-thresholding: noise features' weights are driven to
    (near-)zero while the signal survives — the CoordinateDelta clamp."""
    rng = np.random.default_rng(3)
    n = 4000
    X = rng.normal(size=(n, 6)).astype(np.float32)
    yv = 3.0 * X[:, 0] + 0.02 * rng.normal(size=n)
    d = {f"f{i}": X[:, i] for i in range(6)}
    d["y"] = yv
    fr = h2o.H2OFrame_from_python(d)
    x = [f"f{i}" for i in range(6)]
    xgb = H2OXGBoostEstimator(booster="gblinear", ntrees=200, learn_rate=0.5,
                              reg_lambda=0.0, reg_alpha=200.0, seed=1)
    xgb.train(x=x, y="y", training_frame=fr)
    c = xgb.model.coef()
    assert abs(c["f0"]) > 1.0               # signal survives
    for k in ("f1", "f2", "f3", "f4", "f5"):
        assert abs(c[k]) < 5e-3, (k, c[k])  # noise soft-thresholded away


def test_gblinear_multinomial():
    rng = np.random.default_rng(5)
    n = 3000
    X = rng.normal(size=(n, 4)).astype(np.float32)
    cls = (X[:, 0] > 0.5).astype(int) + (X[:, 1] > 0).astype(int)
    d = {f"f{i}": X[:, i] for i in range(4)}
    d["y"] = np.asarray(["a", "b", "c"], dtype=object)[cls]
    fr = h2o.H2OFrame_from_python(d, column_types={"y": "enum"})
    xgb = H2OXGBoostEstimator(booster="gblinear", ntrees=150, learn_rate=0.5,
                              reg_lambda=1.0, seed=1)
    xgb.train(x=[f"f{i}" for i in range(4)], y="y", training_frame=fr)
    pred = xgb.predict(fr)
    assert pred.names == ["predict", "a", "b", "c"]
    acc = (np.asarray(pred.vec("predict").data)
           == np.asarray(fr.vec("y").data)).mean()
    assert acc > 0.75, acc


def test_gblinear_rejects_dart_params():
    fr, x = _frame(n=300)
    est = H2OXGBoostEstimator(booster="gblinear", rate_drop=0.3, ntrees=2)
    with pytest.raises(ValueError):
        est.train(x=x, y="y", training_frame=fr)


def test_gblinear_cv_and_identity():
    """nfolds CV works on the linear booster, and the model carries the
    xgboost identity (id prefix + summary algo), not glm."""
    fr, x = _frame(n=1500)
    xgb = H2OXGBoostEstimator(booster="gblinear", ntrees=60, learn_rate=0.5,
                              nfolds=3, seed=1)
    xgb.train(x=x, y="y", training_frame=fr)
    assert xgb.model.model_id.startswith("xgboost")
    assert xgb.model.summary()["algo"] == "xgboost"
    assert float(xgb.auc()) > 0.8
    assert xgb.model.cross_validation_metrics is not None


def test_gblinear_rejects_rank_and_exotic_distributions():
    fr, x = _frame(n=300)
    with pytest.raises(ValueError):
        H2OXGBoostEstimator(booster="gblinear", objective="rank:ndcg",
                            group_column="qid", ntrees=2).train(
            x=x, y="y", training_frame=fr)
    with pytest.raises(ValueError):
        H2OXGBoostEstimator(booster="gblinear", distribution="poisson",
                            ntrees=2).train(x=x, y="y", training_frame=fr)
