"""Fused device-resident estimator engine (ISSUE 15) — legacy-vs-fused
parity, the standardized-matrix cache contract (one upload per sweep, zero
new traces on the second candidate), blocks==mesh bit-identity, the
k-means++ seeding determinism pin, and the observability surfaces."""

import os

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models import dataset_cache
from h2o3_tpu.models import estimator_engine as est
from h2o3_tpu.models.glm import H2OGeneralizedLinearEstimator
from h2o3_tpu.models.glrm import H2OGeneralizedLowRankEstimator
from h2o3_tpu.models.kmeans import H2OKMeansEstimator, _seed_centers
from h2o3_tpu.models.pca import H2OPrincipalComponentAnalysisEstimator


@pytest.fixture(autouse=True)
def _clean_cache():
    dataset_cache.clear()
    dataset_cache.reset_stats()
    yield
    dataset_cache.clear()
    os.environ.pop("H2O3_EST_LEGACY", None)
    os.environ.pop("H2O3_EST_SHARD", None)


def _legacy(on: bool):
    if on:
        os.environ["H2O3_EST_LEGACY"] = "1"
    else:
        os.environ.pop("H2O3_EST_LEGACY", None)


def _glm_frame(n=1500, f=5, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    beta = np.linspace(1.5, -1.5, f)
    eta = X @ beta
    return X, eta, rng


# -- GLM family x solver parity matrix ---------------------------------------

GLM_CASES = [
    ("gaussian", 0.1, 0.0),     # ridge
    ("gaussian", 0.05, 1.0),    # lasso
    ("binomial", 0.1, 0.0),
    ("binomial", 0.05, 1.0),
    ("poisson", 0.1, 0.0),
    ("poisson", 0.05, 1.0),
    ("tweedie", 0.1, 0.0),
    ("tweedie", 0.05, 1.0),
]


@pytest.mark.parametrize("family,lam,alpha", GLM_CASES)
def test_glm_fused_matches_legacy(cloud1, family, lam, alpha):
    """Fused whole-fit IRLS (f32 on-device solves) reproduces the host f64
    loop's de-standardized coefficients at tolerance, family × ridge/lasso
    (ISSUE 15 parity matrix)."""
    X, eta, rng = _glm_frame()
    if family == "binomial":
        y = (rng.random(len(eta)) < 1 / (1 + np.exp(-eta))).astype(float)
    elif family == "poisson":
        y = rng.poisson(np.exp(np.clip(eta / 3, -3, 3))).astype(float)
    elif family == "tweedie":
        y = np.abs(eta) + rng.random(len(eta))
    else:
        y = eta + 0.1 * rng.normal(size=len(eta))
    names = [f"x{i}" for i in range(X.shape[1])] + ["y"]
    fr = Frame.from_numpy(np.column_stack([X, y]), names=names)
    if family == "binomial":
        fr = fr.asfactor("y")

    def coefs(legacy):
        _legacy(legacy)
        g = H2OGeneralizedLinearEstimator(family=family, lambda_=lam,
                                          alpha=alpha, seed=7)
        g.train(y="y", training_frame=fr)
        return np.asarray(list(g.coef().values()), np.float64)

    fused = coefs(False)
    plan = est.est_stats()["plans"][-1]
    assert plan["algo"] == "glm" and plan["path"] == "fused"
    assert plan["iterations"] >= 1 and plan["converged"]
    legacy = coefs(True)
    scale = max(np.abs(legacy).max(), 1e-3)
    assert np.abs(fused - legacy).max() < 5e-3 * scale, (fused, legacy)


def test_glm_lambda_search_legacy_comparator(cloud1):
    """H2O3_EST_LEGACY=1 routes lambda_search through the host IRLS loop;
    both paths select comparable lambdas and coefficients."""
    X, eta, rng = _glm_frame(1200, 6)
    y = (rng.random(len(eta)) < 1 / (1 + np.exp(-eta))).astype(float)
    names = [f"x{i}" for i in range(6)] + ["y"]
    fr = Frame.from_numpy(np.column_stack([X, y]), names=names).asfactor("y")

    def fit(legacy):
        _legacy(legacy)
        g = H2OGeneralizedLinearEstimator(family="binomial",
                                          lambda_search=True, nlambdas=8,
                                          alpha=0.5, seed=7)
        g.train(y="y", training_frame=fr)
        return g

    gf = fit(False)
    assert est.est_stats()["plans"][-1]["path"] == "fused_path"
    gl = fit(True)
    assert est.est_stats()["plans"][-1]["path"] == "legacy"
    cf = np.asarray(list(gf.coef().values()))
    cl = np.asarray(list(gl.coef().values()))
    assert np.abs(cf - cl).max() < 5e-2 * max(np.abs(cl).max(), 1e-3)
    assert abs(gf.auc() - gl.auc()) < 0.02


# -- K-Means ------------------------------------------------------------------

def _blob_frame(n=900, k=3, f=4, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.uniform(-10, 10, (k, f))
    X = np.concatenate([c + rng.normal(size=(n // k, f)) for c in centers])
    return Frame.from_numpy(X, names=[f"c{i}" for i in range(f)]), X


def test_kmeans_fused_matches_legacy(cloud1):
    fr, _ = _blob_frame()
    def fit(legacy):
        _legacy(legacy)
        km = H2OKMeansEstimator(k=3, max_iterations=20, seed=1,
                                init="PlusPlus")
        km.train(training_frame=fr)
        return km
    kf = fit(False)
    plan = est.est_stats()["plans"][-1]
    assert plan["path"] == "fused" and plan["iterations"] >= 1
    kl = fit(True)
    assert np.abs(kf.model.centers() - kl.model.centers()).max() < 1e-4
    assert kf.model.tot_withinss() == pytest.approx(
        kl.model.tot_withinss(), rel=1e-5)


def test_kmeans_plusplus_seeding_running_min_pin(cloud1):
    """The O(k·n·p) running-min seeding draws BITWISE the same centers as
    the former O(k²·n·p) recompute-all-centers form, for both PlusPlus and
    Furthest (the seed-determinism pin)."""
    _, X = _blob_frame(600, 4, 5, seed=3)
    X = X.astype(np.float32)

    def reference(X, k, init, rng):
        cents = [X[rng.integers(len(X))]]
        for _ in range(k - 1):
            d2 = np.min([np.sum((X - c) ** 2, axis=1) for c in cents],
                        axis=0)
            if init == "Furthest":
                cents.append(X[int(d2.argmax())])
            else:
                probs = d2 / max(d2.sum(), 1e-12)
                cents.append(X[rng.choice(len(X), p=probs)])
        return np.asarray(cents, np.float32)

    for init in ("PlusPlus", "Furthest"):
        got = _seed_centers(X, 4, init, np.random.default_rng(11))
        want = reference(X, 4, init, np.random.default_rng(11))
        assert np.array_equal(got, want), init


def test_kmeans_user_points_stay_legacy(cloud1):
    fr, X = _blob_frame()
    pts = X[:3].copy()
    km = H2OKMeansEstimator(k=3, max_iterations=5, standardize=False,
                            user_points=pts, seed=1)
    km.train(training_frame=fr)
    assert est.est_stats()["plans"][-1]["path"] == "legacy"
    assert km.model.tot_withinss() < km.model.totss()


# -- PCA / GLRM ---------------------------------------------------------------

def test_pca_gramsvd_fused_bitwise_matches_legacy(cloud1):
    """Unsharded fused GramSVD computes the same device Gram + host f64
    eigh the legacy path did — bitwise-equal eigenpairs."""
    rng = np.random.default_rng(4)
    X = np.column_stack([3 * rng.normal(size=700), rng.normal(size=700),
                         0.1 * rng.normal(size=700)])
    fr = Frame.from_numpy(X, names=["a", "b", "c"])
    def fit(legacy):
        _legacy(legacy)
        p = H2OPrincipalComponentAnalysisEstimator(
            k=3, transform="STANDARDIZE")
        p.train(training_frame=fr)
        return p
    pf, pl = fit(False), fit(True)
    assert np.array_equal(np.asarray(pf.model.eigenvalues),
                          np.asarray(pl.model.eigenvalues))
    assert np.array_equal(np.asarray(pf.model.eigenvectors),
                          np.asarray(pl.model.eigenvectors))


def test_pca_randomized_fused_close_to_exact(cloud1):
    rng = np.random.default_rng(5)
    X = rng.normal(size=(600, 10)) @ np.diag([5, 3] + [0.1] * 8)
    fr = Frame.from_numpy(X)
    pr = H2OPrincipalComponentAnalysisEstimator(
        k=2, pca_method="Randomized", transform="DEMEAN", seed=6)
    pr.train(training_frame=fr)
    plan = est.est_stats()["plans"][-1]
    assert plan["path"] == "fused" and plan["method"] == "Randomized"
    sd = pr.model.importance["Standard deviation"]
    assert sd[0] == pytest.approx(5.0, rel=0.15)
    assert sd[1] == pytest.approx(3.0, rel=0.15)


def test_glrm_fused_matches_legacy(cloud1):
    rng = np.random.default_rng(2)
    X = rng.normal(size=(150, 6))
    X[rng.random(X.shape) < 0.1] = np.nan
    fr = Frame.from_numpy(X)
    def fit(legacy):
        _legacy(legacy)
        g = H2OGeneralizedLowRankEstimator(k=2, max_iterations=40, seed=1)
        g.train(training_frame=fr)
        return g
    gf, gl = fit(False), fit(True)
    assert gf.model.objective == pytest.approx(gl.model.objective, rel=1e-4)
    pf = est.est_stats()["plans"]
    assert [p["path"] for p in pf[-2:]] == ["fused", "legacy"]
    assert pf[-2]["iterations"] == pf[-1]["iterations"]


# -- the sweep contract: one matrix, one upload, zero retraces ----------------

def test_second_candidate_hits_matrix_cache_zero_new_traces(cloud1):
    """Two sweep candidates on the same frame: the second fit's
    standardized design comes out of the std cache layer (zero new H2D
    bytes) and traces ZERO new programs (the ISSUE 15 acceptance pin)."""
    from h2o3_tpu.runtime import phases

    X, eta, rng = _glm_frame(2000, 6, seed=9)
    y = (rng.random(len(eta)) < 1 / (1 + np.exp(-eta))).astype(float)
    names = [f"x{i}" for i in range(6)] + ["y"]
    fr = Frame.from_numpy(np.column_stack([X, y]), names=names).asfactor("y")
    g = H2OGeneralizedLinearEstimator(family="binomial", lambda_=0.05,
                                      seed=1)
    g.train(y="y", training_frame=fr)
    snap0 = dataset_cache.snapshot()
    xla0 = phases.xla_counts()
    bytes0 = phases.snapshot().get("bytes_h2d", 0)
    g2 = H2OGeneralizedLinearEstimator(family="binomial", lambda_=0.05,
                                       seed=2)
    g2.train(y="y", training_frame=fr)
    snap1 = dataset_cache.snapshot()
    xla1 = phases.xla_counts()
    bytes1 = phases.snapshot().get("bytes_h2d", 0)
    assert snap1["std_hits"] > snap0["std_hits"]
    assert snap1["std_misses"] == snap0["std_misses"]
    assert xla1["traces"] == xla0["traces"], "second fit must not trace"
    assert bytes1 == bytes0, "second fit must not re-upload the design"
    assert est.est_stats()["plans"][-1]["matrix_cache"] == "hit"


def test_kmeans_pca_share_std_matrix(cloud1):
    """K-Means and PCA on one all-numeric frame share the SAME std-layer
    artifacts (use_all_factor_levels is normalized out of the key when no
    categorical column exists)."""
    fr, _ = _blob_frame(600, 3, 4, seed=5)
    km = H2OKMeansEstimator(k=3, max_iterations=5, seed=1)
    km.train(training_frame=fr)
    snap0 = dataset_cache.snapshot()
    p = H2OPrincipalComponentAnalysisEstimator(k=2, transform="STANDARDIZE")
    p.train(training_frame=fr)
    snap1 = dataset_cache.snapshot()
    assert snap1["std_misses"] == snap0["std_misses"]
    assert snap1["std_hits"] > snap0["std_hits"]


def test_est_legacy_disables_engine_and_cache(cloud1):
    _legacy(True)
    fr, _ = _blob_frame(300, 3, 4)
    km = H2OKMeansEstimator(k=3, max_iterations=5, seed=1)
    km.train(training_frame=fr)
    assert dataset_cache.snapshot()["std_misses"] == 0
    assert est.est_stats()["plans"][-1]["path"] == "legacy"


# -- shard plan: blocks == mesh bit-identity ----------------------------------

def test_kmeans_blocks_equals_mesh_bitwise(cloud8):
    """An 8-device mesh K-Means fit is BIT-IDENTICAL to the 1-device
    forced-shard (H2O3_EST_SHARD=1) fit sharing S — the PR 9 contract
    routed to the estimators (ISSUE 15 acceptance)."""
    import jax

    from h2o3_tpu.parallel import mesh

    fr, _ = _blob_frame(640, 3, 4, seed=7)
    mesh.init(jax.devices()[:1])
    os.environ["H2O3_EST_SHARD"] = "1"
    km1 = H2OKMeansEstimator(k=3, max_iterations=15, seed=1)
    km1.train(training_frame=fr)
    assert est.est_stats()["plans"][-1]["path"] == "fused_blocks"
    del os.environ["H2O3_EST_SHARD"]
    dataset_cache.clear()
    mesh.reset()
    mesh.init(jax.devices())
    km8 = H2OKMeansEstimator(k=3, max_iterations=15, seed=1)
    km8.train(training_frame=fr)
    plan = est.est_stats()["plans"][-1]
    assert plan["path"] == "fused_mesh" and plan["n_devices"] == 8
    assert np.array_equal(np.asarray(km1.model.centers_std),
                          np.asarray(km8.model.centers_std))


def test_pca_blocks_equals_mesh_bitwise(cloud8):
    import jax

    from h2o3_tpu.parallel import mesh

    fr, _ = _blob_frame(640, 3, 4, seed=8)
    mesh.init(jax.devices()[:1])
    os.environ["H2O3_EST_SHARD"] = "1"
    p1 = H2OPrincipalComponentAnalysisEstimator(k=3,
                                                transform="STANDARDIZE")
    p1.train(training_frame=fr)
    del os.environ["H2O3_EST_SHARD"]
    dataset_cache.clear()
    mesh.reset()
    mesh.init(jax.devices())
    p8 = H2OPrincipalComponentAnalysisEstimator(k=3,
                                                transform="STANDARDIZE")
    p8.train(training_frame=fr)
    assert np.array_equal(np.asarray(p1.model.eigenvalues),
                          np.asarray(p8.model.eigenvalues))
    assert np.array_equal(np.asarray(p1.model.eigenvectors),
                          np.asarray(p8.model.eigenvectors))


def test_est_shard_escape_hatch(cloud8):
    """H2O3_EST_SHARD=0 on a mesh cloud: fits run unsharded ("off"),
    bit-equal to a plain 1-device fused fit."""
    import jax

    from h2o3_tpu.parallel import mesh

    fr, _ = _blob_frame(320, 3, 4, seed=9)
    mesh.init(jax.devices()[:1])
    km1 = H2OKMeansEstimator(k=3, max_iterations=10, seed=1)
    km1.train(training_frame=fr)
    dataset_cache.clear()
    mesh.reset()
    mesh.init(jax.devices())
    os.environ["H2O3_EST_SHARD"] = "0"
    km0 = H2OKMeansEstimator(k=3, max_iterations=10, seed=1)
    km0.train(training_frame=fr)
    assert est.est_stats()["plans"][-1]["path"] == "fused"
    assert np.array_equal(np.asarray(km1.model.centers_std),
                          np.asarray(km0.model.centers_std))


@pytest.mark.slow
def test_glm_blocks_equals_mesh_bitwise_slow(cloud8):
    import jax

    from h2o3_tpu.parallel import mesh

    X, eta, rng = _glm_frame(640, 4, seed=12)
    y = (rng.random(len(eta)) < 1 / (1 + np.exp(-eta))).astype(float)
    names = [f"x{i}" for i in range(4)] + ["y"]
    fr = Frame.from_numpy(np.column_stack([X, y]), names=names).asfactor("y")
    for fam, lam, alpha in (("binomial", 0.01, 0.5),
                            ("gaussian", 0.01, 0.0)):
        frx = fr
        if fam == "gaussian":
            frx = Frame.from_numpy(
                np.column_stack([X, eta]), names=names)
        mesh.reset()
        mesh.init(jax.devices()[:1])
        os.environ["H2O3_EST_SHARD"] = "1"
        dataset_cache.clear()
        g1 = H2OGeneralizedLinearEstimator(family=fam, lambda_=lam,
                                           alpha=alpha)
        g1.train(y="y", training_frame=frx)
        del os.environ["H2O3_EST_SHARD"]
        dataset_cache.clear()
        mesh.reset()
        mesh.init(jax.devices())
        g8 = H2OGeneralizedLinearEstimator(family=fam, lambda_=lam,
                                           alpha=alpha)
        g8.train(y="y", training_frame=frx)
        assert np.array_equal(np.asarray(g1.model.beta),
                              np.asarray(g8.model.beta)), fam


# -- observability -------------------------------------------------------------

def test_est_observability_surfaces(cloud1):
    from h2o3_tpu.runtime import metrics_registry, phases, profiler

    fr, _ = _blob_frame(300, 3, 4)
    before = phases.snapshot().get("est_iter_s", 0.0)
    km = H2OKMeansEstimator(k=3, max_iterations=5, seed=1)
    km.train(training_frame=fr)
    # est_iter phase bucket accumulated the fused loop's wall
    assert phases.snapshot().get("est_iter_s", 0.0) >= before
    stats = profiler.est_stats()
    assert stats["active"] and stats["plans"]
    assert any(p["algo"] == "kmeans" for p in stats["plans"])
    assert stats["dispatch"].get("kmeans/fused", 0) >= 1
    assert stats["iterations"].get("kmeans", 0) >= 1
    # Prometheus families on the scrape surface
    text = metrics_registry.prometheus_text()
    assert "h2o3_est_dispatch" in text
    assert "h2o3_est_iterations" in text


def test_profiler_rest_carries_est_fold(cloud1):
    from h2o3_tpu.client import H2OConnection
    from h2o3_tpu.rest.server import start_server

    fr, _ = _blob_frame(300, 3, 4)
    km = H2OKMeansEstimator(k=3, max_iterations=5, seed=1)
    km.train(training_frame=fr)
    srv = start_server(port=0)
    try:
        # a direct connection object — h2o.connect() would make this
        # throwaway server the process-wide default and poison every
        # later test once it stops
        conn = H2OConnection(f"http://127.0.0.1:{srv.port}")
        prof = conn.get("/3/Profiler")
        assert "est" in prof and prof["est"]["plans"]
        assert prof["est"]["plans"][-1]["algo"] == "kmeans"
    finally:
        srv.stop()


# -- AutoML heterogeneous pool -------------------------------------------------

@pytest.mark.slow
def test_automl_heterogeneous_parallel_leaderboard_identical(cloud1):
    """The PR 4 leaderboard-parallelism invariant holds over the NEW
    engine-backed candidates: an AutoML pool of GLM + DRF + XRT produces
    an identical leaderboard at parallelism 1 and 2 (ISSUE 15
    acceptance). Slow lane (tracked reason): two full CV'd AutoML runs —
    ~250s, the single largest tier-1 line with the suite at the 870s
    cliff (tools/t1_budget.py); the parallelism invariant itself is also
    pinned cheaply by test_training_pool.py::test_automl_parallel_smoke."""
    from h2o3_tpu.automl.automl import H2OAutoML

    rng = np.random.default_rng(21)
    X = rng.normal(size=(400, 5))
    yv = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2]
          + 0.3 * rng.normal(size=400) > 0).astype(float)
    names = [f"f{i}" for i in range(5)] + ["y"]
    fr = Frame.from_numpy(np.column_stack([X, yv]),
                          names=names).asfactor("y")

    def lb(par):
        # max_models=2 → one GLM + one DRF wave: a genuinely mixed pool
        # without two CONCURRENT tree fits (pathologically slow on a
        # 1-core host, with or without the engine)
        aml = H2OAutoML(max_models=2, seed=5, nfolds=2, parallelism=par,
                        include_algos=["GLM", "DRF"])
        aml.train(y="y", training_frame=fr)
        return [(r["algo"], round(r["auc"], 12))
                for r in aml.leaderboard.rows]

    l1, l2 = lb(1), lb(2)
    assert l1 == l2, (l1, l2)
    assert len({r[0] for r in l1}) >= 2, "pool must be heterogeneous"
