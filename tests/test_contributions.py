"""predict_contributions (TreeSHAP) + predict_leaf_node_assignment.

Mirrors the reference's contribution tests (h2o-py pyunit predict_contributions
suites; hex/genmodel/algos/tree/TreeSHAP.java): the local-accuracy contract
(contributions + BiasTerm == raw prediction), exact agreement with a
brute-force Shapley oracle, MOJO round-trip consistency, and the native C++
kernel vs the numpy mirror.
"""

import sys
from collections import namedtuple

import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu.estimators import (
    H2OGradientBoostingEstimator,
    H2ORandomForestEstimator,
    H2OXGBoostEstimator,
)
from h2o3_tpu.models import tree_shap as ts

Fst = namedtuple("Fst", "feat thr is_split value")


def _binomial_frame(n=1500, seed=0):
    rng = np.random.default_rng(seed)
    x0, x1, x2 = rng.normal(size=n), rng.normal(size=n), rng.normal(size=n)
    logit = 1.5 * x0 - 0.8 * x1 + 0.3 * x0 * x2
    y = (rng.random(n) < 1 / (1 + np.exp(-logit))).astype(int)
    return h2o.H2OFrame_from_python(
        {"a": x0, "b": x1, "c": x2, "y": y.astype(str)},
        column_types={"y": "enum"},
    )


def _contrib_matrix(fr):
    return np.column_stack(
        [np.asarray(fr.vec(n).data, np.float64) for n in fr.names]
    )


def test_treeshap_matches_bruteforce_random_trees():
    rng = np.random.default_rng(3)
    D, F = 3, 3
    T = 2 ** (D + 1) - 1
    for trial in range(5):
        feat = rng.integers(0, F, T).astype(np.int64)
        thr = rng.normal(size=T)
        issp = np.zeros(T, bool)
        issp[: 2 ** D - 1] = rng.random(2 ** D - 1) < 0.8
        for i in range(1, T):
            if not issp[(i - 1) // 2]:
                issp[i] = False
        value = rng.normal(size=T)
        cov = np.zeros(T)
        cov[2 ** D - 1:] = rng.random(2 ** D) + 0.1
        for i in range(2 ** D - 2, -1, -1):
            cov[i] = cov[2 * i + 1] + cov[2 * i + 2]
        forest = Fst(feat[None], thr[None], issp[None], value[None])
        X = rng.normal(size=(4, F))
        X[0, 1] = np.nan
        phi = ts.tree_shap_numpy(forest, cov[None], X)
        for r in range(X.shape[0]):
            bf = ts.shapley_bruteforce(forest, cov[None], X[r])
            np.testing.assert_allclose(phi[r], bf, atol=1e-10)


def test_gbm_contributions_local_accuracy():
    fr = _binomial_frame()
    gbm = H2OGradientBoostingEstimator(ntrees=15, max_depth=4, seed=7)
    gbm.train(x=["a", "b", "c"], y="y", training_frame=fr)
    m = gbm.model
    contrib = m.predict_contributions(fr)
    assert contrib.names == ["a", "b", "c", "BiasTerm"]
    C = _contrib_matrix(contrib)
    margins = m._margins(m._matrix(fr))[:, 0]
    np.testing.assert_allclose(C.sum(axis=1), margins, atol=1e-5)


def test_native_kernel_matches_numpy():
    from h2o3_tpu.native import loader

    if not loader.available():
        pytest.skip("native lib unavailable")
    fr = _binomial_frame(400)
    gbm = H2OGradientBoostingEstimator(ntrees=5, max_depth=3, seed=1)
    gbm.train(x=["a", "b", "c"], y="y", training_frame=fr)
    m = gbm.model
    st = m.forest[0]
    args = (np.asarray(st.feat), np.asarray(st.thr),
            np.asarray(st.is_split), np.asarray(st.value))
    cov = np.asarray(m.covers[0])
    X = m._matrix(fr)[:64]
    nat = loader.tree_shap(*args, cov, X)
    ref = ts.tree_shap_numpy(Fst(*args), cov, X)
    np.testing.assert_allclose(nat, ref, atol=1e-12)


def test_drf_regression_contributions_sum_to_prediction():
    rng = np.random.default_rng(1)
    n = 1000
    x0, x1, x2 = rng.normal(size=n), rng.normal(size=n), rng.normal(size=n)
    fr = h2o.H2OFrame_from_python(
        {"a": x0, "b": x1, "c": x2, "y": 2 * x0 - x1 + 0.1 * rng.normal(size=n)}
    )
    drf = H2ORandomForestEstimator(ntrees=8, max_depth=5, seed=2)
    drf.train(x=["a", "b", "c"], y="y", training_frame=fr)
    C = _contrib_matrix(drf.model.predict_contributions(fr))
    pred = np.asarray(drf.model.predict(fr).vec("predict").data, np.float64)
    np.testing.assert_allclose(C.sum(axis=1), pred, atol=1e-5)


def test_xgboost_contributions_local_accuracy():
    fr = _binomial_frame(800, seed=5)
    xgb = H2OXGBoostEstimator(ntrees=10, max_depth=4, seed=3)
    xgb.train(x=["a", "b", "c"], y="y", training_frame=fr)
    m = xgb.model
    C = _contrib_matrix(m.predict_contributions(fr))
    margins = m._margins(m._matrix(fr))[:, 0]
    np.testing.assert_allclose(C.sum(axis=1), margins, atol=1e-5)


def test_contributions_multinomial_raises():
    rng = np.random.default_rng(4)
    n = 300
    x = rng.normal(size=n)
    y = np.digitize(x, [-0.5, 0.5]).astype(str)
    fr = h2o.H2OFrame_from_python(
        {"a": x, "b": rng.normal(size=n), "y": y}, column_types={"y": "enum"}
    )
    gbm = H2OGradientBoostingEstimator(ntrees=3, max_depth=3, seed=1)
    gbm.train(x=["a", "b"], y="y", training_frame=fr)
    with pytest.raises(ValueError, match="multinomial"):
        gbm.model.predict_contributions(fr)


def test_contributions_top_n_pairs():
    fr = _binomial_frame(500, seed=9)
    gbm = H2OGradientBoostingEstimator(ntrees=5, max_depth=3, seed=1)
    gbm.train(x=["a", "b", "c"], y="y", training_frame=fr)
    out = gbm.model.predict_contributions(fr, top_n=2)
    assert out.names == ["top_feature_1", "top_value_1",
                         "top_feature_2", "top_value_2", "BiasTerm"]
    v1 = np.asarray(out.vec("top_value_1").data, np.float64)
    v2 = np.asarray(out.vec("top_value_2").data, np.float64)
    assert (v1 >= v2).all()


def test_mojo_contributions_round_trip(tmp_path):
    fr = _binomial_frame(600, seed=11)
    gbm = H2OGradientBoostingEstimator(ntrees=8, max_depth=4, seed=4)
    gbm.train(x=["a", "b", "c"], y="y", training_frame=fr)
    in_cluster = _contrib_matrix(gbm.model.predict_contributions(fr))
    path = h2o.save_model(gbm, str(tmp_path))
    scorer = h2o.load_model(path)
    offline = _contrib_matrix(scorer.predict_contributions(fr))
    np.testing.assert_allclose(offline, in_cluster, atol=1e-6)


def test_leaf_node_assignment_path_and_node_id():
    fr = _binomial_frame(300, seed=13)
    gbm = H2OGradientBoostingEstimator(ntrees=4, max_depth=3, seed=1)
    gbm.train(x=["a", "b", "c"], y="y", training_frame=fr)
    m = gbm.model
    la = m.predict_leaf_node_assignment(fr, type="Path")
    assert la.names == [f"T{t + 1}.C1" for t in range(4)]
    # paths are L/R strings of length <= max_depth
    dom = la.vec("T1.C1").domain
    assert all(set(p) <= {"L", "R"} and len(p) <= 3 for p in dom)
    ni = m.predict_leaf_node_assignment(fr, type="Node_ID")
    ids = np.asarray(ni.vec("T1.C1").data, np.int64)
    # node ids must be valid heap indices and consistent with the path depth
    assert ids.min() >= 0 and ids.max() < 2 ** 4 - 1
    # routing consistency: each row's leaf value summed over trees == margin
    st = m.forest[0]
    val = np.asarray(st.value)
    total = np.zeros(fr.nrow)
    for t in range(4):
        ids_t = np.asarray(
            ni.vec(f"T{t + 1}.C1").data, np.int64)
        total += val[t][ids_t]
    f0 = m.f0 if np.ndim(m.f0) == 0 else m.f0[0]
    margins = m._margins(m._matrix(fr))[:, 0]
    np.testing.assert_allclose(total + f0, margins, atol=1e-5)
