"""Seed determinism + padded-shape invariance of trained models.

VERDICT r02 weak #2: the flagship fixed-seed AUC moved 0.85226 → 0.85022
between rounds. The r03 bisect (BASELINE.md round-3 notes) pinned it to the
r02 histogram-method default change (onehot → pallas_factored): different
f32 accumulation order at 1M rows flips near-tie splits. These tests lock
the invariants that SHOULD hold: same seed ⇒ identical model (across runs,
and across padded row-count changes such as `_bucket_rows` bucketing), per
histogram method.

These are SINGLE-DEVICE pins (cloud1): on a mesh the sharded path's
reduction geometry is a function of the padded shape (S blocks of npad/S
rows), so changing npad moves block boundaries — dust-level histogram
deltas that can flip a near-tie split, exactly the r03 mechanism. The
mesh-side determinism contract is different and pinned in
tests/test_tree_sharded.py: any two fits sharing the canonical block
count (at ANY device count 1/2/4/8) are bit-identical.
"""

import os

import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator


def _frame(n=20_000, f=6, seed=5):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = ((X[:, 0] * X[:, 1] + 0.5 * X[:, 2] + 0.4 * rng.normal(size=n)) > 0)
    d = {f"f{i}": X[:, i] for i in range(f)}
    d["y"] = y.astype(int).astype(str)
    return (h2o.H2OFrame_from_python(d, column_types={"y": "enum"}),
            [f"f{i}" for i in range(f)])


def _train_probs(fr, x, **env):
    old = {k: os.environ.get(k) for k in env}
    os.environ.update({k: str(v) for k, v in env.items()})
    try:
        gbm = H2OGradientBoostingEstimator(
            ntrees=10, max_depth=5, learn_rate=0.2, seed=42,
            sample_rate=0.8, col_sample_rate=0.8)
        gbm.train(x=x, y="y", training_frame=fr)
        return gbm.predict(fr).vec("1").numeric_np(), float(gbm.auc())
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def test_same_seed_same_model(cloud1):
    fr, x = _frame()
    p1, auc1 = _train_probs(fr, x)
    p2, auc2 = _train_probs(fr, x)
    assert auc1 == auc2
    np.testing.assert_array_equal(p1, p2)


def test_padded_shape_invariance(cloud1):
    """Bucketing pads 20k rows up to 20480 zero-weight rows. Zero rows add
    exactly 0.0 to every histogram sum, but a different array SHAPE changes
    XLA's f32 reduction order (machine-dependent SIMD regrouping), and a
    dust-level histogram delta can flip ONE near-tie split whose rerouting
    then cascades through later boosting rounds — the r03 bisect mechanism
    (BASELINE.md round-3: a method change moved flagship AUC 0.002).
    Measured on this 1-core box: dAUC ≈ 6e-3 with most per-row
    probabilities moving, from exactly such a flip. The invariant that
    HOLDS everywhere is model QUALITY: AUC agrees to ~1e-2 and both
    models clearly learn; per-row equality across padded shapes is pinned
    where it is actually guaranteed — same shape + same seed
    (test_same_seed_same_model), and the sharded lane's canonical-block
    contract (tests/test_tree_sharded.py)."""
    fr, x = _frame()
    p_bucket, auc_bucket = _train_probs(fr, x, H2O3_BUCKET_ROWS="1")
    p_exact, auc_exact = _train_probs(fr, x, H2O3_BUCKET_ROWS="0")
    assert abs(auc_bucket - auc_exact) < 0.02
    assert min(auc_bucket, auc_exact) > 0.8
    # the two probability vectors rank rows the same way to high agreement
    assert np.corrcoef(p_bucket, p_exact)[0, 1] > 0.98
    # flip noise is SYMMETRIC; a real histogram bug (dropped rows/blocks,
    # shifted bins) moves probabilities systematically — calibration and
    # confidence mass must stay put (measured noise: ~5e-4 and ~8e-3)
    assert abs(p_bucket.mean() - p_exact.mean()) < 0.01
    assert abs(np.abs(p_bucket - 0.5).mean()
               - np.abs(p_exact - 0.5).mean()) < 0.03


@pytest.mark.parametrize("method", ["segment", "onehot"])
def test_hist_methods_agree_small(method, cloud1):
    """Histogram methods accumulate in different f32 orders (scatter fold
    vs MXU matmul tree), so a near-tie split may flip and cascade (see
    test_padded_shape_invariance — the same r03 mechanism, dAUC ≈ 1e-3
    measured here for onehot). A WRONG histogram — dropped rows,
    off-by-one bins — moves AUC by orders of magnitude more than this
    bound and destroys the prediction correlation."""
    fr, x = _frame(n=8_000)
    p_auto, auc_auto = _train_probs(fr, x)
    p_m, auc_m = _train_probs(fr, x, H2O3_HIST_METHOD=method)
    assert abs(auc_auto - auc_m) < 0.02
    assert min(auc_auto, auc_m) > 0.8
    assert np.corrcoef(p_auto, p_m)[0, 1] > 0.98
    # systematic-shift detectors (see test_padded_shape_invariance): a
    # kernel that loses or double-counts rows shifts calibration or
    # confidence mass far beyond the symmetric flip noise
    assert abs(p_auto.mean() - p_m.mean()) < 0.01
    assert abs(np.abs(p_auto - 0.5).mean()
               - np.abs(p_m - 0.5).mean()) < 0.03
