"""Seed determinism + padded-shape invariance of trained models.

VERDICT r02 weak #2: the flagship fixed-seed AUC moved 0.85226 → 0.85022
between rounds. The r03 bisect (BASELINE.md round-3 notes) pinned it to the
r02 histogram-method default change (onehot → pallas_factored): different
f32 accumulation order at 1M rows flips near-tie splits. These tests lock
the invariants that SHOULD hold: same seed ⇒ identical model (across runs,
and across padded row-count changes such as `_bucket_rows` bucketing), per
histogram method.
"""

import os

import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator


def _frame(n=20_000, f=6, seed=5):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = ((X[:, 0] * X[:, 1] + 0.5 * X[:, 2] + 0.4 * rng.normal(size=n)) > 0)
    d = {f"f{i}": X[:, i] for i in range(f)}
    d["y"] = y.astype(int).astype(str)
    return (h2o.H2OFrame_from_python(d, column_types={"y": "enum"}),
            [f"f{i}" for i in range(f)])


def _train_probs(fr, x, **env):
    old = {k: os.environ.get(k) for k in env}
    os.environ.update({k: str(v) for k, v in env.items()})
    try:
        gbm = H2OGradientBoostingEstimator(
            ntrees=10, max_depth=5, learn_rate=0.2, seed=42,
            sample_rate=0.8, col_sample_rate=0.8)
        gbm.train(x=x, y="y", training_frame=fr)
        return gbm.predict(fr).vec("1").numeric_np(), float(gbm.auc())
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def test_same_seed_same_model():
    fr, x = _frame()
    p1, auc1 = _train_probs(fr, x)
    p2, auc2 = _train_probs(fr, x)
    assert auc1 == auc2
    np.testing.assert_array_equal(p1, p2)


def test_padded_shape_invariance():
    """Bucketing pads 20k rows up to 20480 zero-weight rows. Zero rows add
    exactly 0.0 to every histogram sum, but a different array SHAPE changes
    XLA's f32 reduction order, so leaf values may differ by float dust
    (measured ~1e-6 relative). The trees themselves must agree — same
    splits, predictions equal to tight tolerance, same AUC."""
    fr, x = _frame()
    p_bucket, auc_bucket = _train_probs(fr, x, H2O3_BUCKET_ROWS="1")
    p_exact, auc_exact = _train_probs(fr, x, H2O3_BUCKET_ROWS="0")
    assert abs(auc_bucket - auc_exact) < 1e-4
    np.testing.assert_allclose(p_bucket, p_exact, rtol=3e-5, atol=2e-6)


@pytest.mark.parametrize("method", ["segment", "onehot"])
def test_hist_methods_agree_small(method):
    """Histogram methods must agree up to f32 accumulation-order dust
    (measured ≤8e-4 relative after 10 boosting rounds at 8k rows — the same
    mechanism as the flagship-scale 0.002 AUC delta; BASELINE.md round-3
    notes). A wrong histogram — dropped rows, off-by-one bins — moves
    predictions by orders of magnitude more than this bound."""
    fr, x = _frame(n=8_000)
    p_auto, auc_auto = _train_probs(fr, x)
    p_m, auc_m = _train_probs(fr, x, H2O3_HIST_METHOD=method)
    assert abs(auc_auto - auc_m) < 1e-3
    np.testing.assert_allclose(p_auto, p_m, rtol=3e-3, atol=1e-4)
