"""Rapids munging + checkpoint tests — `testdir_munging` analog."""

import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

from conftest import make_classification


def test_group_by_aggregates(cloud1):
    fr = Frame.from_dict({
        "g": np.asarray(["a", "b", "a", "b", "a"], dtype=object),
        "v": [1.0, 2.0, 3.0, 4.0, np.nan],
    })
    out = fr.group_by("g").count().sum("v", na="rm").mean("v", na="rm").get_frame()
    assert out.nrow == 2
    d = out.as_data_frame()
    ia = list(d["g"]).index("a")
    ib = list(d["g"]).index("b")
    assert d["nrow"][ia] == 3
    assert d["sum_v"][ia] == pytest.approx(4.0)   # na="rm" skips NAs
    assert d["mean_v"][ib] == pytest.approx(3.0)
    # the default na="all" PROPAGATES NA into the aggregate (AstGroup
    # NAHandling.ALL) — group a contains an NA, group b does not
    d2 = fr.group_by("g").sum("v").get_frame().as_data_frame()
    assert np.isnan(d2["sum_v"][list(d2["g"]).index("a")])
    assert d2["sum_v"][list(d2["g"]).index("b")] == pytest.approx(6.0)


def test_group_by_multi_key(cloud1):
    rng = np.random.default_rng(0)
    n = 200
    g1 = rng.integers(0, 3, n)
    g2 = rng.integers(0, 2, n)
    v = rng.random(n)
    fr = Frame.from_dict({
        "g1": np.asarray(["x", "y", "z"], dtype=object)[g1],
        "g2": g2.astype(float),
        "v": v,
    })
    out = fr.group_by(["g1", "g2"]).mean("v").get_frame()
    assert out.nrow == 6
    d = out.as_data_frame()
    # verify one cell against numpy
    m = (g1 == 0) & (g2 == 1)
    expect = v[m].mean()
    row = [i for i in range(6) if d["g1"][i] == "x" and d["g2"][i] == 1][0]
    assert d["mean_v"][row] == pytest.approx(expect)


def test_merge_inner_and_outer(cloud1):
    left = Frame.from_dict({"k": [1.0, 2.0, 3.0], "a": [10.0, 20.0, 30.0]})
    right = Frame.from_dict({"k": [2.0, 3.0, 4.0], "b": [200.0, 300.0, 400.0]})
    inner = h2o.merge(left, right)
    assert inner.nrow == 2
    d = inner.as_data_frame()
    assert set(d["k"]) == {2.0, 3.0}
    louter = h2o.merge(left, right, all_x=True)
    assert louter.nrow == 3
    d = louter.as_data_frame()
    i1 = list(d["k"]).index(1.0)
    assert np.isnan(d["b"][i1])


def test_quantile_and_table(cloud1):
    fr = Frame.from_dict({"v": np.arange(101, dtype=float)})
    q = fr.quantile(prob=[0.1, 0.5, 0.9])
    d = q.as_data_frame()
    assert d["vQuantiles"][1] == pytest.approx(50.0)
    fr2 = Frame.from_dict({"c": np.asarray(["a", "b", "a"], dtype=object)})
    t = fr2.table().as_data_frame()
    assert list(t["Count"]) == [2.0, 1.0]


def test_frame_arithmetic_and_masks(cloud1):
    fr = Frame.from_dict({"a": [1.0, 2.0, 3.0], "b": [10.0, 20.0, 30.0]})
    s = fr["a"] + fr["b"]
    assert list(s._col0()) == [11.0, 22.0, 33.0]
    mask = fr["a"] > 1.5
    assert fr[mask].nrow == 2
    # enum equality mask
    fr2 = Frame.from_dict({"c": np.asarray(["x", "y", "x"], dtype=object)})
    assert fr2[fr2["c"] == "x"].nrow == 2


def test_gbm_checkpoint_continue(cloud1):
    X, y = make_classification(1200, 6, seed=1)
    fr = Frame.from_numpy(np.column_stack([X, y]),
                          names=[f"x{i}" for i in range(6)] + ["y"]).asfactor("y")
    base = H2OGradientBoostingEstimator(ntrees=10, max_depth=3, seed=2)
    base.train(y="y", training_frame=fr)
    ll10 = base.logloss()
    cont = H2OGradientBoostingEstimator(ntrees=25, max_depth=3, seed=2,
                                        checkpoint=base)
    cont.train(y="y", training_frame=fr)
    assert cont.model.ntrees_built == 25
    assert cont.logloss() < ll10  # more trees, better training fit
    # direct 25-tree model should be in the same ballpark
    direct = H2OGradientBoostingEstimator(ntrees=25, max_depth=3, seed=2)
    direct.train(y="y", training_frame=fr)
    assert abs(cont.logloss() - direct.logloss()) < 0.05


def test_checkpoint_incompatible_depth_raises(cloud1):
    X, y = make_classification(600, 4, seed=3)
    fr = Frame.from_numpy(np.column_stack([X, y]),
                          names=["a", "b", "c", "d", "y"]).asfactor("y")
    base = H2OGradientBoostingEstimator(ntrees=5, max_depth=3, seed=4)
    base.train(y="y", training_frame=fr)
    bad = H2OGradientBoostingEstimator(ntrees=10, max_depth=5, seed=4, checkpoint=base)
    with pytest.raises(ValueError, match="checkpoint"):
        bad.train(y="y", training_frame=fr)


def test_merge_right_outer_keeps_keys(cloud1):
    left = Frame.from_dict({"k": [1.0, 2.0], "a": [10.0, 20.0]})
    right = Frame.from_dict({"k": [2.0, 4.0], "b": [200.0, 400.0]})
    router = h2o.merge(left, right, all_y=True)
    d = router.as_data_frame()
    assert 4.0 in list(d["k"])  # unmatched right row keeps its join key
    i4 = list(d["k"]).index(4.0)
    assert np.isnan(d["a"][i4]) and d["b"][i4] == 400.0
