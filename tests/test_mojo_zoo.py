"""MOJO export across the model zoo (VERDICT r03 #5): every new artifact
kind round-trips save → load → predict with row-level parity against the
in-cluster model. Reference: `hex/genmodel/algos/**` scorers +
`EasyPredictModelWrapper` (in-cluster ≡ MOJO parity is upstream's
contract)."""

import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu.frame.frame import Frame


def _cls_frame(n=500, p=4, seed=0, enum_col=False):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, p))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(int)
    names = [f"c{i}" for i in range(p)]
    d = {nm: X[:, i] for i, nm in enumerate(names)}
    if enum_col:
        d["cat"] = np.asarray(
            [f"k{v}" for v in rng.integers(0, 3, n)], dtype=object)
    d["y"] = y.astype(str)
    return h2o.H2OFrame_from_python(
        d, column_types={"y": "enum", **({"cat": "enum"} if enum_col else {})})


def _roundtrip(est, tmp_path):
    path = h2o.save_model(est, str(tmp_path))
    return h2o.load_model(path)


def test_mojo_eif(tmp_path, cloud1):
    from h2o3_tpu.models.extended_isolation_forest import \
        H2OExtendedIsolationForestEstimator

    fr = _cls_frame(400, seed=1)
    est = H2OExtendedIsolationForestEstimator(ntrees=12, sample_size=64,
                                              extension_level=1, seed=2)
    est.train(x=[f"c{i}" for i in range(4)], training_frame=fr)
    sc = _roundtrip(est, tmp_path)
    live = est.predict(fr)
    mojo = sc.predict(fr)
    np.testing.assert_allclose(mojo.vec("anomaly_score").numeric_np(),
                               live.vec("anomaly_score").numeric_np(),
                               rtol=1e-5, atol=1e-6)


def test_mojo_stacked_ensemble(tmp_path, cloud1):
    from h2o3_tpu.estimators import (H2OGradientBoostingEstimator,
                                     H2OGeneralizedLinearEstimator,
                                     H2OStackedEnsembleEstimator)

    fr = _cls_frame(600, seed=3)
    x = [f"c{i}" for i in range(4)]
    gbm = H2OGradientBoostingEstimator(
        ntrees=6, max_depth=3, seed=1, nfolds=3,
        keep_cross_validation_predictions=True)
    gbm.train(x=x, y="y", training_frame=fr)
    glm = H2OGeneralizedLinearEstimator(
        family="binomial", nfolds=3, seed=1,
        keep_cross_validation_predictions=True)
    glm.train(x=x, y="y", training_frame=fr)
    se = H2OStackedEnsembleEstimator(base_models=[gbm, glm], seed=1)
    se.train(x=x, y="y", training_frame=fr)
    sc = _roundtrip(se, tmp_path)
    np.testing.assert_allclose(
        sc.predict(fr).vec("1").numeric_np(),
        se.predict(fr).vec("1").numeric_np(), rtol=1e-5, atol=1e-6)


def test_mojo_word2vec(tmp_path, cloud1):
    from h2o3_tpu.models.word2vec import H2OWord2vecEstimator

    rng = np.random.default_rng(0)
    words = [w for _ in range(60)
             for w in ("cat", "dog", "fish", "bird", "tree")]
    rng.shuffle(words)
    fr = h2o.H2OFrame_from_python(
        {"w": np.asarray(words, dtype=object)}, column_types={"w": "enum"})
    est = H2OWord2vecEstimator(vec_size=8, epochs=2, seed=1)
    est.train(training_frame=fr)
    sc = _roundtrip(est, tmp_path)
    live = est.model.transform(fr)
    mojo = sc.transform(fr)
    for j in range(8):
        np.testing.assert_allclose(mojo.vec(f"C{j+1}").numeric_np(),
                                   live.vec(f"C{j+1}").numeric_np(),
                                   rtol=1e-5, atol=1e-6)
    syn_live = est.model.find_synonyms("cat", 3)
    syn_mojo = sc.find_synonyms("cat", 3)
    assert list(syn_live) == list(syn_mojo)


def test_mojo_glrm(tmp_path, cloud1):
    from h2o3_tpu.models.glrm import H2OGeneralizedLowRankEstimator

    rng = np.random.default_rng(4)
    base = rng.normal(size=(200, 2))
    X = base @ rng.normal(size=(2, 5)) + 0.01 * rng.normal(size=(200, 5))
    X[rng.random(X.shape) < 0.05] = np.nan
    fr = h2o.H2OFrame_from_python({f"c{i}": X[:, i] for i in range(5)})
    est = H2OGeneralizedLowRankEstimator(k=2, seed=1)
    est.train(x=[f"c{i}" for i in range(5)], training_frame=fr)
    sc = _roundtrip(est, tmp_path)
    live = est.predict(fr)
    mojo = sc.predict(fr)
    for nm in live.names:
        np.testing.assert_allclose(mojo.vec(nm).numeric_np(),
                                   live.vec(nm).numeric_np(),
                                   rtol=1e-4, atol=1e-5)
    # transform (archetype loadings) parity too
    lt = est.model.transform(fr)
    mt = sc.transform(fr)
    for nm in lt.names:
        np.testing.assert_allclose(mt.vec(nm).numeric_np(),
                                   lt.vec(nm).numeric_np(),
                                   rtol=1e-4, atol=1e-5)


def test_mojo_targetencoder(tmp_path, cloud1):
    from h2o3_tpu.models.targetencoder import H2OTargetEncoderEstimator

    fr = _cls_frame(400, seed=5, enum_col=True)
    est = H2OTargetEncoderEstimator(blending=True, noise=0.0)
    est.train(x=["cat"], y="y", training_frame=fr)
    sc = _roundtrip(est, tmp_path)
    live = est.model.transform(fr)
    mojo = sc.predict(fr)
    np.testing.assert_allclose(mojo.vec("cat_te").numeric_np(),
                               live.vec("cat_te").numeric_np(),
                               rtol=1e-5, atol=1e-6)


def test_mojo_rulefit(tmp_path, cloud1):
    from h2o3_tpu.models.rulefit import H2ORuleFitEstimator

    fr = _cls_frame(600, seed=6)
    est = H2ORuleFitEstimator(rule_generation_ntrees=10, seed=1,
                              max_rule_length=3)
    est.train(x=[f"c{i}" for i in range(4)], y="y", training_frame=fr)
    sc = _roundtrip(est, tmp_path)
    np.testing.assert_allclose(
        sc.predict(fr).vec("1").numeric_np(),
        est.predict(fr).vec("1").numeric_np(), rtol=1e-5, atol=1e-6)


def test_mojo_coxph(tmp_path, cloud1):
    from h2o3_tpu.models.coxph import H2OCoxProportionalHazardsEstimator

    rng = np.random.default_rng(7)
    n = 300
    age = rng.normal(60, 10, n)
    sev = rng.normal(size=n)
    t = rng.exponential(np.exp(-0.02 * (age - 60) - 0.4 * sev))
    ev = (rng.random(n) < 0.8).astype(int)
    fr = h2o.H2OFrame_from_python(
        {"age": age, "sev": sev, "time": t, "event": ev.astype(np.float64)})
    est = H2OCoxProportionalHazardsEstimator(stop_column="time")
    est.train(x=["age", "sev"], y="event", training_frame=fr)
    sc = _roundtrip(est, tmp_path)
    np.testing.assert_allclose(
        sc.predict(fr).vec("lp").numeric_np(),
        est.predict(fr).vec("lp").numeric_np(), rtol=1e-5, atol=1e-6)


def test_mojo_naive_bayes(tmp_path, cloud1):
    from h2o3_tpu.models.naive_bayes import H2ONaiveBayesEstimator

    fr = _cls_frame(500, seed=8, enum_col=True)
    est = H2ONaiveBayesEstimator(laplace=1.0)
    est.train(x=["c0", "c1", "c2", "c3", "cat"], y="y", training_frame=fr)
    sc = _roundtrip(est, tmp_path)
    np.testing.assert_allclose(
        sc.predict(fr).vec("1").numeric_np(),
        est.predict(fr).vec("1").numeric_np(), rtol=1e-5, atol=1e-6)


def test_mojo_isotonic(tmp_path, cloud1):
    from h2o3_tpu.models.isotonic import H2OIsotonicRegressionEstimator

    rng = np.random.default_rng(9)
    x = rng.uniform(0, 10, 400)
    y = np.sqrt(x) + 0.1 * rng.normal(size=400)
    fr = h2o.H2OFrame_from_python({"x": x, "y": y})
    est = H2OIsotonicRegressionEstimator()
    est.train(x=["x"], y="y", training_frame=fr)
    sc = _roundtrip(est, tmp_path)
    np.testing.assert_allclose(
        sc.predict(fr).vec("predict").numeric_np(),
        est.predict(fr).vec("predict").numeric_np(),
        rtol=1e-6, atol=1e-8)


def test_mojo_svd(tmp_path, cloud1):
    from h2o3_tpu.models.svd import H2OSingularValueDecompositionEstimator

    rng = np.random.default_rng(10)
    X = rng.normal(size=(200, 4))
    fr = h2o.H2OFrame_from_python({f"c{i}": X[:, i] for i in range(4)})
    est = H2OSingularValueDecompositionEstimator(nv=2)
    est.train(x=[f"c{i}" for i in range(4)], training_frame=fr)
    sc = _roundtrip(est, tmp_path)
    live = est.predict(fr)
    mojo = sc.predict(fr)
    for nm in live.names:
        np.testing.assert_allclose(mojo.vec(nm).numeric_np(),
                                   live.vec(nm).numeric_np(),
                                   rtol=1e-5, atol=1e-7)


def test_mojo_unexportable_raises_documented(tmp_path, cloud1):
    from h2o3_tpu.models.aggregator import H2OAggregatorEstimator

    fr = _cls_frame(300, seed=11)
    est = H2OAggregatorEstimator(target_num_exemplars=20)
    est.train(x=[f"c{i}" for i in range(4)], training_frame=fr)
    with pytest.raises(TypeError, match="docs/mojo.md"):
        h2o.save_model(est, str(tmp_path))


def test_mojo_gam_carries_spline_basis(tmp_path, cloud1):
    """VERDICT r04 #6: the GAM artifact scores NEW data offline with the
    same spline basis (knots + centering) the cluster fit — not just the
    inner GLM."""
    from h2o3_tpu.models.gam import H2OGeneralizedAdditiveEstimator

    rng = np.random.default_rng(2)
    n = 800
    X = rng.normal(size=(n, 3))
    y = (np.sin(X[:, 0] * 2) + 0.5 * X[:, 1] + 0.3 * rng.normal(size=n) > 0)
    d = {f"c{i}": X[:, i] for i in range(3)}
    d["y"] = y.astype(int).astype(str)
    fr = h2o.H2OFrame_from_python(d, column_types={"y": "enum"})
    est = H2OGeneralizedAdditiveEstimator(
        family="binomial", gam_columns=["c0"], num_knots=[6])
    est.train(x=["c1", "c2"], y="y", training_frame=fr)
    sc = _roundtrip(est, tmp_path)
    # NEW data — the basis must transfer, not just memorized training rows
    Xn = rng.normal(size=(300, 3))
    fn = h2o.H2OFrame_from_python({f"c{i}": Xn[:, i] for i in range(3)})
    live = est.model.predict(fn)
    mojo = sc.predict(fn)
    np.testing.assert_allclose(mojo.vec("1").numeric_np(),
                               live.vec("1").numeric_np(),
                               rtol=1e-5, atol=1e-6)
    assert list(mojo.names) == list(live.names)


def test_mojo_upliftdrf(tmp_path, cloud1):
    """UpliftDRF artifact: offline uplift_predict ≡ in-cluster on new
    rows (upstream genmodel uplift scoring)."""
    from h2o3_tpu.models.uplift import H2OUpliftRandomForestEstimator

    rng = np.random.default_rng(4)
    n = 1200
    X = rng.normal(size=(n, 4))
    treat = rng.integers(0, 2, n)
    # treatment helps when c0 > 0
    p = 0.3 + 0.3 * treat * (X[:, 0] > 0) + 0.1 * (X[:, 1] > 0)
    y = (rng.random(n) < p).astype(int)
    d = {f"c{i}": X[:, i] for i in range(4)}
    d["treatment"] = np.asarray(["control", "treatment"],
                                dtype=object)[treat]
    d["y"] = y.astype(str)
    fr = h2o.H2OFrame_from_python(
        d, column_types={"y": "enum", "treatment": "enum"})
    est = H2OUpliftRandomForestEstimator(
        treatment_column="treatment", ntrees=10, max_depth=5, seed=7)
    est.train(x=[f"c{i}" for i in range(4)], y="y", training_frame=fr)
    sc = _roundtrip(est, tmp_path)
    Xn = rng.normal(size=(300, 4))
    fn = h2o.H2OFrame_from_python({f"c{i}": Xn[:, i] for i in range(4)})
    live = est.model.predict(fn)
    mojo = sc.predict(fn)
    np.testing.assert_allclose(mojo.vec("uplift_predict").numeric_np(),
                               live.vec("uplift_predict").numeric_np(),
                               rtol=1e-5, atol=1e-6)
