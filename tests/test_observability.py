"""Observability spine (ISSUE 6) — central metrics registry, request/job
tracing, /3/Metrics Prometheus exposition, /3/Trace Chrome-trace export,
XLA retrace counters, bounded /3/Timeline tailing, open-loop loadgen.

The acceptance pins live here: end-to-end trace-id propagation (client →
REST → Job → trainpool candidate → serving batch under ONE trace id),
Prometheus text validity (unique families, HELP/TYPE lines, monotone
counters), histogram percentiles vs a numpy reference, warm-path
zero-new-traces counter pins, and the metrics-consistency check that
makes it impossible to ship a REST counter outside the scrape surface.
"""

import json
import re
import threading
import urllib.request

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.runtime import metrics_registry as registry
from h2o3_tpu.runtime import phases, tracing
from h2o3_tpu.runtime.dkv import DKV
from h2o3_tpu.runtime.metrics_registry import (LATENCY_MS_BOUNDS, Counter,
                                               Gauge, Histogram)
from h2o3_tpu.runtime.timeline import Timeline


# -- registry primitives ------------------------------------------------------

def test_counter_monotone_and_labels():
    c = registry.counter("h2o3_test_obs_events", "test events",
                         labelnames=("kind",))
    v0 = c.value("a")
    c.inc(1, "a")
    c.inc(2.5, "a")
    c.inc(1, "b")
    assert c.value("a") == pytest.approx(v0 + 3.5)
    assert c.total() >= c.value("a") + c.value("b") - 1e-9
    with pytest.raises(ValueError):
        c.inc(-1, "a")                      # counters only go up
    # idempotent by name, kind conflicts rejected
    assert registry.counter("h2o3_test_obs_events") is c
    with pytest.raises(ValueError):
        registry.gauge("h2o3_test_obs_events")


def test_gauge_set_and_callback():
    g = registry.gauge("h2o3_test_obs_level", "a level")
    g.set(7.5)
    assert g.value() == 7.5
    g.set(3.0)                              # gauges go both ways
    assert g.value() == 3.0
    cb = registry.gauge("h2o3_test_obs_cb", "sampled", fn=lambda: 42.0)
    assert cb.value() == 42.0
    assert "h2o3_test_obs_cb 42" in registry.prometheus_text()


def test_histogram_percentiles_vs_numpy():
    """Bucket-interpolated percentile estimates must land inside the
    bucket that holds the exact numpy percentile — the histogram state is
    O(bounds), so bucket resolution is the contract, not exactness."""
    rng = np.random.default_rng(42)
    vals = rng.lognormal(mean=3.0, sigma=1.2, size=5000)   # ~1..1000 ms
    h = Histogram("local_pctl_test", "unregistered", bounds=LATENCY_MS_BOUNDS)
    for v in vals:
        h.observe(float(v))
    bounds = (0.0,) + tuple(LATENCY_MS_BOUNDS) + (float("inf"),)
    for q in (0.50, 0.95, 0.99):
        ref = float(np.percentile(vals, q * 100))
        est = h.percentile(q)
        i = next(k for k in range(len(bounds) - 1)
                 if bounds[k] < ref <= bounds[k + 1] or bounds[k + 1] == ref)
        lo, hi = bounds[i], min(bounds[i + 1], float(np.max(vals)))
        assert lo <= est <= hi + 1e-9, (q, ref, est, (lo, hi))
    s = h.summary()
    assert s["count"] == 5000
    assert s["p50"] <= s["p95"] <= s["p99"]
    assert s["min"] == pytest.approx(float(np.min(vals)))
    assert s["max"] == pytest.approx(float(np.max(vals)))


def test_histogram_percentile_edge_cases():
    h = Histogram("local_pctl_edge", "x", bounds=(1.0, 10.0))
    assert h.percentile(0.5) is None        # empty
    h.observe(5.0)
    assert 1.0 <= h.percentile(0.5) <= 5.0  # single value clamps to max
    h2 = Histogram("local_pctl_over", "x", bounds=(1.0,))
    for v in (50.0, 60.0, 70.0):
        h2.observe(v)                       # all overflow bucket
    assert 50.0 <= h2.percentile(0.99) <= 70.0


def test_label_cardinality_caps_at_overflow_series():
    """Past H2O3_METRICS_MAX_SERIES distinct label tuples, new labels
    collapse into one `_overflow` series — model churn on a long-lived
    fleet cannot grow the registry or the scrape body without bound."""
    c = registry.counter("h2o3_test_obs_churn", "churny",
                         labelnames=("model",))
    cap = registry._MAX_SERIES
    for i in range(cap + 50):
        c.inc(1, f"model_{i:04d}")
    kids = c.children()
    assert len(kids) <= cap + 1              # the cap + one overflow child
    assert (registry._OVERFLOW,) in kids
    assert c.value(registry._OVERFLOW) >= 50.0
    assert c.total() == pytest.approx(cap + 50)   # totals stay correct
    # an existing series keeps its own child past the cap
    c.inc(1, "model_0000")
    assert c.value("model_0000") == 2.0


def test_counter_rate_window():
    c = registry.counter("h2o3_test_obs_rate", "rated")
    assert c.rate(60.0) is None             # no samples yet
    c.inc(5)                                # first ring sample
    # the ring samples at most once per interval; a second inc inside the
    # interval must not crash the rate read
    c.inc(5)
    assert c.rate(60.0) is None or c.rate(60.0) >= 0.0


# -- Prometheus exposition ----------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? (NaN|[+-]Inf|-?[0-9.eE+-]+)$")


def _parse_expo(text):
    """Tiny exposition parser: {family: {"type":..., "samples": {line: v}}}."""
    fams, cur = {}, None
    for line in text.rstrip("\n").split("\n"):
        if line.startswith("# HELP "):
            cur = line.split(" ", 3)[2]
            fams.setdefault(cur, {"help": 1, "type": None, "samples": {}})
        elif line.startswith("# TYPE "):
            _, _, name, kind = line.split(" ", 3)
            assert name == cur, f"TYPE {name} not right after HELP {cur}"
            assert fams[cur]["type"] is None, f"duplicate TYPE for {name}"
            fams[cur]["type"] = kind
        else:
            assert _SAMPLE_RE.match(line), f"malformed sample line: {line!r}"
            mname = line.split("{")[0].split(" ")[0]
            base = re.sub(r"_(bucket|sum|count)$", "", mname)
            owner = (cur if cur and (mname == cur or base == cur
                                     or mname.startswith(cur)) else mname)
            fams.setdefault(owner, {"help": 0, "type": None, "samples": {}})
            key = line.rsplit(" ", 1)[0]
            v = line.rsplit(" ", 1)[1]
            fams[owner]["samples"][key] = float(
                v.replace("+Inf", "inf").replace("-Inf", "-inf"))
    return fams


def test_prometheus_exposition_validity():
    c = registry.counter("h2o3_test_expo_ops", "ops with labels",
                         labelnames=("op",))
    c.inc(3, 'we"ird\nlabel')               # escaping must round-trip
    h = registry.histogram("h2o3_test_expo_ms", "latencies",
                           bounds=(1.0, 10.0, 100.0))
    for v in (0.5, 5.0, 50.0, 500.0):
        h.observe(v)
    text = registry.prometheus_text()
    assert text.endswith("\n")
    fams = _parse_expo(text)
    # every family exactly one HELP/TYPE pair (parser asserts duplicates)
    assert fams["h2o3_test_expo_ops_total"]["type"] == "counter"
    assert fams["h2o3_test_expo_ms"]["type"] == "histogram"
    sam = fams["h2o3_test_expo_ms"]["samples"]
    # cumulative buckets monotone, +Inf == _count
    cum = [sam[k] for k in sorted(sam) if "_bucket" in k and "+Inf" not in k]
    assert cum == sorted(cum)
    inf_key = next(k for k in sam if "+Inf" in k)
    count_key = next(k for k in sam if k.endswith("_count"))
    assert sam[inf_key] == sam[count_key] == 4
    # label escaping survived
    assert r'op="we\"ird\nlabel"' in text


def test_prometheus_counters_monotone_across_scrapes():
    c = registry.counter("h2o3_test_expo_mono", "monotone")
    c.inc(1)
    t1 = _parse_expo(registry.prometheus_text())
    c.inc(2)
    t2 = _parse_expo(registry.prometheus_text())
    for fam, d in t1.items():
        if d["type"] != "counter" or fam not in t2:
            continue
        for k, v in d["samples"].items():
            if k in t2[fam]["samples"]:
                assert t2[fam]["samples"][k] >= v, (fam, k)


# -- tracing engine -----------------------------------------------------------

def test_span_nesting_parents_and_chrome_export():
    tracing.clear()
    with tracing.span("outer", kind="request") as outer:
        tid = outer.trace_id
        with tracing.span("inner", kind="job") as inner:
            assert inner.trace_id == tid
            assert inner.parent_id == outer.span_id
            tracing.event("retry", policy="client")
        assert tracing.current() is outer
    assert tracing.current() is None
    out = tracing.export_chrome(tid)
    evs = [e for e in out["traceEvents"] if e.get("ph") == "X"]
    assert {e["name"] for e in evs} == {"outer", "inner"}
    assert all(e["args"]["trace_id"] == tid for e in evs)
    assert any(e["ph"] == "i" and e["name"] == "retry"
               for e in out["traceEvents"])
    assert any(e["ph"] == "M" for e in out["traceEvents"])  # thread names


def test_attach_cross_thread_and_record_span():
    tracing.clear()
    with tracing.span("root", kind="request") as root:
        tid, pid = root.trace_id, root.span_id

        def worker():
            with tracing.attach(tid, pid, name="hop", kind="job"):
                tracing.record_span("retro", 0.25, kind="ingest", rows=10)

        t = threading.Thread(target=worker)
        t.start()
        t.join()
    spans = tracing.spans(trace_id=tid)
    by_name = {s["name"]: s for s in spans}
    assert by_name["hop"]["parent_id"] == pid
    assert by_name["retro"]["parent_id"] == by_name["hop"]["span_id"]
    assert by_name["retro"]["duration_s"] == pytest.approx(0.25)
    # attach with no trace id is a recorded no-op
    with tracing.attach(None) as sp:
        assert sp is None


def test_span_error_annotation_and_ring_bound():
    tracing.clear()
    with pytest.raises(RuntimeError):
        with tracing.span("boom"):
            raise RuntimeError("kaput")
    (sp,) = tracing.spans(n=1)
    assert "RuntimeError: kaput" in sp["attrs"]["error"]
    for i in range(5000):
        tracing.record_span(f"s{i}", 0.0)
    assert tracing.span_count() <= 4096      # bounded ring, oldest evicted


def test_timeline_bounded_and_since_cursor():
    Timeline.clear()
    Timeline.record("test", "a")
    c1 = Timeline.cursor()
    Timeline.record("test", "b")
    Timeline.record("test", "c")
    tail = Timeline.snapshot(since=c1)
    assert [e["detail"] for e in tail] == ["b", "c"]
    assert all(e["seq"] > c1 for e in tail)
    assert Timeline.cursor() == c1 + 2
    for i in range(6000):
        Timeline.record("flood", str(i))
    assert len(Timeline.snapshot(n=100_000)) <= 4096   # ring stays bounded
    assert Timeline.cursor() == c1 + 2 + 6000          # cursor still exact
    Timeline.clear()


# -- XLA retrace tracker ------------------------------------------------------

def test_xla_tracker_counts_and_retrace_detection():
    phases.install_listener()
    before = phases.xla_counts()
    sig = "test:retrace_probe"
    phases._xla_count("traces", sig)
    phases._xla_count("traces", sig)         # same signature → retrace
    after = phases.xla_counts()
    assert after["traces"] == before["traces"] + 2
    assert after["retraces"] == before["retraces"] + 1
    snap = phases.xla_snapshot()
    assert snap["signatures"][sig]["traces"] == 2
    assert snap["signatures"][sig]["retraces"] == 1
    # the registry fold moved too
    assert registry.get("h2o3_xla_retraces").total() >= 1


def test_xla_signature_is_program_identity_not_span_name(cloud1):
    """Two different shape-bucket programs of one function, traced under
    ONE span, are distinct first traces (no fabricated retrace); the same
    program genuinely re-traced is counted no matter which span is open.
    Signatures come from jax's own emission-site locals (fun_name +
    input-avals digest), not from whatever span happens to be open."""
    import jax
    import jax.numpy as jnp

    phases.install_listener()

    def obs_sig_probe(x):
        return x * 2.0 + 1.0

    f = jax.jit(obs_sig_probe)
    before = phases.xla_counts()
    with tracing.span("batch:one_model", kind="batch"):
        f(jnp.zeros((4,), jnp.float32)).block_until_ready()
        f(jnp.zeros((8,), jnp.float32)).block_until_ready()  # new bucket
    mid = phases.xla_counts()
    assert mid["traces"] >= before["traces"] + 2
    assert mid["retraces"] == before["retraces"], \
        "cold shape buckets under one span fabricated a retrace"
    sigs = [s for s in phases.xla_snapshot()["signatures"]
            if s.startswith("obs_sig_probe")]
    assert len(sigs) >= 2                   # per-avals identity
    # a genuine retrace (cache dropped, same program+shape) IS counted,
    # under a differently-named span
    jax.clear_caches()
    with tracing.span("candidate:other_name", kind="candidate") as sp:
        f(jnp.zeros((4,), jnp.float32)).block_until_ready()
    after = phases.xla_counts()
    assert after["retraces"] >= mid["retraces"] + 1, \
        "a real retrace under a new span name went uncounted"
    # the span got the event as an annotation (correlation without
    # leaking span names into program identity)
    assert any(ev["name"] == "xla_retrace" for ev in sp.events)


def test_cached_sweep_fit_records_zero_new_traces(cloud1):
    """Acceptance pin: a repeat sweep fit over cached programs must not
    trace a single new XLA program — the PR 4 'warm cache never
    re-traces' invariant as a counter, not a monkeypatch."""
    phases.install_listener()
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
    from h2o3_tpu.models.grid import H2OGridSearch

    rng = np.random.default_rng(3)
    n = 200
    X = rng.normal(size=(n, 3))
    y = (X[:, 0] - X[:, 1] > 0).astype(np.int64)
    fr = Frame.from_dict(
        {"a": X[:, 0], "b": X[:, 1], "c": X[:, 2],
         "y": np.asarray(["n", "p"], dtype=object)[y]},
        column_types={"y": "enum"})

    def fit():
        g = H2OGridSearch(
            H2OGradientBoostingEstimator(ntrees=2, seed=1),
            {"max_depth": [2, 3]})
        g.train(x=["a", "b", "c"], y="y", training_frame=fr)
        assert len(g.models) == 2

    fit()                                   # cold: traces/compiles happen
    warm0 = phases.xla_counts()
    fit()                                   # warm: every program cached
    warm1 = phases.xla_counts()
    assert warm1["traces"] == warm0["traces"], \
        f"cached sweep re-traced: {warm0} -> {warm1}"
    assert warm1["retraces"] == warm0["retraces"]


# -- REST surfaces ------------------------------------------------------------

@pytest.fixture(scope="module")
def obs_server():
    from h2o3_tpu.rest import start_server
    from h2o3_tpu.serving import reset_engine

    srv = start_server(port=0)
    engine = reset_engine()
    yield srv
    srv.stop()
    reset_engine()


def _http(method, port, path, headers=None, body=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=(body if body is not None
              else (b"" if method == "POST" else None)),
        method=method, headers=headers or {})
    with urllib.request.urlopen(req) as r:
        raw = r.read()
        ctype = r.headers.get("Content-Type", "")
        out = raw if "json" not in ctype else json.loads(raw)
        return out, dict(r.headers)


def _tiny_frame(key, n=200, seed=7):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 3))
    y = (X[:, 0] + X[:, 1] > 0).astype(np.int64)
    fr = Frame.from_dict(
        {"a": X[:, 0], "b": X[:, 1], "c": X[:, 2],
         "y": np.asarray(["n", "p"], dtype=object)[y]},
        column_types={"y": "enum"})
    fr.key = key
    DKV.put(key, fr)
    return fr


def _register_all_subsystems():
    """Force-register every subsystem's registry families (they register
    lazily on first record; the scrape/consistency checks need the
    declarations, not traffic)."""
    from h2o3_tpu.frame import ingest_stats, munge_stats
    from h2o3_tpu.parallel import mesh
    from h2o3_tpu.runtime import faults, fleet, memory_ledger, retry, \
        supervisor, trainpool
    from h2o3_tpu.serving import metrics as serving_metrics
    from h2o3_tpu.serving import router

    serving_metrics._registry()
    router._router_registry()  # router families + /3/Router bindings
    ingest_stats._registry()
    munge_stats._registry()
    trainpool._registry()
    retry._reg_counter()
    faults._fired_counter(registry)
    memory_ledger._registry()
    fleet._registry()          # fleet families + /3/Fleet bindings
    mesh._lane_registry()      # collective-skew/straggler families
    supervisor._registry()     # supervisor families + /3/Supervisor bindings


def test_rest_metrics_prometheus_endpoint(obs_server, cloud1):
    """Acceptance: GET /3/Metrics serves valid Prometheus text covering
    serving, ingest, munge, training, retry, and fault counters."""
    _register_all_subsystems()
    _http("GET", obs_server.port, "/3/Cloud")   # at least one request done
    body, headers = _http("GET", obs_server.port, "/3/Metrics")
    assert headers["Content-Type"].startswith("text/plain")
    assert "version=0.0.4" in headers["Content-Type"]
    text = body.decode()
    fams = _parse_expo(text)                 # parses clean
    for needle in ("h2o3_serving_requests_total", "h2o3_ingest_rows_total",
                   "h2o3_munge_ops", "h2o3_train_submitted_total",
                   "h2o3_retry_events", "h2o3_fault_fires",
                   "h2o3_rest_requests_total", "h2o3_xla_retraces",
                   "h2o3_rest_request_ms_bucket"):
        assert needle in text, f"{needle} missing from /3/Metrics"
    # the scrape itself is counted: a second scrape sees the first
    body2, _ = _http("GET", obs_server.port, "/3/Metrics")
    assert 'handler="metrics"' in body2.decode()
    assert fams  # non-empty
    # ?schema=1 returns the ObservabilityV3 field metadata as JSON (the
    # sibling /3/*/metrics convention), also folded into /3/Metadata
    doc, _ = _http("GET", obs_server.port, "/3/Metrics?schema=1")
    assert doc["name"] == "ObservabilityV3" and doc["fields"]
    meta, _ = _http("GET", obs_server.port, "/3/Metadata/schemas")
    assert any(s.get("name") == "ObservabilityV3"
               for s in meta["schemas"])


def test_rest_trace_header_echo_and_server_mint(obs_server, cloud1):
    tid = tracing.new_trace_id()
    _, headers = _http("GET", obs_server.port, "/3/Cloud",
                       headers={"X-H2O3-Trace-Id": tid})
    assert headers.get("X-H2O3-Trace-Id") == tid       # client id echoed
    _, headers2 = _http("GET", obs_server.port, "/3/Cloud")
    minted = headers2.get("X-H2O3-Trace-Id")
    assert minted and minted != tid                    # server minted one
    out, _ = _http("GET", obs_server.port, f"/3/Trace?trace_id={tid}")
    evs = [e for e in out["traceEvents"] if e.get("ph") == "X"]
    assert len(evs) == 1 and evs[0]["cat"] == "request"
    assert evs[0]["args"]["trace_id"] == tid


def test_rest_timeline_since_cursor_tailing(obs_server, cloud1):
    out1, _ = _http("GET", obs_server.port, "/3/Timeline")
    cur = out1["cursor"]
    assert "spans" in out1                   # recent span summaries fold in
    _http("GET", obs_server.port, "/3/Cloud")          # records an event
    out2, _ = _http("GET", obs_server.port, f"/3/Timeline?since={cur}")
    assert out2["cursor"] > cur
    assert out2["events"], "incremental tail missed the new event"
    assert all(e["seq"] > cur for e in out2["events"])
    # n= caps the page
    out3, _ = _http("GET", obs_server.port, "/3/Timeline?n=1")
    assert len(out3["events"]) <= 1
    # n=0 clamps to 1: it must not dump the whole ring, and with since=
    # it must not return an empty page whose cursor skips unread events
    out4, _ = _http("GET", obs_server.port, "/3/Timeline?n=0")
    assert len(out4["events"]) <= 1
    out5, _ = _http("GET", obs_server.port,
                    f"/3/Timeline?since={cur}&n=0")
    assert out5["events"] and out5["cursor"] == out5["events"][-1]["seq"]


def test_trace_id_propagation_client_job_candidate_batch(obs_server, cloud1):
    """THE tentpole acceptance pin: one client-minted trace id correlates
    the REST request spans, the training Job span, every trainpool
    candidate span, and the serving batch span of the follow-up predict."""
    from h2o3_tpu.client import H2OConnection

    fr = _tiny_frame("obs_e2e_fr")
    conn = H2OConnection(f"http://127.0.0.1:{obs_server.port}")
    with conn.trace() as tid:
        r = conn.post("/99/Grid/gbm", training_frame=fr.key,
                      response_column="y",
                      hyper_parameters=json.dumps({"max_depth": [2, 3]}),
                      ntrees=2, seed=1, parallelism=2)
        job_key = r["job"]["key"]["name"]
        conn.wait_for_job(job_key, timeout=300.0)
        grid = DKV.get(DKV.get(job_key).result)   # in-process server: DKV
        mid = grid.models[0].model.model_id
        conn.post(f"/3/Predictions/models/{mid}/frames/{fr.key}")
    # a request span records when the HANDLER finishes writing the
    # response, which legitimately races the client's next request — poll
    # briefly until the final request span (the batch span's parent) has
    # landed in the ring before pinning the tree shape
    import time as _time

    deadline = _time.time() + 5.0
    while True:
        out, _ = _http("GET", obs_server.port, f"/3/Trace?trace_id={tid}")
        evs = [e for e in out["traceEvents"] if e.get("ph") == "X"]
        _ids = {e["args"]["span_id"] for e in evs}
        if all(e["args"]["parent_id"] in _ids for e in evs
               if e["args"]["parent_id"] is not None) \
                or _time.time() > deadline:
            break
        _time.sleep(0.05)
    kinds = {e["cat"] for e in evs}
    assert {"request", "job", "candidate", "batch"} <= kinds, kinds
    assert all(e["args"]["trace_id"] == tid for e in evs)
    # both grid candidates landed in the one trace
    cands = [e for e in evs if e["cat"] == "candidate"]
    assert len(cands) == 2
    # spans parent into a single tree: every non-root span's parent exists
    ids = {e["args"]["span_id"] for e in evs}
    roots = [e for e in evs if e["args"]["parent_id"] is None]
    non_roots = [e for e in evs if e["args"]["parent_id"] is not None]
    assert roots and non_roots
    assert all(e["args"]["parent_id"] in ids for e in non_roots)


def test_rest_warm_predict_zero_new_traces_pin(obs_server, cloud1):
    """Acceptance: warm-cache predict records ZERO new XLA traces — the
    counter pin that replaces monkeypatch-based no-retrace assertions."""
    fr = _tiny_frame("obs_warm_fr", seed=11)
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

    est = H2OGradientBoostingEstimator(ntrees=2, max_depth=2, seed=1,
                                       model_id="obs_warm_gbm")
    est.train(x=["a", "b", "c"], y="y", training_frame=fr)
    DKV.put("obs_warm_gbm", est.model)
    _http("POST", obs_server.port,
          f"/3/Predictions/models/obs_warm_gbm/frames/{fr.key}")
    c1 = phases.xla_counts()
    _http("POST", obs_server.port,
          f"/3/Predictions/models/obs_warm_gbm/frames/{fr.key}")
    c2 = phases.xla_counts()
    assert c2["traces"] == c1["traces"], "warm predict traced a program!"
    assert c2["retraces"] == c1["retraces"]
    assert c2["compiles"] == c1["compiles"]


def test_metrics_consistency_registry_backs_every_rest_field(
        obs_server, cloud1):
    """CI check (ISSUE 6 satellite): every registered metric appears in
    GET /3/Metrics, every declared REST binding resolves to a live
    registry metric, and every counter-ish `totals`/`cv` field of every
    /3/*/metrics document is declared — a new counter cannot ship outside
    the scrape surface."""
    from h2o3_tpu.rest import schemas

    _register_all_subsystems()
    text = _http("GET", obs_server.port, "/3/Metrics")[0].decode()
    # 1) every registered family reaches the scrape surface
    for name in registry.names():
        m = registry.get(name)
        expo = (name if name.endswith("_total") or m.kind != "counter"
                else name + "_total")
        assert f"# TYPE {expo} {m.kind}" in text, \
            f"registered metric {name} missing from /3/Metrics"
    # 2) every declared binding points at a live metric
    bindings = registry.rest_bindings()
    for endpoint, fields in bindings.items():
        for path, metric in fields.items():
            assert registry.get(metric) is not None, \
                f"{endpoint}:{path} bound to unknown metric {metric}"
    # 3) every counter-ish field of every metrics document is declared
    derived = ("_per_s",)                    # ratios derived at read time
    for endpoint, route in schemas.METRICS_ENDPOINTS.items():
        doc, _ = _http("GET", obs_server.port, route)
        declared = bindings.get(endpoint, {})
        for section in ("totals", "cv"):
            for k, v in (doc.get(section) or {}).items():
                if not isinstance(v, (int, float)):
                    continue
                if any(k.endswith(sfx) for sfx in derived):
                    continue
                assert f"{section}.{k}" in declared, \
                    (f"/3/{endpoint} field {section}.{k} is not "
                     f"registry-backed (bind_rest_field missing)")


def test_profiler_folds_registry_xla_and_tracing(obs_server, cloud1):
    doc, _ = _http("GET", obs_server.port, "/3/Profiler")
    assert "totals" in doc["xla"]
    assert "retraces" in doc["xla"]["totals"]
    assert "recorded" in doc["tracing"]
    # the registry fold is served under /3/Profiler too (the documented
    # contract of metrics_registry.snapshot())
    assert any(k.startswith("h2o3_rest_requests") for k in doc["metrics"])
    fam = doc["metrics"]["h2o3_rest_requests"]
    assert fam["kind"] == "counter" and fam["series"]


def test_fault_fire_annotates_span_and_registry(cloud1):
    from h2o3_tpu.runtime import faults

    tracing.clear()
    faults.arm("client.request", error="conn", rate=1.0, seed=1)
    try:
        with tracing.span("req", kind="request") as sp:
            with pytest.raises(Exception):
                faults.check("client.request", "unit")
        assert any(ev["name"] == "fault_fired" for ev in sp.events)
        assert registry.get("h2o3_fault_fires").value("client.request") >= 1
    finally:
        faults.reset()


def test_retry_bump_feeds_registry_and_span_event(cloud1):
    from h2o3_tpu.runtime import retry

    before = registry.get("h2o3_retry_events")
    before_v = before.value("unit_test_policy", "retries") if before else 0
    with tracing.span("op") as sp:
        retry.record("unit_test_policy", "retries")
    c = registry.get("h2o3_retry_events")
    assert c.value("unit_test_policy", "retries") == before_v + 1
    assert any(ev["name"] == "retry" for ev in sp.events)


# -- open-loop loadgen --------------------------------------------------------

def test_loadgen_open_loop_percentiles(obs_server, cloud1):
    import os
    import sys

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "deploy"))
    from loadgen import run_load_open

    fr = _tiny_frame("obs_lg_fr", n=64, seed=5)
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

    est = H2OGradientBoostingEstimator(ntrees=2, max_depth=2, seed=1,
                                       model_id="obs_lg_gbm")
    est.train(x=["a", "b", "c"], y="y", training_frame=fr)
    DKV.put("obs_lg_gbm", est.model)
    stats = run_load_open("127.0.0.1", obs_server.port, "obs_lg_gbm",
                          "obs_lg_fr", rate=10.0, duration_s=1.5,
                          timeout_s=30.0)
    assert stats["completed"] >= 1
    assert stats["errors"] == 0
    assert stats["offered"] == 15
    for q in ("p50_ms", "p95_ms", "p99_ms"):
        assert stats[q] is not None and np.isfinite(stats[q])
    assert stats["p50_ms"] <= stats["p95_ms"] <= stats["p99_ms"]
    # the shared-bucket contract: bounds are the platform's latency bounds
    assert tuple(stats["hist_bounds_ms"]) == tuple(LATENCY_MS_BOUNDS)
    # every request folded into the scrapable registry family (the
    # platform is loaded in this process, so the fold is active)
    fam = registry.get("h2o3_loadgen_request_ms")
    assert fam is not None
    assert fam.summary("open")["count"] >= stats["completed"]


def test_loadgen_bounds_pinned_to_registry_bounds():
    """loadgen carries a literal copy of LATENCY_MS_BOUNDS (the standalone
    CLI must not import the platform); this pin keeps them in lockstep."""
    import os
    import sys

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "deploy"))
    import loadgen

    assert tuple(loadgen.LATENCY_MS_BOUNDS) == tuple(LATENCY_MS_BOUNDS)


def test_loadgen_cli_is_stdlib_only():
    """The standalone loadgen CLI must not drag jax/h2o3_tpu into the
    loadgen process — importing the module and resolving the registry
    fold outside the platform loads nothing beyond the stdlib."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    code = (
        "import sys, os\n"
        "sys.path.insert(0, os.path.join(%r, 'deploy'))\n"
        "import loadgen\n"
        "assert loadgen._registry_hist() is None\n"
        "assert 'jax' not in sys.modules, 'loadgen imported jax'\n"
        "assert 'h2o3_tpu' not in sys.modules, 'loadgen imported h2o3_tpu'\n"
        "assert 'numpy' not in sys.modules, 'loadgen imported numpy'\n"
        % repo)
    subprocess.run([sys.executable, "-c", code], check=True, timeout=60)
