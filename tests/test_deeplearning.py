"""DeepLearning tests — `testdir_algos/deeplearning` analog. Accuracy
targets, not trajectories (Hogwild → sync-DP semantic change, SURVEY §2.4)."""

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.deeplearning import H2ODeepLearningEstimator

from conftest import make_classification, make_regression


def test_dl_binomial(cloud1):
    X, y = make_classification(2000, 8, seed=0)
    fr = Frame.from_numpy(np.column_stack([X, y]),
                          names=[f"x{i}" for i in range(8)] + ["y"]).asfactor("y")
    dl = H2ODeepLearningEstimator(hidden=[32, 32], epochs=30, seed=1,
                                  mini_batch_size=128)
    dl.train(y="y", training_frame=fr)
    assert dl.auc() > 0.85
    pred = dl.predict(fr)
    assert pred.names == ["predict", "0", "1"]
    p1 = pred.vec("1").numeric_np()
    assert ((p1 >= 0) & (p1 <= 1)).all()


def test_dl_regression(cloud1):
    X, y = make_regression(1500, 6, seed=1, noise=0.05)
    fr = Frame.from_numpy(np.column_stack([X, y]),
                          names=[f"x{i}" for i in range(6)] + ["y"])
    dl = H2ODeepLearningEstimator(hidden=[64, 64], epochs=40, seed=2,
                                  mini_batch_size=128)
    dl.train(y="y", training_frame=fr)
    assert dl.mse() < 0.5 * float(np.var(y))


def test_dl_multinomial_tanh(cloud1):
    rng = np.random.default_rng(3)
    n = 2000
    X = rng.normal(size=(n, 4)).astype(np.float32)
    y = (np.arctan2(X[:, 0], X[:, 1]) // (2 * np.pi / 3) + 1).astype(int) % 3
    fr = Frame.from_numpy(np.column_stack([X, y]),
                          names=["a", "b", "c", "d", "y"]).asfactor("y")
    dl = H2ODeepLearningEstimator(hidden=[32], activation="Tanh", epochs=30,
                                  seed=3, mini_batch_size=128)
    dl.train(y="y", training_frame=fr)
    assert dl.model.training_metrics.accuracy > 0.8


def test_dl_dropout_and_maxout(cloud1):
    X, y = make_classification(1200, 6, seed=4)
    fr = Frame.from_numpy(np.column_stack([X, y]),
                          names=[f"x{i}" for i in range(6)] + ["y"]).asfactor("y")
    for act in ("RectifierWithDropout", "Maxout"):
        dl = H2ODeepLearningEstimator(hidden=[32], activation=act, epochs=15,
                                      seed=5, mini_batch_size=128,
                                      input_dropout_ratio=0.1)
        dl.train(y="y", training_frame=fr)
        assert dl.auc() > 0.7, act


def test_dl_momentum_sgd(cloud1):
    X, y = make_classification(1200, 6, seed=6)
    fr = Frame.from_numpy(np.column_stack([X, y]),
                          names=[f"x{i}" for i in range(6)] + ["y"]).asfactor("y")
    dl = H2ODeepLearningEstimator(hidden=[32], epochs=25, seed=7,
                                  adaptive_rate=False, rate=0.01,
                                  momentum_start=0.5, momentum_stable=0.9,
                                  mini_batch_size=128)
    dl.train(y="y", training_frame=fr)
    assert dl.auc() > 0.8


def test_dl_multichip_dp(cloud8):
    X, y = make_classification(2048, 6, seed=8)
    fr = Frame.from_numpy(np.column_stack([X, y]),
                          names=[f"x{i}" for i in range(6)] + ["y"]).asfactor("y")
    dl = H2ODeepLearningEstimator(hidden=[16], epochs=10, seed=9,
                                  mini_batch_size=256)
    dl.train(y="y", training_frame=fr)
    assert dl.auc() > 0.75


def test_dl_autoencoder_anomaly(cloud1):
    import numpy as np
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models.deeplearning import H2ODeepLearningEstimator

    rng = np.random.default_rng(0)
    X = rng.normal(size=(600, 6)).astype(np.float32)
    X[:5] += 8.0  # planted anomalies
    fr = Frame.from_numpy(X, names=[f"c{i}" for i in range(6)])
    ae = H2ODeepLearningEstimator(autoencoder=True, hidden=[3], epochs=30,
                                  mini_batch_size=64, seed=1)
    ae.train(x=fr.names, training_frame=fr)  # no y
    assert ae.model.training_metrics.mse < 1.5
    an = ae.model.anomaly(fr).vec("Reconstruction.MSE").numeric_np()
    # the planted outliers reconstruct worst
    top = np.argsort(-an)[:8]
    assert len(set(top) & set(range(5))) >= 4
    rec = ae.predict(fr)
    assert rec.ncol == 6 and rec.names[0].startswith("reconstr_")


def test_dl_trains_on_mesh_with_padding(cloud8):
    """Single-process 8-device mesh: the scan path ingests byte-compressed
    sharded packs with quota padding (n not divisible by 8) and still
    learns; padded zero-weight rows must not distort the fit."""
    import numpy as np

    import h2o3_tpu as h2o
    from h2o3_tpu.models.deeplearning import H2ODeepLearningEstimator

    rng = np.random.default_rng(0)
    n = 1999                                # forces tail padding
    X = rng.normal(size=(n, 5)).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 0).astype(int)
    d = {f"f{i}": X[:, i] for i in range(5)}
    d["y"] = y.astype(str)
    fr = h2o.H2OFrame_from_python(d, column_types={"y": "enum"})
    est = H2ODeepLearningEstimator(hidden=[16], epochs=8, seed=1,
                                   mini_batch_size=64)
    est.train(x=[f"f{i}" for i in range(5)], y="y", training_frame=fr)
    assert float(est.auc()) > 0.85
    pred = est.predict(fr)
    assert pred.nrow == n
    assert np.isfinite(pred.vec("1").numeric_np()).all()
