"""Round-4 REST hardening (VERDICT r03 #9): TLS, request-size caps, and
the next route tier (validate-parameters, MOJO download, DownloadDataset,
SplitFrame, sessions, DKV removal, capabilities). Reference:
`water/api/RequestServer.java`, `water/network/SocketChannelFactory`."""

import json
import os
import subprocess
import urllib.error
import urllib.request

import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu.rest.server import start_server
from h2o3_tpu.runtime.dkv import DKV


@pytest.fixture(scope="module")
def server():
    import jax
    from h2o3_tpu.parallel import mesh

    mesh.init(jax.devices()[:1])
    srv = start_server(port=0)
    rng = np.random.default_rng(0)
    n = 300
    X = rng.normal(size=(n, 3))
    y = (X[:, 0] > 0).astype(int)
    d = {f"c{i}": X[:, i] for i in range(3)}
    d["y"] = y.astype(str)
    fr = h2o.H2OFrame_from_python(d, column_types={"y": "enum"})
    fr.key = "hard_fr"
    DKV.put(fr.key, fr)
    yield srv, fr
    srv.stop()


def _get(srv, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{srv.port}{path}") as r:
        return json.loads(r.read())


def _post(srv, path, **params):
    import urllib.parse

    data = urllib.parse.urlencode(params).encode()
    req = urllib.request.Request(f"http://127.0.0.1:{srv.port}{path}",
                                 data=data)
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read())


def _delete(srv, path):
    req = urllib.request.Request(f"http://127.0.0.1:{srv.port}{path}",
                                 method="DELETE")
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read())


def test_validate_parameters(server):
    srv, _ = server
    ok = _post(srv, "/3/ModelBuilders/gbm/parameters", ntrees="5",
               max_depth="3")
    assert ok["error_count"] == 0
    bad = _post(srv, "/3/ModelBuilders/gbm/parameters", bogus_knob="7")
    assert bad["error_count"] == 1
    assert "bogus_knob" in bad["messages"][0]["message"]
    # value-level validation reaches the estimator's _check_params
    bad2 = _post(srv, "/3/ModelBuilders/xgboost/parameters",
                 booster="gbforest")
    assert bad2["error_count"] == 1


def test_mojo_download_roundtrip(server, tmp_path):
    srv, fr = server
    from h2o3_tpu.estimators import H2OGradientBoostingEstimator

    est = H2OGradientBoostingEstimator(ntrees=3, max_depth=3, seed=1)
    est.train(x=["c0", "c1", "c2"], y="y", training_frame=fr)
    mid = est.model_id
    DKV.put(mid, est.model)
    with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/3/Models/{mid}/mojo") as r:
        blob = r.read()
        assert r.headers["Content-Type"] == "application/zip"
    p = tmp_path / "m.zip"
    p.write_bytes(blob)
    scorer = h2o.load_model(str(p))
    np.testing.assert_allclose(
        scorer.predict(fr).vec("1").numeric_np(),
        est.predict(fr).vec("1").numeric_np(), rtol=1e-5, atol=1e-6)


def test_download_dataset_csv(server):
    srv, fr = server
    with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/3/DownloadDataset"
            f"?frame_id=hard_fr") as r:
        text = r.read().decode()
    lines = text.strip().splitlines()
    assert lines[0] == "c0,c1,c2,y"
    assert len(lines) == fr.nrow + 1


def test_split_frame(server):
    srv, fr = server
    out = _post(srv, "/3/SplitFrame", dataset="hard_fr",
                ratios="[0.7]", seed="42",
                destination_frames='["hard_tr", "hard_te"]')
    keys = [d["name"] for d in out["destination_frames"]]
    assert keys == ["hard_tr", "hard_te"]
    tr = _get(srv, "/3/Frames/hard_tr")["frames"][0]
    te = _get(srv, "/3/Frames/hard_te")["frames"][0]
    assert tr["rows"] + te["rows"] == fr.nrow
    assert abs(tr["rows"] / fr.nrow - 0.7) < 0.1


def test_sessions_and_dkv_routes(server):
    srv, _ = server
    sid = _post(srv, "/4/sessions")["session_key"]
    assert sid.startswith("_sid")
    assert _delete(srv, f"/4/sessions/{sid}")["session_key"] == sid
    DKV.put("doomed", {"x": 1})
    _delete(srv, "/3/DKV/doomed")
    assert DKV.get("doomed") is None


def test_capabilities_ping_logecho(server):
    srv, _ = server
    caps = {c["name"] for c in _get(srv, "/3/Capabilities")["capabilities"]}
    assert {"Algos", "AutoML", "Rapids", "MOJO"} <= caps
    assert _get(srv, "/3/Ping")["status"] == "healthy"
    assert _post(srv, "/3/LogAndEcho",
                 message="hello")["message"] == "hello"


def test_column_summary(server):
    srv, fr = server
    s = _get(srv, "/3/Frames/hard_fr/columns/c0/summary")
    col = s["frames"][0]["columns"][0]
    assert col["label"] == "c0"
    assert len(col["histogram_bins"]) == 20
    assert sum(col["histogram_bins"]) == fr.nrow
    assert len(col["percentiles"]) == 7
    se = _get(srv, "/3/Frames/hard_fr/columns/y/summary")
    ycol = se["frames"][0]["columns"][0]
    assert ycol["domain_cardinality"] == 2


def test_request_body_cap_413(server, monkeypatch):
    srv, _ = server
    monkeypatch.setenv("H2O3_MAX_BODY_MB", "1")
    big = b"x" * (2 << 20)
    req = urllib.request.Request(
        f"http://127.0.0.1:{srv.port}/3/PostFile", data=big,
        headers={"Content-Type": "application/octet-stream"})
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(req)
    assert e.value.code == 413


def test_https_e2e(tmp_path):
    """TLS end-to-end: self-signed cert, https client by URL only."""
    cert = tmp_path / "cert.pem"
    key = tmp_path / "key.pem"
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(key), "-out", str(cert), "-days", "1",
         "-subj", "/CN=localhost"],
        check=True, capture_output=True)
    srv = start_server(port=0, ssl_certfile=str(cert), ssl_keyfile=str(key))
    try:
        assert srv.scheme == "https"
        conn = h2o.connect(url=f"https://127.0.0.1:{srv.port}",
                           verify_ssl=False, verbose=False)
        assert conn.cluster_info()["cloud_name"] == "h2o3_tpu"
        # plain-HTTP client against the TLS port fails cleanly
        with pytest.raises(Exception):
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/3/Cloud", timeout=5)
    finally:
        h2o.shutdown()
        srv.stop()


def test_malformed_requests_never_5xx(server):
    """EVERY registered route, hit with garbage params, answers with a
    clean 2xx/3xx/4xx — never a 5xx (the round-4 hardening property,
    pinned across the full route table so new routes can't regress it)."""
    import re

    from h2o3_tpu.rest.server import _Handler
    from h2o3_tpu.runtime.dkv import DKV

    srv, _fr = server
    # the fuzz hits destructive routes too (DELETE /3/DKV clears the
    # store) — snapshot the live objects and restore them afterwards so
    # later tests keep their fixture state
    saved = {k: DKV.get(k) for k in DKV.keys()}
    garbage = {"path": "/no/such/file", "dataset": "nope", "frame_id": "nope",
               "model_id": "nope", "ast": "(((", "rows": "-3", "cols": "zz",
               "source_frames": '["zzz"]', "predictor": "zz",
               "response": "zz", "factor_columns": '["zz"]', "word": "w",
               "model": "m", "words_frame": "wf", "hyper_parameters": "{",
               "training_frame": "none", "response_column": "zz",
               "ratios": "zz", "name": "zz*bad", "dir": "/no/dir",
               "nfolds": "x", "pattern": "["}
    failures = []
    for method, rx, handler in _Handler.ROUTES:
        if handler == "shutdown":
            continue                       # would stop the shared fixture
        path = rx.strip("^$")
        path = path.replace("(?:flow(?:/index\\.html)?/?)?", "")
        path = path.replace("(?:/download)?", "")
        path = path.replace("(?:\\.bin)?", "")
        path = re.sub(r"\(\[\^/\]\+\)", "zzz", path)
        path = re.sub(r"\(\\d\+\)", "1", path)
        path = path.replace("\\.", ".")
        path = path.rstrip("?").rstrip("/") or "/"   # optional trailing /
        # coverage guard: a route whose regex uses a construct this
        # templating doesn't handle would otherwise be silently skipped
        assert re.match(rx, path or "/"), (rx, path)
        url = f"http://127.0.0.1:{srv.port}{path or '/'}"
        data = None
        if method == "GET":
            url += "?" + urllib.parse.urlencode(garbage)
        else:
            data = urllib.parse.urlencode(garbage).encode()
        req = urllib.request.Request(url, data=data, method=method)
        try:
            with urllib.request.urlopen(req, timeout=120) as r:
                code = r.status
        except urllib.error.HTTPError as e:
            code = e.code
        except Exception as e:              # connection-level breakage
            failures.append((method, path, repr(e)))
            continue
        if code >= 500:
            failures.append((method, path, code))
    for k, v in saved.items():
        if DKV.get(k) is None:
            DKV.put(k, v)
    assert not failures, failures
