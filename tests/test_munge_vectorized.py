"""Vectorized munging engine (ISSUE 3) — legacy-vs-vectorized parity pins.

Every rewritten op (radix merge, apply-over-rows, factorize+scatter
pivot/table, datetime64 moment, factorized asDate) must be BIT-IDENTICAL
to the seed per-row paths, which stay reachable via ``H2O3_MUNGE_LEGACY=1``
(frame/munge_stats.legacy_enabled). The matrix covers empty frames,
duplicate keys, all-NA columns, enum domains with unused levels, mixed
enum/numeric keys (the stringify pin), and single-row frames; plus the
GroupBy NA-mode satellite (all/rm/ignore) and the munge observability
surface. Mirrors tests/test_parse_parallel.py's structure, including the
slow-marked throughput floor."""

import os
import time
from contextlib import contextmanager

import numpy as np
import pytest

from h2o3_tpu.frame import munge_stats
from h2o3_tpu.frame import rapids as R
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.rapids_expr import RapidsSession
from h2o3_tpu.runtime.dkv import DKV


@contextmanager
def legacy():
    os.environ["H2O3_MUNGE_LEGACY"] = "1"
    try:
        yield
    finally:
        os.environ.pop("H2O3_MUNGE_LEGACY", None)


def frames_equal(f1: Frame, f2: Frame):
    """Bit-exact frame comparison: names, types, enum domains, and raw
    column buffers (dtype included; NaN == NaN)."""
    assert f1.names == f2.names, (f1.names, f2.names)
    for n in f1.names:
        v1, v2 = f1.vec(n), f2.vec(n)
        assert v1.type == v2.type, (n, v1.type, v2.type)
        if v1.type == "enum":
            assert v1.domain == v2.domain, (n, v1.domain, v2.domain)
            assert np.array_equal(np.asarray(v1.data), np.asarray(v2.data)), n
        elif v1.type == "string":
            assert list(v1.to_numpy()) == list(v2.to_numpy()), n
        else:
            a, b = np.asarray(v1.data), np.asarray(v2.data)
            assert a.dtype == b.dtype, (n, a.dtype, b.dtype)
            assert np.array_equal(a, b, equal_nan=True), (n, a, b)
    return True


def both(fn):
    """(legacy result, vectorized result) of the same op."""
    with legacy():
        l = fn()
    return l, fn()


def assert_parity(fn):
    l, v = both(fn)
    frames_equal(l, v)
    return v


MERGE_MODES = [(False, False), (True, False), (False, True), (True, True)]


# -- merge parity matrix ------------------------------------------------------
def _mixed_frames(seed=0, n=300, m=200):
    rng = np.random.default_rng(seed)
    lk1 = rng.choice(["a", "b", "c", "d", "e"], n).astype(object)
    lk1[rng.random(n) < 0.1] = None
    lk2 = rng.integers(0, 5, n).astype(float)
    lk2[rng.random(n) < 0.1] = np.nan
    left = Frame.from_dict(
        {"k1": lk1, "k2": lk2, "x": rng.random(n)},
        column_types={"k1": "enum"})
    rk1 = rng.choice(["b", "c", "d", "zz"], m).astype(object)
    rk1[rng.random(m) < 0.1] = None
    rk2 = rng.integers(0, 6, m).astype(float)
    rk2[rng.random(m) < 0.05] = np.nan
    right = Frame.from_dict(
        {"k1": rk1, "k2": rk2, "y": rng.random(m), "x": rng.random(m)},
        column_types={"k1": "enum"})
    return left, right


@pytest.mark.parametrize("all_x,all_y", MERGE_MODES)
def test_merge_two_key_enum_numeric_parity(cloud1, all_x, all_y):
    """Two-key (enum + numeric) join with NA keys, duplicate keys on both
    sides, and a non-key name collision ('x' exists on both sides)."""
    left, right = _mixed_frames()
    out = assert_parity(lambda: R.merge(left, right, by=["k1", "k2"],
                                        all_x=all_x, all_y=all_y))
    assert out.nrow > 0
    assert "x" in out.names and "x0" in out.names  # h2o dedup convention


@pytest.mark.parametrize("all_x,all_y", MERGE_MODES)
def test_merge_numeric_dup_keys_parity(cloud1, all_x, all_y):
    left = Frame.from_dict({"k": [1.0, 2.0, 2.0, 3.0, np.nan],
                            "a": [10.0, 20.0, 21.0, 30.0, 40.0]})
    right = Frame.from_dict({"k": [2.0, 2.0, 4.0, np.nan],
                             "b": [200.0, 201.0, 400.0, 500.0]})
    assert_parity(lambda: R.merge(left, right, all_x=all_x, all_y=all_y))


def test_merge_match_order_matches_seed(cloud1):
    """Duplicate right keys emit in ascending right-row order per left
    row, left rows in order — exactly the seed hash join's output order."""
    left = Frame.from_dict({"k": [2.0, 1.0, 2.0], "a": [0.0, 1.0, 2.0]})
    right = Frame.from_dict({"k": [2.0, 3.0, 2.0], "b": [10.0, 20.0, 30.0]})
    out = R.merge(left, right)
    assert list(out.vec("a").numeric_np()) == [0.0, 0.0, 2.0, 2.0]
    assert list(out.vec("b").numeric_np()) == [10.0, 30.0, 10.0, 30.0]


def test_merge_na_key_semantics_pinned(cloud1):
    """Numeric NaN keys never match (NaN != NaN in the seed's tuple join);
    categorical NA keys DO match each other (both decode to the None
    label, and None == None). Pinned on both paths."""
    left = Frame.from_dict({"k": [1.0, np.nan], "a": [10.0, 20.0]})
    right = Frame.from_dict({"k": [np.nan, 1.0], "b": [100.0, 200.0]})
    l, v = both(lambda: R.merge(left, right))
    frames_equal(l, v)
    assert v.nrow == 1 and list(v.vec("b").numeric_np()) == [200.0]

    eleft = Frame.from_dict({"k": np.asarray(["x", None], object),
                             "a": [1.0, 2.0]}, column_types={"k": "enum"})
    eright = Frame.from_dict({"k": np.asarray([None, "x"], object),
                              "b": [10.0, 20.0]}, column_types={"k": "enum"})
    l, v = both(lambda: R.merge(eleft, eright))
    frames_equal(l, v)
    assert v.nrow == 2  # the NA-label row matched the NA-label row


def test_merge_enum_unused_levels_parity(cloud1):
    """Enum domains with unused levels and DIFFERENT domains on the two
    sides still join by label."""
    lv = np.asarray(["b", "a", "b"], object)
    rv = np.asarray(["b", "c"], object)
    left = Frame.from_dict({"k": lv, "a": [1.0, 2.0, 3.0]},
                           column_types={"k": "enum"})
    right = Frame.from_dict({"k": rv, "b": [10.0, 20.0]},
                            column_types={"k": "enum"})
    # force an unused level into the left domain
    from h2o3_tpu.frame.vec import Vec

    kv = left.vec("k")
    left["k"] = Vec(np.asarray(kv.data), "enum",
                    domain=list(kv.domain) + ["unused_lvl"])
    for all_x, all_y in MERGE_MODES:
        out = assert_parity(lambda: R.merge(left, right,
                                            all_x=all_x, all_y=all_y))
        assert out.nrow >= 2


@pytest.mark.parametrize("all_x,all_y", MERGE_MODES)
def test_merge_mixed_enum_numeric_key_parity(cloud1, all_x, all_y):
    """SATELLITE PIN (pre-rewrite semantics): an enum key column against a
    numeric key column NEVER matches (labels are strings, the seed tuple
    join compared them to floats), and right-outer rows stringify the key
    labels when the sides disagree on type."""
    left = Frame.from_dict({"k": np.asarray(["1.0", "2.0", "x"], object),
                            "a": [1.0, 2.0, 3.0]},
                           column_types={"k": "enum"})
    right = Frame.from_dict({"k": [1.0, 2.0, 3.0], "b": [10.0, 20.0, 30.0]})
    out = assert_parity(lambda: R.merge(left, right,
                                        all_x=all_x, all_y=all_y))
    inner_rows = 0
    assert out.nrow == inner_rows + (3 if all_x else 0) + (3 if all_y else 0)
    if all_y and not all_x:
        # unmatched right rows keep their keys, stringified — and because
        # every "1.0"-style label re-parses numeric, the interned output
        # column comes back numeric (seed behavior, pinned)
        assert out.vec("k").type in ("real", "int")
        assert sorted(out.vec("k").numeric_np()) == [1.0, 2.0, 3.0]
    if all_y and all_x:
        # left's unparseable "x" label keeps the stringified column enum
        assert "x" in (out.vec("k").domain or [])
        assert "3.0" in (out.vec("k").domain or [])


def test_merge_empty_and_single_row_parity(cloud1):
    empty = Frame.from_dict({"k": np.empty(0), "a": np.empty(0)})
    one = Frame.from_dict({"k": [1.0], "b": [5.0]})
    for all_x, all_y in MERGE_MODES:
        assert_parity(lambda: R.merge(empty, one, all_x=all_x, all_y=all_y))
        assert_parity(lambda: R.merge(Frame.from_dict(
            {"k": [1.0], "a": [7.0]}), one, all_x=all_x, all_y=all_y))
    # empty RIGHT with all_y adds nothing; single-row × single-row matches
    assert_parity(lambda: R.merge(one, empty.rename({"a": "c"}),
                                  all_y=True))


def test_merge_outer_against_empty_side_na_fills(cloud1):
    """Outer join against an EMPTY side NA-fills instead of the seed's
    IndexError (fixed in the shared assembly, so both paths agree)."""
    left = Frame.from_dict({"k": [1.0, 2.0], "a": [10.0, 20.0]})
    empty = Frame.from_dict({"k": np.empty(0), "b": np.empty(0)})
    out = assert_parity(lambda: R.merge(left, empty, all_x=True))
    assert out.nrow == 2
    assert np.isnan(out.vec("b").numeric_np()).all()
    assert list(out.vec("a").numeric_np()) == [10.0, 20.0]
    out2 = assert_parity(lambda: R.merge(
        empty.rename({"b": "c"}), left.rename({"a": "b"}), all_y=True))
    assert out2.nrow == 2 and np.isnan(out2.vec("c").numeric_np()).all()


def test_merge_all_na_key_column_parity(cloud1):
    left = Frame.from_dict({"k": [np.nan, np.nan], "a": [1.0, 2.0]})
    right = Frame.from_dict({"k": [np.nan, 1.0], "b": [10.0, 20.0]})
    for all_x, all_y in MERGE_MODES:
        out = assert_parity(lambda: R.merge(left, right,
                                            all_x=all_x, all_y=all_y))
        assert out.nrow == (2 if all_x else 0) + (2 if all_y else 0)


def test_merge_all_na_enum_key_empty_domain_parity(cloud1):
    """An all-NA categorical key column interns with an EMPTY domain; its
    NA labels still match the other side's NA level like the seed
    (code-review repro: the vectorized remap used to IndexError here)."""
    left = Frame.from_dict({"k": np.asarray([None, None], object),
                            "a": [1.0, 2.0]}, column_types={"k": "enum"})
    right = Frame.from_dict({"k": np.asarray([None, "x"], object),
                             "b": [10.0, 20.0]}, column_types={"k": "enum"})
    assert left.vec("k").domain in ([], None) or not left.vec("k").domain
    for all_x, all_y in MERGE_MODES:
        assert_parity(lambda: R.merge(left, right,
                                      all_x=all_x, all_y=all_y))
    out = R.merge(left, right)
    assert out.nrow == 2  # both NA-label left rows match the NA right row


def test_pivot_table_all_na_enum_empty_domain(cloud1):
    """pivot/table over an all-NA enum column (empty domain) must not
    crash the factorizer (code-review repro)."""
    fr = Frame.from_dict({"i": np.asarray([None, None], object),
                          "c": [1.0, 2.0], "v": [3.0, 4.0]},
                         column_types={"i": "enum"})
    assert_parity(lambda: fr.pivot("i", "c", "v"))
    assert_parity(lambda: fr[["i", "c"]].table())


# -- apply(axis=1) ------------------------------------------------------------
def test_apply_rows_parity_and_paths(cloud1):
    rng = np.random.default_rng(0)
    fr = Frame.from_dict({"a": rng.random(40), "b": rng.random(40)})
    munge_stats.reset()
    assert_parity(lambda: fr.apply(lambda row: row["a"] + row["b"], axis=1))
    # elementwise frame result → k output columns
    assert_parity(lambda: fr.apply(lambda row: row[["a", "b"]] * 2.0,
                                   axis=1))
    snap = munge_stats.snapshot()
    paths = snap["ops"]["apply_rows"]["paths"]
    assert paths.get("vectorized", 0) >= 2 and paths.get("legacy", 0) >= 2


def test_apply_rows_fallback_exactness(cloud1):
    """A constant-width-k array per row does NOT vectorize (the whole-frame
    result is k values, not nrow) — the engine must detect the mismatch by
    per-row probing and fall back to the exact loop."""
    fr = Frame.from_dict({"a": [1.0, 2.0], "b": [3.0, 4.0]})  # nrow == ncol
    munge_stats.reset()
    out = assert_parity(lambda: fr.apply(
        lambda row: np.asarray([1.0, 2.0]), axis=1))
    assert out.shape == (2, 2)
    assert munge_stats.snapshot()["ops"]["apply_rows"]["paths"].get(
        "fallback", 0) >= 1


def test_apply_rows_non_rowlocal_callable_falls_back(cloud1):
    """A callable that MIXES rows (reverse) must not be accepted by the
    vectorized path even when its END rows coincide — interior probe rows
    catch it and the exact loop runs (code-review repro)."""
    fr = Frame.from_dict({"a": [1.0, 5.0, 2.0, 1.0]})
    out = assert_parity(lambda: fr.apply(
        lambda f: f.vec("a").numeric_np()[::-1], axis=1))
    assert list(out._col0()) == [1.0, 5.0, 2.0, 1.0]


def test_apply_aggregate_callable_falls_back(cloud1):
    """Mean-centering with zeros planted at the fixed probe positions used
    to slip through; the column-extreme probe rows catch any aggregate-
    shifted result (code-review repro)."""
    fr = Frame.from_dict(
        {"a": [0.0, 5.0, -5.0, 0.0, 0.0, 3.0, 0.0, -3.0, 0.0]})
    out = assert_parity(lambda: fr.apply(
        lambda f: f.vec("a").numeric_np()
        - f.vec("a").numeric_np().mean(), axis=1))
    # per-row semantics: every single-row mean is the row itself → 0
    assert list(out._col0()) == [0.0] * 9


def test_apply_positional_mixing_falls_back(cloud1):
    """A sort over a nearly-sorted column fixes every probe row yet mixes
    two interior rows — the permutation-equivariance certificate rejects
    it (code-review repro: probe-only checks accepted the sorted data)."""
    fr = Frame.from_dict(
        {"x": [0.0, 1.0, 2.0, 3.0, 4.0, 5.0, 6.0, 8.0, 7.0, 9.0]})
    out = assert_parity(lambda: fr.apply(
        lambda sub: np.sort(sub.vec("x").numeric_np()), axis=1))
    # per-row semantics: sorting a single row is the identity
    assert list(out._col0()) == [0.0, 1.0, 2.0, 3.0, 4.0, 5.0, 6.0,
                                 8.0, 7.0, 9.0]


def test_apply_late_column_aggregate_falls_back(cloud1):
    """Every column contributes its extreme rows to the probe set — an
    aggregate-shifted callable reading a LATE column with zeros planted
    at the fixed probe rows must still be caught (code-review repro)."""
    n = 100
    cols = {k: np.ones(n) for k in ("a", "b", "c", "d")}
    e = np.zeros(n)
    planted = {0, n // 3, n // 2, (2 * n) // 3, n - 1, 0, 0}
    for i in range(n):
        if i not in planted:
            e[i] = float(i)
    cols["e"] = e
    fr = Frame.from_dict(cols)
    out = assert_parity(lambda: fr.apply(
        lambda r: r.vec("e").numeric_np() * r.nrow, axis=1))
    # per-row semantics: r.nrow == 1, so the output is just column e
    assert np.array_equal(out._col0(), e)


def test_group_by_count_na_rm_string_column(cloud1):
    """nrow na='rm' over a STRING column counts non-None rows instead of
    crashing on the missing numeric view (code-review repro)."""
    from h2o3_tpu.frame.vec import Vec

    fr = Frame({"g": Vec.from_numpy(
        np.asarray(["a", "a", "b"], object), "enum"),
        "s": Vec(None, "string",
                 strings=np.asarray(["x", None, "y"], object))})
    fr.group_by("g")._aggs.append(("count", "s", "rm"))
    d = fr.group_by("g")
    d._aggs.append(("count", "s", "rm"))
    out = d.get_frame().as_data_frame(use_pandas=False)
    assert out["nrow"][list(out["g"]).index("a")] == 1.0
    assert out["nrow"][list(out["g"]).index("b")] == 1.0


def test_apply_mutating_callable_does_not_corrupt_frame(cloud1):
    """The vectorized trial eval hands the callable a COPY — a callable
    that writes into its argument must not corrupt the source frame
    (code-review repro; the seed only passed throwaway row frames)."""
    fr = Frame.from_dict({"a": [1.0, 2.0, 3.0]})

    def evil(row):
        np.asarray(row.vec("a").data)[:] = 0.0
        return 99.0

    out = assert_parity(lambda: fr.apply(evil, axis=1))
    assert list(fr.vec("a").numeric_np()) == [1.0, 2.0, 3.0]
    assert list(out._col0()) == [99.0, 99.0, 99.0]


def test_apply_single_row_frame_parity(cloud1):
    fr = Frame.from_dict({"a": [2.0], "b": [3.0]})
    out = assert_parity(lambda: fr.apply(lambda row: row["a"] * row["b"],
                                         axis=1))
    assert out.nrow == 1 and float(out._col0()[0]) == 6.0


def test_apply_empty_frame_raises_both_paths(cloud1):
    fr = Frame.from_dict({"a": np.empty(0), "b": np.empty(0)})
    munge_stats.reset()
    with pytest.raises(IndexError):
        fr.apply(lambda row: row["a"] + row["b"], axis=1)
    with legacy():
        with pytest.raises(IndexError):
            fr.apply(lambda row: row["a"] + row["b"], axis=1)
    # the raising calls book as ERRORS, never as successful ops
    assert munge_stats.snapshot()["ops"]["apply_rows"]["errors"] == 2


# -- pivot / table ------------------------------------------------------------
def _pivot_frame(seed=1, n=300):
    rng = np.random.default_rng(seed)
    idx = rng.choice(["r1", "r2", "r3"], n).astype(object)
    idx[rng.random(n) < 0.05] = None
    colv = rng.integers(0, 4, n).astype(float)
    colv[rng.random(n) < 0.05] = np.nan
    return Frame.from_dict({"i": idx, "c": colv, "v": rng.random(n)},
                           column_types={"i": "enum"})


def test_pivot_parity(cloud1):
    fr = _pivot_frame()
    out = assert_parity(lambda: fr.pivot("i", "c", "v"))
    assert out.shape == (3, 5) and out.names[0] == "i"
    # numeric index too (column names stringify the levels)
    fr2 = Frame.from_dict({"i": [2.0, 1.0, 2.0], "c": [0.0, 1.0, 0.0],
                           "v": [5.0, 6.0, 7.0]})
    out2 = assert_parity(lambda: fr2.pivot("i", "c", "v"))
    assert out2.names == ["i", "0.0", "1.0"]


def test_pivot_last_write_wins_parity(cloud1):
    """Duplicate (index, column) cells: the LAST row in frame order wins —
    the seed loop's overwrite semantics, reproduced by the scatter."""
    fr = Frame.from_dict({"i": [1.0, 1.0, 1.0], "c": [0.0, 0.0, 0.0],
                          "v": [7.0, 8.0, 9.0]})
    out = assert_parity(lambda: fr.pivot("i", "c", "v"))
    assert float(out.vec("0.0").numeric_np()[0]) == 9.0


def test_pivot_empty_and_single_row_parity(cloud1):
    empty = Frame.from_dict({"i": np.empty(0), "c": np.empty(0),
                             "v": np.empty(0)})
    assert_parity(lambda: empty.pivot("i", "c", "v"))
    one = Frame.from_dict({"i": [1.0], "c": [2.0], "v": [3.0]})
    out = assert_parity(lambda: one.pivot("i", "c", "v"))
    assert out.shape == (1, 2)
    # all-NA value/key columns
    alln = Frame.from_dict({"i": [np.nan, np.nan], "c": [1.0, 2.0],
                            "v": [1.0, 2.0]})
    assert_parity(lambda: alln.pivot("i", "c", "v"))


def test_table_parity(cloud1):
    fr = _pivot_frame(seed=3)
    assert_parity(lambda: fr[["i", "c"]].table())
    assert_parity(lambda: fr[["i"]].table())
    assert_parity(lambda: fr[["c"]].table())
    # two numeric columns and the empty edge
    fr2 = Frame.from_dict({"a": [1.0, 1.0, 2.0, np.nan],
                           "b": [0.0, 0.0, 1.0, 1.0]})
    out = assert_parity(lambda: fr2.table())
    assert list(out.vec("Counts").numeric_np()) == [2.0, 1.0]
    # empty 2-col frame: the seed's boolean-keep crashed on an empty mask
    # (dtype float64); the vectorized path returns the sane empty table —
    # a pinned improvement, not a parity case
    empty = Frame.from_dict({"a": np.empty(0), "b": np.empty(0)})
    out_e = empty.table()
    assert out_e.nrow == 0 and out_e.names == ["a", "b", "Counts"]
    with legacy():
        with pytest.raises(IndexError):
            empty.table()


# -- time ops -----------------------------------------------------------------
def test_moment_parity(cloud1):
    sess = RapidsSession()
    yrs = Frame.from_dict({"y": [2020.0, 2021.0, np.nan, 1800.0, 2024.9,
                                 9999.0, 1.0, -5.0]})
    DKV.put("m_yrs", yrs)
    # valid dates, fractional components (truncate), out-of-range year
    assert_parity(lambda: sess.execute("(moment m_yrs 2 28 12 30 15 250)"))
    # day-in-month overflow (Feb 30) → NA, month 13 → NA, ms 1000 → NA
    assert_parity(lambda: sess.execute("(moment m_yrs 2 30 12 30 15 250)"))
    assert_parity(lambda: sess.execute("(moment m_yrs 13 1 0 0 0 0)"))
    assert_parity(lambda: sess.execute("(moment m_yrs 1 1 0 0 0 1000)"))
    # all-scalar call (single row)
    one = assert_parity(lambda: sess.execute("(moment 1970 1 1 0 0 0 1)"))
    assert float(one._col0()[0]) == 1.0
    # column-valued day with NAs against scalar year
    days = Frame.from_dict({"d": [1.0, 31.0, np.nan, 29.0]})
    DKV.put("m_days", days)
    assert_parity(lambda: sess.execute("(moment 2021 2 m_days 0 0 0 0)"))


def test_asdate_parity(cloud1):
    sess = RapidsSession()
    sarr = np.asarray(["2020-01-02", "bad", "2020-01-02", "1999-12-31",
                       None], object)
    DKV.put("d_str", Frame.from_dict({"d": sarr},
                                     column_types={"d": "string"}))
    l, v = both(lambda: sess.execute('(asDate d_str "yyyy-MM-dd")'))
    frames_equal(l, v)
    assert v.vecs()[0].type == "time"
    # enum input parses each domain label once
    DKV.put("d_enum", Frame.from_dict(
        {"d": np.asarray(["2020-01-02", "bad", "2020-01-02"], object)}))
    assert_parity(lambda: sess.execute('(asDate d_enum "yyyy-MM-dd")'))


def test_num_valid_substrings_parity(cloud1, tmp_path):
    words = tmp_path / "words.txt"
    words.write_text("ab\nbc\ncd\n")
    sess = RapidsSession()
    DKV.put("nvs", Frame.from_dict(
        {"s": np.asarray(["abcd", None, "xyz", "abcd", "bc"], object)},
        column_types={"s": "string"}))
    l, v = both(lambda: sess.execute(f'(num_valid_substrings nvs "{words}")'))
    frames_equal(l, v)
    got = v._col0()
    assert list(got[[0, 2, 4]]) == [3.0, 0.0, 1.0] and np.isnan(got[1])


# -- GroupBy NA modes (satellite) --------------------------------------------
def test_group_by_na_modes(cloud1):
    g = np.asarray(["a", "a", "b", "a", "b"], object)
    v = np.asarray([1.0, np.nan, 2.0, 3.0, 4.0])
    fr = Frame.from_dict({"g": g, "v": v}, column_types={"g": "enum"})

    def agg(op, na):
        out = getattr(fr.group_by("g"), op)("v", na=na).get_frame()
        d = out.as_data_frame(use_pandas=False)
        return dict(zip(d["g"], d[f"{op}_v"]))

    # rm: drop NA rows from numerator AND denominator
    assert agg("sum", "rm")["a"] == pytest.approx(4.0)
    assert agg("mean", "rm")["a"] == pytest.approx(2.0)
    assert agg("sd", "rm")["a"] == pytest.approx(np.std([1.0, 3.0], ddof=1))
    # all: NA propagates into the group's aggregate
    for op in ("sum", "mean", "min", "max", "sd", "var", "median", "mode"):
        va = agg(op, "all")
        assert np.isnan(va["a"]), op
        assert not np.isnan(va["b"]), op
    # ignore: skip NAs in the accumulation, keep rows in the denominator
    assert agg("sum", "ignore")["a"] == pytest.approx(4.0)
    assert agg("mean", "ignore")["a"] == pytest.approx(4.0 / 3.0)
    n, s1, s2 = 3.0, 4.0, 10.0
    var_ign = (s2 - n * (s1 / n) ** 2) / (n - 1)
    assert agg("var", "ignore")["a"] == pytest.approx(var_ign)
    assert agg("sd", "ignore")["a"] == pytest.approx(np.sqrt(var_ign))
    # min/max/median unaffected by ignore-vs-rm
    for op in ("min", "max", "median"):
        assert agg(op, "ignore")["a"] == pytest.approx(agg(op, "rm")["a"])
    # groups without NA agree across all modes
    for op in ("sum", "mean", "sd"):
        assert agg(op, "all")["b"] == pytest.approx(agg(op, "rm")["b"])
    with pytest.raises(ValueError, match="na must be"):
        fr.group_by("g").sum("v", na="bogus")


def test_group_by_na_key_is_own_group(cloud1):
    """An NA in the GROUPING column forms its own group — the seed fed the
    -1 enum code into the mixed radix, where it decoded as the LAST domain
    label and silently collided with that group (code-review repro)."""
    fr = Frame.from_dict({"g": np.asarray(["a", "b", None, "b"], object),
                          "v": [1.0, 2.0, 3.0, 4.0]},
                         column_types={"g": "enum"})
    out = fr.group_by("g").sum("v", na="rm").get_frame()
    assert out.nrow == 3
    gv = out.vec("g")
    sums = out.vec("sum_v").numeric_np()
    nas = gv.isna_np()
    assert nas.sum() == 1  # the NA-key group, labeled NA
    assert float(sums[np.flatnonzero(nas)[0]]) == 3.0
    by_label = dict(zip(Frame({"g": gv})._string_rows(), sums))
    assert by_label["a"] == 1.0 and by_label["b"] == 6.0


def test_merge_outer_preserves_time_column_precision(cloud1):
    """Outer merges must not downcast epoch-ms 'time' columns to f32 —
    the seed's unconditional cast lost ~seconds of precision on every
    masked column (code-review repro); both paths share the fix."""
    from h2o3_tpu.frame.vec import Vec

    ts = 1700000000123.0
    left = Frame.from_dict({"k": [1.0, 2.0], "a": [1.0, 2.0]})
    right = Frame({"k": Vec(np.asarray([1.0], np.float32), "real"),
                   "ts": Vec(np.asarray([ts], np.float64), "time")})
    out = assert_parity(lambda: R.merge(left, right, all_x=True))
    got = out.vec("ts").numeric_np()
    assert float(got[0]) == ts  # exact, not f32-rounded
    assert np.isnan(got[1])
    assert out.vec("ts").type == "time"


def test_group_by_count_na_rm_counts_non_na(cloud1):
    """Rapids GB nrow with na='rm' counts the NON-NA rows of the
    referenced column (AstGroup nrow agg); 'all' keeps the group size."""
    fr = Frame.from_dict({"g": np.asarray(["a", "a", "a", "b"], object),
                          "v": [1.0, np.nan, np.nan, 2.0]},
                         column_types={"g": "enum"})
    DKV.put("gbcnt", fr)
    sess = RapidsSession()
    d = sess.execute('(GB gbcnt [0] nrow 1 "rm")').as_data_frame(
        use_pandas=False)
    assert d["nrow"][list(d["g"]).index("a")] == 1.0
    d2 = sess.execute('(GB gbcnt [0] nrow 1 "all")').as_data_frame(
        use_pandas=False)
    assert d2["nrow"][list(d2["g"]).index("a")] == 3.0
    # builder count() has no referenced column — always the group size
    d3 = fr.group_by("g").count(na="rm").get_frame().as_data_frame(
        use_pandas=False)
    assert d3["nrow"][list(d3["g"]).index("a")] == 3.0


def test_table_single_column_books_legacy_path(cloud1):
    munge_stats.reset()
    fr = Frame.from_dict({"c": np.asarray(["a", "b", "a"], object)},
                         column_types={"c": "enum"})
    with legacy():
        fr.table()
    fr.table()
    paths = munge_stats.snapshot()["ops"]["table"]["paths"]
    assert paths == {"legacy": 1, "vectorized": 1}


def test_group_by_radix_overflow_compaction(cloud1):
    """4 high-cardinality keys whose radix product exceeds int64 must
    compact instead of silently wrapping (merge-radix guard reused)."""
    rng = np.random.default_rng(0)
    n = 70_000  # ~70001^4 ≈ 2.4e19 > 2^62 → compaction engages
    base = {f"k{j}": np.round(rng.random(n // 2), 9) for j in range(4)}
    fr = Frame.from_dict(
        {k: np.r_[v, v] for k, v in base.items()} |
        {"v": rng.random(n)})
    out = fr.group_by(["k0", "k1", "k2", "k3"]).count().get_frame()
    assert out.nrow == n // 2  # every duplicated row pair is one group
    assert np.array_equal(out.vec("nrow").numeric_np(),
                          np.full(n // 2, 2.0))


def test_group_by_na_mode_via_rapids(cloud1):
    fr = Frame.from_dict({"g": np.asarray(["a", "a", "b"], object),
                          "v": [1.0, np.nan, 2.0]},
                         column_types={"g": "enum"})
    DKV.put("gbna", fr)
    sess = RapidsSession()
    out_all = sess.execute('(GB gbna [0] sum 1 "all")').as_data_frame(
        use_pandas=False)
    assert np.isnan(out_all["sum_v"][list(out_all["g"]).index("a")])
    out_rm = sess.execute('(GB gbna [0] sum 1 "rm")').as_data_frame(
        use_pandas=False)
    assert out_rm["sum_v"][list(out_rm["g"]).index("a")] == 1.0


# -- observability ------------------------------------------------------------
def test_munge_stats_and_profiler_surface(cloud1):
    from h2o3_tpu.runtime import phases, profiler

    munge_stats.reset()
    phases.reset()
    left, right = _mixed_frames(seed=7)
    out = R.merge(left, right, by=["k1", "k2"], all_x=True)
    snap = munge_stats.snapshot()
    assert snap["totals"]["ops"] == 1
    assert snap["totals"]["rows_in"] == left.nrow + right.nrow
    assert snap["totals"]["rows_out"] == out.nrow
    assert snap["last"]["op"] == "merge"
    assert snap["last"]["path"] == "vectorized"
    assert snap["last"]["rows_per_s"] > 0
    assert set(snap["last"]["stages"]) == {"factorize", "combine", "match",
                                           "assemble"}
    ph = phases.snapshot()
    assert "munge_merge_s" in ph
    prof = profiler.munge_stats()
    assert prof["active"] is True and prof["totals"]["ops"] == 1
    with legacy():
        R.merge(left, right, by=["k1", "k2"])
    assert munge_stats.snapshot()["ops"]["merge"]["paths"]["legacy"] == 1


def test_munge_stats_errors_not_counted_as_throughput(cloud1):
    """An op that raises books error=True with rows_out=0 — failed calls
    must not fabricate completed rows (code-review finding)."""
    munge_stats.reset()
    fr = Frame.from_dict({"a": [1.0, 2.0], "b": [3.0, 4.0]})
    with pytest.raises(ValueError, match="ragged"):
        fr.apply(lambda row: np.ones(
            1 if float(row["a"]._col0()[0]) == 1.0 else 2), axis=1)
    snap = munge_stats.snapshot()
    assert snap["ops"]["apply_rows"]["errors"] == 1
    assert snap["totals"]["rows_out"] == 0
    assert snap["last"]["error"] is True and snap["last"]["rows_out"] == 0


def test_munge_metrics_rest_endpoint(cloud1):
    import json
    import urllib.request

    from h2o3_tpu.rest.server import start_server

    srv = start_server(port=0)
    try:
        port = srv.httpd.server_address[1]
        left = Frame.from_dict({"k": [1.0, 2.0], "a": [1.0, 2.0]})
        right = Frame.from_dict({"k": [2.0, 3.0], "b": [5.0, 6.0]})
        munge_stats.reset()
        R.merge(left, right)
        body = json.load(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/3/Munge/metrics"))
        assert body["__meta"]["schema_type"] == "MungeMetricsV3"
        assert body["totals"]["ops"] >= 1 and "merge" in body["ops"]
        prof = json.load(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/3/Profiler"))
        assert prof["munge"]["active"] is True
    finally:
        srv.stop()


def test_munge_metrics_schema():
    from h2o3_tpu.rest import schemas

    sch = schemas.munge_metrics_schema()
    assert sch["name"] == schemas.MUNGE_SCHEMA_NAME
    names = [f["name"] for f in sch["fields"]]
    assert "totals" in names and "last.stages" in names and "ops" in names


# -- throughput smoke (tier-2) ------------------------------------------------
@pytest.mark.slow
def test_munge_throughput_floor(cloud1):
    """The radix join must beat the seed per-row hash join by a wide
    margin even on a loaded 2-core CI host (bench floor is 5× at 1M rows;
    here 200k rows with a 3× safety floor, best-of-reps to damp scheduler
    noise, mirroring test_ingest_throughput_floor)."""
    rng = np.random.default_rng(0)
    n, m = 200_000, 40_000
    levels = np.asarray([f"L{i}" for i in range(1000)])
    left = Frame.from_dict(
        {"k1": rng.choice(levels, n).astype(object),
         "k2": rng.integers(0, 100, n).astype(float),
         "x": rng.random(n)}, column_types={"k1": "enum"})
    right = Frame.from_dict(
        {"k1": rng.choice(levels, m).astype(object),
         "k2": rng.integers(0, 110, m).astype(float),
         "y": rng.random(m)}, column_types={"k1": "enum"})

    def best(reps=3, use_legacy=False):
        t_best = float("inf")
        for _ in range(reps):
            ctx = legacy() if use_legacy else None
            if ctx:
                ctx.__enter__()
            try:
                t0 = time.perf_counter()
                R.merge(left, right, by=["k1", "k2"], all_x=True)
                t_best = min(t_best, time.perf_counter() - t0)
            finally:
                if ctx:
                    ctx.__exit__(None, None, None)
        return t_best

    best(reps=1)  # warm-up: numpy kernels + page cache
    for _ in range(2):  # one re-measure before calling it a regression
        t_vec = best(reps=3)
        t_leg = best(reps=2, use_legacy=True)
        if t_leg / t_vec >= 3.0:
            break
    assert t_leg / t_vec >= 3.0, (t_vec, t_leg)
