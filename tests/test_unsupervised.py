"""KMeans / PCA / IsolationForest tests — `testdir_algos/{kmeans,pca}` analog."""

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.kmeans import H2OKMeansEstimator
from h2o3_tpu.models.pca import H2OPrincipalComponentAnalysisEstimator
from h2o3_tpu.models.isolation_forest import H2OIsolationForestEstimator


def _blobs(n=900, k=3, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.uniform(-10, 10, (k, 4))
    X = np.concatenate([c + rng.normal(size=(n // k, 4)) for c in centers])
    labels = np.repeat(np.arange(k), n // k)
    return X, labels, centers


def test_kmeans_recovers_blobs(cloud1):
    X, labels, centers = _blobs()
    fr = Frame.from_numpy(X, names=["a", "b", "c", "d"])
    km = H2OKMeansEstimator(k=3, max_iterations=20, standardize=False, seed=1)
    km.train(training_frame=fr)
    got = km.model.centers()
    # every true center matched within 0.5 by some found center
    for c in centers:
        assert np.min(np.linalg.norm(got - c, axis=1)) < 0.5
    pred = km.predict(fr).vec("predict").numeric_np().astype(int)
    # cluster assignments align with blob structure (same-blob rows agree)
    for b in range(3):
        vals, counts = np.unique(pred[labels == b], return_counts=True)
        assert counts.max() / counts.sum() > 0.95
    assert km.model.tot_withinss() < km.model.totss()


def test_kmeans_plusplus_and_random_init(cloud1):
    X, _, _ = _blobs(seed=2)
    fr = Frame.from_numpy(X)
    for init in ("PlusPlus", "Random", "Furthest"):
        km = H2OKMeansEstimator(k=3, init=init, seed=3, max_iterations=15)
        km.train(training_frame=fr)
        assert km.model.training_metrics.tot_withinss < 0.2 * km.model.totss()


def test_pca_variance_order(cloud1):
    rng = np.random.default_rng(4)
    n = 1000
    z = rng.normal(size=(n, 2))
    X = np.column_stack([3 * z[:, 0], 1 * z[:, 1], 0.1 * rng.normal(size=n)])
    fr = Frame.from_numpy(X, names=["a", "b", "c"])
    pca = H2OPrincipalComponentAnalysisEstimator(k=3, transform="DEMEAN")
    pca.train(training_frame=fr)
    imp = pca.model.importance
    sd = imp["Standard deviation"]
    assert sd[0] > sd[1] > sd[2]
    assert sd[0] == pytest.approx(3.0, rel=0.1)
    scores = pca.model.predict(fr)
    assert scores.ncol == 3
    # PC1 aligns with the dominant axis
    pc1 = scores.vec("PC1").numeric_np()
    assert abs(np.corrcoef(pc1, z[:, 0])[0, 1]) > 0.99


def test_pca_randomized(cloud1):
    rng = np.random.default_rng(5)
    X = rng.normal(size=(500, 10)) @ np.diag([5, 3] + [0.1] * 8)
    fr = Frame.from_numpy(X)
    pca = H2OPrincipalComponentAnalysisEstimator(k=2, pca_method="Randomized",
                                                 transform="DEMEAN", seed=6)
    pca.train(training_frame=fr)
    sd = pca.model.importance["Standard deviation"]
    assert sd[0] == pytest.approx(5.0, rel=0.15)


def test_isolation_forest_flags_outliers(cloud1):
    rng = np.random.default_rng(7)
    inliers = rng.normal(size=(500, 3))
    outliers = rng.normal(loc=8.0, size=(10, 3))
    X = np.concatenate([inliers, outliers])
    fr = Frame.from_numpy(X, names=["a", "b", "c"])
    iso = H2OIsolationForestEstimator(ntrees=50, sample_size=128, seed=8)
    iso.train(training_frame=fr)
    scores = iso.predict(fr).vec("predict").numeric_np()
    assert scores[-10:].mean() > scores[:-10].mean() + 0.1
    # outliers rank in the top 5%
    thresh = np.quantile(scores, 0.95)
    assert (scores[-10:] > thresh).mean() > 0.8
