"""Long-tail algos batch 3: RuleFit, PSVM, UpliftDRF, ExtendedIsolationForest.

Mirrors reference pyunits `pyunit_rulefit_*`, `pyunit_psvm_*`,
`pyunit_uplift_*`, `pyunit_extended_isolation_forest_*`."""

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.extended_isolation_forest import (
    H2OExtendedIsolationForestEstimator,
)
from h2o3_tpu.models.psvm import H2OSupportVectorMachineEstimator
from h2o3_tpu.models.rulefit import H2ORuleFitEstimator
from h2o3_tpu.models.uplift import H2OUpliftRandomForestEstimator, auuc


def _binary_frame(n=800, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 4))
    y = ((X[:, 0] > 0.3) & (X[:, 1] < 0.5) | (X[:, 2] > 1.0)).astype(int)
    d = {f"x{i}": X[:, i] for i in range(4)}
    d["y"] = np.asarray(["no", "yes"], dtype=object)[y]
    return Frame.from_dict(d, column_types={"y": "enum"})


def test_rulefit_rules_and_predict(cloud1):
    fr = _binary_frame()
    rf = H2ORuleFitEstimator(max_num_rules=20, min_rule_length=2,
                             max_rule_length=3, rule_generation_ntrees=20, seed=7)
    rf.train(x=["x0", "x1", "x2", "x3"], y="y", training_frame=fr)
    assert rf.model.training_metrics.auc > 0.8
    imp = rf.model.rule_importance()
    assert 0 < imp.nrow <= 25  # rules + linear terms, sparse
    # rule strings mention real feature names
    rv = imp.vec("rule")
    rules_txt = [rv.domain[c] for c in np.asarray(rv.data)]
    assert any("x0" in r or "x2" in r for r in rules_txt)
    p = rf.predict(fr)
    assert "predict" in p.names


def test_rulefit_regression(cloud1):
    rng = np.random.default_rng(1)
    X = rng.normal(size=(500, 3))
    y = np.where(X[:, 0] > 0, 2.0, -1.0) + 0.1 * rng.normal(size=500)
    d = {f"x{i}": X[:, i] for i in range(3)}
    d["y"] = y
    fr = Frame.from_dict(d)
    rf = H2ORuleFitEstimator(model_type="rules", min_rule_length=1,
                             max_rule_length=2, rule_generation_ntrees=10, seed=3)
    rf.train(x=["x0", "x1", "x2"], y="y", training_frame=fr)
    assert rf.model.training_metrics.rmse < 0.6


def test_psvm_separable(cloud1):
    rng = np.random.default_rng(2)
    n = 400
    X = rng.normal(size=(n, 2))
    y = (X[:, 0] + X[:, 1] > 0).astype(int)
    fr = Frame.from_dict(
        {"a": X[:, 0], "b": X[:, 1],
         "y": np.asarray(["n", "p"], dtype=object)[y]},
        column_types={"y": "enum"})
    svm = H2OSupportVectorMachineEstimator(hyper_param=1.0, kernel_type="gaussian",
                                           seed=5)
    svm.train(x=["a", "b"], y="y", training_frame=fr)
    assert svm.model.training_metrics.auc > 0.95
    assert svm.model.svs_count > 0
    pred = svm.predict(fr)
    assert set(pred.names) >= {"predict", "decision_function"}
    # nonlinear ring data needs the gaussian kernel
    r = np.sqrt((X**2).sum(axis=1))
    y2 = (r > 1.1).astype(int)
    fr2 = Frame.from_dict(
        {"a": X[:, 0], "b": X[:, 1],
         "y": np.asarray(["in", "out"], dtype=object)[y2]},
        column_types={"y": "enum"})
    svm2 = H2OSupportVectorMachineEstimator(kernel_type="gaussian", gamma=1.0, seed=5)
    svm2.train(x=["a", "b"], y="y", training_frame=fr2)
    assert svm2.model.training_metrics.auc > 0.9


def test_uplift_drf(cloud1):
    rng = np.random.default_rng(3)
    n = 2000
    X = rng.normal(size=(n, 3))
    treat = rng.integers(0, 2, n)
    # uplift only where x0>0: treated respond more
    base = (X[:, 1] > 0.5).astype(float) * 0.2
    lift = np.where(X[:, 0] > 0, 0.4, 0.0) * treat
    y = (rng.uniform(size=n) < base + lift + 0.1).astype(int)
    fr = Frame.from_dict({
        "x0": X[:, 0], "x1": X[:, 1], "x2": X[:, 2],
        "treatment": np.asarray(["control", "treatment"], dtype=object)[treat],
        "y": np.asarray(["0", "1"], dtype=object)[y],
    }, column_types={"treatment": "enum", "y": "enum"})
    up = H2OUpliftRandomForestEstimator(
        treatment_column="treatment", uplift_metric="KL", ntrees=20,
        max_depth=4, seed=11)
    up.train(x=["x0", "x1", "x2"], y="y", training_frame=fr)
    u = up.predict(fr).vec("uplift_predict").numeric_np()
    # predicted uplift should be higher where true uplift exists
    assert u[X[:, 0] > 0].mean() > u[X[:, 0] <= 0].mean() + 0.1
    m = up.model.training_metrics
    assert np.isfinite(m.auuc)
    # qini auuc of the model ranking beats a random ranking
    rand_auuc, _ = auuc(y.astype(float), treat.astype(float),
                        rng.uniform(size=n))
    assert m.auuc > rand_auuc


def test_extended_isolation_forest(cloud1):
    rng = np.random.default_rng(4)
    X = rng.normal(size=(500, 3))
    X[:5] += 8.0  # planted anomalies
    fr = Frame.from_numpy(X, names=["a", "b", "c"])
    eif = H2OExtendedIsolationForestEstimator(ntrees=50, sample_size=128,
                                              extension_level=2, seed=9)
    eif.train(x=["a", "b", "c"], training_frame=fr)
    out = eif.predict(fr)
    s = out.vec("anomaly_score").numeric_np()
    assert out.vec("mean_length").numeric_np().min() >= 0
    # planted anomalies rank in the top scores
    top = np.argsort(-s)[:10]
    assert len(set(top) & set(range(5))) >= 4
    assert s.min() >= 0 and s.max() <= 1


def test_eif_extension_level_validation(cloud1):
    fr = Frame.from_numpy(np.random.default_rng(0).normal(size=(50, 2)),
                          names=["a", "b"])
    with pytest.raises(ValueError):
        H2OExtendedIsolationForestEstimator(extension_level=5).train(
            x=["a", "b"], training_frame=fr)


def test_save_grid_load_grid_roundtrip(tmp_path, cloud1):
    """h2o.save_grid on a grid trained WITHOUT recovery_dir exports state +
    artifacts; h2o.load_grid restores the models and their metrics."""
    import numpy as np

    import h2o3_tpu as h2o
    from h2o3_tpu.estimators import H2OGradientBoostingEstimator
    from h2o3_tpu.models.grid import H2OGridSearch

    rng = np.random.default_rng(0)
    n = 800
    X = rng.normal(size=(n, 4))
    y = (X[:, 0] + X[:, 1] > 0).astype(int)
    d = {f"c{i}": X[:, i] for i in range(4)}
    d["y"] = y.astype(str)
    fr = h2o.H2OFrame_from_python(d, column_types={"y": "enum"})
    gs = H2OGridSearch(H2OGradientBoostingEstimator(ntrees=4, seed=1),
                       hyper_params={"max_depth": [2, 3]}, grid_id="sg1")
    gs.train(x=[f"c{i}" for i in range(4)], y="y", training_frame=fr)
    out = h2o.save_grid(gs, str(tmp_path / "gdir"))
    g2 = h2o.load_grid(out)
    assert g2.grid_id == "sg1"
    assert len(g2.models) == 2
    # restored models score: predictions finite on the training frame
    p = g2.models[0].predict(fr)
    assert np.isfinite(p.vec("1").numeric_np()).all()
    # a SECOND save to a different dir must carry the artifacts along
    out2 = h2o.save_grid(gs, str(tmp_path / "gdir2"))
    g3 = h2o.load_grid(out2)
    assert len(g3.models) == 2


def test_save_grid_numpy_hypers_and_kwargs(tmp_path, cloud1):
    import numpy as np
    import pytest

    import h2o3_tpu as h2o
    from h2o3_tpu.estimators import H2OGradientBoostingEstimator
    from h2o3_tpu.models.grid import H2OGridSearch

    rng = np.random.default_rng(1)
    d = {"a": rng.normal(size=300), "b": rng.normal(size=300),
         "y": (rng.random(300) > 0.5).astype(int).astype(str)}
    fr = h2o.H2OFrame_from_python(d, column_types={"y": "enum"})
    gs = H2OGridSearch(H2OGradientBoostingEstimator(ntrees=2, seed=1),
                       hyper_params={"max_depth": np.arange(2, 4)},
                       grid_id="sgnp")
    gs.train(x=["a", "b"], y="y", training_frame=fr)
    out = h2o.save_grid(gs, str(tmp_path / "np_gdir"))   # np scalars OK
    assert len(h2o.load_grid(out).models) == 2
    with pytest.raises(NotImplementedError):
        h2o.save_grid(gs, str(tmp_path / "x"),
                      export_cross_validation_predictions=True)


def test_misc_surface_functions(tmp_path, cloud1):
    """h2o.models/as_list/list_timezones/estimate_cluster_mem/
    log_and_echo/download_all_logs/network_test/cluster_status parity."""
    import numpy as np
    import pytest

    import h2o3_tpu as h2o
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

    rng = np.random.default_rng(0)
    d = {"a": rng.normal(size=200), "y": (rng.random(200) > 0.5).astype(int).astype(str)}
    fr = h2o.H2OFrame_from_python(d, column_types={"y": "enum"})
    m = H2OGradientBoostingEstimator(ntrees=2, max_depth=2, seed=1)
    m.train(y="y", training_frame=fr)
    assert m.model.model_id in h2o.ls()

    lst = h2o.as_list(fr, header=True)
    assert lst[0] == ["a", "y"] and len(lst) == 201

    tz = h2o.list_timezones()
    assert tz.nrow > 100 and "UTC" in set(tz.vec("Timezones").to_numpy())

    gb = h2o.estimate_cluster_mem(ncols=10, nrows=1_000_000)
    assert gb == pytest.approx(4 * 10 * 8 * 1e6 / 1e9, rel=1e-6)
    with pytest.raises(ValueError):
        h2o.estimate_cluster_mem(ncols=2, nrows=10, string_cols=3)

    h2o.log_and_echo("marker-xyz")
    z = h2o.download_all_logs(str(tmp_path))
    import zipfile

    with zipfile.ZipFile(z) as zf:
        text = zf.read("h2o3_tpu.log").decode()
    assert "marker-xyz" in text

    res = h2o.network_test()
    assert len(res) == 3 and all(r["mbytes_per_sec"] > 0 for r in res)
    h2o.cluster_status()        # prints, must not raise


def test_model_transfer_and_make_metrics(tmp_path, cloud1):
    """h2o.download_model/print_mojo/make_metrics in-process parity."""
    import json

    import numpy as np
    import pytest

    import h2o3_tpu as h2o
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

    rng = np.random.default_rng(2)
    n = 800
    X = rng.normal(size=(n, 3))
    y = (X[:, 0] + X[:, 1] > 0).astype(int)
    d = {f"c{i}": X[:, i] for i in range(3)}
    d["y"] = y.astype(str)
    fr = h2o.H2OFrame_from_python(d, column_types={"y": "enum"})
    m = H2OGradientBoostingEstimator(ntrees=4, max_depth=3, seed=1)
    m.train(y="y", training_frame=fr)

    path = h2o.download_model(m, str(tmp_path))
    dump = json.loads(h2o.print_mojo(path))
    assert dump["meta"]["kind"] == "tree"
    assert any(k.startswith("forest0") for k in dump["arrays"])

    # make_metrics(binomial): must agree with the model's own AUC
    p1 = m.predict(fr)["1"]
    # predict-frame probabilities vs training-margin metrics: same model,
    # slightly different float paths — agree to ~1e-3, not bitwise
    mm = h2o.make_metrics(p1, fr["y"], domain=["0", "1"])
    assert float(mm.auc) == pytest.approx(float(m.auc()), abs=2e-3)
    # regression
    t = X[:, 0] * 2.0
    mm2 = h2o.make_metrics(t + 0.1, h2o.H2OFrame_from_python({"t": t})["t"])
    assert float(mm2.rmse) == pytest.approx(0.1, abs=1e-9)
    # h2o.api without a connection raises cleanly
    from h2o3_tpu.client import H2OConnectionError

    with pytest.raises(H2OConnectionError):
        h2o.api("GET /3/Cloud")


def test_upload_model_remote(tmp_path):
    """h2o.upload_model pushes a local artifact to a separate server
    process; the returned server-side model predicts over the wire."""
    import os
    import subprocess
    import sys
    import time

    import numpy as np

    import h2o3_tpu as h2o
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

    rng = np.random.default_rng(3)
    n = 400
    X = rng.normal(size=(n, 3))
    y = (X[:, 0] > 0).astype(int)
    d = {f"c{i}": X[:, i] for i in range(3)}
    d["y"] = y.astype(str)
    fr_local = h2o.H2OFrame_from_python(d, column_types={"y": "enum"})
    m = H2OGradientBoostingEstimator(ntrees=3, max_depth=2, seed=1)
    m.train(y="y", training_frame=fr_local)
    path = h2o.save_model(m, str(tmp_path))

    env = dict(os.environ, PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu",
               PYTHONPATH=os.path.dirname(os.path.dirname(
                   os.path.abspath(__file__))))
    proc = subprocess.Popen([sys.executable, "-c", """
import jax; jax.config.update("jax_platforms", "cpu")
import time
from h2o3_tpu.rest.server import start_server
import h2o3_tpu as h2o
h2o.init()
s = start_server(port=0, auth_token=None)
print(s.port, flush=True)
time.sleep(600)
"""], env=env, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
    try:
        port = int(proc.stdout.readline())
        h2o.connect(url=f"http://127.0.0.1:{port}", verbose=False)
        rm = h2o.upload_model(path)
        fr = h2o.H2OFrame_from_python(
            {f"c{i}": X[:, i] for i in range(3)})
        pred = rm.predict(fr)
        got = np.asarray(pred.as_data_frame(use_pandas=False)["1"])
        want = m.predict(fr_local).vec("1").numeric_np()
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
        # the uploaded artifact is inspectable and downloads back
        info = h2o.connection().get(
            f"/3/Models/{rm.model_id}")["models"][0]
        assert info["uploaded_artifact"] and info["kind"] == "tree"
        back = h2o.download_model(rm, str(tmp_path / "back"))
        p2 = h2o.load_model(back).predict(fr_local).vec("1").numeric_np()
        np.testing.assert_allclose(p2, want, rtol=1e-5, atol=1e-6)
    finally:
        proc.kill()
        h2o.shutdown()
