"""Multi-process training correctness: N local processes under
jax.distributed (the reference's multi-JVM loopback cloud, SURVEY.md §4)
must reproduce the single-process model within tolerance — VERDICT r01
item 5. Ingest is per-process byte ranges (distributed_parse), so these
tests exercise the full distributed path: parse → global domains → global
row-sharded arrays → collective training math."""

import csv

import numpy as np
import pytest

from tests.multiproc_util import run_workers


def _write_glm_csv(path, n=4000, seed=11):
    rng = np.random.default_rng(seed)
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    cat = rng.integers(0, 4, size=n)
    # order-correlated column with NAs: a per-shard imputation mean or
    # one-pass variance would visibly skew the 2-process coefficients
    xs = np.sort(rng.normal(size=n)) * 0.3
    eff = 1.2 * x1 - 0.7 * x2 + 0.5 * (cat == 2) + 0.4 * xs
    y = (rng.random(n) < 1 / (1 + np.exp(-eff))).astype(int)
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["x1", "x2", "xs", "cat", "y"])
        for i in range(n):
            xs_tok = "" if i % 17 == 0 else f"{xs[i]:.6f}"
            w.writerow([f"{x1[i]:.6f}", f"{x2[i]:.6f}", xs_tok, f"g{cat[i]}",
                        "yes" if y[i] else "no"])


GLM_BODY = """
import numpy as np
import h2o3_tpu as h2o
from h2o3_tpu.models.glm import H2OGeneralizedLinearEstimator
h2o.init()
fr = h2o.import_file({csv!r})
fr["y"] = fr["y"].asfactor()
g = H2OGeneralizedLinearEstimator(family="binomial", lambda_=0.0,
                                  solver="IRLSM")
g.train(x=["x1", "x2", "xs", "cat"], y="y", training_frame=fr)
import jax
if jax.process_index() == 0:
    c = g.model.coef()
    np.savez({out!r}, **{{k: float(v) for k, v in c.items()}})
print("rank", jax.process_index(), "done")
"""


def test_glm_two_process_matches_single(tmp_path, cloud1):
    p = str(tmp_path / "glm.csv")
    _write_glm_csv(p)

    import h2o3_tpu as h2o
    from h2o3_tpu.models.glm import H2OGeneralizedLinearEstimator

    fr = h2o.import_file(p)
    fr["y"] = fr["y"].asfactor()
    ref = H2OGeneralizedLinearEstimator(family="binomial", lambda_=0.0,
                                        solver="IRLSM")
    ref.train(x=["x1", "x2", "xs", "cat"], y="y", training_frame=fr)
    ref_coef = ref.model.coef()

    out = str(tmp_path / "coef2.npz")
    run_workers(2, GLM_BODY.format(csv=p, out=out))
    got = np.load(out)
    assert set(got.files) == set(ref_coef)
    for k in ref_coef:
        assert float(got[k]) == pytest.approx(float(ref_coef[k]),
                                              abs=2e-3), k


def _write_gbm_csv(path, n=3000, seed=7):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 6))
    cat = rng.integers(0, 3, size=n)
    eff = X[:, 0] + 0.8 * X[:, 1] * X[:, 2] + 0.6 * (cat == 1)
    y = (eff + 0.3 * rng.normal(size=n) > 0).astype(int)
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow([f"x{i}" for i in range(6)] + ["c", "y"])
        for i in range(n):
            w.writerow([f"{v:.6f}" for v in X[i]] + [f"k{cat[i]}", int(y[i])])


GBM_BODY = """
import numpy as np
import h2o3_tpu as h2o
from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
h2o.init()
fr = h2o.import_file({csv!r})
fr["y"] = fr["y"].asfactor()
g = H2OGradientBoostingEstimator(ntrees=15, max_depth=4, seed=5)
g.train(x=[f"x{{i}}" for i in range(6)] + ["c"], y="y", training_frame=fr)
import jax
if jax.process_index() == 0:
    m = g.model
    feat = np.concatenate([np.asarray(t.feat).ravel() for t in m.forest])
    thr = np.concatenate([np.asarray(t.thr).ravel() for t in m.forest])
    val = np.concatenate([np.asarray(t.value).ravel() for t in m.forest])
    np.savez({out!r}, feat=feat, thr=thr, val=val,
             auc=float(m.training_metrics.auc))
print("rank", jax.process_index(), "ok")
"""


def test_gbm_two_process_matches_single(tmp_path, cloud1):
    p = str(tmp_path / "gbm.csv")
    _write_gbm_csv(p)

    import h2o3_tpu as h2o
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

    fr = h2o.import_file(p)
    fr["y"] = fr["y"].asfactor()
    ref = H2OGradientBoostingEstimator(ntrees=15, max_depth=4, seed=5)
    ref.train(x=[f"x{i}" for i in range(6)] + ["c"], y="y",
              training_frame=fr)
    rm = ref.model
    ref_feat = np.concatenate([np.asarray(t.feat).ravel() for t in rm.forest])
    ref_thr = np.concatenate([np.asarray(t.thr).ravel() for t in rm.forest])
    ref_val = np.concatenate([np.asarray(t.value).ravel() for t in rm.forest])

    out = str(tmp_path / "gbm2.npz")
    run_workers(2, GBM_BODY.format(csv=p, out=out))
    got = np.load(out)
    # identical binning edges + exact psum histograms -> same split structure
    assert (got["feat"] == ref_feat).mean() > 0.98
    np.testing.assert_allclose(got["thr"], ref_thr, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(got["val"], ref_val, rtol=5e-3, atol=5e-3)
    assert float(got["auc"]) == pytest.approx(
        float(rm.training_metrics.auc), abs=0.02)


DL_BODY = """
import numpy as np
import h2o3_tpu as h2o
from h2o3_tpu.models.deeplearning import H2ODeepLearningEstimator
h2o.init()
fr = h2o.import_file({csv!r})
fr["y"] = fr["y"].asfactor()
d = H2ODeepLearningEstimator(hidden=[16], epochs=6, seed=3,
                             mini_batch_size=32)
d.train(x=[f"x{{i}}" for i in range(6)] + ["c"], y="y", training_frame=fr)
import jax
if jax.process_index() == 0:
    m = d.model_performance(fr)
    np.savez({out!r}, auc=float(m.auc))
print("rank", jax.process_index(), "ok")
"""


def test_dl_two_process_learns(tmp_path, cloud1):
    p = str(tmp_path / "dl.csv")
    _write_gbm_csv(p)

    import h2o3_tpu as h2o
    from h2o3_tpu.models.deeplearning import H2ODeepLearningEstimator

    fr = h2o.import_file(p)
    fr["y"] = fr["y"].asfactor()
    ref = H2ODeepLearningEstimator(hidden=[16], epochs=6, seed=3,
                                   mini_batch_size=32)
    ref.train(x=[f"x{i}" for i in range(6)] + ["c"], y="y",
              training_frame=fr)
    ref_auc = float(ref.model_performance(fr).auc())

    out = str(tmp_path / "dl2.npz")
    run_workers(2, DL_BODY.format(csv=p, out=out))
    got_auc = float(np.load(out)["auc"])
    # different batch composition (padded permutation) -> tolerance, not
    # bit-identity; both must clearly learn the signal
    assert ref_auc > 0.85
    assert got_auc == pytest.approx(ref_auc, abs=0.08)


DRF_BODY = """
import numpy as np
import h2o3_tpu as h2o
from h2o3_tpu.models.drf import H2ORandomForestEstimator
h2o.init()
fr = h2o.import_file({csv!r})
fr["y"] = fr["y"].asfactor()
d = H2ORandomForestEstimator(ntrees=10, max_depth=6, seed=9)
d.train(x=[f"x{{i}}" for i in range(6)] + ["c"], y="y", training_frame=fr)
import jax
if jax.process_index() == 0:
    np.savez({out!r}, auc=float(d.model.training_metrics.auc))
print("rank", jax.process_index(), "ok")
"""


def test_drf_two_process_learns(tmp_path, cloud1):
    """DRF adds OOB accounting + row sampling + mtries on top of the GBM
    path — the 2-process OOB AUC must match single-process within noise."""
    p = str(tmp_path / "drf.csv")
    _write_gbm_csv(p)

    import h2o3_tpu as h2o
    from h2o3_tpu.models.drf import H2ORandomForestEstimator

    fr = h2o.import_file(p)
    fr["y"] = fr["y"].asfactor()
    ref = H2ORandomForestEstimator(ntrees=10, max_depth=6, seed=9)
    ref.train(x=[f"x{i}" for i in range(6)] + ["c"], y="y",
              training_frame=fr)
    ref_auc = float(ref.model.training_metrics.auc)

    out = str(tmp_path / "drf2.npz")
    run_workers(2, DRF_BODY.format(csv=p, out=out))
    got_auc = float(np.load(out)["auc"])
    assert ref_auc > 0.8
    # different sampling RNG (npad differs) -> tolerance, not bit-identity
    assert got_auc == pytest.approx(ref_auc, abs=0.06)
