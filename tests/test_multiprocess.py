"""Multi-process training correctness: N local processes under
jax.distributed (the reference's multi-JVM loopback cloud, SURVEY.md §4)
must reproduce the single-process model within tolerance — VERDICT r01
item 5. Ingest is per-process byte ranges (distributed_parse), so these
tests exercise the full distributed path: parse → global domains → global
row-sharded arrays → collective training math.

SLOW LANE (ISSUE 13 triage): this whole module runs `slow`. The suite
was the tier-1 baseline's 18-failure block — a jax-version skew in the
worker prelude (`jax_num_cpu_devices` does not exist on jax < 0.5) made
every spawn die at import; the prelude now falls back to XLA_FLAGS
(multiproc_util.WORKER_PRELUDE) and the tests pass again. They stay out
of tier-1 because each spawns 2-4 fresh interpreters that pay a full
jax + platform import and an end-to-end train (~40-150 s per test on
the 1-core CI box, ~3.5 min for the module) against a tier-1 budget
that is already ~826 s of the 870 s timeout. The spawn machinery itself
keeps a tier-1 canary (tests/test_distributed_parse.py::
test_two_process_bit_identical runs run_workers in ~1.5 s), the
8-virtual-device mesh suite (tests/test_tree_sharded.py) covers the
collective lowering, and the fleet-aggregation tests
(tests/test_fleet.py) cover real multi-process scraping; full
cross-process training parity runs here in the slow lane and in the
MULTICHIP dryrun. Two fixes made the suite green again: gloo CPU
collectives selected explicitly (jax 0.4.x default "none" cannot run
multiprocess programs) and check_rep=False on the mesh_psum tree step
(the 0.4.x replication checker rejects the level loop's psum carry)."""

import csv

import numpy as np
import pytest

from tests.multiproc_util import run_workers

pytestmark = pytest.mark.slow


def _write_glm_csv(path, n=4000, seed=11):
    rng = np.random.default_rng(seed)
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    cat = rng.integers(0, 4, size=n)
    # order-correlated column with NAs: a per-shard imputation mean or
    # one-pass variance would visibly skew the 2-process coefficients
    xs = np.sort(rng.normal(size=n)) * 0.3
    eff = 1.2 * x1 - 0.7 * x2 + 0.5 * (cat == 2) + 0.4 * xs
    y = (rng.random(n) < 1 / (1 + np.exp(-eff))).astype(int)
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["x1", "x2", "xs", "cat", "y"])
        for i in range(n):
            xs_tok = "" if i % 17 == 0 else f"{xs[i]:.6f}"
            w.writerow([f"{x1[i]:.6f}", f"{x2[i]:.6f}", xs_tok, f"g{cat[i]}",
                        "yes" if y[i] else "no"])


GLM_BODY = """
import numpy as np
import h2o3_tpu as h2o
from h2o3_tpu.models.glm import H2OGeneralizedLinearEstimator
h2o.init()
fr = h2o.import_file({csv!r})
fr["y"] = fr["y"].asfactor()
g = H2OGeneralizedLinearEstimator(family="binomial", lambda_=0.0,
                                  solver="IRLSM")
g.train(x=["x1", "x2", "xs", "cat"], y="y", training_frame=fr)
import jax
if jax.process_index() == 0:
    c = g.model.coef()
    np.savez({out!r}, **{{k: float(v) for k, v in c.items()}})
print("rank", jax.process_index(), "done")
"""


def test_glm_two_process_matches_single(tmp_path, cloud1):
    p = str(tmp_path / "glm.csv")
    _write_glm_csv(p)

    import h2o3_tpu as h2o
    from h2o3_tpu.models.glm import H2OGeneralizedLinearEstimator

    fr = h2o.import_file(p)
    fr["y"] = fr["y"].asfactor()
    ref = H2OGeneralizedLinearEstimator(family="binomial", lambda_=0.0,
                                        solver="IRLSM")
    ref.train(x=["x1", "x2", "xs", "cat"], y="y", training_frame=fr)
    ref_coef = ref.model.coef()

    out = str(tmp_path / "coef2.npz")
    run_workers(2, GLM_BODY.format(csv=p, out=out))
    got = np.load(out)
    assert set(got.files) == set(ref_coef)
    for k in ref_coef:
        assert float(got[k]) == pytest.approx(float(ref_coef[k]),
                                              abs=2e-3), k


def _write_gbm_csv(path, n=3000, seed=7):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 6))
    cat = rng.integers(0, 3, size=n)
    eff = X[:, 0] + 0.8 * X[:, 1] * X[:, 2] + 0.6 * (cat == 1)
    y = (eff + 0.3 * rng.normal(size=n) > 0).astype(int)
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow([f"x{i}" for i in range(6)] + ["c", "y"])
        for i in range(n):
            w.writerow([f"{v:.6f}" for v in X[i]] + [f"k{cat[i]}", int(y[i])])


GBM_BODY = """
import numpy as np
import h2o3_tpu as h2o
from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
h2o.init()
fr = h2o.import_file({csv!r})
fr["y"] = fr["y"].asfactor()
g = H2OGradientBoostingEstimator(ntrees=15, max_depth=4, seed=5)
g.train(x=[f"x{{i}}" for i in range(6)] + ["c"], y="y", training_frame=fr)
import jax
if jax.process_index() == 0:
    m = g.model
    t = m.forest[0]
    np.savez({out!r}, feat=np.asarray(t.feat), bins=np.asarray(t.bin),
             thr=np.asarray(t.thr), val=np.asarray(t.value),
             auc=float(m.training_metrics.auc))
print("rank", jax.process_index(), "ok")
"""


@pytest.mark.parametrize("nproc", [2, 4])
def test_gbm_multiprocess_matches_single(tmp_path, cloud1, nproc):
    """n=4 exercises uneven byte ranges / odd local row counts that n=2
    cannot (3001 rows split 4 ways); the first three tree levels must match
    the single-process build EXACTLY — the psum'd histograms are the same
    sums, so early splits are deterministic; only deep near-tie levels may
    drift via f32 accumulation order."""
    p = str(tmp_path / "gbm.csv")
    _write_gbm_csv(p, n=3001 if nproc == 4 else 3000)

    import h2o3_tpu as h2o
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

    fr = h2o.import_file(p)
    fr["y"] = fr["y"].asfactor()
    ref = H2OGradientBoostingEstimator(ntrees=15, max_depth=4, seed=5)
    ref.train(x=[f"x{i}" for i in range(6)] + ["c"], y="y",
              training_frame=fr)
    rm = ref.model
    rt = rm.forest[0]

    out = str(tmp_path / f"gbm{nproc}.npz")
    run_workers(nproc, GBM_BODY.format(csv=p, out=out))
    got = np.load(out)
    # heap levels 0-2 (nodes 0..6): exact structural identity
    np.testing.assert_array_equal(got["feat"][:, :7], np.asarray(rt.feat)[:, :7])
    np.testing.assert_array_equal(got["bins"][:, :7], np.asarray(rt.bin)[:, :7])
    # full-tree agreement: near-identity with late-level tie tolerance
    assert (got["feat"] == np.asarray(rt.feat)).mean() > 0.98
    np.testing.assert_allclose(got["thr"], np.asarray(rt.thr),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(got["val"], np.asarray(rt.value),
                               rtol=5e-3, atol=5e-3)
    assert float(got["auc"]) == pytest.approx(
        float(rm.training_metrics.auc), abs=0.02)


DL_BODY = """
import numpy as np
import h2o3_tpu as h2o
from h2o3_tpu.models.deeplearning import H2ODeepLearningEstimator
h2o.init()
fr = h2o.import_file({csv!r})
fr["y"] = fr["y"].asfactor()
d = H2ODeepLearningEstimator(hidden=[16], epochs=6, seed=3,
                             mini_batch_size=32)
d.train(x=[f"x{{i}}" for i in range(6)] + ["c"], y="y", training_frame=fr)
import jax
if jax.process_index() == 0:
    m = d.model_performance(fr)
    np.savez({out!r}, auc=float(m.auc))
print("rank", jax.process_index(), "ok")
"""


def test_dl_two_process_learns(tmp_path, cloud1):
    p = str(tmp_path / "dl.csv")
    _write_gbm_csv(p)

    import h2o3_tpu as h2o
    from h2o3_tpu.models.deeplearning import H2ODeepLearningEstimator

    fr = h2o.import_file(p)
    fr["y"] = fr["y"].asfactor()
    ref = H2ODeepLearningEstimator(hidden=[16], epochs=6, seed=3,
                                   mini_batch_size=32)
    ref.train(x=[f"x{i}" for i in range(6)] + ["c"], y="y",
              training_frame=fr)
    ref_auc = float(ref.model_performance(fr).auc())

    out = str(tmp_path / "dl2.npz")
    run_workers(2, DL_BODY.format(csv=p, out=out))
    got_auc = float(np.load(out)["auc"])
    # different batch composition (padded permutation) -> tolerance, not
    # bit-identity; both must clearly learn the signal
    assert ref_auc > 0.85
    assert got_auc == pytest.approx(ref_auc, abs=0.08)


DRF_BODY = """
import numpy as np
import h2o3_tpu as h2o
from h2o3_tpu.models.drf import H2ORandomForestEstimator
h2o.init()
fr = h2o.import_file({csv!r})
fr["y"] = fr["y"].asfactor()
d = H2ORandomForestEstimator(ntrees=10, max_depth=6, seed=9)
d.train(x=[f"x{{i}}" for i in range(6)] + ["c"], y="y", training_frame=fr)
import jax
if jax.process_index() == 0:
    np.savez({out!r}, auc=float(d.model.training_metrics.auc))
print("rank", jax.process_index(), "ok")
"""


def test_drf_two_process_learns(tmp_path, cloud1):
    """DRF adds OOB accounting + row sampling + mtries on top of the GBM
    path — the 2-process OOB AUC must match single-process within noise."""
    p = str(tmp_path / "drf.csv")
    _write_gbm_csv(p)

    import h2o3_tpu as h2o
    from h2o3_tpu.models.drf import H2ORandomForestEstimator

    fr = h2o.import_file(p)
    fr["y"] = fr["y"].asfactor()
    ref = H2ORandomForestEstimator(ntrees=10, max_depth=6, seed=9)
    ref.train(x=[f"x{i}" for i in range(6)] + ["c"], y="y",
              training_frame=fr)
    ref_auc = float(ref.model.training_metrics.auc)

    out = str(tmp_path / "drf2.npz")
    run_workers(2, DRF_BODY.format(csv=p, out=out))
    got_auc = float(np.load(out)["auc"])
    assert ref_auc > 0.8
    # different sampling RNG (npad differs) -> tolerance, not bit-identity
    assert got_auc == pytest.approx(ref_auc, abs=0.06)


# ---- round-3 envelope: valid frames, early stopping, QuantilesGlobal, ----
# ---- order-statistic dists, balance_classes, GLM multinomial/p-values ----

VALID_STOP_BODY = """
import numpy as np
import h2o3_tpu as h2o
from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
h2o.init()
fr = h2o.import_file({csv!r})
fr["y"] = fr["y"].asfactor()
va = h2o.import_file({vcsv!r})
va["y"] = va["y"].asfactor()
g = H2OGradientBoostingEstimator(ntrees=40, max_depth=3, seed=5,
                                 learn_rate=0.3, stopping_rounds=2,
                                 score_tree_interval=5,
                                 histogram_type="QuantilesGlobal")
g.train(x=[f"x{{i}}" for i in range(6)] + ["c"], y="y", training_frame=fr,
        validation_frame=va)
import jax
if jax.process_index() == 0:
    m = g.model
    hist = m.scoring_history
    np.savez({out!r}, ntrees=m.ntrees_built,
             vll=np.asarray([h["validation_logloss"] for h in hist]),
             vauc=float(m.validation_metrics.logloss))
print("rank", jax.process_index(), "ok")
"""


def test_gbm_valid_early_stop_quantiles_two_process(tmp_path, cloud1):
    """validation_frame + stopping_rounds + QuantilesGlobal binning on a
    2-process cloud: the scoring-history validation logloss is globally
    reduced, so the early-stop decision and stopped tree count must match
    the single-process run."""
    p = str(tmp_path / "t.csv")
    pv = str(tmp_path / "v.csv")
    _write_gbm_csv(p, n=3000)
    _write_gbm_csv(pv, n=1000, seed=99)

    import h2o3_tpu as h2o
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

    fr = h2o.import_file(p)
    fr["y"] = fr["y"].asfactor()
    va = h2o.import_file(pv)
    va["y"] = va["y"].asfactor()
    ref = H2OGradientBoostingEstimator(ntrees=40, max_depth=3, seed=5,
                                       learn_rate=0.3, stopping_rounds=2,
                                       score_tree_interval=5,
                                       histogram_type="QuantilesGlobal")
    ref.train(x=[f"x{i}" for i in range(6)] + ["c"], y="y",
              training_frame=fr, validation_frame=va)
    rm = ref.model
    ref_vll = np.asarray([h["validation_logloss"] for h in rm.scoring_history])

    out = str(tmp_path / "vs2.npz")
    run_workers(2, VALID_STOP_BODY.format(csv=p, vcsv=pv, out=out))
    got = np.load(out)
    assert int(got["ntrees"]) == rm.ntrees_built
    assert len(got["vll"]) == len(ref_vll)
    np.testing.assert_allclose(got["vll"], ref_vll, rtol=5e-3, atol=5e-3)


QDIST_BODY = """
import numpy as np
import h2o3_tpu as h2o
from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
h2o.init()
fr = h2o.import_file({csv!r})
g = H2OGradientBoostingEstimator(ntrees=10, max_depth=3, seed=5,
                                 distribution="quantile",
                                 quantile_alpha=0.8)
g.train(x=[f"x{{i}}" for i in range(6)] + ["c"], y="x0",
        training_frame=fr)
import jax
if jax.process_index() == 0:
    np.savez({out!r}, rmse=float(g.model.training_metrics.rmse))
print("rank", jax.process_index(), "ok")
"""


BALANCE_BODY = """
import numpy as np
import h2o3_tpu as h2o
from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
h2o.init()
fr = h2o.import_file({csv!r})
fr["y"] = fr["y"].asfactor()
g = H2OGradientBoostingEstimator(ntrees=10, max_depth=3, seed=5,
                                 balance_classes=True)
g.train(x=[f"x{{i}}" for i in range(6)] + ["c"], y="y", training_frame=fr)
import jax
if jax.process_index() == 0:
    t = g.model.forest[0]
    np.savez({out!r}, feat=np.asarray(t.feat),
             auc=float(g.model.training_metrics.auc))
print("rank", jax.process_index(), "ok")
"""


def test_gbm_quantile_dist_and_balance_two_process(tmp_path, cloud1):
    """quantile distribution (global order-statistic init) and
    balance_classes (global class counts) on a 2-process cloud."""
    p = str(tmp_path / "q.csv")
    _write_gbm_csv(p, n=2500)

    import h2o3_tpu as h2o
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

    fr = h2o.import_file(p)
    ref = H2OGradientBoostingEstimator(ntrees=10, max_depth=3, seed=5,
                                       distribution="quantile",
                                       quantile_alpha=0.8)
    ref.train(x=[f"x{i}" for i in range(6)] + ["c"], y="x0",
              training_frame=fr)
    out = str(tmp_path / "qd2.npz")
    run_workers(2, QDIST_BODY.format(csv=p, out=out))
    got = float(np.load(out)["rmse"])
    assert got == pytest.approx(float(ref.model.training_metrics.rmse),
                                rel=0.02)

    fr["y"] = fr["y"].asfactor()
    ref2 = H2OGradientBoostingEstimator(ntrees=10, max_depth=3, seed=5,
                                        balance_classes=True)
    ref2.train(x=[f"x{i}" for i in range(6)] + ["c"], y="y",
               training_frame=fr)
    out2 = str(tmp_path / "bal2.npz")
    run_workers(2, BALANCE_BODY.format(csv=p, out=out2))
    got2 = np.load(out2)
    rt = ref2.model.forest[0]
    assert (got2["feat"] == np.asarray(rt.feat)).mean() > 0.95
    assert float(got2["auc"]) == pytest.approx(
        float(ref2.model.training_metrics.auc), abs=0.02)


GLM_MULTI_BODY = """
import numpy as np
import h2o3_tpu as h2o
from h2o3_tpu.models.glm import H2OGeneralizedLinearEstimator
h2o.init()
fr = h2o.import_file({csv!r})
fr["cls"] = fr["cls"].asfactor()
g = H2OGeneralizedLinearEstimator(family="multinomial", lambda_=0.0)
g.train(x=["x1", "x2", "xs"], y="cls", training_frame=fr)
import jax
if jax.process_index() == 0:
    np.savez({out!r}, beta=np.asarray(g.model.beta, np.float64))
print("rank", jax.process_index(), "ok")
"""


GLM_PV_BODY = """
import numpy as np
import h2o3_tpu as h2o
from h2o3_tpu.models.glm import H2OGeneralizedLinearEstimator
h2o.init()
fr = h2o.import_file({csv!r})
fr["y"] = fr["y"].asfactor()
g = H2OGeneralizedLinearEstimator(family="binomial", lambda_=0.0,
                                  compute_p_values=True)
g.train(x=["x1", "x2", "xs", "cat"], y="y", training_frame=fr)
import jax
if jax.process_index() == 0:
    tab = g.model.coef_with_p_values()
    np.savez({out!r}, pv=np.asarray([r["p_value"] for r in tab], np.float64),
             names=np.asarray([r["names"] for r in tab]))
print("rank", jax.process_index(), "ok")
"""


def _write_multiclass_csv(path, n=3000, seed=21):
    rng = np.random.default_rng(seed)
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    xs = rng.normal(size=n) * 0.5
    logits = np.stack([1.5 * x1, -1.0 * x1 + x2, 0.8 * xs - 0.5 * x2], axis=1)
    cls = (logits + rng.gumbel(size=(n, 3))).argmax(axis=1)
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["x1", "x2", "xs", "cls"])
        for i in range(n):
            w.writerow([f"{x1[i]:.6f}", f"{x2[i]:.6f}", f"{xs[i]:.6f}",
                        f"c{cls[i]}"])


def test_glm_multinomial_two_process(tmp_path, cloud1):
    p = str(tmp_path / "m.csv")
    _write_multiclass_csv(p)

    import h2o3_tpu as h2o
    from h2o3_tpu.models.glm import H2OGeneralizedLinearEstimator

    fr = h2o.import_file(p)
    fr["cls"] = fr["cls"].asfactor()
    ref = H2OGeneralizedLinearEstimator(family="multinomial", lambda_=0.0)
    ref.train(x=["x1", "x2", "xs"], y="cls", training_frame=fr)

    out = str(tmp_path / "m2.npz")
    run_workers(2, GLM_MULTI_BODY.format(csv=p, out=out))
    got = np.load(out)["beta"]
    ref_b = np.asarray(ref.model.beta, np.float64)
    # L-BFGS over a padded global array vs local: same optimum within
    # optimizer tolerance
    np.testing.assert_allclose(got, ref_b, rtol=0.05, atol=0.02)


def test_glm_p_values_two_process(tmp_path, cloud1):
    p = str(tmp_path / "pv.csv")
    _write_glm_csv(p)

    import h2o3_tpu as h2o
    from h2o3_tpu.models.glm import H2OGeneralizedLinearEstimator

    fr = h2o.import_file(p)
    fr["y"] = fr["y"].asfactor()
    ref = H2OGeneralizedLinearEstimator(family="binomial", lambda_=0.0,
                                        compute_p_values=True)
    ref.train(x=["x1", "x2", "xs", "cat"], y="y", training_frame=fr)
    ref_tab = ref.model.coef_with_p_values()
    ref_pv = np.asarray([r["p_value"] for r in ref_tab], np.float64)

    out = str(tmp_path / "pv2.npz")
    run_workers(2, GLM_PV_BODY.format(csv=p, out=out))
    d = np.load(out)
    assert list(d["names"]) == [r["names"] for r in ref_tab]
    np.testing.assert_allclose(d["pv"], ref_pv, rtol=0.05, atol=2e-3)


DL_STOP_BODY = """
import numpy as np
import h2o3_tpu as h2o
from h2o3_tpu.models.deeplearning import H2ODeepLearningEstimator
h2o.init()
fr = h2o.import_file({csv!r})
fr["y"] = fr["y"].asfactor()
d = H2ODeepLearningEstimator(hidden=[16], epochs=50, seed=3,
                             mini_batch_size=64, stopping_rounds=2,
                             score_interval=1,
                             train_samples_per_iteration=2000)
d.train(x=[f"x{{i}}" for i in range(6)] + ["c"], y="y", training_frame=fr)
import jax
if jax.process_index() == 0:
    m = d.model
    np.savez({out!r}, events=len(m.scoring_history),
             auc=float(d.model_performance(fr).auc()))
print("rank", jax.process_index(), "ok")
"""


def test_dl_early_stop_two_process(tmp_path, cloud1):
    """DL early stopping on a 2-process cloud: the any-rank-stops vote must
    keep the ranks aligned (no collective deadlock) and stop before the
    full 50 epochs."""
    p = str(tmp_path / "dls.csv")
    _write_gbm_csv(p)
    out = str(tmp_path / "dls2.npz")
    run_workers(2, DL_STOP_BODY.format(csv=p, out=out), timeout=420)
    got = np.load(out)
    assert int(got["events"]) >= 2          # scored more than once
    assert float(got["auc"]) > 0.8          # actually learned


CKPT_BODY = """
import numpy as np
import h2o3_tpu as h2o
from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
h2o.init()
fr = h2o.import_file({csv!r})
fr["y"] = fr["y"].asfactor()
g1 = H2OGradientBoostingEstimator(ntrees=8, max_depth=3, seed=5)
g1.train(x=[f"x{{i}}" for i in range(6)] + ["c"], y="y", training_frame=fr)
g2 = H2OGradientBoostingEstimator(ntrees=16, max_depth=3, seed=5,
                                  checkpoint=g1)
g2.train(x=[f"x{{i}}" for i in range(6)] + ["c"], y="y", training_frame=fr)
import jax
if jax.process_index() == 0:
    t = g2.model.forest[0]
    np.savez({out!r}, ntrees=g2.model.ntrees_built,
             feat=np.asarray(t.feat),
             auc=float(g2.model.training_metrics.auc))
print("rank", jax.process_index(), "ok")
"""


CALIB_BODY = """
import numpy as np
import h2o3_tpu as h2o
from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
h2o.init()
fr = h2o.import_file({csv!r})
fr["y"] = fr["y"].asfactor()
ca = h2o.import_file({ccsv!r})
ca["y"] = ca["y"].asfactor()
g = H2OGradientBoostingEstimator(ntrees=10, max_depth=3, seed=5,
                                 calibrate_model=True,
                                 calibration_frame=ca)
g.train(x=[f"x{{i}}" for i in range(6)] + ["c"], y="y", training_frame=fr)
pf = g.predict(fr)
import jax
if jax.process_index() == 0:
    cal = np.asarray(pf.vec("cal_p1").numeric_np()) \
        if "cal_p1" in pf.names else np.asarray(pf.vec("1").numeric_np())
    np.savez({out!r}, cal=cal[:50])
print("rank", jax.process_index(), "ok")
"""


def test_gbm_checkpoint_two_process(tmp_path, cloud1):
    """checkpoint continuation on a 2-process cloud: the continued forest
    must match the single-process continuation (same edges, same key
    stream from tree index n_prior)."""
    p = str(tmp_path / "ck.csv")
    _write_gbm_csv(p, n=2500)

    import h2o3_tpu as h2o
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

    fr = h2o.import_file(p)
    fr["y"] = fr["y"].asfactor()
    r1 = H2OGradientBoostingEstimator(ntrees=8, max_depth=3, seed=5)
    r1.train(x=[f"x{i}" for i in range(6)] + ["c"], y="y", training_frame=fr)
    r2 = H2OGradientBoostingEstimator(ntrees=16, max_depth=3, seed=5,
                                      checkpoint=r1)
    r2.train(x=[f"x{i}" for i in range(6)] + ["c"], y="y", training_frame=fr)

    out = str(tmp_path / "ck2.npz")
    run_workers(2, CKPT_BODY.format(csv=p, out=out))
    got = np.load(out)
    assert int(got["ntrees"]) == r2.model.ntrees_built == 16
    rt = np.asarray(r2.model.forest[0].feat)
    assert (got["feat"] == rt).mean() > 0.98
    assert float(got["auc"]) == pytest.approx(
        float(r2.model.training_metrics.auc), abs=0.02)


def test_gbm_calibrate_two_process(tmp_path, cloud1):
    """calibrate_model on a 2-process cloud: the Platt coefficients come
    from globally-summed Newton steps, so calibrated probabilities match
    the single-process fit."""
    p = str(tmp_path / "cal.csv")
    pc = str(tmp_path / "calf.csv")
    _write_gbm_csv(p, n=2500)
    _write_gbm_csv(pc, n=800, seed=31)

    import h2o3_tpu as h2o
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

    fr = h2o.import_file(p)
    fr["y"] = fr["y"].asfactor()
    ca = h2o.import_file(pc)
    ca["y"] = ca["y"].asfactor()
    ref = H2OGradientBoostingEstimator(ntrees=10, max_depth=3, seed=5,
                                       calibrate_model=True,
                                       calibration_frame=ca)
    ref.train(x=[f"x{i}" for i in range(6)] + ["c"], y="y",
              training_frame=fr)
    pref = ref.predict(fr)
    col = "cal_p1" if "cal_p1" in pref.names else "1"
    ref_cal = np.asarray(pref.vec(col).numeric_np())[:50]

    out = str(tmp_path / "cal2.npz")
    run_workers(2, CALIB_BODY.format(csv=p, ccsv=pc, out=out))
    got = np.load(out)["cal"]
    np.testing.assert_allclose(got, ref_cal, rtol=5e-3, atol=5e-3)


DART_BODY = """
import numpy as np
import h2o3_tpu as h2o
from h2o3_tpu.models.xgboost import H2OXGBoostEstimator
h2o.init()
fr = h2o.import_file({csv!r})
fr["y"] = fr["y"].asfactor()
g = H2OXGBoostEstimator(booster="dart", rate_drop=0.3, one_drop=True,
                        ntrees=8, max_depth=3, seed=5)
g.train(x=[f"x{{i}}" for i in range(6)] + ["c"], y="y", training_frame=fr)
import jax
if jax.process_index() == 0:
    np.savez({out!r}, auc=float(g.model.training_metrics.auc))
print("rank", jax.process_index(), "ok")
"""


def test_dart_multiprocess_trains(tmp_path, cloud1):
    """DART's drop/commit round adjustments (jit-concatenated chunk
    selection) must run on a 2-process cloud; the dropout path is
    host-RNG-deterministic so the AUC matches single-process closely."""
    p = str(tmp_path / "dart.csv")
    _write_gbm_csv(p)

    import h2o3_tpu as h2o
    from h2o3_tpu.models.xgboost import H2OXGBoostEstimator

    fr = h2o.import_file(p)
    fr["y"] = fr["y"].asfactor()
    ref = H2OXGBoostEstimator(booster="dart", rate_drop=0.3, one_drop=True,
                              ntrees=8, max_depth=3, seed=5)
    ref.train(x=[f"x{i}" for i in range(6)] + ["c"], y="y",
              training_frame=fr)

    out = str(tmp_path / "dart2.npz")
    run_workers(2, DART_BODY.format(csv=p, out=out))
    got = np.load(out)
    assert float(got["auc"]) == pytest.approx(
        float(ref.model.training_metrics.auc), abs=2e-3)


def _write_rank_csv(path, n=2400, nq=60, seed=9):
    rng = np.random.default_rng(seed)
    qid = np.sort(rng.integers(0, nq, n))
    X = rng.normal(size=(n, 5))
    rel = np.clip((X[:, 0] + 0.5 * X[:, 1]
                   + rng.normal(scale=0.5, size=n)) * 1.2 + 1.5,
                  0, 4).astype(int)
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow([f"f{i}" for i in range(5)] + ["qid", "rel"])
        for i in range(n):
            w.writerow([f"{v:.6f}" for v in X[i]] + [int(qid[i]),
                                                     int(rel[i])])


RANK_BODY = """
import numpy as np
import h2o3_tpu as h2o
from h2o3_tpu.models.xgboost import H2OXGBoostEstimator
h2o.init()
fr = h2o.import_file({csv!r})
xgb = H2OXGBoostEstimator(ntrees=8, max_depth=4, seed=1,
                          objective="rank:ndcg", group_column="qid")
xgb.train(x=[f"f{{i}}" for i in range(5)], y="rel", training_frame=fr)
nd = xgb.ndcg(fr)
import jax
if jax.process_index() == 0:
    np.savez({out!r}, ndcg=float(nd))
print("rank", jax.process_index(), "ok")
"""


@pytest.mark.parametrize("nproc", [2, 4])
def test_lambdarank_multiprocess_matches_single(tmp_path, cloud1, nproc):
    """The custom-objective acid test (VERDICT r03 #4): lambdarank's
    per-query pass sees whole queries even when they span ingest shards —
    the global-gather contract. NDCG@10 must match the single-process
    model closely (identical global inputs; f32 drift only)."""
    p = str(tmp_path / "rank.csv")
    _write_rank_csv(p)

    import h2o3_tpu as h2o
    from h2o3_tpu.models.xgboost import H2OXGBoostEstimator

    fr = h2o.import_file(p)
    ref = H2OXGBoostEstimator(ntrees=8, max_depth=4, seed=1,
                              objective="rank:ndcg", group_column="qid")
    ref.train(x=[f"f{i}" for i in range(5)], y="rel", training_frame=fr)
    ref_ndcg = ref.ndcg(fr)

    out = str(tmp_path / f"rank{nproc}.npz")
    run_workers(nproc, RANK_BODY.format(csv=p, out=out))
    got = np.load(out)
    assert float(got["ndcg"]) == pytest.approx(ref_ndcg, abs=5e-3)


DL_COMPRESSED_BODY = """
import numpy as np
import h2o3_tpu as h2o
from h2o3_tpu.models.model_base import DataInfo
from h2o3_tpu.parallel import distdata
from h2o3_tpu.parallel import mesh as cloudlib
h2o.init()
fr = h2o.import_file({csv!r})
cols = [f"x{{i}}" for i in range(3)] + ["c"]
dinfo = DataInfo(fr, cols, standardize=True)
X = dinfo.fit_transform(fr)               # dense f32 path (global stats)
cloud = cloudlib.cloud()
quota = distdata.local_quota(fr.nrow)
Xd = dinfo.device_design(fr, fit=False, cloud=cloud, quota=quota)
# the uint8-able and int16-able columns really travel compressed
assert dinfo._transfer_groups[0] == 0, dinfo._transfer_groups
assert dinfo._transfer_groups[1] == 1, dinfo._transfer_groups
assert dinfo._transfer_groups[2] == 2, dinfo._transfer_groups
import jax
shards = sorted(Xd.addressable_shards, key=lambda s: s.index[0].start or 0)
local = np.concatenate([np.asarray(s.data) for s in shards])
np.testing.assert_allclose(local[: X.shape[0]], X, rtol=1e-5, atol=1e-5)
# quota-padded tail rows all expand from the same zero fill
tail = local[X.shape[0]:]
if tail.shape[0] > 1:
    assert np.all(tail == tail[:1]), tail
print("rank", jax.process_index(), "ok")
"""


def test_dl_compressed_sharded_ingest_two_process(tmp_path, cloud1):
    """VERDICT r04 #4: on a multi-process cloud the design matrix arrives
    as byte-compressed packs (uint8/int16 integer columns) expanded on
    device, and equals the dense f32 fit_transform path row-for-row."""
    rng = np.random.default_rng(8)
    n = 600
    p = str(tmp_path / "comp.csv")
    with open(p, "w") as f:
        f.write("x0,x1,x2,c,y\n")
        for i in range(n):
            f.write(f"{rng.integers(0, 256)},{rng.integers(-3000, 3000)},"
                    f"{rng.normal():.6f},k{rng.integers(0, 3)},"
                    f"{rng.integers(0, 2)}\n")
    run_workers(2, DL_COMPRESSED_BODY.format(csv=p))


GBLINEAR_BODY = """
import numpy as np
import h2o3_tpu as h2o
from h2o3_tpu.models.xgboost import H2OXGBoostEstimator
h2o.init()
fr = h2o.import_file({csv!r})
m = H2OXGBoostEstimator(booster="gblinear", ntrees=200, learn_rate=0.5,
                        reg_lambda=0.0, reg_alpha=0.0, seed=1)
m.train(x=[f"x{{i}}" for i in range(4)], y="t", training_frame=fr)
import jax
if jax.process_index() == 0:
    c = m.model.coef()
    np.savez({out!r}, **{{k: float(v) for k, v in c.items()}})
print("rank", jax.process_index(), "ok")
"""


def test_gblinear_two_process_matches_single(tmp_path, cloud1):
    """gblinear's global-row ingest: a 2-process cloud converges to the
    same coefficients as single-process (the jitted scan's Xᵀg/(X∘X)ᵀh
    reductions become cross-host collectives via the sharded arrays)."""
    rng = np.random.default_rng(5)
    n = 1200
    X = rng.normal(size=(n, 4))
    t = X @ np.asarray([1.5, -0.5, 0.25, 0.0]) + 0.7
    p = str(tmp_path / "gbl.csv")
    with open(p, "w") as f:
        f.write("x0,x1,x2,x3,t\n")
        for i in range(n):
            f.write(",".join(f"{v:.6f}" for v in X[i]) + f",{t[i]:.6f}\n")

    import h2o3_tpu as h2o
    from h2o3_tpu.models.xgboost import H2OXGBoostEstimator

    fr = h2o.import_file(p)
    ref = H2OXGBoostEstimator(booster="gblinear", ntrees=200, learn_rate=0.5,
                              reg_lambda=0.0, reg_alpha=0.0, seed=1)
    ref.train(x=[f"x{i}" for i in range(4)], y="t", training_frame=fr)
    want = ref.model.coef()

    out = str(tmp_path / "gbl2.npz")
    run_workers(2, GBLINEAR_BODY.format(csv=p, out=out))
    got = np.load(out)
    for k in want:
        assert abs(float(got[k]) - want[k]) < 5e-3, (k, float(got[k]), want[k])


DL_TSPI_BODY = """
import numpy as np
import h2o3_tpu as h2o
from h2o3_tpu.models.deeplearning import H2ODeepLearningEstimator
h2o.init()
fr = h2o.import_file({csv!r})
fr["y"] = fr["y"].asfactor()
d = H2ODeepLearningEstimator(hidden=[16], epochs=8, seed=3,
                             mini_batch_size=32,
                             train_samples_per_iteration=-2,
                             score_duty_cycle=0.05)
d.train(x=[f"x{{i}}" for i in range(6)] + ["c"], y="y", training_frame=fr)
import jax
if jax.process_index() == 0:
    np.savez({out!r}, auc=float(d.model_performance(fr).auc),
             events=len(d.model.scoring_history))
print("rank", jax.process_index(), "ok")
"""


def test_dl_duty_cycle_autotune_two_process(tmp_path, cloud1):
    """train_samples_per_iteration=-2 on a 2-process cloud: the scoring
    duty-cycle skip is a unanimous collective vote, so ranks never desync
    (this config previously forced every scoring event on multiproc)."""
    p = str(tmp_path / "dlt.csv")
    _write_gbm_csv(p)
    out = str(tmp_path / "dlt2.npz")
    run_workers(2, DL_TSPI_BODY.format(csv=p, out=out))
    got = np.load(out)
    assert float(got["auc"]) > 0.85
    # no-skip maximum: total/score_every = 8 epochs * 3000 / 3000 rows = 8
    # events; the duty-cycle skip keeps it at or under that cadence
    assert 1 <= int(got["events"]) <= 8


# ---- ISSUE 18: pod lane bit-identity + 1/N memory pins ----------------------
# Spawn tests (slow-lane reason: each pays 1-2 fresh-interpreter clouds,
# ~60-120 s apiece on the 1-core CI box; the pure layout math runs in
# tier-1 via tests/test_pod_layout.py instead).

POD_GBM_BODY = """
import numpy as np
import h2o3_tpu as h2o
from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
from h2o3_tpu.parallel import distdata
from h2o3_tpu.runtime import memory_ledger
h2o.init()
fr = h2o.import_file({csv!r})
fr["y"] = fr["y"].asfactor()
g = H2OGradientBoostingEstimator(ntrees=30, max_depth=4, seed=5,
                                 score_each_iteration=True,
                                 stopping_rounds=2,
                                 stopping_tolerance=0.05)
g.train(x=[f"x{{i}}" for i in range(6)] + ["c"], y="y", training_frame=fr)
import jax
m = g.model
pred = g.predict(fr)
wm = memory_ledger.peak()
# collective — EVERY rank must call it, not just the rank-0 saver
row_off = distdata.row_offset(fr.nrow)
if jax.process_index() == 0:
    sh = m.scoring_history
    np.savez(
        {out!r},
        feat=np.stack([np.asarray(t.feat) for t in m.forest]),
        bins=np.stack([np.asarray(t.bin) for t in m.forest]),
        thr=np.stack([np.asarray(t.thr) for t in m.forest]),
        val=np.stack([np.asarray(t.value) for t in m.forest]),
        ntrees=m.ntrees_built,
        auc=float(m.training_metrics.auc),
        sh_auc=np.asarray([ev.get("auc") for ev in sh], np.float64),
        sh_ll=np.asarray([ev.get("logloss") for ev in sh], np.float64),
        sh_nt=np.asarray([ev.get("number_of_trees") for ev in sh]),
        vi_names=np.asarray([r[0] for r in m.varimp_table]),
        vi_gain=np.asarray([r[1] for r in m.varimp_table], np.float64),
        p1=pred.vec("1").numeric_np(),
        row_off=row_off,
        peak_host=wm["host_bytes"], peak_dev=wm["device_bytes"])
print("rank", jax.process_index(), "ok")
"""


@pytest.fixture(scope="module")
def pod_gbm_runs(tmp_path_factory):
    """One 2-process pod fit + one 1-device forced-shard (blocks) fit of
    the same frame, shared by the bit-identity and memory-pin tests."""
    tmp = tmp_path_factory.mktemp("pod_gbm")
    p = str(tmp / "gbm.csv")
    _write_gbm_csv(p, n=5000)
    ref_out = str(tmp / "ref.npz")
    pod_out = str(tmp / "pod.npz")
    run_workers(1, POD_GBM_BODY.format(csv=p, out=ref_out),
                extra_env={"H2O3_TREE_SHARD": "1"})
    run_workers(2, POD_GBM_BODY.format(csv=p, out=pod_out))
    return np.load(ref_out), np.load(pod_out)


def test_pod_gbm_bit_identical_to_forced_shard(cloud1, pod_gbm_runs):
    """ISSUE 18 acceptance pin: a 2-process pod GBM fit (trees + chunked
    scoring events + a firing early stop) is BIT-identical to the
    1-device H2O3_TREE_SHARD=1 fit sharing S=8 — forests, varimp,
    scoring history, early-stop tree count, predictions."""
    ref, pod = pod_gbm_runs
    assert int(pod["ntrees"]) == int(ref["ntrees"])
    assert int(ref["ntrees"]) < 30          # the early stop actually fired
    for k in ("feat", "bins", "thr", "val"):
        np.testing.assert_array_equal(pod[k], ref[k], err_msg=k)
    np.testing.assert_array_equal(pod["sh_nt"], ref["sh_nt"])
    np.testing.assert_array_equal(pod["sh_ll"], ref["sh_ll"])
    np.testing.assert_array_equal(pod["sh_auc"], ref["sh_auc"])
    np.testing.assert_array_equal(pod["vi_names"], ref["vi_names"])
    np.testing.assert_array_equal(pod["vi_gain"], ref["vi_gain"])
    # final training_metrics are LOCAL-SHARD on a multi-host cloud by
    # design (the global numbers live in the scoring history, pinned
    # bitwise above) — rank 0's 2500-row AUC only approximates the full one
    assert float(pod["auc"]) == pytest.approx(float(ref["auc"]), abs=0.02)
    # rank 0's chunked-scoring predictions == the same ingest rows of the
    # 1-device fit, bitwise
    off, n0 = int(pod["row_off"]), len(pod["p1"])
    assert off == 0 and 0 < n0 < len(ref["p1"])
    np.testing.assert_array_equal(pod["p1"], ref["p1"][:n0])


def test_pod_gbm_per_rank_memory_scales(cloud1, pod_gbm_runs):
    """ISSUE 18 acceptance pin: per-rank peak host+device bytes of the
    2-process fit are ~1/N of the 1-process fit (ledger-measured, loose
    pin — replicated model/histogram state keeps it above exactly 1/2):
    no rank ever stages the global packed matrix."""
    ref, pod = pod_gbm_runs
    assert int(pod["peak_dev"]) <= 0.75 * int(ref["peak_dev"]), (
        int(pod["peak_dev"]), int(ref["peak_dev"]))
    assert int(pod["peak_host"]) <= 0.80 * int(ref["peak_host"]), (
        int(pod["peak_host"]), int(ref["peak_host"]))


POD_GLM_BODY = """
import numpy as np
import h2o3_tpu as h2o
from h2o3_tpu.models.glm import H2OGeneralizedLinearEstimator
from h2o3_tpu.models import estimator_engine as _est
h2o.init()
fr = h2o.import_file({csv!r})
fr["y"] = fr["y"].asfactor()
g = H2OGeneralizedLinearEstimator(family="binomial", lambda_=0.05,
                                  alpha=0.0, standardize=False,
                                  solver="IRLSM")
g.train(x=["x1", "x2", "x3", "cat"], y="y", training_frame=fr)
import jax
if jax.process_index() == 0:
    c = g.model.coef_norm()
    plans = _est.est_stats()["plans"]
    np.savez({out!r}, path=np.asarray(plans[-1]["path"]),
             **{{k: float(v) for k, v in c.items()}})
print("rank", jax.process_index(), "ok")
"""


def _write_glm_clean_csv(path, n=4000, seed=29):
    """No NAs + standardize=False in the fit: the pod's host-expanded
    design and the comparator's on-device expansion are bitwise the same
    values, so β must match exactly."""
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 3))
    cat = rng.integers(0, 3, size=n)
    eff = 1.1 * X[:, 0] - 0.6 * X[:, 1] + 0.4 * (cat == 2)
    y = (rng.random(n) < 1 / (1 + np.exp(-eff))).astype(int)
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["x1", "x2", "x3", "cat", "y"])
        for i in range(n):
            w.writerow([f"{X[i, 0]:.6f}", f"{X[i, 1]:.6f}",
                        f"{X[i, 2]:.6f}", f"g{cat[i]}",
                        "yes" if y[i] else "no"])


def test_pod_glm_bit_identical_to_forced_shard(tmp_path, cloud1):
    """ISSUE 18 acceptance pin (estimator engine): a 2-process pod GLM
    fit through the fused mesh IRLS is bit-identical to the 1-device
    H2O3_EST_SHARD=1 (blocks) fit sharing S=8."""
    p = str(tmp_path / "glm.csv")
    _write_glm_clean_csv(p)
    ref_out = str(tmp_path / "ref.npz")
    pod_out = str(tmp_path / "pod.npz")
    run_workers(1, POD_GLM_BODY.format(csv=p, out=ref_out),
                extra_env={"H2O3_EST_SHARD": "1"})
    run_workers(2, POD_GLM_BODY.format(csv=p, out=pod_out))
    ref, pod = np.load(ref_out), np.load(pod_out)
    assert str(ref["path"]) == "fused_blocks"
    assert str(pod["path"]) == "fused_mesh"
    ks = [k for k in ref.files if k != "path"]
    assert set(ks) == {k for k in pod.files if k != "path"}
    for k in ks:
        assert float(pod[k]) == float(ref[k]), k
