"""Spawn N local worker processes joined via jax.distributed on CPU — the
analog of the reference's N-JVMs-on-one-host test clouds (SURVEY.md §4:
multi-JVM loopback cloud), exercising real process boundaries that the
8-virtual-device single-process mesh cannot (per-process ingest,
make_array_from_process_local_data, coordination-service collectives)."""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER_PRELUDE = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, {repo!r})
    import jax
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 1)
    except AttributeError:
        # jax < 0.5 has no jax_num_cpu_devices config option — the
        # XLA_FLAGS run_workers exports is the same lever there
        pass
    try:
        # jax 0.4.x CPU backend: cross-process collectives need the gloo
        # implementation selected explicitly (default "none" raises
        # "Multiprocess computations aren't implemented on the CPU
        # backend"); newer jax selects a working default itself
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, ValueError):
        pass
    jax.distributed.initialize(
        coordinator_address=os.environ["H2O3_TEST_COORD"],
        num_processes=int(os.environ["H2O3_TEST_NPROCS"]),
        process_id=int(os.environ["H2O3_TEST_RANK"]),
    )
""")


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def run_workers(n: int, body: str, extra_env=None, timeout=300):
    """Run `body` (python source, after the jax.distributed prelude) in n
    local processes. Returns per-rank CompletedProcess; raises on any
    nonzero exit with the failing rank's output in the message."""
    coord = f"127.0.0.1:{free_port()}"
    script = WORKER_PRELUDE.format(repo=REPO) + textwrap.dedent(body)
    env = dict(os.environ)
    # exactly ONE cpu device per worker: replace the parent suite's
    # 8-virtual-device XLA_FLAGS rather than inheriting it (on jax < 0.5
    # this flag is also the only working lever — the jax_num_cpu_devices
    # config option does not exist there, see WORKER_PRELUDE)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env["PALLAS_AXON_POOL_IPS"] = ""   # disable the axon TPU hook
    env["JAX_PLATFORMS"] = "cpu"
    env["H2O3_TEST_COORD"] = coord
    env["H2O3_TEST_NPROCS"] = str(n)
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_test_cache")
    if extra_env:
        env.update(extra_env)
    procs = []
    for rank in range(n):
        e = dict(env)
        e["H2O3_TEST_RANK"] = str(rank)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", script], env=e,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = []
    for rank, p in enumerate(procs):
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise AssertionError(f"worker {rank} timed out")
        outs.append(out)
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, (
            f"worker {rank} exited {p.returncode}:\n{out[-4000:]}")
    return outs
