#!/usr/bin/env python
"""Benchmark driver — HIGGS-like GBM training wall-clock (the BASELINE.json
flagship config: H2OGradientBoostingEstimator, 100 trees,
histogram_type=UniformAdaptive, binary response).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
The real HIGGS csv is not shipped in this image; the synthetic generator
reproduces its shape (11M rows × 28 numeric features in the full set; we
default to 1M rows to keep the bench under control) with an XOR-ish nonlinear
response so the trees actually learn. vs_baseline is wall-clock relative to
BASELINE.md's reference number when one exists (none published in-repo —
SURVEY.md §6), else 1.0.
"""

import json
import os
import sys
import time

# persistent XLA compilation cache: repeat bench runs (fresh processes) skip
# the ~20s trace+compile of the per-tree program and measure training itself
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_bench_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")

import numpy as np


def make_higgs_like(n_rows: int, n_feat: int = 28, seed: int = 0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n_rows, n_feat)).astype(np.float32)
    logits = (
        1.2 * X[:, 0]
        - 0.8 * X[:, 1]
        + 1.5 * X[:, 2] * X[:, 3]
        + 0.7 * np.sin(3 * X[:, 4])
        + 0.5 * (X[:, 5] ** 2 - 1)
    )
    y = (rng.random(n_rows) < 1 / (1 + np.exp(-logits))).astype(np.float32)
    return X, y


def main():
    n_rows = int(os.environ.get("BENCH_ROWS", 1_000_000))
    ntrees = int(os.environ.get("BENCH_TREES", 100))
    max_depth = int(os.environ.get("BENCH_DEPTH", 6))

    import jax

    # env vars alone do not engage the persistent cache under the remote-TPU
    # plugin — the config must be set programmatically
    jax.config.update("jax_compilation_cache_dir",
                      os.environ["JAX_COMPILATION_CACHE_DIR"])
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

    X, y = make_higgs_like(n_rows)
    names = [f"f{i}" for i in range(X.shape[1])] + ["label"]
    fr = Frame.from_numpy(np.column_stack([X, y]), names=names).asfactor("label")

    gbm = H2OGradientBoostingEstimator(
        ntrees=ntrees, max_depth=max_depth, learn_rate=0.1,
        histogram_type="UniformAdaptive", seed=42,
    )
    t0 = time.time()
    gbm.train(y="label", training_frame=fr)
    wall = time.time() - t0
    auc = gbm.auc()

    result = {
        "metric": f"higgs_gbm_{n_rows//1000}k_{ntrees}trees_wall_s",
        "value": round(wall, 3),
        "unit": "s",
        "vs_baseline": 1.0,
        "auc": round(float(auc), 5),
        "backend": __import__("jax").default_backend(),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
