#!/usr/bin/env python
"""Benchmark driver — HIGGS-like GBM training wall-clock (the BASELINE.json
flagship config: H2OGradientBoostingEstimator, 100 trees,
histogram_type=UniformAdaptive, binary response).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
The real HIGGS csv is not shipped in this image; the synthetic generator
reproduces its shape (11M rows × 28 numeric features in the full set; we
default to 1M rows to keep the bench under control) with an XOR-ish nonlinear
response so the trees actually learn. vs_baseline compares against the best
recorded round-2 warm measurements in R02_BASELINE below (mirrored in
BASELINE.md), normalized so >1.0 always means better than the best known
prior state; metrics without an anchor (env-overridden shapes) report 1.0.
Each config runs BENCH_REPEATS times (per-config defaults below) and the
best run is reported, with all runs in the `runs` field.
"""

import json
import os
import sys
import threading
import time

# persistent XLA compilation cache: repeat bench runs (fresh processes) skip
# the ~20s trace+compile of the per-tree program and measure training itself
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_bench_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.1")
# per-phase accounting (VERDICT r04 #2): training drivers sync at phase
# boundaries and record {h2d, compile, deserialize, compute, ...} so the
# JSON decomposes wall-clock instead of conflating tunnel + compile + MXU
os.environ.setdefault("H2O3_PHASE_ACCOUNTING", "1")

import numpy as np


def _note_devices() -> int:
    """Record the device count the training path actually spans — the
    `n_devices` bench axis (ISSUE 12): 1 on a lone chip or under the
    H2O3_TREE_SHARD=0 escape hatch, N on a mesh running sharded fits.
    Comparing a `higgs_gbm` line across rounds without this axis conflates
    chip speed with scale-out. Called from the bench fns (main thread,
    backend known-good) and CACHED so `_n_devices` readers — notably the
    watchdog thread escaping a HUNG backend — never call into jax, whose
    backend-init lock may be held by the stuck main thread."""
    try:
        import jax

        nd = (1 if os.environ.get("H2O3_TREE_SHARD", "").strip() == "0"
              else int(jax.device_count()))
    except Exception:
        nd = 1
    _RUN_STATE["n_devices"] = nd
    return nd


def _n_devices() -> int:
    """The cached device count (`_note_devices`); 1 before any bench fn
    has observed the backend. NEVER initializes or queries jax — safe
    from the watchdog thread while the main thread hangs in the
    backend."""
    return int(_RUN_STATE.get("n_devices") or 1)


def _note_ranks():
    """`n_ranks` bench axis (ISSUE 18): process count of the pod this fit
    spanned, None (dropped from the record) on single-process clouds —
    a pod record is distinguishable from an N-virtual-device one."""
    try:
        import jax

        nr = int(jax.process_count())
    except Exception:
        nr = 1
    _RUN_STATE["n_ranks"] = nr
    return nr if nr > 1 else None


def make_higgs_like(n_rows: int, n_feat: int = 28, seed: int = 0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n_rows, n_feat)).astype(np.float32)
    logits = (
        1.2 * X[:, 0]
        - 0.8 * X[:, 1]
        + 1.5 * X[:, 2] * X[:, 3]
        + 0.7 * np.sin(3 * X[:, 4])
        + 0.5 * (X[:, 5] ** 2 - 1)
    )
    y = (rng.random(n_rows) < 1 / (1 + np.exp(-logits))).astype(np.float32)
    return X, y


def bench_gbm():
    """Flagship: HIGGS-like GBM (BASELINE.json config 1)."""
    n_rows = int(os.environ.get("BENCH_ROWS", 1_000_000))
    ntrees = int(os.environ.get("BENCH_TREES", 100))
    max_depth = int(os.environ.get("BENCH_DEPTH", 6))
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

    X, y = make_higgs_like(n_rows)
    names = [f"f{i}" for i in range(X.shape[1])] + ["label"]
    fr = Frame.from_numpy(np.column_stack([X, y]), names=names).asfactor("label")
    gbm = H2OGradientBoostingEstimator(
        ntrees=ntrees, max_depth=max_depth, learn_rate=0.1,
        histogram_type="UniformAdaptive", seed=42,
    )
    lane_seq0 = _lane_seq()
    t0 = time.time()
    gbm.train(y="label", training_frame=fr)
    wall = time.time() - t0
    # roofline-style utilization: the hist kernel streams 1 byte of bin
    # code per (row, feature, level, tree) update — updates/s and the
    # implied code-read GB/s make "fast" auditable against chip peak
    from h2o3_tpu.runtime import phases as _phz

    comp = _phz.snapshot().get("compute_s") or wall
    updates = n_rows * X.shape[1] * max_depth * ntrees
    return (f"higgs_gbm_{n_rows//1000}k_{ntrees}trees_wall_s", wall,
            {"auc": round(float(gbm.auc()), 5),
             "n_devices": _note_devices(),
             "n_ranks": _note_ranks(),
             "collective_skew_ms": _skew_embed(lane_seq0),
             "hist_updates_per_s": round(updates / comp),
             "hist_stream_gbps": round(updates / comp / 1e9, 3),
             # present when the fit auto-streamed (device or host budget
             # exceeded): block/spill counters beside the memory embeds
             "stream": getattr(gbm.model, "_stream_stats", None) or None})


def bench_gbm_cpu():
    """Forced-CPU GBM trajectory lane (ISSUE 7): a scaled-down higgs-like
    fit through the SAME fused hot path as the device config — packed-code
    host histograms (`np.add.at` callback), single-pass split search,
    overlapped chunk scoring — plus one H2O3_TREE_LEGACY=1 comparator rep,
    so the lane keeps measuring kernel progress when the accelerator
    tunnel is down (round 5 recorded a value-0.0 `gbm_unavailable` line
    instead). Never probes the accelerator, so there is nothing to fail.
    Acceptance floor: vs_seed ≥ 1.5 (pinned as a slow test)."""
    n_rows = int(os.environ.get("BENCH_ROWS", 100_000))
    ntrees = int(os.environ.get("BENCH_TREES", 20))
    max_depth = int(os.environ.get("BENCH_DEPTH", 6))
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models.dataset_cache import clear as _cache_clear
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

    X, y = make_higgs_like(n_rows)
    names = [f"f{i}" for i in range(X.shape[1])] + ["label"]
    from h2o3_tpu.runtime import phases as _phz_mod

    def run(legacy, reps):
        best, auc = float("inf"), None
        for _ in range(reps):
            _cache_clear()
            with _forced_env("H2O3_TREE_LEGACY", legacy):
                fr = Frame.from_numpy(np.column_stack([X, y]),
                                      names=names).asfactor("label")
                gbm = H2OGradientBoostingEstimator(
                    ntrees=ntrees, max_depth=max_depth, learn_rate=0.1,
                    histogram_type="UniformAdaptive", seed=42,
                    score_tree_interval=max(ntrees // 4, 1))
                t0 = time.perf_counter()
                gbm.train(y="label", training_frame=fr)
                best = min(best, time.perf_counter() - t0)
            auc = float(gbm.auc())
        return best, auc

    # best-of-2 for BOTH paths (rep 1 absorbs each path's own trace +
    # compile, so vs_seed compares warm kernels with warm kernels); phase
    # accounting stays on for both (same barriers, comparable walls), but
    # the record embeds the FUSED reps' phase split only — buckets mixed
    # across comparator paths decompose nothing
    _phz_mod.reset()
    lane_seq0 = _lane_seq()
    wall_new, auc = run(False, reps=2)
    fused_phases = _phz_mod.snapshot()
    # snapshot BEFORE the legacy comparator reps: the embed describes the
    # fused measurement's fences only
    skew = _skew_embed(lane_seq0)
    _phz_mod.reset()
    wall_seed, _ = run(True, reps=2)
    _phz_mod.reset()
    return (f"gbm_cpu_{n_rows//1000}k_{ntrees}trees_wall_s", wall_new,
            {"auc": round(auc, 5),
             "n_devices": _note_devices(),
             "collective_skew_ms": skew,
             "seed_wall_s": round(wall_seed, 3),
             "vs_seed": round(wall_seed / wall_new, 2),
             "phases": fused_phases or None})


def bench_glm():
    """Airlines-like logistic GLM, IRLS (BASELINE.json config 2): mixed
    numeric + high-cardinality categoricals, like Year/Month/Origin/Dest."""
    n_rows = int(os.environ.get("BENCH_ROWS", 1_000_000))
    rng = np.random.default_rng(0)
    import h2o3_tpu as h2o
    from h2o3_tpu.models.glm import H2OGeneralizedLinearEstimator

    dep = rng.integers(0, 2400, n_rows).astype(np.float64)
    dist = np.abs(rng.normal(800, 500, n_rows))
    origin = rng.integers(0, 100, n_rows)
    dest = rng.integers(0, 100, n_rows)
    month = rng.integers(0, 12, n_rows)
    dow = rng.integers(0, 7, n_rows)
    eff = (0.002 * (dep - 1200) + 0.4 * (origin % 7 == 0)
           - 0.3 * (dest % 11 == 0) + 0.1 * (dow >= 5))
    y = (rng.random(n_rows) < 1 / (1 + np.exp(-eff))).astype(int)
    fr = h2o.H2OFrame_from_python(
        {"DepTime": dep, "Distance": dist,
         "Origin": np.char.add("O", origin.astype(str)),
         "Dest": np.char.add("D", dest.astype(str)),
         "Month": month.astype(str), "DayOfWeek": dow.astype(str),
         "IsDepDelayed": np.where(y == 1, "YES", "NO")},
        column_types={"Origin": "enum", "Dest": "enum", "Month": "enum",
                      "DayOfWeek": "enum", "IsDepDelayed": "enum"})
    glm = H2OGeneralizedLinearEstimator(family="binomial", solver="IRLSM",
                                        lambda_=0.0)
    t0 = time.time()
    glm.train(y="IsDepDelayed", training_frame=fr)
    wall = time.time() - t0
    return (f"airlines_glm_{n_rows//1000}k_wall_s", wall,
            {"auc": round(float(glm.auc()), 5)})


def bench_dl():
    """MNIST-like DeepLearning (BASELINE.json config 3): 784→200→200→10
    rectifier MLP, sync-DP SGD replacing Hogwild; reports samples/sec."""
    n_rows = int(os.environ.get("BENCH_ROWS", 60_000))
    epochs = float(os.environ.get("BENCH_EPOCHS", 5))
    rng = np.random.default_rng(0)
    import h2o3_tpu as h2o
    from h2o3_tpu.models.deeplearning import H2ODeepLearningEstimator

    # MNIST is uint8 pixel intensities — integer-valued features, like the
    # real benchmark input (the DL path ships them over the tunnel at
    # 1 byte/value, the C1Chunk-compression analog)
    X = np.floor(rng.random((n_rows, 784)) * 256).astype(np.float32)
    proto = rng.normal(size=(10, 784)).astype(np.float32)
    y = ((X / 255.0) @ proto.T
         + 0.5 * rng.normal(size=(n_rows, 10))).argmax(axis=1)
    d = {f"p{i}": X[:, i] for i in range(784)}
    d["label"] = y.astype(str)
    fr = h2o.H2OFrame_from_python(d, column_types={"label": "enum"})
    dl = H2ODeepLearningEstimator(hidden=[200, 200], activation="Rectifier",
                                  epochs=epochs, seed=1)
    t0 = time.time()
    dl.train(y="label", training_frame=fr)
    wall = time.time() - t0
    sps = n_rows * epochs / wall
    # fwd+bwd ≈ 3× the forward matmul FLOPs of the 784→200→200→10 MLP
    flops_per_sample = 3 * 2 * (784 * 200 + 200 * 200 + 200 * 10)
    return (f"mnist_dl_{n_rows//1000}k_samples_per_s", sps,
            {"wall_s": round(wall, 3), "unit_override": "samples/s",
             "gflops": round(sps * flops_per_sample / 1e9, 2)})


def bench_xgb_rank():
    """MSLR-like lambdarank XGBoost (BASELINE.json config 4):
    tree_method=tpu_hist, NDCG@10 objective over query groups."""
    n_rows = int(os.environ.get("BENCH_ROWS", 200_000))
    ntrees = int(os.environ.get("BENCH_TREES", 50))
    rng = np.random.default_rng(0)
    import h2o3_tpu as h2o
    from h2o3_tpu.models.xgboost import H2OXGBoostEstimator

    nq = n_rows // 100
    qid = np.sort(rng.integers(0, nq, n_rows))
    X = rng.normal(size=(n_rows, 40)).astype(np.float32)
    rel = np.clip((X[:, 0] + 0.5 * X[:, 1] + rng.normal(scale=0.5, size=n_rows)
                   ) * 1.2 + 1.5, 0, 4).astype(int)
    d = {f"f{i}": X[:, i] for i in range(40)}
    d["qid"] = qid.astype(np.float64)
    d["rel"] = rel.astype(np.float64)
    fr = h2o.H2OFrame_from_python(d)
    xgb = H2OXGBoostEstimator(ntrees=ntrees, max_depth=6, seed=1,
                              objective="rank:ndcg", group_column="qid")
    t0 = time.time()
    xgb.train(x=[f"f{i}" for i in range(40)], y="rel", training_frame=fr)
    wall = time.time() - t0
    ndcg = xgb.ndcg(fr)
    return (f"mslr_xgb_rank_{n_rows//1000}k_{ntrees}trees_wall_s", wall,
            {"ndcg10": round(float(ndcg), 5)})


def bench_score():
    """Deep-forest scoring on a FRESH frame (VERDICT r03 #1): DRF 50 trees
    depth-20 on 50k rows, then warm `model_performance(new_frame)` — the
    path that taxes AutoML leaderboard_frame, calibration, and REST
    Predictions. Uses the fused subtree-fetch scorer (models/tree.py
    `predict_forest_fused`)."""
    n_rows = int(os.environ.get("BENCH_ROWS", 50_000))
    ntrees = int(os.environ.get("BENCH_TREES", 50))
    import time as _t
    import h2o3_tpu as h2o
    from h2o3_tpu.models.drf import H2ORandomForestEstimator

    X, y = make_higgs_like(n_rows, n_feat=12)
    d = {f"f{i}": X[:, i] for i in range(12)}
    d["label"] = y.astype(int).astype(str)
    fr = h2o.H2OFrame_from_python(d, column_types={"label": "enum"})
    drf = H2ORandomForestEstimator(ntrees=ntrees, max_depth=20, seed=1)
    drf.train(y="label", training_frame=fr)
    Xs, ys = make_higgs_like(n_rows, n_feat=12, seed=7)
    ds = {f"f{i}": Xs[:, i] for i in range(12)}
    ds["label"] = ys.astype(int).astype(str)
    frs = h2o.H2OFrame_from_python(ds, column_types={"label": "enum"})
    perf = drf.model_performance(frs)      # first call: table build + compile
    best = float("inf")
    for _ in range(3):
        t0 = _t.time()
        perf = drf.model_performance(frs)
        best = min(best, _t.time() - t0)
    return (f"drf_score_{n_rows//1000}k_{ntrees}t_d20_wall_s", best,
            {"auc": round(float(perf.auc()), 5)})


def bench_oversubscription():
    """Out-of-core streaming lane (ISSUE 14): a GBM fit whose packed code
    matrix is ~10× the stream budget, measured three ways in one record —
    STREAMED (`H2O3_TREE_OOC=1`, blocked host↔device double buffering),
    the IN-CORE comparator (`H2O3_TREE_OOC=0` + the matching blocked
    reduction — the bit-identical baseline), and streamed with
    gradient-based SAMPLING on (`goss=True`: later trees stream a fraction
    of the bytes). Forced-CPU like gbm_cpu, so the lane keeps measuring
    the streaming machinery when the accelerator tunnel is down and stays
    comparable round over round; the budget is forced small
    (`H2O3_STREAM_BUDGET_MB` = matrix/10) so oversubscription is real on
    any host. The record embeds streamed bytes, the resident-block peak
    (asserted ≤ budget) and block counters next to the memory embeds."""
    n_rows = int(os.environ.get("BENCH_ROWS", 120_000))
    ntrees = int(os.environ.get("BENCH_TREES", 12))
    max_depth = int(os.environ.get("BENCH_DEPTH", 5))
    n_feat = 16
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models.dataset_cache import clear as _cache_clear
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

    X, y = make_higgs_like(n_rows, n_feat=n_feat)
    names = [f"f{i}" for i in range(n_feat)] + ["label"]
    # 5-bit pack at the default nbins=20 → ~n·F·5/8 packed bytes; force
    # the budget to a tenth of that so the fit is genuinely out of core
    budget_mb = max(n_rows * n_feat * 5 / 8 / 1e6 / 10, 0.05)
    keys = ("H2O3_TREE_OOC", "H2O3_STREAM_BUDGET_MB", "H2O3_TREE_SHARD",
            "H2O3_TREE_SHARD_BLOCKS", "H2O3_STREAM_BLOCKS",
            "H2O3_WARM_THREAD")

    def run(env, goss=False):
        _cache_clear()
        saved = {k: os.environ.pop(k, None) for k in keys}
        os.environ.update(env)
        try:
            fr = Frame.from_numpy(np.column_stack([X, y]),
                                  names=names).asfactor("label")
            gbm = H2OGradientBoostingEstimator(
                ntrees=ntrees, max_depth=max_depth, learn_rate=0.1,
                histogram_type="UniformAdaptive", seed=42,
                score_tree_interval=max(ntrees // 4, 1),
                **(dict(goss=True, goss_start_tree=max(ntrees // 4, 1))
                   if goss else {}))
            t0 = time.perf_counter()
            gbm.train(y="label", training_frame=fr)
            return time.perf_counter() - t0, gbm
        finally:
            for k in keys:
                os.environ.pop(k, None)
                if saved.get(k) is not None:
                    os.environ[k] = saved[k]

    budget = f"{budget_mb:.3f}"
    wall_stream, m_stream = run({"H2O3_TREE_OOC": "1",
                                 "H2O3_STREAM_BUDGET_MB": budget})
    st = getattr(m_stream.model, "_stream_stats", {}) or {}
    blocks = str(st.get("blocks", 8))
    # in-core comparator shares the streamed fit's block grid so the two
    # walls bracket the same bit-identical computation. Warm thread stays
    # ON (round 19): the old H2O3_WARM_THREAD=0 here worked around the
    # 1-core in-graph callback deadlock, which `host_callback_safe` now
    # closes at method selection — single-core hosts keep the segment
    # kernel, so the comparator rep can no longer wedge
    wall_incore, _ = run({"H2O3_TREE_OOC": "0", "H2O3_TREE_SHARD": "1",
                          "H2O3_TREE_SHARD_BLOCKS": blocks})
    wall_goss, m_goss = run({"H2O3_TREE_OOC": "1",
                             "H2O3_STREAM_BUDGET_MB": budget}, goss=True)
    gs = getattr(m_goss.model, "_stream_stats", {}) or {}
    return (f"oversub_{n_rows//1000}k_{ntrees}trees_wall_s", wall_stream,
            {"auc": round(float(m_stream.auc()), 5),
             "n_devices": _note_devices(),
             "stream_budget_mb": float(budget),
             "incore_wall_s": round(wall_incore, 3),
             "goss_wall_s": round(wall_goss, 3),
             "vs_incore": round(wall_incore / wall_stream, 3),
             "goss_vs_streamed": round(wall_stream / wall_goss, 3),
             "streamed_bytes": st.get("streamed_bytes"),
             "goss_streamed_bytes": gs.get("streamed_bytes"),
             "resident_block_peak": st.get("resident_block_peak"),
             "stream": st or None})


def bench_disk_oversubscription():
    """Three-tier disk-spill lane (round 19): a GBM fit whose packed code
    matrix exceeds BOTH a forced device budget and a forced HOST budget
    (matrix/10 each), measured four ways in one record — SPILLED (host
    blocks overflow to disk files and stream back through the resuming
    reader), HOST-STREAMED (same device budget, disk tier off — the PR 14
    two-tier shape), the IN-CORE comparator on the same block grid (the
    bit-identical baseline), and GOSS-ON-DISK (sampling on: later trees
    gather compact samples and read measurably fewer spill bytes). Forced
    CPU like the oversubscription lane, so the record stays comparable
    round over round and never emits a value-0.0 line. Embeds the spill
    counters (`spilled/restored` blocks+bytes), `disk_bytes`, and the
    host-resident watermark (asserted ≤ the forced host budget by the
    tier-1 pins)."""
    n_rows = int(os.environ.get("BENCH_ROWS", 120_000))
    ntrees = int(os.environ.get("BENCH_TREES", 12))
    max_depth = int(os.environ.get("BENCH_DEPTH", 5))
    n_feat = 16
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models.dataset_cache import clear as _cache_clear
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

    X, y = make_higgs_like(n_rows, n_feat=n_feat)
    names = [f"f{i}" for i in range(n_feat)] + ["label"]
    budget_mb = max(n_rows * n_feat * 5 / 8 / 1e6 / 10, 0.05)
    keys = ("H2O3_TREE_OOC", "H2O3_STREAM_BUDGET_MB",
            "H2O3_STREAM_HOST_BUDGET_MB", "H2O3_TREE_OOC_DISK",
            "H2O3_TREE_SHARD", "H2O3_TREE_SHARD_BLOCKS",
            "H2O3_STREAM_BLOCKS")

    def run(env, goss=False):
        _cache_clear()
        saved = {k: os.environ.pop(k, None) for k in keys}
        os.environ.update(env)
        try:
            fr = Frame.from_numpy(np.column_stack([X, y]),
                                  names=names).asfactor("label")
            gbm = H2OGradientBoostingEstimator(
                ntrees=ntrees, max_depth=max_depth, learn_rate=0.1,
                histogram_type="UniformAdaptive", seed=42,
                score_tree_interval=max(ntrees // 4, 1),
                **(dict(goss=True, goss_start_tree=max(ntrees // 4, 1))
                   if goss else {}))
            t0 = time.perf_counter()
            gbm.train(y="label", training_frame=fr)
            return time.perf_counter() - t0, gbm
        finally:
            for k in keys:
                os.environ.pop(k, None)
                if saved.get(k) is not None:
                    os.environ[k] = saved[k]

    budget = f"{budget_mb:.3f}"
    spill_env = {"H2O3_TREE_OOC": "1", "H2O3_STREAM_BUDGET_MB": budget,
                 "H2O3_STREAM_HOST_BUDGET_MB": budget}
    wall_spill, m_spill = run(spill_env)
    st = getattr(m_spill.model, "_stream_stats", {}) or {}
    blocks = str(st.get("blocks", 8))
    # same device budget, disk tier OFF — isolates the spill tier's cost
    # from the host↔device streaming it rides on
    wall_host, m_host = run({"H2O3_TREE_OOC": "1",
                             "H2O3_STREAM_BUDGET_MB": budget,
                             "H2O3_TREE_OOC_DISK": "0"})
    hs = getattr(m_host.model, "_stream_stats", {}) or {}
    wall_incore, _ = run({"H2O3_TREE_OOC": "0", "H2O3_TREE_SHARD": "1",
                          "H2O3_TREE_SHARD_BLOCKS": blocks})
    wall_goss, m_goss = run(spill_env, goss=True)
    gs = getattr(m_goss.model, "_stream_stats", {}) or {}
    return (f"disk_oversub_{n_rows//1000}k_{ntrees}trees_wall_s",
            wall_spill,
            {"auc": round(float(m_spill.auc()), 5),
             "n_devices": _note_devices(),
             "stream_budget_mb": float(budget),
             "host_budget_mb": float(budget),
             "host_streamed_wall_s": round(wall_host, 3),
             "incore_wall_s": round(wall_incore, 3),
             "goss_wall_s": round(wall_goss, 3),
             "vs_incore": round(wall_incore / wall_spill, 3),
             "vs_host_streamed": round(wall_host / wall_spill, 3),
             "spilled_bytes": st.get("spilled_bytes"),
             "restored_bytes": st.get("restored_bytes"),
             "goss_restored_bytes": gs.get("restored_bytes"),
             "disk_bytes": st.get("disk_bytes"),
             "resident_host_peak": st.get("resident_host_peak"),
             "host_streamed_spilled_bytes": hs.get("spilled_bytes"),
             "stream": st or None,
             "goss_stream": gs or None})


def bench_estimators():
    """Fused estimator-engine lane (ISSUE 15): GLM lambda path + K-Means +
    PCA on ONE cached frame, measured fused vs the `H2O3_EST_LEGACY=1`
    comparator (host per-iteration loops: per-λ/per-Lloyd-step dispatch +
    sync + host solves, re-extracting the float matrix per fit). Forced-CPU
    like gbm_cpu — never probes the accelerator, so the lane keeps
    measuring engine progress when the tunnel is down. Acceptance: vs_seed
    (legacy wall / fused wall over the combined three-fit sequence) ≥ 3 at
    equal results (the tier-1 parity matrix pins equality).

    Default shape: 8k×12 — the dispatch-bound small/medium-fit regime the
    engine targets (an AutoML sweep's non-tree candidates), where the
    per-iteration dispatch + sync + host-solve round-trips the fused
    programs eliminate ARE the wall. At ≥24k rows on a forced-CPU host the
    per-iteration einsum compute dominates both paths and the ratio
    compresses toward 1 (recorded in docs/perf.md §7); on a real
    accelerator behind a tunnel the round-trip term grows with latency,
    not rows, so the fused win holds at scale there."""
    n_rows = int(os.environ.get("BENCH_ROWS", 8_000))
    kmeans_iters = int(os.environ.get("BENCH_KMEANS_ITERS", 120))
    nlambdas = int(os.environ.get("BENCH_NLAMBDAS", 30))
    n_feat = 12
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models.dataset_cache import clear as _cache_clear
    from h2o3_tpu.models.dataset_cache import snapshot as _cache_snap
    from h2o3_tpu.models.glm import H2OGeneralizedLinearEstimator
    from h2o3_tpu.models.kmeans import H2OKMeansEstimator
    from h2o3_tpu.models.pca import H2OPrincipalComponentAnalysisEstimator
    from h2o3_tpu.runtime import phases as _phz_mod

    X, y = make_higgs_like(n_rows, n_feat=n_feat)
    names = [f"f{i}" for i in range(n_feat)] + ["label"]
    xcols = names[:-1]

    def run(legacy, reps):
        best = float("inf")
        walls = auc = None
        for _ in range(reps):
            _cache_clear()
            with _forced_env("H2O3_EST_LEGACY", legacy):
                fr = Frame.from_numpy(np.column_stack([X, y]),
                                      names=names).asfactor("label")
                t0 = time.perf_counter()
                glm = H2OGeneralizedLinearEstimator(
                    family="binomial", lambda_search=True,
                    nlambdas=nlambdas, alpha=0.5, seed=42)
                glm.train(x=xcols, y="label", training_frame=fr)
                t1 = time.perf_counter()
                km = H2OKMeansEstimator(k=8, max_iterations=kmeans_iters,
                                        init="PlusPlus", seed=42)
                km.train(x=xcols, training_frame=fr)
                t2 = time.perf_counter()
                pca = H2OPrincipalComponentAnalysisEstimator(
                    k=5, transform="STANDARDIZE", pca_method="Randomized",
                    seed=42)
                pca.train(x=xcols, training_frame=fr)
                t3 = time.perf_counter()
                if t3 - t0 < best:
                    best = t3 - t0
                    walls = {"glm_s": round(t1 - t0, 3),
                             "kmeans_s": round(t2 - t1, 3),
                             "pca_s": round(t3 - t2, 3)}
                    auc = round(float(glm.auc()), 5)
        return best, walls, auc

    # best-of-2 for BOTH paths (rep 1 absorbs each path's own trace +
    # compile, so vs_seed compares warm programs with warm programs — the
    # gbm_cpu stance)
    _phz_mod.reset()
    wall_fused, walls_fused, auc = run(False, reps=2)
    fused_phases = _phz_mod.snapshot()
    cache = _cache_snap()
    _phz_mod.reset()
    wall_seed, walls_seed, _ = run(True, reps=2)
    _phz_mod.reset()
    return (f"estimators_{n_rows//1000}k_glm_kmeans_pca_wall_s", wall_fused,
            {"auc": auc,
             "n_devices": _note_devices(),
             "seed_wall_s": round(wall_seed, 3),
             "vs_seed": round(wall_seed / wall_fused, 2),
             "walls": walls_fused,
             "seed_walls": walls_seed,
             "std_cache": {k: cache.get(k) for k in ("std_hits",
                                                     "std_misses")},
             "phases": fused_phases or None})


from contextlib import contextmanager


@contextmanager
def _forced_env(name: str, on: bool):
    """Force a legacy-comparator env flag on or OFF for one timed rep —
    a pre-exported value must not mislabel the non-legacy reps — then
    restore whatever the operator had set."""
    prior = os.environ.get(name)
    if on:
        os.environ[name] = "1"
    else:
        os.environ.pop(name, None)
    try:
        yield
    finally:
        if prior is None:
            os.environ.pop(name, None)
        else:
            os.environ[name] = prior


def _write_ingest_csv(path: str, target_mb: float, seed: int = 0) -> int:
    """Synthesize a mixed numeric/enum CSV of ~target_mb MB (16 numeric
    columns with NA holes + 4 enum columns, quoted cells in one — the
    HIGGS-like numeric-heavy shape the flagship GBM bench ingests) and
    return the row count. Built in vectorized blocks so generation stays a
    small fraction of the parse being measured."""
    rng = np.random.default_rng(seed)
    levels = np.asarray([f"lvl{i}" for i in range(40)])
    n_num = 16
    block = 50_000
    rows = 0
    with open(path, "w") as f:
        f.write(",".join([f"n{i}" for i in range(n_num)]
                         + ["e0", "e1", "e2", "e3"]) + "\n")
        while f.tell() < target_mb * 1e6:
            cols = []
            for j in range(n_num):
                c = rng.normal(scale=10.0 ** (j % 6), size=block) \
                    .round(4).astype(str)
                c[rng.random(block) < 0.03] = "NA"   # NA-token holes
                cols.append(c)
            cols.append(rng.integers(0, 7, block).astype(str))
            cols.append(rng.choice(levels, block))
            cols.append(np.char.add("city ", rng.integers(0, 200, block).astype(str)))
            # ~1% quoted cells carrying the separator — enough to exercise
            # the RFC-4180 fallback without drowning the bulk fast path
            e2 = np.char.add("tag", rng.integers(0, 9, block).astype(str))
            qm = rng.random(block) < 0.01
            e2 = np.where(qm, np.char.add(np.char.add('"q,', e2), '"'), e2)
            cols.append(e2)
            out = cols[0]
            for c in cols[1:]:
                out = np.char.add(np.char.add(out, ","), c)
            f.write("\n".join(out.tolist()) + "\n")
            rows += block
    return rows


def bench_ingest():
    """Chunked parallel CSV ingest (ISSUE 2): ~50 MB mixed numeric/enum CSV
    in tmp; reports rows/s of the N-thread chunked parse plus the speedups
    vs a 1-thread chunked run and vs the legacy per-line tokenizer
    (acceptance: chunked ≥ 3× legacy on a multi-core host)."""
    import shutil
    import tempfile

    mb = float(os.environ.get("BENCH_INGEST_MB", 50))
    from h2o3_tpu.frame.parse import parse_csv

    tmpdir = tempfile.mkdtemp(prefix="h2o3_ingest_bench_")
    path = os.path.join(tmpdir, "ingest_bench.csv")
    try:
        nrows = _write_ingest_csv(path, mb)

        def run(nthreads=None, legacy=False, reps=2):
            best = float("inf")
            for _ in range(reps):   # best-of-reps damps scheduler noise
                with _forced_env("H2O3_INGEST_LEGACY", legacy):
                    t0 = time.perf_counter()
                    fr = parse_csv(path, nthreads=nthreads)
                    best = min(best, time.perf_counter() - t0)
                assert fr.nrow == nrows, (fr.nrow, nrows)
            return nrows / best, best

        legacy_rps, legacy_s = run(legacy=True, reps=1)
        st_rps, st_s = run(nthreads=1)
        par_rps, par_s = run(nthreads=os.cpu_count() or 1)
        size_mb = os.path.getsize(path) / 1e6
        return (f"csv_ingest_{int(round(size_mb))}mb_rows_per_s", par_rps,
                {"unit_override": "rows/s",
                 "wall_s": round(par_s, 3),
                 "rows": nrows,
                 "mb": round(size_mb, 1),
                 "mb_per_s": round(size_mb / par_s, 1),
                 "nthreads": os.cpu_count() or 1,
                 "speedup_vs_legacy": round(par_rps / legacy_rps, 2),
                 "speedup_vs_1thread": round(par_rps / st_rps, 2),
                 "legacy_rows_per_s": round(legacy_rps),
                 "onethread_rows_per_s": round(st_rps)})
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)


def bench_munge():
    """Vectorized munging engine (ISSUE 3): radix join + group-by + pivot
    over a ~1M-row two-key frame; reports rows/s of the vectorized merge
    plus the speedups vs the seed per-row paths (H2O3_MUNGE_LEGACY=1;
    acceptance: merge ≥ 5× legacy rows/s on a 2-core host). Pure host
    numpy — never needs the accelerator, so there is no probe to fail and
    never a value-0.0 line."""
    n_rows = int(os.environ.get("BENCH_MUNGE_ROWS",
                                os.environ.get("BENCH_ROWS", 1_000_000)))
    from h2o3_tpu.frame import rapids as R
    from h2o3_tpu.frame.frame import Frame

    rng = np.random.default_rng(0)
    levels = np.asarray([f"L{i}" for i in range(1000)])
    left = Frame.from_dict(
        {"k1": rng.choice(levels, n_rows).astype(object),
         "k2": rng.integers(0, 100, n_rows).astype(float),
         "x": rng.random(n_rows)},
        column_types={"k1": "enum"})
    m = max(n_rows // 5, 1)
    rlevels = np.asarray([f"L{i}" for i in range(1200)])
    right = Frame.from_dict(
        {"k1": rng.choice(rlevels, m).astype(object),
         "k2": rng.integers(0, 110, m).astype(float),
         "y": rng.random(m)},
        column_types={"k1": "enum"})
    plong = Frame.from_dict(
        {"i": rng.integers(0, 2000, n_rows).astype(float),
         "c": rng.integers(0, 12, n_rows).astype(float),
         "v": rng.random(n_rows)})

    def best(fn, reps=2, legacy=False):
        t_best = float("inf")
        for _ in range(reps):
            with _forced_env("H2O3_MUNGE_LEGACY", legacy):
                t0 = time.perf_counter()
                fn()
                t_best = min(t_best, time.perf_counter() - t0)
        return t_best

    do_merge = lambda: R.merge(left, right, by=["k1", "k2"], all_x=True)  # noqa: E731
    do_gb = lambda: left.group_by(["k1", "k2"]).mean("x").sum("x").get_frame()  # noqa: E731
    do_pivot = lambda: plong.pivot("i", "c", "v")  # noqa: E731
    t_merge = best(do_merge)
    t_merge_legacy = best(do_merge, reps=1, legacy=True)
    t_gb = best(do_gb)
    t_pivot = best(do_pivot)
    t_pivot_legacy = best(do_pivot, reps=1, legacy=True)
    rps = n_rows / t_merge
    legacy_rps = n_rows / t_merge_legacy
    return (f"munge_merge_{n_rows//1000}k_rows_per_s", rps,
            {"unit_override": "rows/s",
             "wall_s": round(t_merge, 3),
             "rows": n_rows,
             "vs_seed": round(rps / legacy_rps, 2),
             "legacy_rows_per_s": round(legacy_rps),
             "groupby_rows_per_s": round(n_rows / t_gb),
             "pivot_rows_per_s": round(n_rows / t_pivot),
             "pivot_vs_seed": round(t_pivot_legacy / t_pivot, 2)})


_SCALING_CHILD = r"""
import json, os, time, sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", {nd})
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
try:
    from jax import shard_map
except ImportError:
    from jax.experimental.shard_map import shard_map
sys.path.insert(0, {repo!r})
from h2o3_tpu.frame.binning import build_bins
from h2o3_tpu.models import tree as treelib
from h2o3_tpu.parallel import mesh as cloudlib

nd = {nd}
cloud = cloudlib.init(jax.devices()[:nd])
rng = np.random.default_rng(0)
N, F, B, D = {rows}, 28, 64, 6
X = rng.normal(size=(N, F)).astype(np.float32)
y = (X[:, 0] + X[:, 1] * X[:, 2] > 0).astype(np.float32)
bm = build_bins(X, nbins=B)
edges = np.full((F, B - 2), np.inf, np.float32)
for j, e in enumerate(bm.edges):
    edges[j, : len(e)] = e
rspec = P(cloudlib.ROWS_AXIS)
codes = jax.device_put(jnp.asarray(bm.codes), cloud.row_sharding())
yj = jax.device_put(jnp.asarray(y), cloud.row_sharding())
margin = jax.device_put(jnp.zeros(N, jnp.float32), cloud.row_sharding())
edges_j = jax.device_put(jnp.asarray(edges), cloud.replicated())

def train_step(codes, margin, y, edges):
    p = jax.nn.sigmoid(margin)
    g, h = p - y, p * (1 - p)
    tree, leaf_idx, _, _ = treelib.build_tree(
        codes, g, h, jnp.ones_like(g), jnp.ones(F, jnp.float32), edges,
        max_depth=D, nbins=B, min_rows=1.0, axis_name=cloudlib.ROWS_AXIS)
    return margin + 0.1 * tree.value[leaf_idx]

fn = jax.jit(shard_map(train_step, mesh=cloud.mesh,
                       in_specs=(rspec, rspec, rspec, P()),
                       out_specs=rspec))
m = fn(codes, margin, yj, edges_j)
jax.block_until_ready(m)            # compile absorb (real barrier on CPU)
reps = {reps}
t0 = time.perf_counter()
for _ in range(reps):
    m = fn(codes, m, yj, edges_j)
jax.block_until_ready(m)
print(json.dumps(dict(nd=nd, step_ms=(time.perf_counter() - t0) / reps * 1e3)))
"""


def bench_scaling():
    """1/2/4/8-virtual-device scaling curve (VERDICT r03 #8 — the
    BASELINE.json "1→8 host" metric's measurable analog here): the
    flagship GBM tree-build step over a row-sharded CPU mesh at FIXED
    global rows. The virtual devices share one host's cores, so the curve
    bounds collective/sharding overhead rather than demonstrating chip
    speedup — bit-identity across cloud sizes is pinned separately by
    tests/test_multiprocess.py."""
    import json as _json
    import subprocess
    import sys as _sys

    rows = int(os.environ.get("BENCH_ROWS", 131_072))
    reps = int(os.environ.get("BENCH_REPEATS_STEPS", 5))
    repo = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ, PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    times = {}
    for nd in (1, 2, 4, 8):
        src = _SCALING_CHILD.format(nd=nd, rows=rows, reps=reps, repo=repo)
        # own session + registered pgid so the watchdog can reap the child
        # instead of orphaning a core-burning subprocess on _exit
        p = subprocess.Popen([_sys.executable, "-c", src], env=env,
                             stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                             text=True, start_new_session=True)
        _LIVE_CHILD_PGIDS.add(p.pid)
        try:
            stdout, stderr = p.communicate(timeout=1200)
        except subprocess.TimeoutExpired:
            import signal

            try:
                os.killpg(p.pid, signal.SIGKILL)
            except OSError:
                pass
            p.communicate()
            raise RuntimeError(f"scaling child nd={nd} timed out") from None
        finally:
            _LIVE_CHILD_PGIDS.discard(p.pid)
        line = [ln for ln in stdout.splitlines() if ln.startswith("{")]
        if not line:
            raise RuntimeError(f"scaling child nd={nd} failed: {stderr[-2000:]}")
        times[nd] = _json.loads(line[-1])["step_ms"]
    ratio = times[1] / max(times[8], 1e-9)
    return ("scaling_1to8dev_step_speedup", ratio,
            {"step_ms": {str(k): round(v, 1) for k, v in times.items()},
             "rows": rows, "unit_override": "x"})


def bench_grid():
    """Parallel multi-model training (ISSUE 4): a small GBM grid with
    5-fold CV, reporting rows-trained/s of the pooled path (shared
    dataset-artifact cache + CV fold reuse + parallelism) and the speedup
    vs the sequential seed walk (H2O3_TRAIN_LEGACY=1: no cache, per-fold
    re-bin, no pool). Works forced-CPU (BENCH_PLATFORM=cpu skips the
    probe); acceptance floor: vs_seed ≥ 2 on a 2-core host."""
    n_rows = int(os.environ.get("BENCH_ROWS", 20_000))
    ntrees = int(os.environ.get("BENCH_TREES", 20))
    nfolds = int(os.environ.get("BENCH_FOLDS", 5))
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models.dataset_cache import clear as _cache_clear
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
    from h2o3_tpu.models.grid import H2OGridSearch

    X, y = make_higgs_like(n_rows, n_feat=12)
    names = [f"f{i}" for i in range(12)] + ["label"]
    fr = Frame.from_numpy(np.column_stack([X, y]), names=names) \
        .asfactor("label")
    hyper = {"max_depth": [3, 4], "learn_rate": [0.1, 0.2]}
    n_combos = 4
    # oversubscribe the cores: candidates spend real wall in host python /
    # dispatch gaps, so 4 in flight beat cpu_count on a 2-core box
    par = 4
    # the per-chunk phase-accounting sync barriers serialize exactly the
    # overlap this bench measures — time both paths without them
    from h2o3_tpu.runtime import phases as _phz_mod

    acct_prior = _phz_mod.ENABLED
    _phz_mod.ENABLED = False

    def run(parallelism, legacy, reps=1):
        best = float("inf")
        for _ in range(reps):
            _cache_clear()
            with _forced_env("H2O3_TRAIN_LEGACY", legacy):
                grid = H2OGridSearch(
                    H2OGradientBoostingEstimator(
                        ntrees=ntrees, nfolds=nfolds, seed=42,
                        histogram_type="UniformAdaptive"),
                    hyper, parallelism=parallelism)
                t0 = time.perf_counter()
                grid.train(y="label", training_frame=fr)
                best = min(best, time.perf_counter() - t0)
            assert len(grid.models) == n_combos, grid.failed
        return best

    # pooled reps first (rep 1 absorbs compile into the shared cache), the
    # legacy comparator last — both measure compile-warm walls
    try:
        wall_new = run(par, legacy=False, reps=2)
        wall_seq = run(1, legacy=True, reps=1)
    finally:
        _phz_mod.ENABLED = acct_prior
    # the phase buckets accumulated across both comparator paths and all
    # reps (and without the accounting barriers) — meaningless as a
    # decomposition of the reported wall; drop them from this config
    _phz_mod.reset()
    # every candidate trains the parent fit + nfolds fold fits
    rows_trained = n_combos * (nfolds + 1) * n_rows
    rps = rows_trained / wall_new
    return (f"grid_gbm_{n_rows//1000}k_{n_combos}combo_{nfolds}cv_rows_per_s",
            rps,
            {"unit_override": "rows/s",
             "wall_s": round(wall_new, 3),
             "seq_seed_wall_s": round(wall_seq, 3),
             "vs_seed": round(wall_seq / wall_new, 2),
             "rows": n_rows, "n_models": n_combos, "nfolds": nfolds,
             "parallelism": par,
             "seed_rows_per_s": round(rows_trained / wall_seq)})


def bench_chaos():
    """Chaos smoke (ISSUE 5): loadgen against a live REST serving engine
    with 1% injected scorer device-faults (`serving.scorer`, seeded). The
    quarantine → rebuild → CPU-fallback failover path must keep p99 finite
    and the hard-error rate at zero — a crashing scorer degrades to
    latency, never to a 5xx storm. Reports p99 under fault injection plus
    the failover counters."""
    n_rows = int(os.environ.get("BENCH_ROWS", 5_000))
    threads = int(os.environ.get("BENCH_CHAOS_THREADS", 6))
    requests = int(os.environ.get("BENCH_CHAOS_REQUESTS", 40))
    fault_rate = float(os.environ.get("BENCH_CHAOS_FAULT_RATE", 0.01))
    import sys as _sys

    _sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "deploy"))
    from loadgen import run_load

    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
    from h2o3_tpu.rest.server import start_server
    from h2o3_tpu.runtime import faults
    from h2o3_tpu.runtime.dkv import DKV
    from h2o3_tpu.serving import get_engine

    X, y = make_higgs_like(n_rows, n_feat=8)
    names = [f"f{i}" for i in range(8)] + ["label"]
    fr = Frame.from_numpy(np.column_stack([X, y]), names=names) \
        .asfactor("label")
    gbm = H2OGradientBoostingEstimator(ntrees=10, max_depth=4, seed=42)
    gbm.train(y="label", training_frame=fr)
    DKV.put("chaos_gbm", gbm.model)
    score_fr = Frame({n: fr.vec(n) for n in names[:-1]})
    score_fr.key = "chaos_frame"
    DKV.put(score_fr.key, score_fr)
    srv = start_server(port=0)
    try:
        # warm the serving path before arming faults so the measured run
        # exercises failover, not first-compile
        run_load("127.0.0.1", srv.port, "chaos_gbm", "chaos_frame",
                 threads=2, requests=2)
        faults.arm("serving.scorer", error="device", rate=fault_rate,
                   seed=int(os.environ.get("BENCH_CHAOS_SEED", 1)))
        t0 = time.time()
        stats = run_load("127.0.0.1", srv.port, "chaos_gbm", "chaos_frame",
                         threads=threads, requests=requests)
        wall = time.time() - t0
        eng = get_engine().snapshot()["totals"]
    finally:
        faults.reset()
        srv.stop()
    total = threads * requests
    err_rate = stats["errors"] / max(total, 1)
    p99 = stats["p99_ms"]
    assert p99 is not None and np.isfinite(p99), "p99 must stay finite"
    assert err_rate <= 0.01, f"error rate {err_rate} above bound"
    return (f"chaos_serving_{n_rows//1000}k_p99_ms", p99,
            {"unit_override": "ms", "wall_s": round(wall, 3),
             "completed": stats["completed"], "errors": stats["errors"],
             "shed_429": stats["shed_429"],
             "error_rate": round(err_rate, 4),
             "fault_rate": fault_rate,
             "throughput_rps": stats["throughput_rps"],
             "p50_ms": stats["p50_ms"],
             "scorer_faults": eng.get("scorer_faults", 0),
             "quarantines": eng.get("quarantines", 0),
             "fallback_scores": eng.get("fallback_scores", 0),
             "breaker_opens": eng.get("breaker_opens", 0)})


_POD_CHAOS_WORKER = """
import os, sys, json, time
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 1)
except AttributeError:
    pass
try:
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except (AttributeError, ValueError):
    pass
jax.distributed.initialize(
    coordinator_address=os.environ["H2O3_POD_COORD"],
    num_processes=int(os.environ["H2O3_POD_NPROCS"]),
    process_id=int(os.environ["H2O3_POD_RANK"]),
)
import numpy as np
import h2o3_tpu as h2o
from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
from h2o3_tpu.runtime import supervisor
h2o.init()
fr = h2o.import_file({csv!r})
fr["y"] = fr["y"].asfactor()
g = H2OGradientBoostingEstimator(ntrees=20, max_depth=3, seed=5,
                                 score_tree_interval=5)
t0 = time.time()
err = None
try:
    g.train(x=[f"x{{i}}" for i in range(6)], y="y", training_frame=fr)
except BaseException as e:
    err = f"{{type(e).__name__}}: {{e}}"
snap = supervisor.snapshot()
info = dict(rank=jax.process_index(), error=err, wall_s=time.time() - t0,
            aborts=snap["totals"]["aborts"], last_abort=snap["last_abort"],
            last_resume=snap["last_resume"],
            resumes=snap["totals"]["resumes"])
if jax.process_index() == 0:
    with open({info!r}, "w") as f:
        json.dump(info, f, default=str)
    if err is None:
        m = g.model
        np.savez({out!r},
                 feat=np.stack([np.asarray(t.feat) for t in m.forest]),
                 bins=np.stack([np.asarray(t.bin) for t in m.forest]),
                 thr=np.stack([np.asarray(t.thr) for t in m.forest]),
                 val=np.stack([np.asarray(t.value) for t in m.forest]),
                 ntrees=m.ntrees_built,
                 sh_ll=np.asarray([ev.get("logloss")
                                   for ev in m.scoring_history], np.float64),
                 vi_gain=np.asarray([r[1] for r in m.varimp_table],
                                    np.float64))
print("rank", jax.process_index(), "done err=", err)
"""


def _pod_chaos_spawn(nproc, csv, out, info, extra_env=None, rank_env=None,
                     timeout=600):
    """Spawn an n-rank loopback pod running the pod_chaos worker. Unlike a
    test harness this does NOT assert rc==0 — rank death (rc 43) is the
    scenario. Returns per-rank (rc, output)."""
    import socket
    import subprocess

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        coord = f"127.0.0.1:{s.getsockname()[1]}"
    repo = os.path.dirname(os.path.abspath(__file__))
    script = _POD_CHAOS_WORKER.format(repo=repo, csv=csv, out=out, info=info)
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env["H2O3_POD_COORD"] = coord
    env["H2O3_POD_NPROCS"] = str(nproc)
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_bench_cache")
    env.update(extra_env or {})
    procs = []
    for rank in range(nproc):
        e = dict(env)
        e["H2O3_POD_RANK"] = str(rank)
        e.update((rank_env or {}).get(rank, {}))
        p = subprocess.Popen([sys.executable, "-c", script], env=e,
                             stdout=subprocess.PIPE,
                             stderr=subprocess.STDOUT, text=True,
                             start_new_session=True)
        _LIVE_CHILD_PGIDS.add(p.pid)
        procs.append(p)
    results = []
    for rank, p in enumerate(procs):
        try:
            outp, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            import signal

            for q in procs:
                try:
                    os.killpg(q.pid, signal.SIGKILL)
                except OSError:
                    pass
            raise RuntimeError(
                f"pod_chaos rank {rank} exceeded {timeout}s — the abort "
                "deadline did not fire (the hang this lane exists to "
                "catch)") from None
        finally:
            _LIVE_CHILD_PGIDS.discard(p.pid)
        results.append((p.returncode, outp))
    return results


def bench_pod_chaos():
    """Pod chaos lane (ISSUE 20): a 2-process pod GBM fit loses one rank
    mid-fit (armed ``mesh.rank_kill`` hard-exits it at a collective
    arrival), the survivor's deadline'd fence aborts within
    H2O3_FENCE_DEADLINE_S instead of hanging (never a silent rc:124), and
    a degraded single-host resume (H2O3_TREE_SHARD=1, same shard plan S)
    restores the rank-sharded checkpoints and completes BIT-IDENTICAL to
    an undisturbed comparator fit. Reports detection latency, abort
    count, and trees retrained after the kill."""
    import csv as _csv
    import json as _json
    import tempfile

    deadline_s = float(os.environ.get("BENCH_POD_DEADLINE_S", 15))
    # the rank_kill point is checked at the ONE instrumented fence per
    # scoring interval (ops/histogram ordered_axis_fold's event-loss tag),
    # so a 20-tree fit at score_tree_interval=5 sees only ~4 arrivals per
    # rank: after=2 lands the kill at the 3rd arrival (~tree 15), with the
    # tree-5/10 checkpoints already committed
    kill_after = int(os.environ.get("BENCH_POD_KILL_AFTER", 2))
    tmp = tempfile.mkdtemp(prefix="pod_chaos_")
    ckpt_dir = os.path.join(tmp, "ckpt")
    csv_p = os.path.join(tmp, "data.csv")
    rng = np.random.default_rng(7)
    Xc = rng.normal(size=(5000, 6))
    yc = (Xc[:, 0] + 0.8 * Xc[:, 1] * Xc[:, 2]
          + 0.3 * rng.normal(size=5000) > 0).astype(int)
    with open(csv_p, "w", newline="") as f:
        w = _csv.writer(f)
        w.writerow([f"x{i}" for i in range(6)] + ["y"])
        for i in range(5000):
            w.writerow([f"{v:.6f}" for v in Xc[i]] + [int(yc[i])])

    shared = {"H2O3_CKPT_DIR": ckpt_dir, "H2O3_CKPT_TREES": "5"}
    # A: undisturbed 1-process forced-shard comparator (same S as the pod)
    ref_out = os.path.join(tmp, "ref.npz")
    res = _pod_chaos_spawn(1, csv_p, ref_out, os.path.join(tmp, "ref.json"),
                           extra_env={"H2O3_TREE_SHARD": "1",
                                      "H2O3_CKPT": "0"})
    if res[0][0] != 0 or not os.path.exists(ref_out):
        raise RuntimeError(f"comparator fit failed: {res[0][1][-2000:]}")
    # B: 2-rank pod; rank 1 dies at its (kill_after+1)-th collective
    # arrival; rank 0's fences run under the supervisor deadline. The
    # doomed pod gets a THROWAWAY compilation cache: os._exit mid-write
    # would tear the shared persistent cache and the resume leg then
    # segfaults deserializing the torn entry (observed once) — cache
    # poisoning is a different failure than the one this lane pins
    info_p = os.path.join(tmp, "chaos.json")
    t_kill = time.time()
    res = _pod_chaos_spawn(
        2, csv_p, os.path.join(tmp, "pod.npz"), info_p,
        extra_env=dict(shared, H2O3_FENCE_DEADLINE_S=str(deadline_s),
                       JAX_COMPILATION_CACHE_DIR=os.path.join(
                           tmp, "xla_cache_b")),
        rank_env={1: {"H2O3_FAULT_MESH_RANK_KILL":
                      f"error=crash,count=1,after={kill_after}"}},
        timeout=max(deadline_s * 8, 240))
    detect_wall = time.time() - t_kill
    assert res[1][0] == 43, (
        f"rank 1 should have been hard-killed (rc 43), got {res[1][0]}:"
        f"\n{res[1][1][-2000:]}")
    chaos = _json.loads(open(info_p).read()) if os.path.exists(info_p) \
        else {}
    assert chaos.get("error"), (
        "rank 0 completed despite a dead peer — the kill never landed:"
        f"\n{res[0][1][-2000:]}")
    ckpts = [f for f in os.listdir(ckpt_dir)] if os.path.isdir(ckpt_dir) \
        else []
    assert ckpts, "no fit checkpoints were committed before the kill"
    # C: degraded single-host resume on the SAME shard plan S — restores
    # the rank-sharded snapshots (rank-ordered concat) and completes
    res_out = os.path.join(tmp, "resumed.npz")
    res_info = os.path.join(tmp, "resumed.json")
    res = _pod_chaos_spawn(1, csv_p, res_out, res_info,
                           extra_env=dict(shared, H2O3_TREE_SHARD="1"))
    if res[0][0] != 0 or not os.path.exists(res_out):
        raise RuntimeError(f"degraded resume failed: {res[0][1][-2000:]}")
    rinfo = _json.loads(open(res_info).read())
    restored = int((rinfo.get("last_resume") or {}).get("restored") or 0)
    assert restored > 0, f"resume did not restore a checkpoint: {rinfo}"
    ref, got = np.load(ref_out), np.load(res_out)
    assert int(got["ntrees"]) == int(ref["ntrees"])
    for k in ("feat", "bins", "thr", "val", "vi_gain", "sh_ll"):
        np.testing.assert_array_equal(got[k], ref[k], err_msg=k)
    abort = chaos.get("last_abort") or {}
    detect_s = abort.get("latency_s", None)
    return ("pod_chaos_detect_s",
            float(detect_s if detect_s is not None else detect_wall),
            {"unit_override": "s",
             "aborts": int(chaos.get("aborts") or 0),
             "abort_error": str(chaos.get("error"))[:160],
             "suspect_ranks": (abort.get("suspect_ranks") if abort
                               else None),
             "detect_wall_s": round(detect_wall, 2),
             "deadline_s": deadline_s,
             "restored_at_tree": restored,
             "trees_retrained": int(got["ntrees"]) - restored,
             "ckpt_files": len(ckpts),
             "resumed_mid_fit": int(rinfo.get("resumes") or 0),
             "bitexact": True})


def bench_serving():
    """Serving-SLO lane (ROADMAP item 4 groundwork): open-loop loadgen at
    a FIXED arrival rate against a live REST serving engine — queueing
    delay shows up as latency instead of reduced offered load, so p99 is
    an SLO verdict rather than a throughput echo. Percentiles come from
    the shared fixed latency buckets (runtime/metrics_registry
    LATENCY_MS_BOUNDS), bucket-comparable with GET /3/Metrics. Forced-CPU
    like the chaos lane (the failure-era alternative was a value-0.0
    line): the micro-batcher + admission behavior under load is
    backend-representative on CPU."""
    n_rows = int(os.environ.get("BENCH_ROWS", 2_000))
    rate = float(os.environ.get("BENCH_SERVING_RATE", 25))
    duration = float(os.environ.get("BENCH_SERVING_S", 10))
    import sys as _sys

    _sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "deploy"))
    from loadgen import run_load, run_load_open

    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
    from h2o3_tpu.rest.server import start_server
    from h2o3_tpu.runtime import phases as _phz
    from h2o3_tpu.runtime.dkv import DKV

    X, y = make_higgs_like(n_rows, n_feat=8)
    names = [f"f{i}" for i in range(8)] + ["label"]
    fr = Frame.from_numpy(np.column_stack([X, y]), names=names) \
        .asfactor("label")
    gbm = H2OGradientBoostingEstimator(ntrees=10, max_depth=4, seed=42)
    gbm.train(y="label", training_frame=fr)
    DKV.put("slo_gbm", gbm.model)
    score_fr = Frame({n: fr.vec(n) for n in names[:-1]})
    score_fr.key = "slo_frame"
    DKV.put(score_fr.key, score_fr)
    srv = start_server(port=0)
    try:
        # closed-loop warm-up: the measured open-loop window must exercise
        # steady-state batching, not first-compile of the scorer buckets
        run_load("127.0.0.1", srv.port, "slo_gbm", "slo_frame",
                 threads=2, requests=2)
        xla0 = _phz.xla_counts()
        stats = run_load_open("127.0.0.1", srv.port, "slo_gbm",
                              "slo_frame", rate=rate, duration_s=duration)
        xla1 = _phz.xla_counts()
    finally:
        srv.stop()
    p99 = stats["p99_ms"]
    assert p99 is not None and np.isfinite(p99), "p99 must be measurable"
    err_rate = stats["errors"] / max(stats["offered"], 1)
    assert err_rate <= 0.01, f"hard errors under open load: {stats}"
    # the warm-path pin, in the artifact: a steady-state serving window
    # must not trace a single new program
    new_traces = xla1["traces"] - xla0["traces"]
    # leak canary (ISSUE 8): per-decile RSS/ledger samples → growth slope;
    # past the floor the record is TAGGED (soft fail — a leak verdict must
    # not erase the latency measurement it rode along with)
    growth = stats.get("mem_growth_bytes_per_min")
    floor_mb = float(os.environ.get("BENCH_MEM_GROWTH_FLOOR_MB_MIN", 64))
    exceeded = growth is not None and growth > floor_mb * 1e6
    # router self-description (ISSUE 16): every serving-path record embeds
    # the router's shed counters next to the memory canary — zeros when no
    # router ran in this process (peek, never instantiate)
    from h2o3_tpu.serving import peek_router

    rt = peek_router()
    rt_totals = rt.snapshot(probe=False)["totals"] if rt is not None \
        else {}
    return (f"serving_openloop_{int(rate)}rps_p99_ms", p99,
            {"unit_override": "ms",
             "rate_rps": rate, "duration_s": duration,
             "offered": stats["offered"], "completed": stats["completed"],
             "shed_429": stats["shed_429"], "dropped": stats["dropped"],
             "errors": stats["errors"],
             "achieved_rps": stats["achieved_rps"],
             "drain_s": stats["drain_s"],
             "p50_ms": stats["p50_ms"], "p95_ms": stats["p95_ms"],
             "steady_state_new_traces": new_traces,
             "mem_growth_bytes_per_min": growth,
             "ledger_growth_bytes_per_min":
                 stats.get("ledger_growth_bytes_per_min"),
             "mem_growth_exceeded": True if exceeded else None,
             "router_shed": rt_totals.get("shed", 0),
             "router_rollbacks": rt_totals.get("rollbacks", 0),
             "router_failovers": rt_totals.get("failovers", 0)})


def bench_qos():
    """Multi-tenant QoS lane (ISSUE 19, ROADMAP item 5): serving-shaped
    open-loop load CONCURRENTLY with a 4-candidate GBM grid sweep on the
    same device, three windows in one record:

      1. idle — open-loop against a quiet server: the near-idle SLO p99
      2. contended, QoS OFF — the same load while the sweep trains with
         the gate disarmed: the unbounded-blowup comparator
      3. contended, QoS ON — gate armed, SLO knob set to the idle p99:
         the headline; acceptance wants p99_on ≲ ~2× idle

    The headline metric is the QoS-ON contended p99; the record embeds
    the idle baseline, the QoS-OFF comparator, both ratios, the sweep
    walls and the qos yield/wait totals — never a value-0.0 line.
    Forced-CPU like the chaos/serving lanes. Candidates use
    score_tree_interval=1 (per-tree chunks → densest yield cadence)."""
    n_rows = int(os.environ.get("BENCH_ROWS", 2_000))
    rate = float(os.environ.get("BENCH_QOS_RATE", 15))
    window = float(os.environ.get("BENCH_QOS_WINDOW_S", 6))
    sweep_rows = int(os.environ.get("BENCH_QOS_SWEEP_ROWS", 20_000))
    candidates = int(os.environ.get("BENCH_QOS_CANDIDATES", 4))
    sweep_trees = int(os.environ.get("BENCH_QOS_SWEEP_TREES", 10))
    import sys as _sys

    _sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "deploy"))
    from loadgen import run_concurrent_sweep, run_load, run_load_open

    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
    from h2o3_tpu.rest.server import start_server
    from h2o3_tpu.runtime import qos as _qos
    from h2o3_tpu.runtime.dkv import DKV

    X, y = make_higgs_like(n_rows, n_feat=8)
    names = [f"f{i}" for i in range(8)] + ["label"]
    fr = Frame.from_numpy(np.column_stack([X, y]), names=names) \
        .asfactor("label")
    gbm = H2OGradientBoostingEstimator(ntrees=10, max_depth=4, seed=42)
    gbm.train(y="label", training_frame=fr)
    DKV.put("qos_gbm", gbm.model)
    score_fr = Frame({n: fr.vec(n) for n in names[:-1]})
    score_fr.key = "qos_frame"
    DKV.put(score_fr.key, score_fr)
    qos_env = {k: v for k, v in os.environ.items()
               if k.startswith("H2O3_QOS")}
    srv = start_server(port=0)
    try:
        # closed-loop warm-up: the measured windows must exercise
        # steady-state batching, not first-compile of the scorer buckets
        run_load("127.0.0.1", srv.port, "qos_gbm", "qos_frame",
                 threads=2, requests=2)
        # window 1: idle SLO baseline
        os.environ.pop("H2O3_QOS", None)
        idle = run_load_open("127.0.0.1", srv.port, "qos_gbm", "qos_frame",
                             rate=rate, duration_s=window)
        idle_p99 = idle["p99_ms"]
        assert idle_p99 is not None and np.isfinite(idle_p99), \
            "idle p99 must be measurable"
        # window 2: contended with the gate DISARMED — the comparator
        off = run_concurrent_sweep(
            "127.0.0.1", srv.port, "qos_gbm", "qos_frame", rate=rate,
            window_s=window, candidates=candidates, sweep_rows=sweep_rows,
            sweep_ntrees=sweep_trees, idle=False)
        # window 3: contended with the gate ARMED, SLO = the measured
        # idle p99 (the admission throttle's hysteresis baseline)
        os.environ["H2O3_QOS"] = "1"
        os.environ.setdefault("H2O3_QOS_SLO_MS", str(idle_p99))
        _qos.reset()
        on = run_concurrent_sweep(
            "127.0.0.1", srv.port, "qos_gbm", "qos_frame", rate=rate,
            window_s=window, candidates=candidates, sweep_rows=sweep_rows,
            sweep_ntrees=sweep_trees, idle=False)
        qos_totals = _qos.totals()
    finally:
        srv.stop()
        for k in list(os.environ):
            if k.startswith("H2O3_QOS") and k not in qos_env:
                del os.environ[k]
        os.environ.update(qos_env)
    p99_off = off["contended"]["p99_ms"]
    p99_on = on["contended"]["p99_ms"]
    assert p99_off is not None and np.isfinite(p99_off), \
        f"QoS-off contended p99 must be measurable: {off['contended']}"
    assert p99_on is not None and np.isfinite(p99_on), \
        f"QoS-on contended p99 must be measurable: {on['contended']}"
    assert off["sweep"].get("done") == candidates, \
        f"QoS-off sweep must complete: {off['sweep']}"
    assert on["sweep"].get("done") == candidates, \
        f"sweep must complete under QoS (anti-starvation): {on['sweep']}"
    assert qos_totals["yields"] > 0, \
        f"gate never engaged — no yield points visited: {qos_totals}"
    err = (on["contended"]["errors"] + off["contended"]["errors"])
    offered = (on["contended"]["offered"] + off["contended"]["offered"])
    assert err / max(offered, 1) <= 0.01, \
        f"hard errors under contended load: off={off}, on={on}"
    ratio_on = p99_on / idle_p99
    ratio_off = p99_off / idle_p99
    # the ~2× SLO verdict is TAGGED, not hard-asserted: a noisy CI box
    # must not erase the measurement the verdict is ABOUT
    slo_target = float(os.environ.get("BENCH_QOS_SLO_RATIO", 2.0))
    return (f"qos_contended_{int(rate)}rps_p99_ms", p99_on,
            {"unit_override": "ms",
             "rate_rps": rate, "window_s": window,
             "candidates": candidates, "sweep_rows": sweep_rows,
             "idle_p99_ms": idle_p99,
             "idle_p50_ms": idle["p50_ms"], "idle_p95_ms": idle["p95_ms"],
             "p99_qos_off_ms": p99_off, "p99_qos_on_ms": p99_on,
             "p50_qos_on_ms": on["contended"]["p50_ms"],
             "p95_qos_on_ms": on["contended"]["p95_ms"],
             "p99_contended_over_idle_qos_on": round(ratio_on, 3),
             "p99_contended_over_idle_qos_off": round(ratio_off, 3),
             "qos_off_sweep_wall_s": off["sweep"].get("wall_s"),
             "qos_on_sweep_wall_s": on["sweep"].get("wall_s"),
             "qos_slo_ratio_target": slo_target,
             "qos_slo_exceeded": (True if ratio_on > slo_target else None),
             "qos_yields": qos_totals["yields"],
             "qos_waits_ms": qos_totals["waits_ms"],
             "qos_throttle_transitions":
                 qos_totals["throttle_transitions"],
             "completed": (on["contended"]["completed"]
                           + off["contended"]["completed"]),
             "shed_429": (on["contended"]["shed_429"]
                          + off["contended"]["shed_429"]),
             "errors": err})


# each fleet_serving replica is a real subprocess serving the same
# deterministic GBM: the router's failover claim is only meaningful across
# process boundaries (a thread-backed "replica" shares the scorer cache and
# the GIL with the router)
_FLEET_REPLICA_BODY = """
import sys, os, time
sys.path.insert(0, {repo!r})
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["H2O3_REPLICA_NAME"] = {name!r}
import numpy as np
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
from h2o3_tpu.rest.server import start_server
from h2o3_tpu.runtime.dkv import DKV
rng = np.random.default_rng(7)
X = rng.normal(size=({rows}, 8))
w = rng.normal(size=8)
y = (X @ w + 0.5 * rng.normal(size={rows}) > 0).astype(float)
names = [f"f{{i}}" for i in range(8)] + ["label"]
fr = Frame.from_numpy(np.column_stack([X, y]), names=names) \\
    .asfactor("label")
gbm = H2OGradientBoostingEstimator(ntrees=10, max_depth=4, seed=42)
gbm.train(y="label", training_frame=fr)
DKV.put("fleet_gbm", gbm.model)
sf = Frame({{n: fr.vec(n) for n in names[:-1]}})
sf.key = "fleet_frame"
DKV.put(sf.key, sf)
srv = start_server(port={port})
import urllib.request
for _ in range(2):   # warm the scorer cache before the measured window
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/3/Predictions/models/fleet_gbm"
        "/frames/fleet_frame", data=b"")
    urllib.request.urlopen(req, timeout=120).read()
print("READY", flush=True)
time.sleep(600)
"""


def bench_fleet_serving():
    """Fleet-serving lane (ISSUE 16): open-loop loadgen through the
    serving ROUTER fronting 3 replica processes, with one replica killed
    mid-run via the fault registry (`serving.scorer` crash at rate 1.0 —
    every request it receives 500s deterministically). The router must
    drain the victim and retry its in-flight work on peers: USER errors
    stay 0, and the post-drain p99 is the headline. Reports the reroute
    latency blip (post/pre p99 ratio), router shed/failover/drain
    counters and the fleet-merged predict p99. Wired through the same
    watchdog/partial machinery as every lane — an assertion here raises,
    it never emits a value-0.0 line."""
    import socket
    import subprocess
    import sys as _sys
    import urllib.request

    n_rows = int(os.environ.get("BENCH_ROWS", 2_000))
    rate = float(os.environ.get("BENCH_FLEET_RATE", 15))
    window = float(os.environ.get("BENCH_FLEET_WINDOW_S", 6))
    _sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "deploy"))
    from loadgen import fleet_summary, run_load_open

    from h2o3_tpu.rest.server import start_server
    from h2o3_tpu.runtime import fleet
    from h2o3_tpu.serving import reset_router
    from h2o3_tpu.serving.router import RouterConfig

    repo = os.path.dirname(os.path.abspath(__file__))

    def free_port():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    ports = [free_port() for _ in range(3)]
    procs = []
    srv = None
    try:
        for i, port in enumerate(ports):
            procs.append(subprocess.Popen(
                [_sys.executable, "-c", _FLEET_REPLICA_BODY.format(
                    repo=repo, name=f"r{i + 1}", port=port, rows=n_rows)],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True))
        for i, p in enumerate(procs):
            deadline = time.monotonic() + 300
            while time.monotonic() < deadline:
                line = p.stdout.readline()
                if "READY" in line:
                    break
                if p.poll() is not None:
                    raise AssertionError(
                        f"replica {i} died: {p.stdout.read()[-2000:]}")
            else:
                raise AssertionError(f"replica {i} never came up")
        fleet.reset()
        for i, port in enumerate(ports):
            fleet.register_peer(f"r{i + 1}", f"http://127.0.0.1:{port}")
        # long drain cooldown: the poisoned victim must STAY out of the
        # ring for the whole post-kill window, not resurface as a probe
        router = reset_router(RouterConfig(
            refresh_s=0.5, drain_errors=2, drain_cooldown_s=60.0,
            max_attempts=3))
        srv = start_server(port=0)
        t0 = time.time()
        pre = run_load_open("127.0.0.1", srv.port, "fleet_gbm",
                            "fleet_frame", rate=rate, duration_s=window,
                            router=True)
        # the mid-run kill, via the fault registry: every predict on the
        # victim now raises InjectedCrash (NOT a device error, so the
        # replica's CPU-fallback failover cannot mask it — it 500s)
        victim = f"http://127.0.0.1:{ports[-1]}/3/Faults"
        body = "point=serving.scorer&error=crash&rate=1.0".encode()
        with urllib.request.urlopen(urllib.request.Request(
                victim, data=body), timeout=30) as r:
            r.read()
        post = run_load_open("127.0.0.1", srv.port, "fleet_gbm",
                             "fleet_frame", rate=rate, duration_s=window,
                             router=True)
        wall = time.time() - t0
        totals = router.snapshot(probe=False)["totals"]
        fsum = fleet_summary("127.0.0.1", srv.port) or {}
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            try:
                p.wait(timeout=30)
            except Exception:
                pass
        if srv is not None:
            srv.stop()
    errors = pre["errors"] + post["errors"]
    assert errors == 0, \
        f"router must hide the killed replica (pre={pre} post={post})"
    p99_pre, p99_post = pre["p99_ms"], post["p99_ms"]
    assert p99_post is not None and np.isfinite(p99_post), \
        "post-kill p99 must be measurable"
    assert totals["failovers"] >= 1 and totals["drains"] >= 1, \
        f"the kill must be visible in the router counters: {totals}"
    blip = (round(p99_post / p99_pre, 3)
            if p99_pre and p99_post is not None else None)
    return (f"fleet_serving_3rep_{int(rate)}rps_p99_ms", p99_post,
            {"unit_override": "ms", "wall_s": round(wall, 3),
             "rate_rps": rate, "window_s": window,
             "p99_pre_kill_ms": p99_pre, "p99_post_kill_ms": p99_post,
             "reroute_blip_ratio": blip,
             "offered": pre["offered"] + post["offered"],
             "completed": pre["completed"] + post["completed"],
             "errors": errors,
             "shed_429": pre["shed_429"] + post["shed_429"],
             "router_shed": totals.get("shed", 0),
             "router_retries": totals.get("retries", 0),
             "router_failovers": totals.get("failovers", 0),
             "router_drains": totals.get("drains", 0),
             "fleet_predict_p99_ms": fsum.get("predict_p99_ms"),
             "replicas_up": fsum.get("replicas_up")})


def bench_automl():
    """AutoML leaderboard (BASELINE.json config 5)."""
    n_rows = int(os.environ.get("BENCH_ROWS", 50_000))
    max_models = int(os.environ.get("BENCH_MODELS", 8))
    import h2o3_tpu as h2o
    from h2o3_tpu.automl.automl import H2OAutoML

    X, y = make_higgs_like(n_rows, n_feat=12)
    d = {f"f{i}": X[:, i] for i in range(12)}
    d["label"] = y.astype(int).astype(str)
    fr = h2o.H2OFrame_from_python(d, column_types={"label": "enum"})
    aml = H2OAutoML(max_models=max_models, seed=1, nfolds=3)
    t0 = time.time()
    aml.train(y="label", training_frame=fr)
    wall = time.time() - t0
    rows = aml.leaderboard.rows
    best_auc = (round(float(rows[0].get("auc", float("nan"))), 5)
                if rows else None)
    return (f"automl_{n_rows//1000}k_{max_models}models_wall_s", wall,
            {"n_models": len(rows), "best_auc": best_auc})


# Best recorded round-2 warm measurements on the same chip (BASELINE.md
# round-2 progression) — the de-facto baseline every later round must beat
# (rebased each round to the best known state, per VERDICT r02 #5). Keyed by
# metric name so env-overridden shapes (different name) fall back to 1.0.
# vs_baseline is normalized so >1.0 ALWAYS means better than the baseline:
# baseline/value for wall-clock, value/baseline for throughput.
R02_BASELINE = {
    "higgs_gbm_1000k_100trees_wall_s": 11.0,
    "higgs_gbm_100k_10trees_wall_s": 7.0,
    "airlines_glm_1000k_wall_s": 7.0,
    "mnist_dl_60k_samples_per_s": 15850.0,
    "mslr_xgb_rank_200k_50trees_wall_s": 19.0,
    "automl_50k_8models_wall_s": 215.0,
    # r03 per-level walk scorer on the same model/frame (BASELINE.md round-4)
    "drf_score_50k_50t_d20_wall_s": 3.55,
}

# The remote-chip tunnel adds ±40% wall-time noise and its compile server
# randomly evicts cached executables; a single run measures the weather,
# not the machine. Repeat each wall-clock config and report the BEST run
# (first run also absorbs executable deserialization for later ones).
DEFAULT_REPEATS = {"gbm": 3, "glm": 3, "xgb_rank": 2, "dl": 2, "automl": 2,
                   "scaling": 1, "ingest": 2, "munge": 2, "grid": 1,
                   "chaos": 1, "serving": 1, "gbm_cpu": 1, "estimators": 1,
                   "disk_oversubscription": 1, "fleet_serving": 1,
                   "qos": 1}


def _probe_accelerator(timeout_s: float):
    """Fail-fast tunnel liveness check (VERDICT r04 #1b: never hang).

    Backend init runs in a THROWAWAY subprocess under a hard timeout: when
    the axon tunnel is dead, jax.devices() blocks forever with no timeout of
    its own, so an in-process probe would become the hang it exists to
    prevent. Returns (platform, None) on success or (None, reason) on
    failure — a fast child crash is diagnosed differently from a hang.
    """
    import signal
    import subprocess

    code = "import jax; print('PLATFORM=' + jax.devices()[0].platform)"
    # own session + group-kill: the axon plugin may spawn helper grandchildren
    # holding the stdout pipe, which would make a plain run(timeout=) block
    # in the pipe drain even after the direct child is killed
    p = subprocess.Popen([sys.executable, "-c", code],
                         stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                         text=True, start_new_session=True)
    try:
        out, err = p.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(p.pid, signal.SIGKILL)
        except OSError:
            pass
        p.communicate()
        return None, (f"device init did not answer within {timeout_s:.0f}s "
                      f"— axon tunnel down?")
    if p.returncode != 0:
        tail = " | ".join(err.strip().splitlines()[-3:])
        return None, f"device init crashed (rc={p.returncode}): {tail}"
    for ln in out.splitlines():
        if ln.startswith("PLATFORM="):
            return ln.split("=", 1)[1], None
    return None, "device init printed no platform"


_EMITTED = threading.Event()
_EMIT_LOCK = threading.Lock()
# process groups the watchdog must kill before _exit (scaling-curve children)
_LIVE_CHILD_PGIDS = set()
# completed reps, shared with the watchdog: each entry is
# ((metric, value, extra), phase_snapshot, xla_delta). A watchdog that
# fires mid-round emits the best COMPLETED measurement tagged "partial"
# instead of a value-0.0 line — rounds 4–5 lost their headline number to
# exactly that silent-timeout/absent-line failure mode.
_DONE_RUNS: list = []
_RUN_STATE = {"cpu_fallback_reason": None, "cold": False}


def _emit(obj) -> None:
    """Print the single result JSON line exactly once (main vs watchdog)."""
    with _EMIT_LOCK:
        if not _EMITTED.is_set():
            _EMITTED.set()
            print(json.dumps(obj), flush=True)


def _observability_embed() -> dict:
    """Compile/retrace counters (runtime/phases XLA tracker) every emitted
    record carries — even a failure line attributes WHERE the wall went."""
    try:
        from h2o3_tpu.runtime import phases as _phz

        return dict(_phz.xla_counts())
    except Exception:
        return {}


def _lane_seq() -> int:
    """Fence-sequence cursor: capture before the measured fit(s) and pass
    to `_skew_embed` so the embed covers exactly the fences the
    measurement recorded — not warm-up fits or comparator reps."""
    try:
        from h2o3_tpu.parallel import mesh as _mesh

        return _mesh.lane_seq()
    except Exception:
        return 0


def _skew_embed(since_seq: int = 0):
    """Per-lane collective skew of the measured fit (ISSUE 13): p50/max
    fence skew + the worst lane, from the mesh lane-timing recorder. None
    when the fit recorded no instrumented fences (single-device lanes, or
    a fit that never ran a scoring event — the event-loss fence is the
    only instrumented collective) — like every other extra, a None embed
    is dropped from the record."""
    try:
        from h2o3_tpu.parallel import mesh as _mesh

        s = _mesh.lane_summary(since_seq)
        if s.get("fences"):
            return {"p50": s["skew_p50_ms"], "max": s["skew_max_ms"],
                    "fences": s["fences"], "worst_lane": s["worst_lane"]}
    except Exception:
        pass
    return None


def _lane_waits_embed():
    """Last observed per-lane fence waits — host-side dict only, safe
    from the watchdog thread while the backend hangs: a hung collective's
    partial/fail line names the suspect lane (the one MISSING from, or
    slowest in, the last recorded fence)."""
    try:
        from h2o3_tpu.parallel import mesh as _mesh

        return _mesh.lane_last_waits() or None
    except Exception:
        return None


def _hang_report_embed():
    """Multi-process hang attribution (ISSUE 18): the cached lane→rank
    topology plus the open fence's missing lanes name the suspect RANK of
    a hung pod collective — host dicts only, watchdog-thread safe. None
    on single-process clouds (the lane waits embed already covers those)."""
    try:
        from h2o3_tpu.parallel import mesh as _mesh

        rep = _mesh.lane_hang_report()
        if rep and rep.get("n_ranks", 1) > 1:
            return rep
    except Exception:
        pass
    return None


def _mark_suspects_down(hr) -> None:
    """Watchdog-fired pod hang (ISSUE 20 satellite): the hang report's
    suspect ranks flip their ``h2o3_fleet_peer_up`` series to 0 and a
    Timeline event names them — the failure the watchdog just attributed
    reaches the fleet scrape and the driver immediately, instead of
    waiting for the next failed peer scrape."""
    if not hr:
        return
    try:
        from h2o3_tpu.runtime import supervisor as _sup

        _sup.mark_ranks_down(list(hr.get("suspect_ranks") or []),
                             reason="bench_watchdog")
    except Exception:
        pass


def _memory_embed() -> dict:
    """Memory trajectory every emitted record carries (ISSUE 8): process
    peak RSS, the ledger's device high watermark, and the top-3 owners
    captured at the combined peak — a memory regression is attributable
    from the BENCH_*.json alone, like the phase/XLA embeds."""
    out = {}
    try:
        import resource

        out["peak_rss_bytes"] = int(resource.getrusage(
            resource.RUSAGE_SELF).ru_maxrss) * 1024   # Linux: KB
    except Exception:
        pass
    try:
        from h2o3_tpu.runtime import memory_ledger as _ml

        wm = _ml.peak()
        out["peak_device_bytes"] = int(wm["device_bytes"])
        out["peak_ledger_bytes"] = int(wm["total_bytes"])
        out["peak_owners"] = wm["top_owners"]
    except Exception:
        pass
    try:
        # out-of-core stream totals (ISSUE 14): ride next to the memory
        # embeds in every record when the streamed path ran this process
        import sys as _sys

        bs = _sys.modules.get("h2o3_tpu.models.block_store")
        if bs is not None:
            st = bs.process_totals()
            if st.get("streamed_bytes"):
                out["streamed_bytes"] = int(st["streamed_bytes"])
                out["resident_block_peak"] = int(st["resident_block_peak"])
    except Exception:
        pass
    return out


def _qos_embed() -> "dict | None":
    """Multi-tenant QoS totals every record embeds next to phases/memory
    (ISSUE 19): yields, time training waited for serving, and admission-
    throttle transitions — absent when the gate never saw traffic."""
    try:
        from h2o3_tpu.runtime import qos as _qos

        t = _qos.totals()
        if (t.get("yields") or t.get("serving_dispatches")
                or t.get("throttle_transitions")):
            return {"yields": t["yields"], "waits_ms": t["waits_ms"],
                    "throttle_transitions": t["throttle_transitions"],
                    "serving_dispatches": t["serving_dispatches"]}
    except Exception:
        pass
    return None


def _qos_gate_embed() -> "dict | None":
    """The gate-holder verdict for hang lines: which CLASS (serving or
    training) held the dispatch gate when the watchdog fired."""
    try:
        from h2o3_tpu.runtime import qos as _qos

        gs = _qos.gate_state()
        if gs.get("enabled") or gs.get("holder") != "idle":
            return gs
    except Exception:
        pass
    return None


def _fail_line(config: str, why: str) -> dict:
    nd = _n_devices()
    if nd > 1:
        # a multi-device rep that never completes is indistinguishable
        # from a hung collective (one participant never reached the
        # rendezvous) — name the suspect so the record is diagnosable
        why += (f" [n_devices={nd}: possible hung collective — "
                "H2O3_TREE_SHARD=0 forces the single-device path]")
    line = {"metric": f"{config}_unavailable", "value": 0.0, "unit": "s",
            "vs_baseline": 0.0, "error": why, "backend": None,
            "n_devices": nd}
    lw = _lane_waits_embed()
    if lw:
        # the last fence's per-lane waits: on a hung collective the lane
        # everyone was waiting on is the one with the largest wait here
        # (or the one missing from the dict entirely)
        line["lane_waits_ms"] = lw
    hr = _hang_report_embed()
    if hr:
        # pod runs: name the suspect RANK, not just the lane — the driver
        # reads `ranks.suspect_ranks` straight off the fail line
        line["ranks"] = hr
    xla = _observability_embed()
    if xla:
        line["xla"] = xla
    try:
        from h2o3_tpu.runtime import phases as _phz

        ph = _phz.snapshot()
        if ph:
            line["phases"] = ph
    except Exception:
        pass
    mem = _memory_embed()
    if mem:
        line["memory"] = mem
    qe = _qos_embed()
    if qe:
        line["qos"] = qe
    gs = _qos_gate_embed()
    if gs:
        # on a hang, name the class holding the gate — a stuck serving
        # dispatch reads very differently from a training loop that never
        # reached its next yield point
        line["qos_gate"] = gs
    return line


def _build_result(runs, snaps, xlas, partial: bool = False) -> dict:
    """Fold completed reps into the single result line: best run, its
    phase split, and its compile/trace/retrace delta (plus process
    totals) so a regression is attributable from the JSON alone."""
    metric = runs[0][0]
    higher_better = (metric.endswith(("samples_per_s", "rows_per_s"))
                     or metric.endswith("speedup"))
    values = [r[1] for r in runs]
    best_i = (max if higher_better else min)(
        range(len(values)), key=lambda i: values[i])
    metric, value, extra = runs[best_i]
    extra = dict(extra)
    base = R02_BASELINE.get(metric)
    if base is None:
        vs = 1.0
    elif higher_better:
        vs = float(value) / base
    else:
        vs = base / float(value)
    cpu_fallback_reason = _RUN_STATE["cpu_fallback_reason"]
    try:
        import jax

        backend = ("cpu-fallback" if cpu_fallback_reason
                   else jax.default_backend())
    except Exception:
        backend = "cpu-fallback" if cpu_fallback_reason else None
    result = {
        "metric": metric,
        "value": round(float(value), 3),
        "unit": extra.pop("unit_override", "s"),
        "vs_baseline": round(vs, 3),
        "backend": backend,
        "runs": [round(float(v), 3) for v in values],
    }
    if partial:
        result["partial"] = True
    if cpu_fallback_reason:
        result["fallback_reason"] = cpu_fallback_reason
    if _RUN_STATE["cold"]:
        result["cold"] = True
    ph = snaps[best_i]
    if ph:
        # residual = wall not claimed by any accounted phase (dispatch,
        # host python, tunnel latency between phases)
        wall = extra.get("wall_s") if "wall_s" in extra else (
            float(value) if result["unit"] == "s" else None)
        if wall is not None:
            known = sum(v for k, v in ph.items() if k.endswith("_s"))
            ph["residual_s"] = round(max(wall - known, 0.0), 3)
        result["phases"] = ph
    # per-best-rep compile-pipeline delta + monotone process totals — the
    # "compile/retrace counts from the registry" embed (ISSUE 6): a wall
    # regression is attributable (recompiled? retraced? cache-cold?)
    # without re-running anything
    if xlas and xlas[best_i]:
        result["xla"] = xlas[best_i]
    totals = _observability_embed()
    if totals:
        result["xla_process_totals"] = totals
    mem = _memory_embed()
    if mem:
        result["memory"] = mem
    qe = _qos_embed()
    if qe:
        result["qos"] = qe
    if partial:
        gs = _qos_gate_embed()
        if gs:
            result["qos_gate"] = gs
    result.update({k: v for k, v in extra.items() if v is not None})
    return result


def _cpu_rerun(config: str, deadline: float) -> "dict | None":
    """Re-run this bench forced-CPU in a fresh subprocess (a half-dead jax
    backend cannot be re-platformed in-process) and return its result JSON,
    or None if the rerun also failed. `deadline` is the parent watchdog's
    absolute fire time — the child gets the time actually REMAINING (minus
    margin to emit), not the full budget, else a late accelerator failure
    would see the watchdog kill the rerun mid-measurement."""
    import subprocess

    budget = deadline - time.time() - 30.0
    if budget < 60.0:
        return None     # not enough runway for a meaningful CPU datapoint
    env = dict(os.environ, BENCH_PLATFORM="cpu", BENCH_REPEATS="1")
    # cpu-fallback lines must stay comparable ACROSS rounds: force the
    # rerun onto ONE device (strip any virtual-device-count flag and pin
    # the sharded tree path off) so its n_devices axis is always 1 —
    # a fallback that silently inherited an 8-virtual-device XLA_FLAGS
    # would measure collective overhead, not the kernel trajectory
    env["XLA_FLAGS"] = " ".join(
        t for t in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in t)
    env["H2O3_TREE_SHARD"] = "0"
    if "BENCH_ROWS" not in os.environ:
        fallback_rows = {"gbm": 100_000, "glm": 100_000,
                         "xgb_rank": 50_000, "dl": 20_000,
                         "automl": 20_000}.get(config)
        if fallback_rows:
            env["BENCH_ROWS"] = str(fallback_rows)
    p = subprocess.Popen([sys.executable, os.path.abspath(__file__)],
                         env=env, stdout=subprocess.PIPE,
                         stderr=subprocess.PIPE, text=True,
                         start_new_session=True)
    _LIVE_CHILD_PGIDS.add(p.pid)
    try:
        out, _err = p.communicate(timeout=budget)
    except subprocess.TimeoutExpired:
        import signal

        try:
            os.killpg(p.pid, signal.SIGKILL)
        except OSError:
            pass
        p.communicate()
        return None
    finally:
        _LIVE_CHILD_PGIDS.discard(p.pid)
    for ln in reversed(out.splitlines()):
        if ln.startswith("{"):
            try:
                got = json.loads(ln)
            except ValueError:
                return None
            return got if got.get("value") else None
    return None


def main():
    t_main = time.time()
    config = os.environ.get("BENCH_CONFIG", "gbm")
    # the watchdog covers the probe too (the probe's own pipe drain can block
    # if an axon helper grandchild survives): whatever happens below, the
    # driver gets ONE JSON line instead of rc:124, even if the tunnel flaps
    # after a healthy probe. Default lowered from 1500s: round 4 recorded
    # rc:124 — the DRIVER's budget fired first and the round lost its line
    # entirely, so the watchdog must win that race with margin.
    watchdog_s = float(os.environ.get("BENCH_WATCHDOG_S", 1200))

    def _watchdog():
        if not _EMITTED.wait(timeout=watchdog_s):
            # a completed rep beats a value-0.0 line: emit the best
            # measurement so far, tagged partial, before killing anything
            if _DONE_RUNS:
                runs = [r for r, _ph, _x in _DONE_RUNS]
                snaps = [ph for _r, ph, _x in _DONE_RUNS]
                xlas = [x for _r, _ph, x in _DONE_RUNS]
                line = _build_result(runs, snaps, xlas, partial=True)
                err = (f"watchdog fired at {watchdog_s:.0f}s "
                       f"with {len(runs)} completed rep(s); "
                       "later reps abandoned")
                nd = _n_devices()
                if nd > 1:
                    # a hung COLLECTIVE rep is tagged exactly like any
                    # other hung rep: best completed measurement, partial
                    err += (f" [n_devices={nd}: possible hung collective]")
                line["error"] = err
                lw = _lane_waits_embed()
                if lw:
                    line["lane_waits_ms"] = lw
                hr = _hang_report_embed()
                if hr:
                    line["ranks"] = hr
                    _mark_suspects_down(hr)
                gs = _qos_gate_embed()
                if gs:
                    # name the class (serving/training) holding the QoS
                    # gate when the hang fired — `holder` is the verdict
                    line["qos_gate"] = gs
                _emit(line)
            else:
                _mark_suspects_down(_hang_report_embed())
                _emit(_fail_line(config,
                                 f"bench exceeded {watchdog_s:.0f}s "
                                 "watchdog with no completed rep"))
            import signal

            for pgid in list(_LIVE_CHILD_PGIDS):
                try:
                    os.killpg(pgid, signal.SIGKILL)
                except OSError:
                    pass
            os._exit(0)

    threading.Thread(target=_watchdog, daemon=True).start()
    cpu_fallback_reason = None
    forced = os.environ.get("BENCH_PLATFORM")  # e.g. "cpu" for local checks
    if config in ("scaling", "munge", "chaos", "pod_chaos", "serving",
                  "gbm_cpu", "oversubscription", "disk_oversubscription",
                  "estimators", "fleet_serving", "qos") or forced:
        # the scaling curve runs in CPU subprocesses, the munge bench is
        # pure host numpy, the chaos/serving lanes measure FAILOVER/SLO
        # behavior (CPU is representative), and gbm_cpu IS the forced-CPU
        # trajectory lane; keep the parent off the (possibly unavailable)
        # TPU backend entirely — no probe, never a value-0.0 line
        import jax

        jax.config.update("jax_platforms", forced or "cpu")
    else:
        # the tunnel to the real chip can die mid-round; a bench that hangs
        # for the driver's whole budget records nothing. Probe first; when
        # the chip is unreachable, re-run the whole bench forced-CPU in a
        # SUBPROCESS (a half-dead backend plugin can poison in-process
        # state) and emit ITS measurement tagged "backend": "cpu-fallback"
        # — the PR 1/PR 4 contract, never a `*_unavailable` value-0.0 line
        # (the round-5 failure mode).
        probe_s = float(os.environ.get("BENCH_PROBE_TIMEOUT_S", 90))
        platform, why = _probe_accelerator(probe_s)
        if platform is None:
            print(f"# accelerator unreachable ({why}); re-running "
                  "forced-CPU in a subprocess", file=sys.stderr)
            line = _cpu_rerun(config, t_main + watchdog_s)
            if line is not None:
                line["backend"] = "cpu-fallback"
                line["fallback_reason"] = why
                _emit(line)
                sys.exit(0)
            # subprocess rerun impossible (no runway) or failed: last
            # resort is the in-process CPU run — still a datapoint
            cpu_fallback_reason = why
            print("# subprocess rerun unavailable; falling back to an "
                  "in-process CPU bench run", file=sys.stderr)
            import jax

            jax.config.update("jax_platforms", "cpu")
            if "BENCH_ROWS" not in os.environ:
                # shrink only the un-asked-for default workload: the CPU
                # must land a datapoint inside the watchdog budget. An
                # explicit BENCH_ROWS is honored as given.
                fallback_rows = {"gbm": 100_000, "glm": 100_000,
                                 "xgb_rank": 50_000}.get(config)
                if fallback_rows:
                    os.environ["BENCH_ROWS"] = str(fallback_rows)
    import jax

    # env vars alone do not engage the persistent cache under the remote-TPU
    # plugin — the config must be set programmatically
    cold = os.environ.get("BENCH_COLD") == "1"
    cache_dir = os.environ["JAX_COMPILATION_CACHE_DIR"]
    if cold:
        # a fresh cache dir forces every program through trace+compile, so
        # the recorded run is the cold-start a first-time user pays
        import tempfile

        cache_dir = tempfile.mkdtemp(prefix="jax_cold_cache_")
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
    from h2o3_tpu.runtime import phases as _phz

    _phz.install_listener()
    fn = {"gbm": bench_gbm, "glm": bench_glm, "dl": bench_dl,
          "xgb_rank": bench_xgb_rank, "automl": bench_automl,
          "score": bench_score, "scaling": bench_scaling,
          "ingest": bench_ingest, "munge": bench_munge,
          "grid": bench_grid, "chaos": bench_chaos,
          "pod_chaos": bench_pod_chaos,
          "serving": bench_serving, "gbm_cpu": bench_gbm_cpu,
          "oversubscription": bench_oversubscription,
          "disk_oversubscription": bench_disk_oversubscription,
          "estimators": bench_estimators,
          "fleet_serving": bench_fleet_serving,
          "qos": bench_qos}[config]
    # cold is strictly one run: repeats within a process share the live
    # executable cache, so any second run would be warm yet labeled cold
    repeats = 1 if cold else int(os.environ.get(
        "BENCH_REPEATS", DEFAULT_REPEATS.get(config, 1)))
    _RUN_STATE["cpu_fallback_reason"] = cpu_fallback_reason
    _RUN_STATE["cold"] = cold
    runs, snaps, xlas = [], [], []
    try:
        for _ in range(max(repeats, 1)):
            _phz.reset()
            xla0 = _phz.xla_counts()
            run = fn()
            xla1 = _phz.xla_counts()
            runs.append(run)
            snaps.append(_phz.snapshot())
            xlas.append({k: xla1[k] - xla0.get(k, 0) for k in xla1})
            # watchdog-visible progress: a timeout after this point emits
            # this rep instead of a value-0.0 line
            _DONE_RUNS.append((runs[-1], snaps[-1], xlas[-1]))
    except Exception as e:  # a mid-run tunnel death raises rather than hangs
        import traceback

        traceback.print_exc(file=sys.stderr)
        # the probe passed but the run itself died (tunnel flap mid-flight):
        # re-run the whole bench forced-CPU in a subprocess and emit ITS
        # measurement tagged cpu-fallback — an on-CPU datapoint beats an
        # error-only value-0.0 line (VERDICT r05: the artifact must carry a
        # measurement unconditionally). Already-CPU runs have nothing to
        # fall back to.
        try:
            backend_is_cpu = jax.default_backend() == "cpu"
        except Exception:
            # the accelerator backend itself may be what died — never let
            # the fallback decision kill the guaranteed emit
            backend_is_cpu = False
        already_cpu = (cpu_fallback_reason is not None
                       or forced == "cpu"
                       or backend_is_cpu)
        if runs:
            # completed accelerator reps beat a forced-CPU rerun: they ARE
            # the comparable measurement — emit the partial best instead
            # of discarding them for minutes of non-comparable CPU wall
            partial = _build_result(runs, snaps, xlas, partial=True)
            partial["error"] = (f"rep {len(runs) + 1} raised: {e!r}; "
                                "earlier rep(s) reported")
            _emit(partial)
            sys.exit(0)
        line = None if already_cpu else _cpu_rerun(config,
                                                   t_main + watchdog_s)
        if line is not None:
            line["backend"] = "cpu-fallback"
            line["fallback_reason"] = f"bench raised on accelerator: {e!r}"
            _emit(line)
        else:
            _emit(_fail_line(config, f"bench raised: {e!r}"))
        sys.exit(0)
    _emit(_build_result(runs, snaps, xlas))


if __name__ == "__main__":
    main()
