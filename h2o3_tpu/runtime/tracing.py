"""Request/job tracing — the span engine of the observability spine.

Every REST request opens a ROOT span (trace id minted by the client and
propagated via the ``X-H2O3-Trace-Id`` header, or minted server-side when
absent); training Jobs, trainpool candidates, serving batch dispatches,
ingest parses and munge ops open CHILD spans; retry attempts and fired
fault injections annotate the owning span as zero-duration events. The
result is one correlated tree per user action instead of five disconnected
counter snapshots — ``GET /3/Trace`` exports any trace as Chrome-trace/
Perfetto JSON, and recent span summaries fold into ``GET /3/Timeline``.

Design:

- spans parent through a THREAD-LOCAL stack (`span()` nests naturally in
  one thread); crossing a thread boundary is explicit — the spawning side
  captures `current()` (or just the ids) and the worker re-attaches with
  ``attach(trace_id, parent_id)``. `Job` objects carry ``trace_id`` for
  the REST→worker hop, `_Pending` carries it for the batcher hop.
- finished spans land in one bounded ring (``H2O3_TRACE_SPANS``, default
  4096) — O(1) append under a single lock, oldest evicted first, so
  sustained traffic cannot grow the host (same stance as the Timeline
  ring). An UNSAMPLED fraction is not implemented: span volume here is
  per-request/per-op, not per-row.
- ops whose instrumentation already measures wall-clock (ingest/munge
  stats modules) register retroactively via ``record_span`` instead of
  wrapping their hot paths twice.

Metric fold: ``h2o3_trace_spans_total{kind}`` counts completed spans per
kind in the central registry, so span volume itself is scrapable.
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from collections import deque
from contextlib import contextmanager
from typing import Dict, List, Optional

from . import env_int

__all__ = ["Span", "span", "attach", "current", "current_trace_id",
           "new_trace_id", "event", "record_span", "export_chrome",
           "summaries", "clear", "span_count"]

_MAX_SPANS = env_int("H2O3_TRACE_SPANS", 4096)
_MAX_EVENTS_PER_SPAN = 64

_LOCK = threading.Lock()
_SPANS: deque = deque(maxlen=_MAX_SPANS)
_TLS = threading.local()


def new_trace_id() -> str:
    return uuid.uuid4().hex[:16]


def _new_span_id() -> str:
    return uuid.uuid4().hex[:8]


class Span:
    """One timed operation. Mutable while open; immutable once recorded."""

    __slots__ = ("name", "kind", "trace_id", "span_id", "parent_id",
                 "t_wall", "t0", "duration_s", "attrs", "events", "thread")

    def __init__(self, name: str, kind: str = "span",
                 trace_id: Optional[str] = None,
                 parent_id: Optional[str] = None,
                 attrs: Optional[Dict] = None):
        self.name = name
        self.kind = kind
        self.trace_id = trace_id or new_trace_id()
        self.span_id = _new_span_id()
        self.parent_id = parent_id
        self.t_wall = time.time()
        self.t0 = time.perf_counter()
        self.duration_s: Optional[float] = None
        self.attrs: Dict = dict(attrs or {})
        self.events: List[Dict] = []
        self.thread = threading.current_thread().name

    def annotate(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def add_event(self, name: str, **attrs) -> None:
        if len(self.events) < _MAX_EVENTS_PER_SPAN:
            ev = dict(name=name, ts=time.time())
            if attrs:
                ev.update(attrs)
            self.events.append(ev)

    def to_dict(self) -> Dict:
        return dict(name=self.name, kind=self.kind, trace_id=self.trace_id,
                    span_id=self.span_id, parent_id=self.parent_id,
                    ts=self.t_wall,
                    duration_s=(round(self.duration_s, 6)
                                if self.duration_s is not None else None),
                    thread=self.thread, attrs=dict(self.attrs),
                    events=list(self.events))


def _stack() -> List[Span]:
    s = getattr(_TLS, "stack", None)
    if s is None:
        s = _TLS.stack = []
    return s


def current() -> Optional[Span]:
    """The innermost open span on this thread, or None."""
    s = getattr(_TLS, "stack", None)
    return s[-1] if s else None


def current_trace_id() -> Optional[str]:
    sp = current()
    return sp.trace_id if sp is not None else None


_SPAN_COUNTER = None


def _record(sp: Span) -> None:
    global _SPAN_COUNTER
    with _LOCK:
        _SPANS.append(sp)
    # registry fold; the family is memoized so ending a span never takes
    # the registry's registration lock (deferred first resolve: tracing
    # must stay importable before metrics_registry)
    c = _SPAN_COUNTER
    if c is None:
        from . import metrics_registry as _reg

        c = _SPAN_COUNTER = _reg.counter(
            "h2o3_trace_spans", "completed trace spans",
            labelnames=("kind",))
    c.inc(1, sp.kind)


@contextmanager
def span(name: str, kind: str = "span", trace_id: Optional[str] = None,
         parent_id: Optional[str] = None, **attrs):
    """Open a span as a child of this thread's current span (or of the
    explicit trace_id/parent_id for cross-thread hops); record it on exit.
    Exceptions mark the span ``error`` and propagate."""
    cur = current()
    if trace_id is None and cur is not None:
        trace_id = cur.trace_id
        if parent_id is None:
            parent_id = cur.span_id
    sp = Span(name, kind=kind, trace_id=trace_id, parent_id=parent_id,
              attrs=attrs)
    st = _stack()
    st.append(sp)
    try:
        yield sp
    except BaseException as e:
        sp.attrs["error"] = f"{type(e).__name__}: {e}"
        raise
    finally:
        st.pop()
        sp.duration_s = time.perf_counter() - sp.t0
        _record(sp)


@contextmanager
def attach(trace_id: Optional[str], parent_id: Optional[str] = None,
           name: str = "attached", kind: str = "span", **attrs):
    """Worker-thread re-entry point: continue `trace_id` on this thread.
    No-op passthrough (no span recorded) when trace_id is falsy — callers
    wrap unconditionally and un-traced work stays un-traced."""
    if not trace_id:
        yield None
        return
    with span(name, kind=kind, trace_id=trace_id,
              parent_id=parent_id, **attrs) as sp:
        yield sp


def event(name: str, **attrs) -> None:
    """Annotate the current span with a zero-duration event (retry
    attempts, fired fault injections). Silently dropped when no span is
    open — hardening paths run identically traced or not."""
    sp = current()
    if sp is not None:
        sp.add_event(name, **attrs)


def record_span(name: str, duration_s: float, kind: str = "span",
                trace_id: Optional[str] = None,
                parent_id: Optional[str] = None,
                t_wall: Optional[float] = None, **attrs) -> Span:
    """Retroactively record an already-measured operation (ingest parses,
    munge ops — their stats modules time the work themselves). Parents to
    the current span when no explicit ids are given."""
    cur = current()
    if trace_id is None and cur is not None:
        trace_id = cur.trace_id
        if parent_id is None:
            parent_id = cur.span_id
    sp = Span(name, kind=kind, trace_id=trace_id, parent_id=parent_id,
              attrs=attrs)
    sp.duration_s = float(duration_s)
    if t_wall is not None:
        sp.t_wall = float(t_wall)
    else:
        sp.t_wall = time.time() - sp.duration_s
    _record(sp)
    return sp


# -- read side ----------------------------------------------------------------

def _snapshot_spans() -> List[Span]:
    with _LOCK:
        return list(_SPANS)


def span_count() -> int:
    with _LOCK:
        return len(_SPANS)


def spans(trace_id: Optional[str] = None, n: Optional[int] = None
          ) -> List[Dict]:
    """Recorded spans (oldest first), optionally filtered to one trace."""
    out = [s for s in _snapshot_spans()
           if trace_id is None or s.trace_id == trace_id]
    if n is not None:
        out = out[-n:]
    return [s.to_dict() for s in out]


def summaries(n: int = 50) -> List[Dict]:
    """Compact recent-span lines for the /3/Timeline fold."""
    out = []
    for s in _snapshot_spans()[-n:]:
        d = dict(ts=round(s.t_wall, 3), name=s.name, kind=s.kind,
                 trace_id=s.trace_id,
                 duration_ms=(round(s.duration_s * 1e3, 3)
                              if s.duration_s is not None else None))
        if "error" in s.attrs:
            d["error"] = s.attrs["error"]
        out.append(d)
    return out


def export_chrome(trace_id: Optional[str] = None) -> Dict:
    """Chrome-trace (Perfetto-loadable) JSON object: one complete ("X")
    event per span with trace/span ids in args, one instant ("i") event
    per span annotation. Load at ui.perfetto.dev or chrome://tracing."""
    pid = os.getpid()
    events: List[Dict] = []
    tids: Dict[str, int] = {}
    for s in _snapshot_spans():
        if trace_id is not None and s.trace_id != trace_id:
            continue
        tid = tids.setdefault(s.thread, len(tids) + 1)
        ts_us = s.t_wall * 1e6
        args = dict(trace_id=s.trace_id, span_id=s.span_id,
                    parent_id=s.parent_id, **s.attrs)
        events.append(dict(
            name=s.name, cat=s.kind, ph="X", ts=ts_us,
            dur=max((s.duration_s or 0.0) * 1e6, 1.0),
            pid=pid, tid=tid, args=args))
        for ev in s.events:
            events.append(dict(
                name=ev["name"], cat=s.kind, ph="i", s="t",
                ts=ev.get("ts", s.t_wall) * 1e6, pid=pid, tid=tid,
                args={k: v for k, v in ev.items()
                      if k not in ("name", "ts")}))
    meta = [dict(name="thread_name", ph="M", pid=pid, tid=tid,
                 args=dict(name=thread))
            for thread, tid in tids.items()]
    return dict(traceEvents=meta + events, displayTimeUnit="ms",
                otherData=dict(source="h2o3_tpu", trace_id=trace_id))


def clear() -> None:
    """Drop recorded spans (tests). Open spans on live threads are
    unaffected — they record on exit as usual."""
    with _LOCK:
        _SPANS.clear()
