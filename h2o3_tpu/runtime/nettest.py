"""Transport microbenchmark — `water/api/NetworkTestHandler` analog.

The reference measures node↔node RPC; this framework's data plane is the
host↔device link, so the test times H2D+D2H round-trips per payload size
(warm-up first — the first shape pays an XLA compile, which is not
bandwidth). Shared by `GET /3/NetworkTest` and `h2o.network_test()`. No
collectives run here: invoked from a REST request it reaches ONE rank, and
a single-rank collective would hang the cloud.
"""

from __future__ import annotations

import time
from typing import Dict, List

import numpy as np


def run_network_test(sizes=(1 << 10, 1 << 16, 1 << 20)) -> List[Dict]:
    import jax

    results = []
    for size in sizes:
        payload = np.zeros(size, np.uint8)
        dev = jax.device_put(payload)          # warm-up: compile + path
        np.asarray(dev)
        t0 = time.time()
        dev = jax.device_put(payload)
        np.asarray(dev)                        # forces the D2H
        dt = max(time.time() - t0, 1e-9)
        results.append(dict(bytes=size, seconds=dt,
                            mbytes_per_sec=2 * size / dt / 1e6))
    return results
