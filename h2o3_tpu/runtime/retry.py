"""Shared retry policy — transient-failure discipline for every layer.

Reference contrast: the JVM platform treats failure as routine — heartbeat
clouds (`water/HeartBeatThread.java`), grid auto-recovery (`hex.grid`), and
client HTTP retries. This module is the one place that discipline lives for
the TPU rebuild: persist I/O, the remote-attach client, the train pool's
candidate scheduler and the serving failover path all share ONE policy
object shape instead of five ad-hoc retry loops.

Pieces:

* **classification** — `is_transient(exc)` separates errors worth retrying
  (connection drops, timeouts, 429/5xx, device/XLA runtime errors, injected
  transients) from permanent ones (4xx semantics, ValueError/KeyError,
  missing files, cancellation) that must fail fast; `is_device_error(exc)`
  recognizes the accelerator-runtime subset the serving layer quarantines
  on (arXiv:2005.09148's degrade-to-slower-path stance).
* **RetryPolicy** — capped exponential backoff with DECORRELATED jitter
  (`sleep = U(base, prev*3)` capped), a per-call wall deadline, and a
  process-wide retry BUDGET (token bucket) so a hard outage degrades to
  fail-fast instead of a retry storm.
* **counters** — per-policy attempts/retries/exhaustions, surfaced through
  `snapshot()` into `/3/Training/metrics` and `/3/Profiler`.

Env knobs (all optional; constructor args win):
``H2O3_RETRY_MAX_ATTEMPTS``, ``H2O3_RETRY_BASE_MS``, ``H2O3_RETRY_MAX_MS``,
``H2O3_RETRY_DEADLINE_S``, ``H2O3_RETRY_BUDGET``, ``H2O3_RETRY_SEED``.
"""

from __future__ import annotations

import os
import random
import threading
import time
import urllib.error
from typing import Callable, Dict, Optional

__all__ = ["RetryPolicy", "RetryBudget", "is_transient", "is_device_error",
           "snapshot", "reset", "record", "default_budget"]


# -- error classification ----------------------------------------------------

# substrings that mark an accelerator-runtime failure in the message of a
# bare RuntimeError (jaxlib surfaces XlaRuntimeError with these status
# tags). Deliberately NARROW: a bare "device" would misclassify ordinary
# config errors ("no device found") as retryable accelerator faults and
# quarantine healthy scorers.
_DEVICE_MARKERS = ("XLA", "RESOURCE_EXHAUSTED", "DATA_LOSS", "rendezvous",
                   "failed to enqueue")

# permanent OSError subclasses: retrying cannot make the file appear or the
# permission bit flip
_PERMANENT_OS = (FileNotFoundError, PermissionError, IsADirectoryError,
                 NotADirectoryError, FileExistsError)


def is_device_error(exc: BaseException) -> bool:
    """True for accelerator-runtime failures (XLA runtime errors and the
    injected `faults.InjectedDeviceError`) — the class the serving layer
    quarantines + falls back on rather than plainly retrying."""
    name = type(exc).__name__
    if name == "XlaRuntimeError":
        return True
    from . import faults

    if isinstance(exc, faults.InjectedDeviceError):
        return True
    if isinstance(exc, RuntimeError):
        msg = str(exc)
        return any(m in msg for m in _DEVICE_MARKERS)
    return False


def is_transient(exc: BaseException) -> bool:
    """True when a retry has a chance: connection-level failures, timeouts,
    HTTP 429/5xx, device/XLA runtime errors. False for semantic errors
    (4xx, ValueError/TypeError/KeyError, missing files, cancellation)."""
    from ..models.model_base import JobCancelled

    if isinstance(exc, JobCancelled):
        return False
    from . import faults

    if isinstance(exc, faults.FaultInjected):
        # injected faults declare their own class: transient kinds subclass
        # transient builtins, InjectedCrash is the permanent one
        return not isinstance(exc, faults.InjectedCrash)
    if isinstance(exc, urllib.error.HTTPError):
        return exc.code == 429 or exc.code >= 500
    status = getattr(exc, "status", None)   # client.H2OServerError
    if isinstance(status, int):
        return status == 429 or status >= 500
    if isinstance(exc, urllib.error.URLError):
        return True
    if isinstance(exc, (ConnectionError, TimeoutError, InterruptedError,
                        BrokenPipeError)):
        return True
    if is_device_error(exc):
        return True
    if isinstance(exc, _PERMANENT_OS):
        return False
    if isinstance(exc, (ValueError, TypeError, KeyError, NotImplementedError,
                        AssertionError)):
        return False
    if isinstance(exc, OSError):
        # residual OSErrors are I/O-shaped (EIO, network filesystems) —
        # worth one more try
        return True
    return False


# -- retry budget ------------------------------------------------------------

class RetryBudget:
    """Token bucket bounding retries per process: a hard outage must
    degrade to fail-fast, not multiply load by max_attempts (the classic
    retry-storm failure mode). Refills continuously."""

    def __init__(self, capacity: int = 64, refill_per_s: float = 2.0):
        self.capacity = max(int(capacity), 0)
        self.refill_per_s = float(refill_per_s)
        self._tokens = float(self.capacity)
        self._t_last = time.monotonic()
        self._lock = threading.Lock()

    def try_spend(self, n: float = 1.0) -> bool:
        with self._lock:
            now = time.monotonic()
            self._tokens = min(self.capacity,
                               self._tokens
                               + (now - self._t_last) * self.refill_per_s)
            self._t_last = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    def remaining(self) -> float:
        with self._lock:
            return self._tokens


_DEFAULT_BUDGET: Optional[RetryBudget] = None
_BUDGET_LOCK = threading.Lock()


def default_budget() -> RetryBudget:
    """The process-wide retry budget shared by every policy without an
    explicit one (trainpool candidate retries spend from it too)."""
    return _default_budget()


def _default_budget() -> RetryBudget:
    global _DEFAULT_BUDGET
    with _BUDGET_LOCK:
        if _DEFAULT_BUDGET is None:
            cap = int(os.environ.get("H2O3_RETRY_BUDGET", 64) or 64)
            _DEFAULT_BUDGET = RetryBudget(cap)
        return _DEFAULT_BUDGET


# -- counters ----------------------------------------------------------------

_STATS_LOCK = threading.Lock()
_STATS: Dict[str, Dict[str, int]] = {}

_COUNTER_KEYS = ("calls", "retries", "recovered", "permanent_failures",
                 "deadline_exceeded", "attempts_exhausted",
                 "budget_exhausted")


_REG_COUNTER = None


def _reg_counter():
    """Central-registry family backing the retry counters (GET /3/Metrics):
    one labeled counter, policy × event."""
    global _REG_COUNTER
    if _REG_COUNTER is None:
        from . import metrics_registry as reg

        _REG_COUNTER = reg.counter(
            "h2o3_retry_events",
            "shared retry-policy events (calls/retries/recovered/"
            "exhaustions) per policy", labelnames=("policy", "event"))
        for k in _COUNTER_KEYS:
            reg.bind_rest_field("training", f"retry.totals.{k}",
                                "h2o3_retry_events")
    return _REG_COUNTER


def _bump(policy: str, counter: str, by: int = 1) -> None:
    with _STATS_LOCK:
        d = _STATS.setdefault(policy, {k: 0 for k in _COUNTER_KEYS})
        d[counter] += by
    _reg_counter().inc(by, policy, counter)
    if counter == "retries":
        from . import tracing as _tracing

        _tracing.event("retry", policy=policy)


def record(policy: str, counter: str, by: int = 1) -> None:
    """Counter hook for call sites that hand-roll their retry loop (the
    client's Retry-After honoring) but want unified accounting. Valid
    counters: """ + ", ".join(_COUNTER_KEYS)
    if counter not in _COUNTER_KEYS:
        raise ValueError(f"unknown retry counter {counter!r}")
    _bump(policy, counter, by)


def snapshot() -> Dict:
    """Per-policy retry counters + totals (folded into /3/Profiler and
    /3/Training/metrics)."""
    with _STATS_LOCK:
        policies = {k: dict(v) for k, v in _STATS.items()}
    totals = {c: sum(p[c] for p in policies.values()) for c in _COUNTER_KEYS}
    out = dict(policies=policies, totals=totals)
    b = _DEFAULT_BUDGET
    if b is not None:
        out["budget_remaining"] = round(b.remaining(), 1)
    return out


def reset() -> None:
    global _DEFAULT_BUDGET
    with _STATS_LOCK:
        _STATS.clear()
    with _BUDGET_LOCK:
        _DEFAULT_BUDGET = None


# -- the policy --------------------------------------------------------------

class RetryPolicy:
    """Capped decorrelated-jitter backoff with a wall deadline and budget.

    ``call(fn)`` runs `fn()` to success or final failure; the LAST error is
    re-raised unchanged so callers keep their existing except clauses.
    """

    def __init__(self, name: str = "default",
                 max_attempts: Optional[int] = None,
                 base_delay_s: Optional[float] = None,
                 max_delay_s: Optional[float] = None,
                 deadline_s: Optional[float] = None,
                 classify: Callable[[BaseException], bool] = is_transient,
                 budget: Optional[RetryBudget] = None,
                 sleep: Callable[[float], None] = time.sleep):
        from . import env_float

        self.name = name
        self.max_attempts = int(max_attempts if max_attempts is not None
                                else env_float("H2O3_RETRY_MAX_ATTEMPTS", 4))
        self.base_delay_s = (base_delay_s if base_delay_s is not None
                             else env_float("H2O3_RETRY_BASE_MS", 50) / 1e3)
        self.max_delay_s = (max_delay_s if max_delay_s is not None
                            else env_float("H2O3_RETRY_MAX_MS", 2000) / 1e3)
        self.deadline_s = (deadline_s if deadline_s is not None
                           else env_float("H2O3_RETRY_DEADLINE_S", 30.0))
        self.classify = classify
        self._budget = budget
        self._sleep = sleep
        seed = os.environ.get("H2O3_RETRY_SEED")
        self._rng = random.Random(int(seed) if seed not in (None, "")
                                  else None)

    @property
    def budget(self) -> RetryBudget:
        return self._budget if self._budget is not None \
            else _default_budget()

    def next_delay(self, prev_delay: float) -> float:
        """Decorrelated jitter (AWS architecture-blog variant): uniform on
        [base, prev*3], capped — spreads synchronized retriers apart
        without the full-jitter's near-zero sleeps."""
        hi = max(self.base_delay_s, min(self.max_delay_s, prev_delay * 3.0))
        return self._rng.uniform(self.base_delay_s, hi)

    def call(self, fn: Callable, *args, **kwargs):
        """Run fn(*args, **kwargs) under this policy."""
        _bump(self.name, "calls")
        t0 = time.monotonic()
        delay = self.base_delay_s
        attempt = 1
        while True:
            try:
                out = fn(*args, **kwargs)
                if attempt > 1:
                    _bump(self.name, "recovered")
                return out
            except BaseException as e:
                if not self.classify(e):
                    _bump(self.name, "permanent_failures")
                    raise
                if attempt >= self.max_attempts:
                    _bump(self.name, "attempts_exhausted")
                    raise
                delay = self.next_delay(delay)
                if time.monotonic() - t0 + delay > self.deadline_s:
                    _bump(self.name, "deadline_exceeded")
                    raise
                if not self.budget.try_spend():
                    _bump(self.name, "budget_exhausted")
                    raise
                _bump(self.name, "retries")
                self._sleep(delay)
                attempt += 1

    def wraps(self, fn: Callable) -> Callable:
        """Decorator form of call()."""
        import functools

        @functools.wraps(fn)
        def inner(*a, **kw):
            return self.call(fn, *a, **kw)

        return inner
