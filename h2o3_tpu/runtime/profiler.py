"""Profiler — stack sampling + XLA trace capture.

Reference parity: `/3/Profiler` (`water/api/ProfilerHandler.java` +
`water/util/JProfile.java`) collects stack-trace samples from every node —
here `stack_samples()` snapshots all Python threads of this process (one
process per TPU host). `trace()` wraps `jax.profiler` (perfetto/tensorboard
capture) — strictly stronger than the reference's sampler for device time.
"""

from __future__ import annotations

import contextlib
import sys
import threading
import traceback
from collections import Counter
from typing import Dict, List


def stack_samples(depth: int = 20) -> List[Dict]:
    """One stack snapshot per live thread (the JProfile node sample)."""
    out = []
    names = {t.ident: t.name for t in threading.enumerate()}
    for tid, frame in sys._current_frames().items():
        stack = traceback.format_stack(frame)[-depth:]
        out.append(dict(thread=names.get(tid, str(tid)), stack=stack))
    return out


def profile(nsamples: int = 10, interval: float = 0.02, depth: int = 10) -> List[Dict]:
    """Repeated sampling aggregated by stack — the /3/Profiler table."""
    import time

    counts: Counter = Counter()
    for _ in range(nsamples):
        for s in stack_samples(depth):
            counts["".join(s["stack"])] += 1
        time.sleep(interval)
    return [dict(stack=k, count=v) for k, v in counts.most_common()]


def serving_stats() -> Dict:
    """Serving-subsystem observability folded into the profiler surface:
    `/3/Profiler` reports host stacks AND the scoring path's counters/
    latency histograms in one document. Never instantiates the serving
    engine — a profiler read on a training-only cluster reports absence."""
    from ..serving import peek_engine

    eng = peek_engine()
    if eng is None:
        return dict(active=False)
    out = eng.snapshot()
    out["active"] = True
    return out


def ingest_stats() -> Dict:
    """Ingest-pipeline observability folded into the profiler surface
    (mirrors `serving_stats`): cumulative + last-parse rows/s, bytes/s and
    the per-phase split (setup/read/tokenize/coerce/intern/place) recorded
    by frame/ingest_stats. Pure counter read — never triggers a parse."""
    from ..frame import ingest_stats as stats

    out = stats.snapshot()
    out["active"] = out["totals"]["parses"] > 0
    return out


def munge_stats() -> Dict:
    """Munging-engine observability folded into the profiler surface
    (mirrors `ingest_stats`): cumulative + per-op + last-op rows/s and the
    per-stage split (e.g. merge's factorize/combine/match/assemble)
    recorded by frame/munge_stats. Pure counter read — never runs an op."""
    from ..frame import munge_stats as stats

    out = stats.snapshot()
    out["active"] = out["totals"]["ops"] > 0
    return out


def training_stats() -> Dict:
    """Multi-model training observability folded into the profiler surface
    (mirrors `serving_stats`): train-pool occupancy, per-candidate phase
    splits, CV fold reuse counters and the dataset-artifact cache. Pure
    counter read — never trains anything."""
    from ..models import dataset_cache
    from . import trainpool

    out = trainpool.snapshot()
    out["cache"] = dataset_cache.snapshot()
    return out


def fault_stats() -> Dict:
    """Hardening observability folded into the profiler surface: armed
    fault-injection points + fire counts (runtime/faults) and the shared
    retry-policy counters (runtime/retry). Pure counter read."""
    from . import faults, retry

    out = dict(faults=faults.snapshot(), retry=retry.snapshot())
    out["active"] = bool(out["faults"]["active"]
                         or out["retry"]["totals"]["calls"])
    return out


def tree_stats() -> Dict:
    """Tree-kernel observability folded into the profiler surface
    (ISSUE 7 satellite): the per-fit histogram kernel plans recorded by
    `ops.histogram.record_fit_plan` (method, pallas row_chunk, pack bits,
    VMEM-pressure fallbacks per level) plus the cumulative dispatch
    counters — `build_histograms`' auto-dispatch made visible. Pure
    counter read — never builds a histogram."""
    from ..ops import histogram

    out = histogram.kernel_stats()
    out["active"] = bool(out["plans"]) or bool(out["dispatch"])
    return out


def est_stats() -> Dict:
    """Estimator-engine observability folded into the profiler surface
    (ISSUE 15): the per-fit plans recorded by
    `models.estimator_engine.record_fit` (algo, fused/legacy path,
    on-device iterations, converged flag, standardized-matrix cache
    hit/miss, shard count) plus the cumulative dispatch/iteration
    counters. Pure counter read — never fits anything."""
    from ..models import estimator_engine

    out = estimator_engine.est_stats()
    out["active"] = bool(out["plans"]) or bool(out["dispatch"])
    return out


def xla_stats() -> Dict:
    """XLA compile/trace/retrace counters folded into the profiler surface
    (runtime/phases tracker): totals + per-program-signature breakdown.
    Pure counter read."""
    from . import phases

    out = phases.xla_snapshot()
    out["active"] = any(out["totals"].values())
    return out


def memory_stats() -> Dict:
    """Memory-ledger fold (ISSUE 8): per-owner host/device bytes, by-kind
    totals, watermarks, pressure vs budget, leak report, and the device
    probe reconciliation — the same document GET /3/Memory serves, but
    from the rate-limited cached pass (force=False): a dashboard polling
    /3/Profiler never pays more than one accounting walk per
    H2O3_MEM_REFRESH_S interval."""
    from . import memory_ledger

    out = memory_ledger.snapshot(force=False)
    out["active"] = out["totals"]["owner_count"] > 0
    return out


def fleet_stats() -> Dict:
    """Fleet-aggregation fold (ISSUE 13): registered peers + last scrape
    status + scrape counters. `scrape=False` — a profiler read must never
    block on peer HTTP round-trips; GET /3/Fleet is the probing surface."""
    from . import fleet

    out = fleet.snapshot(scrape=False)
    out["active"] = bool(out["totals"]["peers"])
    return out


def router_stats() -> Dict:
    """Serving-fleet-router fold (ISSUE 16): ring + version table + shed/
    rollback counters. Peeks — a profiler read must never instantiate a
    routing layer (or fan out to replicas) just to report there isn't
    one; `probe=False` keeps it scrape-free like fleet_stats."""
    from ..serving.router import peek_router

    r = peek_router()
    if r is None:
        return dict(active=False)
    out = r.snapshot(probe=False)
    out["active"] = bool(out["ring"]) or bool(out["models"])
    return out


def qos_stats() -> Dict:
    """Multi-tenant QoS fold (ISSUE 19): gate state (who holds it —
    serving/training/idle), cumulative yield/wait totals, admission
    throttle state and the live knobs. Pure counter read — never waits
    at the gate."""
    from . import qos

    out = qos.stats()
    t = out.get("totals", {})
    out["active"] = bool(out.get("enabled") or t.get("yields")
                         or t.get("serving_dispatches"))
    return out


def registry_stats() -> Dict:
    """The central metrics registry's JSON view (counters/gauges/histogram
    summaries + windowed rates) — the /3/Profiler fold of the same store
    GET /3/Metrics scrapes as Prometheus text."""
    from . import metrics_registry

    return metrics_registry.snapshot()


def tracing_stats(n: int = 20) -> Dict:
    """Recent span summaries (the /3/Timeline fold, also available here)."""
    from . import tracing

    return dict(recorded=tracing.span_count(),
                recent=tracing.summaries(n))


@contextlib.contextmanager
def trace(log_dir: str):
    """`with profiler.trace('/tmp/tb'):` — device + host trace via
    jax.profiler (viewable in tensorboard/perfetto)."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
