"""Memory ledger — unified host+device byte accounting for every subsystem.

The PR 6 spine made *time* observable (spans, latency histograms, retrace
counters); this module is its byte-side twin. Before it, every byte-holding
subsystem kept a private, incompatible count (``DKV._nbytes``,
``dataset_cache._Entry.nbytes``, ``ScorerCache.stats``) and nothing reported
live HBM occupancy, watermarks or leaks — the exact blind spot that gates
out-of-core training (stream blocks against an HBM budget, arXiv 2005.09148)
and sustained-SLO serving (ROADMAP items 3 and 4).

Design — an *accountant*, not an allocator:

- every byte-holding subsystem **registers owners** (``dkv:<key>``,
  ``dataset_cache:<fp>:<layer>``, ``scorer:<model_key>:<kind>``,
  ``ingest:<what>``) with byte callbacks and an optional weakref *referent*
  whose death marks the owner dead. Callbacks must never strongly pin the
  accounted object — they dereference weakrefs and report 0 once it died.
- ``refresh()`` walks the owners (rate-limited, callbacks run lock-free,
  one shared ``measure()`` dedup set per pass so a buffer reachable from
  two owners is attributed once), reconciles attributed device bytes
  against what the runtime actually holds (``device.memory_stats()`` where
  available, live-buffer census fallback on CPU — the unattributed delta
  is reported as ``owner_kind="unaccounted"``), tracks high watermarks and
  the top owners at the peak, and feeds the
  ``h2o3_memory_bytes{owner_kind,space}`` gauges.
- the **leak detector**: a dead owner whose callbacks still report bytes
  (the referent died but something else pins its buffers), or a FAILED/
  CANCELLED Job whose dest key is still in the DKV (``job_end``). Leaks
  surface as ``h2o3_memory_leaked_bytes`` + timeline events and *clear*
  when the bytes are finally released.
- the **pressure API**: ``pressure()`` ∈ [0,1] against
  ``H2O3_MEM_BUDGET_MB`` (host; default: /proc/meminfo MemTotal) and the
  device capacity (``memory_stats()['bytes_limit']`` or
  ``H2O3_DEVICE_BUDGET_MB``). Serving admission control sheds at
  ``H2O3_SERVING_SHED_PRESSURE`` and ``dataset_cache._evict_locked``
  evicts LRU entries past ``H2O3_MEM_EVICT_PRESSURE``; threshold
  crossings are traced.

Read surfaces: ``GET /3/Memory`` (JSON breakdown; ``?schema=1`` →
MemoryV3), the normal ``/3/Metrics`` Prometheus scrape (a registry collect
hook refreshes the gauges at scrape time), and the ``/3/Profiler`` fold.
Alloc/evict/free/leak events land in the Timeline ring and annotate the
open tracing span (docs/observability.md "Memory accounting").
"""

from __future__ import annotations

import os
import sys
import threading
import time
import weakref
import zlib
from typing import Callable, Dict, List, Optional, Tuple

from . import env_float, env_int

__all__ = ["register", "unregister", "unregister_prefix", "record_event",
           "measure", "refresh", "snapshot", "totals", "pressure", "peak",
           "owners", "dkv_stats", "job_end", "ingest_buffer",
           "evict_threshold", "device_capacity_bytes", "clear"]

# how stale a cached refresh may be before a read recomputes (scrape-time
# collect hooks and the admission-path pressure() both ride this)
_REFRESH_S = env_float("H2O3_MEM_REFRESH_S", 0.5)
# pressure above this emits a threshold-crossing event (and below, a
# recovery event) — the observability signal, not an action threshold
_PRESS_THRESHOLD = env_float("H2O3_MEM_PRESSURE_THRESHOLD", 0.85)
# owners listed in a snapshot (the rest aggregate into by_kind totals)
_SNAPSHOT_OWNERS = env_int("H2O3_MEM_SNAPSHOT_OWNERS", 256)

_STR_SAMPLE = 256          # sampled string-column estimate (DKV._nbytes rule)
_MEASURE_DEPTH = 4         # object-graph walk bound
_LOCK_TYPE = type(threading.Lock())


class _Owner:
    __slots__ = ("owner", "kind", "type_name", "bytes_fn", "ref", "dead",
                 "leaked", "t_register", "last_host", "last_device",
                 "last_disk", "__weakref__")

    def __init__(self, owner: str, kind: str, type_name: str,
                 bytes_fn: Callable[[], Tuple[int, int]]):
        self.owner = owner
        self.kind = kind
        self.type_name = type_name
        self.bytes_fn = bytes_fn
        self.ref: Optional[weakref.ref] = None
        self.dead = False          # referent died (weakref callback fired)
        self.leaked = False        # leak event already emitted
        self.t_register = time.time()
        self.last_host = 0
        self.last_device = 0
        self.last_disk = 0


_REG_LOCK = threading.Lock()       # guards _OWNERS / _JOB_LEAKS only
_OWNERS: Dict[str, _Owner] = {}
_JOB_LEAKS: Dict[str, Dict] = {}   # dest key -> {status, t_end, bytes}

_REFRESH_LOCK = threading.Lock()   # one refresh pass at a time
_STATE_LOCK = threading.Lock()     # guards the cached-result REFERENCE
# the cached refresh result: REBOUND atomically, never mutated in place —
# readers got handed this dict lock-free, so a clear()+update() swap would
# expose them to a transient KeyError mid-pass
_STATE: Dict = dict(
    t=0.0, by_kind={}, totals=dict(host_bytes=0, device_bytes=0,
                                   disk_bytes=0, leaked_bytes=0,
                                   unaccounted_device_bytes=0,
                                   owner_count=0),
    owners=[], leaks=[], device={}, pressure={}, )
_HWM = dict(host=0, device=0, disk=0, total=0)
_PEAK_TOP: List[Dict] = []
_PRESS_HIGH = [False]

_TLS = threading.local()           # .seen — per-refresh measure dedup set


# -- registry families ---------------------------------------------------------

_REG: Dict = {}


def _registry() -> Dict:
    """Memoized registry families + REST bindings + the scrape-time collect
    hook (same lazy-memoization stance as every other subsystem)."""
    if not _REG:
        from . import metrics_registry as reg

        _REG["bytes"] = reg.gauge(
            "h2o3_memory_bytes",
            "ledger-attributed bytes per owner kind and memory space "
            "(owner_kind=unaccounted is the device-census remainder)",
            labelnames=("owner_kind", "space"))
        _REG["hwm"] = reg.gauge(
            "h2o3_memory_high_watermark_bytes",
            "high watermark of ledger-attributed bytes per space",
            labelnames=("space",))
        _REG["leaked"] = reg.gauge(
            "h2o3_memory_leaked_bytes",
            "bytes held by dead owners (referent died, buffers persist) "
            "plus DKV keys not freed after a failed job")
        _REG["owners"] = reg.gauge(
            "h2o3_memory_owners", "registered ledger owners")
        _REG["pressure"] = reg.gauge(
            "h2o3_memory_pressure",
            "memory pressure in [0,1]: max of host bytes vs "
            "H2O3_MEM_BUDGET_MB and device bytes vs device capacity")
        _REG["events"] = reg.counter(
            "h2o3_memory_events",
            "memory lifecycle events (alloc/evict/free/leak/leak_cleared/"
            "pressure_high/pressure_normal)",
            labelnames=("event", "owner_kind"))
        for f, m in (("host_bytes", "h2o3_memory_bytes"),
                     ("device_bytes", "h2o3_memory_bytes"),
                     ("disk_bytes", "h2o3_memory_bytes"),
                     ("unaccounted_device_bytes", "h2o3_memory_bytes"),
                     ("leaked_bytes", "h2o3_memory_leaked_bytes"),
                     ("owner_count", "h2o3_memory_owners")):
            reg.bind_rest_field("memory", f"totals.{f}", m)
        # scrape-time pull: GET /3/Metrics and the /3/Profiler fold see
        # gauges no staler than the refresh rate limit
        reg.register_collect_hook(lambda: refresh())
    return _REG


# -- budgets / probes ----------------------------------------------------------

def _host_budget_bytes() -> int:
    mb = env_float("H2O3_MEM_BUDGET_MB", 0.0)
    if mb > 0:
        return int(mb * 1e6)
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return 16 << 30


def _rss_bytes() -> int:
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        try:
            import resource

            return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
        except Exception:
            return 0


def evict_threshold() -> float:
    """Pressure above which byte caches (dataset_cache) shed LRU entries."""
    return env_float("H2O3_MEM_EVICT_PRESSURE", 0.9)


def device_capacity_bytes() -> int:
    """Device byte capacity as the ledger sees it: ``memory_stats()``
    limit where the backend reports one, else the census fallback's cap
    (``H2O3_DEVICE_BUDGET_MB`` / host budget). The out-of-core streaming
    layer derives its resident budget from this — one authoritative
    number instead of a guessed HBM cap (ISSUE 14)."""
    cap = int(_probe_device().get("capacity_bytes", 0))
    return cap or _host_budget_bytes()


def _probe_device() -> Dict:
    """What the runtime actually holds on-device: per-device
    ``memory_stats()`` where the backend reports them (TPU/GPU), else a
    live-buffer census (sum of live jax.Array nbytes — the CPU fallback).
    Never *imports* jax: if the platform isn't loaded there are no device
    buffers to probe."""
    jx = sys.modules.get("jax")
    if jx is None:
        return dict(probe="unavailable", in_use_bytes=0, capacity_bytes=0,
                    devices=[])
    devices = []
    in_use = limit = 0
    try:
        for d in jx.devices():
            stats = None
            try:
                stats = d.memory_stats()
            except Exception:
                stats = None
            if stats and "bytes_in_use" in stats:
                devices.append(dict(id=str(d.id), platform=d.platform,
                                    bytes_in_use=int(stats["bytes_in_use"]),
                                    bytes_limit=int(stats.get("bytes_limit",
                                                              0))))
                in_use += int(stats["bytes_in_use"])
                limit += int(stats.get("bytes_limit", 0))
    except Exception:
        pass
    if devices:
        return dict(probe="memory_stats", in_use_bytes=in_use,
                    capacity_bytes=limit, devices=devices)
    # census fallback (forced-CPU lanes, backends without memory_stats)
    census = n = 0
    try:
        for a in jx.live_arrays():
            try:
                census += int(a.nbytes)
                n += 1
            except Exception:
                pass
    except Exception:
        return dict(probe="unavailable", in_use_bytes=0, capacity_bytes=0,
                    devices=[])
    cap_mb = env_float("H2O3_DEVICE_BUDGET_MB", 0.0)
    cap = int(cap_mb * 1e6) if cap_mb > 0 else _host_budget_bytes()
    return dict(probe="census", in_use_bytes=census, capacity_bytes=cap,
                live_buffers=n, devices=[])


# -- the one deep sizer --------------------------------------------------------

def measure(value) -> Tuple[int, int]:
    """(host_bytes, device_bytes) of one object graph — the ONE sizing rule
    DKV, the scorer cache and the job-leak check share. numpy buffers are
    host; jax Arrays are device (``.nbytes`` without materializing — a
    device array must never pay a D2H to be counted); string columns use
    the sampled estimate; nested Frames/Vecs/BinnedMatrix/model ``__dict__``
    graphs are walked to a bounded depth with a cycle/shared-buffer guard.
    Inside a ledger refresh pass the guard set is shared across owners, so
    a buffer reachable from two owners is attributed to the first."""
    seen = getattr(_TLS, "seen", None)
    if seen is None:
        seen = set()
    acc = [0, 0]
    _measure_into(value, acc, seen, 0)
    return acc[0], acc[1]


def _measure_into(x, acc, seen, depth) -> None:
    if x is None or isinstance(x, (bool, int, float, complex)):
        return
    if isinstance(x, (str, bytes, bytearray)):
        acc[0] += len(x)
        return
    i = id(x)
    if i in seen:
        return
    seen.add(i)
    import numpy as np

    if isinstance(x, np.ndarray):
        if x.dtype == object:
            # sampled estimate — a per-element loop would make a scrape
            # O(total string cells)
            import itertools

            flat = x.ravel()
            sample = list(itertools.islice(
                (s for s in flat if s is not None), _STR_SAMPLE))
            avg = (sum(len(str(s)) for s in sample) / len(sample)
                   if sample else 0.0)
            acc[0] += int(avg * flat.size)
        else:
            acc[0] += int(x.nbytes)
        return
    jx = sys.modules.get("jax")
    if jx is not None and isinstance(x, jx.Array):
        try:
            if getattr(x, "is_fully_addressable", True):
                acc[1] += int(x.nbytes)
            else:
                # process-spanning global array (pod training): count only
                # THIS rank's resident shards — the per-rank ledger must
                # show the 1/N local footprint, not the global bytes
                acc[1] += sum(int(s.data.nbytes)
                              for s in x.addressable_shards)
        except Exception:
            pass
        return
    if depth >= _MEASURE_DEPTH:
        return
    if isinstance(x, dict):
        for v in x.values():
            _measure_into(v, acc, seen, depth + 1)
        return
    if isinstance(x, (list, tuple, set, frozenset)):
        for v in x:
            _measure_into(v, acc, seen, depth + 1)
        return
    if isinstance(x, (type, threading.Thread, _LOCK_TYPE,
                      weakref.ref)) or callable(x):
        return
    vecs = getattr(x, "_vecs", None)
    if isinstance(vecs, dict):                 # Frame
        for v in vecs.values():
            _measure_into(v, acc, seen, depth + 1)
        return
    d = getattr(x, "__dict__", None)
    if isinstance(d, dict):                    # models, BinnedMatrix, ...
        for v in d.values():
            _measure_into(v, acc, seen, depth + 1)
        return
    slots = getattr(type(x), "__slots__", None)
    if slots:                                  # Vec and friends
        for s in slots:
            if s == "__weakref__":
                continue
            try:
                _measure_into(getattr(x, s, None), acc, seen, depth + 1)
            except Exception:
                pass


# -- owner lifecycle -----------------------------------------------------------

def register(owner: str, kind: Optional[str] = None, *,
             bytes_fn: Optional[Callable[[], Tuple[int, int]]] = None,
             host_fn: Optional[Callable[[], int]] = None,
             device_fn: Optional[Callable[[], int]] = None,
             referent=None, type_name: str = "") -> str:
    """Register (or replace) a byte owner. `bytes_fn` returns
    (host, device) or (host, device, disk) — the optional third element
    accounts persist-backed spill files (the block store's disk tier);
    or pass `host_fn`/`device_fn` separately. `referent`
    is the object whose death marks the owner dead (weakref-backed —
    never pinned); callbacks must not strongly hold the referent either,
    or the ledger itself becomes the leak it exists to find."""
    if kind is None:
        kind = owner.split(":", 1)[0]
    if bytes_fn is None:
        hf, df = host_fn, device_fn
        bytes_fn = lambda: (int(hf() if hf else 0),   # noqa: E731
                            int(df() if df else 0))
    o = _Owner(owner, kind, type_name, bytes_fn)
    if referent is not None:
        try:
            o.ref = weakref.ref(referent, lambda _r, _o=weakref.ref(o):
                                _mark_dead(_o))
        except TypeError:
            o.ref = None
    with _REG_LOCK:
        _OWNERS[owner] = o
    _registry()
    return owner


def _mark_dead(owner_ref) -> None:
    o = owner_ref()
    if o is None:
        return
    with _REG_LOCK:
        if _OWNERS.get(o.owner) is o:
            o.dead = True


def unregister(owner: str, *, event: Optional[str] = None,
               nbytes: Optional[int] = None, trigger: str = "",
               space: str = "host") -> bool:
    """Remove an owner; optionally emit a lifecycle event sized by
    `nbytes` (defaults to the owner's last-refreshed bytes)."""
    with _REG_LOCK:
        o = _OWNERS.pop(owner, None)
    if o is None:
        return False
    if event:
        if nbytes is None:
            nbytes = o.last_host + o.last_device + o.last_disk
        record_event(event, owner, nbytes, trigger=trigger, space=space,
                     kind=o.kind)
    return True


def unregister_prefix(prefix: str) -> int:
    with _REG_LOCK:
        doomed = [k for k in _OWNERS if k.startswith(prefix)]
        for k in doomed:
            _OWNERS.pop(k, None)
    return len(doomed)


def owners(prefix: str = "") -> List[Dict]:
    """Registered owners (id, kind, last-refreshed bytes, dead flag)."""
    with _REG_LOCK:
        items = [o for k, o in _OWNERS.items() if k.startswith(prefix)]
    return [dict(owner=o.owner, kind=o.kind, type=o.type_name,
                 host_bytes=o.last_host, device_bytes=o.last_device,
                 disk_bytes=o.last_disk, dead=o.dead) for o in items]


def record_event(event: str, owner: str, nbytes: int = 0, *,
                 trigger: str = "", space: str = "host",
                 kind: Optional[str] = None) -> None:
    """One memory lifecycle event → registry counter + Timeline ring +
    an annotation on the open tracing span (so an eviction that happens
    inside a request/candidate shows up in its trace)."""
    if kind is None:
        kind = owner.split(":", 1)[0]
    _registry()["events"].inc(1, event, kind)
    try:
        from .timeline import Timeline

        Timeline.record("memory", f"{event} {owner}", owner=owner,
                        bytes=int(nbytes), trigger=trigger, space=space)
    except Exception:
        pass
    try:
        from . import tracing

        tracing.event(f"memory_{event}", owner=owner, bytes=int(nbytes),
                      trigger=trigger)
    except Exception:
        pass


def job_end(dest_key: str, status: str) -> None:
    """Job-lifecycle leak check: a FAILED/CANCELLED job whose dest key is
    still in the DKV is a leak candidate (the partial model should have
    been deleted — docs/robustness.md); it surfaces in the leak report
    until the key is freed."""
    if status not in ("FAILED", "CANCELLED"):
        with _REG_LOCK:
            _JOB_LEAKS.pop(dest_key, None)
        return
    from .dkv import DKV, _owner_kind

    v = DKV.get(dest_key)
    if v is None or _owner_kind(v) == "dkv":
        # nothing there, or only bookkeeping (the Job itself stays for
        # status polling) — no byte-owner left behind
        return
    with _REG_LOCK:
        known = dest_key in _JOB_LEAKS
        if not known:
            _JOB_LEAKS[dest_key] = dict(status=status, t_end=time.time(),
                                        bytes=0)
    if not known:
        record_event("leak", f"dkv:{dest_key}", 0,
                     trigger=f"job_{status.lower()}", kind="dkv")


# -- ingest transient buffers --------------------------------------------------

_INGEST_LOCK = threading.Lock()
_INGEST_BYTES = [0]
_INGEST_REGISTERED = [False]


class ingest_buffer:
    """``with ingest_buffer(len(data)):`` — account a parse payload while
    it is being tokenized (the `ingest:<what>` owner of the taxonomy)."""

    def __init__(self, nbytes: int, what: str = "tokenize"):
        self.nbytes = int(nbytes)
        self.what = what

    def __enter__(self):
        with _INGEST_LOCK:
            _INGEST_BYTES[0] += self.nbytes
            if not _INGEST_REGISTERED[0]:
                _INGEST_REGISTERED[0] = True
                register("ingest:tokenize", kind="ingest",
                         host_fn=lambda: _INGEST_BYTES[0],
                         type_name="bytes")
        record_event("alloc", f"ingest:{self.what}", self.nbytes,
                     trigger="parse", kind="ingest")
        return self

    def __exit__(self, *exc):
        with _INGEST_LOCK:
            _INGEST_BYTES[0] = max(_INGEST_BYTES[0] - self.nbytes, 0)
        return False


# -- refresh: the accounting pass ----------------------------------------------

def refresh(force: bool = False) -> Dict:
    """Recompute the ledger: per-owner bytes (one shared measure() dedup
    set), leak scan, device reconciliation, watermarks, pressure, gauges.
    Rate-limited (`H2O3_MEM_REFRESH_S`) unless `force`; concurrent callers
    get the cached result instead of a second pass. Callbacks run without
    any ledger lock held, so a callback may take its subsystem's lock
    (DKV, dataset_cache) without ordering hazards."""
    now = time.time()
    with _STATE_LOCK:
        if not force and now - _STATE["t"] < _REFRESH_S:
            return _STATE
    if not _REFRESH_LOCK.acquire(blocking=False):
        with _STATE_LOCK:
            return _STATE
    try:
        return _refresh_locked(now)
    finally:
        _REFRESH_LOCK.release()


def _refresh_locked(now: float) -> Dict:
    reg = _registry()
    with _REG_LOCK:
        owner_objs = list(_OWNERS.values())
        job_leaks = dict(_JOB_LEAKS)
    _TLS.seen = set()
    try:
        by_kind: Dict[str, List[int]] = {}
        rows: List[Dict] = []
        leaks: List[Dict] = []
        retire: List[_Owner] = []
        host_total = dev_total = disk_total = leaked = 0
        # job leaks FIRST: the leaked value usually also has a live `dkv:`
        # owner (the key never left the store), and the shared dedup set
        # attributes each buffer to whichever view measures it first — an
        # operator reading the leak report needs its size, so the leak
        # entry wins and the aliasing owner reports ~0 for the pass
        from .dkv import DKV

        for dest, info in job_leaks.items():
            v = DKV.get(dest)
            if v is None:
                with _REG_LOCK:
                    _JOB_LEAKS.pop(dest, None)
                record_event("leak_cleared", f"dkv:{dest}", info["bytes"],
                             kind="dkv")
                continue
            h, d = measure(v)
            b = h + d
            info["bytes"] = b
            with _REG_LOCK:
                if dest in _JOB_LEAKS:
                    _JOB_LEAKS[dest]["bytes"] = b
            leaked += b
            host_total += h
            dev_total += d
            agg = by_kind.setdefault("leaked", [0, 0, 0, 0])
            agg[0] += h
            agg[1] += d
            agg[3] += 1
            rows.append(dict(owner=f"dkv:{dest}", kind="leaked",
                             host_bytes=h, device_bytes=d, disk_bytes=0,
                             dead=False))
            leaks.append(dict(owner=f"dkv:{dest}", kind="dkv", bytes=b,
                              reason=f"job_{info['status'].lower()}"))
        for o in owner_objs:
            try:
                vals = o.bytes_fn()
                h, d = int(vals[0]), int(vals[1])
                k = int(vals[2]) if len(vals) > 2 else 0
            except Exception:
                h = d = k = 0
            o.last_host, o.last_device, o.last_disk = h, d, k
            if o.dead:
                if h + d + k <= 0:
                    if o.leaked:
                        record_event("leak_cleared", o.owner, 0,
                                     kind=o.kind)
                    retire.append(o)
                    continue
                leaked += h + d + k
                leaks.append(dict(owner=o.owner, kind=o.kind,
                                  bytes=h + d + k, reason="referent_dead"))
                if not o.leaked:
                    o.leaked = True
                    record_event("leak", o.owner, h + d + k,
                                 trigger="referent_dead", kind=o.kind,
                                 space="disk" if (k and not d and not h)
                                 else ("device" if d else "host"))
            host_total += h
            dev_total += d
            disk_total += k
            agg = by_kind.setdefault(o.kind, [0, 0, 0, 0])
            agg[0] += h
            agg[1] += d
            agg[2] += k
            agg[3] += 1
            rows.append(dict(owner=o.owner, kind=o.kind,
                             host_bytes=h, device_bytes=d, disk_bytes=k,
                             dead=o.dead))
    finally:
        _TLS.seen = None
    with _REG_LOCK:
        for o in retire:
            if _OWNERS.get(o.owner) is o:
                _OWNERS.pop(o.owner, None)
        n_owners = len(_OWNERS)

    device = _probe_device()
    unaccounted = max(int(device.get("in_use_bytes", 0)) - dev_total, 0) \
        if device.get("probe") != "unavailable" else 0

    # pressure: host bytes vs budget, device bytes vs capacity
    host_budget = _host_budget_bytes()
    rss = _rss_bytes()
    host_press = max(rss, host_total) / max(host_budget, 1)
    dev_cap = int(device.get("capacity_bytes", 0))
    dev_used = max(int(device.get("in_use_bytes", 0)), dev_total)
    dev_press = dev_used / dev_cap if dev_cap > 0 else 0.0
    press = min(max(host_press, dev_press, 0.0), 1.0)
    if press >= _PRESS_THRESHOLD and not _PRESS_HIGH[0]:
        _PRESS_HIGH[0] = True
        record_event("pressure_high", "ledger", 0,
                     trigger=f"{press:.3f}", kind="ledger")
    elif press < _PRESS_THRESHOLD and _PRESS_HIGH[0]:
        _PRESS_HIGH[0] = False
        record_event("pressure_normal", "ledger", 0,
                     trigger=f"{press:.3f}", kind="ledger")

    # watermarks + top owners at the combined peak
    total = host_total + dev_total + disk_total
    _HWM["host"] = max(_HWM["host"], host_total)
    _HWM["device"] = max(_HWM["device"], dev_total)
    _HWM["disk"] = max(_HWM["disk"], disk_total)
    if total > _HWM["total"]:
        _HWM["total"] = total
        top = sorted(rows, key=lambda r: -(r["host_bytes"]
                                           + r["device_bytes"]
                                           + r.get("disk_bytes", 0)))[:3]
        _PEAK_TOP[:] = [dict(owner=r["owner"], kind=r["kind"],
                             bytes=r["host_bytes"] + r["device_bytes"]
                             + r.get("disk_bytes", 0))
                        for r in top]

    # gauges (zero kinds that vanished so stale series don't lie)
    seen_labels = set()
    for kind, (h, d, k, _n) in by_kind.items():
        reg["bytes"].set(h, kind, "host")
        reg["bytes"].set(d, kind, "device")
        seen_labels.add((kind, "host"))
        seen_labels.add((kind, "device"))
        if k:
            reg["bytes"].set(k, kind, "disk")
            seen_labels.add((kind, "disk"))
    reg["bytes"].set(unaccounted, "unaccounted", "device")
    seen_labels.add(("unaccounted", "device"))
    for lv in reg["bytes"].children():
        if lv not in seen_labels and lv != ("_overflow", "_overflow"):
            reg["bytes"].set(0, *lv)
    reg["hwm"].set(_HWM["host"], "host")
    reg["hwm"].set(_HWM["device"], "device")
    reg["hwm"].set(_HWM["disk"], "disk")
    reg["leaked"].set(leaked)
    reg["owners"].set(n_owners)
    reg["pressure"].set(round(press, 4))

    rows.sort(key=lambda r: -(r["host_bytes"] + r["device_bytes"]
                              + r.get("disk_bytes", 0)))
    state = dict(
        t=now,
        totals=dict(host_bytes=host_total, device_bytes=dev_total,
                    disk_bytes=disk_total, leaked_bytes=leaked,
                    unaccounted_device_bytes=unaccounted,
                    owner_count=n_owners),
        by_kind={k: dict(host_bytes=v[0], device_bytes=v[1],
                         disk_bytes=v[2], owners=v[3])
                 for k, v in sorted(by_kind.items())},
        owners=rows[:_SNAPSHOT_OWNERS],
        leaks=leaks,
        device=device,
        pressure=dict(value=round(press, 4),
                      host=round(min(host_press, 1.0), 4),
                      device=round(min(dev_press, 1.0), 4),
                      threshold=_PRESS_THRESHOLD,
                      host_budget_bytes=host_budget,
                      device_capacity_bytes=dev_cap,
                      rss_bytes=rss),
    )
    global _STATE
    with _STATE_LOCK:
        _STATE = state
    return state


# -- read side -----------------------------------------------------------------

def totals() -> Dict:
    return dict(refresh()["totals"])


def pressure() -> float:
    """The [0,1] pressure signal admission control and cache eviction
    consult — a cached read between refresh intervals."""
    return float(refresh()["pressure"].get("value", 0.0))


def peak() -> Dict:
    """High watermarks + the top-3 owners captured at the combined peak
    (the bench-record memory embed)."""
    refresh()
    return dict(host_bytes=_HWM["host"], device_bytes=_HWM["device"],
                disk_bytes=_HWM["disk"], total_bytes=_HWM["total"],
                top_owners=list(_PEAK_TOP))


def snapshot(force: bool = True) -> Dict:
    """The GET /3/Memory document: owners, by-kind totals, watermarks,
    pressure, device probe + reconciliation, leaks. `force=False` serves
    the rate-limited cached pass (the /3/Profiler fold) instead of paying
    a fresh accounting walk per read."""
    st = refresh(force=force)
    out = {k: v for k, v in st.items() if k != "t"}
    out["watermarks"] = peak()
    return out


def dkv_stats() -> Dict:
    """The DKV's store-level accounting, derived from the ledger's
    `dkv:`-prefixed owners — `DKV.stats()` delegates here so the two
    surfaces can never disagree."""
    refresh(force=True)
    with _REG_LOCK:
        items = [o for k, o in _OWNERS.items() if k.startswith("dkv:")]
    by_kind: Dict[str, Dict] = {}
    total = 0
    for o in items:
        b = o.last_host + o.last_device
        d = by_kind.setdefault(o.type_name or "object",
                               {"count": 0, "bytes": 0})
        d["count"] += 1
        d["bytes"] += b
        total += b
    return {"entries": len(items), "total_bytes": total, "by_kind": by_kind}


def fingerprint(key) -> str:
    """Short stable digest for owner ids built from unhashable-ish keys."""
    return "%08x" % (zlib.crc32(repr(key).encode()) & 0xFFFFFFFF)


def clear() -> None:
    """Forget every owner, leak and watermark (tests)."""
    global _STATE
    with _REG_LOCK:
        _OWNERS.clear()
        _JOB_LEAKS.clear()
    with _STATE_LOCK:
        _STATE = dict(_STATE, t=0.0)   # rebind: readers hold the old dict
    _HWM.update(host=0, device=0, disk=0, total=0)
    _PEAK_TOP.clear()
    _PRESS_HIGH[0] = False
