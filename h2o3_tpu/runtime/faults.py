"""Fault injection — deterministic, seedable failure points in the runtime.

The hardening layer (runtime/retry.py, trainpool candidate retries, serving
failover) is only trustworthy if its failure paths are EXERCISED, not just
written; this registry lets tests, the chaos bench (`BENCH_CONFIG=chaos`)
and operators arm specific failure points without touching product code.
Every wired call site costs one dict lookup when nothing is armed (the
`_ACTIVE` fast path), so production runs pay ~nothing.

Wired points (each named like the layer it lives in):

==========================  ==================================================
``persist.open``            raises before a storage backend opens a URI
``persist.read``            raises inside an http persist stream's read()
``persist.list``            raises before a backend lists a URI
``client.request``          raises before the remote client's HTTP round-trip
``trainpool.candidate``     raises before a sweep candidate's build fn runs
``serving.scorer``          raises inside the compiled scorer's device call
``mesh.lane_delay``         sleeps ``latency_ms`` inside ONE mesh lane's
                            collective-arrival callback (``lane=N`` selects
                            the lane) — the deterministic straggler
                            injection the skew profiler/detector is proven
                            against (parallel/mesh lane timing, ISSUE 13)
``qos.starve``              armed with ``error="none"``: every QoS
                            yield point sees a CLOSED gate (sustained
                            serving-load simulation) — the chaos lane's
                            proof that ``H2O3_QOS_TRAIN_MIN_SHARE`` still
                            guarantees training forward progress
                            (``match=`` scopes to one yield site)
``qos.preempt_delay``       sleeps ``latency_ms`` at a QoS yield point
                            itself (``error="none"``) — injected
                            preemption latency, surfaced in
                            ``h2o3_qos_preempt_latency_ms``
``mesh.rank_kill``          HARD-EXITS this process (``os._exit``) inside a
                            mesh lane's collective-arrival callback — the
                            rank-death injection of the pod chaos lane
                            (``BENCH_CONFIG=pod_chaos``); ``after=N`` delays
                            the kill to the N+1-th fence so checkpoints
                            exist before the death (parallel/mesh)
``supervisor.ckpt_corrupt`` truncates a fit checkpoint's serialized blob
                            BEFORE its atomic rename — the committed file
                            is torn exactly like a mid-write crash, and
                            restore must reject it (runtime/supervisor)
``supervisor.fit_abort``    raises at a tree-fit chunk boundary — the
                            in-process candidate-crash injection the
                            kill-and-resume pins use (models/shared_tree)
==========================  ==================================================

Arming — programmatic, env, or REST:

* ``faults.arm("serving.scorer", error="device", rate=0.01, seed=7)``
* ``H2O3_FAULT_SERVING_SCORER="error=device,rate=0.01,seed=7"`` (the
  subsystem dot maps to the FIRST underscore, upper-cased — later
  underscores stay, so ``H2O3_FAULT_MESH_RANK_KILL`` → ``mesh.rank_kill``)
* ``POST /3/Faults`` with the same fields; ``GET /3/Faults`` shows armed
  points + fire counts; ``DELETE /3/Faults[?point=]`` disarms.

Determinism: ``count=N`` fires the FIRST N checks of a point (the
retry-then-succeed shape tests pin); ``after=K`` skips the first K checks
before the count/rate schedule applies (fire at fence N, not fence 1);
``rate=p`` draws from a dedicated ``numpy.random.default_rng(seed)`` per
point, so the same seed produces the same fire sequence. Fault points are
DEFAULT-OFF; `reset()` disarms all.

``latency_ms`` injects sleep without (or in addition to) an error — the
injected-latency fault of the issue spec. ``match=substr`` scopes a point
to checks whose detail string contains the substring — e.g.
``arm("serving.scorer", error="crash", match="m@v2")`` fails exactly one
model VERSION's traffic (how the canary-rollback pin poisons the
candidate while live traffic keeps flowing).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional

__all__ = ["FaultInjected", "InjectedIOError", "InjectedConnectionError",
           "InjectedDeviceError", "InjectedCrash", "arm", "disarm", "reset",
           "check", "is_armed", "snapshot", "active"]


class FaultInjected(Exception):
    """Marker base: every injected error is recognizable as synthetic."""


class InjectedIOError(FaultInjected, IOError):
    """Injected persist/storage I/O failure (transient)."""


class InjectedConnectionError(FaultInjected, ConnectionError):
    """Injected HTTP/connection drop (transient)."""


class InjectedDeviceError(FaultInjected, RuntimeError):
    """Injected device/XLA runtime failure (transient, quarantine-class)."""


class InjectedCrash(FaultInjected, RuntimeError):
    """Injected permanent failure — retry must NOT mask it."""


ERROR_KINDS = {
    "io": InjectedIOError,
    "conn": InjectedConnectionError,
    "device": InjectedDeviceError,
    "crash": InjectedCrash,
    "none": None,          # latency-only point
}


class _Point:
    __slots__ = ("name", "kind", "rate", "count", "latency_ms", "seed",
                 "lane", "match", "after", "checks", "fires", "_rng")

    def __init__(self, name: str, kind: str, rate: float,
                 count: Optional[int], latency_ms: float, seed: int,
                 lane: Optional[int] = None, match: Optional[str] = None,
                 after: int = 0):
        if kind not in ERROR_KINDS:
            raise ValueError(f"unknown fault error kind {kind!r} "
                             f"(one of {sorted(ERROR_KINDS)})")
        self.name = name
        self.kind = kind
        self.rate = float(rate)
        self.count = None if count in (None, "") else int(count)
        self.latency_ms = float(latency_ms)
        self.seed = int(seed)
        # lane-scoped points (mesh.lane_delay): only checks carrying this
        # lane index fire — the deterministic per-lane straggler injection
        self.lane = None if lane in (None, "") else int(lane)
        # detail-scoped points: only checks whose detail string contains
        # `match` fire — e.g. arm("serving.scorer", match="m@v2") fails
        # exactly one model version's traffic (the canary-rollback pin)
        self.match = match or None
        # deferred arming: the first `after` in-scope checks never fire —
        # "kill at fence N" needs fences 1..N-1 to pass undisturbed
        self.after = int(after or 0)
        self.checks = 0
        self.fires = 0
        self._rng = None    # built lazily; numpy import stays off hot path

    def should_fire(self) -> bool:
        if self.kind == "none":
            return False
        if self.checks <= self.after:
            return False
        if self.count is not None:
            return self.fires < self.count
        if self.rate >= 1.0:
            return True
        if self.rate <= 0.0:
            return False
        if self._rng is None:
            import numpy as np

            self._rng = np.random.default_rng(self.seed)
        return bool(self._rng.random() < self.rate)

    def describe(self) -> Dict:
        return dict(point=self.name, error=self.kind, rate=self.rate,
                    count=self.count, latency_ms=self.latency_ms,
                    seed=self.seed, lane=self.lane, match=self.match,
                    after=self.after, checks=self.checks, fires=self.fires)


_LOCK = threading.Lock()
_POINTS: Dict[str, _Point] = {}
_ACTIVE = False           # fast-path flag: no armed points → check() is free
_TOTAL_FIRES = 0


def _env_parse() -> None:
    """Arm points from H2O3_FAULT_* env vars (once, at import)."""
    for k, v in os.environ.items():
        if not k.startswith("H2O3_FAULT_") or not v:
            continue
        # point names are <subsystem>.<name> where <name> may itself
        # carry underscores (mesh.rank_kill): only the first maps to a dot
        point = k[len("H2O3_FAULT_"):].lower().replace("_", ".", 1)
        if v in ("1", "true", "on"):
            arm(point)
            continue
        kw: Dict[str, str] = {}
        try:
            for part in v.split(","):
                key, _, val = part.partition("=")
                kw[key.strip()] = val.strip()
            arm(point,
                error=kw.get("error", "io"),
                rate=float(kw.get("rate", 1.0)),
                count=int(kw["count"]) if kw.get("count") else None,
                latency_ms=float(kw.get("latency_ms", 0.0)),
                seed=int(kw.get("seed", 0)),
                lane=int(kw["lane"]) if kw.get("lane") else None,
                match=kw.get("match") or None,
                after=int(kw.get("after", 0) or 0))
        except (ValueError, TypeError) as e:
            raise ValueError(f"bad {k}={v!r}: {e}") from None


def arm(point: str, error: str = "io", rate: float = 1.0,
        count: Optional[int] = None, latency_ms: float = 0.0,
        seed: int = 0, lane: Optional[int] = None,
        match: Optional[str] = None, after: int = 0) -> Dict:
    """Arm one fault point; returns its description. `match` scopes the
    point to checks whose detail contains the substring (version-targeted
    canary faults); `after=K` lets the first K in-scope checks pass before
    the count/rate schedule applies (fire at fence N, not fence 1)."""
    global _ACTIVE
    p = _Point(point, error, rate, count, latency_ms, seed, lane=lane,
               match=match, after=after)
    with _LOCK:
        _POINTS[point] = p
        _ACTIVE = True
    return p.describe()


def disarm(point: str) -> bool:
    global _ACTIVE
    with _LOCK:
        existed = _POINTS.pop(point, None) is not None
        _ACTIVE = bool(_POINTS)
    return existed


def reset() -> None:
    global _ACTIVE, _TOTAL_FIRES
    with _LOCK:
        _POINTS.clear()
        _ACTIVE = False
        _TOTAL_FIRES = 0


def active() -> bool:
    return _ACTIVE


def check(point: str, detail: str = "", lane: Optional[int] = None) -> None:
    """The wired call sites' hook: no-op unless `point` is armed; sleeps
    the configured latency, then raises the configured error class when
    the deterministic schedule says so. `lane` scopes the check to a
    lane-armed point (mesh.lane_delay): a point armed with ``lane=N``
    only fires for checks carrying lane N."""
    if not _ACTIVE:             # unlocked fast path: default-off is free
        return
    global _TOTAL_FIRES
    with _LOCK:
        p = _POINTS.get(point)
        if p is None:
            return
        if p.lane is not None and (lane is None or int(lane) != p.lane):
            return
        if p.match is not None and p.match not in (detail or ""):
            return
        p.checks += 1
        fire = p.should_fire()
        if fire:
            p.fires += 1
            _TOTAL_FIRES += 1
        latency = p.latency_ms
        kind = ERROR_KINDS[p.kind]
    if fire:
        # observability spine: fired injections are scrapable and annotate
        # the owning span (only on fire — the disarmed fast path above and
        # the armed-but-quiet path stay allocation-free)
        from . import metrics_registry as _reg
        from . import tracing as _tracing

        _fired_counter(_reg).inc(1, point)
        _tracing.event("fault_fired", point=point, kind=p.kind,
                       **(dict(detail=detail) if detail else {}))
    if latency:
        time.sleep(latency / 1e3)
    if fire and kind is not None:
        raise kind(f"injected fault at {point}"
                   + (f" ({detail})" if detail else ""))


def is_armed(point: str, detail: str = "",
             lane: Optional[int] = None) -> bool:
    """Read-only probe: is `point` armed and in scope for this check?

    Unlike `check` it never sleeps and never raises — sites that need a
    boolean CONDITION rather than an injected failure use it (the QoS
    gate's ``qos.starve`` sustained-load simulation). Honors the same
    ``lane=`` / ``match=`` scoping; counts as a check for GET /3/Faults
    visibility. Free when nothing is armed."""
    if not _ACTIVE:
        return False
    with _LOCK:
        p = _POINTS.get(point)
        if p is None:
            return False
        if p.lane is not None and (lane is None or int(lane) != p.lane):
            return False
        if p.match is not None and p.match not in (detail or ""):
            return False
        p.checks += 1
        return True


_FIRED = None


def _fired_counter(reg):
    global _FIRED
    if _FIRED is None:
        _FIRED = reg.counter("h2o3_fault_fires",
                             "injected faults fired, per armed point",
                             labelnames=("point",))
    return _FIRED


def snapshot() -> Dict:
    """Armed points + fire counts (GET /3/Faults, /3/Profiler fold)."""
    with _LOCK:
        pts = [p.describe() for p in _POINTS.values()]
        return dict(active=bool(pts), points=pts, total_fires=_TOTAL_FIRES)


_env_parse()
