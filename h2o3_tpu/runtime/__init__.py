"""Runtime services — logging, timeline, profiler, persistence, DKV.

The host-side control plane of the platform (SURVEY.md §5): the data plane is
compiled XLA programs; these modules are the observability and bookkeeping
that `water/util/Log.java`, `water/TimeLine.java`, `water/api/ProfilerHandler`,
`water/persist/Persist.java` and `water/DKV.java` provide in the reference.
"""

def env_int(name: str, default: int) -> int:
    """Integer env knob with an empty-string-safe default (the one parser
    every H2O3_* knob shares). Defined before the submodule imports below
    so modules they pull in (timeline) can use it during package init."""
    import os

    v = os.environ.get(name)
    return default if v in (None, "") else int(v)


def env_float(name: str, default: float) -> float:
    import os

    v = os.environ.get(name)
    return default if v in (None, "") else float(v)


from .dkv import DKV  # noqa: E402,F401
from .log import Log  # noqa: E402,F401
from .timeline import Timeline  # noqa: E402,F401
