"""Multi-tenant QoS — the cooperative two-class dispatch gate (ISSUE 19).

One runtime holding a live serving fleet AND a background AutoML sweep on
the same accelerator needs a priority scheduler between them; this module
is that scheduler, in its cheapest honest form: a cooperative gate with
two classes, SERVING > TRAINING.

- **Serving dispatches never wait.** `serving_dispatch()` registers a
  scoring batch (batcher) or router forward as in flight — entry is
  non-blocking, always. The serving path's latency is never a function of
  what training is doing between its own dispatches.
- **Training yields at safe boundaries.** The tree driver calls
  `yield_point()` at chunk boundaries and before each scoring-event
  dispatch, the streamed tree step at per-BLOCK visits, and the estimator
  engine between bounded `while_loop` segments
  (`estimator_engine.max_iters_per_dispatch`). While any serving dispatch
  is in flight (or within a short linger window after one — back-to-back
  requests keep priority across their gaps), the yield blocks instead of
  enqueueing the next training program behind which a serving batch would
  otherwise queue.
- **Anti-starvation floor** (``H2O3_QOS_TRAIN_MIN_SHARE``): a training
  thread's cumulative wait is bounded so that
  ``ran / (ran + waited) >= share`` — under SUSTAINED serving load
  training still makes forward progress at roughly the configured share
  of wall-clock, one bounded wait per yield. ``H2O3_QOS_MAX_WAIT_MS``
  additionally caps any single wait (a progress backstop against a leaked
  in-flight count).
- **Admission throttle** (`admission_gate`, consulted by `trainpool`
  before each candidate): a hysteresis state machine over ONE
  `pressure_view()` snapshot and the live serving p99 read from the
  central registry (``h2o3_rest_request_ms{handler=predict}``): enter
  throttled at ``pressure >= H2O3_QOS_PRESSURE_HI`` OR
  ``p99 >= H2O3_QOS_SLO_MS * H2O3_QOS_P99_RATIO_HI``; exit only at
  ``pressure <= H2O3_QOS_PRESSURE_LO`` AND
  ``p99 <= SLO * H2O3_QOS_P99_RATIO_LO``. Every transition is a counter
  bump + gauge flip + trace event.
- **One pressure snapshot** (`pressure_view()`): serving admission and
  `dataset_cache._evict_locked` both read the ledger's pressure through
  this single consistent view, so a scrape-time refresh between their two
  reads can never shed serving scorers while admitting training work.
  Within one view ``shed_serving`` implies ``evict_cache`` (0.97 vs 0.9
  default thresholds): training artifacts always shed BEFORE serving does.

QoS is DEFAULT-OFF (``H2O3_QOS=1`` arms it) and changes WHEN programs
dispatch, never what they compute — every bit-exactness pin holds with the
gate armed (pinned in tests/test_qos.py).

Observability: ``h2o3_qos_yields{site}``, ``h2o3_qos_waits_ms{site}``,
``h2o3_qos_throttle_state``, ``h2o3_qos_throttle_transitions{state}``,
``h2o3_qos_preempt_latency_ms`` registry families; waits booked into the
``qos_wait`` phase bucket (subtracted from the enclosing compute bucket at
sites that would otherwise double-book); `stats()` is the /3/Profiler
``qos`` fold; `gate_state()` names the class holding the gate (the bench
watchdog's hang-attribution line).

Fault points (runtime/faults.py, REST-armable, ``match=`` scoped):
``qos.starve`` (error="none") makes every yield see a closed gate —
sustained-serving simulation proving the min-share floor; and
``qos.preempt_delay`` (error="none", latency_ms=X) injects latency at the
yield itself.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Dict, NamedTuple, Optional

from . import env_float
from . import faults as _faults

__all__ = ["enabled", "serving_dispatch", "yield_point", "admission_gate",
           "pressure_view", "PressureView", "serving_p99_ms", "throttled",
           "gate_state", "totals", "stats", "reset", "train_min_share"]


# -- knobs (read per call: tests and the bench flip env live) -----------------

def enabled() -> bool:
    """``H2O3_QOS=1`` arms the gate; default off (free: one env read)."""
    import os

    return os.environ.get("H2O3_QOS", "").lower() in ("1", "true", "yes")


def train_min_share() -> float:
    """Anti-starvation floor: training is entitled to at least this
    fraction of its own (ran + waited) wall under sustained serving
    load."""
    return min(max(env_float("H2O3_QOS_TRAIN_MIN_SHARE", 0.1), 0.0), 0.9)


def _linger_s() -> float:
    return env_float("H2O3_QOS_LINGER_MS", 5.0) / 1e3


def _max_wait_s() -> float:
    return env_float("H2O3_QOS_MAX_WAIT_MS", 1000.0) / 1e3


# -- gate state ---------------------------------------------------------------

_CV = threading.Condition()
_SERVING_INFLIGHT = 0
_LAST_SERVING_END = 0.0       # monotonic; 0 = never served
_LAST_SERVING_DETAIL = ""
# most recent training yield (any thread): the watchdog's holder verdict
# and the preempt-latency proxy both read it — plain dict, GIL-atomic
_LAST_TRAIN_YIELD = {"t": 0.0, "site": ""}
_TLS = threading.local()      # per-training-thread share ledger

_TLOCK = threading.Lock()
_TOTALS = {"yields": 0, "waits_ms": 0.0, "serving_dispatches": 0,
           "throttle_transitions": 0, "throttle_waits_ms": 0.0}
_THROTTLE = {"state": 0, "since": 0.0}
_VIEW: Dict[str, object] = {"view": None}

_REG: dict = {}


def _reg() -> dict:
    """Memoized registry families (registration never on the hot path)."""
    if not _REG:
        from . import metrics_registry as reg

        _REG["yields"] = reg.counter(
            "h2o3_qos_yields",
            "training yield-point visits, per site (tree_chunk/tree_block/"
            "score_event/est_segment)", labelnames=("site",))
        _REG["waits"] = reg.histogram(
            "h2o3_qos_waits_ms",
            "time training waited at a yield point for the serving class "
            "(ms), per site", labelnames=("site",))
        _REG["throttle_state"] = reg.gauge(
            "h2o3_qos_throttle_state",
            "trainpool admission throttle: 1 = candidate admission paused "
            "(pressure/serving-p99 hysteresis), 0 = open")
        _REG["transitions"] = reg.counter(
            "h2o3_qos_throttle_transitions",
            "admission-throttle state transitions", labelnames=("state",))
        _REG["preempt"] = reg.histogram(
            "h2o3_qos_preempt_latency_ms",
            "age of training's most recent yield point when a serving "
            "dispatch arrived (ms) — the cooperative bound on how long "
            "serving could wait for training to yield the device")
    return _REG


def _gate_closed(now: float) -> bool:
    """Training must hold back: a serving dispatch is in flight, one just
    finished (linger window — back-to-back requests keep priority across
    their gaps), or the chaos lane armed sustained-serving simulation."""
    if _SERVING_INFLIGHT > 0:
        return True
    if _LAST_SERVING_END and (now - _LAST_SERVING_END) < _linger_s():
        return True
    return _faults.is_armed("qos.starve")


def _tls_state() -> dict:
    st = getattr(_TLS, "share", None)
    if st is None:
        st = _TLS.share = {"ran_s": 0.0, "waited_s": 0.0, "t_resume": 0.0}
    return st


# -- the two classes ----------------------------------------------------------

@contextmanager
def serving_dispatch(detail: str = ""):
    """Register a serving-class dispatch (batcher batch, router forward).

    NEVER waits — serving's only relationship to the gate is to close it
    for training while in flight. Entry also records the preempt-latency
    proxy: the age of training's most recent yield point, i.e. the
    cooperative upper bound on how long this request could have sat
    behind a training program had the gate not held it back."""
    global _SERVING_INFLIGHT, _LAST_SERVING_END, _LAST_SERVING_DETAIL
    if not enabled():
        yield
        return
    now = time.monotonic()
    lt = _LAST_TRAIN_YIELD
    if lt["t"] and (now - lt["t"]) < 5.0:
        try:
            _reg()["preempt"].observe((now - lt["t"]) * 1e3)
        except Exception:
            pass
    with _CV:
        _SERVING_INFLIGHT += 1
        _LAST_SERVING_DETAIL = detail
    with _TLOCK:
        _TOTALS["serving_dispatches"] += 1
    try:
        yield
    finally:
        with _CV:
            _SERVING_INFLIGHT -= 1
            _LAST_SERVING_END = time.monotonic()
            if _SERVING_INFLIGHT <= 0:
                _CV.notify_all()


def yield_point(site: str = "train",
                compensate: Optional[str] = None) -> float:
    """Training-class safe boundary: wait here while serving is in flight.

    Returns seconds waited (0.0 when QoS is off or the gate is open). The
    wait is bounded by the min-share floor — a thread that has computed
    ``ran`` seconds and already waited ``waited`` may wait at most
    ``ran·(1/share − 1) − waited`` more, so training always converges to
    its configured share under sustained load — and by
    ``H2O3_QOS_MAX_WAIT_MS`` per visit. `compensate` names a phase bucket
    the wait would otherwise be double-booked into (the tree driver's
    chunk marks, the estimator engine's ``est_iter``); the wait is booked
    to ``qos_wait`` and subtracted there."""
    if not enabled():
        return 0.0
    now = time.monotonic()
    st = _tls_state()
    if st["t_resume"]:
        st["ran_s"] += max(now - st["t_resume"], 0.0)
    st["t_resume"] = now
    _LAST_TRAIN_YIELD["t"] = now
    _LAST_TRAIN_YIELD["site"] = site
    with _TLOCK:
        _TOTALS["yields"] += 1
    try:
        _reg()["yields"].inc(1, site)
    except Exception:
        pass
    # injected preemption delay (latency-only fault point; never raises
    # when armed with error="none")
    _faults.check("qos.preempt_delay", site)
    if not _gate_closed(time.monotonic()):
        return 0.0
    share = train_min_share()
    if share > 0:
        budget = st["ran_s"] * (1.0 / share - 1.0) - st["waited_s"]
    else:
        budget = _max_wait_s()
    budget = min(max(budget, 0.0), _max_wait_s())
    if budget <= 0:
        return 0.0
    t0 = time.monotonic()
    deadline = t0 + budget
    with _CV:
        while True:
            now2 = time.monotonic()
            if now2 >= deadline or not _gate_closed(now2):
                break
            # wake on serving release; poll quanta cover linger expiry
            # and a mid-wait qos.starve disarm
            _CV.wait(min(deadline - now2, 0.05))
    waited = time.monotonic() - t0
    st["waited_s"] += waited
    st["t_resume"] = time.monotonic()
    with _TLOCK:
        _TOTALS["waits_ms"] += waited * 1e3
    try:
        _reg()["waits"].observe(waited * 1e3, site)
    except Exception:
        pass
    from . import phases as _phases

    _phases.add("qos_wait", waited)
    if compensate:
        _phases.add(compensate, -waited)
    return waited


# -- one consistent pressure snapshot -----------------------------------------

class PressureView(NamedTuple):
    """One ledger pressure read with BOTH shed decisions evaluated at the
    same instant — `shed_serving` (admission's 429 threshold) can never be
    true while `evict_cache` (the dataset cache's training-artifact shed)
    is false, because the eviction threshold sits below the serving one:
    training artifacts always go first."""

    value: float
    shed_serving: bool
    evict_cache: bool
    at: float

    def decide(self, threshold: float) -> bool:
        """This snapshot's value against a caller-local threshold (the
        serving config's `shed_pressure` may be constructed, not env)."""
        return threshold > 0 and self.value >= threshold


def pressure_view(max_age_s: Optional[float] = None) -> PressureView:
    """The shared pressure snapshot. With QoS armed, views are cached for
    ``H2O3_QOS_PRESSURE_VIEW_S`` (default 0.2 s) so admission and eviction
    decisions inside one contended burst agree on a single value; with QoS
    off every call takes a fresh (ledger-side rate-limited) read — exactly
    the pre-QoS behavior, minus the two-sites-two-reads race."""
    from . import memory_ledger as ml

    if max_age_s is None:
        max_age_s = (env_float("H2O3_QOS_PRESSURE_VIEW_S", 0.2)
                     if enabled() else 0.0)
    now = time.monotonic()
    v = _VIEW.get("view")
    if (isinstance(v, PressureView) and max_age_s > 0
            and (now - v.at) < max_age_s):
        return v
    p = float(ml.pressure())
    shed_at = env_float("H2O3_SERVING_SHED_PRESSURE", 0.97)
    view = PressureView(p, shed_at > 0 and p >= shed_at,
                        p >= ml.evict_threshold(), now)
    _VIEW["view"] = view
    return view


def serving_p99_ms() -> Optional[float]:
    """Live end-to-end predict p99 from the central registry
    (``h2o3_rest_request_ms{handler=predict}``) — None before any predict
    has been served in this process."""
    try:
        from . import metrics_registry as reg

        h = reg.get("h2o3_rest_request_ms")
        if h is None:
            return None
        return h.percentile(0.99, "predict")
    except Exception:
        return None


# -- trainpool admission throttle ---------------------------------------------

def _eval_throttle() -> bool:
    """One hysteresis step; returns the (possibly new) throttled state and
    records every transition (counter + gauge + trace event)."""
    p_hi = env_float("H2O3_QOS_PRESSURE_HI", 0.9)
    p_lo = env_float("H2O3_QOS_PRESSURE_LO", 0.75)
    slo = env_float("H2O3_QOS_SLO_MS", 0.0)
    r_hi = env_float("H2O3_QOS_P99_RATIO_HI", 2.0)
    r_lo = env_float("H2O3_QOS_P99_RATIO_LO", 1.5)
    view = pressure_view()
    p99 = serving_p99_ms() if slo > 0 else None
    cur = _THROTTLE["state"]
    hot_latency = bool(slo > 0 and p99 is not None and p99 >= slo * r_hi)
    cool_latency = (slo <= 0 or p99 is None or p99 <= slo * r_lo)
    if cur == 0:
        new = 1 if (view.value >= p_hi or hot_latency) else 0
    else:
        new = 0 if (view.value <= p_lo and cool_latency) else 1
    if new != cur:
        _THROTTLE["state"] = new
        _THROTTLE["since"] = time.monotonic()
        with _TLOCK:
            _TOTALS["throttle_transitions"] += 1
        try:
            _reg()["throttle_state"].set(float(new))
            _reg()["transitions"].inc(1, "on" if new else "off")
        except Exception:
            pass
        try:
            from . import tracing as _tracing

            _tracing.event("qos_throttle", state="on" if new else "off",
                           pressure=round(view.value, 4),
                           serving_p99_ms=p99)
        except Exception:
            pass
    return bool(new)


def throttled() -> bool:
    """Current admission-throttle verdict (one hysteresis evaluation)."""
    if not enabled():
        return False
    return _eval_throttle()


def admission_gate(label: str = "candidate") -> float:
    """Trainpool's per-candidate admission hook: while the throttle is
    closed (pressure or serving-p99 hysteresis), hold the candidate back —
    bounded by ``H2O3_QOS_THROTTLE_MAX_WAIT_S`` so a sweep can never
    deadlock on a stuck gauge. Returns seconds waited."""
    if not enabled() or not _eval_throttle():
        return 0.0
    max_wait = env_float("H2O3_QOS_THROTTLE_MAX_WAIT_S", 5.0)
    poll = max(env_float("H2O3_QOS_THROTTLE_POLL_MS", 50.0), 1.0) / 1e3
    t0 = time.monotonic()
    deadline = t0 + max_wait
    while time.monotonic() < deadline and _eval_throttle():
        time.sleep(poll)
    waited = time.monotonic() - t0
    with _TLOCK:
        _TOTALS["throttle_waits_ms"] += waited * 1e3
    try:
        _reg()["waits"].observe(waited * 1e3, f"admission:{label}")
    except Exception:
        pass
    from . import phases as _phases

    _phases.add("qos_wait", waited)
    return waited


# -- observability ------------------------------------------------------------

def gate_state() -> Dict:
    """Who holds the gate right now — the bench watchdog's hang line:
    'serving' while any serving dispatch is in flight, 'training' while
    training yielded recently (it is between yields, i.e. inside its own
    dispatch burst), 'idle' otherwise."""
    now = time.monotonic()
    lt = dict(_LAST_TRAIN_YIELD)
    if _SERVING_INFLIGHT > 0:
        holder = "serving"
    elif lt["t"] and (now - lt["t"]) < 5.0:
        holder = "training"
    else:
        holder = "idle"
    out = dict(enabled=enabled(), holder=holder,
               serving_inflight=int(_SERVING_INFLIGHT),
               throttled=bool(_THROTTLE["state"]))
    if holder == "serving" and _LAST_SERVING_DETAIL:
        out["serving_detail"] = _LAST_SERVING_DETAIL
    if lt["t"]:
        out["last_training_site"] = lt["site"] or None
        out["last_training_yield_age_s"] = round(now - lt["t"], 3)
    return out


def totals() -> Dict:
    """Process-cumulative QoS counters — the bench-record embed."""
    with _TLOCK:
        t = dict(_TOTALS)
    t["waits_ms"] = round(t["waits_ms"], 3)
    t["throttle_waits_ms"] = round(t["throttle_waits_ms"], 3)
    return t


def stats() -> Dict:
    """The /3/Profiler ``qos`` fold: gate + throttle state, cumulative
    yield/wait totals, and the live knob values. Pure read."""
    out = dict(enabled=enabled(), gate=gate_state(), totals=totals(),
               throttle=dict(state=int(_THROTTLE["state"]),
                             since_s=(round(time.monotonic()
                                            - _THROTTLE["since"], 3)
                                      if _THROTTLE["since"] else None)),
               train_min_share=train_min_share())
    p99 = serving_p99_ms()
    if p99 is not None:
        out["serving_p99_ms"] = round(p99, 3)
    return out


def reset() -> None:
    """Zero the cumulative counters and gate/throttle state (tests and
    per-window bench measurement; registry families are monotone and
    stay)."""
    global _SERVING_INFLIGHT, _LAST_SERVING_END, _LAST_SERVING_DETAIL
    with _TLOCK:
        _TOTALS.update(yields=0, waits_ms=0.0, serving_dispatches=0,
                       throttle_transitions=0, throttle_waits_ms=0.0)
    with _CV:
        _SERVING_INFLIGHT = 0
        _LAST_SERVING_END = 0.0
        _LAST_SERVING_DETAIL = ""
        _CV.notify_all()
    _LAST_TRAIN_YIELD.update(t=0.0, site="")
    _THROTTLE.update(state=0, since=0.0)
    _VIEW["view"] = None
    _TLS.share = None
