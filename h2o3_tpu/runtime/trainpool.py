"""Train pool — bounded scheduler for multi-model training sweeps.

Grid search (`models/grid.py`) and AutoML (`automl/automl.py`) submit
candidate model builds here instead of looping sequentially. With
``parallelism=N`` (the upstream `H2OGridSearch` knob) up to N candidates
are in flight at once: the device serializes their actual train-step
executions, but each candidate's HOST work — frame→matrix expansion,
binning, bit-packing, metrics, scoring-history, checkpoint serialization —
overlaps with its siblings' device compute, the training analog of the
serving micro-batcher's overlap. Results come back in SUBMISSION order, so
``parallelism=4`` produces the same model list (and therefore the same
leaderboard) as ``parallelism=1``; training itself is seed-deterministic.

Safety: on meshes where concurrent jobs are genuinely unsafe (multi-device
XLA:CPU thunk pools, multi-process clouds — `mesh.must_serialize_training`)
the pool degrades to sequential in-thread execution. It must NOT take
`mesh.training_guard()` from worker threads: the REST grid handler already
holds that RLock around the whole sweep, and its own workers would
deadlock against it.

Error isolation: one candidate's exception is captured on its record (the
sweep continues); `JobCancelled` marks the record cancelled. Each candidate
gets a child `Job` whose cancel check also consults the sweep's parent job,
so the existing `POST /3/Jobs/{id}/cancel` route on a REST-driven grid
stops in-flight candidates at their next scoring boundary and skips the
not-yet-started ones.

Observability: per-candidate wall seconds plus the phase split attributed
through `runtime/phases.candidate_sink` (h2d / compile / trace / host_prep
/ compute / metrics and h2d bytes), pool occupancy (busy worker-seconds ÷
wall·parallelism), and CV fold reuse/rebin counters — served at
``GET /3/Training/metrics`` (TrainingMetricsV3) and folded into
``/3/Profiler`` via `runtime/profiler.training_stats`.

``H2O3_TRAIN_LEGACY=1`` is the bench comparator: callers bypass the pool
(sequential seed loop), the dataset-artifact cache disables itself, and CV
reverts to the per-fold re-bin path.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from . import phases as _phases

# candidate phase keys surfaced per record (subset of runtime/phases keys)
_CAND_PHASES = ("host_prep", "h2d", "compile", "trace", "deserialize",
                "compute", "metrics", "d2h")

_LOCK = threading.Lock()
_TOTALS = dict(pools=0, submitted=0, completed=0, failed=0, cancelled=0,
               skipped=0, busy_s=0.0, wall_s=0.0)
_CV = dict(reuse_folds=0, rebin_folds=0)
_CANDIDATES: deque = deque(maxlen=int(os.environ.get(
    "H2O3_TRAIN_CANDIDATE_LOG", 64)))
_LAST_POOL: Dict = {}


def legacy() -> bool:
    """The seed-comparator switch: sequential loops, no artifact cache,
    per-fold re-binning (bench.py's vs_seed measurement)."""
    return os.environ.get("H2O3_TRAIN_LEGACY", "") not in ("", "0")


def record_cv_fold(reused: bool) -> None:
    with _LOCK:
        _CV["reuse_folds" if reused else "rebin_folds"] += 1


@dataclass
class JobRecord:
    """Outcome of one submitted candidate, in submission order."""

    name: str
    status: str = "pending"   # pending/done/failed/cancelled/skipped
    result: object = None
    error: Optional[str] = None
    exception: Optional[BaseException] = None
    wall_s: float = 0.0
    phases: Dict[str, float] = field(default_factory=dict)
    bytes_h2d: int = 0

    @property
    def ok(self) -> bool:
        return self.status == "done"


def _child_job(dest: str, parent=None):
    """Per-candidate Job whose cancel check also consults the sweep's
    parent job (REST cancel on the grid job reaches running candidates)."""
    from ..models.model_base import Job

    class _J(Job):
        def check_cancelled(self):
            if parent is not None and parent.cancel_requested:
                self.cancel_requested = True
            Job.check_cancelled(self)

    return _J(dest=dest, description="train-pool candidate").start()


class TrainPool:
    """Run candidate build functions with bounded parallelism.

    ``items`` are ``(name, fn)`` where ``fn(job)`` builds and returns one
    model/estimator; ``job`` is the pool-created child Job (wire it in as
    the estimator's ``_external_job`` so cancellation reaches the driver's
    scoring-boundary safe points).
    """

    def __init__(self, parallelism: int = 1, label: str = "train",
                 parent_job=None):
        self.parallelism = max(int(parallelism or 1), 1)
        self.label = label
        self.parent_job = parent_job

    def _effective_parallelism(self) -> int:
        if self.parallelism <= 1 or legacy():
            return 1
        from ..parallel import mesh as cloudlib

        cloudlib.cloud()  # resolve the lazy default before deciding
        if cloudlib.must_serialize_training():
            return 1
        return self.parallelism

    def run(self, items: Sequence[Tuple[str, Callable]],
            stop_when: Optional[Callable[[], bool]] = None
            ) -> List[JobRecord]:
        records = [JobRecord(name=name) for name, _ in items]
        par = self._effective_parallelism()
        t0 = time.perf_counter()

        def _one(i: int) -> None:
            rec = records[i]
            name, fn = items[i]
            if self.parent_job is not None \
                    and self.parent_job.cancel_requested:
                rec.status = "cancelled"
                return
            if stop_when is not None and stop_when():
                rec.status = "skipped"
                return
            job = _child_job(f"{self.label}_{name}", parent=self.parent_job)
            t1 = time.perf_counter()
            from ..models.model_base import JobCancelled

            with _phases.candidate_sink() as sink:
                try:
                    rec.result = fn(job)
                    rec.status = "done"
                except JobCancelled:
                    rec.status = "cancelled"
                except Exception as e:  # error isolation: sweep continues
                    rec.status = "failed"
                    rec.error = str(e)
                    rec.exception = e
            rec.wall_s = time.perf_counter() - t1
            secs = sink["secs"]
            rec.phases = {k: round(secs[k], 4) for k in _CAND_PHASES
                          if k in secs}
            rec.bytes_h2d = int(sink["bytes"].get("h2d", 0))
            _record_candidate(self.label, rec, par)

        if par <= 1:
            for i in range(len(records)):
                _one(i)
        else:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(
                    max_workers=par,
                    thread_name_prefix=f"h2o3tpu-train-{self.label}") as ex:
                list(ex.map(_one, range(len(records))))

        wall = time.perf_counter() - t0
        busy = sum(r.wall_s for r in records)
        entry = dict(
            label=self.label, parallelism=par,
            requested_parallelism=self.parallelism,
            n_jobs=len(records),
            done=sum(r.status == "done" for r in records),
            failed=sum(r.status == "failed" for r in records),
            cancelled=sum(r.status == "cancelled" for r in records),
            skipped=sum(r.status == "skipped" for r in records),
            wall_s=round(wall, 4), busy_s=round(busy, 4),
            occupancy=round(busy / max(wall * par, 1e-9), 4),
        )
        with _LOCK:
            _TOTALS["pools"] += 1
            _TOTALS["submitted"] += len(records)
            _TOTALS["completed"] += entry["done"]
            _TOTALS["failed"] += entry["failed"]
            _TOTALS["cancelled"] += entry["cancelled"]
            _TOTALS["skipped"] += entry["skipped"]
            _TOTALS["busy_s"] += busy
            _TOTALS["wall_s"] += wall
            _LAST_POOL.clear()
            _LAST_POOL.update(entry)
        return records


def _record_candidate(label: str, rec: JobRecord, parallelism: int) -> None:
    entry = dict(label=label, name=rec.name, status=rec.status,
                 wall_s=round(rec.wall_s, 4), parallelism=parallelism,
                 phases=rec.phases, bytes_h2d=rec.bytes_h2d)
    if rec.error:
        entry["error"] = rec.error
    with _LOCK:
        _CANDIDATES.append(entry)


def snapshot() -> Dict:
    """The GET /3/Training/metrics body (cache section joined in by the
    REST handler from models/dataset_cache.snapshot())."""
    with _LOCK:
        totals = dict(_TOTALS)
        cv = dict(_CV)
        cands = list(_CANDIDATES)
        last = dict(_LAST_POOL) if _LAST_POOL else None
    busy, wall = totals.pop("busy_s"), totals.pop("wall_s")
    totals["busy_s"] = round(busy, 4)
    totals["wall_s"] = round(wall, 4)
    return dict(totals=totals, cv=cv, candidates=cands, last_pool=last,
                active=totals["submitted"] > 0)


def reset() -> None:
    with _LOCK:
        _TOTALS.update(pools=0, submitted=0, completed=0, failed=0,
                       cancelled=0, skipped=0, busy_s=0.0, wall_s=0.0)
        _CV.update(reuse_folds=0, rebin_folds=0)
        _CANDIDATES.clear()
        _LAST_POOL.clear()
