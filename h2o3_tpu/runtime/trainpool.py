"""Train pool — bounded scheduler for multi-model training sweeps.

Grid search (`models/grid.py`) and AutoML (`automl/automl.py`) submit
candidate model builds here instead of looping sequentially. With
``parallelism=N`` (the upstream `H2OGridSearch` knob) up to N candidates
are in flight at once: the device serializes their actual train-step
executions, but each candidate's HOST work — frame→matrix expansion,
binning, bit-packing, metrics, scoring-history, checkpoint serialization —
overlaps with its siblings' device compute, the training analog of the
serving micro-batcher's overlap. Results come back in SUBMISSION order, so
``parallelism=4`` produces the same model list (and therefore the same
leaderboard) as ``parallelism=1``; training itself is seed-deterministic.

Safety: on meshes where concurrent jobs are genuinely unsafe (multi-device
XLA:CPU thunk pools, multi-process clouds — `mesh.must_serialize_training`)
the pool degrades to sequential in-thread execution. It must NOT take
`mesh.training_guard()` from worker threads: the REST grid handler already
holds that RLock around the whole sweep, and its own workers would
deadlock against it.

Error isolation + hardening (docs/robustness.md):

* one candidate's exception is captured on its record (the sweep
  continues); `JobCancelled` marks the record cancelled;
* TRANSIENT failures (connection drops, device/XLA runtime errors —
  `runtime/retry.is_transient`) are retried up to
  ``H2O3_TRAIN_CAND_RETRIES`` times (default 1) against the shared retry
  budget; permanent errors (bad params) fail fast on the first attempt;
* an optional per-candidate WATCHDOG deadline
  (``H2O3_TRAIN_CAND_DEADLINE_S``, or ``TrainPool(candidate_deadline_s=)``)
  cancels a runaway candidate at its next scoring boundary and records it
  failed — one wedged build cannot absorb a whole sweep's wall-clock;
* a failed/cancelled candidate's PARTIAL artifacts are deleted from the
  DKV (the model key its child job registered) so a crashed sweep does not
  leak half-built models into `h2o.ls`;
* `SweepCheckpoint` persists per-candidate completion records so a killed
  sweep re-submitted with the same params skips already-trained candidates
  (the reference's `hex.grid` recovery; grid recovery_dir state and
  AutoML ``checkpoint_dir`` both ride it — counters land in ``resumed``).

Each candidate gets a child `Job` whose cancel check also consults the
sweep's parent job, so the existing `POST /3/Jobs/{id}/cancel` route on a
REST-driven grid stops in-flight candidates at their next scoring boundary
and skips the not-yet-started ones.

Observability: per-candidate wall seconds plus the phase split attributed
through `runtime/phases.candidate_sink` (h2d / compile / trace / host_prep
/ compute / metrics and h2d bytes), pool occupancy (busy worker-seconds ÷
wall·parallelism), CV fold reuse/rebin counters, and the hardening
counters (retried / watchdog_cancelled / resumed + the shared retry-policy
stats) — served at ``GET /3/Training/metrics`` (TrainingMetricsV3) and
folded into ``/3/Profiler`` via `runtime/profiler.training_stats`.

``H2O3_TRAIN_LEGACY=1`` is the bench comparator: callers bypass the pool
(sequential seed loop), the dataset-artifact cache disables itself, and CV
reverts to the per-fold re-bin path.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from . import faults as _faults
from . import phases as _phases
from . import retry as _retry
from . import tracing as _tracing

# candidate phase keys surfaced per record (subset of runtime/phases keys)
_CAND_PHASES = ("host_prep", "h2d", "compile", "trace", "deserialize",
                "compute", "metrics", "d2h")

_LOCK = threading.Lock()
_TOTALS = dict(pools=0, submitted=0, completed=0, failed=0, cancelled=0,
               skipped=0, retried=0, watchdog_cancelled=0, resumed=0,
               resumed_mid_fit=0, busy_s=0.0, wall_s=0.0)
_CV = dict(reuse_folds=0, rebin_folds=0)
_CANDIDATES: deque = deque(maxlen=int(os.environ.get(
    "H2O3_TRAIN_CANDIDATE_LOG", 64)))
_LAST_POOL: Dict = {}


def legacy() -> bool:
    """The seed-comparator switch: sequential loops, no artifact cache,
    per-fold re-binning (bench.py's vs_seed measurement)."""
    return os.environ.get("H2O3_TRAIN_LEGACY", "") not in ("", "0")


_TOTAL_FIELDS = ("pools", "submitted", "completed", "failed", "cancelled",
                 "skipped", "retried", "watchdog_cancelled", "resumed",
                 "resumed_mid_fit")


_REGISTRY = None


def _registry():
    """Central-registry counters backing /3/Training/metrics totals + CV
    fold accounting (GET /3/Metrics scrape surface). Memoized — this runs
    per CV fold and per pool."""
    global _REGISTRY
    if _REGISTRY is not None:
        return _REGISTRY
    from . import metrics_registry as reg

    c = {f: reg.counter(f"h2o3_train_{f}",
                        f"train pool {f.replace('_', ' ')}")
         for f in _TOTAL_FIELDS}
    c["busy_s"] = reg.counter("h2o3_train_busy_seconds",
                              "busy worker-seconds across pool candidates")
    c["wall_s"] = reg.counter("h2o3_train_wall_seconds",
                              "pool wall-seconds")
    c["cv"] = reg.counter("h2o3_train_cv_folds",
                          "CV folds by preparation mode",
                          labelnames=("mode",))
    for f in _TOTAL_FIELDS:
        reg.bind_rest_field("training", f"totals.{f}", f"h2o3_train_{f}")
    reg.bind_rest_field("training", "totals.busy_s",
                        "h2o3_train_busy_seconds")
    reg.bind_rest_field("training", "totals.wall_s",
                        "h2o3_train_wall_seconds")
    reg.bind_rest_field("training", "cv.reuse_folds", "h2o3_train_cv_folds")
    reg.bind_rest_field("training", "cv.rebin_folds", "h2o3_train_cv_folds")
    _REGISTRY = c
    return c


def record_cv_fold(reused: bool) -> None:
    with _LOCK:
        _CV["reuse_folds" if reused else "rebin_folds"] += 1
    _registry()["cv"].inc(1, "reuse" if reused else "rebin")


def record_resumed(n: int = 1) -> None:
    """Sweep candidates satisfied from a checkpoint instead of retrained
    (grid recovery_dir auto-resume + AutoML checkpoint_dir)."""
    with _LOCK:
        _TOTALS["resumed"] += n
    _registry()["resumed"].inc(n)


def bump_total(field: str, n: int = 1) -> None:
    """Increment one /3/Training/metrics total by name from another
    subsystem (the supervisor bumps ``resumed_mid_fit`` when a fit
    restores a mid-fit snapshot)."""
    if field not in _TOTAL_FIELDS:
        raise KeyError(f"unknown train total {field!r}")
    with _LOCK:
        _TOTALS[field] += n
    _registry()[field].inc(n)


@dataclass
class JobRecord:
    """Outcome of one submitted candidate, in submission order."""

    name: str
    status: str = "pending"   # pending/done/failed/cancelled/skipped/resumed
    result: object = None
    error: Optional[str] = None
    exception: Optional[BaseException] = None
    wall_s: float = 0.0
    retries: int = 0
    phases: Dict[str, float] = field(default_factory=dict)
    bytes_h2d: int = 0

    @property
    def ok(self) -> bool:
        return self.status in ("done", "resumed")


class SweepCheckpoint:
    """Per-candidate completion records of one sweep, persisted as JSON.

    ``mark(key, payload)`` is atomic (tmp + os.replace) after every
    completion, so a sweep killed mid-flight leaves a readable record; a
    re-submitted sweep with the same id skips `completed()` candidates.
    The payload shape is the CALLER's (grid stores combo params + artifact
    file, AutoML stores leaderboard metrics + artifact file).

    ``fingerprint`` (a JSON-safe dict of the sweep's identity — response,
    features, seed, data shape, ...) guards against restoring someone
    else's records: candidate names like ``GBM_1`` are constants, so
    without it a checkpoint written for dataset A would silently serve
    A's models under a re-run on dataset B. A stored file whose
    fingerprint differs is treated as "no records".

    **In-flight records** (mid-fit resume rider): ``mark_inflight(key,
    info)`` persists that a candidate STARTED and where its fit-level
    checkpoints live (the supervisor's run fingerprint + checkpoint dir).
    A sweep killed mid-candidate therefore leaves a pointer a re-run can
    follow: the candidate retrains, its fit restores the newest valid
    mid-fit snapshot via that fingerprint, and only the uncheckpointed
    tail is rebuilt (``totals.resumed_mid_fit``). ``mark`` clears the
    key's in-flight record — a completed candidate needs no pointer."""

    def __init__(self, directory: str, sweep_id: str,
                 fingerprint: Optional[Dict] = None):
        self.directory = directory
        self.sweep_id = sweep_id
        self.fingerprint = fingerprint
        self.path = os.path.join(directory, f"{sweep_id}.sweep.json")
        self._lock = threading.Lock()
        self._records: Dict[str, Dict] = {}
        self._inflight: Dict[str, Dict] = {}
        if os.path.exists(self.path):
            try:
                with open(self.path) as f:
                    data = json.load(f)
                if data.get("sweep_id") != sweep_id:
                    pass
                elif (fingerprint is not None
                        and data.get("fingerprint") != fingerprint):
                    from .log import Log

                    Log.warn(
                        f"sweep checkpoint {self.path}: stored fingerprint "
                        "does not match this run (different data/response/"
                        "seed?); ignoring its records")
                else:
                    self._records = dict(data.get("candidates") or {})
                    self._inflight = dict(data.get("inflight") or {})
            except (ValueError, OSError):
                # a torn/corrupt checkpoint means "no records", not a crash
                self._records = {}

    def completed(self, key: str) -> Optional[Dict]:
        with self._lock:
            return self._records.get(key)

    def keys(self) -> List[str]:
        with self._lock:
            return list(self._records)

    def inflight(self, key: Optional[str] = None):
        """The interrupted-candidate pointers the prior run left behind
        (all of them, or one key's)."""
        with self._lock:
            if key is not None:
                return self._inflight.get(key)
            return dict(self._inflight)

    def _write_locked(self) -> None:
        os.makedirs(self.directory, exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(dict(sweep_id=self.sweep_id,
                           fingerprint=self.fingerprint,
                           candidates=self._records,
                           inflight=self._inflight), f)
        os.replace(tmp, self.path)

    def mark(self, key: str, payload: Dict) -> None:
        with self._lock:
            self._records[key] = payload
            self._inflight.pop(key, None)
            self._write_locked()

    def mark_inflight(self, key: str, info: Optional[Dict] = None) -> None:
        with self._lock:
            self._inflight[key] = dict(info or {}, ts=time.time())
            self._write_locked()

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)


def _child_job(dest: str, parent=None):
    """Per-candidate Job whose cancel check also consults the sweep's
    parent job (REST cancel on the grid job reaches running candidates)."""
    from ..models.model_base import Job

    class _J(Job):
        def check_cancelled(self):
            if parent is not None and parent.cancel_requested:
                self.cancel_requested = True
            Job.check_cancelled(self)

    return _J(dest=dest, description="train-pool candidate").start()


def _cleanup_partial(job) -> int:
    """Remove a failed/cancelled candidate's partial model artifacts from
    the DKV: the model key its job registered (job.result — set by
    model_base.train right after DKV.put) and the job's own dest key if a
    model landed under it. Only H2OModel values are touched."""
    from ..models.model_base import H2OModel
    from .dkv import DKV

    from . import memory_ledger

    removed = 0
    for k in {getattr(job, "result", None), getattr(job, "dest", None)}:
        if k and isinstance(DKV.get(k), H2OModel):
            DKV.remove(k)
            removed += 1
        if k:
            # leak canary: job_end no-ops when the key is gone (the normal
            # case right after the remove above); if a future path leaves
            # a partial model behind, it surfaces in the memory ledger's
            # leak report instead of silently leaking into h2o.ls
            memory_ledger.job_end(k, "FAILED")
    return removed


class TrainPool:
    """Run candidate build functions with bounded parallelism.

    ``items`` are ``(name, fn)`` where ``fn(job)`` builds and returns one
    model/estimator; ``job`` is the pool-created child Job (wire it in as
    the estimator's ``_external_job`` so cancellation — REST cancel AND the
    watchdog deadline — reaches the driver's scoring-boundary safe points).
    """

    def __init__(self, parallelism: int = 1, label: str = "train",
                 parent_job=None, candidate_retries: Optional[int] = None,
                 candidate_deadline_s: Optional[float] = None):
        self.parallelism = max(int(parallelism or 1), 1)
        self.label = label
        self.parent_job = parent_job
        from . import env_float, env_int

        self.candidate_retries = max(
            candidate_retries if candidate_retries is not None
            else env_int("H2O3_TRAIN_CAND_RETRIES", 1), 0)
        self.candidate_deadline_s = (
            candidate_deadline_s if candidate_deadline_s is not None
            else env_float("H2O3_TRAIN_CAND_DEADLINE_S", 0.0))

    def _effective_parallelism(self) -> int:
        if self.parallelism <= 1 or legacy():
            return 1
        from ..parallel import mesh as cloudlib

        cloudlib.cloud()  # resolve the lazy default before deciding
        if cloudlib.must_serialize_training():
            return 1
        return self.parallelism

    def _run_candidate(self, rec: JobRecord, name: str,
                       fn: Callable) -> None:
        """One candidate: up to 1+retries attempts, each under a fresh
        child job and (when configured) a watchdog timer."""
        from ..models.model_base import JobCancelled

        deadline = self.candidate_deadline_s
        max_tries = 1 + self.candidate_retries
        attempt = 0
        while True:
            attempt += 1
            job = _child_job(f"{self.label}_{name}", parent=self.parent_job)
            watchdog = None
            if deadline > 0:
                def _fire(j=job):
                    j._watchdog_fired = True
                    j.cancel()

                watchdog = threading.Timer(deadline, _fire)
                watchdog.daemon = True
                watchdog.start()
            try:
                _faults.check("trainpool.candidate", name)
                rec.result = fn(job)
                rec.status = "done"
                return
            except JobCancelled:
                if getattr(job, "_watchdog_fired", False):
                    with _LOCK:
                        _TOTALS["watchdog_cancelled"] += 1
                    _registry()["watchdog_cancelled"].inc()
                    _tracing.event("watchdog_cancelled",
                                   deadline_s=deadline)
                    _cleanup_partial(job)
                    # mid-fit resume: with fit checkpointing active the
                    # re-attempt restores the newest snapshot and finishes
                    # the tail instead of retraining from tree 0 — so a
                    # watchdog kill is worth retrying (runtime/supervisor)
                    from . import supervisor as _sup

                    if (attempt < max_tries and _sup.ckpt_enabled()
                            and _sup.ckpt_dir()
                            and _retry.default_budget().try_spend()):
                        rec.retries += 1
                        _retry.record("trainpool", "retries")
                        with _LOCK:
                            _TOTALS["retried"] += 1
                        _registry()["retried"].inc()
                        _tracing.event("retry", attempt=attempt,
                                       error="watchdog_cancelled")
                        continue
                    rec.status = "failed"
                    rec.error = (f"candidate exceeded its {deadline:g}s "
                                 "watchdog deadline and was cancelled")
                    return
                rec.status = "cancelled"
                _cleanup_partial(job)
                return
            except Exception as e:  # error isolation: sweep continues
                _cleanup_partial(job)
                if (attempt < max_tries and _retry.is_transient(e)
                        and _retry.default_budget().try_spend()):
                    rec.retries += 1
                    _retry.record("trainpool", "retries")
                    with _LOCK:
                        _TOTALS["retried"] += 1
                    _registry()["retried"].inc()
                    _tracing.event("retry", attempt=attempt,
                                   error=f"{type(e).__name__}: {e}")
                    continue
                rec.status = "failed"
                rec.error = str(e)
                rec.exception = e
                return
            finally:
                if watchdog is not None:
                    watchdog.cancel()

    def run(self, items: Sequence[Tuple[str, Callable]],
            stop_when: Optional[Callable[[], bool]] = None
            ) -> List[JobRecord]:
        records = [JobRecord(name=name) for name, _ in items]
        par = self._effective_parallelism()
        t0 = time.perf_counter()
        # trace correlation: candidates run on pool worker threads, so the
        # submitting thread's span (the REST job span, usually) is captured
        # here and re-attached per candidate — every candidate span shares
        # the request's trace id
        parent_span = _tracing.current()
        trace_id = (parent_span.trace_id if parent_span is not None
                    else getattr(self.parent_job, "trace_id", None))
        parent_id = (parent_span.span_id if parent_span is not None
                     else None)

        def _one(i: int) -> None:
            rec = records[i]
            name, fn = items[i]
            if self.parent_job is not None \
                    and self.parent_job.cancel_requested:
                rec.status = "cancelled"
                return
            if stop_when is not None and stop_when():
                rec.status = "skipped"
                return
            # QoS admission throttle (hysteresis over ledger pressure +
            # live serving p99): hold the candidate back while the device
            # is contended; bounded wait, booked to the qos_wait phase
            from . import qos as _qos

            _qos.admission_gate(name)
            t1 = time.perf_counter()
            with _tracing.span(f"candidate:{name}", kind="candidate",
                               trace_id=trace_id, parent_id=parent_id,
                               label=self.label) as sp, \
                    _phases.candidate_sink() as sink:
                self._run_candidate(rec, name, fn)
                sp.annotate(status=rec.status)
            rec.wall_s = time.perf_counter() - t1
            secs = sink["secs"]
            rec.phases = {k: round(secs[k], 4) for k in _CAND_PHASES
                          if k in secs}
            rec.bytes_h2d = int(sink["bytes"].get("h2d", 0))
            _record_candidate(self.label, rec, par)

        if par <= 1:
            for i in range(len(records)):
                _one(i)
        else:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(
                    max_workers=par,
                    thread_name_prefix=f"h2o3tpu-train-{self.label}") as ex:
                list(ex.map(_one, range(len(records))))

        wall = time.perf_counter() - t0
        busy = sum(r.wall_s for r in records)
        entry = dict(
            label=self.label, parallelism=par,
            requested_parallelism=self.parallelism,
            n_jobs=len(records),
            done=sum(r.status == "done" for r in records),
            failed=sum(r.status == "failed" for r in records),
            cancelled=sum(r.status == "cancelled" for r in records),
            skipped=sum(r.status == "skipped" for r in records),
            retried=sum(r.retries for r in records),
            wall_s=round(wall, 4), busy_s=round(busy, 4),
            occupancy=round(busy / max(wall * par, 1e-9), 4),
        )
        with _LOCK:
            _TOTALS["pools"] += 1
            _TOTALS["submitted"] += len(records)
            _TOTALS["completed"] += entry["done"]
            _TOTALS["failed"] += entry["failed"]
            _TOTALS["cancelled"] += entry["cancelled"]
            _TOTALS["skipped"] += entry["skipped"]
            _TOTALS["busy_s"] += busy
            _TOTALS["wall_s"] += wall
            _LAST_POOL.clear()
            _LAST_POOL.update(entry)
        reg = _registry()
        reg["pools"].inc()
        reg["submitted"].inc(len(records))
        reg["completed"].inc(entry["done"])
        reg["failed"].inc(entry["failed"])
        reg["cancelled"].inc(entry["cancelled"])
        reg["skipped"].inc(entry["skipped"])
        reg["busy_s"].inc(busy)
        reg["wall_s"].inc(wall)
        return records


def _record_candidate(label: str, rec: JobRecord, parallelism: int) -> None:
    entry = dict(label=label, name=rec.name, status=rec.status,
                 wall_s=round(rec.wall_s, 4), parallelism=parallelism,
                 phases=rec.phases, bytes_h2d=rec.bytes_h2d)
    if rec.retries:
        entry["retries"] = rec.retries
    if rec.error:
        entry["error"] = rec.error
    with _LOCK:
        _CANDIDATES.append(entry)


def snapshot() -> Dict:
    """The GET /3/Training/metrics body (cache section joined in by the
    REST handler from models/dataset_cache.snapshot())."""
    with _LOCK:
        totals = dict(_TOTALS)
        cv = dict(_CV)
        cands = list(_CANDIDATES)
        last = dict(_LAST_POOL) if _LAST_POOL else None
    busy, wall = totals.pop("busy_s"), totals.pop("wall_s")
    totals["busy_s"] = round(busy, 4)
    totals["wall_s"] = round(wall, 4)
    return dict(totals=totals, cv=cv, candidates=cands, last_pool=last,
                retry=_retry.snapshot(), faults=_faults.snapshot(),
                active=totals["submitted"] > 0)


def reset() -> None:
    with _LOCK:
        _TOTALS.update(pools=0, submitted=0, completed=0, failed=0,
                       cancelled=0, skipped=0, retried=0,
                       watchdog_cancelled=0, resumed=0, resumed_mid_fit=0,
                       busy_s=0.0, wall_s=0.0)
        _CV.update(reuse_folds=0, rebin_folds=0)
        _CANDIDATES.clear()
        _LAST_POOL.clear()
