"""Timeline — lock-free-ish event ring for distributed debugging.

Reference parity: `h2o-core/src/main/java/water/TimeLine.java` — a ring of
64-byte records (timestamp, peer, task) for every packet send/recv, dumped
cluster-wide via `/3/Timeline` (`water/util/TimelineSnapshot.java` merges the
per-node rings). Here the interesting events are compiles, device transfers,
collective launches and training milestones; one ring per process.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List


class Timeline:
    _ring: deque = deque(maxlen=4096)
    _lock = threading.Lock()

    @classmethod
    def record(cls, kind: str, detail: str = "", **extra):
        ev = dict(ts=time.time(), kind=kind, detail=detail)
        if extra:
            ev.update(extra)
        with cls._lock:
            cls._ring.append(ev)

    @classmethod
    def snapshot(cls, n: int = 1000) -> List[Dict]:
        with cls._lock:
            return list(cls._ring)[-n:]

    @classmethod
    def clear(cls):
        with cls._lock:
            cls._ring.clear()
