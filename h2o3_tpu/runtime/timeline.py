"""Timeline — bounded event ring for distributed debugging.

Reference parity: `h2o-core/src/main/java/water/TimeLine.java` — a ring of
64-byte records (timestamp, peer, task) for every packet send/recv, dumped
cluster-wide via `/3/Timeline` (`water/util/TimelineSnapshot.java` merges the
per-node rings). Here the interesting events are compiles, device transfers,
collective launches, REST requests and training milestones; one ring per
process, bounded (``H2O3_TIMELINE_EVENTS``, default 4096) so sustained REST
traffic recycles slots instead of growing the host.

Every event carries a monotone ``seq`` cursor: ``GET /3/Timeline?since=N``
returns only events recorded after cursor N plus the new cursor, so a
tailing client polls incrementally instead of re-downloading the ring.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from . import env_int


class Timeline:
    _ring: deque = deque(maxlen=max(env_int("H2O3_TIMELINE_EVENTS", 4096),
                                    16))
    _lock = threading.Lock()
    _seq = 0

    @classmethod
    def record(cls, kind: str, detail: str = "", **extra):
        ev = dict(ts=time.time(), kind=kind, detail=detail)
        if extra:
            ev.update(extra)
        with cls._lock:
            cls._seq += 1
            ev["seq"] = cls._seq
            cls._ring.append(ev)

    @classmethod
    def snapshot(cls, n: int = 1000,
                 since: Optional[int] = None) -> List[Dict]:
        """Latest `n` events; with `since`, only events with seq > since
        (incremental tailing — each event's own ``seq`` is the cursor)."""
        with cls._lock:
            evs = list(cls._ring)
        if since is not None:
            evs = [e for e in evs if e["seq"] > since]
        return evs[-n:]

    @classmethod
    def tail(cls, since: Optional[int],
             n: int = 1000) -> Tuple[List[Dict], int]:
        """One atomic tailing page: ``(events, cursor)`` under a single
        lock acquisition, so the cursor always corresponds to the events
        actually returned. With ``since``, the page is the OLDEST `n`
        events after the cursor (a burst larger than one page is paged
        through, never silently skipped) and the cursor is the last
        returned event's seq; without, the page is the latest `n` and the
        cursor is the global latest seq (start tailing from now)."""
        with cls._lock:
            evs = list(cls._ring)
            latest = cls._seq
        if since is None:
            return evs[-n:], latest
        page = [e for e in evs if e["seq"] > since][:n]
        return page, (page[-1]["seq"] if page else latest)

    @classmethod
    def cursor(cls) -> int:
        """The latest sequence number (pass back as ``since=``)."""
        with cls._lock:
            return cls._seq

    @classmethod
    def clear(cls):
        with cls._lock:
            cls._ring.clear()
